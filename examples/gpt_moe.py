"""Expert-parallel (Switch-MoE) GPT training example (beyond the
reference: the reference framework is data-parallel only, SURVEY §2.7 —
but its alltoall collective is exactly the EP dispatch primitive,
operations.cc:1031-1092).

Trains a small MoE GPT whose experts shard over the mesh's local axis
while the batch shards over BOTH axes (every rank sees distinct
tokens), with a choice of dispatch protocol:

* ``--dispatch fixed``: classic Switch routing into a static
  ``[E, capacity, C]`` buffer — tokens drop when one (sender, expert)
  pair overflows its quota;
* ``--dispatch ragged``: uneven-split exchanges over
  ``hvd.alltoall_ragged`` — each local expert's capacity pools across
  ALL senders, so only rank-level skew or global expert overflow drops
  tokens (the reference's MPI_Alltoallv analogue, compiled).

Gradient correctness without per-class rescaling: the objective is the
GLOBAL token mean, formed inside shard_map via ``psum``, so autodiff
delivers exactly d(global)/dθ for every parameter class — expert shards
collect contributions through the all_to_all transpose (+ the implicit
cross-axis psum), the replicated backbone through the standard pvary
transpose. The router's load-balancing aux loss is mixed in.

Runs anywhere a mesh exists; to try 4-way EP x 2-way DP without TPUs:

    python examples/gpt_moe.py --steps 10 --cpu 8 --dp 2
"""

import _path_setup  # noqa: F401  (repo-root import shim)
from _path_setup import add_cpu_flag, apply_cpu_flag

import argparse
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import GPT, gpt_tiny
from horovod_tpu.parallel.expert import ep_split_params
from horovod_tpu.parallel.tensor import tp_merge_params


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dispatch", choices=["fixed", "ragged"],
                    default="ragged")
    ap.add_argument("--experts", type=int, default=8)
    ap.add_argument("--capacity-factor", type=float, default=1.5)
    ap.add_argument("--aux-weight", type=float, default=0.01)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=4,
                    help="per-RANK batch")
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--lr", type=float, default=1e-2)
    ap.add_argument("--dp", type=int, default=None,
                    help="DP (cross-axis) size; default 1 in a single "
                         "process — set e.g. --dp 2 with --cpu 8 for "
                         "2-way DP x 4-way EP")
    add_cpu_flag(ap)
    args = ap.parse_args()

    apply_cpu_flag(args)
    mesh_shape = None
    if args.dp:
        nd = jax.device_count()
        if args.dp <= 0 or nd % args.dp:
            raise SystemExit(f"--dp {args.dp} must be positive and "
                             f"divide the device count {nd}")
        mesh_shape = (args.dp, nd // args.dp)
    hvd.init(mesh_shape=mesh_shape)
    mesh = hvd.mesh()
    n_dp, ep_n = int(mesh.devices.shape[0]), int(mesh.devices.shape[1])
    n_world = n_dp * ep_n
    if args.experts % ep_n:
        raise SystemExit(f"--experts {args.experts} must divide by the "
                         f"EP axis size {ep_n}")

    cfg = gpt_tiny(dtype=jnp.float32, moe_experts=args.experts,
                   moe_capacity_factor=args.capacity_factor)
    cfg = dataclasses.replace(
        cfg, ep_axis=hvd.LOCAL_AXIS,
        moe_ragged=args.dispatch == "ragged")
    cfg_dense = dataclasses.replace(cfg, ep_axis=None)

    rs = np.random.RandomState(0)
    toks = rs.randint(0, cfg.vocab_size,
                      (args.batch_size * n_world, args.seq_len + 1))
    x, y = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])
    total_tokens = x.size
    # Init a dense (all-experts-local) model, then shard the expert
    # weights over the EP axis; the router and backbone replicate.
    variables = GPT(cfg_dense).init(jax.random.PRNGKey(0), x[:1])
    sharded, repl = ep_split_params(variables["params"], ep_n)
    aux_w = args.aux_weight

    def step(stk, rp, xb, yb):
        def loss_fn(stk1, rp1):
            local = tp_merge_params(
                jax.tree.map(lambda a: a[0], stk1), rp1)
            out, mods = GPT(cfg).apply({"params": local}, xb,
                                       mutable=["intermediates"])
            tok_ce = optax.softmax_cross_entropy_with_integer_labels(
                out, yb)
            # GLOBAL token mean: grads need no per-class rescaling.
            ce = jax.lax.psum(jnp.sum(tok_ce), hvd.HVD_AXES) / total_tokens
            aux = sum(jnp.sum(a) for a in
                      jax.tree.leaves(mods["intermediates"]))
            aux = jax.lax.pmean(aux, hvd.HVD_AXES) / cfg.num_layers
            return ce + aux_w * aux

        loss, (g_stk, g_rp) = jax.value_and_grad(
            loss_fn, argnums=(0, 1))(stk, rp)
        stk = jax.tree.map(lambda a, g: a - args.lr * g, stk, g_stk)
        rp = jax.tree.map(lambda a, g: a - args.lr * g, rp, g_rp)
        return stk, rp, loss

    stepc = jax.jit(hvd.shard_map(
        step, mesh=mesh,
        in_specs=(P(hvd.LOCAL_AXIS), P(),
                  P((hvd.CROSS_AXIS, hvd.LOCAL_AXIS)),
                  P((hvd.CROSS_AXIS, hvd.LOCAL_AXIS))),
        out_specs=(P(hvd.LOCAL_AXIS), P(), P())))

    print(f"MoE GPT: {args.experts} experts over {ep_n}-way EP x "
          f"{n_dp}-way DP, dispatch={args.dispatch}")
    for i in range(args.steps):
        sharded, repl, loss = stepc(sharded, repl, x, y)
        print(f"step {i}: loss {float(loss):.4f}", flush=True)
    print("done")


if __name__ == "__main__":
    main()
