"""Expert-parallel (Switch-MoE) GPT training example (beyond the
reference: the reference framework is data-parallel only, SURVEY §2.7 —
but its alltoall collective is exactly the EP dispatch primitive,
operations.cc:1031-1092).

Trains a small MoE GPT whose experts shard over the mesh's local axis
(DP rides the cross axis), with a choice of dispatch protocol:

* ``--dispatch fixed``: classic Switch routing into a static
  ``[E, capacity, C]`` buffer — tokens drop when one (sender, expert)
  pair overflows its quota;
* ``--dispatch ragged``: uneven-split exchanges over
  ``hvd.alltoall_ragged`` — each local expert's capacity pools across
  ALL senders, so only rank-level skew or global expert overflow drops
  tokens (the reference's MPI_Alltoallv analogue, compiled).

The router's load-balancing aux loss is mixed into the objective.
Runs anywhere a mesh exists; to try 4-way EP x 2-way DP without TPUs:

    python examples/gpt_moe.py --steps 10 --cpu 8
"""

import _path_setup  # noqa: F401  (repo-root import shim)

import argparse
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import GPT, gpt_tiny
from horovod_tpu.parallel.expert import ep_split_params
from horovod_tpu.parallel.tensor import tp_merge_params


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dispatch", choices=["fixed", "ragged"],
                    default="ragged")
    ap.add_argument("--experts", type=int, default=8)
    ap.add_argument("--capacity-factor", type=float, default=1.5)
    ap.add_argument("--aux-weight", type=float, default=0.01)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=4,
                    help="per-DP-rank batch")
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--lr", type=float, default=1e-2)
    ap.add_argument("--cpu", type=int, default=0, metavar="N",
                    help="force an N-virtual-device CPU mesh")
    args = ap.parse_args()

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)
    hvd.init()
    mesh = hvd.mesh()
    n_dp, ep_n = int(mesh.devices.shape[0]), int(mesh.devices.shape[1])
    if args.experts % ep_n:
        raise SystemExit(f"--experts {args.experts} must divide by the "
                         f"EP axis size {ep_n}")

    cfg = gpt_tiny(dtype=jnp.float32, moe_experts=args.experts,
                   moe_capacity_factor=args.capacity_factor)
    cfg = dataclasses.replace(
        cfg, ep_axis=hvd.LOCAL_AXIS,
        moe_ragged=args.dispatch == "ragged")
    cfg_dense = dataclasses.replace(cfg, ep_axis=None)

    rs = np.random.RandomState(0)
    toks = rs.randint(0, cfg.vocab_size,
                      (args.batch_size * n_dp, args.seq_len + 1))
    x, y = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])
    # Init a dense (all-experts-local) model, then shard the expert
    # weights over the EP axis; the router and backbone replicate.
    variables = GPT(cfg_dense).init(jax.random.PRNGKey(0), x[:1])
    sharded, repl = ep_split_params(variables["params"], ep_n)
    aux_w = args.aux_weight

    def step(stk, rp, xb, yb):
        def loss_fn(stk1, rp1):
            local = tp_merge_params(
                jax.tree.map(lambda a: a[0], stk1), rp1)
            out, mods = GPT(cfg).apply({"params": local}, xb,
                                       mutable=["intermediates"])
            ll = optax.softmax_cross_entropy_with_integer_labels(
                out, yb).mean()
            aux = sum(jnp.sum(a) for a in
                      jax.tree.leaves(mods["intermediates"]))
            return (jax.lax.pmean(ll, hvd.CROSS_AXIS)
                    + aux_w * aux / cfg.num_layers)

        loss, (g_stk, g_rp) = jax.value_and_grad(
            loss_fn, argnums=(0, 1))(stk, rp)
        # Expert shards: DP-average over cross; replicated backbone:
        # average over the whole world.
        g_stk = jax.tree.map(
            lambda t: jax.lax.pmean(t, hvd.CROSS_AXIS), g_stk)
        g_rp = jax.tree.map(
            lambda t: jax.lax.pmean(t, hvd.HVD_AXES), g_rp)
        stk = jax.tree.map(lambda a, g: a - args.lr * g, stk, g_stk)
        rp = jax.tree.map(lambda a, g: a - args.lr * g, rp, g_rp)
        return stk, rp, jax.lax.pmean(loss, hvd.HVD_AXES)

    stepc = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(hvd.LOCAL_AXIS), P(), P(hvd.CROSS_AXIS),
                  P(hvd.CROSS_AXIS)),
        out_specs=(P(hvd.LOCAL_AXIS), P(), P())))

    print(f"MoE GPT: {args.experts} experts over {ep_n}-way EP x "
          f"{n_dp}-way DP, dispatch={args.dispatch}")
    for i in range(args.steps):
        sharded, repl, loss = stepc(sharded, repl, x, y)
        print(f"step {i}: loss {float(loss):.4f}", flush=True)
    print("done")


if __name__ == "__main__":
    main()
