"""Pipeline-parallel GPT training example (beyond the reference: the
reference framework is data-parallel only, SURVEY §2.7).

Trains a small GPT whose transformer blocks are sharded into pipeline
stages across the mesh, with a choice of training path:

* ``--schedule gpipe``: differentiable :func:`hvd.pipelined_gpt_loss`
  under ``jax.value_and_grad`` — vocab-parallel LM head (the [B, T, V]
  einsum sharded over the ranks), activation memory O(num_microbatches).
* ``--schedule 1f1b``: :func:`hvd.pipelined_gpt_train_1f1b` — the fused
  one-forward-one-backward schedule returning (loss, grads) directly,
  activation memory O(pipeline_depth) however many microbatches you use.

Runs anywhere a mesh exists; to try the 8-stage pipeline without TPUs:

    python examples/gpt_pipeline.py --steps 10 --cpu 8
"""

import _path_setup  # noqa: F401  (repo-root import shim)
from _path_setup import add_cpu_flag, apply_cpu_flag

import argparse

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import GPT, gpt_tiny


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--schedule", choices=["gpipe", "1f1b"],
                    default="1f1b")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--microbatches", type=int, default=4)
    ap.add_argument("--lr", type=float, default=0.3)
    add_cpu_flag(ap)
    args = ap.parse_args()

    apply_cpu_flag(args)
    hvd.init()
    n = hvd.size()
    mesh = hvd.mesh()
    print(f"pipeline of {n} stage(s), mesh={mesh.devices.shape}, "
          f"schedule={args.schedule}")

    # One transformer block per stage; pp_split_blocks slices the dense
    # checkpoint into stacked per-stage trees + the replicated rest.
    cfg = gpt_tiny(dtype=jnp.float32, num_layers=max(n, 2),
                   max_seq_len=args.seq_len)
    rs = np.random.RandomState(0)
    toks = rs.randint(0, cfg.vocab_size,
                      (args.batch_size, args.seq_len + 1))
    x, y = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])
    params = GPT(cfg).init(jax.random.PRNGKey(0), x)["params"]
    stages, rest = hvd.pp_split_blocks(params, n)

    if args.schedule == "1f1b":
        def spmd(stg, rst, tok, tgt):
            local = jax.tree.map(lambda a: a[0], stg)
            loss, g_st, g_rest = hvd.pipelined_gpt_train_1f1b(
                cfg, local, rst, tok, tgt, axis=hvd.HVD_AXES,
                num_microbatches=args.microbatches)
            local = jax.tree.map(lambda p, g: p - args.lr * g,
                                 local, g_st)
            rst = jax.tree.map(
                lambda p, g: p - args.lr * g.astype(p.dtype), rst, g_rest)
            return jax.tree.map(lambda a: a[None], local), rst, loss
    else:
        def spmd(stg, rst, tok, tgt):
            local = jax.tree.map(lambda a: a[0], stg)

            def loss_fn(local, rst):
                return hvd.pipelined_gpt_loss(
                    cfg, local, rst, tok, tgt, axis=hvd.HVD_AXES,
                    num_microbatches=args.microbatches)

            loss, (g_st, g_rest) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(local, rst)
            local = jax.tree.map(lambda p, g: p - args.lr * g,
                                 local, g_st)
            rst = jax.tree.map(
                lambda p, g: p - args.lr * g.astype(p.dtype), rst, g_rest)
            return jax.tree.map(lambda a: a[None], local), rst, loss

    step = jax.jit(hvd.shard_map(
        spmd, mesh=mesh,
        in_specs=(P(hvd.HVD_AXES), P(), P(), P()),
        out_specs=(P(hvd.HVD_AXES), P(), P())))

    losses = []
    for i in range(args.steps):
        stages, rest, loss = step(stages, rest, x, y)
        losses.append(float(loss))
        print(f"step {i}: loss {losses[-1]:.4f}")
    assert losses[-1] < losses[0], "loss did not decrease"
    print(f"OK: {losses[0]:.4f} -> {losses[-1]:.4f} "
          f"({args.schedule}, {n} stages, M={args.microbatches})")


if __name__ == "__main__":
    main()
