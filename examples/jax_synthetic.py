"""Data-parallel JAX training example (reference analogue:
examples/tensorflow2/tensorflow2_synthetic_benchmark.py, adapted to the
JAX-first API).

Single-process: uses every local device through the Horovod mesh.
Multi-process (one process per TPU host):

    hvdrun -np 2 -H localhost:2 python examples/jax_synthetic.py
"""

import _path_setup  # noqa: F401  (repo-root import shim)
from _path_setup import add_cpu_flag, apply_cpu_flag

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import MnistNet


def main():
    ap = add_cpu_flag(argparse.ArgumentParser())
    args = ap.parse_args()
    apply_cpu_flag(args)
    hvd.init()
    mesh = hvd.mesh()
    print(f"rank {hvd.rank()}/{hvd.size()} devices={mesh.devices.shape}")

    model = MnistNet(num_classes=10)
    rng = jax.random.PRNGKey(42)
    params = model.init(rng, jnp.zeros((1, 28, 28, 1)))["params"]

    # DistributedOptimizer averages gradients across the mesh in-jit.
    tx = hvd.DistributedOptimizer(optax.adam(1e-3))
    opt_state = tx.init(params)

    rs = np.random.RandomState(0)
    global_batch = 32 * hvd.size()
    images = jnp.asarray(rs.randn(global_batch, 28, 28, 1), jnp.float32)
    labels = jnp.asarray(rs.randint(0, 10, global_batch))

    def loss_fn(p, xb, yb):
        logits = model.apply({"params": p}, xb)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, yb).mean()

    @jax.jit
    def train_step(p, s, xb, yb):
        def spmd(p, s, xb, yb):
            loss, grads = hvd.value_and_grad(loss_fn)(p, xb, yb)
            updates, ns = tx.update(grads, s, p)
            return optax.apply_updates(p, updates), ns, hvd.allreduce(loss)

        return hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(), P(), hvd.data_pspec(), hvd.data_pspec()),
            out_specs=(P(), P(), P()))(p, s, xb, yb)

    losses = []
    for step in range(20):
        params, opt_state, loss = train_step(params, opt_state,
                                             images, labels)
        losses.append(float(loss))
        if hvd.rank() == 0 and step % 5 == 0:
            print(f"step {step}: loss {losses[-1]:.4f}")

    assert losses[-1] < losses[0], "loss did not decrease"
    if hvd.rank() == 0:
        print(f"OK: loss {losses[0]:.4f} -> {losses[-1]:.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
