"""Adasum example (reference analogue: examples/adasum — pytorch
scripts): train the same model with op=Average and op=Adasum and print
both loss curves. Adasum interpolates between summing (ranks moving in
orthogonal directions) and averaging (ranks agreeing), so it tolerates
the single-worker learning rate at any world size — no ``lr x size``
rescale or warmup (docs/adasum.md).

Runs on the virtual CPU mesh, no TPU needed::

    python examples/adasum_jax.py --cpu 8 [--steps 30]
"""

import argparse

import _path_setup  # noqa: F401  (repo root onto sys.path)
from _path_setup import add_cpu_flag, apply_cpu_flag


def train(op_name: str, steps: int, seed: int = 0):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd

    op = {"average": hvd.Average, "adasum": hvd.Adasum}[op_name]
    opt = hvd.DistributedOptimizer(optax.sgd(0.05), op=op)

    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(16, 1)).astype("float32")
    params = jnp.zeros((16, 1))
    state = opt.init(params)
    world = hvd.size()

    def loss_fn(p, x, y):
        return jnp.mean((x @ p - y) ** 2)

    @jax.jit
    def step(params, state, x, y):
        def spmd(params, state, x, y):
            loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
            updates, state2 = opt.update(grads, state, params)
            return optax.apply_updates(params, updates), state2, \
                hvd.allreduce(loss, op=hvd.Average)
        return hvd.shard_map(
            spmd, mesh=hvd.mesh(),
            in_specs=(P(), P(), hvd.data_pspec(), hvd.data_pspec()),
            out_specs=(P(), P(), P()))(params, state, x, y)

    losses = []
    for i in range(steps):
        x = jnp.asarray(rng.normal(size=(8 * world, 16)), jnp.float32)
        y = x @ jnp.asarray(w_true)
        params, state, loss = step(params, state, x, y)
        losses.append(float(loss))
    return losses


def main():
    ap = add_cpu_flag(argparse.ArgumentParser())
    ap.add_argument("--steps", type=int, default=30)
    args = ap.parse_args()
    apply_cpu_flag(args)

    import horovod_tpu as hvd

    hvd.init()
    avg = train("average", args.steps)
    ada = train("adasum", args.steps)
    if hvd.rank() == 0:
        print(f"world={hvd.size()}  (same lr=0.05 for both ops)")
        for i in range(0, args.steps, max(1, args.steps // 6)):
            print(f"step {i:3d}: average {avg[i]:9.5f}   "
                  f"adasum {ada[i]:9.5f}")
        print(f"final   : average {avg[-1]:9.5f}   adasum {ada[-1]:9.5f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
