"""Elastic PyTorch training example (reference analogue:
examples/elastic/pytorch/pytorch_synthetic_benchmark_elastic.py):
@hvd.elastic.run with a TorchState carrying the model, optimizer, and
progress counters over world changes.

Run under the elastic launcher (the driver re-forms the world on host
churn; training rolls back to the last commit)::

    hvdrun -np 2 --min-np 1 -H localhost:2 python examples/pytorch_elastic.py
"""

import _path_setup  # noqa: F401  (repo-root import shim)

import jax

# Workers must not touch a (possibly wedged) TPU backend for a host-side
# torch job; see docs/troubleshooting.md "Launcher can't form a world".
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import torch  # noqa: E402
import torch.nn.functional as F  # noqa: E402

import horovod_tpu.torch as hvd  # noqa: E402

TOTAL_BATCHES = 40
MODEL_DIM = 16


def main():
    torch.manual_seed(0)
    model = torch.nn.Sequential(
        torch.nn.Linear(MODEL_DIM, 32), torch.nn.ReLU(),
        torch.nn.Linear(32, 1))
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.05),
        named_parameters=model.named_parameters())

    @hvd.elastic.run
    def train(state):
        loss = torch.tensor(float("inf"))  # resume-at-end: loop may not run
        while state.batch < TOTAL_BATCHES:
            rs = np.random.RandomState(state.batch)  # deterministic data
            x = torch.tensor(rs.randn(8, MODEL_DIM), dtype=torch.float32)
            y = torch.tensor(rs.randn(8, 1), dtype=torch.float32)
            state.optimizer.zero_grad()
            loss = F.mse_loss(state.model(x), y)
            loss.backward()
            state.optimizer.step()
            state.batch += 1
            if state.batch % 5 == 0:
                state.commit()  # checkpoint + raise on host churn
        return float(loss.detach())

    state = hvd.elastic.TorchState(model=model, optimizer=opt, batch=0)
    final_loss = train(state)
    if hvd.rank() == 0:
        print(f"done: world={hvd.size()} batches={state.batch} "
              f"final loss {final_loss:.5f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
