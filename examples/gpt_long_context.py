"""Long-context GPT training: flash attention + sequence parallelism.

No reference analogue (the reference is a CNN-era data-parallel framework,
SURVEY §5.7); this demonstrates the TPU build's long-context flagship:

* ``--attention flash`` (default): the Pallas flash kernel
  (horovod_tpu/ops/flash_attention.py) trains at sequence lengths where
  the dense path cannot even allocate its score tensor — at seq 8192,
  batch 2, 12 heads, dense attention needs B*H*T^2 fp32 = 6.4 GB *per
  layer* for the scores alone; flash streams them through VMEM.
* ``--attention ring`` / ``--attention flash_ring``: sequence
  parallelism — shards the sequence over the mesh (`ppermute` ring over
  ICI) so per-chip memory is O(T/n); ``flash_ring`` runs the Pallas
  flash kernel at every ring step (scores stay in VMEM too). Run on the
  8-device CPU mesh to see an 8-way sequence shard:

      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
          python examples/gpt_long_context.py --attention ring --platform cpu

  (On the CPU mesh the Pallas kernels run in interpreter mode — an
  emulator. For ``flash_ring`` there, shrink the model:
  ``--layers 2 --seq-len 256 --steps 2``. Real speed needs real chips.)

Single real chip: `python examples/gpt_long_context.py` (flash, seq 8192).
"""

import _path_setup  # noqa: F401  (repo-root import shim)

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--attention",
                    choices=["flash", "ring", "flash_ring", "dense"],
                    default="flash")
    ap.add_argument("--seq-len", type=int, default=8192)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--batch-size", type=int, default=2,
                    help="global batch (sequences)")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu for the virtual "
                         "8-device mesh)")
    args = ap.parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import horovod_tpu as hvd
    from horovod_tpu.models import GPT, GPTConfig

    hvd.init()
    mesh = hvd.mesh()
    print(f"world {hvd.size()} mesh={mesh.devices.shape} "
          f"attention={args.attention} seq={args.seq_len}")

    cfg = GPTConfig(vocab_size=8192, num_layers=args.layers, num_heads=12,
                    d_model=768, d_ff=3072, max_seq_len=args.seq_len,
                    attention=args.attention, seq_axis=hvd.HVD_AXES,
                    remat=True)
    model = GPT(cfg)

    rs = np.random.RandomState(0)
    toks = rs.randint(0, cfg.vocab_size, (args.batch_size,
                                          args.seq_len + 1))
    x = jnp.asarray(toks[:, :-1])
    y = jnp.asarray(toks[:, 1:])

    # Ring modes shard the SEQUENCE over the mesh; flash/dense shard the
    # batch (plain DP).
    data_spec = (P(None, hvd.HVD_AXES)
                 if args.attention in ("ring", "flash_ring")
                 else hvd.data_pspec())

    variables = model.init(jax.random.PRNGKey(0), x[:1, :128])
    tx = hvd.DistributedOptimizer(optax.adamw(3e-4),
                                  compression=hvd.Compression.bf16)
    opt_state = tx.init(variables["params"])

    def loss_fn(p, xb, yb):
        logits = model.apply({"params": p}, xb)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, yb).mean()

    @jax.jit
    def train_step(p, s, xb, yb):
        def spmd(p, s, xb, yb):
            loss, grads = hvd.value_and_grad(loss_fn)(p, xb, yb)
            updates, ns = tx.update(grads, s, p)
            return optax.apply_updates(p, updates), ns, hvd.allreduce(loss)

        return hvd.shard_map(
            spmd, mesh=mesh,
            in_specs=(P(), P(), data_spec, data_spec),
            out_specs=(P(), P(), P()))(p, s, xb, yb)

    import time

    params = variables["params"]
    losses = []
    for step in range(args.steps):
        t0 = time.perf_counter()
        params, opt_state, loss = train_step(params, opt_state, x, y)
        loss = float(jax.block_until_ready(loss))
        losses.append(loss)
        if hvd.rank() == 0:
            dt = time.perf_counter() - t0
            tps = args.batch_size * args.seq_len / dt
            print(f"step {step}: loss {loss:.4f}  "
                  f"({dt * 1e3:.0f} ms, {tps:,.0f} tok/s)")

    assert losses[-1] < losses[0], "loss did not decrease"
    if hvd.rank() == 0:
        print(f"OK: loss {losses[0]:.4f} -> {losses[-1]:.4f} at "
              f"seq {args.seq_len} ({args.attention})")
    hvd.shutdown()


if __name__ == "__main__":
    main()
