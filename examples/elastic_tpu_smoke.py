"""Elastic-on-TPU smoke: shutdown→init cycles under the REAL runtime.

The elastic path's TPU-specific risk is not the rendezvous logic (covered
by tests/test_elastic_integration.py on CPU) but the runtime underneath:
PJRT client teardown and re-acquisition — the exact failure mode that
wedged the round-4 bench (a killed process left the tunnel/client in a
state where every later creation hung). This script drives that risk on
hardware, world of 1:

  cycle i:  hvd.init() → jit'd train step (compile on cycle 0, the XLA
            compilation cache must serve later cycles) → N steps →
            hvd.shutdown()  [optionally + PJRT backend reset]

and reports per-cycle compile/step/throughput timings as one JSON line.
Pass ``--reset-backend`` to also drop JAX's cached PJRT client between
cycles (``_reset_backends``) so every cycle re-creates the client from
scratch — device re-acquisition, the risky leg.

Run:  python examples/elastic_tpu_smoke.py [--cycles 3] [--steps 20]
                                           [--reset-backend]
Reference anchor: the reference's elastic driver re-forms NCCL contexts
on every world change (horovod/common/operations.cc shutdown path +
elastic/driver re-rendezvous); this is the TPU analogue of that teardown
churn at the PJRT layer.
"""

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import _path_setup  # noqa: F401  (repo root onto sys.path)
import horovod_tpu as hvd
from horovod_tpu.common.backend import (
    acquire_devices, clear_stale_tpu_locks, diagnose_backend,
    probe_backend, _reset_backends)
from horovod_tpu.models import GPT, gpt_tiny


def one_cycle(cycle: int, steps: int):
    t0 = time.perf_counter()
    hvd.init()
    init_s = time.perf_counter() - t0

    cfg = gpt_tiny()
    rs = np.random.RandomState(cycle)
    toks = rs.randint(0, cfg.vocab_size, (8, 129))
    x, y = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])
    model = GPT(cfg)
    variables = model.init(jax.random.PRNGKey(0), x[:1])
    tx = optax.adam(1e-3)
    opt = tx.init(variables["params"])

    @jax.jit
    def step(p, o, xb, yb):
        def loss_fn(p):
            out = model.apply({"params": p}, xb)
            return optax.softmax_cross_entropy_with_integer_labels(
                out, yb).mean()

        l, g = jax.value_and_grad(loss_fn)(p)
        u, o = tx.update(g, o, p)
        return jax.tree.map(lambda a, b: a + b, p, u), o, l

    t0 = time.perf_counter()
    p, opt, loss = step(variables["params"], opt, x, y)
    float(loss)  # host fetch = the only real barrier on relay runtimes
    compile_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    for _ in range(steps):
        p, opt, loss = step(p, opt, x, y)
    last = float(loss)  # fetch drains the chain
    steps_s = time.perf_counter() - t0

    hvd.shutdown()
    return {"cycle": cycle, "init_s": round(init_s, 3),
            "compile_s": round(compile_s, 2),
            "steps_s": round(steps_s, 3),
            "step_ms": round(steps_s / steps * 1e3, 2),
            "loss": round(last, 4)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cycles", type=int, default=3)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--reset-backend", action="store_true",
                    help="drop the cached PJRT client between cycles so "
                         "each one re-acquires the device from scratch")
    ap.add_argument("--probe-timeout", type=float, default=150.0)
    args = ap.parse_args()

    # Persistent compilation cache: the property under test is that a
    # re-init cycle reuses compiled programs instead of paying the full
    # 20-40 s TPU compile again (jit caches are per-Python-function, so
    # only the on-disk XLA cache survives the cycle).
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/horovod_tpu_elastic_smoke_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    # A programmatic CPU override (logic check) skips the accelerator
    # probe — the probe subprocess inherits the env, not jax.config.
    cpu_forced = jax.config.jax_platforms == "cpu"
    if not cpu_forced:
        clear_stale_tpu_locks()
        if not probe_backend(timeout=args.probe_timeout):
            diagnose_backend()
            raise SystemExit(
                "backend probe failed; not starting elastic cycles "
                "(diagnostics above)")
    devices = acquire_devices()
    platform = devices[0].platform
    print(f"platform={platform} device={getattr(devices[0], 'device_kind', platform)}")

    results = []
    for c in range(args.cycles):
        if c and args.reset_backend:
            t0 = time.perf_counter()
            _reset_backends()
            devices = acquire_devices()  # re-create the PJRT client
            print(f"cycle {c}: PJRT client re-acquired in "
                  f"{time.perf_counter() - t0:.2f}s")
        r = one_cycle(c, args.steps)
        results.append(r)
        print(f"cycle {c}: init {r['init_s']}s compile {r['compile_s']}s "
              f"{args.steps} steps {r['steps_s']}s "
              f"({r['step_ms']} ms/step) loss {r['loss']}")

    # Later cycles must reuse the compilation cache: a conservative 2x
    # bound (identical program; only the RNG data differs). Asserted on
    # TPU only — the persistent XLA cache does not serve the CPU
    # backend, so the CPU logic check just reports timings.
    if len(results) > 1 and platform == "tpu":
        warm = min(r["compile_s"] for r in results[1:])
        assert warm < max(2.0, 0.5 * results[0]["compile_s"]), (
            "compile cache not reused across re-init: "
            f"cold {results[0]['compile_s']}s vs warm {warm}s")
    print(json.dumps({"metric": "elastic_smoke_cycles",
                      "value": len(results), "unit": "cycles",
                      "platform": platform,
                      "reset_backend": bool(args.reset_backend),
                      "cycles": results}))


if __name__ == "__main__":
    main()
