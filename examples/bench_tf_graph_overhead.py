"""Per-collective overhead: TF eager vs tf.function(py_function) vs JAX.

Measures the cost of the `tf.py_function` boundary the TF binding uses
inside `tf.function` graphs (reference comparison point: the reference's
TF collectives are native AsyncOpKernels, tensorflow/mpi_ops.cc:371-419,
with no Python hop). Run directly: spawns a 2-process world over the
native TCP data plane on localhost and prints median per-allreduce
latency for each path and payload size; rank 0 prints a JSON summary.

    python examples/bench_tf_graph_overhead.py
"""

import _path_setup  # noqa: F401  (repo-root import shim)

import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def worker():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import tensorflow as tf

    import horovod_tpu as hvd_jax
    import horovod_tpu.tensorflow as hvd_tf

    hvd_tf.init()
    rank = hvd_tf.rank()
    results = {}
    for label, n in [("4KB", 1024), ("4MB", 1024 * 1024)]:
        x_tf = tf.constant(np.random.randn(n).astype(np.float32))
        x_jax = jnp.asarray(np.random.randn(n).astype(np.float32))

        @tf.function
        def graph_allreduce(t):
            return hvd_tf.allreduce(t, name=f"g.{label}")

        def timeit(fn, iters=30):
            fn()  # warm (trace + first negotiation)
            ts = []
            for _ in range(iters):
                t0 = time.perf_counter()
                fn()
                ts.append(time.perf_counter() - t0)
            return float(np.median(ts) * 1e6)  # us

        results[label] = {
            "tf_eager_us": timeit(
                lambda: hvd_tf.allreduce(x_tf, name=f"e.{label}")),
            "tf_function_us": timeit(lambda: graph_allreduce(x_tf)),
            "jax_eager_us": timeit(
                lambda: hvd_jax.allreduce(x_jax, name=f"j.{label}")),
        }
    from horovod_tpu.tensorflow import _native_ops

    if rank == 0:
        for label, r in results.items():
            # positive = the graph boundary costs vs eager (py_function
            # path); negative = the native custom op beats eager dispatch
            r["graph_vs_eager_us"] = round(
                r["tf_function_us"] - r["tf_eager_us"], 1)
        results["native_graph_ops"] = _native_ops() is not None
        print(json.dumps(results, indent=2), flush=True)
    hvd_tf.shutdown()


def main():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    procs = []
    for r in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update({
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
            "HOROVOD_RANK": str(r),
            "HOROVOD_SIZE": "2",
            "HOROVOD_CONTROLLER_ADDR": "127.0.0.1",
            "HOROVOD_CONTROLLER_PORT": str(port),
            "HVD_TF_BENCH_WORKER": "1",
        })
        procs.append(subprocess.Popen([sys.executable, __file__], env=env))
    rc = max(p.wait() for p in procs)
    sys.exit(rc)


if __name__ == "__main__":
    if os.environ.get("HVD_TF_BENCH_WORKER"):
        worker()
    else:
        main()
