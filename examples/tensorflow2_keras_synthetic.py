"""Keras training example (reference analogue:
examples/tensorflow2/tensorflow2_keras_mnist.py — DistributedOptimizer +
broadcast/metric-average callbacks).

Run with the launcher (one process per rank):

    hvdrun -np 2 -H localhost:2 python examples/tensorflow2_keras_synthetic.py
"""

import _path_setup  # noqa: F401  (repo-root import shim)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import keras  # noqa: E402

import horovod_tpu.keras as hvd  # noqa: E402


def main():
    hvd.init()

    model = keras.Sequential([
        keras.layers.Input(shape=(32,)),
        keras.layers.Dense(64, activation="relu"),
        keras.layers.Dense(10),
    ])
    opt = keras.optimizers.Adam(1e-2 * hvd.size())
    model.compile(
        optimizer=hvd.DistributedOptimizer(opt),
        loss=keras.losses.SparseCategoricalCrossentropy(from_logits=True),
    )

    rs = np.random.RandomState(hvd.rank())  # per-rank shard
    x = rs.randn(512, 32).astype(np.float32)
    y = rs.randint(0, 10, 512)

    history = model.fit(
        x, y, batch_size=64, epochs=5,
        verbose=1 if hvd.rank() == 0 else 0,
        callbacks=[
            # Rank-0 weights win at start; metrics averaged across ranks.
            hvd.callbacks.BroadcastGlobalVariablesCallback(0),
            hvd.callbacks.MetricAverageCallback(),
        ])
    losses = history.history["loss"]
    assert losses[-1] < losses[0], losses
    print(f"rank {hvd.rank()}: OK loss {losses[0]:.4f} -> {losses[-1]:.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
