"""MXNet (gluon) training example over the native data plane (reference
analogue: examples/mxnet/mxnet_mnist.py — synthetic features instead of
an MNIST download; this image has zero egress).

Run with the launcher on a machine with mxnet installed::

    hvdrun -np 2 -H localhost:2 python examples/mxnet_synthetic.py

The DistributedTrainer syncs gradients in gluon's ``_allreduce_grads``
hook via one grouped sum-allreduce; the world average rides the
trainer's ``rescale_grad``. Parameters broadcast from rank 0 after the
deferred gluon initialization (the binding's deferred-init hook covers
shapes that only materialize at first forward).
"""

import _path_setup  # noqa: F401  (repo-root import shim)

import jax

# MXNet here is a host-side framework; force the CPU JAX platform so
# workers never race each other for an accelerator.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import horovod_tpu.mxnet as hvd  # noqa: E402


def main():
    import mxnet as mx
    from mxnet import autograd, gluon

    hvd.init()

    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(64, activation="relu"), gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())

    trainer = hvd.DistributedTrainer(
        net.collect_params(), "sgd",
        {"learning_rate": 0.01 * hvd.size()})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    rng = np.random.default_rng(hvd.rank())
    first = True
    for step in range(50):
        x = mx.nd.array(rng.normal(size=(32, 32)).astype("float32"))
        y = mx.nd.array(rng.integers(0, 10, size=(32,)))
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        if first:
            # After the first forward materialized every shape.
            hvd.broadcast_parameters(net.collect_params(), root_rank=0)
            first = False
        trainer.step(x.shape[0])
        if step % 10 == 0 and hvd.rank() == 0:
            print(f"step {step}: loss {float(loss.mean().asscalar()):.4f}")

    if hvd.rank() == 0:
        print(f"done: world={hvd.size()}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
