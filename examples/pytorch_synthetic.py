"""PyTorch training example over the native data plane (reference
analogue: examples/pytorch/pytorch_synthetic_benchmark.py / pytorch_mnist
— the README recipe of the torch binding).

Run with the launcher (one process per rank):

    hvdrun -np 2 -H localhost:2 python examples/pytorch_synthetic.py
"""

import _path_setup  # noqa: F401  (repo-root import shim)

import os

# Torch here is a host-side framework; force the CPU JAX platform so
# workers never race each other for an accelerator.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import torch  # noqa: E402
import torch.nn.functional as F  # noqa: E402

import horovod_tpu.torch as hvd  # noqa: E402


class Net(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = torch.nn.Linear(32, 64)
        self.fc2 = torch.nn.Linear(64, 10)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


def main():
    hvd.init()
    torch.manual_seed(1234)  # same init everywhere; broadcast confirms

    model = Net()
    # Scale the learning rate by world size (reference docs recipe).
    optimizer = torch.optim.SGD(model.parameters(), lr=0.01 * hvd.size())
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    rs = np.random.RandomState(hvd.rank())  # per-rank data shard
    x = torch.from_numpy(rs.randn(256, 32).astype(np.float32))
    y = torch.from_numpy(rs.randint(0, 10, 256))

    losses = []
    for epoch in range(10):
        for i in range(0, len(x), 32):
            optimizer.zero_grad()
            loss = F.cross_entropy(model(x[i:i + 32]), y[i:i + 32])
            loss.backward()
            optimizer.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0], (losses[0], losses[-1])

    # All ranks converged to IDENTICAL weights (averaged gradients).
    w = torch.cat([p.detach().reshape(-1) for p in model.parameters()])
    gathered = hvd.allgather(w[None, :])
    assert torch.allclose(gathered[0], gathered[-1], atol=1e-6)
    print(f"rank {hvd.rank()}: OK loss {losses[0]:.4f} -> {losses[-1]:.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
