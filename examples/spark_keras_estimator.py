"""Spark estimator example (reference analogue:
examples/spark/keras/keras_spark_mnist.py — synthetic features instead
of an MNIST download; this image has zero egress).

Run on a machine with pyspark installed::

    python examples/spark_keras_estimator.py [--num-proc 2] [--epochs 3]

Builds a small DataFrame, fits a Keras model across ``--num-proc``
barrier-stage workers with the distributed optimizer (weights
broadcast from rank 0, per-epoch metrics rank-averaged, a 15%
validation split evaluated each epoch), and scores the returned
Spark Transformer. The Store materializes each rank's shard as
chunked npz files which workers stream one chunk at a time, so the
dataset never has to fit in worker memory
(HOROVOD_SPARK_CHUNK_ROWS tunes the chunk size). Feature columns are
scalar columns, one per feature — the reference estimator's
convention.
"""

import argparse
import tempfile

import numpy as np

import _path_setup  # noqa: F401  (repo root onto sys.path)

N_FEATURES = 8


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-proc", type=int, default=2)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--rows", type=int, default=4096)
    ap.add_argument("--work-dir", default=None,
                    help="Store prefix (default: a temp dir; use an "
                         "hdfs:// or dbfs:/ path on a cluster)")
    args = ap.parse_args()

    from pyspark.sql import SparkSession

    import keras
    from horovod_tpu.spark import KerasEstimator
    from horovod_tpu.spark.store import Store

    spark = (SparkSession.builder.master(f"local[{args.num_proc}]")
             .appName("hvdtpu-estimator").getOrCreate())

    # y = sign(w.x) on N_FEATURES features — learnable by a tiny MLP.
    rng = np.random.default_rng(0)
    w = rng.normal(size=N_FEATURES)
    feats = rng.normal(size=(args.rows, N_FEATURES))
    labels = (feats @ w > 0).astype("float32")
    feature_cols = [f"f{i}" for i in range(N_FEATURES)]
    df = spark.createDataFrame(
        [tuple(map(float, feats[i])) + (float(labels[i]),)
         for i in range(args.rows)],
        feature_cols + ["label"])

    model = keras.Sequential([
        keras.layers.Input(shape=(N_FEATURES,)),
        keras.layers.Dense(16, activation="relu"),
        keras.layers.Dense(1, activation="sigmoid"),
    ])

    store = Store.create(args.work_dir or tempfile.mkdtemp())
    est = KerasEstimator(model=model, store=store,
                         feature_cols=feature_cols, label_cols=["label"],
                         batch_size=64, epochs=args.epochs,
                         num_proc=args.num_proc,
                         validation=0.15, loss="binary_crossentropy")
    transformer = est.fit(df)

    print("per-epoch loss (rank-averaged):", est.history_["loss"])
    print("per-epoch val_loss:", est.history_.get("val_loss"))
    pred = transformer.transform(df.limit(256)).toPandas()
    acc = (pred["prediction"].map(lambda p: float(p[0]) > 0.5)
           == pred["label"].astype(bool)).mean()
    print(f"accuracy on 256 rows: {acc:.3f}")
    spark.stop()


if __name__ == "__main__":
    main()
