"""Make `import horovod_tpu` work from a source checkout: the launcher
spawns `python examples/<name>.py`, whose sys.path[0] is examples/, not
the repo root. Imported for its side effect."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
