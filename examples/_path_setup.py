"""Make `import horovod_tpu` work from a source checkout: the launcher
spawns `python examples/<name>.py`, whose sys.path[0] is examples/, not
the repo root. Imported for its side effect; also hosts the shared
``--cpu`` virtual-mesh helpers the examples use."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def add_cpu_flag(ap):
    ap.add_argument("--cpu", type=int, default=0, metavar="N",
                    help="force an N-virtual-device CPU mesh (no TPU "
                         "needed; works even when a TPU backend exists)")
    return ap


def apply_cpu_flag(args):
    if getattr(args, "cpu", 0):
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)
