"""Ray executor example (reference analogue:
examples/ray/ray_train.py shape): run the synthetic JAX DP training
function on Ray actors colocated via a placement group.

Run on a machine with ray installed::

    python examples/ray_synthetic.py [--num-workers 2] [--steps 20]
    python examples/ray_synthetic.py --elastic --min-np 1 --max-np 4

Each worker forces the CPU backend (Ray actors share the host; a TPU
variant would instead map one worker per TPU host and skip the
override). The elastic variant uses ElasticRayExecutor — discovery
comes from the Ray cluster's live node set, and ``run`` returns
whether the job finished with a successful worker.
"""

import argparse
import functools

import _path_setup  # noqa: F401  (repo root onto sys.path)


def train_fn(steps: int = 20):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd

    hvd.init()
    rng = np.random.default_rng(hvd.rank())
    w_true = jnp.arange(4.0)
    params = jnp.zeros(4)
    opt = hvd.DistributedOptimizer(optax.sgd(0.1))
    state = opt.init(params)

    def loss_fn(p, x, y):
        return jnp.mean((x @ p - y) ** 2)

    loss = None
    for _ in range(steps):
        x = jnp.asarray(rng.normal(size=(32, 4)), jnp.float32)
        y = x @ w_true
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, state = opt.update(grads, state, params)
        params = optax.apply_updates(params, updates)
    final = hvd.allreduce(loss, op=hvd.Average)
    out = (hvd.rank(), hvd.size(), float(final))
    hvd.shutdown()
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-workers", type=int, default=2)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--elastic", action="store_true")
    ap.add_argument("--min-np", type=int, default=1)
    ap.add_argument("--max-np", type=int, default=4)
    args = ap.parse_args()

    import ray

    ray.init(ignore_reinit_error=True)
    if args.elastic:
        from horovod_tpu.ray import ElasticRayExecutor

        ex = ElasticRayExecutor(min_np=args.min_np, max_np=args.max_np)
        ex.start()
        ok = ex.run(functools.partial(train_fn, args.steps))
        print(f"elastic job {'succeeded' if ok else 'failed'}")
    else:
        from horovod_tpu.ray import RayExecutor

        ex = RayExecutor(num_workers=args.num_workers)
        ex.start()
        results = ex.run(train_fn, kwargs={"steps": args.steps})
        ex.shutdown()
        for rank, size, loss in results:
            print(f"rank {rank}/{size}: final rank-averaged loss "
                  f"{loss:.5f}")


if __name__ == "__main__":
    main()
