"""Elastic (fault-tolerant) JAX training example (reference analogue:
examples/elastic/tensorflow2_mnist_elastic.py — @hvd.elastic.run +
committed State).

Run elastically (the driver respawns workers and re-forms the world on
host churn; state rolls back to the last commit):

    hvdrun -np 2 --min-np 2 -H localhost:2 python examples/elastic_jax.py
"""

import _path_setup  # noqa: F401  (repo-root import shim)

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import elastic  # noqa: E402

TOTAL_BATCHES = 20


def main():
    # NOTE: no hvd.init() here — elastic.run rendezvouses with the driver
    # and initializes each world incarnation itself.
    model_dim = 16
    tx = optax.sgd(0.05)

    @elastic.run
    def train(state):
        loss = jnp.asarray(float("inf"))  # resume-at-end: loop may not run
        while state.batch < TOTAL_BATCHES:
            rs = np.random.RandomState(state.batch)  # deterministic data
            x = jnp.asarray(rs.randn(8, model_dim), jnp.float32)
            y = jnp.asarray(rs.randn(8, 1), jnp.float32)

            def loss_fn(w):
                return jnp.mean((x @ w - y) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(state.params["w"])
            grads = hvd.allreduce(grads, name=f"g.{state.batch}")
            updates, state.opt_state = tx.update(grads, state.opt_state)
            state.params = {"w": optax.apply_updates(state.params["w"],
                                                     updates)}
            state.batch += 1
            state.commit()  # checkpoint + raise on host churn
        return float(loss)

    w0 = jnp.zeros((model_dim, 1))
    state = elastic.JaxState(params={"w": w0}, opt_state=tx.init(w0),
                             batch=0)
    final_loss = train(state)
    print(f"rank {hvd.rank()}: OK trained {state.batch} batches, "
          f"final loss {final_loss:.4f} (world={hvd.size()})")
    hvd.shutdown()


if __name__ == "__main__":
    main()
