#include "tensor_queue.h"

namespace hvdtpu {

Status TensorQueue::AddToTensorQueue(EntryPtr entry, Request message) {
  std::lock_guard<std::mutex> g(mu_);
  if (table_.find(entry->name) != table_.end()) {
    return Status::InvalidArgument(HVDTPU_DUPLICATE_NAME_ERROR);
  }
  table_.emplace(entry->name, std::move(entry));
  messages_.push_back(std::move(message));
  return Status::OK();
}

std::vector<Request> TensorQueue::PopMessages() {
  std::lock_guard<std::mutex> g(mu_);
  std::vector<Request> out(messages_.begin(), messages_.end());
  messages_.clear();
  return out;
}

std::vector<EntryPtr> TensorQueue::GetAndRemoveEntries(
    const std::vector<std::string>& names) {
  std::lock_guard<std::mutex> g(mu_);
  std::vector<EntryPtr> out;
  out.reserve(names.size());
  for (const auto& n : names) {
    auto it = table_.find(n);
    if (it != table_.end()) {
      out.push_back(it->second);
      table_.erase(it);
    } else {
      out.push_back(nullptr);
    }
  }
  return out;
}

EntryPtr TensorQueue::Get(const std::string& name) {
  std::lock_guard<std::mutex> g(mu_);
  auto it = table_.find(name);
  return it == table_.end() ? nullptr : it->second;
}

void TensorQueue::AbortAll(const Status& reason) {
  std::vector<EntryPtr> victims;
  {
    std::lock_guard<std::mutex> g(mu_);
    for (auto& kv : table_) victims.push_back(kv.second);
    table_.clear();
    messages_.clear();
  }
  for (auto& e : victims) e->MarkDone(reason);
}

size_t TensorQueue::size() {
  std::lock_guard<std::mutex> g(mu_);
  return table_.size();
}

}  // namespace hvdtpu
