#include "socket.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <thread>

#include "logging.h"

namespace hvdtpu {

TcpSocket& TcpSocket::operator=(TcpSocket&& o) noexcept {
  if (this != &o) {
    Close();
    fd_ = o.fd_;
    o.fd_ = -1;
  }
  return *this;
}

void TcpSocket::Close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

TcpSocket TcpSocket::Connect(const std::string& host, int port,
                             double timeout_secs) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration<double>(timeout_secs);
  std::string port_s = std::to_string(port);
  while (true) {
    struct addrinfo hints;
    memset(&hints, 0, sizeof(hints));
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    int rc = ::getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res);
    if (rc == 0) {
      for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
        int fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
        if (fd < 0) continue;
        if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) {
          int one = 1;
          ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          ::freeaddrinfo(res);
          return TcpSocket(fd);
        }
        ::close(fd);
      }
      ::freeaddrinfo(res);
    }
    if (std::chrono::steady_clock::now() >= deadline) {
      HVDTPU_LOG(ERROR) << "connect to " << host << ":" << port
                        << " timed out after " << timeout_secs << "s";
      return TcpSocket();
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

void TcpSocket::SetNonBlocking() {
  int flags = ::fcntl(fd_, F_GETFL, 0);
  if (flags >= 0) ::fcntl(fd_, F_SETFL, flags | O_NONBLOCK);
}

bool TcpSocket::SendAll(const void* data, size_t size) {
  const char* p = static_cast<const char*>(data);
  while (size > 0) {
    ssize_t n = ::send(fd_, p, size, MSG_NOSIGNAL);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        struct pollfd pfd{fd_, POLLOUT, 0};
        if (::poll(&pfd, 1, 30000) <= 0) return false;
        continue;
      }
      return false;
    }
    p += n;
    size -= static_cast<size_t>(n);
  }
  return true;
}

bool TcpSocket::RecvAll(void* data, size_t size) {
  char* p = static_cast<char*>(data);
  while (size > 0) {
    ssize_t n = ::recv(fd_, p, size, 0);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        struct pollfd pfd{fd_, POLLIN, 0};
        if (::poll(&pfd, 1, 30000) <= 0) return false;
        continue;
      }
      return false;
    }
    p += n;
    size -= static_cast<size_t>(n);
  }
  return true;
}

bool TcpSocket::SendFrame(const std::vector<char>& payload) {
  int64_t len = static_cast<int64_t>(payload.size());
  if (!SendAll(&len, 8)) return false;
  return payload.empty() || SendAll(payload.data(), payload.size());
}

bool TcpSocket::RecvFrame(std::vector<char>* payload) {
  int64_t len = 0;
  if (!RecvAll(&len, 8)) return false;
  if (len < 0 || len > (int64_t{1} << 40)) return false;
  payload->resize(static_cast<size_t>(len));
  return len == 0 || RecvAll(payload->data(), payload->size());
}

bool TcpSocket::SendRecv(const void* send_buf, size_t send_size,
                         void* recv_buf, size_t recv_size) {
  const char* sp = static_cast<const char*>(send_buf);
  char* rp = static_cast<char*>(recv_buf);
  size_t to_send = send_size, to_recv = recv_size;
  while (to_send > 0 || to_recv > 0) {
    struct pollfd pfd;
    pfd.fd = fd_;
    pfd.events = 0;
    if (to_send > 0) pfd.events |= POLLOUT;
    if (to_recv > 0) pfd.events |= POLLIN;
    pfd.revents = 0;
    int rc = ::poll(&pfd, 1, 30000);
    if (rc < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    if (rc == 0) {
      HVDTPU_LOG(ERROR) << "SendRecv poll timeout (30s)";
      return false;
    }
    if (pfd.revents & (POLLERR | POLLHUP | POLLNVAL)) {
      // Drain pending reads before declaring the peer dead.
      if (!(pfd.revents & POLLIN)) return false;
    }
    if ((pfd.revents & POLLOUT) && to_send > 0) {
      ssize_t n = ::send(fd_, sp, to_send, MSG_NOSIGNAL);
      if (n < 0 && errno != EINTR && errno != EAGAIN) return false;
      if (n > 0) {
        sp += n;
        to_send -= static_cast<size_t>(n);
      }
    }
    if ((pfd.revents & POLLIN) && to_recv > 0) {
      ssize_t n = ::recv(fd_, rp, to_recv, 0);
      if (n == 0) return false;
      if (n < 0 && errno != EINTR && errno != EAGAIN) return false;
      if (n > 0) {
        rp += n;
        to_recv -= static_cast<size_t>(n);
      }
    }
  }
  return true;
}

bool TcpServer::Listen(int port) {
  fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd_ < 0) return false;
  int one = 1;
  ::setsockopt(fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd_, reinterpret_cast<struct sockaddr*>(&addr), sizeof(addr)) <
      0) {
    ::close(fd_);
    fd_ = -1;
    return false;
  }
  if (::listen(fd_, 128) < 0) {
    ::close(fd_);
    fd_ = -1;
    return false;
  }
  socklen_t len = sizeof(addr);
  ::getsockname(fd_, reinterpret_cast<struct sockaddr*>(&addr), &len);
  port_ = ntohs(addr.sin_port);
  return true;
}

TcpSocket TcpServer::Accept(double timeout_secs) {
  struct pollfd pfd;
  pfd.fd = fd_;
  pfd.events = POLLIN;
  pfd.revents = 0;
  int rc = ::poll(&pfd, 1, static_cast<int>(timeout_secs * 1000));
  if (rc <= 0) return TcpSocket();
  int cfd = ::accept(fd_, nullptr, nullptr);
  if (cfd < 0) return TcpSocket();
  int one = 1;
  ::setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return TcpSocket(cfd);
}

void TcpServer::Close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

}  // namespace hvdtpu
