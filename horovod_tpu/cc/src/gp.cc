#include "gp.h"

#include <cmath>

namespace hvdtpu {

double GaussianProcess::Kernel(const std::vector<double>& a,
                               const std::vector<double>& b) const {
  double d2 = 0.0;
  for (int i = 0; i < dims_; ++i) {
    double d = a[i] - b[i];
    d2 += d * d;
  }
  return std::exp(-d2 / (2.0 * length_scale_ * length_scale_));
}

bool GaussianProcess::Fit(const std::vector<std::vector<double>>& x,
                          const std::vector<double>& y) {
  size_t n = x.size();
  x_ = x;
  // K + noise^2 I
  std::vector<std::vector<double>> k(n, std::vector<double>(n));
  for (size_t i = 0; i < n; ++i) {
    for (size_t j = 0; j <= i; ++j) {
      k[i][j] = k[j][i] = Kernel(x[i], x[j]);
    }
    k[i][i] += noise_ * noise_;
  }
  // Cholesky K = L L^T
  l_.assign(n, std::vector<double>(n, 0.0));
  for (size_t i = 0; i < n; ++i) {
    for (size_t j = 0; j <= i; ++j) {
      double s = k[i][j];
      for (size_t m = 0; m < j; ++m) s -= l_[i][m] * l_[j][m];
      if (i == j) {
        if (s <= 0.0) return false;
        l_[i][i] = std::sqrt(s);
      } else {
        l_[i][j] = s / l_[j][j];
      }
    }
  }
  // alpha = K^-1 y via two triangular solves.
  std::vector<double> z(n);
  for (size_t i = 0; i < n; ++i) {
    double s = y[i];
    for (size_t m = 0; m < i; ++m) s -= l_[i][m] * z[m];
    z[i] = s / l_[i][i];
  }
  alpha_.assign(n, 0.0);
  for (size_t ii = n; ii-- > 0;) {
    double s = z[ii];
    for (size_t m = ii + 1; m < n; ++m) s -= l_[m][ii] * alpha_[m];
    alpha_[ii] = s / l_[ii][ii];
  }
  return true;
}

void GaussianProcess::Predict(const std::vector<double>& x, double* mean,
                              double* stddev) const {
  size_t n = x_.size();
  std::vector<double> kstar(n);
  for (size_t i = 0; i < n; ++i) kstar[i] = Kernel(x, x_[i]);
  double mu = 0.0;
  for (size_t i = 0; i < n; ++i) mu += kstar[i] * alpha_[i];
  // v = L^-1 k*; var = k(x,x) - v.v
  std::vector<double> v(n);
  for (size_t i = 0; i < n; ++i) {
    double s = kstar[i];
    for (size_t m = 0; m < i; ++m) s -= l_[i][m] * v[m];
    v[i] = s / l_[i][i];
  }
  double var = Kernel(x, x);
  for (size_t i = 0; i < n; ++i) var -= v[i] * v[i];
  *mean = mu;
  *stddev = var > 0.0 ? std::sqrt(var) : 0.0;
}

double GaussianProcess::ExpectedImprovement(const std::vector<double>& x,
                                            double best_y, double xi) const {
  double mu, sigma;
  Predict(x, &mu, &sigma);
  if (sigma <= 1e-12) return 0.0;
  double imp = mu - best_y - xi;
  double z = imp / sigma;
  double cdf = 0.5 * std::erfc(-z / std::sqrt(2.0));
  double pdf = std::exp(-0.5 * z * z) / std::sqrt(2.0 * M_PI);
  return imp * cdf + sigma * pdf;
}

}  // namespace hvdtpu
