// Adasum adaptive-summation allreduce (host path).
//
// Reference: horovod/common/ops/adasum/adasum.h — recursive
// distance-doubling where each pairwise merge computes dot products and
// squared norms and combines `a*(1 - dot/2|a|²) + b*(1 - dot/2|b|²)` so
// orthogonal gradient contributions add and parallel ones average
// (adasum.h:73-141, FusedAllreduce VHDD at 196+). Like the reference's MPI
// tree (adasum_mpi.cc), ranks must be a power of two
// (torch/mpi_ops.py:95-115 enforces the same).
//
// This host implementation exchanges full buffers per level (log2(N)
// rounds) instead of vector-halving — numerically identical, simpler, and
// the eager path is latency- not bandwidth-bound. The compiled TPU path has
// its own XLA implementation (horovod_tpu/ops/adasum.py).
#ifndef HVDTPU_ADASUM_H
#define HVDTPU_ADASUM_H

#include "common.h"
#include "transport.h"

namespace hvdtpu {

// In-place adasum allreduce of `count` elements. Supports float32/float64
// (16-bit floats are widened by the caller). Returns PreconditionError for
// non-power-of-2 world sizes.
Status AdasumAllreduce(Transport& t, void* buf, int64_t count, DataType dt);

}  // namespace hvdtpu

#endif  // HVDTPU_ADASUM_H
