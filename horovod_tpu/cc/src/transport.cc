#include "transport.h"

#include <arpa/inet.h>
#include <atomic>
#include <netinet/in.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>

#include "logging.h"

namespace hvdtpu {

namespace {

// The address this process uses to reach the coordinator — the right NIC for
// peers to reach us on multi-host jobs (the reference discovers routable
// interfaces with a driver/task RPC dance, driver_service.py; asking the
// kernel which source address the control connection bound to achieves the
// same for our star topology).
std::string LocalAddrOf(const TcpSocket& sock) {
  struct sockaddr_storage ss;
  socklen_t len = sizeof(ss);
  if (::getsockname(sock.fd(), reinterpret_cast<struct sockaddr*>(&ss),
                    &len) != 0) {
    return "127.0.0.1";
  }
  char buf[64] = {0};
  if (ss.ss_family == AF_INET) {
    auto* a = reinterpret_cast<struct sockaddr_in*>(&ss);
    ::inet_ntop(AF_INET, &a->sin_addr, buf, sizeof(buf));
  } else if (ss.ss_family == AF_INET6) {
    auto* a = reinterpret_cast<struct sockaddr_in6*>(&ss);
    ::inet_ntop(AF_INET6, &a->sin6_addr, buf, sizeof(buf));
  }
  return buf[0] ? std::string(buf) : std::string("127.0.0.1");
}

}  // namespace

namespace {
std::atomic<int> g_bound_control_port{0};
}  // namespace

int BoundControlPort() { return g_bound_control_port.load(); }

void ResetBoundControlPort() { g_bound_control_port.store(0); }

Transport::~Transport() = default;

std::unique_ptr<Transport> Transport::Create(int rank, int size,
                                             const std::string& coord_addr,
                                             int coord_port,
                                             double timeout_secs) {
  std::unique_ptr<Transport> t(new Transport(rank, size));
  g_bound_control_port.store(0);  // fresh world incarnation
  if (size == 1) return t;  // no wires needed
  if (!t->data_server_.Listen(0)) {
    HVDTPU_LOG(ERROR) << "failed to open data-plane listener";
    return nullptr;
  }
  bool ok = rank == 0 ? t->SetupCoordinator(coord_port, timeout_secs)
                      : t->SetupWorker(coord_addr, coord_port, timeout_secs);
  if (!ok) return nullptr;
  return t;
}

bool Transport::SetupCoordinator(int coord_port, double timeout_secs) {
  if (!control_server_.Listen(coord_port)) {
    HVDTPU_LOG(ERROR) << "coordinator failed to listen on port " << coord_port;
    return false;
  }
  // Publish the actually-bound port (meaningful when coord_port was 0)
  // BEFORE blocking in Accept: the elastic rank-0 worker's watcher thread
  // reads it and reports to the driver so peers can connect.
  g_bound_control_port.store(control_server_.port());
  control_.resize(static_cast<size_t>(size_));
  std::vector<std::string> addrs(static_cast<size_t>(size_), "127.0.0.1");
  std::vector<int> ports(static_cast<size_t>(size_), 0);
  ports[0] = data_server_.port();
  // Accept size-1 hellos: {rank, data_port}; data addr observed from the
  // connection itself.
  for (int i = 1; i < size_; ++i) {
    TcpSocket s = control_server_.Accept(timeout_secs);
    if (!s.valid()) {
      HVDTPU_LOG(ERROR) << "coordinator: timed out waiting for workers ("
                        << i - 1 << "/" << size_ - 1 << " connected)";
      return false;
    }
    std::vector<char> hello;
    if (!s.RecvFrame(&hello)) return false;
    WireReader r(hello);
    int32_t wrank = r.i32();
    int32_t wport = r.i32();
    if (wrank <= 0 || wrank >= size_ || control_[wrank].valid()) {
      HVDTPU_LOG(ERROR) << "coordinator: bad hello rank " << wrank;
      return false;
    }
    struct sockaddr_storage ss;
    socklen_t len = sizeof(ss);
    char buf[64] = {0};
    if (::getpeername(s.fd(), reinterpret_cast<struct sockaddr*>(&ss), &len) ==
        0) {
      if (ss.ss_family == AF_INET) {
        auto* a = reinterpret_cast<struct sockaddr_in*>(&ss);
        ::inet_ntop(AF_INET, &a->sin_addr, buf, sizeof(buf));
      } else if (ss.ss_family == AF_INET6) {
        auto* a = reinterpret_cast<struct sockaddr_in6*>(&ss);
        ::inet_ntop(AF_INET6, &a->sin6_addr, buf, sizeof(buf));
      }
    }
    addrs[wrank] = buf[0] ? buf : "127.0.0.1";
    ports[wrank] = wport;
    control_[wrank] = std::move(s);
  }
  // Coordinator's own data addr: as seen by workers we don't know generally;
  // use the address of the first worker's control socket's local end.
  addrs[0] = LocalAddrOf(control_[1]);
  // Broadcast the address book.
  WireWriter w;
  for (int i = 0; i < size_; ++i) {
    w.str(addrs[i]);
    w.i32(ports[i]);
  }
  std::vector<char> book = w.take();
  for (int i = 1; i < size_; ++i) {
    if (!control_[i].SendFrame(book)) return false;
  }
  return SetupDataMesh(addrs, ports, timeout_secs);
}

bool Transport::SetupWorker(const std::string& coord_addr, int coord_port,
                            double timeout_secs) {
  control_.resize(1);
  control_[0] = TcpSocket::Connect(coord_addr, coord_port, timeout_secs);
  if (!control_[0].valid()) return false;
  WireWriter hello;
  hello.i32(rank_);
  hello.i32(data_server_.port());
  if (!control_[0].SendFrame(hello.data())) return false;
  std::vector<char> book;
  if (!control_[0].RecvFrame(&book)) return false;
  WireReader r(book);
  std::vector<std::string> addrs(static_cast<size_t>(size_));
  std::vector<int> ports(static_cast<size_t>(size_));
  for (int i = 0; i < size_; ++i) {
    addrs[i] = r.str();
    ports[i] = r.i32();
  }
  return SetupDataMesh(addrs, ports, timeout_secs);
}

bool Transport::SetupDataMesh(const std::vector<std::string>& addrs,
                              const std::vector<int>& ports,
                              double timeout_secs) {
  // Deterministic full mesh: rank r dials every lower rank and accepts from
  // every higher rank; the dialer announces its rank.
  data_.resize(static_cast<size_t>(size_));
  for (int peer = 0; peer < rank_; ++peer) {
    TcpSocket s = TcpSocket::Connect(addrs[peer], ports[peer], timeout_secs);
    if (!s.valid()) {
      HVDTPU_LOG(ERROR) << "data mesh: rank " << rank_
                        << " failed to reach rank " << peer << " at "
                        << addrs[peer] << ":" << ports[peer];
      return false;
    }
    int32_t me = rank_;
    if (!s.SendAll(&me, 4)) return false;
    s.SetNonBlocking();
    data_[peer] = std::move(s);
  }
  for (int n = rank_ + 1; n < size_; ++n) {
    TcpSocket s = data_server_.Accept(timeout_secs);
    if (!s.valid()) {
      HVDTPU_LOG(ERROR) << "data mesh: rank " << rank_
                        << " timed out accepting peers";
      return false;
    }
    int32_t peer = -1;
    if (!s.RecvAll(&peer, 4) || peer <= rank_ || peer >= size_ ||
        data_[peer].valid()) {
      HVDTPU_LOG(ERROR) << "data mesh: bad peer hello " << peer;
      return false;
    }
    s.SetNonBlocking();
    data_[peer] = std::move(s);
  }
  return true;
}

bool Transport::GatherRequestLists(std::vector<RequestList>* out) {
  out->assign(static_cast<size_t>(size_), RequestList{});
  for (int i = 1; i < size_; ++i) {
    std::vector<char> frame;
    if (!control_[i].RecvFrame(&frame)) {
      HVDTPU_LOG(ERROR) << "coordinator: lost worker rank " << i;
      return false;
    }
    WireReader r(frame);
    (*out)[i] = RequestList::Deserialize(r);
  }
  return true;
}

bool Transport::SendRequestList(const RequestList& list) {
  WireWriter w;
  list.Serialize(w);
  return control_[0].SendFrame(w.data());
}

bool Transport::BcastResponseList(const ResponseList& list) {
  WireWriter w;
  list.Serialize(w);
  std::vector<char> frame = w.take();
  for (int i = 1; i < size_; ++i) {
    if (!control_[i].SendFrame(frame)) return false;
  }
  return true;
}

bool Transport::RecvResponseList(ResponseList* out) {
  std::vector<char> frame;
  if (!control_[0].RecvFrame(&frame)) return false;
  WireReader r(frame);
  *out = ResponseList::Deserialize(r);
  return true;
}

bool Transport::SendToRank(int dst, const void* data, size_t size) {
  return data_[dst].SendAll(data, size);
}

bool Transport::RecvFromRank(int src, void* data, size_t size) {
  return data_[src].RecvAll(data, size);
}

bool Transport::RingExchange(int right, const void* send_buf,
                             size_t send_size, int left, void* recv_buf,
                             size_t recv_size) {
  if (right == left) {
    // 2-rank ring: both directions on one socket.
    return data_[right].SendRecv(send_buf, send_size, recv_buf, recv_size);
  }
  const char* sp = static_cast<const char*>(send_buf);
  char* rp = static_cast<char*>(recv_buf);
  size_t to_send = send_size, to_recv = recv_size;
  while (to_send > 0 || to_recv > 0) {
    struct pollfd pfds[2];
    pfds[0].fd = data_[right].fd();
    pfds[0].events = to_send > 0 ? POLLOUT : 0;
    pfds[0].revents = 0;
    pfds[1].fd = data_[left].fd();
    pfds[1].events = to_recv > 0 ? POLLIN : 0;
    pfds[1].revents = 0;
    int rc = ::poll(pfds, 2, 30000);
    if (rc < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    if (rc == 0) {
      HVDTPU_LOG(ERROR) << "RingExchange poll timeout";
      return false;
    }
    if ((pfds[0].revents & POLLOUT) && to_send > 0) {
      ssize_t n = ::send(pfds[0].fd, sp, to_send, MSG_NOSIGNAL);
      if (n < 0 && errno != EINTR && errno != EAGAIN) return false;
      if (n > 0) {
        sp += n;
        to_send -= static_cast<size_t>(n);
      }
    }
    if ((pfds[1].revents & POLLIN) && to_recv > 0) {
      ssize_t n = ::recv(pfds[1].fd, rp, to_recv, 0);
      if (n == 0) return false;
      if (n < 0 && errno != EINTR && errno != EAGAIN) return false;
      if (n > 0) {
        rp += n;
        to_recv -= static_cast<size_t>(n);
      }
    }
    if ((pfds[0].revents & (POLLERR | POLLHUP | POLLNVAL)) && to_send > 0)
      return false;
    if ((pfds[1].revents & (POLLERR | POLLHUP | POLLNVAL)) &&
        !(pfds[1].revents & POLLIN) && to_recv > 0)
      return false;
  }
  return true;
}

}  // namespace hvdtpu
