// Chrome-tracing timeline writer.
//
// Reference: horovod/common/timeline.{h,cc} — coordinator-only JSON writer
// fed by a lockfree SPSC queue from the negotiation thread (timeline.h:48-80)
// with per-tensor lifecycle phases NEGOTIATE_* → QUEUE → op activities
// (common.h:31-62), runtime start/stop (operations.cc:715-757), and optional
// cycle markers. We use a mutex+cv MPSC queue (the producer is the single
// background thread, so contention is nil) and the same chrome://tracing
// event shapes: ts/ph/B/E/X/i with tid = tensor lane.
#ifndef HVDTPU_TIMELINE_H
#define HVDTPU_TIMELINE_H

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>

namespace hvdtpu {

class Timeline {
 public:
  ~Timeline() { Shutdown(); }

  void Initialize(const std::string& path, bool mark_cycles);
  void Shutdown();
  bool Initialized() const { return initialized_.load(); }
  bool MarkCycles() const { return mark_cycles_; }

  // Per-tensor lifecycle (reference: timeline.h NegotiateStart/End,
  // Start/ActivityStart/ActivityEnd/End).
  void NegotiateStart(const std::string& tensor_name, const char* op_name);
  void NegotiateRankReady(const std::string& tensor_name, int rank);
  void NegotiateEnd(const std::string& tensor_name);
  void Start(const std::string& tensor_name, const char* op_name);
  void ActivityStart(const std::string& tensor_name, const char* activity);
  void ActivityEnd(const std::string& tensor_name);
  void End(const std::string& tensor_name);
  void MarkCycleStart();

 private:
  struct Event {
    char ph;              // 'B','E','X','i'
    std::string name;     // event name (phase/activity)
    std::string tensor;   // lane
    int64_t ts_us;
  };

  void Enqueue(Event e);
  void WriterLoop();
  int64_t NowUs() const;
  int LaneFor(const std::string& tensor);

  std::atomic<bool> initialized_{false};
  bool mark_cycles_ = false;
  std::FILE* file_ = nullptr;
  bool first_event_ = true;
  std::chrono::steady_clock::time_point start_;

  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<Event> queue_;
  bool stop_ = false;
  std::thread writer_;

  std::unordered_map<std::string, int> lanes_;
  int next_lane_ = 1;
};

}  // namespace hvdtpu

#endif  // HVDTPU_TIMELINE_H
