#include "collectives.h"

#include <algorithm>
#include <cstring>

#include "logging.h"

namespace hvdtpu {
namespace collectives {

namespace {

template <typename T>
void ReduceTyped(T* dst, const T* src, int64_t n, ReduceOp op) {
  switch (op) {
    case ReduceOp::SUM:
    case ReduceOp::ADASUM:  // data-plane leg of adasum still sums chunks
      for (int64_t i = 0; i < n; ++i) dst[i] = dst[i] + src[i];
      break;
    case ReduceOp::MIN:
      for (int64_t i = 0; i < n; ++i) dst[i] = std::min(dst[i], src[i]);
      break;
    case ReduceOp::MAX:
      for (int64_t i = 0; i < n; ++i) dst[i] = std::max(dst[i], src[i]);
      break;
    case ReduceOp::PRODUCT:
      for (int64_t i = 0; i < n; ++i) dst[i] = dst[i] * src[i];
      break;
  }
}

template <typename Cvt16>
void Reduce16(uint16_t* dst, const uint16_t* src, int64_t n, ReduceOp op,
              Cvt16 to_f, uint16_t (*from_f)(float)) {
  // convert → float op → convert back (reference: float16_sum, half.h:142).
  for (int64_t i = 0; i < n; ++i) {
    float a = to_f(dst[i]), b = to_f(src[i]);
    float r;
    switch (op) {
      case ReduceOp::SUM:
      case ReduceOp::ADASUM: r = a + b; break;
      case ReduceOp::MIN: r = std::min(a, b); break;
      case ReduceOp::MAX: r = std::max(a, b); break;
      case ReduceOp::PRODUCT: r = a * b; break;
      default: r = a + b;
    }
    dst[i] = from_f(r);
  }
}

}  // namespace

void ReduceInto(void* dst, const void* src, int64_t count, DataType dt,
                ReduceOp op) {
  switch (dt) {
    case DataType::HVDTPU_UINT8:
    case DataType::HVDTPU_BOOL:
      ReduceTyped(static_cast<uint8_t*>(dst),
                  static_cast<const uint8_t*>(src), count, op);
      break;
    case DataType::HVDTPU_INT8:
      ReduceTyped(static_cast<int8_t*>(dst), static_cast<const int8_t*>(src),
                  count, op);
      break;
    case DataType::HVDTPU_INT32:
      ReduceTyped(static_cast<int32_t*>(dst),
                  static_cast<const int32_t*>(src), count, op);
      break;
    case DataType::HVDTPU_INT64:
      ReduceTyped(static_cast<int64_t*>(dst),
                  static_cast<const int64_t*>(src), count, op);
      break;
    case DataType::HVDTPU_FLOAT32:
      ReduceTyped(static_cast<float*>(dst), static_cast<const float*>(src),
                  count, op);
      break;
    case DataType::HVDTPU_FLOAT64:
      ReduceTyped(static_cast<double*>(dst), static_cast<const double*>(src),
                  count, op);
      break;
    case DataType::HVDTPU_FLOAT16:
      Reduce16(static_cast<uint16_t*>(dst),
               static_cast<const uint16_t*>(src), count, op, Fp16ToFloat,
               FloatToFp16);
      break;
    case DataType::HVDTPU_BFLOAT16:
      Reduce16(static_cast<uint16_t*>(dst),
               static_cast<const uint16_t*>(src), count, op, Bf16ToFloat,
               FloatToBf16);
      break;
  }
}

void ScaleBuffer(void* buf, int64_t count, DataType dt, double factor) {
  if (factor == 1.0) return;
  switch (dt) {
    case DataType::HVDTPU_FLOAT32: {
      float* p = static_cast<float*>(buf);
      float f = static_cast<float>(factor);
      for (int64_t i = 0; i < count; ++i) p[i] *= f;
      break;
    }
    case DataType::HVDTPU_FLOAT64: {
      double* p = static_cast<double*>(buf);
      for (int64_t i = 0; i < count; ++i) p[i] *= factor;
      break;
    }
    case DataType::HVDTPU_FLOAT16: {
      uint16_t* p = static_cast<uint16_t*>(buf);
      float f = static_cast<float>(factor);
      for (int64_t i = 0; i < count; ++i)
        p[i] = FloatToFp16(Fp16ToFloat(p[i]) * f);
      break;
    }
    case DataType::HVDTPU_BFLOAT16: {
      uint16_t* p = static_cast<uint16_t*>(buf);
      float f = static_cast<float>(factor);
      for (int64_t i = 0; i < count; ++i)
        p[i] = FloatToBf16(Bf16ToFloat(p[i]) * f);
      break;
    }
    case DataType::HVDTPU_INT32: {
      int32_t* p = static_cast<int32_t*>(buf);
      for (int64_t i = 0; i < count; ++i)
        p[i] = static_cast<int32_t>(p[i] * factor);
      break;
    }
    case DataType::HVDTPU_INT64: {
      int64_t* p = static_cast<int64_t*>(buf);
      for (int64_t i = 0; i < count; ++i)
        p[i] = static_cast<int64_t>(p[i] * factor);
      break;
    }
    default:
      break;  // uint8/int8/bool: scaling is not meaningful
  }
}

Status RingAllreduce(Transport& t, void* buf, int64_t count, DataType dt,
                     ReduceOp op) {
  int size = t.size(), rank = t.rank();
  if (size == 1 || count == 0) return Status::OK();
  size_t es = DataTypeSize(dt);
  char* base = static_cast<char*>(buf);

  // Chunk boundaries: first (count % size) chunks get one extra element.
  auto chunk_count = [&](int c) {
    return count / size + (c < count % size ? 1 : 0);
  };
  std::vector<int64_t> offs(static_cast<size_t>(size) + 1, 0);
  for (int c = 0; c < size; ++c) offs[c + 1] = offs[c] + chunk_count(c);

  int right = (rank + 1) % size;
  int left = (rank - 1 + size) % size;
  std::vector<char> recv_tmp(static_cast<size_t>(chunk_count(0)) * es);

  // Reduce-scatter: after step s, the chunk (rank - s) has absorbed s+1
  // contributions; after size-1 steps rank owns chunk (rank+1)%size fully
  // reduced (ring structure identical to NCCL's ring allreduce).
  for (int s = 0; s < size - 1; ++s) {
    int send_c = ((rank - s) % size + size) % size;
    int recv_c = ((rank - s - 1) % size + size) % size;
    int64_t sc = chunk_count(send_c), rc = chunk_count(recv_c);
    if (!t.RingExchange(right, base + offs[send_c] * es,
                        static_cast<size_t>(sc) * es, left, recv_tmp.data(),
                        static_cast<size_t>(rc) * es)) {
      return Status::UnknownError("ring allreduce: peer connection lost");
    }
    ReduceInto(base + offs[recv_c] * es, recv_tmp.data(), rc, dt, op);
  }
  // Allgather: circulate the reduced chunks.
  for (int s = 0; s < size - 1; ++s) {
    int send_c = ((rank + 1 - s) % size + size) % size;
    int recv_c = ((rank - s) % size + size) % size;
    int64_t sc = chunk_count(send_c), rc = chunk_count(recv_c);
    if (!t.RingExchange(right, base + offs[send_c] * es,
                        static_cast<size_t>(sc) * es, left,
                        base + offs[recv_c] * es,
                        static_cast<size_t>(rc) * es)) {
      return Status::UnknownError("ring allgather: peer connection lost");
    }
  }
  return Status::OK();
}

Status AllgatherV(Transport& t, const void* in, int64_t in_bytes,
                  const std::vector<int64_t>& bytes_per_rank,
                  std::vector<char>* out) {
  int size = t.size(), rank = t.rank();
  std::vector<int64_t> offs(static_cast<size_t>(size) + 1, 0);
  for (int i = 0; i < size; ++i) offs[i + 1] = offs[i] + bytes_per_rank[i];
  out->resize(static_cast<size_t>(offs[size]));
  if (bytes_per_rank[rank] != in_bytes) {
    return Status::InvalidArgument("allgatherv: local size mismatch");
  }
  std::memcpy(out->data() + offs[rank], in, static_cast<size_t>(in_bytes));
  if (size == 1) return Status::OK();
  int right = (rank + 1) % size;
  int left = (rank - 1 + size) % size;
  // Ring: step s passes block (rank - s) onward.
  for (int s = 0; s < size - 1; ++s) {
    int send_b = ((rank - s) % size + size) % size;
    int recv_b = ((rank - s - 1) % size + size) % size;
    if (!t.RingExchange(right, out->data() + offs[send_b],
                        static_cast<size_t>(bytes_per_rank[send_b]), left,
                        out->data() + offs[recv_b],
                        static_cast<size_t>(bytes_per_rank[recv_b]))) {
      return Status::UnknownError("allgatherv: peer connection lost");
    }
  }
  return Status::OK();
}

Status Broadcast(Transport& t, void* buf, int64_t bytes, int root) {
  int size = t.size(), rank = t.rank();
  if (size == 1 || bytes == 0) return Status::OK();
  // Binomial tree in root-relative rank space: log2(size) rounds.
  // After round k every vrank < 2^k holds the data; vrank v in
  // [2^k, 2^{k+1}) receives from v - 2^k.
  int vrank = ((rank - root) % size + size) % size;
  for (int step = 1; step < size; step <<= 1) {
    if (vrank < step) {
      if (vrank + step < size) {
        int dst = (vrank + step + root) % size;
        if (!t.SendToRank(dst, buf, static_cast<size_t>(bytes))) {
          return Status::UnknownError("broadcast: peer connection lost");
        }
      }
    } else if (vrank < 2 * step) {
      int src = (vrank - step + root) % size;
      if (!t.RecvFromRank(src, buf, static_cast<size_t>(bytes))) {
        return Status::UnknownError("broadcast: peer connection lost");
      }
    }
  }
  return Status::OK();
}

Status AllToAllV(Transport& t, const void* in,
                 const std::vector<int64_t>& send_bytes,
                 const std::vector<int64_t>& recv_bytes,
                 std::vector<char>* out) {
  int size = t.size(), rank = t.rank();
  std::vector<int64_t> soffs(static_cast<size_t>(size) + 1, 0);
  std::vector<int64_t> roffs(static_cast<size_t>(size) + 1, 0);
  for (int i = 0; i < size; ++i) {
    soffs[i + 1] = soffs[i] + send_bytes[i];
    roffs[i + 1] = roffs[i] + recv_bytes[i];
  }
  out->resize(static_cast<size_t>(roffs[size]));
  const char* src = static_cast<const char*>(in);
  std::memcpy(out->data() + roffs[rank], src + soffs[rank],
              static_cast<size_t>(send_bytes[rank]));
  // Pairwise rounds: at step s exchange with (rank+s) / (rank-s).
  for (int s = 1; s < size; ++s) {
    int to = (rank + s) % size;
    int from = (rank - s + size) % size;
    if (!t.RingExchange(to, src + soffs[to],
                        static_cast<size_t>(send_bytes[to]), from,
                        out->data() + roffs[from],
                        static_cast<size_t>(recv_bytes[from]))) {
      return Status::UnknownError("alltoallv: peer connection lost");
    }
  }
  return Status::OK();
}

}  // namespace collectives
}  // namespace hvdtpu
