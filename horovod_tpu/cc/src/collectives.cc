#include "collectives.h"

#include <algorithm>
#include <cstring>

#include "logging.h"

namespace hvdtpu {
namespace collectives {

namespace {

template <typename T>
void ReduceTyped(T* dst, const T* src, int64_t n, ReduceOp op) {
  switch (op) {
    case ReduceOp::SUM:
    case ReduceOp::ADASUM:  // data-plane leg of adasum still sums chunks
      for (int64_t i = 0; i < n; ++i) dst[i] = dst[i] + src[i];
      break;
    case ReduceOp::MIN:
      for (int64_t i = 0; i < n; ++i) dst[i] = std::min(dst[i], src[i]);
      break;
    case ReduceOp::MAX:
      for (int64_t i = 0; i < n; ++i) dst[i] = std::max(dst[i], src[i]);
      break;
    case ReduceOp::PRODUCT:
      for (int64_t i = 0; i < n; ++i) dst[i] = dst[i] * src[i];
      break;
  }
}

template <typename Cvt16>
void Reduce16(uint16_t* dst, const uint16_t* src, int64_t n, ReduceOp op,
              Cvt16 to_f, uint16_t (*from_f)(float)) {
  // convert → float op → convert back (reference: float16_sum, half.h:142).
  for (int64_t i = 0; i < n; ++i) {
    float a = to_f(dst[i]), b = to_f(src[i]);
    float r;
    switch (op) {
      case ReduceOp::SUM:
      case ReduceOp::ADASUM: r = a + b; break;
      case ReduceOp::MIN: r = std::min(a, b); break;
      case ReduceOp::MAX: r = std::max(a, b); break;
      case ReduceOp::PRODUCT: r = a * b; break;
      default: r = a + b;
    }
    dst[i] = from_f(r);
  }
}

}  // namespace

void ReduceInto(void* dst, const void* src, int64_t count, DataType dt,
                ReduceOp op) {
  switch (dt) {
    case DataType::HVDTPU_UINT8:
    case DataType::HVDTPU_BOOL:
      ReduceTyped(static_cast<uint8_t*>(dst),
                  static_cast<const uint8_t*>(src), count, op);
      break;
    case DataType::HVDTPU_INT8:
      ReduceTyped(static_cast<int8_t*>(dst), static_cast<const int8_t*>(src),
                  count, op);
      break;
    case DataType::HVDTPU_INT32:
      ReduceTyped(static_cast<int32_t*>(dst),
                  static_cast<const int32_t*>(src), count, op);
      break;
    case DataType::HVDTPU_INT64:
      ReduceTyped(static_cast<int64_t*>(dst),
                  static_cast<const int64_t*>(src), count, op);
      break;
    case DataType::HVDTPU_FLOAT32:
      ReduceTyped(static_cast<float*>(dst), static_cast<const float*>(src),
                  count, op);
      break;
    case DataType::HVDTPU_FLOAT64:
      ReduceTyped(static_cast<double*>(dst), static_cast<const double*>(src),
                  count, op);
      break;
    case DataType::HVDTPU_FLOAT16:
      Reduce16(static_cast<uint16_t*>(dst),
               static_cast<const uint16_t*>(src), count, op, Fp16ToFloat,
               FloatToFp16);
      break;
    case DataType::HVDTPU_BFLOAT16:
      Reduce16(static_cast<uint16_t*>(dst),
               static_cast<const uint16_t*>(src), count, op, Bf16ToFloat,
               FloatToBf16);
      break;
  }
}

void ScaleBuffer(void* buf, int64_t count, DataType dt, double factor) {
  if (factor == 1.0) return;
  switch (dt) {
    case DataType::HVDTPU_FLOAT32: {
      float* p = static_cast<float*>(buf);
      float f = static_cast<float>(factor);
      for (int64_t i = 0; i < count; ++i) p[i] *= f;
      break;
    }
    case DataType::HVDTPU_FLOAT64: {
      double* p = static_cast<double*>(buf);
      for (int64_t i = 0; i < count; ++i) p[i] *= factor;
      break;
    }
    case DataType::HVDTPU_FLOAT16: {
      uint16_t* p = static_cast<uint16_t*>(buf);
      float f = static_cast<float>(factor);
      for (int64_t i = 0; i < count; ++i)
        p[i] = FloatToFp16(Fp16ToFloat(p[i]) * f);
      break;
    }
    case DataType::HVDTPU_BFLOAT16: {
      uint16_t* p = static_cast<uint16_t*>(buf);
      float f = static_cast<float>(factor);
      for (int64_t i = 0; i < count; ++i)
        p[i] = FloatToBf16(Bf16ToFloat(p[i]) * f);
      break;
    }
    case DataType::HVDTPU_INT32: {
      int32_t* p = static_cast<int32_t*>(buf);
      for (int64_t i = 0; i < count; ++i)
        p[i] = static_cast<int32_t>(p[i] * factor);
      break;
    }
    case DataType::HVDTPU_INT64: {
      int64_t* p = static_cast<int64_t*>(buf);
      for (int64_t i = 0; i < count; ++i)
        p[i] = static_cast<int64_t>(p[i] * factor);
      break;
    }
    default:
      break;  // uint8/int8/bool: scaling is not meaningful
  }
}

Status RingAllreduce(Transport& t, void* buf, int64_t count, DataType dt,
                     ReduceOp op) {
  int size = t.size(), rank = t.rank();
  if (size == 1 || count == 0) return Status::OK();
  size_t es = DataTypeSize(dt);
  char* base = static_cast<char*>(buf);

  // Chunk boundaries: first (count % size) chunks get one extra element.
  auto chunk_count = [&](int c) {
    return count / size + (c < count % size ? 1 : 0);
  };
  std::vector<int64_t> offs(static_cast<size_t>(size) + 1, 0);
  for (int c = 0; c < size; ++c) offs[c + 1] = offs[c] + chunk_count(c);

  int right = (rank + 1) % size;
  int left = (rank - 1 + size) % size;
  std::vector<char> recv_tmp(static_cast<size_t>(chunk_count(0)) * es);

  // Reduce-scatter: after step s, the chunk (rank - s) has absorbed s+1
  // contributions; after size-1 steps rank owns chunk (rank+1)%size fully
  // reduced (ring structure identical to NCCL's ring allreduce).
  for (int s = 0; s < size - 1; ++s) {
    int send_c = ((rank - s) % size + size) % size;
    int recv_c = ((rank - s - 1) % size + size) % size;
    int64_t sc = chunk_count(send_c), rc = chunk_count(recv_c);
    if (!t.RingExchange(right, base + offs[send_c] * es,
                        static_cast<size_t>(sc) * es, left, recv_tmp.data(),
                        static_cast<size_t>(rc) * es)) {
      return Status::UnknownError("ring allreduce: peer connection lost");
    }
    ReduceInto(base + offs[recv_c] * es, recv_tmp.data(), rc, dt, op);
  }
  // Allgather: circulate the reduced chunks.
  for (int s = 0; s < size - 1; ++s) {
    int send_c = ((rank + 1 - s) % size + size) % size;
    int recv_c = ((rank - s) % size + size) % size;
    int64_t sc = chunk_count(send_c), rc = chunk_count(recv_c);
    if (!t.RingExchange(right, base + offs[send_c] * es,
                        static_cast<size_t>(sc) * es, left,
                        base + offs[recv_c] * es,
                        static_cast<size_t>(rc) * es)) {
      return Status::UnknownError("ring allgather: peer connection lost");
    }
  }
  return Status::OK();
}

namespace {

// Ring allgather of variable-sized byte blocks within an arbitrary rank
// group (block b lives at out[offs[b]..offs[b+1]]; my_idx's block must be
// filled before the call). The cross-host leg of HierarchicalAllgatherV runs
// this over the leader group; AllgatherV is the full-world specialization.
Status RingAllgatherBlocks(Transport& t, char* out,
                           const std::vector<int64_t>& offs,
                           const std::vector<int64_t>& block_bytes,
                           const std::vector<int>& group, int my_idx) {
  int n = static_cast<int>(group.size());
  if (n <= 1) return Status::OK();
  int right = group[(my_idx + 1) % n];
  int left = group[(my_idx - 1 + n) % n];
  for (int s = 0; s < n - 1; ++s) {
    int send_b = ((my_idx - s) % n + n) % n;
    int recv_b = ((my_idx - s - 1) % n + n) % n;
    if (!t.RingExchange(right, out + offs[send_b],
                        static_cast<size_t>(block_bytes[send_b]), left,
                        out + offs[recv_b],
                        static_cast<size_t>(block_bytes[recv_b]))) {
      return Status::UnknownError("ring allgather: peer connection lost");
    }
  }
  return Status::OK();
}

}  // namespace

Status AllgatherV(Transport& t, const void* in, int64_t in_bytes,
                  const std::vector<int64_t>& bytes_per_rank,
                  std::vector<char>* out) {
  int size = t.size(), rank = t.rank();
  std::vector<int64_t> offs(static_cast<size_t>(size) + 1, 0);
  for (int i = 0; i < size; ++i) offs[i + 1] = offs[i] + bytes_per_rank[i];
  out->resize(static_cast<size_t>(offs[size]));
  if (bytes_per_rank[rank] != in_bytes) {
    return Status::InvalidArgument("allgatherv: local size mismatch");
  }
  std::memcpy(out->data() + offs[rank], in, static_cast<size_t>(in_bytes));
  if (size == 1) return Status::OK();
  int right = (rank + 1) % size;
  int left = (rank - 1 + size) % size;
  // Ring: step s passes block (rank - s) onward.
  for (int s = 0; s < size - 1; ++s) {
    int send_b = ((rank - s) % size + size) % size;
    int recv_b = ((rank - s - 1) % size + size) % size;
    if (!t.RingExchange(right, out->data() + offs[send_b],
                        static_cast<size_t>(bytes_per_rank[send_b]), left,
                        out->data() + offs[recv_b],
                        static_cast<size_t>(bytes_per_rank[recv_b]))) {
      return Status::UnknownError("allgatherv: peer connection lost");
    }
  }
  return Status::OK();
}

Status Broadcast(Transport& t, void* buf, int64_t bytes, int root) {
  int size = t.size(), rank = t.rank();
  if (size == 1 || bytes == 0) return Status::OK();
  // Binomial tree in root-relative rank space: log2(size) rounds.
  // After round k every vrank < 2^k holds the data; vrank v in
  // [2^k, 2^{k+1}) receives from v - 2^k.
  int vrank = ((rank - root) % size + size) % size;
  for (int step = 1; step < size; step <<= 1) {
    if (vrank < step) {
      if (vrank + step < size) {
        int dst = (vrank + step + root) % size;
        if (!t.SendToRank(dst, buf, static_cast<size_t>(bytes))) {
          return Status::UnknownError("broadcast: peer connection lost");
        }
      }
    } else if (vrank < 2 * step) {
      int src = (vrank - step + root) % size;
      if (!t.RecvFromRank(src, buf, static_cast<size_t>(bytes))) {
        return Status::UnknownError("broadcast: peer connection lost");
      }
    }
  }
  return Status::OK();
}

Status HierarchicalAllreduce(Transport& t, void* buf, int64_t count,
                             DataType dt, ReduceOp op, const Topology& topo) {
  if (!topo.Hierarchical(t.size(), t.rank()) || count == 0) {
    return RingAllreduce(t, buf, count, dt, op);
  }
  size_t es = DataTypeSize(dt);
  int leader = topo.cross_rank * topo.local_size;  // local_rank 0 on my host
  bool is_leader = topo.local_rank == 0;

  // 1. Intra-host reduce to the leader (loopback TCP; the reference's
  //    intra-node NCCL ReduceScatter leg, nccl_operations.cc:232-242).
  if (is_leader) {
    std::vector<char> tmp(static_cast<size_t>(count) * es);
    for (int lr = 1; lr < topo.local_size; ++lr) {
      if (!t.RecvFromRank(leader + lr, tmp.data(), tmp.size())) {
        return Status::UnknownError("hier allreduce: local peer lost");
      }
      ReduceInto(buf, tmp.data(), count, dt, op);
    }
  } else {
    if (!t.SendToRank(leader, buf, static_cast<size_t>(count) * es)) {
      return Status::UnknownError("hier allreduce: leader lost");
    }
  }

  // 2. Ring allreduce among leaders — the only cross-host traffic
  //    (reference: the parallel cross-node MPI_Allreduce leg,
  //    nccl_operations.cc:244-307).
  if (is_leader) {
    int size = topo.cross_size, rank = topo.cross_rank;
    auto chunk_count = [&](int c) {
      return count / size + (c < count % size ? 1 : 0);
    };
    std::vector<int64_t> offs(static_cast<size_t>(size) + 1, 0);
    for (int c = 0; c < size; ++c) offs[c + 1] = offs[c] + chunk_count(c);
    int right = ((rank + 1) % size) * topo.local_size;
    int left = ((rank - 1 + size) % size) * topo.local_size;
    char* base = static_cast<char*>(buf);
    std::vector<char> recv_tmp(static_cast<size_t>(chunk_count(0)) * es);
    for (int s = 0; s < size - 1; ++s) {
      int send_c = ((rank - s) % size + size) % size;
      int recv_c = ((rank - s - 1) % size + size) % size;
      int64_t sc = chunk_count(send_c), rc = chunk_count(recv_c);
      if (!t.RingExchange(right, base + offs[send_c] * es,
                          static_cast<size_t>(sc) * es, left, recv_tmp.data(),
                          static_cast<size_t>(rc) * es)) {
        return Status::UnknownError("hier allreduce: cross peer lost");
      }
      ReduceInto(base + offs[recv_c] * es, recv_tmp.data(), rc, dt, op);
    }
    for (int s = 0; s < size - 1; ++s) {
      int send_c = ((rank + 1 - s) % size + size) % size;
      int recv_c = ((rank - s) % size + size) % size;
      if (!t.RingExchange(right, base + offs[send_c] * es,
                          static_cast<size_t>(chunk_count(send_c)) * es, left,
                          base + offs[recv_c] * es,
                          static_cast<size_t>(chunk_count(recv_c)) * es)) {
        return Status::UnknownError("hier allreduce: cross peer lost");
      }
    }
  }

  // 3. Intra-host broadcast of the reduced buffer (the reference's
  //    intra-node ncclAllgather leg).
  if (is_leader) {
    for (int lr = 1; lr < topo.local_size; ++lr) {
      if (!t.SendToRank(leader + lr, buf, static_cast<size_t>(count) * es)) {
        return Status::UnknownError("hier allreduce: local peer lost");
      }
    }
  } else {
    if (!t.RecvFromRank(leader, buf, static_cast<size_t>(count) * es)) {
      return Status::UnknownError("hier allreduce: leader lost");
    }
  }
  return Status::OK();
}

Status HierarchicalAllgatherV(Transport& t, const void* in, int64_t in_bytes,
                              const std::vector<int64_t>& bytes_per_rank,
                              std::vector<char>* out, const Topology& topo) {
  int size = t.size(), rank = t.rank();
  if (!topo.Hierarchical(size, rank)) {
    return AllgatherV(t, in, in_bytes, bytes_per_rank, out);
  }
  std::vector<int64_t> offs(static_cast<size_t>(size) + 1, 0);
  for (int i = 0; i < size; ++i) offs[i + 1] = offs[i] + bytes_per_rank[i];
  int64_t total = offs[size];
  out->resize(static_cast<size_t>(total));
  if (bytes_per_rank[rank] != in_bytes) {
    return Status::InvalidArgument("hier allgatherv: local size mismatch");
  }
  std::memcpy(out->data() + offs[rank], in, static_cast<size_t>(in_bytes));

  int leader = topo.cross_rank * topo.local_size;
  bool is_leader = topo.local_rank == 0;

  // 1. Intra-host gather into the leader's buffer at final offsets
  //    (reference: node leaders assemble through POSIX shared memory,
  //    mpi_operations.cc:213-246).
  if (is_leader) {
    for (int lr = 1; lr < topo.local_size; ++lr) {
      int r = leader + lr;
      if (bytes_per_rank[r] > 0 &&
          !t.RecvFromRank(r, out->data() + offs[r],
                          static_cast<size_t>(bytes_per_rank[r]))) {
        return Status::UnknownError("hier allgather: local peer lost");
      }
    }
  } else if (in_bytes > 0) {
    if (!t.SendToRank(leader, in, static_cast<size_t>(in_bytes))) {
      return Status::UnknownError("hier allgather: leader lost");
    }
  }

  // 2. Ring allgather of per-host superblocks among leaders — the only
  //    cross-host traffic (reference: MPI_Allgatherv over node leaders,
  //    mpi_operations.cc:248-259). Host h's superblock is the contiguous
  //    range [offs[h*ls], offs[(h+1)*ls]) thanks to host-major rank packing.
  if (is_leader) {
    int ls = topo.local_size, cs = topo.cross_size;
    std::vector<int64_t> hoffs(static_cast<size_t>(cs) + 1, 0);
    std::vector<int64_t> hbytes(static_cast<size_t>(cs), 0);
    std::vector<int> group(static_cast<size_t>(cs));
    for (int h = 0; h < cs; ++h) {
      hoffs[h] = offs[static_cast<size_t>(h) * ls];
      hbytes[h] = offs[static_cast<size_t>(h + 1) * ls] -
                  offs[static_cast<size_t>(h) * ls];
      group[h] = h * ls;
    }
    hoffs[cs] = total;
    Status s = RingAllgatherBlocks(t, out->data(), hoffs, hbytes, group,
                                   topo.cross_rank);
    if (!s.ok()) return s;
  }

  // 3. Intra-host broadcast of the assembled result (reference: non-leader
  //    ranks read the shared-memory window, mpi_operations.cc:261-276).
  if (is_leader) {
    for (int lr = 1; lr < topo.local_size; ++lr) {
      if (!t.SendToRank(leader + lr, out->data(),
                        static_cast<size_t>(total))) {
        return Status::UnknownError("hier allgather: local peer lost");
      }
    }
  } else {
    if (!t.RecvFromRank(leader, out->data(), static_cast<size_t>(total))) {
      return Status::UnknownError("hier allgather: leader lost");
    }
  }
  return Status::OK();
}

Status AllToAllV(Transport& t, const void* in,
                 const std::vector<int64_t>& send_bytes,
                 const std::vector<int64_t>& recv_bytes,
                 std::vector<char>* out) {
  int size = t.size(), rank = t.rank();
  std::vector<int64_t> soffs(static_cast<size_t>(size) + 1, 0);
  std::vector<int64_t> roffs(static_cast<size_t>(size) + 1, 0);
  for (int i = 0; i < size; ++i) {
    soffs[i + 1] = soffs[i] + send_bytes[i];
    roffs[i + 1] = roffs[i] + recv_bytes[i];
  }
  out->resize(static_cast<size_t>(roffs[size]));
  const char* src = static_cast<const char*>(in);
  std::memcpy(out->data() + roffs[rank], src + soffs[rank],
              static_cast<size_t>(send_bytes[rank]));
  // Pairwise rounds: at step s exchange with (rank+s) / (rank-s).
  for (int s = 1; s < size; ++s) {
    int to = (rank + s) % size;
    int from = (rank - s + size) % size;
    if (!t.RingExchange(to, src + soffs[to],
                        static_cast<size_t>(send_bytes[to]), from,
                        out->data() + roffs[from],
                        static_cast<size_t>(recv_bytes[from]))) {
      return Status::UnknownError("alltoallv: peer connection lost");
    }
  }
  return Status::OK();
}

}  // namespace collectives
}  // namespace hvdtpu
