#include "logging.h"

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <mutex>

#include "common.h"

namespace hvdtpu {

static std::atomic<int> g_log_rank{-1};
static std::mutex g_log_mutex;

void SetLogRank(int rank) { g_log_rank.store(rank); }

LogLevel MinLogLevelFromEnv() {
  std::string v = EnvString("HOROVOD_LOG_LEVEL", "warning");
  if (v == "trace") return LogLevel::TRACE;
  if (v == "debug") return LogLevel::DEBUG;
  if (v == "info") return LogLevel::INFO;
  if (v == "warning") return LogLevel::WARNING;
  if (v == "error") return LogLevel::ERROR;
  if (v == "fatal") return LogLevel::FATAL;
  return LogLevel::WARNING;
}

bool LogLevelEnabled(LogLevel level) {
  static LogLevel min_level = MinLogLevelFromEnv();
  return static_cast<int>(level) >= static_cast<int>(min_level);
}

static const char* LevelName(LogLevel l) {
  switch (l) {
    case LogLevel::TRACE: return "TRACE";
    case LogLevel::DEBUG: return "DEBUG";
    case LogLevel::INFO: return "INFO";
    case LogLevel::WARNING: return "WARNING";
    case LogLevel::ERROR: return "ERROR";
    case LogLevel::FATAL: return "FATAL";
  }
  return "?";
}

LogMessage::LogMessage(const char* file, int line, LogLevel level)
    : file_(file), line_(line), level_(level) {}

LogMessage::~LogMessage() {
  bool hide_time = EnvBool("HOROVOD_LOG_HIDE_TIME", false);
  std::lock_guard<std::mutex> g(g_log_mutex);
  if (!hide_time) {
    auto now = std::chrono::system_clock::now();
    auto us = std::chrono::duration_cast<std::chrono::microseconds>(
                  now.time_since_epoch())
                  .count();
    std::fprintf(stderr, "[%lld.%06lld] ",
                 static_cast<long long>(us / 1000000),
                 static_cast<long long>(us % 1000000));
  }
  int rank = g_log_rank.load();
  if (rank >= 0) {
    std::fprintf(stderr, "[rank %d] ", rank);
  }
  std::fprintf(stderr, "[%s] %s:%d: %s\n", LevelName(level_), file_, line_,
               stream_.str().c_str());
  if (level_ == LogLevel::FATAL) std::abort();
}

}  // namespace hvdtpu
