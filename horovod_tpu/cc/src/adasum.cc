#include "adasum.h"

#include <cmath>
#include <cstring>
#include <vector>

namespace hvdtpu {

namespace {

// Vector-halving distance-doubling Adasum (reference: FusedAllreduce,
// adasum.h:196+). Per level d the pair (rank, rank^d) splits the current
// segment: the lower rank keeps the first half, the higher the second, and
// they exchange the halves they give up — so per-level traffic *halves*
// (total ≈ 2n per rank across all levels) instead of the full vector every
// level as in plain recursive doubling. The adasum coefficients need dot
// products of the *logical* full vectors, whose pieces are spread over the
// 2d-rank block; a 3-double recursive-doubling allreduce within the block
// assembles them (the reference's SumAllreduceWithComm over
// reduction_comms_, adasum.h:271+).
template <typename T>
Status VhddTyped(Transport& t, T* mine, int64_t count) {
  int size = t.size(), rank = t.rank();
  int levels = 0;
  while ((1 << levels) < size) ++levels;

  int64_t start = 0, len = count;
  std::vector<int64_t> starts(static_cast<size_t>(levels));
  std::vector<int64_t> lens(static_cast<size_t>(levels));
  std::vector<T> recv;

  // Halving phase: after level l each rank holds its combined piece of the
  // block's logical vector.
  for (int l = 0; l < levels; ++l) {
    int d = 1 << l;
    int partner = rank ^ d;
    bool lower = (rank & d) == 0;
    int64_t len_a = len - len / 2;  // first half (kept by the lower rank)
    int64_t len_b = len / 2;
    starts[static_cast<size_t>(l)] = start;
    lens[static_cast<size_t>(l)] = len;

    const T* send_ptr;
    int64_t send_len, keep_off, keep_len;
    if (lower) {
      send_ptr = mine + start + len_a;
      send_len = len_b;
      keep_off = start;
      keep_len = len_a;
    } else {
      send_ptr = mine + start;
      send_len = len_a;
      keep_off = start + len_a;
      keep_len = len_b;
    }
    recv.resize(static_cast<size_t>(keep_len));
    if (!t.RingExchange(partner, send_ptr,
                        static_cast<size_t>(send_len) * sizeof(T), partner,
                        recv.data(),
                        static_cast<size_t>(keep_len) * sizeof(T))) {
      return Status::UnknownError("adasum vhdd: peer connection lost");
    }

    // Deterministic orientation: the lower rank's vector is `a`
    // (reference dispatches the same way, adasum.h:101-141).
    const T* a = lower ? mine + keep_off : recv.data();
    const T* b = lower ? recv.data() : mine + keep_off;
    double p[3] = {0.0, 0.0, 0.0};  // dot, |a|^2, |b|^2 (partial)
    for (int64_t i = 0; i < keep_len; ++i) {
      double ai = static_cast<double>(a[i]), bi = static_cast<double>(b[i]);
      p[0] += ai * bi;
      p[1] += ai * ai;
      p[2] += bi * bi;
    }
    // Block-wide partial sums: recursive doubling over the 2d block.
    for (int s = 1; s < 2 * d; s <<= 1) {
      int p2 = rank ^ s;
      double theirs[3];
      if (!t.RingExchange(p2, p, sizeof(p), p2, theirs, sizeof(theirs))) {
        return Status::UnknownError("adasum vhdd: peer connection lost");
      }
      p[0] += theirs[0];
      p[1] += theirs[1];
      p[2] += theirs[2];
    }
    double acoef = p[1] <= 0.0 ? 1.0 : 1.0 - p[0] / (2.0 * p[1]);
    double bcoef = p[2] <= 0.0 ? 1.0 : 1.0 - p[0] / (2.0 * p[2]);
    T* dst = mine + keep_off;
    for (int64_t i = 0; i < keep_len; ++i) {
      dst[i] = static_cast<T>(acoef * static_cast<double>(a[i]) +
                              bcoef * static_cast<double>(b[i]));
    }
    start = keep_off;
    len = keep_len;
  }

  // Doubling phase: walk the levels back, swapping combined pieces so every
  // rank reassembles the full vector (the allgather half of VHDD).
  for (int l = levels - 1; l >= 0; --l) {
    int d = 1 << l;
    int partner = rank ^ d;
    bool lower = (rank & d) == 0;
    int64_t pstart = starts[static_cast<size_t>(l)];
    int64_t plen = lens[static_cast<size_t>(l)];
    int64_t len_a = plen - plen / 2;
    T* recv_ptr;
    int64_t recv_len;
    if (lower) {
      recv_ptr = mine + pstart + len_a;
      recv_len = plen / 2;
    } else {
      recv_ptr = mine + pstart;
      recv_len = len_a;
    }
    if (!t.RingExchange(partner, mine + start,
                        static_cast<size_t>(len) * sizeof(T), partner,
                        recv_ptr,
                        static_cast<size_t>(recv_len) * sizeof(T))) {
      return Status::UnknownError("adasum vhdd: peer connection lost");
    }
    start = pstart;
    len = plen;
  }
  return Status::OK();
}

// Widen a 16-bit buffer to fp32, run VHDD there, narrow back. The Adasum
// coefficients need fp32-accurate dot products — accumulating them in bf16
// would destroy the scaling — and the wire cost of the widened exchange is
// acceptable on the host path (the reference's AVX fp16 dispatch does the
// same convert-combine-convert per pair, adasum.h:101-141 + half.h:142;
// in-repo precedent: Reduce16, collectives.cc:33).
Status Vhdd16(Transport& t, uint16_t* buf, int64_t count,
              float (*to_f)(uint16_t), uint16_t (*from_f)(float)) {
  std::vector<float> wide(static_cast<size_t>(count));
  for (int64_t i = 0; i < count; ++i) wide[static_cast<size_t>(i)] =
      to_f(buf[i]);
  Status s = VhddTyped(t, wide.data(), count);
  if (!s.ok()) return s;
  for (int64_t i = 0; i < count; ++i) buf[i] =
      from_f(wide[static_cast<size_t>(i)]);
  return Status::OK();
}

}  // namespace

Status AdasumAllreduce(Transport& t, void* buf, int64_t count, DataType dt) {
  int size = t.size();
  if ((size & (size - 1)) != 0) {
    return Status::PreconditionError(
        "Adasum requires a power-of-2 number of ranks (reference: "
        "torch/mpi_ops.py:95-115).");
  }
  if (size == 1 || count == 0) return Status::OK();
  switch (dt) {
    case DataType::HVDTPU_FLOAT32:
      return VhddTyped(t, static_cast<float*>(buf), count);
    case DataType::HVDTPU_FLOAT64:
      return VhddTyped(t, static_cast<double*>(buf), count);
    case DataType::HVDTPU_BFLOAT16:
      return Vhdd16(t, static_cast<uint16_t*>(buf), count, Bf16ToFloat,
                    FloatToBf16);
    case DataType::HVDTPU_FLOAT16:
      return Vhdd16(t, static_cast<uint16_t*>(buf), count, Fp16ToFloat,
                    FloatToFp16);
    default:
      return Status::InvalidArgument(
          "Adasum host path supports float16/bfloat16/float32/float64 "
          "buffers.");
  }
}

}  // namespace hvdtpu
