#include "adasum.h"

#include <cmath>
#include <vector>

namespace hvdtpu {

namespace {

template <typename T>
Status AdasumTyped(Transport& t, T* mine, int64_t count) {
  int size = t.size(), rank = t.rank();
  std::vector<T> theirs(static_cast<size_t>(count));
  for (int d = 1; d < size; d <<= 1) {
    int partner = rank ^ d;
    if (!t.RingExchange(partner, mine, static_cast<size_t>(count) * sizeof(T),
                        partner, theirs.data(),
                        static_cast<size_t>(count) * sizeof(T))) {
      return Status::UnknownError("adasum: peer connection lost");
    }
    // Deterministic orientation: the lower rank's buffer is `a`
    // (reference dispatches the same way so both sides compute the
    // identical combine, adasum.h:101-141).
    const T* a = (rank & d) == 0 ? mine : theirs.data();
    const T* b = (rank & d) == 0 ? theirs.data() : mine;
    double dot = 0.0, na = 0.0, nb = 0.0;
    for (int64_t i = 0; i < count; ++i) {
      double ai = static_cast<double>(a[i]), bi = static_cast<double>(b[i]);
      dot += ai * bi;
      na += ai * ai;
      nb += bi * bi;
    }
    double acoef = na <= 0.0 ? 1.0 : 1.0 - dot / (2.0 * na);
    double bcoef = nb <= 0.0 ? 1.0 : 1.0 - dot / (2.0 * nb);
    for (int64_t i = 0; i < count; ++i) {
      mine[i] = static_cast<T>(acoef * static_cast<double>(a[i]) +
                               bcoef * static_cast<double>(b[i]));
    }
  }
  return Status::OK();
}

}  // namespace

Status AdasumAllreduce(Transport& t, void* buf, int64_t count, DataType dt) {
  int size = t.size();
  if ((size & (size - 1)) != 0) {
    return Status::PreconditionError(
        "Adasum requires a power-of-2 number of ranks (reference: "
        "torch/mpi_ops.py:95-115).");
  }
  if (size == 1 || count == 0) return Status::OK();
  switch (dt) {
    case DataType::HVDTPU_FLOAT32:
      return AdasumTyped(t, static_cast<float*>(buf), count);
    case DataType::HVDTPU_FLOAT64:
      return AdasumTyped(t, static_cast<double*>(buf), count);
    default:
      return Status::InvalidArgument(
          "Adasum host path supports float32/float64 buffers.");
  }
}

}  // namespace hvdtpu
