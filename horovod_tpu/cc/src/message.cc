#include "message.h"

namespace hvdtpu {

const char* Request::TypeName(Type t) {
  switch (t) {
    case ALLREDUCE: return "ALLREDUCE";
    case ALLGATHER: return "ALLGATHER";
    case BROADCAST: return "BROADCAST";
    case JOIN: return "JOIN";
    case ADASUM: return "ADASUM";
    case ALLTOALL: return "ALLTOALL";
    case BARRIER: return "BARRIER";
  }
  return "?";
}

void Request::Serialize(WireWriter& w) const {
  w.i32(request_rank);
  w.u8(request_type);
  w.u8(static_cast<uint8_t>(tensor_type));
  w.str(tensor_name);
  w.i32(root_rank);
  w.i32(device);
  w.i64s(tensor_shape);
  w.f64(prescale_factor);
  w.f64(postscale_factor);
  w.u8(static_cast<uint8_t>(reduce_op));
  w.i64s(splits);
}

Request Request::Deserialize(WireReader& r) {
  Request q;
  q.request_rank = r.i32();
  q.request_type = static_cast<Type>(r.u8());
  q.tensor_type = static_cast<DataType>(r.u8());
  q.tensor_name = r.str();
  q.root_rank = r.i32();
  q.device = r.i32();
  q.tensor_shape = r.i64s();
  q.prescale_factor = r.f64();
  q.postscale_factor = r.f64();
  q.reduce_op = static_cast<ReduceOp>(r.u8());
  q.splits = r.i64s();
  return q;
}

void RequestList::Serialize(WireWriter& w) const {
  w.u8(shutdown ? 1 : 0);
  w.u8(joined ? 1 : 0);
  w.i64s(cache_bits);
  w.i64s(invalid_bits);
  w.i32(static_cast<int32_t>(requests.size()));
  for (const auto& q : requests) q.Serialize(w);
}

RequestList RequestList::Deserialize(WireReader& r) {
  RequestList l;
  l.shutdown = r.u8() != 0;
  l.joined = r.u8() != 0;
  l.cache_bits = r.i64s();
  l.invalid_bits = r.i64s();
  int32_t n = r.i32();
  l.requests.reserve(static_cast<size_t>(n));
  for (int32_t i = 0; i < n; ++i) l.requests.push_back(Request::Deserialize(r));
  return l;
}

void Response::Serialize(WireWriter& w) const {
  w.u8(response_type);
  w.i32(static_cast<int32_t>(tensor_names.size()));
  for (const auto& s : tensor_names) w.str(s);
  w.str(error_message);
  w.i32(static_cast<int32_t>(devices.size()));
  for (auto d : devices) w.i32(d);
  w.i64s(tensor_sizes);
  w.i32(last_joined_rank);
  w.i32(root_rank);
  w.u8(static_cast<uint8_t>(tensor_type));
  w.f64(prescale_factor);
  w.f64(postscale_factor);
  w.u8(static_cast<uint8_t>(reduce_op));
  w.i64s(cache_shape);
}

Response Response::Deserialize(WireReader& r) {
  Response p;
  p.response_type = static_cast<Type>(r.u8());
  int32_t n = r.i32();
  p.tensor_names.reserve(static_cast<size_t>(n));
  for (int32_t i = 0; i < n; ++i) p.tensor_names.push_back(r.str());
  p.error_message = r.str();
  int32_t nd = r.i32();
  p.devices.reserve(static_cast<size_t>(nd));
  for (int32_t i = 0; i < nd; ++i) p.devices.push_back(r.i32());
  p.tensor_sizes = r.i64s();
  p.last_joined_rank = r.i32();
  p.root_rank = r.i32();
  p.tensor_type = static_cast<DataType>(r.u8());
  p.prescale_factor = r.f64();
  p.postscale_factor = r.f64();
  p.reduce_op = static_cast<ReduceOp>(r.u8());
  p.cache_shape = r.i64s();
  return p;
}

void ResponseList::Serialize(WireWriter& w) const {
  w.u8(shutdown ? 1 : 0);
  w.i64s(invalid_bits);
  w.u8(has_tuned_params ? 1 : 0);
  w.i64(tuned_fusion_threshold);
  w.f64(tuned_cycle_time_ms);
  w.u8(tuned_flags);
  w.i32(static_cast<int32_t>(responses.size()));
  for (const auto& p : responses) p.Serialize(w);
}

ResponseList ResponseList::Deserialize(WireReader& r) {
  ResponseList l;
  l.shutdown = r.u8() != 0;
  l.invalid_bits = r.i64s();
  l.has_tuned_params = r.u8() != 0;
  l.tuned_fusion_threshold = r.i64();
  l.tuned_cycle_time_ms = r.f64();
  l.tuned_flags = r.u8();
  int32_t n = r.i32();
  l.responses.reserve(static_cast<size_t>(n));
  for (int32_t i = 0; i < n; ++i)
    l.responses.push_back(Response::Deserialize(r));
  return l;
}

}  // namespace hvdtpu
