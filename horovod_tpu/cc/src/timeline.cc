#include "timeline.h"

#include "logging.h"

namespace hvdtpu {

void Timeline::Initialize(const std::string& path, bool mark_cycles) {
  if (initialized_.load()) return;
  file_ = std::fopen(path.c_str(), "w");
  if (file_ == nullptr) {
    HVDTPU_LOG(ERROR) << "Failed to open timeline file: " << path;
    return;
  }
  std::fputs("[\n", file_);
  first_event_ = true;
  mark_cycles_ = mark_cycles;
  start_ = std::chrono::steady_clock::now();
  stop_ = false;
  writer_ = std::thread(&Timeline::WriterLoop, this);
  initialized_.store(true);
}

void Timeline::Shutdown() {
  if (!initialized_.load()) return;
  {
    std::lock_guard<std::mutex> g(mu_);
    stop_ = true;
    cv_.notify_all();
  }
  if (writer_.joinable()) writer_.join();
  // Leave the JSON array unclosed — chrome://tracing accepts it, and so does
  // the reference's writer (timeline.cc never writes the closing bracket).
  std::fclose(file_);
  file_ = nullptr;
  initialized_.store(false);
}

int64_t Timeline::NowUs() const {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now() - start_)
      .count();
}

int Timeline::LaneFor(const std::string& tensor) {
  auto it = lanes_.find(tensor);
  if (it != lanes_.end()) return it->second;
  int lane = next_lane_++;
  lanes_.emplace(tensor, lane);
  return lane;
}

void Timeline::Enqueue(Event e) {
  std::lock_guard<std::mutex> g(mu_);
  queue_.push_back(std::move(e));
  cv_.notify_one();
}

static void JsonEscape(const std::string& in, std::string* out) {
  for (char c : in) {
    if (c == '"' || c == '\\') {
      out->push_back('\\');
      out->push_back(c);
    } else if (c >= 0x20) {
      out->push_back(c);
    }
  }
}

void Timeline::WriterLoop() {
  // Dedicated writer thread so fwrite latency never blocks the negotiation
  // cycle (reference rationale: timeline.h:48-60).
  std::unique_lock<std::mutex> lk(mu_);
  while (true) {
    cv_.wait(lk, [this] { return stop_ || !queue_.empty(); });
    while (!queue_.empty()) {
      Event e = std::move(queue_.front());
      queue_.pop_front();
      lk.unlock();
      int lane = LaneFor(e.tensor);
      std::string name, tensor;
      JsonEscape(e.name, &name);
      JsonEscape(e.tensor, &tensor);
      if (!first_event_) std::fputs(",\n", file_);
      first_event_ = false;
      if (e.ph == 'i') {
        std::fprintf(file_,
                     "{\"ph\":\"i\",\"name\":\"%s\",\"pid\":0,\"tid\":%d,"
                     "\"ts\":%lld,\"s\":\"g\"}",
                     name.c_str(), lane, static_cast<long long>(e.ts_us));
      } else {
        std::fprintf(file_,
                     "{\"ph\":\"%c\",\"name\":\"%s\",\"pid\":0,\"tid\":%d,"
                     "\"ts\":%lld,\"args\":{\"tensor\":\"%s\"}}",
                     e.ph, name.c_str(), lane,
                     static_cast<long long>(e.ts_us), tensor.c_str());
      }
      lk.lock();
    }
    if (stop_) break;
  }
  std::fflush(file_);
}

void Timeline::NegotiateStart(const std::string& tensor_name,
                              const char* op_name) {
  if (!initialized_.load()) return;
  Enqueue({'B', std::string("NEGOTIATE_") + op_name, tensor_name, NowUs()});
}

void Timeline::NegotiateRankReady(const std::string& tensor_name, int rank) {
  if (!initialized_.load()) return;
  Enqueue({'i', "RANK_READY_" + std::to_string(rank), tensor_name, NowUs()});
}

void Timeline::NegotiateEnd(const std::string& tensor_name) {
  if (!initialized_.load()) return;
  Enqueue({'E', "NEGOTIATE", tensor_name, NowUs()});
}

void Timeline::Start(const std::string& tensor_name, const char* op_name) {
  if (!initialized_.load()) return;
  Enqueue({'B', op_name, tensor_name, NowUs()});
}

void Timeline::ActivityStart(const std::string& tensor_name,
                             const char* activity) {
  if (!initialized_.load()) return;
  Enqueue({'B', activity, tensor_name, NowUs()});
}

void Timeline::ActivityEnd(const std::string& tensor_name) {
  if (!initialized_.load()) return;
  Enqueue({'E', "", tensor_name, NowUs()});
}

void Timeline::End(const std::string& tensor_name) {
  if (!initialized_.load()) return;
  Enqueue({'E', "", tensor_name, NowUs()});
}

void Timeline::MarkCycleStart() {
  if (!initialized_.load() || !mark_cycles_) return;
  Enqueue({'i', "CYCLE_START", "", NowUs()});
}

}  // namespace hvdtpu
