// LRU cache of negotiated Responses, bit-indexed for cross-rank sync.
//
// Reference: horovod/common/response_cache.{h,cc} (response_cache.h:45-167).
// After the first negotiation of a tensor, its Response is cached under a
// stable bit position; on later cycles every rank marks the bits of its
// ready tensors and the ranks agree via one bitwise-AND allreduce of the
// bitvector instead of a full gather/bcast round (controller.cc:75-164).
// This is the steady-state fast path.
#ifndef HVDTPU_RESPONSE_CACHE_H
#define HVDTPU_RESPONSE_CACHE_H

#include <cstdint>
#include <list>
#include <string>
#include <unordered_map>
#include <vector>

#include "message.h"

namespace hvdtpu {

class ResponseCache {
 public:
  enum class CacheState { MISS, HIT, INVALID };

  void set_capacity(uint32_t capacity) { capacity_ = capacity; }
  uint32_t capacity() const { return capacity_; }
  size_t num_active_bits() const { return cache_.size(); }

  // MISS = never seen; HIT = cached with identical params; INVALID = cached
  // but the request's dtype/shape/params changed (entry must be evicted
  // globally before renegotiation) — reference: response_cache.cc cached().
  CacheState cached(const Request& req) const;

  // Cache a single-tensor response under its own carried params (dtype,
  // cache_shape, scales, op) — every rank, joined or not, performs the same
  // insertion so bit numbering stays aligned across the job.
  void put(const Response& response);

  Response get_response(uint32_t bit);
  uint32_t peek_cache_bit(const Request& req) const;
  bool has_bit(uint32_t bit) const { return bit < bit_to_name_.size() &&
                                            !bit_to_name_[bit].empty(); }
  void erase_response(uint32_t bit);
  void clear();

  // Bump LRU position for a hit (reference: update_cache_bits_).
  void touch(uint32_t bit);

 private:
  struct CacheEntry {
    Response response;
    DataType dtype;
    std::vector<int64_t> shape;
    double prescale;
    double postscale;
    ReduceOp reduce_op;
    uint32_t bit;
    std::list<uint32_t>::iterator lru_it;
  };

  uint32_t capacity_ = 1024;
  std::unordered_map<std::string, CacheEntry> cache_;
  std::vector<std::string> bit_to_name_;
  std::vector<uint32_t> free_bits_;
  std::list<uint32_t> lru_;  // front = most recently used
};

// Helpers for the bit-packed vote exchanged between ranks.
std::vector<int64_t> PackBits(const std::vector<uint32_t>& bits, size_t nbits);
std::vector<uint32_t> UnpackBits(const std::vector<int64_t>& words);
std::vector<int64_t> AndWords(const std::vector<int64_t>& a,
                              const std::vector<int64_t>& b);

}  // namespace hvdtpu

#endif  // HVDTPU_RESPONSE_CACHE_H
