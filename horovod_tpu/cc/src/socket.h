// TCP socket helpers for the control and data planes.
//
// The reference's control plane rides MPI_Gather/Bcast or gloo's TCP store
// (mpi_controller.cc:108-189, gloo_context.cc); the TPU control plane is
// plain TCP between worker hosts. All messages are 8-byte-length-prefixed
// frames.
#ifndef HVDTPU_SOCKET_H
#define HVDTPU_SOCKET_H

#include <cstdint>
#include <string>
#include <vector>

namespace hvdtpu {

class TcpSocket {
 public:
  TcpSocket() = default;
  explicit TcpSocket(int fd) : fd_(fd) {}
  TcpSocket(const TcpSocket&) = delete;
  TcpSocket& operator=(const TcpSocket&) = delete;
  TcpSocket(TcpSocket&& o) noexcept : fd_(o.fd_) { o.fd_ = -1; }
  TcpSocket& operator=(TcpSocket&& o) noexcept;
  ~TcpSocket() { Close(); }

  bool valid() const { return fd_ >= 0; }
  int fd() const { return fd_; }
  void Close();

  // Data-plane sockets run non-blocking: a blocking send() of a multi-MB
  // ring chunk would stall past the kernel socket buffer without draining
  // the receive side — symmetric across the ring, that deadlocks. SendAll/
  // RecvAll poll() on EAGAIN so callers keep sequential semantics.
  void SetNonBlocking();

  // Blocking connect with retry (the peer may not be listening yet during
  // job bringup; reference gloo rendezvous retries the same way).
  static TcpSocket Connect(const std::string& host, int port,
                           double timeout_secs);

  bool SendAll(const void* data, size_t size);
  bool RecvAll(void* data, size_t size);

  bool SendFrame(const std::vector<char>& payload);
  bool RecvFrame(std::vector<char>* payload);

  // Bidirectional exchange without deadlock on large payloads: progresses
  // send and recv simultaneously via poll(). Needed by the ring collectives
  // where both neighbors send at once.
  bool SendRecv(const void* send_buf, size_t send_size, void* recv_buf,
                size_t recv_size);

 private:
  int fd_ = -1;
};

class TcpServer {
 public:
  // Listen on an ephemeral (port=0) or fixed port on all interfaces.
  bool Listen(int port);
  int port() const { return port_; }
  TcpSocket Accept(double timeout_secs);
  void Close();
  ~TcpServer() { Close(); }

 private:
  int fd_ = -1;
  int port_ = 0;
};

}  // namespace hvdtpu

#endif  // HVDTPU_SOCKET_H
