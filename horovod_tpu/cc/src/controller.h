// Rank-0 coordinator negotiation protocol.
//
// Reference: horovod/common/controller.{h,cc}. The protocol
// (controller.h:63-100): every cycle each rank reports which tensors became
// ready; the coordinator counts submissions per tensor
// (IncrementTensorCount, controller.cc:837-860), and when every
// participating rank has submitted a tensor it validates cross-rank
// consistency and builds a Response (ConstructResponse,
// controller.cc:380-657), packs small allreduces under the fusion threshold
// (FuseResponses, controller.cc:686-809), and broadcasts the ordered
// ResponseList that every rank then executes identically. A bit-indexed
// response cache short-circuits negotiation for previously seen tensors
// (controller.cc:75-164) — the steady-state fast path.
#ifndef HVDTPU_CONTROLLER_H
#define HVDTPU_CONTROLLER_H

#include <cstdint>
#include <functional>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

#include "message.h"
#include "response_cache.h"
#include "stall_inspector.h"
#include "tensor_queue.h"
#include "timeline.h"
#include "transport.h"

namespace hvdtpu {

// Tuned-parameter set as it rides the cycle broadcast (filled by the
// autotune hook; operations.cc converts from ParameterManager::TunedParams).
struct TunedParamsWire {
  int64_t fusion_threshold = 0;
  double cycle_time_ms = 0.0;
  bool has_flags = false;
  uint8_t flags = 0;  // bit0 cache, bit1 hier_ar, bit2 hier_ag
};

class Controller {
 public:
  Controller(int rank, int size, Transport* transport, TensorQueue* queue,
             ResponseCache* cache, StallInspector* stall, Timeline* timeline)
      : rank_(rank),
        size_(size),
        transport_(transport),
        tensor_queue_(queue),
        cache_(cache),
        stall_(stall),
        timeline_(timeline) {}

  struct CycleResult {
    std::vector<Response> responses;  // ordered, identical on every rank
    bool shutdown = false;
    bool transport_failure = false;
    int64_t tuned_fusion_threshold = 0;   // nonzero → apply
    double tuned_cycle_time_ms = 0.0;     // nonzero → apply
    bool has_tuned_flags = false;
    uint8_t tuned_flags = 0;  // bit0 cache, bit1 hier_ar, bit2 hier_ag
  };

  // One negotiation cycle (reference: ComputeResponseList,
  // controller.cc:63-358). `request_shutdown` = this process wants out.
  // Joined state is tracked internally from JOIN requests.
  CycleResult RunCycle(bool request_shutdown, int64_t fusion_threshold_bytes);

  bool is_coordinator() const { return rank_ == 0; }
  bool self_joined() const { return self_joined_; }

  // Coordinator-side autotune hook (set by operations.cc when
  // HOROVOD_AUTOTUNE=1): called once per cycle with the negotiated
  // responses; returns true + new params when a new setting should be
  // broadcast (reference: parameter_manager.Update / SynchronizeParameters,
  // operations.cc:614-621, controller.cc:34-48).
  std::function<bool(const std::vector<Response>&, TunedParamsWire*)>
      autotune_hook;

  // Response-cache on/off switch, tuned at runtime by the autotuner
  // (reference: PARAMETER cache_enabled_, parameter_manager.cc:51-74).
  // Every rank applies the toggle at the same cycle boundary (it ships in
  // the ResponseList broadcast), so the distributed cache-bit tables stay
  // consistent: while disabled no rank consults or fills the cache.
  void set_cache_enabled(bool enabled);
  bool cache_enabled() const { return cache_enabled_; }

 private:
  // -- coordinator state --
  struct PendingTensor {
    std::vector<Request> requests;  // one per submitting rank
    std::set<int> ready_ranks;
  };

  // Returns true when all participating (non-joined) ranks have submitted
  // (reference: IncrementTensorCount, controller.cc:837-860).
  bool IncrementTensorCount(const Request& req);
  // Cross-rank consistency validation + response construction (reference:
  // ConstructResponse, controller.cc:380-657).
  Response ConstructResponse(const std::string& name);
  // Pack consecutive same-dtype allreduces under the threshold (reference:
  // FuseResponses, controller.cc:686-809).
  std::vector<Response> FuseResponses(std::vector<Response> responses,
                                      int64_t threshold_bytes);
  // Tensors that became complete because `joined_ranks_` grew.
  void CollectNewlyCompleteTensors(std::vector<Response>* out);

  ResponseList CoordinatorCycle(std::vector<RequestList> rank_lists,
                                int64_t fusion_threshold_bytes);
  void ApplyResponseList(const ResponseList& final_list, CycleResult* out);

  // -- per-rank (all ranks) cache voting state --
  // Cached-hit requests held locally (by name) until their bit fires
  // globally; re-voted every cycle.
  std::unordered_map<std::string, Request> pending_cached_;
  // Invalid-bit votes to send this cycle.
  std::vector<uint32_t> my_invalid_bits_;
  // Requests to send as uncached next cycle (post-eviction resubmits).
  std::vector<Request> resend_uncached_;
  bool cache_enabled_ = true;

  int rank_;
  int size_;
  Transport* transport_;
  TensorQueue* tensor_queue_;
  ResponseCache* cache_;
  StallInspector* stall_;
  Timeline* timeline_;

  // Coordinator-only.
  std::unordered_map<std::string, PendingTensor> message_table_;
  std::set<int> joined_ranks_;
  int last_joined_rank_ = -1;
  bool shutdown_latch_ = false;

  bool self_joined_ = false;
};

}  // namespace hvdtpu

#endif  // HVDTPU_CONTROLLER_H
