#include "parameter_manager.h"

#include <algorithm>
#include <cmath>

#include "logging.h"

namespace hvdtpu {

namespace {
// Search bounds, log-scale (reference tunes fusion threshold 0..64MB and
// cycle time 1..25ms over a discrete grid/BO hybrid; we use a continuous
// log box that covers the same region).
constexpr double kMinFusionLog = 10.0;  // 2^10 = 1 KB
constexpr double kMaxFusionLog = 28.0;  // 2^28 = 256 MB
constexpr double kMinCycleLog = -1.0;   // 2^-1 = 0.5 ms
constexpr double kMaxCycleLog = 5.64;   // ~50 ms
constexpr int kDims = 5;  // fusion, cycle, cache, hier_ar, hier_ag
}  // namespace

void ParameterManager::Initialize(int64_t fusion_threshold,
                                  double cycle_time_ms, bool cache_enabled,
                                  bool hierarchical_allreduce,
                                  bool hierarchical_allgather,
                                  bool tune_hierarchical,
                                  const std::string& log_path,
                                  int64_t warmup_samples,
                                  int64_t cycles_per_sample,
                                  int64_t max_samples, double gp_noise) {
  active_ = true;
  current_.fusion_threshold = fusion_threshold;
  current_.cycle_time_ms = cycle_time_ms;
  current_.has_flags = true;
  current_.cache_enabled = cache_enabled;
  current_.hierarchical_allreduce = hierarchical_allreduce;
  current_.hierarchical_allgather = hierarchical_allgather;
  best_ = current_;
  tune_hierarchical_ = tune_hierarchical;
  warmup_samples_ = warmup_samples;
  cycles_per_sample_ = cycles_per_sample;
  max_samples_ = max_samples;
  gp_noise_ = gp_noise;
  window_start_ = std::chrono::steady_clock::now();
  if (!log_path.empty()) {
    log_ = std::fopen(log_path.c_str(), "w");
    if (log_ != nullptr) {
      std::fprintf(log_,
                   "fusion_threshold_bytes,cycle_time_ms,cache_enabled,"
                   "hierarchical_allreduce,hierarchical_allgather,"
                   "score_bytes_per_sec\n");
    }
  }
}

ParameterManager::~ParameterManager() {
  if (log_ != nullptr) std::fclose(log_);
}

void ParameterManager::RecordBytes(int64_t bytes) {
  bytes_accum_ += bytes;
}

std::vector<double> ParameterManager::ToUnit(const TunedParams& p) const {
  double f = std::log2(
      std::max<double>(1.0, static_cast<double>(p.fusion_threshold)));
  double c = std::log2(std::max(1e-3, p.cycle_time_ms));
  // Booleans sit at 0.25/0.75 so the GP sees them well inside the box.
  return {(f - kMinFusionLog) / (kMaxFusionLog - kMinFusionLog),
          (c - kMinCycleLog) / (kMaxCycleLog - kMinCycleLog),
          p.cache_enabled ? 0.75 : 0.25,
          p.hierarchical_allreduce ? 0.75 : 0.25,
          p.hierarchical_allgather ? 0.75 : 0.25};
}

TunedParams ParameterManager::FromUnit(const std::vector<double>& u) const {
  TunedParams p;
  double f = kMinFusionLog + u[0] * (kMaxFusionLog - kMinFusionLog);
  double c = kMinCycleLog + u[1] * (kMaxCycleLog - kMinCycleLog);
  p.fusion_threshold = static_cast<int64_t>(std::pow(2.0, f));
  p.cycle_time_ms = std::pow(2.0, c);
  p.has_flags = true;
  p.cache_enabled = u[2] >= 0.5;
  p.hierarchical_allreduce = tune_hierarchical_ && u[3] >= 0.5;
  p.hierarchical_allgather = tune_hierarchical_ && u[4] >= 0.5;
  return p;
}

void ParameterManager::ProposeNext() {
  // Normalize scores to zero-mean/unit-variance for the GP.
  double mean = 0.0;
  for (double y : ys_) mean += y;
  mean /= static_cast<double>(ys_.size());
  double var = 0.0;
  for (double y : ys_) var += (y - mean) * (y - mean);
  double sd = std::sqrt(var / static_cast<double>(ys_.size()));
  if (sd <= 0.0) sd = 1.0;
  std::vector<double> yn(ys_.size());
  double best_n = -1e30;
  for (size_t i = 0; i < ys_.size(); ++i) {
    yn[i] = (ys_[i] - mean) / sd;
    best_n = std::max(best_n, yn[i]);
  }
  GaussianProcess gp(kDims, 0.3, gp_noise_);
  bool fitted = gp.Fit(xs_, yn);

  auto rnd = [this]() {
    // xorshift64* — deterministic, no external RNG dependency.
    rng_state_ ^= rng_state_ >> 12;
    rng_state_ ^= rng_state_ << 25;
    rng_state_ ^= rng_state_ >> 27;
    return static_cast<double>((rng_state_ * 0x2545F4914F6CDD1Dull) >> 11) /
           static_cast<double>(1ull << 53);
  };
  auto sample = [&]() {
    std::vector<double> x(kDims);
    for (int i = 0; i < kDims; ++i) x[i] = rnd();
    if (!tune_hierarchical_) {
      x[3] = 0.25;
      x[4] = 0.25;
    }
    return x;
  };
  std::vector<double> best_x = sample();
  if (fitted) {
    double best_ei = -1.0;
    for (int i = 0; i < 1000; ++i) {
      std::vector<double> cand = sample();
      double ei = gp.ExpectedImprovement(cand, best_n);
      if (ei > best_ei) {
        best_ei = ei;
        best_x = cand;
      }
    }
  }
  current_ = FromUnit(best_x);
  pending_broadcast_ = true;
}

bool ParameterManager::Update(const std::vector<Response>& responses,
                              TunedParams* out) {
  if (!active_ || done_) return false;
  if (pending_broadcast_) {
    // Ship the newly proposed params this cycle.
    pending_broadcast_ = false;
    *out = current_;
    return true;
  }
  cycles_in_window_++;
  if (cycles_in_window_ < cycles_per_sample_) return false;
  double elapsed = std::chrono::duration<double>(
                       std::chrono::steady_clock::now() - window_start_)
                       .count();
  int64_t bytes = bytes_accum_;
  bytes_accum_ = 0;
  cycles_in_window_ = 0;
  window_start_ = std::chrono::steady_clock::now();
  if (bytes == 0 || elapsed <= 0.0) {
    return false;  // idle window: don't score (reference pauses tuning)
  }
  double score = static_cast<double>(bytes) / elapsed;
  samples_done_++;
  if (samples_done_ <= warmup_samples_) return false;

  if (log_ != nullptr) {
    std::fprintf(log_, "%lld,%.3f,%d,%d,%d,%.1f\n",
                 static_cast<long long>(current_.fusion_threshold),
                 current_.cycle_time_ms, current_.cache_enabled ? 1 : 0,
                 current_.hierarchical_allreduce ? 1 : 0,
                 current_.hierarchical_allgather ? 1 : 0, score);
    std::fflush(log_);
  }
  xs_.push_back(ToUnit(current_));
  ys_.push_back(score);
  if (score > best_score_) {
    best_score_ = score;
    best_ = current_;
  }
  if (static_cast<int64_t>(ys_.size()) >= max_samples_) {
    // Converge: lock in the best seen configuration.
    done_ = true;
    current_ = best_;
    HVDTPU_LOG(INFO) << "autotune converged: fusion_threshold="
                     << best_.fusion_threshold
                     << " cycle_time_ms=" << best_.cycle_time_ms
                     << " cache=" << best_.cache_enabled
                     << " hier_allreduce=" << best_.hierarchical_allreduce
                     << " hier_allgather=" << best_.hierarchical_allgather
                     << " (best " << best_score_ / 1e6 << " MB/s)";
    *out = best_;
    return true;
  }
  ProposeNext();
  return false;  // proposal ships next cycle via pending_broadcast_
}

}  // namespace hvdtpu
