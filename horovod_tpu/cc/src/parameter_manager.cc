#include "parameter_manager.h"

#include <algorithm>
#include <cmath>

#include "logging.h"

namespace hvdtpu {

namespace {
// Search bounds, log-scale (reference tunes fusion threshold 0..64MB and
// cycle time 1..25ms over a discrete grid/BO hybrid; we use a continuous
// log box that covers the same region).
constexpr double kMinFusionLog = 10.0;  // 2^10 = 1 KB
constexpr double kMaxFusionLog = 28.0;  // 2^28 = 256 MB
constexpr double kMinCycleLog = -1.0;   // 2^-1 = 0.5 ms
constexpr double kMaxCycleLog = 5.64;   // ~50 ms
}  // namespace

void ParameterManager::Initialize(int64_t fusion_threshold,
                                  double cycle_time_ms,
                                  const std::string& log_path,
                                  int64_t warmup_samples,
                                  int64_t cycles_per_sample,
                                  int64_t max_samples, double gp_noise) {
  active_ = true;
  current_fusion_ = best_fusion_ = fusion_threshold;
  current_cycle_ = best_cycle_ = cycle_time_ms;
  warmup_samples_ = warmup_samples;
  cycles_per_sample_ = cycles_per_sample;
  max_samples_ = max_samples;
  gp_noise_ = gp_noise;
  window_start_ = std::chrono::steady_clock::now();
  if (!log_path.empty()) {
    log_ = std::fopen(log_path.c_str(), "w");
    if (log_ != nullptr) {
      std::fprintf(log_, "fusion_threshold_bytes,cycle_time_ms,score_bytes_per_sec\n");
    }
  }
}

ParameterManager::~ParameterManager() {
  if (log_ != nullptr) std::fclose(log_);
}

void ParameterManager::RecordBytes(int64_t bytes) {
  bytes_accum_ += bytes;
}

std::vector<double> ParameterManager::ToUnit(int64_t fusion,
                                             double cycle) const {
  double f = std::log2(std::max<double>(1.0, static_cast<double>(fusion)));
  double c = std::log2(std::max(1e-3, cycle));
  return {(f - kMinFusionLog) / (kMaxFusionLog - kMinFusionLog),
          (c - kMinCycleLog) / (kMaxCycleLog - kMinCycleLog)};
}

void ParameterManager::FromUnit(const std::vector<double>& u,
                                int64_t* fusion, double* cycle) const {
  double f = kMinFusionLog + u[0] * (kMaxFusionLog - kMinFusionLog);
  double c = kMinCycleLog + u[1] * (kMaxCycleLog - kMinCycleLog);
  *fusion = static_cast<int64_t>(std::pow(2.0, f));
  *cycle = std::pow(2.0, c);
}

void ParameterManager::ProposeNext() {
  // Normalize scores to zero-mean/unit-variance for the GP.
  double mean = 0.0;
  for (double y : ys_) mean += y;
  mean /= static_cast<double>(ys_.size());
  double var = 0.0;
  for (double y : ys_) var += (y - mean) * (y - mean);
  double sd = std::sqrt(var / static_cast<double>(ys_.size()));
  if (sd <= 0.0) sd = 1.0;
  std::vector<double> yn(ys_.size());
  double best_n = -1e30;
  for (size_t i = 0; i < ys_.size(); ++i) {
    yn[i] = (ys_[i] - mean) / sd;
    best_n = std::max(best_n, yn[i]);
  }
  GaussianProcess gp(2, 0.3, gp_noise_);
  bool fitted = gp.Fit(xs_, yn);

  auto rnd = [this]() {
    // xorshift64* — deterministic, no external RNG dependency.
    rng_state_ ^= rng_state_ >> 12;
    rng_state_ ^= rng_state_ << 25;
    rng_state_ ^= rng_state_ >> 27;
    return static_cast<double>((rng_state_ * 0x2545F4914F6CDD1Dull) >> 11) /
           static_cast<double>(1ull << 53);
  };
  std::vector<double> best_x = {rnd(), rnd()};
  if (fitted) {
    double best_ei = -1.0;
    for (int i = 0; i < 1000; ++i) {
      std::vector<double> cand = {rnd(), rnd()};
      double ei = gp.ExpectedImprovement(cand, best_n);
      if (ei > best_ei) {
        best_ei = ei;
        best_x = cand;
      }
    }
  }
  FromUnit(best_x, &current_fusion_, &current_cycle_);
  pending_broadcast_ = true;
}

bool ParameterManager::Update(const std::vector<Response>& responses,
                              int64_t* fusion_out, double* cycle_out) {
  if (!active_ || done_) return false;
  if (pending_broadcast_) {
    // Ship the newly proposed params this cycle.
    pending_broadcast_ = false;
    *fusion_out = current_fusion_;
    *cycle_out = current_cycle_;
    return true;
  }
  cycles_in_window_++;
  if (cycles_in_window_ < cycles_per_sample_) return false;
  double elapsed = std::chrono::duration<double>(
                       std::chrono::steady_clock::now() - window_start_)
                       .count();
  int64_t bytes = bytes_accum_;
  bytes_accum_ = 0;
  cycles_in_window_ = 0;
  window_start_ = std::chrono::steady_clock::now();
  if (bytes == 0 || elapsed <= 0.0) {
    return false;  // idle window: don't score (reference pauses tuning)
  }
  double score = static_cast<double>(bytes) / elapsed;
  samples_done_++;
  if (samples_done_ <= warmup_samples_) return false;

  if (log_ != nullptr) {
    std::fprintf(log_, "%lld,%.3f,%.1f\n",
                 static_cast<long long>(current_fusion_), current_cycle_,
                 score);
    std::fflush(log_);
  }
  xs_.push_back(ToUnit(current_fusion_, current_cycle_));
  ys_.push_back(score);
  if (score > best_score_) {
    best_score_ = score;
    best_fusion_ = current_fusion_;
    best_cycle_ = current_cycle_;
  }
  if (static_cast<int64_t>(ys_.size()) >= max_samples_) {
    // Converge: lock in the best seen configuration.
    done_ = true;
    current_fusion_ = best_fusion_;
    current_cycle_ = best_cycle_;
    HVDTPU_LOG(INFO) << "autotune converged: fusion_threshold="
                     << best_fusion_ << " cycle_time_ms=" << best_cycle_
                     << " (best " << best_score_ / 1e6 << " MB/s)";
    *fusion_out = best_fusion_;
    *cycle_out = best_cycle_;
    return true;
  }
  ProposeNext();
  return false;  // proposal ships next cycle via pending_broadcast_
}

}  // namespace hvdtpu
