// horovod_tpu native core: shared types.
//
// TPU-native rebuild of the reference's framework-neutral core types
// (reference: horovod/common/common.h:31-258, half.h). The native core is the
// host-side control plane: it negotiates readiness across worker processes
// (one per TPU host), runs the eager/host data plane over TCP, and feeds the
// compiled XLA path with a learned static schedule. No CUDA, no MPI.
#ifndef HVDTPU_COMMON_H
#define HVDTPU_COMMON_H

#include <cstdint>
#include <cstring>
#include <functional>
#include <memory>
#include <string>
#include <vector>

namespace hvdtpu {

// Wire/compute dtypes (reference: DataType, common.h message dtypes).
enum class DataType : uint8_t {
  HVDTPU_UINT8 = 0,
  HVDTPU_INT8 = 1,
  HVDTPU_INT32 = 2,
  HVDTPU_INT64 = 3,
  HVDTPU_FLOAT16 = 4,
  HVDTPU_BFLOAT16 = 5,
  HVDTPU_FLOAT32 = 6,
  HVDTPU_FLOAT64 = 7,
  HVDTPU_BOOL = 8,
};

inline size_t DataTypeSize(DataType dt) {
  switch (dt) {
    case DataType::HVDTPU_UINT8:
    case DataType::HVDTPU_INT8:
    case DataType::HVDTPU_BOOL:
      return 1;
    case DataType::HVDTPU_FLOAT16:
    case DataType::HVDTPU_BFLOAT16:
      return 2;
    case DataType::HVDTPU_INT32:
    case DataType::HVDTPU_FLOAT32:
      return 4;
    case DataType::HVDTPU_INT64:
    case DataType::HVDTPU_FLOAT64:
      return 8;
  }
  return 0;
}

const char* DataTypeName(DataType dt);

// Reduction ops for allreduce (reference: ReduceOp in torch/mpi_ops.py:48-56;
// Sum is the wire op, Average is Sum + postscale, Adasum is its own path).
enum class ReduceOp : uint8_t {
  SUM = 0,
  MIN = 1,
  MAX = 2,
  PRODUCT = 3,
  ADASUM = 4,
};

// Status codes (reference: StatusType, common.h:132-150).
enum class StatusType : uint8_t {
  OK = 0,
  UNKNOWN_ERROR = 1,
  PRECONDITION_ERROR = 2,
  ABORTED = 3,
  INVALID_ARGUMENT = 4,
  IN_PROGRESS = 5,
};

class Status {
 public:
  Status() = default;
  static Status OK() { return Status(); }
  static Status UnknownError(const std::string& m) {
    return Status(StatusType::UNKNOWN_ERROR, m);
  }
  static Status PreconditionError(const std::string& m) {
    return Status(StatusType::PRECONDITION_ERROR, m);
  }
  static Status Aborted(const std::string& m) {
    return Status(StatusType::ABORTED, m);
  }
  static Status InvalidArgument(const std::string& m) {
    return Status(StatusType::INVALID_ARGUMENT, m);
  }
  static Status InProgress() { return Status(StatusType::IN_PROGRESS, ""); }
  bool ok() const { return type_ == StatusType::OK; }
  bool in_progress() const { return type_ == StatusType::IN_PROGRESS; }
  StatusType type() const { return type_; }
  const std::string& reason() const { return reason_; }

 private:
  Status(StatusType t, std::string r) : type_(t), reason_(std::move(r)) {}
  StatusType type_ = StatusType::OK;
  std::string reason_;
};

// bf16 <-> f32 (truncation / round-to-nearest-even) and fp16 <-> f32 software
// conversion for host-side reductions (reference: half.{h,cc} float16 sum
// with the same convert-accumulate-convert structure).
inline float Bf16ToFloat(uint16_t v) {
  uint32_t bits = static_cast<uint32_t>(v) << 16;
  float out;
  std::memcpy(&out, &bits, 4);
  return out;
}

inline uint16_t FloatToBf16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  // round to nearest even
  uint32_t rounding = 0x7fff + ((bits >> 16) & 1);
  return static_cast<uint16_t>((bits + rounding) >> 16);
}

float Fp16ToFloat(uint16_t h);
uint16_t FloatToFp16(float f);

// Duplicate-name message (reference: DUPLICATE_NAME_ERROR, common.h:163-166).
#define HVDTPU_DUPLICATE_NAME_ERROR                                         \
  "Requested to collect a tensor with the same name as another tensor "     \
  "that is currently being processed. If you want to request another "      \
  "tensor, use a different tensor name."

// Environment knob names. Same contract as the reference
// (common.h:64-90, gloo_run.py:65-76) so launcher/env docs carry over.
#define HVDTPU_ENV_RANK "HOROVOD_RANK"
#define HVDTPU_ENV_SIZE "HOROVOD_SIZE"
#define HVDTPU_ENV_LOCAL_RANK "HOROVOD_LOCAL_RANK"
#define HVDTPU_ENV_LOCAL_SIZE "HOROVOD_LOCAL_SIZE"
#define HVDTPU_ENV_CROSS_RANK "HOROVOD_CROSS_RANK"
#define HVDTPU_ENV_CROSS_SIZE "HOROVOD_CROSS_SIZE"
#define HVDTPU_ENV_CONTROLLER_ADDR "HOROVOD_CONTROLLER_ADDR"
#define HVDTPU_ENV_CONTROLLER_PORT "HOROVOD_CONTROLLER_PORT"
#define HVDTPU_ENV_FUSION_THRESHOLD "HOROVOD_FUSION_THRESHOLD"
#define HVDTPU_ENV_HIERARCHICAL_ALLREDUCE "HOROVOD_HIERARCHICAL_ALLREDUCE"
#define HVDTPU_ENV_HIERARCHICAL_ALLGATHER "HOROVOD_HIERARCHICAL_ALLGATHER"
#define HVDTPU_ENV_CYCLE_TIME "HOROVOD_CYCLE_TIME"
#define HVDTPU_ENV_CACHE_CAPACITY "HOROVOD_CACHE_CAPACITY"
#define HVDTPU_ENV_TIMELINE "HOROVOD_TIMELINE"
#define HVDTPU_ENV_TIMELINE_MARK_CYCLES "HOROVOD_TIMELINE_MARK_CYCLES"
#define HVDTPU_ENV_STALL_CHECK_DISABLE "HOROVOD_STALL_CHECK_DISABLE"
#define HVDTPU_ENV_STALL_CHECK_TIME "HOROVOD_STALL_CHECK_TIME_SECONDS"
#define HVDTPU_ENV_STALL_SHUTDOWN_TIME "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"
#define HVDTPU_ENV_AUTOTUNE "HOROVOD_AUTOTUNE"
#define HVDTPU_ENV_AUTOTUNE_LOG "HOROVOD_AUTOTUNE_LOG"
#define HVDTPU_ENV_AUTOTUNE_WARMUP_SAMPLES "HOROVOD_AUTOTUNE_WARMUP_SAMPLES"
#define HVDTPU_ENV_AUTOTUNE_STEPS_PER_SAMPLE "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"
#define HVDTPU_ENV_AUTOTUNE_BAYES_OPT_MAX_SAMPLES \
  "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"
#define HVDTPU_ENV_AUTOTUNE_GAUSSIAN_PROCESS_NOISE \
  "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE"

// Env parsing helpers (reference: utils/env_parser.{h,cc}).
int64_t EnvInt64(const char* name, int64_t dflt);
double EnvDouble(const char* name, double dflt);
bool EnvBool(const char* name, bool dflt);
std::string EnvString(const char* name, const std::string& dflt);

}  // namespace hvdtpu

#endif  // HVDTPU_COMMON_H
