// Global state + background loop + C ABI.
//
// Reference: horovod/common/operations.{h,cc} and global_state.h — the
// single background communication thread (BackgroundThreadLoop,
// operations.cc:354) that owns all negotiation and host collectives, the
// Enqueue* API (operations.cc:893-1120), and the C ABI consumed by the
// Python bindings (operations.cc:685-889). The rationale for one thread
// (operations.cc:332-351) carries over: global agreement on op order, async
// submission from any thread, and a single owner for the TCP transport.
#ifndef HVDTPU_OPERATIONS_H
#define HVDTPU_OPERATIONS_H

#include <cstdint>

extern "C" {

// Lifecycle. hvdtpu_init reads the launcher env contract (HOROVOD_RANK/
// SIZE/..., HOROVOD_CONTROLLER_ADDR/PORT) and spawns the background loop.
// Returns 0 on success.
int hvdtpu_init(void);
void hvdtpu_shutdown(void);
int hvdtpu_is_initialized(void);
const char* hvdtpu_last_error(void);

int hvdtpu_rank(void);
int hvdtpu_size(void);
int hvdtpu_local_rank(void);
int hvdtpu_local_size(void);
int hvdtpu_cross_rank(void);
int hvdtpu_cross_size(void);
int64_t hvdtpu_fusion_threshold(void);
double hvdtpu_cycle_time_ms(void);

// Collectives: enqueue returns a handle (>= 0) or -1 (see
// hvdtpu_last_error). dtype = hvdtpu::DataType, op = hvdtpu::ReduceOp.
// Average rides SUM + postscale 1/size, as in the reference wire protocol.
int hvdtpu_allreduce(const char* name, void* data, const int64_t* shape,
                     int ndim, int dtype, int op, double prescale,
                     double postscale);
int hvdtpu_allgather(const char* name, const void* data,
                     const int64_t* shape, int ndim, int dtype);
int hvdtpu_broadcast(const char* name, void* data, const int64_t* shape,
                     int ndim, int dtype, int root);
int hvdtpu_alltoall(const char* name, const void* data, const int64_t* shape,
                    int ndim, int dtype, const int64_t* splits, int nsplits);
int hvdtpu_join(void);
int hvdtpu_barrier(void);

// Handle API (reference: torch handle_manager + poll/synchronize,
// torch/mpi_ops.py:66-161).
int hvdtpu_poll(int handle);
int hvdtpu_wait(int handle);  // blocks; returns StatusType (0 = OK)
const char* hvdtpu_handle_error(int handle);
int64_t hvdtpu_result_bytes(int handle);
void hvdtpu_fetch(int handle, void* out);
int hvdtpu_join_result(int handle);
int hvdtpu_recv_splits(int handle, int64_t* out, int max);
void hvdtpu_release(int handle);

// Timeline (reference: horovod_start_timeline, operations.cc:715-757).
int hvdtpu_start_timeline(const char* path, int mark_cycles);
int hvdtpu_stop_timeline(void);

// Autotune introspection (for tests / AUTOTUNE_LOG tooling).
int hvdtpu_autotune_active(void);

}  // extern "C"

#endif  // HVDTPU_OPERATIONS_H
