#include "common.h"

#include <cstdlib>

namespace hvdtpu {

const char* DataTypeName(DataType dt) {
  switch (dt) {
    case DataType::HVDTPU_UINT8: return "uint8";
    case DataType::HVDTPU_INT8: return "int8";
    case DataType::HVDTPU_INT32: return "int32";
    case DataType::HVDTPU_INT64: return "int64";
    case DataType::HVDTPU_FLOAT16: return "float16";
    case DataType::HVDTPU_BFLOAT16: return "bfloat16";
    case DataType::HVDTPU_FLOAT32: return "float32";
    case DataType::HVDTPU_FLOAT64: return "float64";
    case DataType::HVDTPU_BOOL: return "bool";
  }
  return "unknown";
}

// IEEE fp16 software conversion (reference keeps an AVX/F16C fast path in
// half.h:142; plain bit manipulation is plenty for the host control plane).
float Fp16ToFloat(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1f;
  uint32_t mant = h & 0x3ffu;
  uint32_t bits;
  if (exp == 0) {
    if (mant == 0) {
      bits = sign;  // +-0
    } else {
      // subnormal: normalize
      exp = 127 - 15 + 1;
      while ((mant & 0x400u) == 0) {
        mant <<= 1;
        exp--;
      }
      mant &= 0x3ffu;
      bits = sign | (exp << 23) | (mant << 13);
    }
  } else if (exp == 0x1f) {
    bits = sign | 0x7f800000u | (mant << 13);  // inf/nan
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float out;
  std::memcpy(&out, &bits, 4);
  return out;
}

uint16_t FloatToFp16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  uint32_t sign = (bits >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((bits >> 23) & 0xff) - 127 + 15;
  uint32_t mant = bits & 0x7fffffu;
  if (exp >= 0x1f) {
    // overflow / inf / nan
    uint32_t m = ((bits >> 23) & 0xff) == 0xff && mant ? 0x200u : 0;
    return static_cast<uint16_t>(sign | 0x7c00u | m);
  }
  if (exp <= 0) {
    if (exp < -10) return static_cast<uint16_t>(sign);  // underflow to 0
    // subnormal
    mant |= 0x800000u;
    uint32_t shift = static_cast<uint32_t>(14 - exp);
    uint32_t half_mant = mant >> shift;
    uint32_t rem = mant & ((1u << shift) - 1);
    uint32_t halfway = 1u << (shift - 1);
    if (rem > halfway || (rem == halfway && (half_mant & 1))) half_mant++;
    return static_cast<uint16_t>(sign | half_mant);
  }
  uint32_t half_mant = mant >> 13;
  uint32_t rem = mant & 0x1fffu;
  uint16_t out =
      static_cast<uint16_t>(sign | (static_cast<uint32_t>(exp) << 10) | half_mant);
  if (rem > 0x1000u || (rem == 0x1000u && (out & 1))) out++;
  return out;
}

int64_t EnvInt64(const char* name, int64_t dflt) {
  const char* v = std::getenv(name);
  if (v == nullptr || *v == '\0') return dflt;
  return std::strtoll(v, nullptr, 10);
}

double EnvDouble(const char* name, double dflt) {
  const char* v = std::getenv(name);
  if (v == nullptr || *v == '\0') return dflt;
  return std::strtod(v, nullptr);
}

bool EnvBool(const char* name, bool dflt) {
  const char* v = std::getenv(name);
  if (v == nullptr || *v == '\0') return dflt;
  return !(std::string(v) == "0" || std::string(v) == "false" ||
           std::string(v) == "False" || std::string(v) == "");
}

std::string EnvString(const char* name, const std::string& dflt) {
  const char* v = std::getenv(name);
  if (v == nullptr) return dflt;
  return std::string(v);
}

}  // namespace hvdtpu
