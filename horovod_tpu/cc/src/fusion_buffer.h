// Persistent fusion buffer.
//
// Reference: horovod/common/fusion_buffer_manager.{h,cc} — a per-(device,
// framework) persistent buffer sized by HOROVOD_FUSION_THRESHOLD (64 MB
// default, operations.cc:437); fused tensors are memcpy'd in, reduced as one
// flat buffer, and memcpy'd out (collective_operations.cc:34-59). The host
// control plane has one device (CPU), so one buffer suffices; it grows to
// the high-water mark and is reused across cycles.
#ifndef HVDTPU_FUSION_BUFFER_H
#define HVDTPU_FUSION_BUFFER_H

#include <cstdint>
#include <vector>

namespace hvdtpu {

class FusionBufferManager {
 public:
  // Returns a buffer of at least `bytes`, reusing the persistent allocation.
  char* GetBuffer(int64_t bytes) {
    if (static_cast<int64_t>(buffer_.size()) < bytes) {
      buffer_.resize(static_cast<size_t>(bytes));
    }
    return buffer_.data();
  }
  int64_t capacity() const { return static_cast<int64_t>(buffer_.size()); }
  void Release() {
    buffer_.clear();
    buffer_.shrink_to_fit();
  }

 private:
  std::vector<char> buffer_;
};

}  // namespace hvdtpu

#endif  // HVDTPU_FUSION_BUFFER_H
