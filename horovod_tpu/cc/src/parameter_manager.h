// Autotuner for fusion threshold, cycle time, response-cache enablement,
// and the hierarchical allreduce/allgather switches.
//
// Reference: horovod/common/parameter_manager.{h,cc} — the coordinator
// scores each sample window by bytes/sec and proposes the next point by
// Bayesian optimization over the *mixed* space of two numeric knobs
// (fusion threshold, cycle time — parameter_manager.cc:75-96) and three
// categorical ones (cache enabled, hierarchical allreduce/allgather —
// parameter_manager.cc:51-74), then broadcasts tuned values to the workers
// inside the negotiation round (SynchronizeParameters, controller.cc:34-48;
// update loop operations.cc:614-621). Knobs: HOROVOD_AUTOTUNE,
// HOROVOD_AUTOTUNE_LOG, warmup samples, steps per sample, max samples,
// GP noise (common.h:68-73).
//
// The categoricals ride the same GP as relaxed [0,1] coordinates
// thresholded at 0.5 — the standard continuous relaxation, standing in for
// the reference's CategoricalParameter grid wrapping.
#ifndef HVDTPU_PARAMETER_MANAGER_H
#define HVDTPU_PARAMETER_MANAGER_H

#include <chrono>
#include <cstdint>
#include <cstdio>
#include <string>
#include <vector>

#include "gp.h"
#include "message.h"

namespace hvdtpu {

// One proposed/converged configuration (reference: the Params struct
// broadcast by SynchronizeParameters).
struct TunedParams {
  int64_t fusion_threshold = 0;  // 0 = unset
  double cycle_time_ms = 0.0;    // 0 = unset
  bool has_flags = false;
  bool cache_enabled = true;
  bool hierarchical_allreduce = false;
  bool hierarchical_allgather = false;
};

class ParameterManager {
 public:
  void Initialize(int64_t fusion_threshold, double cycle_time_ms,
                  bool cache_enabled, bool hierarchical_allreduce,
                  bool hierarchical_allgather, bool tune_hierarchical,
                  const std::string& log_path, int64_t warmup_samples,
                  int64_t cycles_per_sample, int64_t max_samples,
                  double gp_noise);
  ~ParameterManager();

  bool active() const { return active_ && !done_; }

  // Byte accounting, called once per cycle after ops execute.
  void RecordBytes(int64_t bytes);

  // Decision point, called from the coordinator cycle. Returns true when
  // new parameters should be broadcast this cycle.
  bool Update(const std::vector<Response>& responses, TunedParams* out);

  int64_t best_fusion_threshold() const { return best_.fusion_threshold; }
  double best_cycle_time_ms() const { return best_.cycle_time_ms; }

 private:
  // Normalized [0,1]^5 <-> (log fusion, log cycle, cache, hier_ar, hier_ag).
  std::vector<double> ToUnit(const TunedParams& p) const;
  TunedParams FromUnit(const std::vector<double>& u) const;
  void ProposeNext();

  bool active_ = false;
  bool done_ = false;
  bool tune_hierarchical_ = false;
  std::FILE* log_ = nullptr;

  int64_t warmup_samples_ = 3;
  int64_t cycles_per_sample_ = 10;
  int64_t max_samples_ = 20;
  double gp_noise_ = 0.8;

  TunedParams current_;
  TunedParams best_;
  double best_score_ = 0.0;

  int64_t bytes_accum_ = 0;
  int64_t cycles_in_window_ = 0;
  std::chrono::steady_clock::time_point window_start_;
  int64_t samples_done_ = 0;

  std::vector<std::vector<double>> xs_;
  std::vector<double> ys_;  // raw bytes/sec scores
  uint64_t rng_state_ = 0x9e3779b97f4a7c15ull;

  bool pending_broadcast_ = false;
};

}  // namespace hvdtpu

#endif  // HVDTPU_PARAMETER_MANAGER_H
