// Autotuner for fusion threshold + cycle time.
//
// Reference: horovod/common/parameter_manager.{h,cc} — the coordinator
// scores each sample window by bytes/sec, proposes the next (fusion
// threshold, cycle time) point by Bayesian optimization, and broadcasts
// tuned values to the workers inside the negotiation round
// (SynchronizeParameters, controller.cc:34-48; update loop
// operations.cc:614-621). Knobs: HOROVOD_AUTOTUNE,
// HOROVOD_AUTOTUNE_LOG, warmup samples, steps per sample, max samples,
// GP noise (common.h:68-73).
#ifndef HVDTPU_PARAMETER_MANAGER_H
#define HVDTPU_PARAMETER_MANAGER_H

#include <chrono>
#include <cstdint>
#include <cstdio>
#include <string>
#include <vector>

#include "gp.h"
#include "message.h"

namespace hvdtpu {

class ParameterManager {
 public:
  void Initialize(int64_t fusion_threshold, double cycle_time_ms,
                  const std::string& log_path, int64_t warmup_samples,
                  int64_t cycles_per_sample, int64_t max_samples,
                  double gp_noise);
  ~ParameterManager();

  bool active() const { return active_ && !done_; }

  // Byte accounting, called once per cycle after ops execute.
  void RecordBytes(int64_t bytes);

  // Decision point, called from the coordinator cycle. Returns true when
  // new parameters should be broadcast this cycle.
  bool Update(const std::vector<Response>& responses, int64_t* fusion_out,
              double* cycle_out);

  int64_t best_fusion_threshold() const { return best_fusion_; }
  double best_cycle_time_ms() const { return best_cycle_; }

 private:
  // Normalized [0,1]^2 <-> (log fusion bytes, log cycle ms).
  std::vector<double> ToUnit(int64_t fusion, double cycle) const;
  void FromUnit(const std::vector<double>& u, int64_t* fusion,
                double* cycle) const;
  void ProposeNext();

  bool active_ = false;
  bool done_ = false;
  std::FILE* log_ = nullptr;

  int64_t warmup_samples_ = 3;
  int64_t cycles_per_sample_ = 10;
  int64_t max_samples_ = 20;
  double gp_noise_ = 0.8;

  int64_t current_fusion_ = 64 << 20;
  double current_cycle_ = 1.0;
  int64_t best_fusion_ = 64 << 20;
  double best_cycle_ = 1.0;
  double best_score_ = 0.0;

  int64_t bytes_accum_ = 0;
  int64_t cycles_in_window_ = 0;
  std::chrono::steady_clock::time_point window_start_;
  int64_t samples_done_ = 0;

  std::vector<std::vector<double>> xs_;
  std::vector<double> ys_;  // raw bytes/sec scores
  uint64_t rng_state_ = 0x9e3779b97f4a7c15ull;

  bool pending_broadcast_ = false;
};

}  // namespace hvdtpu

#endif  // HVDTPU_PARAMETER_MANAGER_H
