// Control-plane star + data-plane mesh over TCP.
//
// Reference split: the controller transport (MPI gather/bcast of serialized
// lists, mpi_controller.cc:108-189, or gloo's store) vs the data plane
// (NCCL/Gloo collectives). Here both ride TCP between worker processes (one
// per TPU host): rank 0 runs the control server every cycle (gather
// RequestLists, broadcast ResponseList — the coordinator protocol of
// controller.h:63-100), and all ranks hold a full mesh of data links used by
// the ring collectives in collectives.cc.
#ifndef HVDTPU_TRANSPORT_H
#define HVDTPU_TRANSPORT_H

#include <memory>
#include <string>
#include <vector>

#include "message.h"
#include "socket.h"

namespace hvdtpu {

// Actual port the coordinator's control server bound, readable from other
// threads while Transport::Create is still blocked accepting workers. This
// is what makes elastic port allocation race-free: rank 0 listens on port 0
// (OS-assigned on ITS host), a watcher thread reads the bound port here and
// reports it to the elastic driver, and only then do peers learn where to
// connect (reference analogue: the gloo rendezvous store's host:port
// registration, gloo_context.cc:49-84).
int BoundControlPort();

// Zero the published port. Called before starting a bound-port watcher and
// on shutdown, so a previous incarnation's port can never be mistaken for
// the next world's (same-process re-coordination is the elastic norm:
// host order keeps rank 0 on a surviving host).
void ResetBoundControlPort();

class Transport {
 public:
  // rank 0 listens on `coord_port`; workers connect to
  // `coord_addr:coord_port`. Establishes the control star and the full data
  // mesh (address book exchanged through the coordinator, the same role the
  // HTTP rendezvous store plays for gloo bootstrap, gloo_context.cc:49-84).
  static std::unique_ptr<Transport> Create(int rank, int size,
                                           const std::string& coord_addr,
                                           int coord_port,
                                           double timeout_secs);

  ~Transport();

  int rank() const { return rank_; }
  int size() const { return size_; }

  // --- control plane (cycle round-trip) ---
  // Coordinator: receive one RequestList from every worker (index = rank,
  // [0] is unused). Returns false on a lost worker.
  bool GatherRequestLists(std::vector<RequestList>* out);
  // Worker: send this cycle's RequestList.
  bool SendRequestList(const RequestList& list);
  // Coordinator: broadcast the agreed ResponseList.
  bool BcastResponseList(const ResponseList& list);
  // Worker: receive it.
  bool RecvResponseList(ResponseList* out);

  // --- data plane ---
  bool SendToRank(int dst, const void* data, size_t size);
  bool RecvFromRank(int src, void* data, size_t size);
  // Simultaneous send-to-right / recv-from-left progress for ring steps
  // (two different sockets, both progressed under one poll loop).
  bool RingExchange(int right, const void* send_buf, size_t send_size,
                    int left, void* recv_buf, size_t recv_size);

 private:
  Transport(int rank, int size) : rank_(rank), size_(size) {}
  bool SetupCoordinator(int coord_port, double timeout_secs);
  bool SetupWorker(const std::string& coord_addr, int coord_port,
                   double timeout_secs);
  bool SetupDataMesh(const std::vector<std::string>& addrs,
                     const std::vector<int>& ports, double timeout_secs);

  int rank_;
  int size_;
  // Control star: coordinator holds worker sockets indexed by rank (slot 0
  // empty); workers hold a single socket to the coordinator in slot 0.
  std::vector<TcpSocket> control_;
  TcpServer control_server_;
  // Full mesh of data links, indexed by peer rank (self slot empty).
  std::vector<TcpSocket> data_;
  TcpServer data_server_;
};

}  // namespace hvdtpu

#endif  // HVDTPU_TRANSPORT_H
