// Leveled logger with rank prefix (reference: common/logging.{h,cc} — same
// LOG(level) macro shape, HOROVOD_LOG_LEVEL + HOROVOD_LOG_HIDE_TIME knobs).
#ifndef HVDTPU_LOGGING_H
#define HVDTPU_LOGGING_H

#include <sstream>
#include <string>

namespace hvdtpu {

enum class LogLevel : int {
  TRACE = 0,
  DEBUG = 1,
  INFO = 2,
  WARNING = 3,
  ERROR = 4,
  FATAL = 5,
};

LogLevel MinLogLevelFromEnv();
void SetLogRank(int rank);

class LogMessage {
 public:
  LogMessage(const char* file, int line, LogLevel level);
  ~LogMessage();
  std::ostringstream& stream() { return stream_; }

 private:
  std::ostringstream stream_;
  const char* file_;
  int line_;
  LogLevel level_;
};

bool LogLevelEnabled(LogLevel level);

#define HVDTPU_LOG(level)                                       \
  if (::hvdtpu::LogLevelEnabled(::hvdtpu::LogLevel::level))     \
  ::hvdtpu::LogMessage(__FILE__, __LINE__,                      \
                       ::hvdtpu::LogLevel::level)               \
      .stream()

}  // namespace hvdtpu

#endif  // HVDTPU_LOGGING_H
