// Pending-tensor table + message queue.
//
// Reference: horovod/common/tensor_queue.{h,cc} — mutex-guarded
// name→TensorTableEntry map plus a queue of negotiation messages; rejects
// duplicate names (tensor_queue.h:28-69, common.h:163).
#ifndef HVDTPU_TENSOR_QUEUE_H
#define HVDTPU_TENSOR_QUEUE_H

#include <condition_variable>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "message.h"

namespace hvdtpu {

// One in-flight collective (reference: TensorTableEntry, common.h:191-258).
// `data` points at caller-owned memory that must stay alive until the entry
// completes; outputs that can't be written in place (allgather/alltoall)
// land in `output`.
struct TensorTableEntry {
  std::string name;
  Request::Type type = Request::ALLREDUCE;
  DataType dtype = DataType::HVDTPU_FLOAT32;
  void* data = nullptr;            // in/out for allreduce & broadcast
  int64_t count = 0;               // element count of `data`
  std::vector<int64_t> shape;
  int32_t root_rank = 0;
  double prescale_factor = 1.0;
  double postscale_factor = 1.0;
  ReduceOp reduce_op = ReduceOp::SUM;
  std::vector<int64_t> splits;     // alltoall send splits (rows per rank)

  // Results.
  std::vector<char> output;        // allgather / alltoall received bytes
  std::vector<int64_t> recv_splits;  // alltoall rows received per rank
  int32_t join_result = -1;        // JOIN: last rank to join

  // Completion signalling (reference uses a callback into the framework,
  // common.h:231; the ctypes binding prefers wait/poll).
  std::mutex mu;
  std::condition_variable cv;
  bool done = false;
  Status status;

  void MarkDone(const Status& s) {
    std::lock_guard<std::mutex> g(mu);
    status = s;
    done = true;
    cv.notify_all();
  }
  Status Wait() {
    std::unique_lock<std::mutex> g(mu);
    cv.wait(g, [this] { return done; });
    return status;
  }
  bool Done() {
    std::lock_guard<std::mutex> g(mu);
    return done;
  }
};

using EntryPtr = std::shared_ptr<TensorTableEntry>;

class TensorQueue {
 public:
  // Queue an entry + its negotiation request. Fails with
  // DUPLICATE_NAME_ERROR if `name` is already in flight
  // (reference: tensor_queue.cc AddToTensorQueue).
  Status AddToTensorQueue(EntryPtr entry, Request message);

  // Drain all pending negotiation messages (reference:
  // PopMessagesFromQueue, controller.cc:79).
  std::vector<Request> PopMessages();

  // Look up + remove entries for a response's tensors (reference:
  // GetTensorEntriesFromResponse). Aligned with `names`: slot i is nullptr
  // when this rank holds no entry for names[i] — the joined-rank case,
  // where the executor substitutes an identity contribution (the
  // reference's zero-tensor substitution).
  std::vector<EntryPtr> GetAndRemoveEntries(
      const std::vector<std::string>& names);

  EntryPtr Get(const std::string& name);

  // Fail every pending entry (shutdown / elastic reset; reference:
  // tensor_queue.cc ClearQueue-style teardown).
  void AbortAll(const Status& reason);

  size_t size();

 private:
  std::mutex mu_;
  std::unordered_map<std::string, EntryPtr> table_;
  std::deque<Request> messages_;
};

}  // namespace hvdtpu

#endif  // HVDTPU_TENSOR_QUEUE_H
