#include "stall_inspector.h"

#include <sstream>

#include "logging.h"

namespace hvdtpu {

void StallInspector::RecordUncachedTensorRank(const std::string& tensor_name,
                                              int rank) {
  if (!enabled_) return;
  auto it = pending_.find(tensor_name);
  if (it == pending_.end()) {
    PendingTensor p;
    p.first_seen = std::chrono::steady_clock::now();
    p.ready_ranks.insert(rank);
    pending_.emplace(tensor_name, std::move(p));
  } else {
    it->second.ready_ranks.insert(rank);
  }
}

void StallInspector::RemoveUncachedTensor(const std::string& tensor_name) {
  pending_.erase(tensor_name);
}

bool StallInspector::CheckForStalledTensors() {
  if (!enabled_) return false;
  auto now = std::chrono::steady_clock::now();
  bool should_abort = false;
  for (auto& kv : pending_) {
    auto& p = kv.second;
    double waited =
        std::chrono::duration<double>(now - p.first_seen).count();
    if (waited >= warning_secs_ && !p.warned) {
      // Same diagnostic the reference emits: which ranks are ready, which
      // are missing (stall_inspector.cc warning text structure).
      std::ostringstream ready, missing;
      for (int r : p.ready_ranks) ready << r << " ";
      for (int r = 0; r < world_size_; ++r) {
        if (p.ready_ranks.find(r) == p.ready_ranks.end()) missing << r << " ";
      }
      HVDTPU_LOG(WARNING)
          << "One or more tensors were submitted to be reduced, gathered "
          << "or broadcasted by subset of ranks and are waiting for "
          << "remainder of ranks for more than " << warning_secs_
          << " seconds. Stalled tensor: " << kv.first
          << " [ready ranks: " << ready.str()
          << "| missing ranks: " << missing.str() << "]";
      p.warned = true;
    }
    if (shutdown_secs_ > 0 && waited >= shutdown_secs_) {
      HVDTPU_LOG(ERROR) << "Tensor " << kv.first << " stalled for " << waited
                        << "s, exceeding the shutdown deadline of "
                        << shutdown_secs_ << "s; aborting.";
      should_abort = true;
    }
  }
  return should_abort;
}

}  // namespace hvdtpu
