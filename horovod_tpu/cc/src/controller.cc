#include "controller.h"

#include <algorithm>
#include <deque>
#include <numeric>
#include <sstream>

#include "logging.h"

namespace hvdtpu {

namespace {

int64_t ElementCount(const std::vector<int64_t>& shape) {
  int64_t n = 1;
  for (auto d : shape) n *= d;
  return n;
}

// Canonical shape used for cache validation. Allreduce is shape-agnostic on
// the wire (the fused buffer is flat), so its cache key is the flattened
// count — this also lets fused responses be split back into cacheable
// singles without carrying per-tensor shapes (the reference's
// ResponseCache::put does the same split using local entry params).
std::vector<int64_t> CacheKeyShape(const Request& req) {
  if (req.request_type == Request::ALLREDUCE ||
      req.request_type == Request::ADASUM) {
    return {ElementCount(req.tensor_shape)};
  }
  return req.tensor_shape;
}

Request CanonicalizedForCache(const Request& req) {
  Request c = req;
  c.tensor_shape = CacheKeyShape(req);
  return c;
}

bool IsDataResponse(Response::Type t) {
  return t == Response::ALLREDUCE || t == Response::ADASUM ||
         t == Response::ALLGATHER || t == Response::BROADCAST ||
         t == Response::ALLTOALL;
}

}  // namespace

bool Controller::IncrementTensorCount(const Request& req) {
  auto& p = message_table_[req.tensor_name];
  if (p.ready_ranks.insert(req.request_rank).second) {
    p.requests.push_back(req);
  }
  if (timeline_ != nullptr) {
    if (p.ready_ranks.size() == 1) {
      timeline_->NegotiateStart(req.tensor_name,
                                Request::TypeName(req.request_type));
    }
    timeline_->NegotiateRankReady(req.tensor_name, req.request_rank);
  }
  if (stall_ != nullptr) {
    stall_->RecordUncachedTensorRank(req.tensor_name, req.request_rank);
  }
  size_t required = static_cast<size_t>(size_) - joined_ranks_.size();
  return p.ready_ranks.size() >= required;
}

Response Controller::ConstructResponse(const std::string& name) {
  // Cross-rank consistency validation — the "distributed sanitizer"
  // (reference: ConstructResponse, controller.cc:380-657). Must run before
  // any data hits the wire or compiled code, so mismatches surface as clear
  // errors instead of corrupt reductions.
  PendingTensor p = std::move(message_table_[name]);
  message_table_.erase(name);
  if (stall_ != nullptr) stall_->RemoveUncachedTensor(name);
  if (timeline_ != nullptr) timeline_->NegotiateEnd(name);

  const Request& first = p.requests[0];
  Response resp;
  resp.tensor_names = {name};
  resp.tensor_type = first.tensor_type;
  resp.prescale_factor = first.prescale_factor;
  resp.postscale_factor = first.postscale_factor;
  resp.reduce_op = first.reduce_op;
  resp.root_rank = first.root_rank;
  auto fail = [&](const std::string& msg) {
    Response e;
    e.response_type = Response::ERROR;
    e.tensor_names = {name};
    e.error_message = msg;
    return e;
  };

  std::ostringstream err;
  for (size_t i = 1; i < p.requests.size(); ++i) {
    const Request& r = p.requests[i];
    if (r.request_type != first.request_type) {
      err << "Mismatched collective operations: rank " << first.request_rank
          << " requested " << Request::TypeName(first.request_type)
          << " but rank " << r.request_rank << " requested "
          << Request::TypeName(r.request_type) << ".";
      return fail(err.str());
    }
    if (r.tensor_type != first.tensor_type) {
      err << "Mismatched data types: rank " << first.request_rank << " has "
          << DataTypeName(first.tensor_type) << " but rank " << r.request_rank
          << " has " << DataTypeName(r.tensor_type) << ".";
      return fail(err.str());
    }
    if (r.prescale_factor != first.prescale_factor ||
        r.postscale_factor != first.postscale_factor) {
      return fail("Mismatched prescale/postscale factors across ranks.");
    }
  }

  switch (first.request_type) {
    case Request::ALLREDUCE:
    case Request::ADASUM: {
      for (size_t i = 1; i < p.requests.size(); ++i) {
        const Request& r = p.requests[i];
        if (r.tensor_shape != first.tensor_shape) {
          err << "Mismatched allreduce tensor shapes: rank "
              << first.request_rank << " and rank " << r.request_rank
              << " disagree for tensor " << name << ".";
          return fail(err.str());
        }
        if (r.reduce_op != first.reduce_op) {
          return fail("Mismatched reduce ops across ranks for tensor " +
                      name + ".");
        }
      }
      resp.response_type = first.request_type == Request::ADASUM
                               ? Response::ADASUM
                               : Response::ALLREDUCE;
      resp.tensor_sizes = {ElementCount(first.tensor_shape)};
      resp.cache_shape = {ElementCount(first.tensor_shape)};
      break;
    }
    case Request::BROADCAST: {
      for (size_t i = 1; i < p.requests.size(); ++i) {
        const Request& r = p.requests[i];
        if (r.tensor_shape != first.tensor_shape) {
          return fail("Mismatched broadcast tensor shapes across ranks for "
                      "tensor " + name + ".");
        }
        if (r.root_rank != first.root_rank) {
          err << "Mismatched broadcast root ranks: rank "
              << first.request_rank << " specified root "
              << first.root_rank << " but rank " << r.request_rank
              << " specified root " << r.root_rank << ".";
          return fail(err.str());
        }
      }
      if (joined_ranks_.count(first.root_rank) != 0) {
        return fail("Broadcast root rank " +
                    std::to_string(first.root_rank) + " has joined.");
      }
      resp.response_type = Response::BROADCAST;
      resp.tensor_sizes = {ElementCount(first.tensor_shape)};
      resp.cache_shape = first.tensor_shape;
      break;
    }
    case Request::ALLGATHER: {
      // First dims may differ; the rest must match
      // (reference: controller.cc allgather leg).
      for (size_t i = 1; i < p.requests.size(); ++i) {
        const Request& r = p.requests[i];
        if (r.tensor_shape.size() != first.tensor_shape.size()) {
          return fail("Mismatched allgather tensor ranks for tensor " +
                      name + ".");
        }
        for (size_t d = 1; d < first.tensor_shape.size(); ++d) {
          if (r.tensor_shape[d] != first.tensor_shape[d]) {
            return fail("Mismatched allgather non-first dimensions for "
                        "tensor " + name + ".");
          }
        }
      }
      if (first.tensor_shape.empty()) {
        return fail("Allgather requires at least a 1-D tensor.");
      }
      resp.response_type = Response::ALLGATHER;
      resp.tensor_sizes.assign(static_cast<size_t>(size_), 0);
      for (const Request& r : p.requests) {
        resp.tensor_sizes[r.request_rank] = r.tensor_shape[0];
      }
      resp.cache_shape = first.tensor_shape;  // representative row shape
      break;
    }
    case Request::ALLTOALL: {
      for (size_t i = 1; i < p.requests.size(); ++i) {
        const Request& r = p.requests[i];
        if (r.tensor_shape.size() != first.tensor_shape.size()) {
          return fail("Mismatched alltoall tensor ranks for tensor " + name +
                      ".");
        }
        for (size_t d = 1; d < first.tensor_shape.size(); ++d) {
          if (r.tensor_shape[d] != first.tensor_shape[d]) {
            return fail("Mismatched alltoall non-first dimensions for "
                        "tensor " + name + ".");
          }
        }
      }
      if (first.tensor_shape.empty()) {
        return fail("Alltoall requires at least a 1-D tensor.");
      }
      // Build the size×size split matrix [src*size+dst] (the reference
      // exchanges recv splits via AlltoallGetRecvSplits,
      // controller.h:148-151; we centralize it in the response).
      resp.response_type = Response::ALLTOALL;
      resp.tensor_sizes.assign(static_cast<size_t>(size_) * size_, 0);
      for (const Request& r : p.requests) {
        std::vector<int64_t> splits = r.splits;
        if (splits.empty()) {
          if (r.tensor_shape[0] % size_ != 0) {
            return fail("Alltoall first dimension (" +
                        std::to_string(r.tensor_shape[0]) +
                        ") is not divisible by the world size and no splits "
                        "were provided for tensor " + name + ".");
          }
          splits.assign(static_cast<size_t>(size_),
                        r.tensor_shape[0] / size_);
        }
        if (static_cast<int>(splits.size()) != size_) {
          return fail("Alltoall splits length must equal the world size for "
                      "tensor " + name + ".");
        }
        int64_t total = std::accumulate(splits.begin(), splits.end(),
                                        int64_t{0});
        if (total != r.tensor_shape[0]) {
          return fail("Alltoall splits sum (" + std::to_string(total) +
                      ") does not match the first dimension (" +
                      std::to_string(r.tensor_shape[0]) + ") on rank " +
                      std::to_string(r.request_rank) + ".");
        }
        for (int dst = 0; dst < size_; ++dst) {
          resp.tensor_sizes[static_cast<size_t>(r.request_rank) * size_ +
                            dst] = splits[dst];
        }
      }
      resp.cache_shape = first.tensor_shape;  // representative row shape
      break;
    }
    case Request::BARRIER: {
      resp.response_type = Response::BARRIER;
      break;
    }
    case Request::JOIN:
      break;  // handled in CoordinatorCycle
  }
  return resp;
}

void Controller::CollectNewlyCompleteTensors(std::vector<Response>* out) {
  size_t required = static_cast<size_t>(size_) - joined_ranks_.size();
  std::vector<std::string> fire;
  for (auto& kv : message_table_) {
    if (kv.second.ready_ranks.size() >= required) fire.push_back(kv.first);
  }
  std::sort(fire.begin(), fire.end());  // deterministic order
  for (auto& name : fire) out->push_back(ConstructResponse(name));
}

std::vector<Response> Controller::FuseResponses(
    std::vector<Response> responses, int64_t threshold_bytes) {
  // Greedy packing with look-ahead over the whole list (reference:
  // FuseResponses, controller.cc:686-809 — scans past non-matching
  // responses so mixed dtypes don't break fusion runs).
  std::deque<Response> queue(std::make_move_iterator(responses.begin()),
                             std::make_move_iterator(responses.end()));
  std::vector<Response> out;
  while (!queue.empty()) {
    Response r = std::move(queue.front());
    queue.pop_front();
    if (r.response_type == Response::ALLREDUCE ||
        r.response_type == Response::ADASUM) {
      size_t es = DataTypeSize(r.tensor_type);
      int64_t bytes = 0;
      for (auto c : r.tensor_sizes) bytes += c * static_cast<int64_t>(es);
      for (auto it = queue.begin();
           it != queue.end() && bytes < threshold_bytes;) {
        const Response& s = *it;
        if (s.response_type == r.response_type &&
            s.tensor_type == r.tensor_type &&
            s.reduce_op == r.reduce_op &&
            s.prescale_factor == r.prescale_factor &&
            s.postscale_factor == r.postscale_factor) {
          int64_t sbytes = 0;
          for (auto c : s.tensor_sizes)
            sbytes += c * static_cast<int64_t>(es);
          if (bytes + sbytes <= threshold_bytes) {
            r.tensor_names.insert(r.tensor_names.end(),
                                  s.tensor_names.begin(),
                                  s.tensor_names.end());
            r.tensor_sizes.insert(r.tensor_sizes.end(),
                                  s.tensor_sizes.begin(),
                                  s.tensor_sizes.end());
            bytes += sbytes;
            it = queue.erase(it);
            continue;
          }
        }
        ++it;
      }
      if (r.tensor_names.size() > 1) r.cache_shape.clear();
    }
    out.push_back(std::move(r));
  }
  return out;
}

ResponseList Controller::CoordinatorCycle(std::vector<RequestList> rank_lists,
                                          int64_t fusion_threshold_bytes) {
  ResponseList final_list;

  // Shutdown latch: any rank asking out takes the whole job down together
  // (reference: RequestList shutdown bit).
  for (const auto& l : rank_lists) {
    if (l.shutdown) shutdown_latch_ = true;
  }

  // --- cache coordination (reference: controller.cc:75-164) ---
  // Agreed hits = bitwise AND over all ranks (joined ranks vote "all yes");
  // invalidations = bitwise OR.
  std::vector<int64_t> agreed;
  bool first_vote = true;
  std::vector<int64_t> invalid_words;
  for (int r = 0; r < size_; ++r) {
    const auto& l = rank_lists[r];
    for (size_t w = 0; w < l.invalid_bits.size(); ++w) {
      if (w >= invalid_words.size()) invalid_words.resize(w + 1, 0);
      invalid_words[w] |= l.invalid_bits[w];
    }
    if (l.joined) continue;  // all-ones vote: does not constrain the AND
    if (first_vote) {
      agreed = l.cache_bits;
      first_vote = false;
    } else {
      agreed = AndWords(agreed, l.cache_bits);
    }
  }
  // Remove invalidated bits from the agreed set.
  for (size_t w = 0; w < agreed.size() && w < invalid_words.size(); ++w) {
    agreed[w] &= ~invalid_words[w];
  }
  final_list.invalid_bits = invalid_words;

  std::vector<Response> responses;
  // Cached responses fire first, ordered by bit index — identical on every
  // rank by construction.
  for (uint32_t bit : UnpackBits(agreed)) {
    if (!cache_->has_bit(bit)) continue;
    cache_->touch(bit);
    responses.push_back(cache_->get_response(bit));
  }

  // --- negotiation of uncached tensors ---
  bool joined_grew = false;
  for (int r = 0; r < size_; ++r) {
    for (const Request& req : rank_lists[r].requests) {
      if (req.request_type == Request::JOIN) {
        if (joined_ranks_.insert(req.request_rank).second) {
          last_joined_rank_ = req.request_rank;
          joined_grew = true;
        }
        continue;
      }
      if (IncrementTensorCount(req)) {
        responses.push_back(ConstructResponse(req.tensor_name));
      }
    }
  }
  // Ranks joining lowers the participation requirement; re-scan
  // (reference: join handling in ComputeResponseList).
  if (joined_grew) CollectNewlyCompleteTensors(&responses);

  if (static_cast<int>(joined_ranks_.size()) == size_) {
    Response j;
    j.response_type = Response::JOIN;
    j.last_joined_rank = last_joined_rank_;
    responses.push_back(j);
    joined_ranks_.clear();
    last_joined_rank_ = -1;
  }

  // Stall detection on whatever is still pending.
  if (stall_ != nullptr && stall_->CheckForStalledTensors()) {
    shutdown_latch_ = true;
  }

  final_list.responses =
      FuseResponses(std::move(responses), fusion_threshold_bytes);
  final_list.shutdown = shutdown_latch_;

  if (autotune_hook) {
    TunedParamsWire tuned;
    if (autotune_hook(final_list.responses, &tuned)) {
      final_list.has_tuned_params = true;
      final_list.tuned_fusion_threshold = tuned.fusion_threshold;
      final_list.tuned_cycle_time_ms = tuned.cycle_time_ms;
      final_list.tuned_flags =
          tuned.has_flags ? static_cast<uint8_t>(tuned.flags | 0x80) : 0;
    }
  }
  return final_list;
}

void Controller::set_cache_enabled(bool enabled) {
  if (enabled == cache_enabled_) return;
  cache_enabled_ = enabled;
  HVDTPU_LOG(DEBUG) << "cache_enabled -> " << enabled;
  if (!enabled) {
    // Requests parked waiting for their cache bit to fire globally would
    // stall forever once no rank votes bits: push them back into the
    // negotiated (uncached) stream next cycle.
    for (auto& kv : pending_cached_) {
      resend_uncached_.push_back(std::move(kv.second));
    }
    pending_cached_.clear();
    my_invalid_bits_.clear();
  } else {
    // Drop stale entries on re-enable. The toggle is cycle-synchronous but
    // tensor *submission* is not: with stale bits, a rank popping a tensor
    // just after the toggle would classify it HIT while a rank that popped
    // it just before (cache off) negotiated it uncached — mixed
    // classifications for one tensor deadlock both sides. An empty cache
    // makes the first post-toggle classification MISS everywhere.
    cache_->clear();
  }
}

void Controller::ApplyResponseList(const ResponseList& final_list,
                                   CycleResult* out) {
  // 1. Agreed evictions — every rank drops the same bits so numbering stays
  // aligned. Pending hit requests whose entry got evicted are resubmitted
  // as uncached next cycle.
  for (uint32_t bit : UnpackBits(final_list.invalid_bits)) {
    if (!cache_->has_bit(bit)) continue;
    Response victim = cache_->get_response(bit);
    const std::string& name = victim.tensor_names[0];
    auto it = pending_cached_.find(name);
    if (it != pending_cached_.end()) {
      resend_uncached_.push_back(it->second);
      pending_cached_.erase(it);
    }
    cache_->erase_response(bit);
  }

  // 2. Cache insertions: split fused responses into per-tensor singles on
  // every rank identically (reference: ResponseCache::put on the received
  // list splits fused responses the same way).
  for (const Response& resp : final_list.responses) {
    if (resp.response_type == Response::JOIN) {
      self_joined_ = false;
      continue;
    }
    if (!IsDataResponse(resp.response_type)) continue;
    if (!cache_enabled_) continue;  // tuned off: don't fill
    if (resp.tensor_names.size() == 1) {
      if (!resp.cache_shape.empty()) cache_->put(resp);
    } else {
      for (size_t i = 0; i < resp.tensor_names.size(); ++i) {
        Response single = resp;
        single.tensor_names = {resp.tensor_names[i]};
        single.tensor_sizes = {resp.tensor_sizes[i]};
        single.cache_shape = {resp.tensor_sizes[i]};
        cache_->put(single);
      }
    }
    // Fired cached-hit requests are no longer pending.
    for (const auto& name : resp.tensor_names) pending_cached_.erase(name);
  }

  if (final_list.has_tuned_params) {
    out->tuned_fusion_threshold = final_list.tuned_fusion_threshold;
    out->tuned_cycle_time_ms = final_list.tuned_cycle_time_ms;
    if (final_list.tuned_flags & 0x80) {
      out->has_tuned_flags = true;
      out->tuned_flags = final_list.tuned_flags & 0x7f;
    }
  }
  out->responses = final_list.responses;
  out->shutdown = final_list.shutdown;
}

Controller::CycleResult Controller::RunCycle(bool request_shutdown,
                                             int64_t fusion_threshold_bytes) {
  CycleResult result;
  if (timeline_ != nullptr) timeline_->MarkCycleStart();

  // Classify newly ready tensors: cache hit / invalid / uncached.
  RequestList mine;
  mine.shutdown = request_shutdown;
  mine.joined = self_joined_;
  mine.requests = std::move(resend_uncached_);
  resend_uncached_.clear();
  for (Request& req : tensor_queue_->PopMessages()) {
    if (req.request_type == Request::JOIN) {
      self_joined_ = true;
      mine.joined = true;
      mine.requests.push_back(std::move(req));
      continue;
    }
    if (!cache_enabled_) {
      // Cache tuned off: everything negotiates as a miss; no bits are
      // consulted, voted, or filled, so the distributed bit tables stay
      // frozen (and consistent) until the cache is re-enabled.
      mine.requests.push_back(std::move(req));
      continue;
    }
    Request canon = CanonicalizedForCache(req);
    switch (cache_->cached(canon)) {
      case ResponseCache::CacheState::HIT:
        pending_cached_.emplace(req.tensor_name, std::move(req));
        break;
      case ResponseCache::CacheState::INVALID:
        my_invalid_bits_.push_back(cache_->peek_cache_bit(canon));
        // Held locally; resent once the eviction round-trips.
        pending_cached_.emplace(req.tensor_name, std::move(req));
        break;
      case ResponseCache::CacheState::MISS:
        mine.requests.push_back(std::move(req));
        break;
    }
  }
  // Vote all currently pending hits (re-voted every cycle until they fire).
  {
    std::vector<uint32_t> bits;
    for (const auto& kv : pending_cached_) {
      Request canon = CanonicalizedForCache(kv.second);
      if (cache_->cached(canon) == ResponseCache::CacheState::HIT) {
        bits.push_back(cache_->peek_cache_bit(canon));
      }
    }
    mine.cache_bits = PackBits(bits, cache_->num_active_bits());
  }
  mine.invalid_bits = PackBits(my_invalid_bits_, cache_->num_active_bits());
  my_invalid_bits_.clear();

  ResponseList final_list;
  if (size_ == 1) {
    final_list = CoordinatorCycle({std::move(mine)}, fusion_threshold_bytes);
  } else if (is_coordinator()) {
    std::vector<RequestList> rank_lists;
    if (!transport_->GatherRequestLists(&rank_lists)) {
      result.transport_failure = true;
      return result;
    }
    rank_lists[0] = std::move(mine);
    final_list = CoordinatorCycle(std::move(rank_lists),
                                  fusion_threshold_bytes);
    if (!transport_->BcastResponseList(final_list)) {
      result.transport_failure = true;
      return result;
    }
  } else {
    if (!transport_->SendRequestList(mine) ||
        !transport_->RecvResponseList(&final_list)) {
      result.transport_failure = true;
      return result;
    }
  }

  ApplyResponseList(final_list, &result);
  return result;
}

}  // namespace hvdtpu
