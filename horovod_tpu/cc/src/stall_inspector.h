// Coordinator-side stall detection.
//
// Reference: horovod/common/stall_inspector.{h,cc} (stall_inspector.h:36-66,
// wired into the negotiation at controller.cc:119-131): warns when a tensor
// has been submitted by some-but-not-all ranks for longer than the warning
// interval, listing ready vs missing ranks; optionally aborts the job after
// a hard deadline.
#ifndef HVDTPU_STALL_INSPECTOR_H
#define HVDTPU_STALL_INSPECTOR_H

#include <chrono>
#include <cstdint>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

namespace hvdtpu {

class StallInspector {
 public:
  void Configure(bool enabled, double warning_secs, double shutdown_secs,
                 int world_size) {
    enabled_ = enabled;
    warning_secs_ = warning_secs;
    shutdown_secs_ = shutdown_secs;
    world_size_ = world_size;
  }

  // Record that `rank` submitted `tensor_name` this cycle.
  void RecordUncachedTensorRank(const std::string& tensor_name, int rank);

  // Tensor completed: forget it.
  void RemoveUncachedTensor(const std::string& tensor_name);

  // Scan for stalls; logs warnings. Returns true if the hard shutdown
  // deadline has passed for some tensor (caller should abort, as the
  // reference does when stall_shutdown_time elapses).
  bool CheckForStalledTensors();

  bool enabled() const { return enabled_; }

 private:
  struct PendingTensor {
    std::chrono::steady_clock::time_point first_seen;
    std::set<int> ready_ranks;
    bool warned = false;
  };

  bool enabled_ = true;
  double warning_secs_ = 60.0;
  double shutdown_secs_ = 0.0;  // 0 = never hard-abort
  int world_size_ = 1;
  std::unordered_map<std::string, PendingTensor> pending_;
};

}  // namespace hvdtpu

#endif  // HVDTPU_STALL_INSPECTOR_H
