// Minimal length-prefixed little-endian serializer.
//
// The reference serializes Request/Response lists with FlatBuffers
// (wire/message.fbs, message.cc:>serialize). Both ends of our wire are this
// library, so a compact hand-rolled format avoids the vendored dependency
// while keeping the same message semantics.
#ifndef HVDTPU_WIRE_H
#define HVDTPU_WIRE_H

#include <cstdint>
#include <cstring>
#include <stdexcept>
#include <string>
#include <vector>

namespace hvdtpu {

class WireWriter {
 public:
  void u8(uint8_t v) { buf_.push_back(static_cast<char>(v)); }
  void i32(int32_t v) { raw(&v, 4); }
  void i64(int64_t v) { raw(&v, 8); }
  void f64(double v) { raw(&v, 8); }
  void str(const std::string& s) {
    i32(static_cast<int32_t>(s.size()));
    buf_.insert(buf_.end(), s.begin(), s.end());
  }
  void i64s(const std::vector<int64_t>& v) {
    i32(static_cast<int32_t>(v.size()));
    for (auto x : v) i64(x);
  }
  void bytes(const std::vector<char>& v) {
    i32(static_cast<int32_t>(v.size()));
    buf_.insert(buf_.end(), v.begin(), v.end());
  }
  const std::vector<char>& data() const { return buf_; }
  std::vector<char> take() { return std::move(buf_); }

 private:
  void raw(const void* p, size_t n) {
    const char* c = static_cast<const char*>(p);
    buf_.insert(buf_.end(), c, c + n);
  }
  std::vector<char> buf_;
};

class WireReader {
 public:
  WireReader(const char* data, size_t size) : p_(data), end_(data + size) {}
  explicit WireReader(const std::vector<char>& v)
      : WireReader(v.data(), v.size()) {}
  uint8_t u8() { return static_cast<uint8_t>(*take(1)); }
  int32_t i32() { int32_t v; std::memcpy(&v, take(4), 4); return v; }
  int64_t i64() { int64_t v; std::memcpy(&v, take(8), 8); return v; }
  double f64() { double v; std::memcpy(&v, take(8), 8); return v; }
  std::string str() {
    int32_t n = i32();
    return std::string(take(static_cast<size_t>(n)), static_cast<size_t>(n));
  }
  std::vector<int64_t> i64s() {
    int32_t n = i32();
    std::vector<int64_t> v(static_cast<size_t>(n));
    for (auto& x : v) x = i64();
    return v;
  }
  std::vector<char> bytes() {
    int32_t n = i32();
    const char* p = take(static_cast<size_t>(n));
    return std::vector<char>(p, p + n);
  }
  bool done() const { return p_ == end_; }

 private:
  const char* take(size_t n) {
    if (p_ + n > end_) throw std::runtime_error("wire: truncated message");
    const char* r = p_;
    p_ += n;
    return r;
  }
  const char* p_;
  const char* end_;
};

}  // namespace hvdtpu

#endif  // HVDTPU_WIRE_H
