// Global state + background loop + C ABI implementation.
//
// Reference structure: operations.cc — InitializeHorovodOnce spawns the
// background thread (628-674); BackgroundThreadLoop reads env knobs and
// builds contexts (354-569); RunLoopOnce paces cycles and executes
// responses (571-624); Enqueue* push TensorTableEntries (893-1120); the C
// ABI exposes init/rank/size/... (685-889).
#include "operations.h"

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <cstring>
#include <limits>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>

#include "adasum.h"
#include "collectives.h"
#include "common.h"
#include "controller.h"
#include "fusion_buffer.h"
#include "logging.h"
#include "message.h"
#include "parameter_manager.h"
#include "response_cache.h"
#include "socket.h"
#include "stall_inspector.h"
#include "tensor_queue.h"
#include "timeline.h"
#include "transport.h"

namespace hvdtpu {
namespace {

struct Global {
  int rank = 0, size = 1, local_rank = 0, local_size = 1;
  int cross_rank = 0, cross_size = 1;

  TensorQueue tensor_queue;
  ResponseCache response_cache;
  StallInspector stall_inspector;
  Timeline timeline;
  FusionBufferManager fusion_manager;
  ParameterManager parameter_manager;
  std::unique_ptr<Transport> transport;
  std::unique_ptr<Controller> controller;

  std::atomic<int64_t> fusion_threshold{64 * 1024 * 1024};
  std::atomic<int64_t> cycle_time_us{1000};
  // Hierarchical decomposition knobs (reference: operations.cc:463-487);
  // atomics so the autotuner can flip them between cycles.
  std::atomic<bool> hierarchical_allreduce{false};
  std::atomic<bool> hierarchical_allgather{false};
  std::atomic<bool> shutdown_requested{false};
  std::atomic<bool> loop_running{false};

  std::thread background;

  std::mutex handle_mu;
  std::unordered_map<int, EntryPtr> handles;
  int next_handle = 0;

  std::mutex join_mu;
  EntryPtr current_join;

  std::atomic<int> op_counter{0};       // join auto-names (rank-local)
  std::atomic<int> barrier_counter{0};  // barrier sequence — must align
                                        // across ranks, so joins (rank-local
                                        // events) get their own counter
};

std::mutex g_mu;
std::unique_ptr<Global> g;

std::mutex g_err_mu;
std::string g_last_error;

void SetLastError(const std::string& msg) {
  std::lock_guard<std::mutex> l(g_err_mu);
  g_last_error = msg;
}

int64_t ShapeCount(const std::vector<int64_t>& shape) {
  int64_t n = 1;
  for (auto d : shape) n *= d;
  return n;
}

int64_t RowElems(const std::vector<int64_t>& shape) {
  int64_t n = 1;
  for (size_t i = 1; i < shape.size(); ++i) n *= shape[i];
  return n;
}

// Identity element per reduce op for joined ranks' zero-substitute
// contribution (reference substitutes zero tensors,
// tensor_queue.cc GetTensorEntriesFromResponse; zeros are only the identity
// for SUM, so we use the true identity per op).
void FillIdentity(void* buf, int64_t count, DataType dt, ReduceOp op) {
  size_t bytes = static_cast<size_t>(count) * DataTypeSize(dt);
  if (op == ReduceOp::SUM || op == ReduceOp::ADASUM) {
    std::memset(buf, 0, bytes);
    return;
  }
  auto fill = [&](auto value, auto* p) {
    for (int64_t i = 0; i < count; ++i) p[i] = value;
  };
  switch (dt) {
    case DataType::HVDTPU_UINT8:
    case DataType::HVDTPU_BOOL: {
      uint8_t* p = static_cast<uint8_t*>(buf);
      fill(op == ReduceOp::MIN ? uint8_t{255}
           : op == ReduceOp::MAX ? uint8_t{0} : uint8_t{1}, p);
      break;
    }
    case DataType::HVDTPU_INT8: {
      int8_t* p = static_cast<int8_t*>(buf);
      fill(op == ReduceOp::MIN ? int8_t{127}
           : op == ReduceOp::MAX ? int8_t{-128} : int8_t{1}, p);
      break;
    }
    case DataType::HVDTPU_INT32: {
      int32_t* p = static_cast<int32_t*>(buf);
      fill(op == ReduceOp::MIN ? std::numeric_limits<int32_t>::max()
           : op == ReduceOp::MAX ? std::numeric_limits<int32_t>::min()
                                 : int32_t{1}, p);
      break;
    }
    case DataType::HVDTPU_INT64: {
      int64_t* p = static_cast<int64_t*>(buf);
      fill(op == ReduceOp::MIN ? std::numeric_limits<int64_t>::max()
           : op == ReduceOp::MAX ? std::numeric_limits<int64_t>::min()
                                 : int64_t{1}, p);
      break;
    }
    case DataType::HVDTPU_FLOAT32: {
      float* p = static_cast<float*>(buf);
      fill(op == ReduceOp::MIN ? std::numeric_limits<float>::infinity()
           : op == ReduceOp::MAX ? -std::numeric_limits<float>::infinity()
                                 : 1.0f, p);
      break;
    }
    case DataType::HVDTPU_FLOAT64: {
      double* p = static_cast<double*>(buf);
      fill(op == ReduceOp::MIN ? std::numeric_limits<double>::infinity()
           : op == ReduceOp::MAX ? -std::numeric_limits<double>::infinity()
                                 : 1.0, p);
      break;
    }
    case DataType::HVDTPU_FLOAT16:
    case DataType::HVDTPU_BFLOAT16: {
      uint16_t* p = static_cast<uint16_t*>(buf);
      float v = op == ReduceOp::MIN ? std::numeric_limits<float>::infinity()
                : op == ReduceOp::MAX
                    ? -std::numeric_limits<float>::infinity()
                    : 1.0f;
      uint16_t w = dt == DataType::HVDTPU_FLOAT16 ? FloatToFp16(v)
                                                  : FloatToBf16(v);
      fill(w, p);
      break;
    }
  }
}

collectives::Topology MakeTopology(const Global& gs) {
  collectives::Topology topo;
  topo.local_rank = gs.local_rank;
  topo.local_size = gs.local_size;
  topo.cross_rank = gs.cross_rank;
  topo.cross_size = gs.cross_size;
  return topo;
}

Status RunAllreduceWire(Global& gs, void* buf, int64_t count, DataType dt,
                        ReduceOp op) {
  if (op != ReduceOp::ADASUM) {
    if (gs.hierarchical_allreduce.load()) {
      return collectives::HierarchicalAllreduce(*gs.transport, buf, count,
                                                dt, op, MakeTopology(gs));
    }
    return collectives::RingAllreduce(*gs.transport, buf, count, dt, op);
  }
  // Adasum: widen 16-bit floats to f32 for the dot-product math
  // (reference computes adasum in full precision with fp16 AVX
  // specializations, adasum.h:101-141).
  if (dt == DataType::HVDTPU_FLOAT16 || dt == DataType::HVDTPU_BFLOAT16) {
    std::vector<float> wide(static_cast<size_t>(count));
    const uint16_t* p = static_cast<const uint16_t*>(buf);
    for (int64_t i = 0; i < count; ++i) {
      wide[i] = dt == DataType::HVDTPU_FLOAT16 ? Fp16ToFloat(p[i])
                                               : Bf16ToFloat(p[i]);
    }
    Status s = AdasumAllreduce(*gs.transport, wide.data(), count,
                               DataType::HVDTPU_FLOAT32);
    if (!s.ok()) return s;
    uint16_t* q = static_cast<uint16_t*>(buf);
    for (int64_t i = 0; i < count; ++i) {
      q[i] = dt == DataType::HVDTPU_FLOAT16 ? FloatToFp16(wide[i])
                                            : FloatToBf16(wide[i]);
    }
    return s;
  }
  return AdasumAllreduce(*gs.transport, buf, count, dt);
}

void PerformOperation(Global& gs, const Response& resp) {
  // Identity substitution for names this rank holds no entry for — the
  // joined-rank case (reference: zero-tensor substitution in
  // GetTensorEntriesFromResponse). Driven purely by entry presence, not the
  // controller's joined flag: a rank that enqueued a tensor and then joined
  // still contributes its real data.
  switch (resp.response_type) {
    case Response::ALLREDUCE:
    case Response::ADASUM: {
      DataType dt = resp.tensor_type;
      size_t es = DataTypeSize(dt);
      int64_t total = 0;
      for (auto c : resp.tensor_sizes) total += c;
      std::vector<EntryPtr> entries =
          gs.tensor_queue.GetAndRemoveEntries(resp.tensor_names);
      bool have_all = true, have_any = false;
      for (const auto& e : entries) {
        if (e != nullptr) have_any = true;
        else have_all = false;
      }
      const std::string& lane =
          resp.tensor_names.empty() ? std::string("fused")
                                    : resp.tensor_names[0];
      gs.timeline.Start(lane, resp.response_type == Response::ADASUM
                                  ? "ADASUM" : "ALLREDUCE");
      char* buf;
      bool in_place = entries.size() == 1 && entries[0] != nullptr;
      if (in_place) {
        buf = static_cast<char*>(entries[0]->data);
      } else {
        buf = gs.fusion_manager.GetBuffer(total * static_cast<int64_t>(es));
        gs.timeline.ActivityStart(lane, "MEMCPY_IN_FUSION_BUFFER");
        int64_t off = 0;
        for (size_t i = 0; i < entries.size(); ++i) {
          if (entries[i] != nullptr) {
            std::memcpy(buf + off * es, entries[i]->data,
                        static_cast<size_t>(resp.tensor_sizes[i]) * es);
          } else {
            FillIdentity(buf + off * es, resp.tensor_sizes[i], dt,
                         resp.reduce_op);
          }
          off += resp.tensor_sizes[i];
        }
        gs.timeline.ActivityEnd(lane);
      }
      if (have_any && resp.prescale_factor != 1.0) {
        // Prescale only real contributions; identity slices are already the
        // op's neutral element. (Identity values are scale-invariant for
        // SUM(0) and MIN/MAX(±inf); fused buffers are single-op anyway.)
        if (have_all) {
          collectives::ScaleBuffer(buf, total, dt, resp.prescale_factor);
        } else {
          int64_t off = 0;
          for (size_t i = 0; i < entries.size(); ++i) {
            if (entries[i] != nullptr) {
              collectives::ScaleBuffer(buf + off * es, resp.tensor_sizes[i],
                                       dt, resp.prescale_factor);
            }
            off += resp.tensor_sizes[i];
          }
        }
      }
      gs.timeline.ActivityStart(lane, "TCP_ALLREDUCE");
      Status s = RunAllreduceWire(gs, buf, total, dt, resp.reduce_op);
      gs.timeline.ActivityEnd(lane);
      if (s.ok() && resp.postscale_factor != 1.0) {
        collectives::ScaleBuffer(buf, total, dt, resp.postscale_factor);
      }
      if (!in_place && have_any) {
        gs.timeline.ActivityStart(lane, "MEMCPY_OUT_FUSION_BUFFER");
        int64_t off = 0;
        for (size_t i = 0; i < entries.size(); ++i) {
          if (entries[i] != nullptr) {
            std::memcpy(entries[i]->data, buf + off * es,
                        static_cast<size_t>(resp.tensor_sizes[i]) * es);
          }
          off += resp.tensor_sizes[i];
        }
        gs.timeline.ActivityEnd(lane);
      }
      gs.timeline.End(lane);
      for (auto& e : entries) {
        if (e) e->MarkDone(s);
      }
      break;
    }
    case Response::ALLGATHER: {
      DataType dt = resp.tensor_type;
      size_t es = DataTypeSize(dt);
      int64_t row = RowElems(resp.cache_shape);
      std::vector<int64_t> bytes_per_rank(resp.tensor_sizes.size());
      for (size_t r = 0; r < resp.tensor_sizes.size(); ++r) {
        bytes_per_rank[r] =
            resp.tensor_sizes[r] * row * static_cast<int64_t>(es);
      }
      EntryPtr e =
          gs.tensor_queue.GetAndRemoveEntries(resp.tensor_names).at(0);
      const std::string& lane = resp.tensor_names[0];
      gs.timeline.Start(lane, "ALLGATHER");
      std::vector<char> scratch;
      std::vector<char>* out = e ? &e->output : &scratch;
      const void* in = e ? e->data : nullptr;
      int64_t in_bytes = e ? bytes_per_rank[gs.rank] : 0;
      Status s =
          gs.hierarchical_allgather.load()
              ? collectives::HierarchicalAllgatherV(*gs.transport, in,
                                                    in_bytes, bytes_per_rank,
                                                    out, MakeTopology(gs))
              : collectives::AllgatherV(*gs.transport, in, in_bytes,
                                        bytes_per_rank, out);
      gs.timeline.End(lane);
      if (e) e->MarkDone(s);
      break;
    }
    case Response::BROADCAST: {
      DataType dt = resp.tensor_type;
      size_t es = DataTypeSize(dt);
      int64_t count = resp.tensor_sizes.empty() ? 0 : resp.tensor_sizes[0];
      EntryPtr e =
          gs.tensor_queue.GetAndRemoveEntries(resp.tensor_names).at(0);
      const std::string& lane = resp.tensor_names[0];
      gs.timeline.Start(lane, "BROADCAST");
      std::vector<char> scratch;
      void* buf;
      if (e) {
        buf = e->data;
      } else {
        scratch.resize(static_cast<size_t>(count) * es);
        buf = scratch.data();
      }
      Status s = collectives::Broadcast(*gs.transport, buf,
                                        count * static_cast<int64_t>(es),
                                        resp.root_rank);
      gs.timeline.End(lane);
      if (e) e->MarkDone(s);
      break;
    }
    case Response::ALLTOALL: {
      DataType dt = resp.tensor_type;
      size_t es = DataTypeSize(dt);
      int64_t row = RowElems(resp.cache_shape);
      int n = gs.size;
      std::vector<int64_t> send_bytes(n), recv_bytes(n), recv_rows(n);
      for (int r = 0; r < n; ++r) {
        send_bytes[r] = resp.tensor_sizes[static_cast<size_t>(gs.rank) * n +
                                          r] * row * static_cast<int64_t>(es);
        recv_rows[r] =
            resp.tensor_sizes[static_cast<size_t>(r) * n + gs.rank];
        recv_bytes[r] = recv_rows[r] * row * static_cast<int64_t>(es);
      }
      EntryPtr e =
          gs.tensor_queue.GetAndRemoveEntries(resp.tensor_names).at(0);
      const std::string& lane = resp.tensor_names[0];
      gs.timeline.Start(lane, "ALLTOALL");
      std::vector<char> scratch;
      std::vector<char>* out = e ? &e->output : &scratch;
      const void* in = e ? e->data : nullptr;
      Status s = collectives::AllToAllV(*gs.transport, in, send_bytes,
                                        recv_bytes, out);
      gs.timeline.End(lane);
      if (e) {
        e->recv_splits = recv_rows;
        e->MarkDone(s);
      }
      break;
    }
    case Response::BARRIER: {
      auto entries = gs.tensor_queue.GetAndRemoveEntries(resp.tensor_names);
      for (auto& e : entries) {
        if (e) e->MarkDone(Status::OK());
      }
      break;
    }
    case Response::JOIN: {
      std::lock_guard<std::mutex> l(gs.join_mu);
      if (gs.current_join) {
        // Drop the name reservation from the tensor table, then complete.
        gs.tensor_queue.GetAndRemoveEntries({gs.current_join->name});
        gs.current_join->join_result = resp.last_joined_rank;
        gs.current_join->MarkDone(Status::OK());
        gs.current_join.reset();
      }
      break;
    }
    case Response::ERROR: {
      auto entries = gs.tensor_queue.GetAndRemoveEntries(resp.tensor_names);
      for (auto& e : entries) {
        if (e) e->MarkDone(Status::PreconditionError(resp.error_message));
      }
      break;
    }
  }
}

void AbortEverything(Global& gs, const Status& reason) {
  gs.tensor_queue.AbortAll(reason);
  std::lock_guard<std::mutex> l(gs.join_mu);
  if (gs.current_join) {
    gs.current_join->MarkDone(reason);
    gs.current_join.reset();
  }
}

// The single communication thread (reference: BackgroundThreadLoop,
// operations.cc:354-569 — one thread owns all negotiation + wire traffic so
// ops execute in a globally agreed order regardless of submission order).
void BackgroundLoop(Global* gs) {
  SetLogRank(gs->rank);
  auto last_cycle = std::chrono::steady_clock::now();
  while (true) {
    // Pace the negotiation cycle (reference: HOROVOD_CYCLE_TIME sleep,
    // operations.cc:571-580).
    auto next = last_cycle + std::chrono::microseconds(
                                 gs->cycle_time_us.load());
    std::this_thread::sleep_until(next);
    last_cycle = std::chrono::steady_clock::now();

    bool want_shutdown = gs->shutdown_requested.load();
    Controller::CycleResult cycle =
        gs->controller->RunCycle(want_shutdown, gs->fusion_threshold.load());
    if (cycle.transport_failure) {
      AbortEverything(*gs,
                      Status::UnknownError(
                          "Horovod background loop lost connection to a "
                          "peer; the job world has changed or a worker "
                          "died (HorovodInternalError)"));
      break;
    }
    if (cycle.tuned_fusion_threshold > 0) {
      gs->fusion_threshold.store(cycle.tuned_fusion_threshold);
    }
    if (cycle.tuned_cycle_time_ms > 0) {
      gs->cycle_time_us.store(
          static_cast<int64_t>(cycle.tuned_cycle_time_ms * 1000));
    }
    if (cycle.has_tuned_flags) {
      // Applied on every rank at the same cycle boundary (the flags ride
      // the ResponseList broadcast), so cache state and collective
      // algorithm stay globally consistent.
      gs->controller->set_cache_enabled((cycle.tuned_flags & 1) != 0);
      gs->hierarchical_allreduce.store((cycle.tuned_flags & 2) != 0);
      gs->hierarchical_allgather.store((cycle.tuned_flags & 4) != 0);
    }
    int64_t bytes_this_cycle = 0;
    for (const Response& r : cycle.responses) {
      PerformOperation(*gs, r);
      if (r.response_type == Response::ALLREDUCE ||
          r.response_type == Response::ADASUM) {
        for (auto c : r.tensor_sizes) {
          bytes_this_cycle +=
              c * static_cast<int64_t>(DataTypeSize(r.tensor_type));
        }
      }
    }
    if (gs->parameter_manager.active() && gs->controller->is_coordinator()) {
      gs->parameter_manager.RecordBytes(bytes_this_cycle);
    }
    if (cycle.shutdown) {
      AbortEverything(*gs, Status::Aborted("Horovod has been shut down"));
      break;
    }
  }
  gs->loop_running.store(false);
}

int EnqueueEntry(EntryPtr entry, Request req) {
  std::lock_guard<std::mutex> l(g_mu);
  if (!g || !g->loop_running.load()) {
    SetLastError("Horovod native core is not initialized");
    return -1;
  }
  Status s = g->tensor_queue.AddToTensorQueue(entry, std::move(req));
  if (!s.ok()) {
    SetLastError(s.reason());
    return -1;
  }
  std::lock_guard<std::mutex> h(g->handle_mu);
  int handle = g->next_handle++;
  g->handles.emplace(handle, std::move(entry));
  return handle;
}

EntryPtr GetHandle(int handle) {
  std::lock_guard<std::mutex> l(g_mu);
  if (!g) return nullptr;
  std::lock_guard<std::mutex> h(g->handle_mu);
  auto it = g->handles.find(handle);
  return it == g->handles.end() ? nullptr : it->second;
}

}  // namespace
}  // namespace hvdtpu

using namespace hvdtpu;  // NOLINT

extern "C" {

int hvdtpu_init(void) {
  std::lock_guard<std::mutex> l(g_mu);
  if (g && g->loop_running.load()) return 0;  // idempotent
  auto gs = std::make_unique<Global>();
  gs->rank = static_cast<int>(EnvInt64(HVDTPU_ENV_RANK, 0));
  gs->size = static_cast<int>(EnvInt64(HVDTPU_ENV_SIZE, 1));
  gs->local_rank = static_cast<int>(EnvInt64(HVDTPU_ENV_LOCAL_RANK, 0));
  gs->local_size = static_cast<int>(EnvInt64(HVDTPU_ENV_LOCAL_SIZE, 1));
  gs->cross_rank = static_cast<int>(
      EnvInt64(HVDTPU_ENV_CROSS_RANK, gs->rank));
  gs->cross_size = static_cast<int>(
      EnvInt64(HVDTPU_ENV_CROSS_SIZE, gs->size));
  SetLogRank(gs->rank);

  gs->fusion_threshold.store(
      EnvInt64(HVDTPU_ENV_FUSION_THRESHOLD, 64 * 1024 * 1024));
  gs->hierarchical_allreduce.store(
      EnvBool(HVDTPU_ENV_HIERARCHICAL_ALLREDUCE, false));
  gs->hierarchical_allgather.store(
      EnvBool(HVDTPU_ENV_HIERARCHICAL_ALLGATHER, false));
  if (gs->hierarchical_allreduce.load() || gs->hierarchical_allgather.load()) {
    // Fail fast on a rank layout the hierarchical decomposition cannot
    // honor. The check must not silently fall back per-rank: ranks whose
    // identity happens to satisfy it would take the hierarchical path
    // while others go flat — mixed protocols on one transport deadlock
    // mid-collective. Dying at init on any rank kills the job cleanly.
    if (gs->local_size < 1 || gs->cross_size < 1 ||
        gs->local_size * gs->cross_size != gs->size ||
        gs->cross_rank * gs->local_size + gs->local_rank != gs->rank) {
      HVDTPU_LOG(ERROR)
          << "hierarchical collectives require host-major rank "
                << "packing (rank = cross_rank*local_size + local_rank and "
                << "local_size*cross_size == size); got rank=" << gs->rank
                << " size=" << gs->size << " local=" << gs->local_rank << "/"
                << gs->local_size << " cross=" << gs->cross_rank << "/"
                << gs->cross_size
                << ". Fix the launcher env or unset "
                << "HOROVOD_HIERARCHICAL_ALLREDUCE/ALLGATHER.";
      return 1;
    }
  }
  // HOROVOD_CYCLE_TIME is milliseconds in the reference (default 5,
  // operations.cc:445); host TCP negotiation is cheap so default 1 ms.
  gs->cycle_time_us.store(static_cast<int64_t>(
      EnvDouble(HVDTPU_ENV_CYCLE_TIME, 1.0) * 1000));
  gs->response_cache.set_capacity(static_cast<uint32_t>(
      EnvInt64(HVDTPU_ENV_CACHE_CAPACITY, 1024)));
  gs->stall_inspector.Configure(
      !EnvBool(HVDTPU_ENV_STALL_CHECK_DISABLE, false),
      EnvDouble(HVDTPU_ENV_STALL_CHECK_TIME, 60.0),
      EnvDouble(HVDTPU_ENV_STALL_SHUTDOWN_TIME, 0.0), gs->size);

  std::string coord_addr =
      EnvString(HVDTPU_ENV_CONTROLLER_ADDR, "127.0.0.1");
  int coord_port =
      static_cast<int>(EnvInt64(HVDTPU_ENV_CONTROLLER_PORT, 42223));
  double timeout = EnvDouble("HOROVOD_START_TIMEOUT", 120.0);
  gs->transport =
      Transport::Create(gs->rank, gs->size, coord_addr, coord_port, timeout);
  if (!gs->transport) {
    SetLastError("failed to establish transport (rendezvous with peers)");
    return 1;
  }

  // Timeline is coordinator-only (reference: operations.cc:420-423).
  std::string timeline_path = EnvString(HVDTPU_ENV_TIMELINE, "");
  if (!timeline_path.empty() && gs->rank == 0) {
    gs->timeline.Initialize(timeline_path,
                            EnvBool(HVDTPU_ENV_TIMELINE_MARK_CYCLES, false));
  }

  gs->controller = std::make_unique<Controller>(
      gs->rank, gs->size, gs->transport.get(), &gs->tensor_queue,
      &gs->response_cache, gs->rank == 0 ? &gs->stall_inspector : nullptr,
      gs->rank == 0 ? &gs->timeline : nullptr);

  if (EnvBool(HVDTPU_ENV_AUTOTUNE, false) && gs->rank == 0) {
    // Hierarchical knobs enter the search space only on a topology that
    // can honor them. Rank 0's view stands for all ranks: every launcher
    // derives the env from host-major get_host_assignments, and
    // explicitly-set flags are validated per-rank at init above.
    collectives::Topology topo = MakeTopology(*gs);
    bool tune_hier = topo.Hierarchical(gs->size, gs->rank);
    gs->parameter_manager.Initialize(
        gs->fusion_threshold.load(),
        gs->cycle_time_us.load() / 1000.0,
        /*cache_enabled=*/true,
        gs->hierarchical_allreduce.load(),
        gs->hierarchical_allgather.load(), tune_hier,
        EnvString(HVDTPU_ENV_AUTOTUNE_LOG, ""),
        EnvInt64(HVDTPU_ENV_AUTOTUNE_WARMUP_SAMPLES, 3),
        EnvInt64(HVDTPU_ENV_AUTOTUNE_STEPS_PER_SAMPLE, 10),
        EnvInt64(HVDTPU_ENV_AUTOTUNE_BAYES_OPT_MAX_SAMPLES, 20),
        EnvDouble(HVDTPU_ENV_AUTOTUNE_GAUSSIAN_PROCESS_NOISE, 0.8));
    Global* raw = gs.get();
    gs->controller->autotune_hook =
        [raw](const std::vector<Response>& responses,
              TunedParamsWire* out) {
          TunedParams p;
          if (!raw->parameter_manager.Update(responses, &p)) return false;
          out->fusion_threshold = p.fusion_threshold;
          out->cycle_time_ms = p.cycle_time_ms;
          out->has_flags = p.has_flags;
          out->flags = static_cast<uint8_t>(
              (p.cache_enabled ? 1 : 0) |
              (p.hierarchical_allreduce ? 2 : 0) |
              (p.hierarchical_allgather ? 4 : 0));
          return true;
        };
  }

  gs->loop_running.store(true);
  gs->background = std::thread(BackgroundLoop, gs.get());
  g = std::move(gs);
  return 0;
}

void hvdtpu_shutdown(void) {
  std::unique_ptr<Global> local;
  {
    std::lock_guard<std::mutex> l(g_mu);
    if (!g) return;
    local = std::move(g);
  }
  local->shutdown_requested.store(true);
  if (local->background.joinable()) local->background.join();
  local->timeline.Shutdown();
  AbortEverything(*local, Status::Aborted("Horovod has been shut down"));
  ResetBoundControlPort();
}

void hvdtpu_clear_controller_port(void) { ResetBoundControlPort(); }

int hvdtpu_is_initialized(void) {
  std::lock_guard<std::mutex> l(g_mu);
  return g && g->loop_running.load() ? 1 : 0;
}

int hvdtpu_controller_port(void) {
  // Deliberately lock-free: called from a watcher thread WHILE hvdtpu_init
  // holds g_mu blocked in world formation — that is the whole point (the
  // coordinator publishes its OS-assigned port before accepting peers).
  return BoundControlPort();
}

const char* hvdtpu_last_error(void) {
  std::lock_guard<std::mutex> l(g_err_mu);
  return g_last_error.c_str();
}

int hvdtpu_rank(void) {
  std::lock_guard<std::mutex> l(g_mu);
  return g ? g->rank : -1;
}
int hvdtpu_size(void) {
  std::lock_guard<std::mutex> l(g_mu);
  return g ? g->size : -1;
}
int hvdtpu_local_rank(void) {
  std::lock_guard<std::mutex> l(g_mu);
  return g ? g->local_rank : -1;
}
int hvdtpu_local_size(void) {
  std::lock_guard<std::mutex> l(g_mu);
  return g ? g->local_size : -1;
}
int hvdtpu_cross_rank(void) {
  std::lock_guard<std::mutex> l(g_mu);
  return g ? g->cross_rank : -1;
}
int hvdtpu_cross_size(void) {
  std::lock_guard<std::mutex> l(g_mu);
  return g ? g->cross_size : -1;
}
int64_t hvdtpu_fusion_threshold(void) {
  std::lock_guard<std::mutex> l(g_mu);
  return g ? g->fusion_threshold.load() : -1;
}
double hvdtpu_cycle_time_ms(void) {
  std::lock_guard<std::mutex> l(g_mu);
  return g ? g->cycle_time_us.load() / 1000.0 : -1;
}

int hvdtpu_allreduce(const char* name, void* data, const int64_t* shape,
                     int ndim, int dtype, int op, double prescale,
                     double postscale) {
  auto entry = std::make_shared<TensorTableEntry>();
  entry->name = name;
  entry->type = static_cast<ReduceOp>(op) == ReduceOp::ADASUM
                    ? Request::ADASUM
                    : Request::ALLREDUCE;
  entry->dtype = static_cast<DataType>(dtype);
  entry->data = data;
  entry->shape.assign(shape, shape + ndim);
  entry->count = ShapeCount(entry->shape);
  entry->prescale_factor = prescale;
  entry->postscale_factor = postscale;
  entry->reduce_op = static_cast<ReduceOp>(op);

  Request req;
  req.request_rank = hvdtpu_rank();
  req.request_type = entry->type;
  req.tensor_type = entry->dtype;
  req.tensor_name = entry->name;
  req.tensor_shape = entry->shape;
  req.prescale_factor = prescale;
  req.postscale_factor = postscale;
  req.reduce_op = entry->reduce_op;
  return EnqueueEntry(std::move(entry), std::move(req));
}

int hvdtpu_allgather(const char* name, const void* data,
                     const int64_t* shape, int ndim, int dtype) {
  auto entry = std::make_shared<TensorTableEntry>();
  entry->name = name;
  entry->type = Request::ALLGATHER;
  entry->dtype = static_cast<DataType>(dtype);
  entry->data = const_cast<void*>(data);
  entry->shape.assign(shape, shape + ndim);
  entry->count = ShapeCount(entry->shape);

  Request req;
  req.request_rank = hvdtpu_rank();
  req.request_type = Request::ALLGATHER;
  req.tensor_type = entry->dtype;
  req.tensor_name = entry->name;
  req.tensor_shape = entry->shape;
  return EnqueueEntry(std::move(entry), std::move(req));
}

int hvdtpu_broadcast(const char* name, void* data, const int64_t* shape,
                     int ndim, int dtype, int root) {
  auto entry = std::make_shared<TensorTableEntry>();
  entry->name = name;
  entry->type = Request::BROADCAST;
  entry->dtype = static_cast<DataType>(dtype);
  entry->data = data;
  entry->shape.assign(shape, shape + ndim);
  entry->count = ShapeCount(entry->shape);
  entry->root_rank = root;

  Request req;
  req.request_rank = hvdtpu_rank();
  req.request_type = Request::BROADCAST;
  req.tensor_type = entry->dtype;
  req.tensor_name = entry->name;
  req.tensor_shape = entry->shape;
  req.root_rank = root;
  return EnqueueEntry(std::move(entry), std::move(req));
}

int hvdtpu_alltoall(const char* name, const void* data, const int64_t* shape,
                    int ndim, int dtype, const int64_t* splits, int nsplits) {
  auto entry = std::make_shared<TensorTableEntry>();
  entry->name = name;
  entry->type = Request::ALLTOALL;
  entry->dtype = static_cast<DataType>(dtype);
  entry->data = const_cast<void*>(data);
  entry->shape.assign(shape, shape + ndim);
  entry->count = ShapeCount(entry->shape);
  if (nsplits > 0) entry->splits.assign(splits, splits + nsplits);

  Request req;
  req.request_rank = hvdtpu_rank();
  req.request_type = Request::ALLTOALL;
  req.tensor_type = entry->dtype;
  req.tensor_name = entry->name;
  req.tensor_shape = entry->shape;
  req.splits = entry->splits;
  return EnqueueEntry(std::move(entry), std::move(req));
}

int hvdtpu_join(void) {
  std::lock_guard<std::mutex> l(g_mu);
  if (!g || !g->loop_running.load()) {
    SetLastError("Horovod native core is not initialized");
    return -1;
  }
  auto entry = std::make_shared<TensorTableEntry>();
  entry->name = "join." + std::to_string(g->op_counter.fetch_add(1));
  entry->type = Request::JOIN;

  Request req;
  req.request_rank = g->rank;
  req.request_type = Request::JOIN;
  req.tensor_name = entry->name;
  {
    std::lock_guard<std::mutex> j(g->join_mu);
    if (g->current_join) {
      SetLastError("join already in progress");
      return -1;
    }
    // Completion comes from the JOIN response (which names no tensors), so
    // track the entry in current_join; it also sits in the tensor table to
    // reserve its name until the join resolves.
    g->current_join = entry;
  }
  Status s = g->tensor_queue.AddToTensorQueue(entry, std::move(req));
  if (!s.ok()) {
    std::lock_guard<std::mutex> j(g->join_mu);
    g->current_join.reset();
    SetLastError(s.reason());
    return -1;
  }
  std::lock_guard<std::mutex> h(g->handle_mu);
  int handle = g->next_handle++;
  g->handles.emplace(handle, std::move(entry));
  return handle;
}

int hvdtpu_barrier(void) {
  int seq, rank;
  {
    std::lock_guard<std::mutex> l(g_mu);
    if (!g || !g->loop_running.load()) {
      SetLastError("Horovod native core is not initialized");
      return -1;
    }
    seq = g->barrier_counter.fetch_add(1);
    rank = g->rank;
  }
  auto entry = std::make_shared<TensorTableEntry>();
  // Sequence-numbered name: ranks align because every rank issues barriers
  // in the same program order.
  entry->name = "barrier." + std::to_string(seq);
  entry->type = Request::BARRIER;

  Request req;
  req.request_rank = rank;
  req.request_type = Request::BARRIER;
  req.tensor_name = entry->name;
  return EnqueueEntry(std::move(entry), std::move(req));
}

int hvdtpu_poll(int handle) {
  EntryPtr e = GetHandle(handle);
  return e == nullptr || e->Done() ? 1 : 0;
}

int hvdtpu_wait(int handle) {
  EntryPtr e = GetHandle(handle);
  if (e == nullptr) {
    SetLastError("unknown handle");
    return static_cast<int>(StatusType::INVALID_ARGUMENT);
  }
  Status s = e->Wait();
  return static_cast<int>(s.type());
}

const char* hvdtpu_handle_error(int handle) {
  EntryPtr e = GetHandle(handle);
  static thread_local std::string msg;
  msg = e == nullptr ? "unknown handle" : e->status.reason();
  return msg.c_str();
}

int64_t hvdtpu_result_bytes(int handle) {
  EntryPtr e = GetHandle(handle);
  return e == nullptr ? -1 : static_cast<int64_t>(e->output.size());
}

void hvdtpu_fetch(int handle, void* out) {
  EntryPtr e = GetHandle(handle);
  if (e != nullptr && !e->output.empty()) {
    std::memcpy(out, e->output.data(), e->output.size());
  }
}

int hvdtpu_join_result(int handle) {
  EntryPtr e = GetHandle(handle);
  return e == nullptr ? -1 : e->join_result;
}

int hvdtpu_recv_splits(int handle, int64_t* out, int max) {
  EntryPtr e = GetHandle(handle);
  if (e == nullptr) return 0;
  int n = static_cast<int>(std::min<size_t>(e->recv_splits.size(),
                                            static_cast<size_t>(max)));
  for (int i = 0; i < n; ++i) out[i] = e->recv_splits[i];
  return n;
}

void hvdtpu_release(int handle) {
  std::lock_guard<std::mutex> l(g_mu);
  if (!g) return;
  std::lock_guard<std::mutex> h(g->handle_mu);
  g->handles.erase(handle);
}

int hvdtpu_start_timeline(const char* path, int mark_cycles) {
  std::lock_guard<std::mutex> l(g_mu);
  if (!g) return 1;
  if (g->rank != 0) return 0;  // coordinator-only writer
  g->timeline.Initialize(path, mark_cycles != 0);
  return 0;
}

int hvdtpu_stop_timeline(void) {
  std::lock_guard<std::mutex> l(g_mu);
  if (!g) return 1;
  g->timeline.Shutdown();
  return 0;
}

int hvdtpu_autotune_active(void) {
  std::lock_guard<std::mutex> l(g_mu);
  return g && g->parameter_manager.active() ? 1 : 0;
}

}  // extern "C"
