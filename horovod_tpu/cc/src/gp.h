// Gaussian process regression + expected improvement, for the autotuner.
//
// Reference: horovod/common/optim/{gaussian_process,bayesian_optimization}
// .{h,cc} — GP with RBF kernel fitted to (params, score) samples, next
// sample point chosen by maximizing expected improvement. The reference uses
// Eigen + LBFGS; the search space here is 2-D and tiny, so plain Cholesky
// and random-candidate EI maximization are ample.
#ifndef HVDTPU_GP_H
#define HVDTPU_GP_H

#include <cstdint>
#include <vector>

namespace hvdtpu {

class GaussianProcess {
 public:
  // noise: observation stddev (reference knob
  // HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE, default 0.8).
  GaussianProcess(int dims, double length_scale, double noise)
      : dims_(dims), length_scale_(length_scale), noise_(noise) {}

  // Fit to n samples of `dims_`-dimensional x in [0,1] and scores y
  // (normalized by the caller). Returns false if the kernel matrix is not
  // positive definite.
  bool Fit(const std::vector<std::vector<double>>& x,
           const std::vector<double>& y);

  // Posterior mean and standard deviation at a point.
  void Predict(const std::vector<double>& x, double* mean,
               double* stddev) const;

  // Expected improvement over `best_y` at `x` (xi = exploration margin).
  double ExpectedImprovement(const std::vector<double>& x, double best_y,
                             double xi = 0.01) const;

 private:
  double Kernel(const std::vector<double>& a,
                const std::vector<double>& b) const;

  int dims_;
  double length_scale_;
  double noise_;
  std::vector<std::vector<double>> x_;
  std::vector<double> alpha_;            // K^-1 y
  std::vector<std::vector<double>> l_;   // Cholesky factor of K
};

}  // namespace hvdtpu

#endif  // HVDTPU_GP_H
