#include "response_cache.h"

#include <algorithm>

namespace hvdtpu {

ResponseCache::CacheState ResponseCache::cached(const Request& req) const {
  auto it = cache_.find(req.tensor_name);
  if (it == cache_.end()) return CacheState::MISS;
  const CacheEntry& e = it->second;
  bool same = e.dtype == req.tensor_type && e.shape == req.tensor_shape &&
              e.prescale == req.prescale_factor &&
              e.postscale == req.postscale_factor &&
              e.reduce_op == req.reduce_op;
  return same ? CacheState::HIT : CacheState::INVALID;
}

void ResponseCache::put(const Response& response) {
  if (capacity_ == 0) return;
  // Only single-tensor data-plane responses are cacheable (fusion happens
  // over cached singles each cycle, as in the reference where fused
  // responses are re-formed from cached bits, controller.cc:205-216).
  // Alltoall stays uncached (splits may change per call) and so does
  // allgather (ragged first dims mean there is no single job-wide shape to
  // validate a hit against; the reference caches it by storing per-rank
  // request params, but we keep one replicated shape so joined ranks can
  // mirror insertions — see controller.cc).
  if (response.tensor_names.size() != 1 ||
      (response.response_type != Response::ALLREDUCE &&
       response.response_type != Response::ADASUM &&
       response.response_type != Response::BROADCAST)) {
    return;
  }
  const std::string& name = response.tensor_names[0];
  auto it = cache_.find(name);
  if (it != cache_.end()) {
    lru_.erase(it->second.lru_it);
    it->second.response = response;
    it->second.dtype = response.tensor_type;
    it->second.shape = response.cache_shape;
    it->second.prescale = response.prescale_factor;
    it->second.postscale = response.postscale_factor;
    it->second.reduce_op = response.reduce_op;
    lru_.push_front(it->second.bit);
    it->second.lru_it = lru_.begin();
    return;
  }
  if (cache_.size() >= capacity_) {
    // Evict least-recently-used (reference evicts via the same LRU list).
    uint32_t victim = lru_.back();
    erase_response(victim);
  }
  uint32_t bit;
  if (!free_bits_.empty()) {
    bit = free_bits_.back();
    free_bits_.pop_back();
  } else {
    bit = static_cast<uint32_t>(bit_to_name_.size());
    bit_to_name_.emplace_back();
  }
  bit_to_name_[bit] = name;
  CacheEntry e;
  e.response = response;
  e.dtype = response.tensor_type;
  e.shape = response.cache_shape;
  e.prescale = response.prescale_factor;
  e.postscale = response.postscale_factor;
  e.reduce_op = response.reduce_op;
  e.bit = bit;
  lru_.push_front(bit);
  e.lru_it = lru_.begin();
  cache_.emplace(name, std::move(e));
}

Response ResponseCache::get_response(uint32_t bit) {
  return cache_.at(bit_to_name_.at(bit)).response;
}

uint32_t ResponseCache::peek_cache_bit(const Request& req) const {
  return cache_.at(req.tensor_name).bit;
}

void ResponseCache::erase_response(uint32_t bit) {
  if (bit >= bit_to_name_.size() || bit_to_name_[bit].empty()) return;
  auto it = cache_.find(bit_to_name_[bit]);
  if (it != cache_.end()) {
    lru_.erase(it->second.lru_it);
    cache_.erase(it);
  }
  bit_to_name_[bit].clear();
  free_bits_.push_back(bit);
}

void ResponseCache::clear() {
  cache_.clear();
  bit_to_name_.clear();
  free_bits_.clear();
  lru_.clear();
}

void ResponseCache::touch(uint32_t bit) {
  if (bit >= bit_to_name_.size() || bit_to_name_[bit].empty()) return;
  auto& e = cache_.at(bit_to_name_[bit]);
  lru_.erase(e.lru_it);
  lru_.push_front(bit);
  e.lru_it = lru_.begin();
}

std::vector<int64_t> PackBits(const std::vector<uint32_t>& bits,
                              size_t nbits) {
  std::vector<int64_t> words((nbits + 63) / 64, 0);
  for (uint32_t b : bits) {
    if (b / 64 >= words.size()) words.resize(b / 64 + 1, 0);
    words[b / 64] |= (int64_t{1} << (b % 64));
  }
  return words;
}

std::vector<uint32_t> UnpackBits(const std::vector<int64_t>& words) {
  std::vector<uint32_t> bits;
  for (size_t w = 0; w < words.size(); ++w) {
    uint64_t word = static_cast<uint64_t>(words[w]);
    while (word) {
      int b = __builtin_ctzll(word);
      bits.push_back(static_cast<uint32_t>(w * 64 + b));
      word &= word - 1;
    }
  }
  return bits;
}

std::vector<int64_t> AndWords(const std::vector<int64_t>& a,
                              const std::vector<int64_t>& b) {
  std::vector<int64_t> out(std::min(a.size(), b.size()));
  for (size_t i = 0; i < out.size(); ++i) out[i] = a[i] & b[i];
  return out;
}

}  // namespace hvdtpu
