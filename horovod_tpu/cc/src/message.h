// Negotiation message types.
//
// Reference: horovod/common/message.{h,cc} — Request (message.h:48-113),
// Response (145-217), RequestList/ResponseList with shutdown bit. Same
// protocol roles, hand-rolled wire format (see wire.h) instead of
// FlatBuffers.
#ifndef HVDTPU_MESSAGE_H
#define HVDTPU_MESSAGE_H

#include <cstdint>
#include <string>
#include <vector>

#include "common.h"
#include "wire.h"

namespace hvdtpu {

// A worker announces "tensor X is ready on my rank" with one Request
// (reference: message.h:48-113).
struct Request {
  enum Type : uint8_t {
    ALLREDUCE = 0,
    ALLGATHER = 1,
    BROADCAST = 2,
    JOIN = 3,
    ADASUM = 4,
    ALLTOALL = 5,
    BARRIER = 6,  // host-side barrier (reference exposes this via controller)
  };

  int32_t request_rank = 0;
  Type request_type = ALLREDUCE;
  DataType tensor_type = DataType::HVDTPU_FLOAT32;
  std::string tensor_name;
  int32_t root_rank = 0;  // broadcast root
  int32_t device = 0;     // CPU=0; kept for cross-rank consistency checks
  std::vector<int64_t> tensor_shape;
  double prescale_factor = 1.0;
  double postscale_factor = 1.0;
  ReduceOp reduce_op = ReduceOp::SUM;
  std::vector<int64_t> splits;  // alltoall send splits (rows per dest rank)

  void Serialize(WireWriter& w) const;
  static Request Deserialize(WireReader& r);
  static const char* TypeName(Type t);
};

// Per-cycle batch of requests from one worker, plus the shutdown flag and
// the response-cache hit bitvector (reference: RequestList message.h:115-143;
// the cache bits ride the same round as in controller.cc:75-164).
struct RequestList {
  std::vector<Request> requests;
  bool shutdown = false;
  bool joined = false;                  // this rank is in joined state
  std::vector<int64_t> cache_bits;      // bit-packed cache hits this cycle
  std::vector<int64_t> invalid_bits;    // cached entries whose params changed

  void Serialize(WireWriter& w) const;
  static RequestList Deserialize(WireReader& r);
};

// Coordinator verdict for one (possibly fused) set of tensors
// (reference: Response, message.h:145-217).
struct Response {
  enum Type : uint8_t {
    ALLREDUCE = 0,
    ALLGATHER = 1,
    BROADCAST = 2,
    JOIN = 3,
    ADASUM = 4,
    ALLTOALL = 5,
    BARRIER = 6,
    ERROR = 7,
  };

  Type response_type = ALLREDUCE;
  std::vector<std::string> tensor_names;  // >1 = fused
  std::string error_message;
  std::vector<int32_t> devices;
  // ALLREDUCE/ADASUM: per-tensor element counts (fusion slicing);
  // ALLGATHER: first-dim sizes per rank (reference: tensor_sizes);
  // ALLTOALL: flattened size×size matrix of send splits [src*size+dst].
  std::vector<int64_t> tensor_sizes;
  int32_t last_joined_rank = -1;  // JOIN: rank of the last rank to join
  int32_t root_rank = 0;          // BROADCAST root
  // Execution + cache-replication params. The reference keeps these on the
  // entries; we carry them in the response so every rank (including joined
  // ranks holding no entry) caches and executes identically.
  DataType tensor_type = DataType::HVDTPU_FLOAT32;
  double prescale_factor = 1.0;
  double postscale_factor = 1.0;
  ReduceOp reduce_op = ReduceOp::SUM;
  std::vector<int64_t> cache_shape;  // single-tensor responses: full shape

  void Serialize(WireWriter& w) const;
  static Response Deserialize(WireReader& r);
};

// Coordinator -> workers broadcast for one cycle (reference: ResponseList,
// message.h:219-247). Carries tuned parameters when autotuning is active
// (reference: ParameterManager::Params broadcast, controller.cc:34-48).
struct ResponseList {
  std::vector<Response> responses;
  bool shutdown = false;
  std::vector<int64_t> invalid_bits;  // cache bits every rank must evict
  bool has_tuned_params = false;
  int64_t tuned_fusion_threshold = 0;
  double tuned_cycle_time_ms = 0.0;
  // bit0 cache_enabled, bit1 hierarchical_allreduce,
  // bit2 hierarchical_allgather (valid when has_tuned_params).
  uint8_t tuned_flags = 0;

  void Serialize(WireWriter& w) const;
  static ResponseList Deserialize(WireReader& r);
};

}  // namespace hvdtpu

#endif  // HVDTPU_MESSAGE_H
