// Host data-plane collectives over the TCP mesh.
//
// These are the TPU framework's analogue of the reference's Gloo CPU backend
// (gloo_operations.cc ring/halving-doubling): the eager/host path for
// metrics, object broadcast, and elastic state sync. The compiled XLA path
// (jax shard_map + psum over ICI) is the training fast path and never touches
// these.
//
// Algorithms: bandwidth-optimal ring allreduce (reduce-scatter + allgather,
// the same decomposition as NCCLAllreduce's ring), ring allgatherv (uneven
// first dims, reference: MPIAllgather's displacement math,
// collective_operations.cc allgather helpers), binomial-tree broadcast, and
// pairwise alltoallv (reference: MPI_Alltoallv, mpi_operations.cc).
#ifndef HVDTPU_COLLECTIVES_H
#define HVDTPU_COLLECTIVES_H

#include <cstdint>
#include <vector>

#include "common.h"
#include "transport.h"

namespace hvdtpu {
namespace collectives {

// In-place sum/min/max/prod allreduce of `count` elements.
Status RingAllreduce(Transport& t, void* buf, int64_t count, DataType dt,
                     ReduceOp op);

// Gather variable-sized byte blocks; `out` = blocks concatenated by rank.
Status AllgatherV(Transport& t, const void* in, int64_t in_bytes,
                  const std::vector<int64_t>& bytes_per_rank,
                  std::vector<char>* out);

// Host/chip topology for hierarchical decompositions. Ranks are host-major:
// rank = cross_rank * local_size + local_rank (the launcher's packing,
// reference hosts.py:100-150), leaders are the local_rank-0 ranks.
struct Topology {
  int local_rank = 0, local_size = 1, cross_rank = 0, cross_size = 1;
  bool Hierarchical(int world_size, int world_rank) const {
    return local_size > 1 && cross_size > 1 &&
           local_size * cross_size == world_size &&
           cross_rank * local_size + local_rank == world_rank;
  }
};

// Hierarchical allreduce: intra-host reduce to the local leader → ring
// allreduce among leaders (the only cross-host traffic) → intra-host
// broadcast. Reference: NCCLHierarchicalAllreduce's intra-RS → cross-AR →
// intra-AG decomposition (nccl_operations.cc:190-380) with the intra legs on
// loopback TCP standing in for NCCL/shared memory.
Status HierarchicalAllreduce(Transport& t, void* buf, int64_t count,
                             DataType dt, ReduceOp op, const Topology& topo);

// Hierarchical allgatherv: intra-host gather to the local leader →
// ring allgather of per-host superblocks among leaders → intra-host
// broadcast of the assembled result. Reference: MPIHierarchicalAllgather
// (mpi_operations.cc:180-280; node leaders gather through shared memory,
// cross leg over MPI).
Status HierarchicalAllgatherV(Transport& t, const void* in, int64_t in_bytes,
                              const std::vector<int64_t>& bytes_per_rank,
                              std::vector<char>* out, const Topology& topo);

// Broadcast `bytes` from `root` (binomial tree, log2(size) rounds).
Status Broadcast(Transport& t, void* buf, int64_t bytes, int root);

// Pairwise exchange: send_bytes[i] bytes go to rank i (taken sequentially
// from `in`), recv_bytes[i] land in `out` at rank-i offset.
Status AllToAllV(Transport& t, const void* in,
                 const std::vector<int64_t>& send_bytes,
                 const std::vector<int64_t>& recv_bytes,
                 std::vector<char>* out);

// dst[i] = dst[i] (op) src[i] — the reduction kernel under the ring
// (reference: the MPI op table + float16_sum custom op, half.h:142).
void ReduceInto(void* dst, const void* src, int64_t count, DataType dt,
                ReduceOp op);

// In-place multiply by `factor` (reference: ScaleBufferCPUImpl,
// collective_operations.h:89-125).
void ScaleBuffer(void* buf, int64_t count, DataType dt, double factor);

}  // namespace collectives
}  // namespace hvdtpu

#endif  // HVDTPU_COLLECTIVES_H
