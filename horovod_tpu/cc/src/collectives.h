// Host data-plane collectives over the TCP mesh.
//
// These are the TPU framework's analogue of the reference's Gloo CPU backend
// (gloo_operations.cc ring/halving-doubling): the eager/host path for
// metrics, object broadcast, and elastic state sync. The compiled XLA path
// (jax shard_map + psum over ICI) is the training fast path and never touches
// these.
//
// Algorithms: bandwidth-optimal ring allreduce (reduce-scatter + allgather,
// the same decomposition as NCCLAllreduce's ring), ring allgatherv (uneven
// first dims, reference: MPIAllgather's displacement math,
// collective_operations.cc allgather helpers), binomial-tree broadcast, and
// pairwise alltoallv (reference: MPI_Alltoallv, mpi_operations.cc).
#ifndef HVDTPU_COLLECTIVES_H
#define HVDTPU_COLLECTIVES_H

#include <cstdint>
#include <vector>

#include "common.h"
#include "transport.h"

namespace hvdtpu {
namespace collectives {

// In-place sum/min/max/prod allreduce of `count` elements.
Status RingAllreduce(Transport& t, void* buf, int64_t count, DataType dt,
                     ReduceOp op);

// Gather variable-sized byte blocks; `out` = blocks concatenated by rank.
Status AllgatherV(Transport& t, const void* in, int64_t in_bytes,
                  const std::vector<int64_t>& bytes_per_rank,
                  std::vector<char>* out);

// Broadcast `bytes` from `root` (binomial tree, log2(size) rounds).
Status Broadcast(Transport& t, void* buf, int64_t bytes, int root);

// Pairwise exchange: send_bytes[i] bytes go to rank i (taken sequentially
// from `in`), recv_bytes[i] land in `out` at rank-i offset.
Status AllToAllV(Transport& t, const void* in,
                 const std::vector<int64_t>& send_bytes,
                 const std::vector<int64_t>& recv_bytes,
                 std::vector<char>* out);

// dst[i] = dst[i] (op) src[i] — the reduction kernel under the ring
// (reference: the MPI op table + float16_sum custom op, half.h:142).
void ReduceInto(void* dst, const void* src, int64_t count, DataType dt,
                ReduceOp op);

// In-place multiply by `factor` (reference: ScaleBufferCPUImpl,
// collective_operations.h:89-125).
void ScaleBuffer(void* buf, int64_t count, DataType dt, double factor);

}  // namespace collectives
}  // namespace hvdtpu

#endif  // HVDTPU_COLLECTIVES_H
