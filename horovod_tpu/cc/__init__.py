"""ctypes binding to the native core (libhvdtpu.so).

Reference surface: ``horovod/common/basics.py:22-258`` wraps the C ABI the
same way (ctypes over operations.cc:685-889). The library is built on demand
with the in-tree Makefile (g++, no external deps) and cached under
``cc/build/``.

The native core is the host-side control plane: the rank-0 coordinator
negotiation loop, response cache, tensor fusion, stall inspector, timeline
writer, autotuner, and the TCP data plane for eager collectives between
worker processes. The compiled XLA path (ops/collective_ops.py) never
touches it.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_HERE, "build", "libhvdtpu.so")

_lib = None
_lib_lock = threading.Lock()

# DataType enum values — must match common.h.
_DTYPE_MAP = {
    np.dtype(np.uint8): 0,
    np.dtype(np.int8): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
    np.dtype(np.float16): 4,
    # bfloat16 handled separately (ml_dtypes), value 5
    np.dtype(np.float32): 6,
    np.dtype(np.float64): 7,
    np.dtype(np.bool_): 8,
}

try:  # bfloat16 numpy extension (ships with jax)
    import ml_dtypes

    _DTYPE_MAP[np.dtype(ml_dtypes.bfloat16)] = 5
except ImportError:  # pragma: no cover
    pass


class NativeBuildError(RuntimeError):
    pass


def _up_to_date() -> bool:
    srcdir = os.path.join(_HERE, "src")
    if not os.path.exists(_LIB_PATH):
        return False
    newest_src = max(
        os.path.getmtime(os.path.join(srcdir, f)) for f in os.listdir(srcdir))
    return os.path.getmtime(_LIB_PATH) >= newest_src


def build(force: bool = False) -> str:
    """Compile libhvdtpu.so if missing (or ``force``). Returns its path.

    Serialized across processes with an flock: N workers launched together
    on one host (the launcher's normal mode) must not race `make` on the
    same build directory.
    """
    if not force and _up_to_date():
        return _LIB_PATH
    import fcntl

    os.makedirs(os.path.join(_HERE, "build"), exist_ok=True)
    lock_path = os.path.join(_HERE, "build", ".lock")
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if not force and _up_to_date():  # another process built it
                return _LIB_PATH
            jobs = os.cpu_count() or 2
            if force:
                subprocess.run(["make", "-C", _HERE, "clean"],
                               capture_output=True, text=True)
            proc = subprocess.run(
                ["make", "-C", _HERE, f"-j{jobs}"],
                capture_output=True, text=True)
            if proc.returncode != 0:
                raise NativeBuildError(
                    f"native core build failed:\n{proc.stdout}\n{proc.stderr}")
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)
    return _LIB_PATH


def load():
    """Build (if needed) and load the native library. Thread-safe, cached."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        path = build()
        lib = ctypes.CDLL(path)  # CDLL releases the GIL during calls
        # Signatures.
        lib.hvdtpu_init.restype = ctypes.c_int
        lib.hvdtpu_shutdown.restype = None
        lib.hvdtpu_is_initialized.restype = ctypes.c_int
        lib.hvdtpu_controller_port.restype = ctypes.c_int
        lib.hvdtpu_clear_controller_port.restype = None
        lib.hvdtpu_last_error.restype = ctypes.c_char_p
        for f in ("rank", "size", "local_rank", "local_size", "cross_rank",
                  "cross_size"):
            getattr(lib, f"hvdtpu_{f}").restype = ctypes.c_int
        lib.hvdtpu_fusion_threshold.restype = ctypes.c_int64
        lib.hvdtpu_cycle_time_ms.restype = ctypes.c_double
        lib.hvdtpu_allreduce.restype = ctypes.c_int
        lib.hvdtpu_allreduce.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_double, ctypes.c_double]
        lib.hvdtpu_allgather.restype = ctypes.c_int
        lib.hvdtpu_allgather.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int]
        lib.hvdtpu_broadcast.restype = ctypes.c_int
        lib.hvdtpu_broadcast.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int,
            ctypes.c_int]
        lib.hvdtpu_alltoall.restype = ctypes.c_int
        lib.hvdtpu_alltoall.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
        lib.hvdtpu_join.restype = ctypes.c_int
        lib.hvdtpu_barrier.restype = ctypes.c_int
        lib.hvdtpu_poll.restype = ctypes.c_int
        lib.hvdtpu_poll.argtypes = [ctypes.c_int]
        lib.hvdtpu_wait.restype = ctypes.c_int
        lib.hvdtpu_wait.argtypes = [ctypes.c_int]
        lib.hvdtpu_handle_error.restype = ctypes.c_char_p
        lib.hvdtpu_handle_error.argtypes = [ctypes.c_int]
        lib.hvdtpu_result_bytes.restype = ctypes.c_int64
        lib.hvdtpu_result_bytes.argtypes = [ctypes.c_int]
        lib.hvdtpu_fetch.restype = None
        lib.hvdtpu_fetch.argtypes = [ctypes.c_int, ctypes.c_void_p]
        lib.hvdtpu_join_result.restype = ctypes.c_int
        lib.hvdtpu_join_result.argtypes = [ctypes.c_int]
        lib.hvdtpu_recv_splits.restype = ctypes.c_int
        lib.hvdtpu_recv_splits.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
        lib.hvdtpu_release.restype = None
        lib.hvdtpu_release.argtypes = [ctypes.c_int]
        lib.hvdtpu_start_timeline.restype = ctypes.c_int
        lib.hvdtpu_start_timeline.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.hvdtpu_stop_timeline.restype = ctypes.c_int
        lib.hvdtpu_autotune_active.restype = ctypes.c_int
        _lib = lib
        return _lib


def _np_dtype_code(arr: np.ndarray) -> int:
    code = _DTYPE_MAP.get(arr.dtype)
    if code is None:
        raise TypeError(f"dtype {arr.dtype} not supported by the native core")
    return code


def _shape_arg(arr: np.ndarray):
    shape = (ctypes.c_int64 * max(1, arr.ndim))(*(arr.shape or (1,)))
    return shape, arr.ndim if arr.ndim > 0 else 1


class NativeError(RuntimeError):
    """An error reported by the native core (precondition/consistency)."""


class NativeShutdownError(RuntimeError):
    """The core aborted (peer lost / shutdown) — maps to
    HorovodInternalError for the elastic path."""


class CoreContext:
    """Process-level handle to the native runtime.

    One per process, created by ``basics.init`` when the launcher env
    contract (HOROVOD_RANK/SIZE + controller address) is present.
    """

    # Reduce op codes (common.h ReduceOp).
    SUM, MIN, MAX, PRODUCT, ADASUM = 0, 1, 2, 3, 4

    def __init__(self, bound_port_callback=None) -> None:
        """``bound_port_callback(port)``: invoked from a watcher thread as
        soon as the rank-0 coordinator's control server has bound its
        (possibly OS-assigned, HOROVOD_CONTROLLER_PORT=0) port — while
        ``hvdtpu_init`` is still blocked accepting peers. The elastic
        rendezvous uses it to report the real port to the driver so peers
        can learn where to connect (race-free port allocation on the
        rank-0 host, not a driver-side guess)."""
        self._lib = load()
        watcher = None
        done = threading.Event()
        if bound_port_callback is not None:
            # Clear any previous incarnation's published port BEFORE the
            # watcher starts (still single-threaded here): a stale value
            # would be reported with the CURRENT world_id, sending every
            # peer to a dead listener until ELASTIC_TIMEOUT.
            self._lib.hvdtpu_clear_controller_port()

            def _watch():
                while not done.is_set():
                    port = self._lib.hvdtpu_controller_port()
                    if port > 0:
                        try:
                            bound_port_callback(port)
                        except Exception:
                            # A lost report must not kill the watcher
                            # silently; formation will time out and the
                            # elastic retry path takes over.
                            import logging

                            logging.exception(
                                "controller bound-port report failed")
                        return
                    done.wait(0.01)

            watcher = threading.Thread(target=_watch, daemon=True)
            watcher.start()
        try:
            if self._lib.hvdtpu_init() != 0:
                raise NativeError(
                    self._lib.hvdtpu_last_error().decode() or "init failed")
        finally:
            done.set()
            if watcher is not None:
                watcher.join(timeout=5.0)

    def controller_port(self) -> int:
        """Bound control-server port (0 unless this rank coordinates)."""
        return int(self._lib.hvdtpu_controller_port())

    # -- world queries --
    def rank(self) -> int: return self._lib.hvdtpu_rank()
    def size(self) -> int: return self._lib.hvdtpu_size()
    def local_rank(self) -> int: return self._lib.hvdtpu_local_rank()
    def local_size(self) -> int: return self._lib.hvdtpu_local_size()
    def cross_rank(self) -> int: return self._lib.hvdtpu_cross_rank()
    def cross_size(self) -> int: return self._lib.hvdtpu_cross_size()
    def fusion_threshold(self) -> int:
        return self._lib.hvdtpu_fusion_threshold()
    def cycle_time_ms(self) -> float:
        return self._lib.hvdtpu_cycle_time_ms()
    def autotune_active(self) -> bool:
        return bool(self._lib.hvdtpu_autotune_active())

    def close(self) -> None:
        self._lib.hvdtpu_shutdown()

    def is_initialized(self) -> bool:
        return bool(self._lib.hvdtpu_is_initialized())

    # -- handle plumbing --
    def _check_handle(self, handle: int, keepalive) -> "NativeHandle":
        if handle < 0:
            msg = self._lib.hvdtpu_last_error().decode()
            if not self._lib.hvdtpu_is_initialized():
                # The background loop aborted under us (peer died /
                # transport lost): elastic must see this as a rollbackable
                # HorovodInternalError, not a hard failure.
                raise NativeShutdownError(msg or "native core aborted")
            raise NativeError(msg)
        return NativeHandle(self._lib, handle, keepalive)

    # -- collectives (async; return NativeHandle) --
    def allreduce_async(self, arr: np.ndarray, name: str, op: int = SUM,
                        prescale: float = 1.0,
                        postscale: float = 1.0) -> "NativeHandle":
        arr = np.ascontiguousarray(arr)
        shape, ndim = _shape_arg(arr)
        h = self._lib.hvdtpu_allreduce(
            name.encode(), arr.ctypes.data_as(ctypes.c_void_p), shape, ndim,
            _np_dtype_code(arr), op, prescale, postscale)
        nh = self._check_handle(h, arr)
        nh.result_array = arr  # reduced in place
        return nh

    def allgather_async(self, arr: np.ndarray, name: str) -> "NativeHandle":
        arr = np.ascontiguousarray(arr)
        shape, ndim = _shape_arg(arr)
        h = self._lib.hvdtpu_allgather(
            name.encode(), arr.ctypes.data_as(ctypes.c_void_p), shape, ndim,
            _np_dtype_code(arr))
        nh = self._check_handle(h, arr)
        nh.gather_row_shape = arr.shape[1:] if arr.ndim else ()
        nh.gather_dtype = arr.dtype
        return nh

    def broadcast_async(self, arr: np.ndarray, name: str,
                        root: int) -> "NativeHandle":
        arr = np.ascontiguousarray(arr)
        shape, ndim = _shape_arg(arr)
        h = self._lib.hvdtpu_broadcast(
            name.encode(), arr.ctypes.data_as(ctypes.c_void_p), shape, ndim,
            _np_dtype_code(arr), root)
        nh = self._check_handle(h, arr)
        nh.result_array = arr  # received in place
        return nh

    def alltoall_async(self, arr: np.ndarray, name: str,
                       splits: Optional[Sequence[int]] = None
                       ) -> "NativeHandle":
        arr = np.ascontiguousarray(arr)
        shape, ndim = _shape_arg(arr)
        if splits is not None:
            sp = (ctypes.c_int64 * len(splits))(*splits)
            nsp = len(splits)
        else:
            sp, nsp = None, 0
        h = self._lib.hvdtpu_alltoall(
            name.encode(), arr.ctypes.data_as(ctypes.c_void_p), shape, ndim,
            _np_dtype_code(arr), sp, nsp)
        nh = self._check_handle(h, arr)
        nh.gather_row_shape = arr.shape[1:] if arr.ndim else ()
        nh.gather_dtype = arr.dtype
        return nh

    def join_async(self) -> "NativeHandle":
        return self._check_handle(self._lib.hvdtpu_join(), None)

    def barrier(self) -> None:
        self._check_handle(self._lib.hvdtpu_barrier(), None).wait()

    # -- timeline --
    def start_timeline(self, path: str, mark_cycles: bool = False) -> None:
        self._lib.hvdtpu_start_timeline(path.encode(), int(mark_cycles))

    def stop_timeline(self) -> None:
        self._lib.hvdtpu_stop_timeline()


class NativeHandle:
    """Async collective handle (reference: torch handle + synchronize,
    torch/mpi_ops.py:66-161)."""

    def __init__(self, lib, handle: int, keepalive) -> None:
        self._lib = lib
        self._handle = handle
        self._keepalive = keepalive  # pin the input buffer until done
        self.result_array: Optional[np.ndarray] = None
        self.gather_row_shape = ()
        self.gather_dtype = None
        self._released = False

    def poll(self) -> bool:
        return bool(self._lib.hvdtpu_poll(self._handle))

    def wait(self):
        """Block until done; return the result array (in-place ops) or the
        fetched output (allgather/alltoall)."""
        status = self._lib.hvdtpu_wait(self._handle)
        if status == 5:  # IN_PROGRESS cannot be returned by wait
            raise AssertionError("wait returned IN_PROGRESS")
        if status != 0:
            msg = self._lib.hvdtpu_handle_error(self._handle).decode()
            self.release()
            if status in (1, 3):  # UNKNOWN_ERROR / ABORTED
                raise NativeShutdownError(msg)
            raise NativeError(msg)
        try:
            # Cache post-completion metadata before the handle is released.
            self._join_result = self._lib.hvdtpu_join_result(self._handle)
            world = self._lib.hvdtpu_size()
            if world > 0:
                buf = (ctypes.c_int64 * world)()
                n = self._lib.hvdtpu_recv_splits(self._handle, buf, world)
                self._recv_splits = list(buf[:n])
            if self.result_array is not None:
                return self.result_array
            nbytes = self._lib.hvdtpu_result_bytes(self._handle)
            out = np.empty(nbytes, dtype=np.uint8)
            if nbytes > 0:
                self._lib.hvdtpu_fetch(
                    self._handle, out.ctypes.data_as(ctypes.c_void_p))
            arr = out.view(self.gather_dtype or np.uint8)
            row = tuple(self.gather_row_shape)
            if row:
                arr = arr.reshape((-1,) + row)
            return arr
        finally:
            self.release()

    def join_result(self) -> int:
        """Last rank to join (valid after wait)."""
        return getattr(self, "_join_result", -1)

    def recv_splits(self):
        """Alltoall rows received per rank (valid after wait)."""
        return getattr(self, "_recv_splits", [])

    def release(self) -> None:
        if not self._released:
            self._lib.hvdtpu_release(self._handle)
            self._released = True
            self._keepalive = None
