"""Elastic state for TensorFlow (reference: horovod/tensorflow/elastic.py).

``TensorFlowState`` snapshots a list of ``tf.Variable`` in memory
(save/restore) and syncs them from the new rank 0 after a world change;
``TensorFlowKerasState`` wraps a Keras model + optimizer the same way
(reference: TensorFlowKerasState, tensorflow/elastic.py:120+). Both carry
arbitrary picklable attrs through the ObjectState machinery, exactly like
the torch and JAX states (torch/elastic/state.py:27, elastic/state.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

try:
    import tensorflow as tf
except ImportError as e:  # pragma: no cover
    raise ImportError("horovod_tpu.tensorflow.elastic requires tensorflow"
                      ) from e

from ..elastic.state import ObjectState
from . import broadcast, broadcast_object


class TensorFlowState(ObjectState):
    """State of a list of tf.Variables + picklable attrs (reference:
    tensorflow/elastic.py TensorFlowState)."""

    def __init__(self, variables: Optional[List[tf.Variable]] = None,
                 **kwargs):
        self.variables = list(variables or [])
        self._saved_variables: List = []
        super().__init__(bcast_object=broadcast_object, **kwargs)
        self.save()

    def save(self) -> None:
        self._saved_variables = [v.numpy() for v in self.variables]
        super().save()

    def restore(self) -> None:
        for v, saved in zip(self.variables, self._saved_variables):
            v.assign(saved)
        super().restore()

    def sync(self) -> None:
        for i, v in enumerate(self.variables):
            v.assign(broadcast(v, root_rank=0, name=f"tf_state.var.{i}"))
        # Snapshot the broadcast values BEFORE ObjectState.sync(): its attr
        # sync ends in a polymorphic self.restore(), which re-assigns the
        # variables from _saved_variables — if that still held the pre-sync
        # local snapshot, the just-broadcast values would be clobbered.
        self._saved_variables = [v.numpy() for v in self.variables]
        super().sync()


class TensorFlowKerasState(TensorFlowState):
    """State of a Keras model (+ optional optimizer) + attrs (reference:
    tensorflow/elastic.py TensorFlowKerasState:120+)."""

    def __init__(self, model, optimizer=None, **kwargs):
        self.model = model
        self.optimizer = optimizer
        variables = list(model.variables)
        if optimizer is not None:
            variables += list(optimizer.variables)
        super().__init__(variables=variables, **kwargs)
