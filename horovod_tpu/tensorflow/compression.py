"""Gradient compression for TF tensors (reference:
horovod/tensorflow/compression.py:46-64 — fp16 cast before allreduce)."""

import tensorflow as tf


class Compressor:
    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype = tf.float16

    @classmethod
    def compress(cls, tensor):
        if tensor.dtype.is_floating:
            return tf.cast(tensor, cls.wire_dtype), tensor.dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor if ctx is None else tf.cast(tensor, ctx)


class FP16Compressor(_CastCompressor):
    wire_dtype = tf.float16


class BF16Compressor(_CastCompressor):
    """TPU-native wire format (fp32 exponent range, MXU dtype)."""
    wire_dtype = tf.bfloat16


class Compression:
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
