"""horovod.tensorflow.keras parity namespace (reference:
horovod/tensorflow/keras/__init__.py — same surface as horovod.keras, for
scripts that import the tf.keras-flavored path)."""

from ...keras import (  # noqa: F401
    Average,
    DistributedOptimizer,
    Sum,
    broadcast_global_variables,
    broadcast_model_state,
    callbacks,
    create_distributed_optimizer,
    cross_rank,
    cross_size,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    mpi_threads_supported,
    rank,
    shutdown,
    size,
)
