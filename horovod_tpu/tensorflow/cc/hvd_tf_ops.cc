// Native TensorFlow custom ops for horovod_tpu collectives.
//
// Reference: horovod/tensorflow/mpi_ops.cc:371-419 — the TF binding's
// collectives are C++ AsyncOpKernels, not Python callbacks. This library
// gives the TPU build the same property: inside a tf.function the
// collective is a real graph node dispatching straight into the shared
// native core's C ABI (libhvdtpu.so — the same handle table and
// controller the ctypes path uses), eliminating the ~1.1-1.4 ms
// tf.py_function boundary measured in examples/bench_tf_graph_overhead.py.
//
// Kernels are ASYNC (like the reference): ComputeAsync enqueues and
// returns the inter-op pool thread immediately; one background waiter
// thread polls outstanding handles and fires the done callbacks. A sync
// kernel would block a pool thread per collective — with per-gradient
// allreduce nodes outnumbering the pool and ranks scheduling disjoint
// subsets, no collective would ever have all ranks enqueued (cross-rank
// deadlock), which is precisely why the reference went async.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <functional>
#include <mutex>
#include <string>
#include <thread>
#include <utility>
#include <vector>

#include "tensorflow/core/framework/op.h"
#include "tensorflow/core/framework/op_kernel.h"
#include "tensorflow/core/framework/shape_inference.h"

// C ABI of the shared native core (cc/src/operations.cc). Declared here
// instead of a header on purpose: the ABI is the compatibility boundary,
// and this file must build against only libtensorflow headers + the .so.
extern "C" {
int hvdtpu_is_initialized(void);
int hvdtpu_size(void);
int hvdtpu_allreduce(const char* name, void* data, const int64_t* shape,
                     int ndim, int dtype, int op, double prescale,
                     double postscale);
int hvdtpu_allgather(const char* name, const void* data,
                     const int64_t* shape, int ndim, int dtype);
int hvdtpu_broadcast(const char* name, void* data, const int64_t* shape,
                     int ndim, int dtype, int root);
int hvdtpu_alltoall(const char* name, const void* data,
                    const int64_t* shape, int ndim, int dtype,
                    const int64_t* splits, int nsplits);
int hvdtpu_join(void);
int hvdtpu_join_result(int handle);
int hvdtpu_recv_splits(int handle, int64_t* out, int max);
int hvdtpu_poll(int handle);
int hvdtpu_wait(int handle);
const char* hvdtpu_handle_error(int handle);
int64_t hvdtpu_result_bytes(int handle);
void hvdtpu_fetch(int handle, void* out);
void hvdtpu_release(int handle);
const char* hvdtpu_last_error(void);
}

namespace {

using ::tensorflow::AsyncOpKernel;
using ::tensorflow::DataType;
using ::tensorflow::OpKernel;
using ::tensorflow::OpKernelConstruction;
using ::tensorflow::OpKernelContext;
using ::tensorflow::Tensor;
using ::tensorflow::TensorShape;
using ::tensorflow::errors::FailedPrecondition;
using ::tensorflow::errors::Internal;
using ::tensorflow::errors::InvalidArgument;

// DataType codes of the native core (cc/src/common.h DataType).
int NativeDtype(DataType dt) {
  switch (dt) {
    case ::tensorflow::DT_UINT8: return 0;
    case ::tensorflow::DT_INT8: return 1;
    case ::tensorflow::DT_INT32: return 2;
    case ::tensorflow::DT_INT64: return 3;
    case ::tensorflow::DT_HALF: return 4;
    case ::tensorflow::DT_BFLOAT16: return 5;
    case ::tensorflow::DT_FLOAT: return 6;
    case ::tensorflow::DT_DOUBLE: return 7;
    case ::tensorflow::DT_BOOL: return 8;
    default: return -1;
  }
}

std::vector<int64_t> ShapeVec(const Tensor& t) {
  std::vector<int64_t> shape(t.dims());
  for (int i = 0; i < t.dims(); ++i) shape[i] = t.dim_size(i);
  return shape;
}

// Background completion watcher: polls outstanding native handles and
// fires their callbacks off the TF inter-op pool (the role the
// per-operation MPI/NCCL event polling plays in the reference's
// AsyncOpKernels). One lazily-started thread per process.
class Waiter {
 public:
  static Waiter& Get() {
    static Waiter* w = new Waiter();  // leaked: outlives TF shutdown order
    return *w;
  }

  void Add(int handle, std::function<void(int)> cb) {
    {
      std::lock_guard<std::mutex> l(mu_);
      pending_.emplace_back(handle, std::move(cb));
      if (!running_) {
        running_ = true;
        std::thread(&Waiter::Loop, this).detach();
      }
    }
    cv_.notify_one();
  }

 private:
  void Loop() {
    // Block (condition-variable, tensor_queue.h:57) on the OLDEST handle,
    // then drain whatever else already completed. Polling all pending
    // handles in a spin loop would burn a core and starve the data-plane
    // threads (measured: 4 MB allreduce 16 ms spinning vs 6.7 ms
    // blocking); completion is roughly negotiation-ordered, so
    // head-of-line blocking costs only callback latency.
    std::vector<std::pair<int, std::function<void(int)>>> ready;
    for (;;) {
      std::pair<int, std::function<void(int)>> front;
      {
        std::unique_lock<std::mutex> l(mu_);
        cv_.wait(l, [this] { return !pending_.empty(); });
        front = std::move(pending_.front());
        pending_.erase(pending_.begin());
      }
      front.second(hvdtpu_wait(front.first));
      {
        std::lock_guard<std::mutex> l(mu_);
        for (size_t i = 0; i < pending_.size();) {
          if (hvdtpu_poll(pending_[i].first)) {
            ready.push_back(std::move(pending_[i]));
            pending_.erase(pending_.begin() +
                           static_cast<ptrdiff_t>(i));
          } else {
            ++i;
          }
        }
      }
      for (auto& r : ready) {
        r.second(hvdtpu_wait(r.first));  // returns immediately: done
      }
      ready.clear();
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::vector<std::pair<int, std::function<void(int)>>> pending_;
  bool running_ = false;
};

bool CheckEnqueued(OpKernelContext* ctx, int handle,
                   const AsyncOpKernel::DoneCallback& done) {
  if (handle >= 0) return true;
  ctx->CtxFailure(Internal("horovod_tpu enqueue failed: ",
                           std::string(hvdtpu_last_error())));
  done();
  return false;
}

void FinishSimple(OpKernelContext* ctx, int handle, int rc,
                  const AsyncOpKernel::DoneCallback& done) {
  if (rc != 0) {
    ctx->CtxFailure(Internal("horovod_tpu collective failed: ",
                             std::string(hvdtpu_handle_error(handle))));
  }
  hvdtpu_release(handle);
  done();
}

class HvdtpuAllreduceOp : public AsyncOpKernel {
 public:
  explicit HvdtpuAllreduceOp(OpKernelConstruction* ctx)
      : AsyncOpKernel(ctx) {
    OP_REQUIRES_OK(ctx, ctx->GetAttr("tensor_name", &tensor_name_));
    OP_REQUIRES_OK(ctx, ctx->GetAttr("reduce_op", &reduce_op_));
    OP_REQUIRES_OK(ctx, ctx->GetAttr("prescale", &prescale_));
    OP_REQUIRES_OK(ctx, ctx->GetAttr("postscale", &postscale_));
  }

  void ComputeAsync(OpKernelContext* ctx, DoneCallback done) override {
    OP_REQUIRES_ASYNC(ctx, hvdtpu_is_initialized(),
                      FailedPrecondition("horovod_tpu native core not "
                                         "initialized; call hvd.init()"),
                      done);
    const Tensor& input = ctx->input(0);
    int dtype = NativeDtype(input.dtype());
    OP_REQUIRES_ASYNC(ctx, dtype >= 0,
                      InvalidArgument("unsupported dtype for allreduce"),
                      done);
    // Forward the input buffer when it is last-use (no copy on the hot
    // per-gradient path); otherwise allocate + copy — the native core
    // reduces in place on the wire buffer either way, so the (possibly
    // shared) input is never clobbered.
    Tensor* output = nullptr;
    OP_REQUIRES_OK_ASYNC(
        ctx, ctx->forward_input_or_allocate_output({0}, 0, input.shape(),
                                                   &output),
        done);
    char* dst = const_cast<char*>(output->tensor_data().data());
    if (dst != input.tensor_data().data()) {
      std::memcpy(dst, input.tensor_data().data(),
                  input.tensor_data().size());
    }
    auto shape = ShapeVec(input);
    int handle = hvdtpu_allreduce(tensor_name_.c_str(), dst, shape.data(),
                                  static_cast<int>(shape.size()), dtype,
                                  reduce_op_, prescale_, postscale_);
    if (!CheckEnqueued(ctx, handle, done)) return;
    Waiter::Get().Add(handle, [ctx, handle, done](int rc) {
      FinishSimple(ctx, handle, rc, done);
    });
  }

 private:
  std::string tensor_name_;
  int reduce_op_;
  float prescale_;
  float postscale_;
};

class HvdtpuBroadcastOp : public AsyncOpKernel {
 public:
  explicit HvdtpuBroadcastOp(OpKernelConstruction* ctx)
      : AsyncOpKernel(ctx) {
    OP_REQUIRES_OK(ctx, ctx->GetAttr("tensor_name", &tensor_name_));
    OP_REQUIRES_OK(ctx, ctx->GetAttr("root_rank", &root_rank_));
  }

  void ComputeAsync(OpKernelContext* ctx, DoneCallback done) override {
    OP_REQUIRES_ASYNC(ctx, hvdtpu_is_initialized(),
                      FailedPrecondition("horovod_tpu native core not "
                                         "initialized; call hvd.init()"),
                      done);
    const Tensor& input = ctx->input(0);
    int dtype = NativeDtype(input.dtype());
    OP_REQUIRES_ASYNC(ctx, dtype >= 0,
                      InvalidArgument("unsupported dtype for broadcast"),
                      done);
    Tensor* output = nullptr;
    OP_REQUIRES_OK_ASYNC(
        ctx, ctx->forward_input_or_allocate_output({0}, 0, input.shape(),
                                                   &output),
        done);
    char* dst = const_cast<char*>(output->tensor_data().data());
    if (dst != input.tensor_data().data()) {
      std::memcpy(dst, input.tensor_data().data(),
                  input.tensor_data().size());
    }
    auto shape = ShapeVec(input);
    int handle = hvdtpu_broadcast(tensor_name_.c_str(), dst, shape.data(),
                                  static_cast<int>(shape.size()), dtype,
                                  root_rank_);
    if (!CheckEnqueued(ctx, handle, done)) return;
    Waiter::Get().Add(handle, [ctx, handle, done](int rc) {
      FinishSimple(ctx, handle, rc, done);
    });
  }

 private:
  std::string tensor_name_;
  int root_rank_;
};

class HvdtpuAllgatherOp : public AsyncOpKernel {
 public:
  explicit HvdtpuAllgatherOp(OpKernelConstruction* ctx)
      : AsyncOpKernel(ctx) {
    OP_REQUIRES_OK(ctx, ctx->GetAttr("tensor_name", &tensor_name_));
  }

  void ComputeAsync(OpKernelContext* ctx, DoneCallback done) override {
    OP_REQUIRES_ASYNC(ctx, hvdtpu_is_initialized(),
                      FailedPrecondition("horovod_tpu native core not "
                                         "initialized; call hvd.init()"),
                      done);
    const Tensor& input = ctx->input(0);
    OP_REQUIRES_ASYNC(ctx, input.dims() >= 1,
                      InvalidArgument("allgather needs rank >= 1 tensors"),
                      done);
    int dtype = NativeDtype(input.dtype());
    OP_REQUIRES_ASYNC(ctx, dtype >= 0,
                      InvalidArgument("unsupported dtype for allgather"),
                      done);
    auto shape = ShapeVec(input);
    int64_t row_elems = 1;
    for (size_t i = 1; i < shape.size(); ++i) row_elems *= shape[i];
    if (row_elems == 0) {
      // Zero-size rows: no payload travels, but the output's first dim is
      // still the SUM of every rank's (possibly ragged) dim 0 — gather
      // the per-rank row counts through a tiny companion collective
      // (sizing locally as dim0*world would be wrong and rank-divergent
      // for ragged inputs).
      int64_t rows = input.dim_size(0);
      int64_t one = 1;
      std::string rows_name = tensor_name_ + ".rows";
      int handle = hvdtpu_allgather(rows_name.c_str(), &rows, &one, 1,
                                    /*dtype=*/3 /* int64 */);
      if (!CheckEnqueued(ctx, handle, done)) return;
      TensorShape base_shape = input.shape();
      Waiter::Get().Add(handle, [ctx, handle, done,
                                 base_shape](int rc) mutable {
        if (rc != 0) {
          ctx->CtxFailure(
              Internal("horovod_tpu collective failed: ",
                       std::string(hvdtpu_handle_error(handle))));
          hvdtpu_release(handle);
          done();
          return;
        }
        int64_t n = hvdtpu_result_bytes(handle) /
                    static_cast<int64_t>(sizeof(int64_t));
        std::vector<int64_t> counts(static_cast<size_t>(n));
        hvdtpu_fetch(handle, counts.data());
        hvdtpu_release(handle);
        int64_t total = 0;
        for (int64_t c : counts) total += c;
        base_shape.set_dim(0, total);
        Tensor* output = nullptr;
        ::tensorflow::Status s =
            ctx->allocate_output(0, base_shape, &output);
        if (!s.ok()) ctx->CtxFailure(s);
        done();
      });
      return;
    }
    int handle = hvdtpu_allgather(
        tensor_name_.c_str(), input.tensor_data().data(), shape.data(),
        static_cast<int>(shape.size()), dtype);
    if (!CheckEnqueued(ctx, handle, done)) return;
    int64_t elem_bytes =
        static_cast<int64_t>(::tensorflow::DataTypeSize(input.dtype()));
    TensorShape base_shape = input.shape();
    Waiter::Get().Add(
        handle, [ctx, handle, done, base_shape, row_elems,
                 elem_bytes](int rc) mutable {
          if (rc != 0) {
            ctx->CtxFailure(
                Internal("horovod_tpu collective failed: ",
                         std::string(hvdtpu_handle_error(handle))));
            hvdtpu_release(handle);
            done();
            return;
          }
          // First dim is data-dependent (ragged per-rank rows): size the
          // output from the completed result.
          int64_t bytes = hvdtpu_result_bytes(handle);
          base_shape.set_dim(0, bytes / (row_elems * elem_bytes));
          Tensor* output = nullptr;
          ::tensorflow::Status s =
              ctx->allocate_output(0, base_shape, &output);
          if (!s.ok()) {
            ctx->CtxFailure(s);
          } else {
            hvdtpu_fetch(handle,
                         const_cast<char*>(output->tensor_data().data()));
          }
          hvdtpu_release(handle);
          done();
        });
  }

 private:
  std::string tensor_name_;
};

// Alltoall with optional uneven splits (reference: HorovodAlltoallOp,
// mpi_ops.cc:754-792). Outputs the concatenated received rows AND the
// per-rank received row counts; both first dims are data-dependent, so
// the kernel sizes them from the completed handle's recv_splits.
class HvdtpuAlltoallOp : public AsyncOpKernel {
 public:
  explicit HvdtpuAlltoallOp(OpKernelConstruction* ctx)
      : AsyncOpKernel(ctx) {
    OP_REQUIRES_OK(ctx, ctx->GetAttr("tensor_name", &tensor_name_));
  }

  void ComputeAsync(OpKernelContext* ctx, DoneCallback done) override {
    OP_REQUIRES_ASYNC(ctx, hvdtpu_is_initialized(),
                      FailedPrecondition("horovod_tpu native core not "
                                         "initialized; call hvd.init()"),
                      done);
    const Tensor& input = ctx->input(0);
    const Tensor& splits = ctx->input(1);
    OP_REQUIRES_ASYNC(ctx, input.dims() >= 1,
                      InvalidArgument("alltoall needs rank >= 1 tensors"),
                      done);
    OP_REQUIRES_ASYNC(ctx, splits.dims() == 1,
                      InvalidArgument("splits must be a vector"), done);
    int dtype = NativeDtype(input.dtype());
    OP_REQUIRES_ASYNC(ctx, dtype >= 0,
                      InvalidArgument("unsupported dtype for alltoall"),
                      done);
    int nsplits = static_cast<int>(splits.dim_size(0));
    const int64_t* splits_ptr =
        nsplits > 0 ? splits.flat<int64_t>().data() : nullptr;
    if (nsplits > 0) {
      int64_t total = 0;
      for (int i = 0; i < nsplits; ++i) {
        int64_t s = splits_ptr[i];
        OP_REQUIRES_ASYNC(ctx, s >= 0,
                          InvalidArgument("splits entries must be >= 0"),
                          done);
        total += s;
      }
      OP_REQUIRES_ASYNC(
          ctx, total == input.dim_size(0),
          InvalidArgument("splits must sum to the tensor's first dim"),
          done);
    }
    auto shape = ShapeVec(input);
    int64_t row_elems = 1;
    for (size_t i = 1; i < shape.size(); ++i) row_elems *= shape[i];
    int64_t elem_bytes =
        static_cast<int64_t>(::tensorflow::DataTypeSize(input.dtype()));
    int handle = hvdtpu_alltoall(
        tensor_name_.c_str(), input.tensor_data().data(), shape.data(),
        static_cast<int>(shape.size()), dtype, splits_ptr, nsplits);
    if (!CheckEnqueued(ctx, handle, done)) return;
    TensorShape base_shape = input.shape();
    Waiter::Get().Add(
        handle, [ctx, handle, done, base_shape, row_elems,
                 elem_bytes](int rc) mutable {
          if (rc != 0) {
            ctx->CtxFailure(
                Internal("horovod_tpu collective failed: ",
                         std::string(hvdtpu_handle_error(handle))));
            hvdtpu_release(handle);
            done();
            return;
          }
          int world = hvdtpu_size();
          std::vector<int64_t> rs(static_cast<size_t>(world), 0);
          int got = hvdtpu_recv_splits(handle, rs.data(), world);
          int64_t total_rows = 0;
          for (int i = 0; i < got; ++i) total_rows += rs[static_cast<
              size_t>(i)];
          base_shape.set_dim(0, total_rows);
          Tensor* output = nullptr;
          ::tensorflow::Status s =
              ctx->allocate_output(0, base_shape, &output);
          if (s.ok() && total_rows * row_elems * elem_bytes > 0) {
            hvdtpu_fetch(handle,
                         const_cast<char*>(output->tensor_data().data()));
          }
          Tensor* out_splits = nullptr;
          if (s.ok()) {
            s = ctx->allocate_output(
                1, TensorShape({static_cast<int64_t>(got)}), &out_splits);
          }
          if (!s.ok()) {
            ctx->CtxFailure(s);
          } else {
            for (int i = 0; i < got; ++i) {
              out_splits->flat<int64_t>()(i) = rs[static_cast<size_t>(i)];
            }
          }
          hvdtpu_release(handle);
          done();
        });
  }

 private:
  std::string tensor_name_;
};

// Join barrier (reference: HorovodJoinOp, mpi_ops.cc:604-634): signals
// this rank has no more collectives this round; resolves when every rank
// joined, outputting the last-joined rank.
class HvdtpuJoinOp : public AsyncOpKernel {
 public:
  explicit HvdtpuJoinOp(OpKernelConstruction* ctx) : AsyncOpKernel(ctx) {}

  void ComputeAsync(OpKernelContext* ctx, DoneCallback done) override {
    OP_REQUIRES_ASYNC(ctx, hvdtpu_is_initialized(),
                      FailedPrecondition("horovod_tpu native core not "
                                         "initialized; call hvd.init()"),
                      done);
    int handle = hvdtpu_join();
    if (!CheckEnqueued(ctx, handle, done)) return;
    Waiter::Get().Add(handle, [ctx, handle, done](int rc) {
      if (rc != 0) {
        ctx->CtxFailure(Internal("horovod_tpu join failed: ",
                                 std::string(hvdtpu_handle_error(handle))));
        hvdtpu_release(handle);
        done();
        return;
      }
      int last = hvdtpu_join_result(handle);
      Tensor* out = nullptr;
      ::tensorflow::Status s =
          ctx->allocate_output(0, TensorShape({}), &out);
      if (!s.ok()) {
        ctx->CtxFailure(s);
      } else {
        out->scalar<int32_t>()() = last;
      }
      hvdtpu_release(handle);
      done();
    });
  }
};

// Runtime world size: lets Average divide by the CURRENT size instead of
// a trace-time constant (elastic world changes reuse cached concrete
// functions; a baked divisor would silently mis-average).
class HvdtpuSizeOp : public OpKernel {
 public:
  explicit HvdtpuSizeOp(OpKernelConstruction* ctx) : OpKernel(ctx) {}

  void Compute(OpKernelContext* ctx) override {
    Tensor* out = nullptr;
    OP_REQUIRES_OK(ctx, ctx->allocate_output(0, TensorShape({}), &out));
    out->scalar<int32_t>()() =
        hvdtpu_is_initialized() ? hvdtpu_size() : 1;
  }
};

}  // namespace

REGISTER_OP("HvdtpuAllreduce")
    .Attr("T: type")
    .Attr("tensor_name: string")
    .Attr("reduce_op: int")
    .Attr("prescale: float = 1.0")
    .Attr("postscale: float = 1.0")
    .Input("tensor: T")
    .Output("output: T")
    .SetShapeFn([](::tensorflow::shape_inference::InferenceContext* c) {
      c->set_output(0, c->input(0));
      return ::tensorflow::OkStatus();
    });

REGISTER_OP("HvdtpuBroadcast")
    .Attr("T: type")
    .Attr("tensor_name: string")
    .Attr("root_rank: int")
    .Input("tensor: T")
    .Output("output: T")
    .SetShapeFn([](::tensorflow::shape_inference::InferenceContext* c) {
      c->set_output(0, c->input(0));
      return ::tensorflow::OkStatus();
    });

REGISTER_OP("HvdtpuAllgather")
    .Attr("T: type")
    .Attr("tensor_name: string")
    .Input("tensor: T")
    .Output("output: T")
    .SetShapeFn([](::tensorflow::shape_inference::InferenceContext* c) {
      ::tensorflow::shape_inference::ShapeHandle out;
      TF_RETURN_IF_ERROR(c->ReplaceDim(
          c->input(0), 0, c->UnknownDim(), &out));
      c->set_output(0, out);
      return ::tensorflow::OkStatus();
    });

REGISTER_OP("HvdtpuAlltoall")
    .Attr("T: type")
    .Attr("tensor_name: string")
    .Input("tensor: T")
    .Input("splits: int64")
    .Output("output: T")
    .Output("received_splits: int64")
    .SetShapeFn([](::tensorflow::shape_inference::InferenceContext* c) {
      ::tensorflow::shape_inference::ShapeHandle out;
      TF_RETURN_IF_ERROR(c->ReplaceDim(
          c->input(0), 0, c->UnknownDim(), &out));
      c->set_output(0, out);
      c->set_output(1, c->Vector(c->UnknownDim()));
      return ::tensorflow::OkStatus();
    });

REGISTER_OP("HvdtpuJoin")
    .Output("last_joined_rank: int32")
    .SetIsStateful()
    .SetShapeFn([](::tensorflow::shape_inference::InferenceContext* c) {
      c->set_output(0, c->Scalar());
      return ::tensorflow::OkStatus();
    });

REGISTER_OP("HvdtpuSize")
    .Output("size: int32")
    .SetShapeFn([](::tensorflow::shape_inference::InferenceContext* c) {
      c->set_output(0, c->Scalar());
      return ::tensorflow::OkStatus();
    });

REGISTER_KERNEL_BUILDER(Name("HvdtpuAllreduce").Device(
                            ::tensorflow::DEVICE_CPU),
                        HvdtpuAllreduceOp);
REGISTER_KERNEL_BUILDER(Name("HvdtpuBroadcast").Device(
                            ::tensorflow::DEVICE_CPU),
                        HvdtpuBroadcastOp);
REGISTER_KERNEL_BUILDER(Name("HvdtpuAllgather").Device(
                            ::tensorflow::DEVICE_CPU),
                        HvdtpuAllgatherOp);
REGISTER_KERNEL_BUILDER(Name("HvdtpuAlltoall").Device(
                            ::tensorflow::DEVICE_CPU),
                        HvdtpuAlltoallOp);
REGISTER_KERNEL_BUILDER(Name("HvdtpuJoin").Device(
                            ::tensorflow::DEVICE_CPU),
                        HvdtpuJoinOp);
REGISTER_KERNEL_BUILDER(Name("HvdtpuSize").Device(
                            ::tensorflow::DEVICE_CPU),
                        HvdtpuSizeOp);
