"""Cross-rank synchronized batch normalization for TensorFlow/Keras.

Reference: ``horovod/tensorflow/sync_batch_norm.py:65`` —
``SyncBatchNormalization`` overrides the moment computation so batch
statistics are computed over the *global* batch (allgather of per-rank
mean/var there). Here the equivalent sufficient statistics (sum, sum of
squares, count) ride one fused allreduce — the same reduction the torch
binding uses (horovod_tpu/torch/sync_batch_norm.py) and the TPU-shaped
version of the math.

Built on Keras 3 (`keras.layers.BatchNormalization` subclass): inference
and world-size-1 fall straight through to the stock layer; in distributed
training the normalization moments and the moving-average updates use the
cross-rank statistics, so every rank normalizes identically.
"""

from __future__ import annotations

try:
    import tensorflow as tf
except ImportError as e:  # pragma: no cover
    raise ImportError(
        "horovod_tpu.tensorflow.SyncBatchNormalization requires tensorflow"
    ) from e

import keras

from . import Sum, allreduce, size


class SyncBatchNormalization(keras.layers.BatchNormalization):
    """BatchNormalization with cross-rank synchronized statistics."""

    def call(self, inputs, training=None, mask=None):
        if not training or size() == 1:
            return super().call(inputs, training=training, mask=mask)

        x = tf.cast(inputs, self.compute_dtype)
        ndim = len(x.shape)
        axis = self.axis if self.axis >= 0 else ndim + self.axis
        red_axes = [i for i in range(ndim) if i != axis]
        c = x.shape[axis]

        n_local = tf.cast(tf.size(x) / c, tf.float32)
        local_sum = tf.cast(tf.reduce_sum(x, axis=red_axes), tf.float32)
        local_sqsum = tf.cast(
            tf.reduce_sum(tf.square(x), axis=red_axes), tf.float32)
        stats = tf.concat(
            [local_sum, local_sqsum, tf.reshape(n_local, [1])], axis=0)
        stats = allreduce(stats, op=Sum, name=f"sync_bn.{self.name}.stats")
        count = stats[-1]
        mean = stats[:c] / count
        var = stats[c:2 * c] / count - tf.square(mean)

        # Moving averages from the global moments. The stock Keras layer
        # feeds the *biased* batch variance (tf.nn.moments output) into the
        # moving estimate, so the synchronized layer must too — world-1 must
        # match keras.layers.BatchNormalization exactly.
        m = tf.cast(self.momentum, tf.float32)
        self.moving_mean.assign(
            tf.cast(self.moving_mean, tf.float32) * m + mean * (1.0 - m))
        self.moving_variance.assign(
            tf.cast(self.moving_variance, tf.float32) * m
            + var * (1.0 - m))

        shape = [1] * ndim
        shape[axis] = c
        mean_b = tf.reshape(tf.cast(mean, self.compute_dtype), shape)
        inv = tf.reshape(
            tf.cast(tf.math.rsqrt(var + self.epsilon), self.compute_dtype),
            shape)
        out = (x - mean_b) * inv
        if self.scale:
            out = out * tf.reshape(tf.cast(self.gamma, self.compute_dtype),
                                   shape)
        if self.center:
            out = out + tf.reshape(tf.cast(self.beta, self.compute_dtype),
                                   shape)
        return tf.cast(out, inputs.dtype)
