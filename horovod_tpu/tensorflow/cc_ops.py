"""Build + load the native TF custom-op library.

Reference: ``horovod/tensorflow/mpi_ops.py`` loads ``mpi_lib`` (the
compiled AsyncOpKernels of mpi_ops.cc:371-419). Here the kernels
(``cc/hvd_tf_ops.cc``) call the shared native core's C ABI directly, so
graph-mode collectives are real TF graph nodes — no ``tf.py_function``
boundary (~1.1-1.4 ms/collective, see examples/bench_tf_graph_overhead.py).

The library is compiled on first use with TensorFlow's advertised flags
(``tf.sysconfig``), linked against ``libhvdtpu.so`` (built on demand, the
same .so the ctypes path loads — one handle table, one controller), and
cached. Every failure mode degrades to the py_function path, loudly via a
one-time warning: a missing compiler must never break training.
"""

from __future__ import annotations

import logging
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "cc", "hvd_tf_ops.cc")
_OUT = os.path.join(_HERE, "cc", "build", "hvd_tf_ops.so")

_lock = threading.Lock()
_lib = None
_load_attempted = False


def _up_to_date() -> bool:
    """Fresh relative to BOTH our source and libhvdtpu.so — a rebuilt
    native core may have changed the C ABI, and a stale kernel calling a
    changed symbol reads garbage arguments."""
    if not os.path.exists(_OUT):
        return False
    newest_dep = os.path.getmtime(_SRC)
    from ..cc import _LIB_PATH

    if os.path.exists(_LIB_PATH):
        newest_dep = max(newest_dep, os.path.getmtime(_LIB_PATH))
    return os.path.getmtime(_OUT) >= newest_dep


def _build() -> str:
    import fcntl

    import tensorflow as tf

    from ..cc import build as build_core

    core_so = build_core()  # libhvdtpu.so (shared with the ctypes path)
    os.makedirs(os.path.dirname(_OUT), exist_ok=True)
    lock_path = _OUT + ".lock"
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if _up_to_date():
                return _OUT
            # Compile to a temp path and os.replace (atomic): load() reads
            # _OUT without the lock, and a reader must see either the old
            # complete library or the new one — never a half-written ELF.
            tmp_out = _OUT + f".tmp.{os.getpid()}"
            cmd = (["g++", "-shared", "-fPIC", "-O2", "-o", tmp_out, _SRC]
                   + tf.sysconfig.get_compile_flags()
                   + tf.sysconfig.get_link_flags()
                   + [core_so, f"-Wl,-rpath,{os.path.dirname(core_so)}"])
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"TF custom-op build failed:\n{proc.stderr[-2000:]}")
            os.replace(tmp_out, _OUT)
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)
    return _OUT


def load() -> Optional[object]:
    """The loaded op library module (with .hvdtpu_allreduce etc.), or None
    when building/loading is impossible here (logged once)."""
    global _lib, _load_attempted
    with _lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        try:
            import tensorflow as tf

            path = _OUT if _up_to_date() else _build()
            _lib = tf.load_op_library(path)
        except Exception as e:
            logging.warning(
                "horovod_tpu: native TF ops unavailable (%s); graph-mode "
                "collectives fall back to tf.py_function", e)
            _lib = None
        return _lib
