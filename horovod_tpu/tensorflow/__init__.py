"""TensorFlow binding for horovod_tpu.

Reference surface: ``horovod/tensorflow/__init__.py`` — eager/tf.function
collectives, ``DistributedGradientTape`` (tensorflow/__init__.py:511-576),
``DistributedOptimizer`` (435-508), ``broadcast_variables`` (functions.py:47),
``broadcast_object`` (functions.py:59-134).

TPU-native redesign: TF is a host-side framework here (the compiled TPU
path is JAX); TF tensors ride the same native C++ controller + TCP data
plane as the eager JAX and torch APIs, so TF, torch, and JAX processes can
participate in one world. Gradient aggregation happens in eager Python (the
reference's AsyncOpKernels + background thread are unnecessary: the native
core already overlaps fused collectives internally).
"""

from __future__ import annotations

import io
from typing import Any, List, Optional

import numpy as np

try:
    import tensorflow as tf
except ImportError as e:  # pragma: no cover
    raise ImportError(
        "horovod_tpu.tensorflow requires tensorflow; install it or use the "
        "JAX (horovod_tpu) / PyTorch (horovod_tpu.torch) surfaces") from e

from ..common import basics as _basics
from ..common.basics import (  # noqa: F401
    cross_rank,
    cross_size,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    mpi_threads_supported,
    shutdown,
)
from ..ops import collective_ops as C
from ..ops.collective_ops import ReduceOp

Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT


def rank() -> int:
    return int(_basics.rank())


def size() -> int:
    return int(_basics.size())


# --------------------------------------------------------------------------
# Collective ops on tf.Tensors (reference: tensorflow/mpi_ops.py). Sync
# eager ops; usable inside tf.function through tf.py_function.
# --------------------------------------------------------------------------


def _to_numpy(tensor) -> np.ndarray:
    if isinstance(tensor, tf.Tensor) or isinstance(tensor, tf.Variable):
        arr = tensor.numpy()
    else:
        arr = np.asarray(tensor)
    if arr.dtype == np.dtype("O"):
        raise TypeError(f"unsupported tensor dtype {arr.dtype}")
    return np.ascontiguousarray(arr)


def _eager_world():
    return C._eager_ctx()


def allreduce(tensor, average=None, name=None, compression=None,
              op=None, prescale_factor=1.0, postscale_factor=1.0):
    """Synchronous, differentiable allreduce (reference:
    tensorflow/__init__.py:53-153; gradient = allreduce of the gradient).

    In graph mode the op is a native graph node (cc/hvd_tf_ops.cc, the
    reference's mpi_ops.cc:371-419 analogue) when the custom-op library is
    available; ``tf.py_function`` is the fallback."""
    from .compression import Compression

    rop = _normalize_op(average, op)
    compression = compression or Compression.none

    @tf.custom_gradient
    def _op(x):
        y = _graph_or_eager_allreduce(x, rop, name, prescale_factor,
                                      postscale_factor, compression)

        def grad(dy):
            return _graph_or_eager_allreduce(dy, rop, None, prescale_factor,
                                             postscale_factor, compression)
        return y, grad

    return _op(tf.convert_to_tensor(tensor))


def _op_code(ctrl, rop):
    """Native ReduceOp code for a binding-level op constant (single source
    shared by the eager and graph paths)."""
    return {Sum: ctrl.SUM, Average: ctrl.SUM, Min: ctrl.MIN,
            Max: ctrl.MAX, Product: ctrl.PRODUCT, Adasum: ctrl.ADASUM}[rop]


def _graph_name(x, name, default):
    """Stable per-node tensor name derived from the traced graph (the
    reference keys on TF node names the same way): deterministic from the
    graph STRUCTURE, so ranks tracing the same function get identical
    name sequences even when one rank retraces more often — a global
    trace-time counter would desync and hang cross-rank negotiation."""
    return x.graph.unique_name(name or default)


def _graph_or_eager_allreduce(x, rop, name, prescale_factor,
                              postscale_factor, compression):
    if tf.executing_eagerly():
        return _allreduce_eager(x, rop, name, prescale_factor,
                                postscale_factor, compression)
    lib = _native_ops()
    if lib is None:
        # py_function fallback — but the tensor NAME must still be the
        # graph-structural one: a rank that failed to build the custom op
        # must negotiate under the same names as its native-op peers, or
        # the mixed-path world deadlocks at the first collective.
        tname = _graph_name(x, name, "hvd.allreduce")
        return _maybe_py_function(
            lambda t: _allreduce_eager(t, rop, tname, prescale_factor,
                                       postscale_factor, compression),
            x, x.dtype, x.shape)
    ctrl, _ = _eager_world()
    wire, cctx = compression.compress(x)
    out = lib.hvdtpu_allreduce(
        wire, tensor_name=_graph_name(x, name, "hvd.allreduce"),
        reduce_op=_op_code(ctrl, rop), prescale=float(prescale_factor),
        postscale=float(postscale_factor))
    if rop == Average:
        # Divide by the RUNTIME world size (HvdtpuSize node): a trace-time
        # constant would keep averaging by the old size when an elastic
        # world change reuses a cached concrete function.
        size_now = lib.hvdtpu_size()
        if out.dtype.is_floating:
            out = out / tf.cast(size_now, out.dtype)
        else:
            out = tf.cast(
                tf.cast(out, tf.float64) / tf.cast(size_now, tf.float64),
                out.dtype)
    return compression.decompress(out, cctx)


def _native_ops():
    """The custom-op library, only when the native core is live (a kernel
    enqueue without a controller would fail; world-1 jobs have none and
    keep the py_function identity path)."""
    if C._controller() is None:
        return None
    from . import cc_ops

    return cc_ops.load()


def _maybe_py_function(fn, x, out_dtype, out_shape):
    """Run ``fn`` eagerly, or via tf.py_function when tracing inside a
    tf.function (reference analogue: the AsyncOpKernel boundary in
    tensorflow/mpi_ops.cc — host-side work escapes the graph; the native
    custom op replaces this wherever cc_ops builds)."""
    if tf.executing_eagerly():
        return fn(x)
    y = tf.py_function(fn, [x], out_dtype)
    if out_shape is not None:
        y.set_shape(out_shape)
    return y


def _allreduce_eager(x, rop, name, prescale_factor, postscale_factor,
                     compression):
    ctrl, world = _eager_world()
    compressed, cctx = compression.compress(x)
    if world == 1:
        scale = prescale_factor * postscale_factor
        out = compressed if scale == 1.0 else compressed * scale
    else:
        post = postscale_factor / world if rop == Average \
            else postscale_factor
        arr = ctrl.allreduce_async(
            _to_numpy(compressed), C._eager_name(name, "tf.allreduce"),
            op=_op_code(ctrl, rop), prescale=float(prescale_factor),
            postscale=float(post)).wait()
        out = tf.convert_to_tensor(arr)
    return compression.decompress(out, cctx)


def _normalize_op(average, op):
    """Reference: handle_average_backwards_compatibility."""
    if average is not None and op is not None:
        raise ValueError("both average and op are specified")
    if op is not None:
        return op
    if average is False:
        return Sum
    return Average


def allgather(tensor, name=None):
    """First-dim concatenation across ranks (reference:
    tensorflow/mpi_ops.py allgather); ragged dim 0 allowed. Graph mode
    uses the native custom op when available."""
    x = tf.convert_to_tensor(tensor)
    eager = tf.executing_eagerly()
    lib = None if eager else _native_ops()
    if lib is not None:
        return lib.hvdtpu_allgather(
            x, tensor_name=_graph_name(x, name, "hvd.allgather"))
    # Graph fallback uses the graph-structural name so mixed native/
    # py_function worlds stay name-aligned (see _graph_or_eager_allreduce)
    tname = name if eager else _graph_name(x, name, "hvd.allgather")

    def fn(t):
        ctrl, world = _eager_world()
        if world == 1:
            return tf.identity(t)
        arr = ctrl.allgather_async(
            _to_numpy(t),
            C._eager_name(tname, "tf.allgather") if eager
            else tname).wait()
        return tf.convert_to_tensor(arr)

    out_shape = tf.TensorShape([None]).concatenate(x.shape[1:]) \
        if x.shape.rank else x.shape
    return _maybe_py_function(fn, x, x.dtype, out_shape)


def broadcast(tensor, root_rank=0, name=None):
    """Reference: tensorflow/mpi_ops.py broadcast. Graph mode uses the
    native custom op when available."""
    x = tf.convert_to_tensor(tensor)
    eager = tf.executing_eagerly()
    lib = None if eager else _native_ops()
    if lib is not None:
        return lib.hvdtpu_broadcast(
            x, tensor_name=_graph_name(x, name, "hvd.broadcast"),
            root_rank=root_rank)
    tname = name if eager else _graph_name(x, name, "hvd.broadcast")

    def fn(t):
        ctrl, world = _eager_world()
        if world == 1:
            return tf.identity(t)
        arr = ctrl.broadcast_async(
            _to_numpy(t),
            C._eager_name(tname, "tf.broadcast") if eager
            else tname, root=root_rank).wait()
        return tf.convert_to_tensor(arr)

    return _maybe_py_function(fn, x, x.dtype, x.shape)


def alltoall(tensor, splits=None, name=None):
    """Returns (output, received_splits) (reference:
    tensorflow/mpi_ops.py alltoall). Graph mode uses the native custom op
    (reference analogue: HorovodAlltoallOp, mpi_ops.cc:754-792) when
    available, ``tf.py_function`` otherwise."""
    x = tf.convert_to_tensor(tensor)
    if tf.executing_eagerly():
        ctrl, world = _eager_world()
        if world == 1:
            n = int(x.shape[0]) if x.shape.rank else 1
            return tf.identity(x), tf.constant([n], dtype=tf.int32)
        sp = None if splits is None else [int(s) for s in np.asarray(splits)]
        h = ctrl.alltoall_async(_to_numpy(x),
                                C._eager_name(name, "tf.alltoall"),
                                splits=sp)
        out = h.wait()
        return (tf.convert_to_tensor(out),
                tf.constant(np.asarray(h.recv_splits(), dtype=np.int32)))

    sp64 = (tf.zeros([0], tf.int64) if splits is None
            else tf.cast(tf.convert_to_tensor(splits), tf.int64))
    tname = _graph_name(x, name, "hvd.alltoall")
    lib = _native_ops()
    if lib is not None:
        out, rs = lib.hvdtpu_alltoall(x, sp64, tensor_name=tname)
        return out, tf.cast(rs, tf.int32)

    def fn(t, s):
        ctrl, world = _eager_world()
        if world == 1:
            # Mirror the eager path's rank-0 guard: a scalar input has
            # no dim 0 to split (degenerate, but the two paths must
            # agree on what they accept).
            n = int(t.shape[0]) if t.shape.rank else 1
            return (tf.identity(t), tf.constant([n], dtype=tf.int32))
        spl = ([int(v) for v in s.numpy()] if int(s.shape[0]) else None)
        h = ctrl.alltoall_async(_to_numpy(t), tname, splits=spl)
        out = h.wait()
        return (tf.convert_to_tensor(out),
                tf.constant(np.asarray(h.recv_splits(), dtype=np.int32)))

    out, rs = tf.py_function(fn, [x, sp64], [x.dtype, tf.int32])
    if x.shape.rank:
        out.set_shape(tf.TensorShape([None]).concatenate(x.shape[1:]))
    rs.set_shape([None])
    return out, rs


def join():
    """Reference: tensorflow/mpi_ops.py join. Eagerly returns the
    last-joined rank as a python int; inside a tf.function it is a graph
    node (reference analogue: HorovodJoinOp, mpi_ops.cc:604-634)
    producing an int32 scalar tensor."""
    if tf.executing_eagerly():
        return C.join()
    lib = _native_ops()
    if lib is not None:
        return lib.hvdtpu_join()
    y = tf.py_function(lambda: tf.constant(C.join(), tf.int32), [],
                       tf.int32)
    y.set_shape([])
    return y


# --------------------------------------------------------------------------
# Variable/state broadcast (reference: tensorflow/functions.py)
# --------------------------------------------------------------------------


def broadcast_variables(variables, root_rank: int = 0) -> None:
    """Assign every variable its root-rank value, in place (reference:
    functions.py:47-57 broadcast_variables)."""
    for i, var in enumerate(variables):
        name = getattr(var, "name", None) or f"var.{i}"
        var.assign(broadcast(var, root_rank, name=f"bv.{name}"))


def broadcast_object(obj: Any, root_rank: int = 0,
                     name: Optional[str] = None) -> Any:
    """Pickle on root, ship as a byte tensor (reference:
    functions.py:59-134)."""
    import cloudpickle

    name = name or "tf.broadcast_object"
    ctrl, world = _eager_world()
    if world == 1:
        return obj
    if rank() == root_rank:
        payload = np.frombuffer(cloudpickle.dumps(obj),
                                dtype=np.uint8).copy()
    else:
        payload = np.empty(0, dtype=np.uint8)
    sz = ctrl.broadcast_async(
        np.array([len(payload)], dtype=np.int64), f"{name}.sz",
        root=root_rank).wait()
    buf = payload if rank() == root_rank \
        else np.zeros(int(sz[0]), dtype=np.uint8)
    data = ctrl.broadcast_async(buf, f"{name}.data", root=root_rank).wait()
    return cloudpickle.loads(bytes(np.asarray(data)))


def allgather_object(obj: Any, name: Optional[str] = None) -> List[Any]:
    """Reference: functions.py:136-177."""
    import cloudpickle

    name = name or "tf.allgather_object"
    ctrl, world = _eager_world()
    if world == 1:
        return [obj]
    payload = np.frombuffer(cloudpickle.dumps(obj), dtype=np.uint8).copy()
    sizes = ctrl.allgather_async(
        np.array([len(payload)], dtype=np.int64), f"{name}.sz").wait()
    data = ctrl.allgather_async(payload, f"{name}.data").wait()
    out, off = [], 0
    for s in np.asarray(sizes).tolist():
        out.append(cloudpickle.loads(bytes(np.asarray(
            data[off:off + s]))))
        off += s
    return out


# --------------------------------------------------------------------------
# DistributedGradientTape (reference: tensorflow/__init__.py:511-576)
# --------------------------------------------------------------------------


class _DistributedGradientTape:
    def __init__(self, tape, compression, op, prescale_factor,
                 postscale_factor, sparse_as_dense=False):
        self._tape = tape
        self._compression = compression
        self._op = op
        self._prescale = prescale_factor
        self._postscale = postscale_factor
        self._sparse_as_dense = sparse_as_dense

    def __getattr__(self, item):
        return getattr(self._tape, item)

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *args):
        return self._tape.__exit__(*args)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources, output_gradients)
        return _allreduce_grads(grads, self._compression, self._op,
                                self._prescale, self._postscale,
                                self._sparse_as_dense)


def _runtime_world():
    """The eager-collective (process) world as a value resolved at
    EXECUTION time in graph mode — native HvdtpuSize node when the op
    library is live, py_function otherwise — so elastic world changes
    reaching a cached concrete function see the new size. Eagerly it is
    just the current int. (Deliberately NOT named _eager_world: the
    module-local ``_eager_world()`` returns a (ctrl, world) tuple.)"""
    if tf.executing_eagerly():
        return C._eager_world()
    lib = _native_ops()
    if lib is not None:
        return lib.hvdtpu_size()
    world = tf.py_function(lambda: np.int32(C._eager_world()), [], tf.int32)
    world.set_shape([])
    return world


def _allreduce_grads(grads, compression, op, prescale, postscale,
                     sparse_as_dense=False):
    out = []
    for i, g in enumerate(grads):
        if isinstance(g, tf.IndexedSlices) and sparse_as_dense:
            # Densify escape hatch (reference: tensorflow/__init__.py:
            # 260,299,437 — what users reach for when the allgather of a
            # large embedding gradient blows memory): one dense
            # allreduce instead of a size-x values+indices gather.
            g = tf.convert_to_tensor(g)
        if g is None:
            out.append(None)
        elif isinstance(g, tf.IndexedSlices):
            # Sparse path: allgather values+indices (reference:
            # tensorflow/__init__.py:91-107). Average divides the gathered
            # values by world size so sparse grads match dense scaling
            # (reference :107); Adasum is rejected for sparse grads
            # (reference :87-90).
            if op == Adasum:
                raise NotImplementedError(
                    "The Adasum reduction does not support sparse "
                    "(IndexedSlices) gradients.")
            values = allgather(g.values, name=f"grad.{i}.values")
            if op == Average:
                # Divide by the world the allgather actually spanned:
                # the host-path collectives run over the PROCESS world,
                # which under single-controller SPMD differs from
                # size()'s device world (reference :107 divides by
                # hvd.size() because its gather always spans it). The
                # divisor must be RUNTIME-evaluated: a trace-time
                # constant keeps averaging by the old size when an
                # elastic world change reuses a cached tf.function.
                values = values / tf.cast(_runtime_world(), values.dtype)
            out.append(tf.IndexedSlices(
                values,
                allgather(g.indices, name=f"grad.{i}.indices"),
                dense_shape=g.dense_shape))
        else:
            out.append(allreduce(
                g, op=op, name=f"grad.{i}", compression=compression,
                prescale_factor=prescale, postscale_factor=postscale))
    return out


def DistributedGradientTape(gradtape, compression=None, op=Average,
                            prescale_factor=1.0, postscale_factor=1.0,
                            sparse_as_dense=False):
    """Wrap tf.GradientTape so gradient() allreduces (reference:
    tensorflow/__init__.py:530-576). ``sparse_as_dense`` densifies
    IndexedSlices gradients before reduction (reference :260)."""
    from .compression import Compression

    return _DistributedGradientTape(
        gradtape, compression or Compression.none, op, prescale_factor,
        postscale_factor, sparse_as_dense)


def DistributedOptimizer(optimizer, name=None, compression=None, op=Average,
                         prescale_factor=1.0, postscale_factor=1.0,
                         backward_passes_per_step=1,
                         sparse_as_dense=False):
    """Wrap a Keras optimizer so apply_gradients() averages gradients
    across ranks first (reference: tensorflow/__init__.py:435-508 +
    _keras/__init__.py:25-85 create_distributed_optimizer).
    ``sparse_as_dense`` densifies IndexedSlices gradients before
    reduction (reference :437)."""
    from .compression import Compression
    from .._keras import create_distributed_optimizer

    return create_distributed_optimizer(
        optimizer, compression or Compression.none, op, prescale_factor,
        postscale_factor, sparse_as_dense=sparse_as_dense)


# Late imports: these modules import names from this package
# (reference keeps the same layout: tensorflow/sync_batch_norm.py and
# tensorflow/elastic.py are sibling modules re-exported here).
from .sync_batch_norm import SyncBatchNormalization  # noqa: F401,E402
from . import elastic  # noqa: F401,E402
