"""Small MNIST convnet — the reference's smoke-test model
(examples/tensorflow2/tensorflow2_mnist.py, examples/pytorch/pytorch_mnist.py:
two convs + two dense layers)."""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class MnistNet(nn.Module):
    """Conv(32) → Conv(64) → maxpool → Dense(128) → Dense(10), matching the
    shape of the reference example models."""

    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(32, (3, 3), dtype=self.dtype)(x))
        x = nn.relu(nn.Conv(64, (3, 3), dtype=self.dtype)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128, dtype=self.dtype)(x))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x
