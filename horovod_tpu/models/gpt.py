"""GPT-style decoder-only transformer with first-class sequence parallelism.

No counterpart exists in the reference (it is a CNN-era data-parallel
framework, SURVEY §5.7); this is the long-context flagship of the TPU
build. TPU-first choices:

* bfloat16 activations, fp32 params/softmax statistics (MXU-native),
* pre-norm blocks, GELU MLP, learned positional embeddings,
* attention is pluggable: ``dense`` (single chip), ``flash`` (Pallas
  flash kernel, :mod:`horovod_tpu.ops.flash_attention` — same numerics,
  no [T, T] HBM round-trip), ``ring`` (ppermute ring over the mesh axis —
  O(T/n) sequence memory/chip), or ``ulysses`` (all-to-all head exchange,
  local attention runs the flash kernel) from
  :mod:`horovod_tpu.parallel.sequence`,
* optional ``remat`` per block (jax.checkpoint) to trade FLOPs for HBM,
* everything is static-shaped, scan-free python loops over layers so XLA
  fuses each block independently.

Under sequence parallelism, ``__call__`` must run inside ``jax.shard_map``
with ``tokens`` sharded on the sequence axis; positional embeddings are
offset by the chip's shard index automatically.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

from ..common.basics import LOCAL_AXIS
from ..parallel import sequence as seqpar


def _tp_size(cfg) -> int:
    """Bound size of the tensor-parallel axis (1 outside shard_map)."""
    return seqpar._axis_size(cfg.tp_axis) if cfg.tp_axis else 1


@dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 32000
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    d_ff: int = 3072
    max_seq_len: int = 2048
    dtype: jnp.dtype = jnp.bfloat16
    attention: str = "dense"    # dense | flash | ring | flash_ring | ulysses
    seq_axis: str = LOCAL_AXIS        # mesh axis carrying the sequence
    remat: bool = False
    embed_init_std: float = 0.02
    # Megatron-style tensor parallelism: when set and bound inside
    # shard_map, attention heads and d_ff shard over this mesh axis —
    # qkv/fc1 are column-parallel (local output slices), proj/fc2 are
    # row-parallel (partial sums combined by one psum per block half).
    # Parameters must be the LOCAL shards; see
    # horovod_tpu.parallel.tensor.tp_shard_params for slicing a dense
    # checkpoint. Composes with DP on the other axis (and with the
    # non-ring attention modes).
    tp_axis: Optional[str] = None
    # Mixture-of-Experts: > 0 replaces every block's dense MLP with a
    # Switch-MoE FFN of this many (GLOBAL) experts; with ep_axis bound
    # inside shard_map, experts shard over that mesh axis and tokens are
    # exchanged by all-to-all (parallel/expert.py). The router's
    # load-balancing aux losses are sown under
    # intermediates/.../moe_aux_loss.
    moe_experts: int = 0
    ep_axis: Optional[str] = None
    moe_capacity_factor: float = 1.25
    # Ragged (uneven-alltoall) expert dispatch: pools expert capacity
    # across senders instead of a per-(sender, expert) quota (reference
    # uneven-splits path: operations.cc:1031-1092).
    # moe_pair_capacity_factor bounds each (sender -> rank) block at
    # factor * N / n rows.
    moe_ragged: bool = False
    moe_pair_capacity_factor: float = 2.0
    # Fused residual-add + LayerNorm Pallas kernel for each block's
    # second LN (ops/layer_norm.py): saves one HBM round trip of the
    # [B, T, C] stream per block when XLA does not fuse the add into the
    # LN reductions. Param tree is identical either way (ln2/scale,
    # ln2/bias), so checkpoints are interchangeable.
    fused_ln: bool = False
    # Return the final-LayerNorm hidden states [B, T, d_model] instead of
    # logits — for a fused LM-head loss (ops/softmax_xent.py) that never
    # materializes the [N, vocab] logits. Parameters are identical either
    # way (wte is created for the embedding lookup regardless).
    return_hidden: bool = False
    # Decode-time KV paging (horovod_tpu/serve/kv_cache.py): when set, the
    # cache's pages stripe round-robin over this mesh axis — contexts
    # longer than one host's page pool — and decode attention merges
    # per-rank flash partials with the ring-attention combine. Must be
    # disjoint from tp_axis (same constraint as seq_axis: the stripe would
    # otherwise rotate between ranks holding different heads). Only
    # affects the cache path (__call__ with cache=); training modes are
    # governed by ``attention``/``seq_axis`` as before.
    kv_ring_axis: Optional[str] = None


class _Attention(nn.Module):
    cfg: GPTConfig

    @nn.compact
    def __call__(self, x, decode=None):
        cfg = self.cfg
        B, T, C = x.shape
        tp = _tp_size(cfg)
        if cfg.num_heads % tp:
            raise ValueError(
                f"num_heads {cfg.num_heads} not divisible by "
                f"tp axis size {tp}")
        if tp > 1 and cfg.attention in ("ring", "flash_ring", "ulysses"):
            tp_axes = ({cfg.tp_axis} if isinstance(cfg.tp_axis, str)
                       else set(cfg.tp_axis))
            seq_axes = ({cfg.seq_axis} if isinstance(cfg.seq_axis, str)
                        else set(cfg.seq_axis))
            if tp_axes & seq_axes:
                # Same mesh axis cannot carry both head shards and
                # sequence shards — the ring would rotate k/v between
                # ranks holding DIFFERENT heads and silently produce
                # garbage. Distinct axes (e.g. tp=local, seq=cross)
                # compose fine.
                raise ValueError(
                    f"tp_axis {cfg.tp_axis!r} overlaps seq_axis "
                    f"{cfg.seq_axis!r} under attention="
                    f"{cfg.attention!r}; use disjoint mesh axes")
        H = cfg.num_heads // tp   # local heads (column-parallel qkv)
        D = C // cfg.num_heads
        qkv = nn.Dense(3 * H * D, dtype=cfg.dtype, name="qkv",
                       kernel_init=nn.initializers.normal(0.02))(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, D)
        k = k.reshape(B, T, H, D)
        v = v.reshape(B, T, H, D)
        if decode is not None:
            # Paged single-token decode (serve/kv_cache.py): append this
            # step's k/v to the layer's page pool, attend over the slot's
            # cached pages. The TRAINING attention mode (dense/flash/ring)
            # is irrelevant here — the cache IS the sequence; tp (local
            # heads + row-parallel proj psum) composes unchanged.
            from ..serve import kv_cache as kvlib

            cache, meta, layer = decode
            if meta.write_page.ndim == 2:
                # Windowed verify/prefill chunk: all T = W positions'
                # k/v land in one scatter, per-query masks keep each
                # position blind to its future.
                cache = kvlib.append_layer_kv(cache, layer, k, v, meta)
            else:
                cache = kvlib.append_layer_kv(cache, layer, k[:, 0],
                                              v[:, 0], meta)
            out = kvlib.paged_attention(
                q, cache.k[layer], cache.v[layer], cache.page_table,
                meta.attend_len, ring_axis=cfg.kv_ring_axis)
            out = out.reshape(B, T, H * D)
            out = nn.Dense(C, dtype=cfg.dtype, name="proj",
                           kernel_init=nn.initializers.normal(
                               0.02 / (2 * cfg.num_layers) ** 0.5))(out)
            out = lax.psum(out, cfg.tp_axis) if tp > 1 else out
            return out, cache
        if cfg.attention == "ring":
            out = seqpar.ring_attention(q, k, v, axis=cfg.seq_axis,
                                        causal=True)
        elif cfg.attention == "flash_ring":
            from ..ops.flash_attention import flash_ring_attention

            out = flash_ring_attention(q, k, v, axis=cfg.seq_axis,
                                       causal=True)
        elif cfg.attention == "ulysses":
            from ..ops.flash_attention import flash_attention

            out = seqpar.ulysses_attention(
                q, k, v, axis=cfg.seq_axis, causal=True,
                attn_fn=lambda qf, kf, vf: flash_attention(
                    qf, kf, vf, causal=True))
        elif cfg.attention == "flash":
            from ..ops.flash_attention import flash_attention

            out = flash_attention(q, k, v, causal=True)
        elif cfg.attention == "dense":
            out = seqpar.dense_attention(q, k, v, causal=True)
        else:
            raise ValueError(
                f"unknown attention {cfg.attention!r}; expected "
                f"dense | flash | ring | flash_ring | ulysses")
        out = out.reshape(B, T, H * D)
        out = nn.Dense(C, dtype=cfg.dtype, name="proj",
                       kernel_init=nn.initializers.normal(
                           0.02 / (2 * cfg.num_layers) ** 0.5))(out)
        # Row-parallel: each rank holds the rows for its heads; partial
        # results sum across the tp axis (biases are sliced 1/tp so the
        # psum restores the dense model's single bias).
        return lax.psum(out, cfg.tp_axis) if tp > 1 else out


class _MLP(nn.Module):
    cfg: GPTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        tp = _tp_size(cfg)
        if cfg.d_ff % tp:
            raise ValueError(
                f"d_ff {cfg.d_ff} not divisible by tp axis size {tp}")
        x = nn.Dense(cfg.d_ff // tp, dtype=cfg.dtype,
                     kernel_init=nn.initializers.normal(0.02))(x)
        x = nn.gelu(x)
        x = nn.Dense(cfg.d_model, dtype=cfg.dtype,
                     kernel_init=nn.initializers.normal(
                         0.02 / (2 * cfg.num_layers) ** 0.5))(x)
        return lax.psum(x, cfg.tp_axis) if tp > 1 else x


class _FusedLNAdd(nn.Module):
    """Residual add + LayerNorm in one Pallas pass (ops/layer_norm.py).

    Param names/shapes match ``nn.LayerNorm`` exactly (scale, bias under
    this module's name) so dense checkpoints load into fused models and
    back."""

    cfg: GPTConfig

    @nn.compact
    def __call__(self, x, sub):
        from ..ops.layer_norm import ln_residual

        C = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (C,),
                           jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (C,),
                          jnp.float32)
        # eps matches flax nn.LayerNorm's default (1e-6) so fused and
        # unfused models are numerically interchangeable.
        y, h = ln_residual(x, sub, scale, bias, 1e-6)
        return y.astype(self.cfg.dtype), h


class _Block(nn.Module):
    cfg: GPTConfig

    @nn.compact
    def __call__(self, x, decode=None):
        cfg = self.cfg
        if decode is not None:
            # Decode path: same params, plain (unfused) pre-norm blocks —
            # fused_ln targets the [B, T, C] training stream and is
            # numerically interchangeable (identical eps/params), so a
            # T=1 decode never pays the Pallas call.
            cache, meta, layer = decode
            attn_out, cache = _Attention(cfg, name="attn")(
                nn.LayerNorm(dtype=cfg.dtype, name="ln1")(x),
                decode=(cache, meta, layer))
            x = x + attn_out
            if cfg.moe_experts:
                from ..parallel.expert import SwitchMoE

                ffn = SwitchMoE(
                    num_experts=cfg.moe_experts, d_ff=cfg.d_ff,
                    capacity_factor=cfg.moe_capacity_factor,
                    ep_axis=cfg.ep_axis, dtype=cfg.dtype,
                    ragged=cfg.moe_ragged,
                    pair_capacity_factor=cfg.moe_pair_capacity_factor,
                    name="moe")
            else:
                ffn = _MLP(cfg, name="mlp")
            x = x + ffn(nn.LayerNorm(dtype=cfg.dtype, name="ln2")(x))
            return x, cache
        attn_out = _Attention(cfg, name="attn")(
            nn.LayerNorm(dtype=cfg.dtype, name="ln1")(x))
        if not cfg.fused_ln:
            x = x + attn_out
        if cfg.moe_experts:
            from ..parallel.expert import SwitchMoE

            ffn = SwitchMoE(num_experts=cfg.moe_experts, d_ff=cfg.d_ff,
                            capacity_factor=cfg.moe_capacity_factor,
                            ep_axis=cfg.ep_axis, dtype=cfg.dtype,
                            ragged=cfg.moe_ragged,
                            pair_capacity_factor=cfg.moe_pair_capacity_factor,
                            name="moe")
        else:
            ffn = _MLP(cfg, name="mlp")
        if cfg.fused_ln:
            # One pass: h = x + attn_out (the stream continues through
            # h), m = ln2(h) — the Pallas kernel's HBM saving.
            m, h = _FusedLNAdd(cfg, name="ln2")(x, attn_out)
            return h + ffn(m)
        x = x + ffn(nn.LayerNorm(dtype=cfg.dtype, name="ln2")(x))
        return x


class GPT(nn.Module):
    """Decoder-only LM. Returns logits [B, T_local, vocab]; with
    ``cache=`` (a :class:`horovod_tpu.serve.kv_cache.KVCache`), runs one
    paged decode step instead — see :meth:`__call__`."""

    cfg: GPTConfig

    @nn.compact
    def __call__(self, tokens, cache=None, active=None):
        """Training/prefill forward, or — when ``cache`` is given — ONE
        continuous-batching decode step (serve/engine.py):

        ``tokens [S]`` (or ``[S, 1]``) holds the step's token per batch
        slot, written at position ``cache.seq_lens[s]`` of every layer's
        page pool; the returned logits ``[S, vocab]`` predict each slot's
        NEXT token, with attention over all cached positions including
        the one just written — so feeding a prompt token-by-token yields
        logits identical (within dtype tolerance) to the full-context
        forward at that position. ``active [S]`` bool masks dead slots
        (their writes hit the null page and their cursor stays put).
        Returns ``(logits, new_cache)``.
        """
        cfg = self.cfg
        if cache is not None:
            return self._decode_step(tokens, cache, active)
        B, T_local = tokens.shape
        wte = self.param("wte", nn.initializers.normal(cfg.embed_init_std),
                         (cfg.vocab_size, cfg.d_model), jnp.float32)
        wpe = self.param("wpe", nn.initializers.normal(cfg.embed_init_std),
                         (cfg.max_seq_len, cfg.d_model), jnp.float32)
        if cfg.attention in ("ring", "flash_ring", "ulysses"):
            # Sequence is sharded: offset positions by the shard index.
            n_shards = seqpar._axis_size(cfg.seq_axis)
            pos = seqpar.seq_shard_positions(T_local, cfg.seq_axis)
        else:
            n_shards = 1
            pos = jnp.arange(T_local)
        if T_local * n_shards > cfg.max_seq_len:
            # JAX gathers clamp out-of-bounds indices under jit, which
            # would silently reuse the last positional embedding — fail
            # loudly instead.
            raise ValueError(
                f"global sequence length {T_local * n_shards} exceeds "
                f"max_seq_len={cfg.max_seq_len}")
        x = (wte[tokens] + wpe[pos][None]).astype(cfg.dtype)
        block = _Block
        if cfg.remat:
            block = nn.remat(_Block)
        for i in range(cfg.num_layers):
            x = block(cfg, name=f"h{i}")(x)
        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_f")(x)
        if cfg.return_hidden:
            return x
        # Tied embedding head. Inputs in the compute dtype (bf16 feeds the
        # MXU at full rate — the fp32 head matmul is ~18% of model FLOPs at
        # half throughput), accumulation and logits in fp32 for a stable
        # softmax.
        return jnp.einsum("btc,vc->btv", x, wte.astype(cfg.dtype),
                          preferred_element_type=jnp.float32)

    def _decode_step(self, tokens, cache, active):
        from ..serve import kv_cache as kvlib

        cfg = self.cfg
        tp = _tp_size(cfg)
        if cfg.kv_ring_axis and cfg.tp_axis and tp > 1:
            ring = ({cfg.kv_ring_axis} if isinstance(cfg.kv_ring_axis, str)
                    else set(cfg.kv_ring_axis))
            tps = ({cfg.tp_axis} if isinstance(cfg.tp_axis, str)
                   else set(cfg.tp_axis))
            if ring & tps:
                raise ValueError(
                    f"kv_ring_axis {cfg.kv_ring_axis!r} overlaps tp_axis "
                    f"{cfg.tp_axis!r}: the page stripe would rotate "
                    f"between ranks holding different heads; use "
                    f"disjoint mesh axes")
        # Windowed step (speculative verify / chunked prefill): a 2-D
        # ``active [S, W]`` batches W tokens per slot through ONE apply.
        # Per-query attend lengths (``seq_lens + w + 1``) keep window
        # position w blind to positions > w, so the logits are
        # bit-identical to W chained single-token steps.
        windowed = active is not None and jnp.ndim(active) == 2
        if not windowed and tokens.ndim == 2:
            tokens = tokens[:, 0]
        S = tokens.shape[0]
        if active is None:
            active = jnp.ones((S,), bool)
        wte = self.param("wte", nn.initializers.normal(cfg.embed_init_std),
                         (cfg.vocab_size, cfg.d_model), jnp.float32)
        wpe = self.param("wpe", nn.initializers.normal(cfg.embed_init_std),
                         (cfg.max_seq_len, cfg.d_model), jnp.float32)
        # One shared write cursor for every layer; the clip keeps the
        # embedding gather in-bounds on inactive slots (the engine bounds
        # live positions by max_seq_len/pages_per_slot at admission).
        meta = kvlib.step_meta(cache, active,
                               page_size=int(cache.k.shape[2]),
                               ring_axis=cfg.kv_ring_axis)
        if windowed:
            W = tokens.shape[1]
            pos = jnp.clip(cache.seq_lens[:, None] + jnp.arange(W)[None],
                           0, cfg.max_seq_len - 1)
            x = (wte[tokens] + wpe[pos]).astype(cfg.dtype)
        else:
            pos = jnp.clip(cache.seq_lens, 0, cfg.max_seq_len - 1)
            x = (wte[tokens] + wpe[pos]).astype(cfg.dtype)[:, None, :]
        block = _Block
        if cfg.remat:
            block = nn.remat(_Block)
        for i in range(cfg.num_layers):
            x, cache = block(cfg, name=f"h{i}")(x, decode=(cache, meta, i))
        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_f")(x)
        if windowed:
            logits = jnp.einsum("swc,vc->swv", x, wte.astype(cfg.dtype),
                                preferred_element_type=jnp.float32)
        else:
            logits = jnp.einsum("sc,vc->sv", x[:, 0],
                                wte.astype(cfg.dtype),
                                preferred_element_type=jnp.float32)
        return logits, kvlib.advance(cache, meta)


def gpt_small(**overrides) -> GPTConfig:
    """GPT-2-small scale (124M)."""
    return GPTConfig(**{**dict(num_layers=12, num_heads=12, d_model=768,
                               d_ff=3072), **overrides})


def gpt_tiny(**overrides) -> GPTConfig:
    """Test/dryrun scale."""
    return GPTConfig(**{**dict(vocab_size=128, num_layers=2, num_heads=4,
                               d_model=64, d_ff=128, max_seq_len=256),
                        **overrides})
