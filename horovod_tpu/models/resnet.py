"""ResNet family (v1.5) in flax linen — the benchmark workhorse.

The reference benchmarks ResNet-50/101 via tf.keras.applications
(examples/tensorflow2/tensorflow2_synthetic_benchmark.py:34,
docs/benchmarks.rst:27-43). This is an independent TPU-first implementation:

* NHWC layout (TPU-native; convolutions tile onto the MXU in NHWC),
* bfloat16 compute with float32 parameters and batch-norm statistics,
* no data-dependent control flow — the whole apply is one XLA program.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    """Basic two-conv residual block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckBlock(nn.Module):
    """1x1 → 3x3 → 1x1 bottleneck block (ResNet-50/101/152), v1.5 style:
    the stride lives on the 3x3 conv."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """ResNet v1.5 over NHWC inputs."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    act: Callable = nn.relu
    sync_batch_norm: bool = False
    # MLPerf-style stem: fold 2x2 spatial blocks into channels and run a
    # 4x4/1 conv instead of the 7x7/2 conv. A 3-channel 7x7 stem pads its
    # contraction dim to the MXU's 8 lanes (~3/8 utilization); the folded
    # stem contracts over 4*4*12 = 192 channels at full tile utilization.
    # Same receptive field and output shape (modulo the SAME-padding
    # alignment, one pixel at the border) — a standard benchmark-legal
    # model variant, off by default.
    space_to_depth: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype,
                                 padding="SAME")
        if self.sync_batch_norm:
            from ..parallel.sync_batch_norm import SyncBatchNorm

            norm = functools.partial(SyncBatchNorm,
                                     use_running_average=not train,
                                     momentum=0.9, epsilon=1e-5,
                                     dtype=self.dtype)
        else:
            norm = functools.partial(nn.BatchNorm,
                                     use_running_average=not train,
                                     momentum=0.9, epsilon=1e-5,
                                     dtype=self.dtype)

        x = x.astype(self.dtype)
        if self.space_to_depth:
            n, h, w, c = x.shape
            if h % 2 or w % 2:
                raise ValueError(
                    f"space_to_depth needs even spatial dims, got {h}x{w}")
            x = x.reshape(n, h // 2, 2, w // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
                n, h // 2, w // 2, 4 * c)
            x = conv(self.num_filters, (4, 4), (1, 1),
                     name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2),
                     name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i,
                                   strides=strides, conv=conv, norm=norm,
                                   act=self.act)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2],
                             block_cls=ResNetBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=ResNetBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BottleneckBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3],
                              block_cls=BottleneckBlock)
ResNet152 = functools.partial(ResNet, stage_sizes=[3, 8, 36, 3],
                              block_cls=BottleneckBlock)
