"""Model zoo for benchmarks and examples (reference benchmarks use
tf.keras.applications ResNet50 et al., docs/benchmarks.rst)."""

from .gpt import GPT, GPTConfig, gpt_small, gpt_tiny  # noqa: F401
from .mnist import MnistNet  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
