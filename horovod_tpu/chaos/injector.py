"""The injection engine behind :func:`horovod_tpu.chaos.inject`.

Call sites across the framework name their hazard points and call
``chaos.inject(point, **ctx)``; with no plan active that is a single flag
check. With a plan active, the injector deterministically decides whether
any rule fires (see :mod:`horovod_tpu.chaos.plan` for the decision
contract), performs ``crash``/``drop``/``delay``/``stall``/``preempt``
inline, and hands ``dup``/``flap`` back to the call site to interpret.

``preempt`` models a spot/maintenance eviction: the injector delivers
SIGTERM to its own process, after an optional ``secs`` grace delay (on a
daemon thread, so the training step that tripped the rule keeps running
through its grace window — exactly how cloud preemption notices arrive).
What happens next is up to the installed SIGTERM handler; under the
flight recorder + resilience supervisor that is a deadline-budgeted
priority snapshot, then a flight dump, then signal re-delivery.

Registered injection points (ctx keys each site provides):

====================== ====================================================
``network.client.send``   RPC client about to dial (service, addr, attempt)
``network.server.handle`` RPC server about to dispatch (service)
``bootstrap.rendezvous``  worker asking the driver/KV for its world
``driver.slot_grant``     driver answering a GetSlotRequest (host, rank)
``driver.worker_exit``    driver processing a worker exit (host, code)
``discovery.update``      HostManager polling the discovery source
``collective.eager``      eager-path collective about to run
====================== ====================================================

Every fired fault bumps a ``chaos.<action>`` counter
(:mod:`horovod_tpu.common.counters`) — and therefore a Timeline instant
event — and is appended to the injector's ``schedule`` log, the artifact
the determinism tests compare across runs.
"""

from __future__ import annotations

import logging
import os
import random
import signal
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..common import counters
from .plan import (
    ACTION_CRASH,
    ACTION_DELAY,
    ACTION_DROP,
    ACTION_DUP,
    ACTION_FLAP,
    ACTION_PREEMPT,
    ACTION_STALL,
    FaultPlan,
)

INJECTION_POINTS = (
    "network.client.send",
    "network.server.handle",
    "bootstrap.rendezvous",
    "driver.slot_grant",
    "driver.worker_exit",
    "discovery.update",
    "collective.eager",
)


class FaultInjectedError(ConnectionError):
    """An injected ``drop``. Subclasses ConnectionError so the hardened
    retry paths treat it exactly like a real network failure."""


def _identity() -> str:
    """This process's worker identity tag (``host:local_rank``), matched
    against rule ``where`` globs. Falls back to '*'-matchable defaults in
    the driver/launcher process."""
    host = os.environ.get("HOROVOD_HOSTNAME", "")
    local_rank = os.environ.get("HOROVOD_LOCAL_RANK", "")
    return f"{host}:{local_rank}" if host else "driver"


class ChaosInjector:
    """Evaluates one :class:`FaultPlan`. Thread-safe; per-rule counters
    advance under a lock, the fault actions run outside it."""

    def __init__(self, plan: FaultPlan) -> None:
        self.plan = plan
        self._lock = threading.Lock()
        # Per-rule RNG streams keyed off (seed, rule index): the decision
        # sequence of one rule is independent of how invocations of OTHER
        # rules interleave with it.
        self._rngs = [random.Random(f"{plan.seed}:{i}")
                      for i in range(len(plan.specs))]
        self._seen = [0] * len(plan.specs)   # matching invocations
        self._fired = [0] * len(plan.specs)  # rule hits
        #: [(point, where, action, rule_index, hit_number)] — the schedule.
        self.schedule: List[Tuple[str, str, str, int, int]] = []

    def decide(self, point: str, where: str) -> Optional[Tuple[int, str]]:
        """(rule index, action) of the first rule that fires, else None."""
        with self._lock:
            for i, spec in enumerate(self.plan.specs):
                if not spec.matches(point, where):
                    continue
                self._seen[i] += 1
                k = self._seen[i]
                if k <= spec.after:
                    continue
                if (k - spec.after - 1) % spec.every != 0:
                    continue
                # Draw even when prob == 1 so adding `prob=` to a rule
                # never shifts the stream of a later decision.
                draw = self._rngs[i].random()
                if draw >= spec.prob:
                    continue
                if spec.max_count is not None and \
                        self._fired[i] >= spec.max_count:
                    continue
                self._fired[i] += 1
                self.schedule.append((point, where, spec.action, i,
                                      self._fired[i]))
                return i, spec.action
        return None

    def inject(self, point: str, where: Optional[str] = None,
               **ctx) -> Optional[str]:
        """Evaluate ``point``; perform inline actions; return the action
        name for caller-interpreted ones (``dup``/``flap``), else None."""
        where = _identity() if where is None else where
        hit = self.decide(point, where)
        if hit is None:
            return None
        i, action = hit
        spec = self.plan.specs[i]
        counters.increment(f"chaos.{action}",
                           attrs={"point": point, "where": where, **ctx})
        logging.warning(
            f"chaos: injecting {action} at {point} (where={where}, "
            f"rule #{i}, ctx={ctx})")
        if action == ACTION_CRASH:
            # A hard death: no atexit, no stack unwind — what a kernel
            # panic or OOM-kill looks like to the rest of the job. The
            # flight recorder dumps FIRST (monitor/flight.py): a real
            # kernel panic leaves no black box, but the simulated one
            # must, so postmortems of chaos runs can name the crashing
            # rank. No-op unless HOROVOD_FLIGHT_RECORDER_DIR is set.
            try:
                from ..monitor import flight as _flight

                _flight.dump_flight_record(
                    reason="chaos.crash",
                    extra={"point": point, "where": where})
            except Exception:
                pass
            os._exit(spec.exit_code)
        if action == ACTION_DROP:
            raise FaultInjectedError(
                f"chaos: injected drop at {point} (where={where})")
        if action in (ACTION_DELAY, ACTION_STALL):
            time.sleep(spec.secs)
            return None
        if action == ACTION_PREEMPT:
            # A spot eviction notice: SIGTERM to self, optionally after a
            # `secs` grace delay on a daemon thread so the call site (and
            # its step) keeps running through the grace window. Delivery
            # via os.kill routes through whatever handler is installed —
            # the resilience supervisor's priority-snapshot path when the
            # job is supervised, plain termination otherwise.
            def _deliver() -> None:
                try:
                    os.kill(os.getpid(), signal.SIGTERM)
                except Exception:
                    pass
            if spec.secs > 0:
                t = threading.Timer(spec.secs, _deliver)
                t.daemon = True
                t.start()
            else:
                _deliver()
            return None
        return action  # dup / flap: the call site interprets


# ---------------------------------------------------------------------------
# Process-global injector: configured programmatically or lazily from env.
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_injector: Optional[ChaosInjector] = None
_env_checked = False


def configure(plan: Optional[FaultPlan]) -> Optional[ChaosInjector]:
    """Install ``plan`` as this process's active fault plan (None clears
    it). Returns the installed injector for schedule inspection."""
    global _injector, _env_checked
    with _lock:
        _injector = ChaosInjector(plan) if plan and plan.specs else None
        _env_checked = True  # programmatic config wins over env
        return _injector


def reset() -> None:
    """Drop any active injector and re-arm env discovery (tests)."""
    global _injector, _env_checked
    with _lock:
        _injector = None
        _env_checked = False


def active() -> Optional[ChaosInjector]:
    """The live injector, initializing from HOROVOD_CHAOS_* on first use."""
    global _injector, _env_checked
    if _env_checked:
        return _injector
    with _lock:
        if not _env_checked:
            plan = FaultPlan.from_env()
            _injector = ChaosInjector(plan) if plan else None
            _env_checked = True
        return _injector


def enabled() -> bool:
    return active() is not None


def inject(point: str, where: Optional[str] = None, **ctx) -> Optional[str]:
    """Module-level injection entry — what framework call sites use. A
    no-op (single cached-flag check) when no plan is active."""
    inj = active()
    if inj is None:
        return None
    return inj.inject(point, where=where, **ctx)
