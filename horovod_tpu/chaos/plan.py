"""Fault plans: deterministic, seedable schedules of injected faults.

A :class:`FaultPlan` is a list of :class:`FaultSpec` rules plus a seed.
Rules are matched against named injection points (see
:mod:`horovod_tpu.chaos.injector` for the point registry) and fire
deterministically: the decision for the k-th matching invocation of a rule
is a pure function of ``(seed, rule index, k)``, so two processes running
the same plan against the same call sequence observe the identical fault
schedule — the property the determinism tests in ``tests/test_chaos.py``
pin down.

Plans cross process boundaries through two env vars (``to_env`` /
``from_env``), which is how the elastic launcher ships a plan into
workers::

    HOROVOD_CHAOS_SEED=42
    HOROVOD_CHAOS_PLAN=network.client.send:drop,prob=0.5,max=3;\
collective.eager:crash,where=hostB:0,after=3,max=1

Wire grammar: rules separated by ``;``, each rule
``<point-glob>:<action>[,key=value]*``. ``where`` values may contain ``:``
(worker identities are ``host:local_rank``), which is why options are
comma- rather than colon-separated.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import os
from typing import Dict, List, Mapping, Optional, Sequence

SEED_ENV = "HOROVOD_CHAOS_SEED"
PLAN_ENV = "HOROVOD_CHAOS_PLAN"

#: Actions performed inline by the injector.
ACTION_CRASH = "crash"    # os._exit — a hard worker death, no cleanup
ACTION_DROP = "drop"      # raise FaultInjectedError (a ConnectionError)
ACTION_DELAY = "delay"    # sleep `secs`
ACTION_STALL = "stall"    # sleep `secs`; semantically a hang, not jitter
ACTION_PREEMPT = "preempt"  # SIGTERM to self after a `secs` grace delay
#: Actions returned to the call site for interpretation.
ACTION_DUP = "dup"        # RPC client: deliver the request twice
ACTION_FLAP = "flap"      # discovery: report an empty host set

ACTIONS = (ACTION_CRASH, ACTION_DROP, ACTION_DELAY, ACTION_STALL,
           ACTION_PREEMPT, ACTION_DUP, ACTION_FLAP)


@dataclasses.dataclass
class FaultSpec:
    """One fault rule.

    point:  glob over injection-point names (``network.client.*``).
    action: one of :data:`ACTIONS`.
    where:  glob over the call's identity/context tag (worker identity
            ``host:local_rank`` at worker-side points; ``*`` = anywhere).
    after:  skip the first ``after`` matching invocations.
    every:  after that, consider every ``every``-th invocation.
    prob:   fire considered invocations with this probability (seeded).
    max_count: stop firing after this many hits (None = unbounded).
    secs:   duration for delay/stall.
    exit_code: process exit code for crash.
    """

    point: str
    action: str
    where: str = "*"
    after: int = 0
    every: int = 1
    prob: float = 1.0
    max_count: Optional[int] = None
    secs: float = 0.0
    exit_code: int = 1

    def __post_init__(self) -> None:
        if self.action not in ACTIONS:
            raise ValueError(
                f"unknown chaos action {self.action!r}; expected one of "
                f"{ACTIONS}")
        if self.every < 1:
            raise ValueError(f"every must be >= 1, got {self.every}")
        if not (0.0 <= self.prob <= 1.0):
            raise ValueError(f"prob must be in [0, 1], got {self.prob}")

    def matches(self, point: str, where: str) -> bool:
        return fnmatch.fnmatchcase(point, self.point) and \
            fnmatch.fnmatchcase(where, self.where)

    # -- wire format -----------------------------------------------------

    def serialize(self) -> str:
        parts = [f"{self.point}:{self.action}"]
        defaults = FaultSpec(point="", action=self.action)
        for field in ("where", "after", "every", "prob", "max_count",
                      "secs", "exit_code"):
            value = getattr(self, field)
            if value != getattr(defaults, field):
                parts.append(f"{_WIRE_KEYS[field]}={value}")
        return ",".join(parts)

    @classmethod
    def parse(cls, text: str) -> "FaultSpec":
        head, *opts = [t.strip() for t in text.split(",") if t.strip()]
        if ":" not in head:
            raise ValueError(
                f"chaos rule {text!r} must start with '<point>:<action>'")
        point, action = head.rsplit(":", 1)
        kwargs: Dict[str, object] = {}
        for opt in opts:
            if "=" not in opt:
                raise ValueError(
                    f"chaos rule option {opt!r} must be key=value")
            key, value = opt.split("=", 1)
            field = _FIELD_KEYS.get(key.strip())
            if field is None:
                raise ValueError(
                    f"unknown chaos rule option {key!r} in {text!r}; "
                    f"expected one of {sorted(_FIELD_KEYS)}")
            kwargs[field] = _COERCE[field](value.strip())
        return cls(point=point.strip(), action=action.strip(), **kwargs)


_WIRE_KEYS = {
    "where": "where", "after": "after", "every": "every", "prob": "prob",
    "max_count": "max", "secs": "secs", "exit_code": "exit_code",
}
_FIELD_KEYS = {v: k for k, v in _WIRE_KEYS.items()}
_COERCE = {
    "where": str, "after": int, "every": int, "prob": float,
    "max_count": lambda v: None if v in ("None", "none", "") else int(v),
    "secs": float, "exit_code": int,
}


class FaultPlan:
    """A seed plus an ordered list of fault rules."""

    def __init__(self, seed: int = 0,
                 specs: Sequence[FaultSpec] = ()) -> None:
        self.seed = int(seed)
        self.specs: List[FaultSpec] = list(specs)

    def add(self, point: str, action: str, **kwargs) -> "FaultPlan":
        """Append a rule; chains: ``plan.add(...).add(...)``."""
        self.specs.append(FaultSpec(point=point, action=action, **kwargs))
        return self

    def __bool__(self) -> bool:
        return bool(self.specs)

    def __repr__(self) -> str:
        return f"FaultPlan(seed={self.seed}, specs={self.specs!r})"

    # -- env round-trip --------------------------------------------------

    def to_env(self) -> Dict[str, str]:
        """Env-var form for shipping into worker subprocesses."""
        return {
            SEED_ENV: str(self.seed),
            PLAN_ENV: ";".join(s.serialize() for s in self.specs),
        }

    @classmethod
    def from_env(cls,
                 environ: Optional[Mapping[str, str]] = None
                 ) -> Optional["FaultPlan"]:
        """Parse a plan from ``environ`` (default ``os.environ``); None
        when no plan is configured."""
        environ = os.environ if environ is None else environ
        text = environ.get(PLAN_ENV, "").strip()
        if not text:
            return None
        seed = int(environ.get(SEED_ENV, "0"))
        specs = [FaultSpec.parse(rule)
                 for rule in text.split(";") if rule.strip()]
        return cls(seed=seed, specs=specs)
