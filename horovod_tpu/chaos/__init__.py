"""Deterministic fault injection for robustness testing.

The elastic fault-tolerance story (blacklist-and-resume on worker failure,
RPC retry, stall detection) can only be *demonstrated* against failures —
and natural failures don't show up on demand. This package injects them
deterministically: worker crashes, RPC drops/delays/duplicates, discovery
flaps, and artificial stalls, at named points in the runner, elastic, and
collective layers.

Two front doors:

* **Env-driven** (crosses process boundaries — how plans reach workers)::

      HOROVOD_CHAOS_SEED=42 \\
      HOROVOD_CHAOS_PLAN='network.client.send:drop,prob=0.3,max=5' \\
      python -m horovod_tpu.runner -np 2 python train.py

* **Programmatic**::

      from horovod_tpu import chaos

      plan = chaos.FaultPlan(seed=42)
      plan.add("collective.eager", "crash", where="hostB:0",
               after=3, max_count=1)
      chaos.configure(plan)          # this process
      env.update(plan.to_env())      # ...or ship it to subprocesses

With a fixed seed the fault schedule is reproducible: rule decisions are a
pure function of (seed, rule index, per-rule invocation count). See
``docs/robustness.md`` for the fault model and the injection-point
registry, and ``scripts/chaos_soak.py`` for soak loops.
"""

from .injector import (  # noqa: F401
    INJECTION_POINTS,
    ChaosInjector,
    FaultInjectedError,
    active,
    configure,
    enabled,
    inject,
    reset,
)
from .plan import (  # noqa: F401
    ACTIONS,
    PLAN_ENV,
    SEED_ENV,
    FaultPlan,
    FaultSpec,
)
