"""CheckpointManager: async rank-sharded save / reshard-aware restore.

The save path is built around where a ZeRO step's state already lives
(docs/zero.md): every sharded leaf — flat bucket moments, stage-3
parameter shards, leading-axis EF residuals — rides ``P(HVD_AXES)`` on
its leading axis, so "each rank writes only its 1/world shards" is
literally iterating ``addressable_shards`` and writing each device's
slice as its own rank-major file. Nothing gathers: the global array is
never materialized on any host, which is the point — a model whose
optimizer state only exists sharded can still checkpoint.

Save is split into a blocking device→host snapshot (jax arrays are
immutable, but the NEXT step may donate these exact buffers, so the
host copy must land before the trainer resumes) and a background write
(serialize + checksum + atomic commit) on the :class:`AsyncWriter`'s
double buffer. The trainer's stall is the snapshot + an enqueue —
``ckpt.save_ms`` measures exactly that.

Restore reassembles each sharded leaf by rank-major concatenation into
its GLOBAL host form (exact — the shard layout is contiguous by
construction), verifying every file's checksum first. A restore at a
DIFFERENT world size returns the same global form; the caller (or
:class:`~horovod_tpu.checkpoint.elastic.CheckpointedJaxState`) then runs
``hvd.zero_reshard_state`` / ``hvd.zero3_reshard_params`` before
``device_put`` — both are exact, which is what makes kill→restore at a
new world bit-identical (scripts/ckpt_smoke.sh proves it end to end).
"""

from __future__ import annotations

import logging
import os
import pickle
import shutil
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..common import basics
from ..monitor import registry as _metrics
from . import layout
from .layout import CheckpointCorruptError, Manifest, LeafEntry
from .writer import AsyncWriter

log = logging.getLogger("horovod_tpu.checkpoint")


def _timeline():
    return basics._state.timeline if basics.is_initialized() else None


def _tl_span(tid: str, activity: str):
    import contextlib

    @contextlib.contextmanager
    def cm():
        tl = _timeline()
        if tl is not None:
            tl.begin(tid, activity)
        try:
            yield
        finally:
            tl = _timeline()
            if tl is not None:
                tl.end(tid, activity)

    return cm()


def _is_jax_array(x) -> bool:
    try:
        import jax

        return isinstance(x, jax.Array)
    except Exception:  # jax missing in a launcher process
        return False


def _snapshot_leaf(leaf) -> Tuple[str, Any]:
    """Device→host copy of one leaf.

    Returns ``("replicated", ndarray)`` or
    ``("sharded", [(rank, ndarray_shard), ...], global_shape)``. A leaf
    counts as sharded when its jax sharding splits the leading axis
    (every ZeRO state leaf rides ``P(HVD_AXES)``); each addressable
    shard maps to its rank by offset — rank-major is the flat-bucket
    contract (ops/fusion.py)."""
    if _is_jax_array(leaf) and not leaf.is_fully_replicated:
        shards = []
        gshape = tuple(leaf.shape)
        for s in leaf.addressable_shards:
            data = np.asarray(s.data)
            start = s.index[0].start or 0
            seg = data.shape[0]
            if seg == 0 or gshape[0] % seg:
                raise ValueError(
                    f"unsupported sharding for checkpoint: leaf "
                    f"{gshape} has a {data.shape} shard (not an even "
                    f"leading-axis split)")
            shards.append((start // seg, data))
        # A leaf replicated ACROSS one mesh axis but sharded over the
        # other can yield duplicate ranks; keep one copy per rank.
        seen: Dict[int, Any] = {}
        for r, d in shards:
            seen.setdefault(r, d)
        return ("sharded", sorted(seen.items()), gshape)
    return ("replicated", np.asarray(leaf))


class CheckpointManager:
    """Async rank-sharded checkpointing with manifest-led atomic commits
    and retention of the last K steps (docs/checkpoint.md).

    ::

        mgr = hvd.checkpoint.CheckpointManager("/ckpt/run1", keep=3)
        mgr.save(step, {"params": params, "opt_state": state,
                        "rng": rng_key})          # blocks ~snapshot only
        ...
        meta, tree = mgr.restore()                # latest committed step
        state = hvd.zero_reshard_state(tree["opt_state"], params0,
                                       from_world=meta.world,
                                       to_world=hvd.size())
    """

    def __init__(self, directory: str, *, keep: int = 3,
                 async_save: bool = True) -> None:
        if keep < 1:
            raise ValueError("keep must be >= 1")
        self.directory = os.path.abspath(directory)
        self.keep = keep
        self.async_save = async_save
        os.makedirs(self.directory, exist_ok=True)
        self._writer = AsyncWriter() if async_save else None
        self._closed = False

    # -- save ------------------------------------------------------------

    def save(self, step: int, tree: Dict[str, Any], *,
             world: Optional[int] = None,
             local_size: Optional[int] = None,
             mesh_shape: Optional[Tuple[int, int]] = None,
             extra: Optional[dict] = None,
             blocking: bool = False) -> None:
        """Snapshot ``tree`` (a dict of named pytrees) and commit it as
        ``step`` off the critical path.

        The call blocks only for the device→host snapshot (plus writer
        backpressure when two saves are already in flight); everything
        else — serialization, checksums, the atomic tmp→rename commit,
        retention — runs on the background writer. ``blocking=True``
        forces the whole write inline (restore-path tests; final save
        before exit)."""
        if self._closed:
            raise RuntimeError("CheckpointManager is closed")
        if not isinstance(tree, dict) or not tree:
            raise ValueError("save() takes a non-empty {name: pytree} "
                             "dict (names become file stems)")
        for key in tree:
            if not key or "/" in key or key.startswith("."):
                raise ValueError(f"bad checkpoint key {key!r}")
        if world is None:
            world = basics.size() if basics.is_initialized() else 1
        if local_size is None:
            local_size = (basics.local_size()
                          if basics.is_initialized() else world)
        t0 = time.perf_counter()
        with _tl_span("ckpt", "CKPT:SNAPSHOT"):
            import jax

            snap: Dict[str, Tuple[Any, List[Tuple[str, Any]]]] = {}
            digest_src: Dict[str, Any] = {}
            for key, subtree in tree.items():
                leaves, treedef = jax.tree.flatten(subtree)
                snap[key] = (treedef, [_snapshot_leaf(l) for l in leaves])
                digest_src[key] = subtree
            digest = layout.plan_digest_for(digest_src)
        manifest = Manifest(step=int(step), world=int(world),
                            local_size=int(local_size),
                            mesh_shape=mesh_shape, plan_digest=digest,
                            entries=[], treedefs={}, extra=extra)

        def job() -> None:
            self._write_committed(manifest, snap)

        if self._writer is not None and not blocking:
            self._writer.submit(job)
        else:
            job()
        stall_ms = (time.perf_counter() - t0) * 1e3
        if _metrics.metrics_enabled():
            r = _metrics.default_registry()
            r.histogram("ckpt.save_ms").observe(stall_ms)
            r.counter("ckpt.snapshots").inc()

    def _write_committed(self, manifest: Manifest, snap) -> None:
        t0 = time.perf_counter()
        final = os.path.join(self.directory,
                             layout.step_dir_name(manifest.step))
        tmp = f"{final}.tmp-{os.getpid()}"
        total_bytes = 0
        with _tl_span("ckpt", "CKPT:WRITE"):
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp)
            for key, (treedef, leaves) in snap.items():
                td_bytes = pickle.dumps(treedef)
                td_file = f"{key}.treedef.pkl"
                with open(os.path.join(tmp, td_file), "wb") as f:
                    f.write(td_bytes)
                manifest.treedefs[key] = {
                    "file": td_file,
                    "checksum": layout.checksum(td_bytes)}
                for i, rec in enumerate(leaves):
                    if rec[0] == "replicated":
                        _, arr = rec
                        fname = f"{key}.leaf{i:04d}.rep.npy"
                        files = {fname: self._write_npy(tmp, fname, arr)}
                        total_bytes += arr.nbytes
                        manifest.entries.append(LeafEntry(
                            key=key, index=i, kind="replicated",
                            dtype=str(arr.dtype),
                            shape=tuple(arr.shape), files=files))
                    else:
                        _, shards, gshape = rec
                        files: Dict[str, str] = {}
                        ranks: List[int] = []
                        dtype = None
                        for rank, arr in shards:
                            fname = f"{key}.leaf{i:04d}.rank{rank:03d}.npy"
                            files[fname] = self._write_npy(tmp, fname, arr)
                            ranks.append(rank)
                            total_bytes += arr.nbytes
                            dtype = arr.dtype
                        manifest.entries.append(LeafEntry(
                            key=key, index=i, kind="sharded",
                            dtype=str(dtype), shape=tuple(gshape),
                            files=files, ranks=ranks))
            layout.write_manifest(tmp, manifest)
            # The atomic commit. Re-saving an already-committed step
            # (an elastic resume re-pinning its restore point) swaps the
            # old directory out first — os.replace cannot replace a
            # non-empty directory — so a reader never sees a partial
            # step: either the old commit, the new one, or (crash
            # between the two renames) no step dir, falling back to the
            # previous retained step.
            old = None
            if os.path.exists(final):
                old = f"{final}.old-{os.getpid()}"
                os.replace(final, old)
            os.replace(tmp, final)
            if old is not None:
                shutil.rmtree(old, ignore_errors=True)
        if _metrics.metrics_enabled():
            r = _metrics.default_registry()
            r.counter("ckpt.commits").inc()
            r.counter("ckpt.bytes").inc(float(total_bytes))
            r.gauge("ckpt.last_step").set(float(manifest.step))
            r.histogram("ckpt.write_ms").observe(
                (time.perf_counter() - t0) * 1e3)
        tl = _timeline()
        if tl is not None:
            tl.instant("CKPT:COMMIT", tid="ckpt",
                       args={"step": manifest.step,
                             "bytes": total_bytes})
        self._apply_retention()

    @staticmethod
    def _write_npy(dirpath: str, fname: str, arr) -> str:
        import io

        buf = io.BytesIO()
        np.save(buf, np.asarray(arr), allow_pickle=False)
        data = buf.getvalue()
        with open(os.path.join(dirpath, fname), "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        return layout.checksum(data)

    def _apply_retention(self) -> None:
        """Keep the last K committed steps; sweep stale tmp orphans."""
        steps = layout.list_steps(self.directory)
        for s in steps[:-self.keep]:
            path = os.path.join(self.directory, layout.step_dir_name(s))
            shutil.rmtree(path, ignore_errors=True)
            log.info("checkpoint retention: dropped step %d", s)
        for name in os.listdir(self.directory):
            if ".tmp-" in name:
                base = name.split(".tmp-", 1)[0]
                s = layout.parse_step_dir(base)
                committed = s is not None and s in steps
                # An orphan from a crashed writer is safe to sweep once
                # its step committed, or when nothing is in flight here.
                if committed or not self.busy:
                    shutil.rmtree(os.path.join(self.directory, name),
                                  ignore_errors=True)

    # -- query -----------------------------------------------------------

    @property
    def busy(self) -> bool:
        return self._writer is not None and self._writer.busy

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Drain in-flight saves; re-raises background write errors."""
        if self._writer is None:
            return True
        return self._writer.drain(timeout)

    def steps(self) -> List[int]:
        return layout.list_steps(self.directory)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    # -- restore ---------------------------------------------------------

    def restore(self, step: Optional[int] = None, *,
                verify: bool = True) -> Tuple[Manifest, Dict[str, Any]]:
        """Load a committed checkpoint into its GLOBAL host form.

        Sharded leaves reassemble by rank-major concatenation (exact);
        every payload file's checksum is verified first (``verify=False``
        is for forensics only) — a mismatch raises
        :class:`CheckpointCorruptError` instead of handing a half-rotten
        state to a training run. Returns ``(manifest, {key: pytree})``;
        reshard with ``hvd.zero_reshard_state`` /
        ``hvd.zero3_reshard_params`` when ``manifest.world`` differs from
        the world you are restoring into, then ``device_put``."""
        t0 = time.perf_counter()
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no committed checkpoint under {self.directory}")
        step_dir = os.path.join(self.directory,
                                layout.step_dir_name(step))
        with _tl_span("ckpt", "CKPT:RESTORE"):
            import jax

            manifest = layout.read_manifest(step_dir)
            by_key: Dict[str, List[LeafEntry]] = {}
            for e in manifest.entries:
                by_key.setdefault(e.key, []).append(e)
            out: Dict[str, Any] = {}
            for key, td_rec in manifest.treedefs.items():
                td_path = os.path.join(step_dir, td_rec["file"])
                td_bytes = self._read_verified(td_path, td_rec["checksum"],
                                               verify)
                treedef = pickle.loads(td_bytes)
                leaves: List[Any] = []
                for e in sorted(by_key.get(key, []), key=lambda x: x.index):
                    leaves.append(self._load_entry(step_dir, e, verify))
                out[key] = jax.tree.unflatten(treedef, leaves)
        if _metrics.metrics_enabled():
            r = _metrics.default_registry()
            r.counter("ckpt.restores").inc()
            r.histogram("ckpt.restore_ms").observe(
                (time.perf_counter() - t0) * 1e3)
        return manifest, out

    def _load_entry(self, step_dir: str, e: LeafEntry, verify: bool):
        import io

        if e.kind == "replicated":
            (fname, csum), = e.files.items()
            data = self._read_verified(os.path.join(step_dir, fname),
                                       csum, verify)
            arr = np.load(io.BytesIO(data), allow_pickle=False)
        else:
            by_rank = sorted(zip(e.ranks, e.files.items()))
            parts = []
            expect = set(range(len(by_rank)))
            got = {r for r, _ in by_rank}
            if got != expect:
                raise CheckpointCorruptError(
                    f"sharded leaf {e.key}[{e.index}] has ranks "
                    f"{sorted(got)}, expected {sorted(expect)} — a rank's "
                    f"shard files are missing from the commit")
            for _, (fname, csum) in by_rank:
                data = self._read_verified(os.path.join(step_dir, fname),
                                           csum, verify)
                parts.append(np.load(io.BytesIO(data), allow_pickle=False))
            arr = np.concatenate(parts, axis=0)
        if tuple(arr.shape) != e.shape:
            raise CheckpointCorruptError(
                f"leaf {e.key}[{e.index}] reassembled to {arr.shape}, "
                f"manifest says {e.shape}")
        return arr

    @staticmethod
    def _read_verified(path: str, csum: str, verify: bool) -> bytes:
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError as e:
            raise CheckpointCorruptError(
                f"missing checkpoint payload {path}: {e}") from e
        if verify and layout.checksum(data) != csum:
            raise CheckpointCorruptError(
                f"checksum mismatch on {path}: file has "
                f"{layout.checksum(data)}, manifest committed {csum} — "
                f"refusing to load corrupt state (restore an earlier "
                f"step)")
        return data

    # -- lifecycle -------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._writer is not None:
            self._writer.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
