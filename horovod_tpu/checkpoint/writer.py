"""Background checkpoint writer: double-buffered, off the critical path.

The save path splits in two (docs/checkpoint.md): the BLOCKING part is
only the device→host snapshot at the step boundary (plus a queue put);
serialization, checksumming, and the atomic commit run on this thread.
Double buffering bounds host memory: at most TWO snapshots exist at once
— one being written, one queued. A third ``submit`` blocks until the
writer drains (that wait is the backpressure the bench's
``ckpt_stall_ms`` would surface if saves outpace the disk).

A failed write never kills the training process mid-step: the exception
is captured and re-raised on the NEXT ``submit``/``drain`` (the reference
posture — a checkpoint subsystem must fail loudly but at a boundary the
trainer can handle).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
import weakref
from typing import Callable, Optional

log = logging.getLogger("horovod_tpu.checkpoint")

# Every live AsyncWriter, so a signal handler can quiesce in-flight
# commits process-wide without plumbing writer references through the
# monitor layer (flight.py drains here before dumping on SIGTERM — a
# torn half-written commit is exactly what the manifest-last protocol
# exists to prevent, and re-delivering the signal mid-write would
# waste the window the preemption grace period grants us).
_live_writers: "weakref.WeakSet[AsyncWriter]" = weakref.WeakSet()


class AsyncWriter:
    """One daemon thread draining a bounded job queue.

    ``submit(job)`` enqueues a zero-argument callable; ``maxsize=1`` plus
    the job in flight gives the double buffer. ``drain()`` blocks until
    every submitted job has finished (the kill-before-commit windows of
    the smoke test live between ``submit`` and ``drain``).

    Idle-tracking is a pending-job counter guarded by one condition
    variable: ``submit`` increments BEFORE enqueueing and the worker
    decrements AFTER the job (and any captured error) lands, so a
    ``drain`` can never observe "idle" while a submitted job is still in
    flight (an Event set from a stale emptiness check could).
    """

    def __init__(self, name: str = "hvd-ckpt-writer") -> None:
        self._queue: "queue.Queue" = queue.Queue(maxsize=1)
        self._cond = threading.Condition()
        self._pending = 0
        self._error: Optional[BaseException] = None
        self._closed = False
        self._thread = threading.Thread(target=self._loop, name=name,
                                        daemon=True)
        self._thread.start()
        _live_writers.add(self)

    def _loop(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                return
            try:
                job()
            except BaseException as e:  # surfaced on next submit/drain
                log.error("async checkpoint write failed: %s", e)
                with self._cond:
                    self._error = e
            finally:
                with self._cond:
                    self._pending -= 1
                    if self._pending == 0:
                        self._cond.notify_all()

    def raise_pending(self) -> None:
        """Re-raise (once) an error captured on the writer thread."""
        with self._cond:
            err, self._error = self._error, None
        if err is not None:
            raise err

    def submit(self, job: Callable[[], None]) -> None:
        """Enqueue a write job; blocks only when two snapshots are
        already in flight (the double-buffer backpressure)."""
        if self._closed:
            raise RuntimeError("AsyncWriter is closed")
        self.raise_pending()
        with self._cond:
            self._pending += 1
        # Outside the lock: a full queue blocks here until the worker
        # frees a slot, and the worker's decrement needs the lock.
        self._queue.put(job)

    @property
    def busy(self) -> bool:
        with self._cond:
            return self._pending > 0

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait for all submitted jobs; True when idle (False = timeout).
        Re-raises a captured writer error."""
        with self._cond:
            done = self._cond.wait_for(lambda: self._pending == 0, timeout)
        self.raise_pending()
        return done

    def close(self, timeout: float = 60.0) -> None:
        """Drain and stop the thread (idempotent)."""
        if self._closed:
            return
        self._closed = True
        self._queue.put(None)
        self._thread.join(timeout)
        self.raise_pending()


def drain_all(timeout: float = 10.0) -> bool:
    """Drain every live AsyncWriter under one shared deadline.

    Signal-handler safe: never raises (captured writer errors stay
    captured for the owner's next ``submit``/``drain`` to surface) and
    never waits past ``timeout`` in total, however many writers exist.
    Returns True when every writer went idle within the budget.
    """
    deadline = time.monotonic() + max(0.0, timeout)
    all_idle = True
    for writer in list(_live_writers):
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            all_idle = all_idle and not writer.busy
            continue
        try:
            with writer._cond:
                idle = writer._cond.wait_for(
                    lambda: writer._pending == 0, remaining)
        except Exception:
            idle = False
        all_idle = all_idle and idle
    return all_idle
