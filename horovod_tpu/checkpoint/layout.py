"""On-disk layout of a rank-sharded checkpoint (docs/checkpoint.md).

A checkpoint directory holds one subdirectory per committed step::

    <directory>/
      step_0000000042/
        MANIFEST.json            # world/mesh/plan digest + entry table
        <key>.treedef.pkl        # pickled pytree structure per top key
        <key>.leaf0003.rep.npy   # a replicated leaf (written once)
        <key>.leaf0007.rank002.npy   # rank 2's shard of a sharded leaf
      step_0000000050/
        ...
      step_0000000050.tmp-<pid>/     # in-flight save (never read)

The manifest is written LAST inside the tmp directory, then the whole
directory commits with one atomic ``os.replace`` — a reader either sees a
complete checkpoint or none at all, and a crash mid-write leaves only a
``.tmp-*`` orphan that the next save sweeps. Every payload file carries a
crc32 in the manifest; restore verifies before deserializing and fails
loudly on mismatch (:class:`CheckpointCorruptError`) rather than loading
garbage into a training run.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import zlib
from typing import Any, Dict, List, Optional, Tuple

MANIFEST = "MANIFEST.json"
LAYOUT_VERSION = 1

_STEP_RE = re.compile(r"^step_(\d{10})$")


class CheckpointCorruptError(RuntimeError):
    """A checkpoint shard failed its checksum (or the manifest is
    malformed): the data on disk is NOT what the writer committed. Raised
    instead of silently loading garbage — restore from an earlier step or
    re-seed."""


def step_dir_name(step: int) -> str:
    return f"step_{int(step):010d}"


def parse_step_dir(name: str) -> Optional[int]:
    m = _STEP_RE.match(name)
    return int(m.group(1)) if m else None


def list_steps(directory: str) -> List[int]:
    """Committed steps in ascending order (tmp dirs excluded)."""
    if not os.path.isdir(directory):
        return []
    steps = []
    for name in os.listdir(directory):
        s = parse_step_dir(name)
        if s is not None and os.path.exists(
                os.path.join(directory, name, MANIFEST)):
            steps.append(s)
    return sorted(steps)


def checksum(data: bytes) -> str:
    """crc32 of the payload bytes — cheap enough to run inline on every
    shard at save AND restore (the corruption this guards against is
    torn/bit-rotted files, not adversaries)."""
    return f"crc32:{zlib.crc32(data) & 0xFFFFFFFF:08x}"


@dataclasses.dataclass
class LeafEntry:
    """One pytree leaf in the manifest entry table.

    ``kind`` is ``"replicated"`` (one file, every rank holds the value)
    or ``"sharded"`` (``world`` files, rank-major leading-axis shards —
    the ZeRO flat-bucket / leading-axis-residual convention). ``files``
    maps a relative path to its checksum; sharded entries also carry the
    per-file rank in ``ranks`` (aligned with ``files`` order)."""

    key: str
    index: int
    kind: str
    dtype: str
    shape: Tuple[int, ...]
    files: Dict[str, str]
    ranks: Optional[List[int]] = None

    def to_json(self) -> dict:
        d = {"key": self.key, "index": self.index, "kind": self.kind,
             "dtype": self.dtype, "shape": list(self.shape),
             "files": self.files}
        if self.ranks is not None:
            d["ranks"] = self.ranks
        return d

    @classmethod
    def from_json(cls, d: dict) -> "LeafEntry":
        return cls(key=d["key"], index=int(d["index"]), kind=d["kind"],
                   dtype=d["dtype"], shape=tuple(d["shape"]),
                   files=dict(d["files"]),
                   ranks=list(d["ranks"]) if "ranks" in d else None)


@dataclasses.dataclass
class Manifest:
    """The checkpoint's self-description — what restore (and the reshard
    path) needs without touching a payload file: the world/mesh geometry
    it was written at, the bucket-plan digest (so a restore can detect a
    changed fusion threshold or model signature before deserializing
    anything), and the per-leaf entry/checksum table."""

    step: int
    world: int
    local_size: int
    mesh_shape: Optional[Tuple[int, int]]
    plan_digest: str
    entries: List[LeafEntry]
    treedefs: Dict[str, Dict[str, str]]  # key -> {file, checksum}
    version: int = LAYOUT_VERSION
    extra: Optional[dict] = None

    def to_json(self) -> dict:
        return {
            "version": self.version,
            "step": self.step,
            "world": self.world,
            "local_size": self.local_size,
            "mesh_shape": (list(self.mesh_shape)
                           if self.mesh_shape else None),
            "plan_digest": self.plan_digest,
            "treedefs": self.treedefs,
            "entries": [e.to_json() for e in self.entries],
            "extra": self.extra or {},
        }

    @classmethod
    def from_json(cls, d: dict) -> "Manifest":
        return cls(
            step=int(d["step"]),
            world=int(d["world"]),
            local_size=int(d.get("local_size", d["world"])),
            mesh_shape=(tuple(d["mesh_shape"]) if d.get("mesh_shape")
                        else None),
            plan_digest=d.get("plan_digest", ""),
            entries=[LeafEntry.from_json(e) for e in d.get("entries", [])],
            treedefs=dict(d.get("treedefs", {})),
            version=int(d.get("version", 1)),
            extra=d.get("extra") or {},
        )


def write_manifest(step_dir: str, manifest: Manifest) -> None:
    path = os.path.join(step_dir, MANIFEST)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest.to_json(), f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_manifest(step_dir: str) -> Manifest:
    path = os.path.join(step_dir, MANIFEST)
    try:
        with open(path) as f:
            return Manifest.from_json(json.load(f))
    except (OSError, ValueError, KeyError) as e:
        raise CheckpointCorruptError(
            f"unreadable checkpoint manifest {path}: {e}") from e


def plan_digest_for(tree: Any) -> str:
    """Structure digest of a saved tree: md5 over treedef + leaf
    shapes/dtypes — the same signature idea as the autotune warm-start
    cache key (values never enter), so a restore against a DIFFERENT
    model or leaf order is caught by the manifest, not by a shape error
    three layers deep."""
    import hashlib

    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree.flatten(tree)
    parts = [str(treedef)]
    for leaf in leaves:
        parts.append(f"{jnp.shape(leaf)}:{jnp.asarray(leaf).dtype}")
    return hashlib.md5("|".join(parts).encode()).hexdigest()
