"""Elastic ↔ checkpoint bridge: durable JaxState (docs/checkpoint.md).

``hvd.elastic``'s in-memory commit/restore survives peer failures inside
one process, but a chaos-injected crash of THIS process (or a resize
that reschedules it) loses the in-memory copy. ``CheckpointedJaxState``
writes every ``save()`` through a :class:`CheckpointManager` off the
critical path and, when a fresh process constructs it over a directory
holding committed steps, restores from the latest one — resharding any
:class:`~horovod_tpu.ZeroState` (and stage-3 parameter-shard tuples) to
the CURRENT world first, so resume after a world change is bit-identical
(the zero_reshard round-trip is exact).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

from ..common import basics
from ..elastic.state import JaxState
from .manager import CheckpointManager

log = logging.getLogger("horovod_tpu.checkpoint")


def _reshard_value(value, params_template, from_world: int,
                   to_world: int, from_local: int):
    """Reshard one restored entry to the current world: ZeroState goes
    through zero_reshard_state, a stage-3 flat-bucket tuple through
    zero3_reshard_params; anything else is world-independent (replicated
    params, RNG keys, scalars) and passes through."""
    from ..parallel import optimizer as O

    if from_world == to_world:
        return value
    if isinstance(value, O.ZeroState):
        if params_template is None:
            raise ValueError(
                "restoring a ZeroState across world sizes needs "
                "params_template= (the model parameter pytree the "
                "bucket plan derives from)")
        return O.zero_reshard_state(value, params_template,
                                    from_world=from_world,
                                    to_world=to_world)
    if isinstance(value, tuple) and params_template is not None:
        import jax
        import jax.numpy as jnp

        from ..ops import fusion

        tleaves = jax.tree.leaves(params_template)
        plan_f = fusion.plan_buckets(tleaves, None,
                                     shard_multiple=from_world)
        if (len(value) == len(plan_f) and all(
                getattr(v, "ndim", 0) == 1
                and v.shape[0] == b.padded_size
                and jnp.dtype(v.dtype) == jnp.dtype(b.dtype)
                for v, b in zip(value, plan_f))):
            return O.zero3_reshard_params(value, params_template,
                                          from_world=from_world,
                                          to_world=to_world)
    return value


class CheckpointedJaxState(JaxState):
    """A :class:`~horovod_tpu.elastic.JaxState` whose commits are durable.

    ::

        mgr = hvd.checkpoint.CheckpointManager(ckpt_dir, keep=3)
        state = hvd.checkpoint.CheckpointedJaxState(
            mgr, params_template=params0,
            params=params, opt_state=opt_state, step=0)

        @hvd.elastic.run
        def train(state):
            while ...:
                ...
                state.step += 1
                state.commit()     # in-memory save + async disk write

    On construction, if ``mgr`` already holds a committed step (the
    process is a post-crash or post-resize replacement), the newest one
    overrides the passed initial values — resharded to the current world
    — and ``state.step`` resumes from the committed step. ``restore()``
    (the elastic rollback on peer failure) stays IN-MEMORY: rolling back
    to the last in-process commit is both correct and cheaper than disk.
    """

    def __init__(self, manager: CheckpointManager, *,
                 params_template=None, step_key: str = "step",
                 **kwargs) -> None:
        self._mgr = manager
        self._params_template = params_template
        self._step_key = step_key
        self.restored_from: Optional[int] = None
        latest = manager.latest_step()
        if latest is not None:
            manifest, tree = manager.restore(latest)
            world = basics.size() if basics.is_initialized() else 1
            # Pipeline geometry guard (docs/pipeline.md): stage params
            # and their optimizer state are laid out per stage CHUNK —
            # there is no world-independent reshard across a stage-count
            # change, so fail loudly with the recovery recipe instead of
            # silently mis-assembling chunks. A same-stage world resize
            # falls through to the ordinary reshard path.
            saved_pp = int((manifest.extra or {}).get("pp_stages", 1)
                           or 1)
            cur_pp = basics.pp_size() if basics.is_initialized() else 1
            if saved_pp != cur_pp:
                raise ValueError(
                    f"checkpoint step {manifest.step} was written on a "
                    f"{saved_pp}-stage pipeline mesh but this process "
                    f"runs {cur_pp} stages: per-stage chunk parameters "
                    f"do not reshard across stage counts. Restore on a "
                    f"mesh with pp_stages={saved_pp}, merge the chunks "
                    f"back to the dense model (pp_split_chunks is a "
                    f"pure reshape), and re-split for the new stage "
                    f"count (docs/pipeline.md).")
            # Expert-parallel geometry guard (docs/moe.md): expert
            # leaves are laid out per ep GROUP (each rank holds
            # E/ep_size experts) — resharding across expert-group
            # counts would silently re-assign experts to the wrong
            # groups, so fail loudly with the recovery recipe. A
            # same-ep world resize falls through as above.
            saved_ep = int((manifest.extra or {}).get("ep_size", 1)
                           or 1)
            cur_ep = basics.ep_size() if basics.is_initialized() else 1
            if saved_ep != cur_ep:
                raise ValueError(
                    f"checkpoint step {manifest.step} was written on a "
                    f"{saved_ep}-group expert-parallel mesh but this "
                    f"process runs {cur_ep} groups: per-group expert "
                    f"parameters do not reshard across expert-group "
                    f"counts. Restore on a mesh with "
                    f"ep_size={saved_ep}, merge the expert shards back "
                    f"to the dense model (ep_stack_params is a pure "
                    f"reshape), and re-split for the new group count "
                    f"(docs/moe.md).")
            for key, value in tree.items():
                if key in kwargs:
                    kwargs[key] = _reshard_value(
                        value, params_template, manifest.world, world,
                        manifest.local_size)
            for k, v in (manifest.extra or {}).get("obj", {}).items():
                if k in kwargs and k != step_key:
                    kwargs[k] = v
            kwargs[step_key] = manifest.step
            self.restored_from = manifest.step
            log.info("CheckpointedJaxState: resumed from committed step "
                     "%d (world %d -> %d)", manifest.step,
                     manifest.world, world)
        super().__init__(**kwargs)

    def _durable_tree(self) -> Dict[str, Any]:
        tree = {k: getattr(self, k) for k in self._tree_keys}
        return tree

    def save(self) -> None:
        super().save()
        step = int(getattr(self, self._step_key, 0))
        self._mgr.save(step, self._durable_tree(),
                       extra={"obj": {k: getattr(self, k)
                                      for k in self._obj_keys
                                      if _jsonable(getattr(self, k))},
                              "pp_stages": (basics.pp_size()
                                            if basics.is_initialized()
                                            else 1),
                              "ep_size": (basics.ep_size()
                                          if basics.is_initialized()
                                          else 1)})

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Drain in-flight checkpoint writes (call before exiting)."""
        return self._mgr.wait(timeout)


def _jsonable(v) -> bool:
    return isinstance(v, (int, float, str, bool, type(None)))
