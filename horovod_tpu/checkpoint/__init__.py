"""Async rank-sharded checkpointing (docs/checkpoint.md).

The scale axis ZeRO opens (docs/zero.md) only holds if recovery fits the
same budget: a model whose parameters, gradients, and optimizer state
exist ONLY as 1/world shards must also checkpoint and restore without
ever materializing the global arrays on one host. This package does
that:

* each rank writes only its own shards (``addressable_shards`` of the
  ``P(HVD_AXES)`` leaves — flat bucket moments, stage-3 parameter
  shards, EF residuals), device→host snapshot at a step boundary, then
  a background double-buffered writer (:mod:`.writer`) — the trainer
  stalls for the snapshot only (``ckpt.save_ms``);
* a manifest-led layout (:mod:`.layout`): world/mesh geometry, a
  bucket-plan digest, per-shard crc32 checksums, atomic tmp→rename
  commit, retention of the last K steps;
* restore (:mod:`.manager`) verifies every checksum (corrupt shards
  raise :class:`CheckpointCorruptError`, never load), reassembles the
  exact global form, and — across world-size changes — hands off to the
  exact ``hvd.zero_reshard_state`` / ``hvd.zero3_reshard_params`` so a
  resized resume is bit-identical (scripts/ckpt_smoke.sh);
* :class:`CheckpointedJaxState` (:mod:`.elastic`) rides the
  ``hvd.elastic`` commit/restore protocol, making chaos-injected crashes
  and elastic resizes resume from the last committed step
  (scripts/chaos_soak.py --fault ckpt).

Metrics: ``ckpt.save_ms`` / ``ckpt.write_ms`` / ``ckpt.restore_ms``
histograms, ``ckpt.commits`` / ``ckpt.restores`` / ``ckpt.bytes``
counters, ``ckpt.last_step`` gauge; Timeline spans ``CKPT:SNAPSHOT`` /
``CKPT:WRITE`` / ``CKPT:RESTORE`` and the ``CKPT:COMMIT`` instant
(docs/observability.md).
"""

from __future__ import annotations

from .layout import (  # noqa: F401
    CheckpointCorruptError,
    LeafEntry,
    Manifest,
    list_steps,
    plan_digest_for,
)
from .manager import CheckpointManager  # noqa: F401
from .writer import AsyncWriter  # noqa: F401
from .elastic import CheckpointedJaxState  # noqa: F401
