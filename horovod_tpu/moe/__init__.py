"""horovod_tpu.moe: expert-parallel MoE training + serving
(docs/moe.md).

The MoE scenario family as a vertical slice of the whole stack: a top-k
gated expert FFN (:class:`MoELayer` / :func:`moe_ffn`) whose
dispatch/combine all-to-alls are first-class ``a2a`` wire plans —
validated IR, int8+error-feedback payloads on DCN-class hops, cost-model
pricing, ``MOE:*`` spans, and ``comm.moe.bytes{hop}`` accounting — over
a dedicated ``hvd_ep`` mesh axis (``hvd.init(ep_size=E)``) that is
deliberately NOT a data/world axis, so expert gradients reduce only
within their own data group. The serving half (per-expert load metrics,
hot-expert replication) lives in ``horovod_tpu/serve/``.
"""

from .layer import (  # noqa: F401
    EXPERT_LEAVES,
    MoEAux,
    MoELayer,
    default_a2a_plan,
    ep_mean_dense_grads,
    ep_param_pspecs,
    ep_stack_params,
    moe_capacity,
    moe_ef_residuals,
    moe_ffn,
    moe_positions,
    moe_router,
)
