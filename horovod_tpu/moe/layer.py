"""Expert-parallel MoE training: top-k gated expert FFN with wire-plan
all-to-all dispatch (docs/moe.md).

The layer is the training half of the MoE scenario family. Routing math:

1. **route** — ``logits = x @ router`` → softmax probs; ``lax.top_k``
   picks each token's K experts, the selected gates renormalize to sum
   one. Two auxiliary losses ride along: the Switch load-balance loss
   (``E · Σ_e f_e · P_e`` over top-1 assignment fractions ``f_e`` and
   mean probs ``P_e``) and the router z-loss
   (``mean(logsumexp(logits)²)``, ST-MoE: keeps logits bounded so the
   int8 dispatch wire stays well-scaled);
2. **capacity** — every expert accepts at most
   ``ceil(K · N · capacity_factor / E)`` token-choices per step.
   Position-in-expert assignment is DETERMINISTIC: choices are ranked
   choice-major (all first choices before all second choices, token
   order within a choice), so a rerun of the same batch dispatches
   identically — no RNG in the hot path;
3. **dispatch** — kept choices scatter into a static ``[E, cap, C]``
   buffer; overflow choices are DROPPED (they contribute zero to the
   combine, so a fully-dropped token passes through the caller's
   residual connection untouched — standard Switch semantics);
4. **exchange** — the buffer crosses the dedicated ``hvd_ep`` mesh axis
   as a first-class ``a2a`` wire plan
   (:func:`horovod_tpu.plan.compiler.lower_a2a`): validated IR,
   blockwise-int8 payload with error feedback on DCN-class hops
   (EQuARX), ``MOE:DISPATCH``/``MOE:COMBINE`` spans, and
   ``comm.moe.bytes{hop}`` / ``WireStats.a2a_bytes`` accounting for
   free;
5. **expert FFN** — batched einsum over this ep rank's local experts;
6. **combine** — the reverse exchange returns expert outputs to their
   source rank; each token sums its kept choices' outputs weighted by
   the renormalized gates.

The ``hvd_ep`` axis is NOT a data/world axis (the hvd_pp pattern,
docs/pipeline.md): ``hvd.init(ep_size=E)`` puts it leading the mesh, so
``axes=None`` gradient collectives resolve to the data axes only and an
expert's gradients reduce exclusively within its own data group —
ZeRO stages, overlap, and the quantized gradient wire compose unchanged.
Router/dense gradients, which ARE data-dependent per ep rank when the
batch shards over ``hvd_ep``, get their explicit ep-mean via
:func:`ep_mean_dense_grads`.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from ..common import basics
from ..common.basics import EP_AXIS
from ..plan import compiler as _compiler
from ..plan import planner as _planner

if not hasattr(jax, "shard_map"):
    # jax < 0.6: the experimental shard_map check_rep loop cannot handle
    # a multiple-results primitive whose operand replication is the bare
    # ``None`` of an untracked/constant-derived value — the upstream
    # ``_standard_check`` rule returns ``None`` un-broadcast and the
    # loop crashes on ``map(write, e.outvars, None)``. ``lax.top_k``
    # (the router's expert selection) hits exactly this, so OVERWRITE
    # its rule with one that always returns the per-output list.
    try:  # pragma: no cover - version-gated compat
        from jax.experimental import shard_map as _sm_compat
        from jax._src.lax.lax import top_k_p as _top_k_p

        def _top_k_rep_rule(mesh, x_rep, **params):
            # Both outputs (values, indices) replicate exactly like the
            # operand.
            return [x_rep, x_rep]

        _sm_compat._check_rules[_top_k_p] = _top_k_rep_rule
    except Exception:  # pragma: no cover - internal-API drift
        pass


def _axis_size(axis) -> int:
    if axis is None:
        return 1
    n = 1
    for a in ((axis,) if isinstance(axis, str) else tuple(axis)):
        n *= basics._axis_size(a)
    return n


@dataclasses.dataclass(frozen=True)
class MoEAux:
    """Per-call routing diagnostics (all scalars/arrays are traced
    values). ``load`` is the kept token-choice count per GLOBAL expert
    ``[E]`` — the expert-load histogram's source; ``dropped_fraction``
    the fraction of token-choices that overflowed capacity."""

    load_balance_loss: jnp.ndarray
    z_loss: jnp.ndarray
    load: jnp.ndarray
    dropped_fraction: jnp.ndarray


def moe_capacity(n_tokens: int, num_experts: int,
                 capacity_factor: float, topk: int) -> int:
    """Per-expert dispatch capacity: ``ceil(K·N·cf / E)``, floor 1."""
    return max(1, int(-(-topk * n_tokens * float(capacity_factor)
                        // num_experts)))


def moe_router(x, router_kernel, *, topk: int = 2,
               router_logits=None):
    """Top-k routing of tokens ``x [N, C]`` through ``router_kernel
    [C, E]``. Returns ``(experts [N, K] int32, gates [N, K] fp32,
    load_balance_loss, z_loss, probs [N, E])``.

    ``router_logits`` overrides the computed logits (tests pin routing
    deterministically with it; shape ``[N, E]``)."""
    E = router_kernel.shape[-1]
    if topk < 1 or topk > E:
        raise ValueError(f"topk must be in 1..{E} (num experts), got "
                         f"{topk}")
    if router_logits is None:
        router_logits = jnp.einsum(
            "nc,ce->ne", x.astype(jnp.float32),
            router_kernel.astype(jnp.float32))
    probs = jax.nn.softmax(router_logits, axis=-1)
    gate_vals, experts = lax.top_k(probs, topk)          # [N, K]
    gates = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)
    # Load-balance aux (Switch eq. 4): f_e from the TOP-1 assignment
    # (the loss targets the primary routing decision), P_e = mean probs.
    top1 = jax.nn.one_hot(experts[:, 0], E, dtype=jnp.float32)
    frac = jnp.mean(top1, axis=0)
    lb = E * jnp.sum(frac * jnp.mean(probs, axis=0))
    # Router z-loss (ST-MoE): keeps logits bounded.
    z = jnp.mean(jax.nn.logsumexp(router_logits, axis=-1) ** 2)
    return experts.astype(jnp.int32), gates, lb, z, probs


def moe_positions(experts, E: int, capacity: int):
    """Deterministic position-in-expert assignment for the ``[N, K]``
    expert choices: choices rank CHOICE-MAJOR (every token's first
    choice before any second choice, token order within a choice), each
    taking the next slot of its expert's queue. Returns ``(pos [N, K]
    int32, keep [N, K] bool)`` — ``keep`` is False for choices past
    ``capacity`` (dropped)."""
    N, K = experts.shape
    flat = jnp.transpose(experts).reshape(K * N)          # choice-major
    oh = jax.nn.one_hot(flat, E, dtype=jnp.int32)         # [KN, E]
    pos_flat = jnp.sum(jnp.cumsum(oh, axis=0) * oh, axis=-1) - 1
    pos = jnp.transpose(pos_flat.reshape(K, N))           # [N, K]
    keep = pos < capacity
    return pos.astype(jnp.int32), keep


def _exchange(buf, plan, axis, residual, kind):
    """One a2a hop of the ``[E, cap, C]`` buffer over ``axis`` (size
    n): canonical row form in, dispatch semantics out. ``kind`` is
    ``DISPATCH`` (→ ``[E_local, n·cap, C]``) or ``COMBINE`` (the
    reverse)."""
    n = _axis_size(axis)
    E, cap, C = buf.shape
    if kind == "DISPATCH":
        out, new_res = _compiler.lower_a2a(plan, buf, axis=axis,
                                           residual=residual, kind=kind)
        # Row block j (= [E/n, cap, C] from rank j) concatenates along
        # the capacity dim: [n, E/n, cap, C] -> [E/n, n*cap, C].
        e_loc = E // n
        return (jnp.transpose(out.reshape(n, e_loc, cap, C),
                              (1, 0, 2, 3)).reshape(e_loc, n * cap, C),
                new_res)
    # COMBINE: [E_local, n*cap, C] -> rows [n, E_local, cap, C] -> a2a
    # -> [E, cap, C] (E = n * E_local, expert-major again).
    e_loc, ncap, C = buf.shape
    cap = ncap // n
    rows = jnp.transpose(buf.reshape(e_loc, n, cap, C),
                         (1, 0, 2, 3)).reshape(n * e_loc, cap, C)
    out, new_res = _compiler.lower_a2a(plan, rows, axis=axis,
                                       residual=residual, kind=kind)
    return out, new_res


def default_a2a_plan(axis=None, *, quantized: bool = False,
                     block: Optional[int] = None,
                     error_feedback: Optional[bool] = None,
                     fused: Optional[bool] = None):
    """The a2a plan of an hvd_ep hop (docs/moe.md): the leg's level is
    the slowest link class one ep hop crosses — the ep axis leads the
    mesh, so it jumps a whole data mesh (``ep_a2a_level``); a custom
    ``axis`` naming data axes maps onto its own widest level.
    Quantization is forced off on an ICI-class hop (the EQuARX rule
    the IR validates)."""
    from ..common.basics import CROSS_AXIS, POD_AXIS

    axes = ({axis} if isinstance(axis, str)
            else set(axis) if axis is not None else {EP_AXIS})
    if POD_AXIS in axes:
        level = _planner.POD
    elif CROSS_AXIS in axes:
        level = _planner.DCN
    elif EP_AXIS in axes and basics.is_initialized():
        level = _planner.ep_a2a_level(basics.data_mesh_shape())
    else:
        level = _planner.ICI
    q = bool(quantized) and level != _planner.ICI
    ef = q if error_feedback is None else (error_feedback and q)
    return _planner.a2a_plan(level, quantized=q, block=block,
                             error_feedback=ef,
                             fused=bool(fused) and q)


def moe_ffn(x, params, *, topk: int = 2, capacity_factor: float = 1.25,
            ep_axis=None, a2a_plan=None, residuals=None,
            router_logits=None) -> Tuple[jnp.ndarray, MoEAux, object]:
    """Top-k gated expert FFN over flattened tokens ``x [N, C]``.

    ``params`` is a dict: ``router [C, E]`` (replicated over hvd_ep),
    ``w1 [E_local, C, F]``, ``b1 [E_local, F]``, ``w2 [E_local, F, C]``,
    ``b2 [E_local, C]`` (expert-sharded: ``E = E_local · ep`` where
    ``ep`` is the bound size of ``ep_axis``). Returns ``(y [N, C],
    :class:`MoEAux`, new_residuals)`` — ``y`` is zero for dropped
    token-choices (the caller's residual connection passes dropped
    tokens through).

    ``a2a_plan`` is the validated dispatch/combine wire plan (default:
    :func:`default_a2a_plan` for ``ep_axis``); ``residuals`` threads the
    int8 error-feedback state as a ``(dispatch_res, combine_res)`` pair
    of zero-initialized buffers (:func:`moe_ef_residuals`) — pass None
    on an exact wire."""
    N, C = x.shape
    ep = _axis_size(ep_axis) if ep_axis is not None else 1
    E_local = params["w1"].shape[0]
    E = E_local * ep
    if params["router"].shape[-1] != E:
        raise ValueError(
            f"router has {params['router'].shape[-1]} experts but "
            f"E_local {E_local} x ep {ep} = {E}")
    capacity = moe_capacity(N, E, capacity_factor, topk)

    experts, gates, lb, z, _probs = moe_router(
        x, params["router"], topk=topk, router_logits=router_logits)
    pos, keep = moe_positions(experts, E, capacity)
    pos_c = jnp.minimum(pos, capacity - 1)

    # Diagnostics: kept choices per global expert + dropped fraction.
    kept_oh = (jax.nn.one_hot(experts, E, dtype=jnp.float32)
               * keep[..., None].astype(jnp.float32))
    load = jnp.sum(kept_oh, axis=(0, 1))                  # [E]
    dropped = 1.0 - jnp.sum(keep) / float(keep.size)
    aux = MoEAux(load_balance_loss=lb, z_loss=z, load=load,
                 dropped_fraction=dropped)

    # Dispatch buffer [E, cap, C]: kept choices scatter-add into their
    # expert's queue slot (disjoint (expert, pos) per kept choice, so
    # the add is a pure placement).
    xk = jnp.broadcast_to(x[:, None, :], (N, topk, C))
    disp = jnp.zeros((E, capacity, C), x.dtype).at[
        experts, pos_c].add(jnp.where(keep[..., None], xk, 0))

    res_d = res_c = None
    if residuals is not None:
        res_d, res_c = residuals
    if ep > 1:
        plan = a2a_plan or default_a2a_plan(ep_axis)
        recv, new_res_d = _exchange(disp, plan, ep_axis, res_d,
                                    "DISPATCH")
    else:
        recv, new_res_d = disp, (None if res_d is None
                                 else jnp.zeros_like(res_d))

    h = jnp.einsum("ekc,ecf->ekf", recv, params["w1"]) \
        + params["b1"][:, None]
    h = nn.gelu(h)
    out = jnp.einsum("ekf,efc->ekc", h, params["w2"]) \
        + params["b2"][:, None]

    if ep > 1:
        back, new_res_c = _exchange(out, plan, ep_axis, res_c, "COMBINE")
    else:
        back, new_res_c = out, (None if res_c is None
                                else jnp.zeros_like(res_c))

    # Combine: each token sums its kept choices' expert outputs,
    # weighted by the renormalized gates.
    yk = back[experts, pos_c]                             # [N, K, C]
    yk = jnp.where(keep[..., None], yk, 0) \
        * gates[..., None].astype(back.dtype)
    y = jnp.sum(yk, axis=1).astype(x.dtype)
    new_residuals = (None if residuals is None
                     else (new_res_d, new_res_c))
    return y, aux, new_residuals


def moe_ef_residuals(n_tokens: int, d_model: int, num_experts: int,
                     capacity_factor: float = 1.25, topk: int = 2,
                     ep: int = 1, dtype=jnp.float32):
    """Zero-initialized error-feedback residual pair for
    :func:`moe_ffn`'s int8 wire: one buffer per exchange direction,
    each matching the exchanged buffer's shape. Thread the returned
    pair through the step's carry exactly like the optimizer's
    ``QuantizedEFState`` residual (docs/moe.md)."""
    E = num_experts
    cap = moe_capacity(n_tokens, E, capacity_factor, topk)
    shape = (E, cap, d_model)
    return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


# ---------------------------------------------------------------------------
# The flax module.
# ---------------------------------------------------------------------------


class MoELayer(nn.Module):
    """Top-k gated MoE FFN (docs/moe.md) — the drop-in for a dense MLP
    block, expert-parallel over the dedicated ``hvd_ep`` axis.

    ``num_experts`` is GLOBAL; with ``ep_axis`` bound inside shard_map
    each rank creates only its ``num_experts / ep`` experts' weights
    (the router is replicated). Sows ``moe_aux_loss`` / ``moe_z_loss``
    / ``moe_expert_load`` / ``moe_dropped_frac`` into
    ``intermediates``; callers add ``aux_weight · aux + z_weight · z``
    to the task loss. ``quantized`` rides the dispatch/combine wire
    blockwise-int8 (error feedback needs the functional
    :func:`moe_ffn` — the flax layer is stateless)."""

    num_experts: int
    d_ff: int
    topk: int = 2
    capacity_factor: float = 1.25
    ep_axis: Optional[str] = None
    quantized: bool = False
    quant_block: int = 256
    dtype: jnp.dtype = jnp.float32
    kernel_init_std: float = 0.02

    @nn.compact
    def __call__(self, x):
        B, T, C = x.shape
        ep = _axis_size(self.ep_axis) if self.ep_axis else 1
        if self.num_experts % ep:
            raise ValueError(
                f"num_experts {self.num_experts} not divisible by "
                f"ep axis size {ep}")
        e_local = self.num_experts // ep
        init = nn.initializers.normal(self.kernel_init_std)
        params = {
            "router": self.param("router", init,
                                 (C, self.num_experts), jnp.float32),
            "w1": self.param("w1", init, (e_local, C, self.d_ff),
                             jnp.float32).astype(self.dtype),
            "b1": self.param("b1", nn.initializers.zeros,
                             (e_local, self.d_ff),
                             jnp.float32).astype(self.dtype),
            "w2": self.param("w2", init, (e_local, self.d_ff, C),
                             jnp.float32).astype(self.dtype),
            "b2": self.param("b2", nn.initializers.zeros, (e_local, C),
                             jnp.float32).astype(self.dtype),
        }
        plan = None
        if ep > 1:
            plan = default_a2a_plan(self.ep_axis,
                                    quantized=self.quantized,
                                    block=self.quant_block,
                                    error_feedback=False)
        y, aux, _ = moe_ffn(x.reshape(B * T, C), params,
                            topk=self.topk,
                            capacity_factor=self.capacity_factor,
                            ep_axis=self.ep_axis, a2a_plan=plan)
        self.sow("intermediates", "moe_aux_loss", aux.load_balance_loss)
        self.sow("intermediates", "moe_z_loss", aux.z_loss)
        self.sow("intermediates", "moe_expert_load", aux.load)
        self.sow("intermediates", "moe_dropped_frac",
                 aux.dropped_fraction)
        return y.reshape(B, T, C)


# ---------------------------------------------------------------------------
# Parameter/gradient plumbing for the hvd_ep mesh.
# ---------------------------------------------------------------------------

#: Leaf names of the expert-sharded half of an MoE params dict.
EXPERT_LEAVES = ("w1", "b1", "w2", "b2")


def ep_param_pspecs(params, ep_axis: str = EP_AXIS):
    """PartitionSpecs for a stacked MoE params tree: expert leaves
    (leading ``[ep, E_local, ...]`` dim) shard over ``ep_axis``,
    everything else (router, dense trunk) replicates."""
    from jax.sharding import PartitionSpec as P

    def spec(path, _leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        return P(ep_axis) if name in EXPERT_LEAVES else P()

    return jax.tree_util.tree_map_with_path(spec, params)


def ep_stack_params(params, ep: int):
    """Split a dense (world-1) MoE params dict into the ``[ep, ...]``
    stacked form ``ep_param_pspecs`` shards: expert leaves split their
    leading expert dim into ``ep`` groups; replicated leaves stay."""
    def split(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in EXPERT_LEAVES:
            E = leaf.shape[0]
            if E % ep:
                raise ValueError(
                    f"expert dim {E} of {name!r} not divisible by "
                    f"ep={ep}")
            return leaf.reshape((ep, E // ep) + leaf.shape[1:])
        return leaf

    return jax.tree_util.tree_map_with_path(split, params)


def ep_mean_dense_grads(grads, ep_axis: str = EP_AXIS,
                        expert_leaves=EXPERT_LEAVES):
    """Normalize a local gradient tree to the GLOBAL-MEAN gradient's ep
    share, ready for the data-axis reduction machinery (docs/moe.md).

    With the batch sharded over ``(hvd_ep, cross, local)`` and the loss
    a global token mean:

    * replicated parameters (router, dense trunk) receive a DIFFERENT
      gradient per ep rank (each saw a different token shard) — they
      take the explicit ``pmean`` over ``hvd_ep``;
    * expert leaves are NEVER averaged across groups (that would mix
      different experts' gradients — the isolation contract the
      dedicated axis exists for). But the owner's autodiff gradient
      already SUMS the contributions every ep source routed to it
      (the combine exchange's backward delivers them), so the
      global-mean normalization is the ``1/ep`` scale, applied locally
      with zero wire.

    After this, a plain ``op=Average`` reduction over the data axes
    (``DistributedOptimizer`` / ``allreduce_pytree``) yields exactly the
    global-mean gradient for every leaf."""
    ep = _axis_size(ep_axis)

    def norm(path, g):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in expert_leaves:
            return g / float(ep)
        return lax.pmean(g, ep_axis)

    return jax.tree_util.tree_map_with_path(norm, grads)
