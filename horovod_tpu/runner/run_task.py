"""Worker entry for the programmatic ``run()`` API.

Reference: ``horovod/runner/run_task.py`` + launch.py:549-568 — the driver
ships a pickled function through the KV store; each worker fetches it,
executes, and puts its return value back under its rank.
"""

from __future__ import annotations

import os
import sys
import traceback

from .http_server import put_data_into_kvstore, read_data_from_kvstore


def main(addr: str, port: int) -> None:
    rank = int(os.environ["HOROVOD_RANK"])
    func, args, kwargs = read_data_from_kvstore(addr, port, "runfunc", "func")
    try:
        result = func(*args, **kwargs)
        put_data_into_kvstore(addr, port, "runfunc_result", str(rank),
                              {"status": "ok", "value": result})
    except BaseException:
        put_data_into_kvstore(addr, port, "runfunc_result", str(rank),
                              {"status": "error",
                               "error": traceback.format_exc()})
        raise


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]))
