"""mpirun launch path for clusters whose process placer is MPI.

Reference surface: ``horovod/runner/mpi_run.py:57-100`` — implementation
detection via ``mpirun --version`` (OpenMPI / IBM Spectrum MPI / MPICH),
per-implementation flag sets, and an ``mpirun`` command that forwards the
env contract to every rank.

TPU-native redesign: the reference's mpirun IS its controller transport
(ranks talk through MPI). Here MPI is purely the process *placer* — the
same role jsrun plays in js_run.py: ``mpirun`` spawns one worker per
slot, each worker derives the HOROVOD_* rank identity from the MPI
environment (``OMPI_COMM_WORLD_*`` for OpenMPI/Spectrum, ``PMI_*`` for
MPICH — bridged in ``common/basics._bridge_mpi_env``), and the native
TCP controller + XLA collectives carry all data. ``--mpi`` on ``hvdrun``
routes here; without a cluster MPI the flag fails loudly with the
alternatives (the reference's _MPI_NOT_FOUND_ERROR_MSG role).
"""

from __future__ import annotations

import os
import shlex
import shutil
import subprocess
from typing import Dict, List, Optional, Sequence

# Implementation names (reference mpi_run.py:25-29).
OPENMPI = "OpenMPI"
SPECTRUM = "SpectrumMPI"
MPICH = "MPICH"
UNKNOWN = "Unknown"
MISSING = "Missing"

# Same fixed-rendezvous convention as the jsrun path: every rank of the
# allocation computes (first host, this port) with no launcher RPC.
from .js_run import apply_rendezvous_defaults  # noqa: E402

MPI_NOT_FOUND_MSG = (
    "no usable MPI found (mpirun missing or unrecognized).\n"
    "Choose one of:\n"
    "1. install Open MPI 4.x / IBM Spectrum MPI / MPICH and re-run with "
    "--mpi;\n"
    "2. use the default ssh/local launcher (no flag);\n"
    "3. on LSF clusters, use --jsrun.")


def detect_mpi_implementation(env: Optional[Dict[str, str]] = None) -> str:
    """Identify the cluster MPI by running ``mpirun --version``
    (reference mpi_run.py:72-107)."""
    if shutil.which("mpirun", path=(env or os.environ).get("PATH")) is None:
        return MISSING
    try:
        r = subprocess.run(["mpirun", "--version"], capture_output=True,
                           text=True, timeout=20, env=env)
    except (OSError, subprocess.TimeoutExpired):
        return MISSING
    out = (r.stdout or "") + (r.stderr or "")
    if r.returncode != 0:
        return MISSING
    if "Open MPI" in out or "OpenRTE" in out:
        return OPENMPI
    if "IBM Spectrum MPI" in out:
        return SPECTRUM
    if "MPICH" in out or "HYDRA" in out:
        return MPICH
    return UNKNOWN


def mpi_available(env: Optional[Dict[str, str]] = None) -> bool:
    """Reference mpi_run.py:57-58."""
    return detect_mpi_implementation(env) not in (UNKNOWN, MISSING)


def _impl_flags(impl: str) -> List[str]:
    """Per-implementation mpirun flags (reference mpi_run.py:30-44).

    OpenMPI: force the ob1 point-to-point layer and drop openib (we only
    need TCP for process placement; the data plane is ours), no process
    binding so jax's threads are free. Spectrum: socket binding. MPICH:
    nothing special.
    """
    if impl == OPENMPI:
        return ["--allow-run-as-root", "--tag-output",
                "-mca", "pml", "ob1", "-mca", "btl", "^openib",
                "-bind-to", "none", "-map-by", "slot"]
    if impl == SPECTRUM:
        return ["--tag-output", "-bind-to", "socket", "-map-by", "socket"]
    return []


def build_mpirun_command(command: Sequence[str],
                         env: Optional[Dict[str, str]] = None,
                         num_proc: Optional[int] = None,
                         hosts: Optional[Dict[str, int]] = None,
                         impl: Optional[str] = None,
                         ssh_port: Optional[int] = None,
                         extra_mpi_args: Optional[str] = None) -> List[str]:
    """Synthesize the mpirun command (reference mpi_run.py:140-210).

    The worker env contract rides an explicit ``env`` prefix inside the
    per-rank command (portable across OpenMPI's ``-x`` and MPICH's
    ``-genvlist``); rank identity comes from the MPI environment at
    worker start via the basics bridge.
    """
    impl = impl if impl is not None else detect_mpi_implementation()
    if impl in (UNKNOWN, MISSING):
        raise RuntimeError(MPI_NOT_FOUND_MSG)
    if num_proc is None:
        if not hosts:
            raise ValueError("num_proc or hosts is required")
        num_proc = sum(hosts.values())

    if not hosts and "HOROVOD_CONTROLLER_ADDR" not in os.environ and \
            not (env or {}).get("HOROVOD_CONTROLLER_ADDR"):
        # Hosts may still be remote (mpirun's own --hostfile via
        # --mpi-args): a 127.0.0.1 rendezvous would never form there.
        import logging

        logging.warning(
            "mpi_run: no -H/--hostfile given; defaulting the controller "
            "rendezvous to 127.0.0.1. If mpirun places ranks on REMOTE "
            "hosts (e.g. via --mpi-args '--hostfile ...'), pass -H or "
            "export HOROVOD_CONTROLLER_ADDR=<rank-0 host> instead.")
    worker_env = apply_rendezvous_defaults(
        dict(env or {}), next(iter(hosts)) if hosts else "127.0.0.1",
        num_proc)

    cmd = ["mpirun", "-np", str(num_proc)]
    if hosts:
        cmd += ["-H", ",".join(f"{h}:{s}" for h, s in hosts.items())]
    cmd += _impl_flags(impl)
    if ssh_port:
        if impl in (OPENMPI, SPECTRUM):
            cmd += ["-mca", "plm_rsh_args", f"-p {ssh_port}"]
        else:
            # Hydra has no portable per-port flag; dropping it silently
            # would dial the wrong sshd with no trail.
            import logging

            logging.warning(
                "mpi_run: --ssh-port is not supported with %s; "
                "configure the port in ~/.ssh/config or via "
                "--mpi-args '-launcher-exec ...' instead", impl)
    if extra_mpi_args:
        cmd += shlex.split(extra_mpi_args)
    # Portable env forwarding: a POSIX `env` prefix in the per-rank
    # command works identically under every implementation (OpenMPI -x /
    # MPICH -genvlist equivalents diverge; the prefix does not).
    cmd += ["env"] + [f"{k}={v}" for k, v in sorted(worker_env.items())]
    cmd += list(command)
    return cmd


def mpi_run(command: Sequence[str], env: Optional[Dict[str, str]] = None,
            num_proc: Optional[int] = None,
            hosts: Optional[Dict[str, int]] = None,
            verbose: int = 0, ssh_port: Optional[int] = None,
            extra_mpi_args: Optional[str] = None) -> int:
    """Build and exec the mpirun command (reference mpi_run.py:123-226)."""
    from . import safe_shell_exec

    impl = detect_mpi_implementation()
    if impl in (UNKNOWN, MISSING):
        raise RuntimeError(MPI_NOT_FOUND_MSG)
    cmd = build_mpirun_command(command, env=env, num_proc=num_proc,
                               hosts=hosts, impl=impl, ssh_port=ssh_port,
                               extra_mpi_args=extra_mpi_args)
    line = " ".join(shlex.quote(c) for c in cmd)
    if verbose >= 2:
        print(line)
    # Per-rank identity must come from MPI's own env at worker start —
    # a stale HOROVOD_* identity var in the LAUNCHER's environment would
    # reach every worker identically (the bridge's setdefault keeps it)
    # and wedge the rendezvous or the hierarchical topology check.
    exec_env = {k: v for k, v in os.environ.items()
                if k not in ("HOROVOD_RANK", "HOROVOD_LOCAL_RANK",
                             "HOROVOD_CROSS_RANK", "HOROVOD_LOCAL_SIZE",
                             "HOROVOD_CROSS_SIZE")}
    return safe_shell_exec.execute(line, env=exec_env)
