"""Translate launcher args / YAML config into HOROVOD_* env vars.

Reference surface: ``horovod/runner/common/util/config_parser.py`` (199 LoC)
— the three equivalent config layers (env vars, CLI flags, YAML file) all
converge on the env the core reads at init (SURVEY §5.6;
operations.cc:416-518).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

# arg attribute → env var. Same knob names as the reference so users can
# carry settings over unchanged (common.h:64-90).
_ARG_ENV = {
    "fusion_threshold_mb": "HOROVOD_FUSION_THRESHOLD",  # MB → bytes below
    "cycle_time_ms": "HOROVOD_CYCLE_TIME",
    "cache_capacity": "HOROVOD_CACHE_CAPACITY",
    "hierarchical_allreduce": "HOROVOD_HIERARCHICAL_ALLREDUCE",
    "hierarchical_allgather": "HOROVOD_HIERARCHICAL_ALLGATHER",
    "autotune": "HOROVOD_AUTOTUNE",
    "autotune_log_file": "HOROVOD_AUTOTUNE_LOG",
    "autotune_warmup_samples": "HOROVOD_AUTOTUNE_WARMUP_SAMPLES",
    "autotune_steps_per_sample": "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE",
    "autotune_bayes_opt_max_samples": "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES",
    "autotune_gaussian_process_noise": "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE",
    "autotune_warm_start": "HOROVOD_AUTOTUNE_WARM_START",
    "timeline_filename": "HOROVOD_TIMELINE",
    "timeline_mark_cycles": "HOROVOD_TIMELINE_MARK_CYCLES",
    "no_stall_check": "HOROVOD_STALL_CHECK_DISABLE",
    "stall_check_warning_time_seconds": "HOROVOD_STALL_CHECK_TIME_SECONDS",
    "stall_check_shutdown_time_seconds": "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS",
    "log_level": "HOROVOD_LOG_LEVEL",
    "log_hide_timestamp": "HOROVOD_LOG_HIDE_TIME",
}


def _set(env: Dict[str, str], key: str, value: Any) -> None:
    if value is None:
        return
    if isinstance(value, bool):
        if value:
            env[key] = "1"
        return
    env[key] = str(value)


def set_env_from_args(env: Dict[str, str], args) -> Dict[str, str]:
    """Apply parsed CLI args onto ``env`` (reference
    config_parser.set_env_from_args)."""
    for attr, key in _ARG_ENV.items():
        value = getattr(args, attr, None)
        if attr == "fusion_threshold_mb" and value is not None:
            value = int(value * 1024 * 1024)
        _set(env, key, value)
    if getattr(args, "elastic", False):
        env["HOROVOD_ELASTIC"] = "1"
    return env


def parse_config_file(path: str, args) -> None:
    """Overlay a YAML config file onto an argparse namespace for every value
    the user did not set on the command line (reference
    launch.py:470-474 + config_parser.py). Nested sections mirror the
    reference schema (fusion/timeline/autotune/stall_check/logging)."""
    import yaml

    with open(path) as f:
        config = yaml.safe_load(f) or {}

    def _maybe(attr: str, value: Any) -> None:
        if value is not None and getattr(args, attr, None) in (None, False):
            setattr(args, attr, value)

    _maybe("fusion_threshold_mb", config.get("fusion", {}).get("threshold-mb"))
    _maybe("cycle_time_ms", config.get("fusion", {}).get("cycle-time-ms"))
    _maybe("cache_capacity", config.get("cache", {}).get("capacity"))
    timeline = config.get("timeline", {})
    _maybe("timeline_filename", timeline.get("filename"))
    _maybe("timeline_mark_cycles", timeline.get("mark-cycles"))
    autotune = config.get("autotune", {})
    _maybe("autotune", autotune.get("enabled"))
    _maybe("autotune_log_file", autotune.get("log-file"))
    _maybe("autotune_warmup_samples", autotune.get("warmup-samples"))
    _maybe("autotune_steps_per_sample", autotune.get("steps-per-sample"))
    _maybe("autotune_bayes_opt_max_samples",
           autotune.get("bayes-opt-max-samples"))
    _maybe("autotune_gaussian_process_noise",
           autotune.get("gaussian-process-noise"))
    _maybe("autotune_warm_start", autotune.get("warm-start"))
    stall = config.get("stall-check", {})
    if stall.get("enabled") is False:
        args.no_stall_check = True
    _maybe("stall_check_warning_time_seconds", stall.get("warning-time-seconds"))
    _maybe("stall_check_shutdown_time_seconds",
           stall.get("shutdown-time-seconds"))
    library = config.get("library", {})
    _maybe("mpi_threads_disable", library.get("mpi-threads-disable"))
    logging_cfg = config.get("logging", {})
    _maybe("log_level", logging_cfg.get("level"))
    _maybe("log_hide_timestamp", logging_cfg.get("hide-timestamp"))


def validate_config_args(args) -> None:
    """Sanity checks mirroring config_parser.validate_config_args."""
    if getattr(args, "fusion_threshold_mb", None) is not None \
            and args.fusion_threshold_mb < 0:
        raise ValueError("--fusion-threshold-mb must be >= 0")
    if getattr(args, "cycle_time_ms", None) is not None \
            and args.cycle_time_ms < 0:
        raise ValueError("--cycle-time-ms must be >= 0")
