"""Static (fixed world) job launch: one worker process per slot.

Reference surface: ``horovod/runner/gloo_run.py`` (331 LoC) — compute slot
assignments, build per-slot commands (local exec or ssh), inject the
``HOROVOD_*`` env contract, launch all slots on threads, and fail fast: if
any worker exits non-zero, terminate the rest (gloo_run.py:221-266).

TPU redesign: workers bootstrap against the rank-0 native coordinator.
By default (``controller_port=None``) the launcher does NOT pick the port:
it advertises its rendezvous KV (``HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT``)
and sets ``HOROVOD_CONTROLLER_BOOTSTRAP=kv`` so rank 0 binds an
OS-assigned port on its own host and publishes ``(hostname, ifaces,
port)`` for the other ranks to resolve (runner/bootstrap.py — the same
rank-0-binds-and-reports protocol the elastic driver uses,
elastic/driver.py:255-303; reference analogue: the static launcher's
driver/task address exchange, driver_service.py). Passing an explicit
``controller_port`` keeps the legacy fixed-port contract for callers that
manage their own port space (spark/ray/js_run).
"""

from __future__ import annotations

import os
import shlex
import socket
import sys
import threading
from typing import Dict, List, Optional, Sequence

from . import safe_shell_exec
from .hosts import SlotInfo

SSH_COMMAND_PREFIX = "ssh -o PasswordAuthentication=no -o StrictHostKeyChecking=no"


def is_local_host(hostname: str) -> bool:
    return hostname in ("localhost", "127.0.0.1", socket.gethostname(),
                        socket.getfqdn())


def slot_env(slot: SlotInfo, controller_addr: Optional[str],
             controller_port: Optional[int],
             rendezvous_port: Optional[int] = None,
             rendezvous_addr: Optional[str] = None,
             base_env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """The launcher-injected env contract (reference gloo_run.py:65-76).

    ``controller_port=None`` selects the KV bootstrap protocol: rank 0
    binds and publishes its own port (runner/bootstrap.py) instead of the
    launcher dictating one. ``rendezvous_addr`` is the address of the
    launcher's KV server as reachable from this slot's host — NOT the
    rank-0 worker host (they differ in general; conflating them was the
    round-3 flaw).
    """
    env = dict(base_env if base_env is not None else os.environ)
    env.update({
        "HOROVOD_RANK": str(slot.rank),
        "HOROVOD_SIZE": str(slot.size),
        "HOROVOD_LOCAL_RANK": str(slot.local_rank),
        "HOROVOD_LOCAL_SIZE": str(slot.local_size),
        "HOROVOD_CROSS_RANK": str(slot.cross_rank),
        "HOROVOD_CROSS_SIZE": str(slot.cross_size),
        "HOROVOD_HOSTNAME": slot.hostname,
    })
    if controller_port is None:
        if rendezvous_port is None:
            raise ValueError("KV bootstrap (controller_port=None) needs a "
                             "running rendezvous server")
        env["HOROVOD_CONTROLLER_BOOTSTRAP"] = "kv"
        env.pop("HOROVOD_CONTROLLER_ADDR", None)
        env.pop("HOROVOD_CONTROLLER_PORT", None)
    else:
        # Symmetric strip: a nested launch from inside a kv-bootstrapped
        # worker must not let the inherited flag override the explicit
        # port contract.
        env.pop("HOROVOD_CONTROLLER_BOOTSTRAP", None)
        env["HOROVOD_CONTROLLER_ADDR"] = controller_addr
        env["HOROVOD_CONTROLLER_PORT"] = str(controller_port)
    if rendezvous_port is not None:
        env["HOROVOD_GLOO_RENDEZVOUS_ADDR"] = \
            rendezvous_addr if rendezvous_addr is not None else controller_addr
        env["HOROVOD_GLOO_RENDEZVOUS_PORT"] = str(rendezvous_port)
    return env


def get_run_command(command: Sequence[str], hostname: str,
                    env: Dict[str, str],
                    ssh_port: Optional[int] = None) -> str:
    """Build the shell command for one slot; remote slots are wrapped in ssh
    with the env contract inlined (reference gloo_run.py:133-178). Shared by
    the static and elastic launchers."""
    cmd = " ".join(shlex.quote(c) for c in command)
    if is_local_host(hostname):
        return cmd
    # ssh: env does not propagate, so inline every HOROVOD_* knob (the
    # launcher-built tuning env included) plus the interpreter basics —
    # the reference forwards the whole run env the same way
    # (gloo_run.py:65-101).
    keys = sorted(k for k in env
                  if k.startswith("HOROVOD_") or k in ("PATH", "PYTHONPATH"))
    exported = " ".join(f"{k}={shlex.quote(env[k])}" for k in keys)
    remote = f"cd {shlex.quote(os.getcwd())} ; env {exported} {cmd}"
    port = f" -p {int(ssh_port)}" if ssh_port else ""
    return f"{SSH_COMMAND_PREFIX}{port} {hostname} {shlex.quote(remote)}"


def rendezvous_advertise_addr(slots: List[SlotInfo]) -> str:
    """The launcher's own address as workers should dial it: loopback when
    every slot is local, this host's FQDN otherwise (the KV server binds
    INADDR_ANY)."""
    if all(is_local_host(s.hostname) for s in slots):
        return "127.0.0.1"
    return socket.getfqdn()


def launch_static(command: Sequence[str], slots: List[SlotInfo],
                  controller_port: Optional[int] = None,
                  rendezvous_port: Optional[int] = None,
                  env: Optional[Dict[str, str]] = None,
                  verbose: int = 0,
                  prefix_output_with_rank: bool = True,
                  ssh_port: Optional[int] = None) -> None:
    """Launch every slot, stream output, fail fast on first failure
    (reference launch_gloo, gloo_run.py:221-266).

    The coordinator (native rank-0 controller) runs inside the rank-0
    worker. With ``controller_port=None`` (default) its address/port reach
    the other workers through the KV bootstrap protocol (module
    docstring); an explicit port reverts to launcher-dictated addressing.
    Raises RuntimeError listing failed ranks if any worker exits non-zero.
    """
    if controller_port is None and rendezvous_port is None:
        # Validate HERE, not in the per-slot threads (where a raise is
        # swallowed and the launch would silently no-op).
        raise ValueError("KV bootstrap (controller_port=None) needs a "
                         "running rendezvous server (rendezvous_port)")
    controller_addr = slots[0].hostname
    if is_local_host(controller_addr):
        controller_addr = "127.0.0.1"
    rdv_addr = rendezvous_advertise_addr(slots)

    if controller_port is None:
        # One world id per launch: the KV bootstrap key is anchored to
        # the launcher invocation (the static analogue of the elastic
        # driver's world_id), so ranks of different launches sharing a
        # KV server can never cross-read each other's port reports.
        import uuid

        env = dict(env if env is not None else os.environ)
        # Unconditional: an inherited id (e.g. a nested launch from
        # inside a worker whose env carries the outer launch's value)
        # must not alias two launches onto the same KV key.
        env["HOROVOD_BOOTSTRAP_WORLD_ID"] = uuid.uuid4().hex[:12]

    abort = threading.Event()
    exit_codes: Dict[int, int] = {}
    lock = threading.Lock()

    def _run_slot(slot: SlotInfo) -> None:
        senv = slot_env(slot, controller_addr, controller_port,
                        rendezvous_port, rendezvous_addr=rdv_addr,
                        base_env=env)
        cmd = get_run_command(command, slot.hostname, senv,
                              ssh_port=ssh_port)
        if verbose >= 2:
            print(f"[launcher] rank {slot.rank} on {slot.hostname}: {cmd}",
                  file=sys.stderr)
        code = safe_shell_exec.execute(
            cmd, env=senv,
            index=slot.rank if prefix_output_with_rank else None,
            events=[abort])
        with lock:
            exit_codes[slot.rank] = code
        if code != 0:
            abort.set()  # fail fast: kill the other workers

    threads = [threading.Thread(target=_run_slot, args=(s,), daemon=True)
               for s in slots]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    failures = {r: c for r, c in exit_codes.items() if c != 0}
    if failures:
        raise RuntimeError(
            "horovod_tpu job failed; non-zero exit codes by rank: "
            + ", ".join(f"{r}→{c}" for r, c in sorted(failures.items())))
