"""Subprocess execution with group kill and event-driven termination.

Reference surface: ``horovod/runner/common/util/safe_shell_exec.py`` (227
LoC): run a command in its own process group, stream stdout/stderr with an
optional per-rank prefix, and terminate the whole group when any of the
supplied ``threading.Event``s fires (the launcher's fail-fast path,
gloo_run.py:260-266).

Redesign: the reference interposes a fork()ed "middleman" process so the
group survives launcher death; here a watcher *thread* + ``start_new_session``
keeps the same kill semantics in-process, which is simpler and sufficient
because the launcher owns worker lifetime on TPU pods.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, TextIO

GRACEFUL_TERMINATION_TIME_S = 2.0


def terminate_process_group(proc: subprocess.Popen,
                            timeout: float = GRACEFUL_TERMINATION_TIME_S) -> None:
    """SIGTERM the process group, escalate to SIGKILL after ``timeout``."""
    if proc.poll() is not None:
        return
    try:
        pgid = os.getpgid(proc.pid)
    except (ProcessLookupError, PermissionError):
        return
    try:
        os.killpg(pgid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        return
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return
        time.sleep(0.05)
    try:
        os.killpg(pgid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass


def _forward_stream(stream, sink: TextIO, prefix: Optional[str]) -> None:
    for line in iter(stream.readline, ""):
        if prefix is not None:
            sink.write(f"[{prefix}]{line}")
        else:
            sink.write(line)
        sink.flush()
    stream.close()


def execute(command,
            env: Optional[Dict[str, str]] = None,
            stdout: Optional[TextIO] = None,
            stderr: Optional[TextIO] = None,
            index: Optional[object] = None,
            events: Optional[Sequence[threading.Event]] = None,
            shell: bool = True) -> int:
    """Run ``command``; return its exit code.

    Mirrors safe_shell_exec.execute: output is line-forwarded (optionally
    ``[index]``-prefixed); if any event in ``events`` fires the whole process
    group is terminated and the exit code reflects the signal.
    """
    stdout = stdout if stdout is not None else sys.stdout
    stderr = stderr if stderr is not None else sys.stderr
    proc = subprocess.Popen(
        command,
        shell=shell,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        bufsize=1,
        start_new_session=True,  # own process group for clean group kill
    )
    prefix = str(index) if index is not None else None
    threads: List[threading.Thread] = []
    for stream, sink in ((proc.stdout, stdout), (proc.stderr, stderr)):
        t = threading.Thread(target=_forward_stream, args=(stream, sink, prefix),
                             daemon=True)
        t.start()
        threads.append(t)

    stop_watch = threading.Event()
    if events:
        def _watch():
            while not stop_watch.is_set():
                for ev in events:
                    if ev.is_set():
                        terminate_process_group(proc)
                        return
                time.sleep(0.05)

        watcher = threading.Thread(target=_watch, daemon=True)
        watcher.start()

    proc.wait()
    stop_watch.set()
    for t in threads:
        t.join(timeout=1.0)
    return proc.returncode
