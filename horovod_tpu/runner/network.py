"""Pickle-over-TCP RPC with HMAC-signed frames.

Reference surface: ``horovod/runner/common/util/network.py`` (268 LoC) —
``BasicService`` (multi-threaded socket server dispatching request objects
to ``_handle``) and ``BasicClient`` (connect, send request, await response),
with every frame signed by an HMAC of the job's secret key so a stray
connection can't inject pickles. Used by the driver/task bootstrap services
and the elastic worker-notification channel (§2.3, §5.3 of the survey).

Wire format per message: ``len(4B big-endian) | hmac(32B) | pickle-bytes``.
"""

from __future__ import annotations

import hmac
import pickle
import socket
import socketserver
import struct
import threading
from typing import Any, Optional, Tuple

from .secret import DIGEST_LENGTH_BYTES

_LEN = struct.Struct(">I")


def find_free_port() -> int:
    """Probe a free TCP port on this machine. NOTE: only authoritative for
    sockets bound locally — a port handed to a *remote* host may be taken
    there; callers on remote paths must tolerate bind failure (the elastic
    driver allocates a fresh port per world incarnation for this reason)."""
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class PingRequest:
    pass


class PingResponse:
    def __init__(self, service_name: str, source_address: str):
        self.service_name = service_name
        self.source_address = source_address


class AckResponse:
    """Generic empty OK response."""


def _sign(key: bytes, payload: bytes) -> bytes:
    return hmac.new(key, payload, "sha256").digest()


def write_message(sock: socket.socket, obj: Any, key: bytes) -> None:
    payload = pickle.dumps(obj)
    sock.sendall(_LEN.pack(len(payload)) + _sign(key, payload) + payload)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("connection closed mid-message")
        buf += chunk
    return buf


def read_message(sock: socket.socket, key: bytes) -> Any:
    (length,) = _LEN.unpack(_read_exact(sock, _LEN.size))
    digest = _read_exact(sock, DIGEST_LENGTH_BYTES)
    payload = _read_exact(sock, length)
    if not hmac.compare_digest(digest, _sign(key, payload)):
        raise PermissionError("HMAC mismatch on RPC message — wrong secret key")
    return pickle.loads(payload)


class BasicService:
    """Threaded TCP server dispatching pickled requests to ``_handle``
    (reference network.py:50-148)."""

    def __init__(self, service_name: str, key: bytes, nics=None):
        self._service_name = service_name
        self._key = key
        service = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                try:
                    req = read_message(sock, service._key)
                    resp = service._handle(req, self.client_address)
                    write_message(sock, resp, service._key)
                except (ConnectionError, PermissionError, EOFError):
                    pass

        class _Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server(("0.0.0.0", 0), _Handler)
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def _handle(self, req: Any, client_address: Tuple[str, int]) -> Any:
        if isinstance(req, PingRequest):
            return PingResponse(self._service_name, client_address[0])
        raise NotImplementedError(
            f"{self._service_name}: unknown request {type(req).__name__}")

    @property
    def port(self) -> int:
        return self._port

    def addresses(self) -> Tuple[str, int]:
        return (socket.gethostname(), self._port)

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class BasicClient:
    """Connects to a BasicService and exchanges one request/response per
    call (reference network.py:150-268)."""

    def __init__(self, service_name: str, addr: str, port: int, key: bytes,
                 attempts: int = 3, timeout: float = 10.0):
        self._service_name = service_name
        self._addr = addr
        self._port = port
        self._key = key
        self._attempts = attempts
        self._timeout = timeout

    def _send(self, req: Any) -> Any:
        last_err: Optional[Exception] = None
        for _ in range(self._attempts):
            try:
                with socket.create_connection((self._addr, self._port),
                                              timeout=self._timeout) as sock:
                    write_message(sock, req, self._key)
                    return read_message(sock, self._key)
            except (OSError, ConnectionError) as e:
                last_err = e
        raise ConnectionError(
            f"{self._service_name} RPC to {self._addr}:{self._port} failed: "
            f"{last_err}")

    def ping(self) -> PingResponse:
        return self._send(PingRequest())
