"""Pickle-over-TCP RPC with HMAC-signed frames.

Reference surface: ``horovod/runner/common/util/network.py`` (268 LoC) —
``BasicService`` (multi-threaded socket server dispatching request objects
to ``_handle``) and ``BasicClient`` (connect, send request, await response),
with every frame signed by an HMAC of the job's secret key so a stray
connection can't inject pickles. Used by the driver/task bootstrap services
and the elastic worker-notification channel (§2.3, §5.3 of the survey).

Wire format per message: ``len(4B big-endian) | hmac(32B) | pickle-bytes``.
"""

from __future__ import annotations

import hmac
import os
import pickle
import random
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Optional, Tuple

from ..chaos import injector as chaos
from ..common import counters
from .secret import DIGEST_LENGTH_BYTES

_LEN = struct.Struct(">I")


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    try:
        return float(v) if v not in (None, "") else default
    except ValueError:
        return default


def find_free_port() -> int:
    """Probe a free TCP port on this machine. NOTE: only authoritative for
    sockets bound locally — a port handed to a *remote* host may be taken
    there; callers on remote paths must tolerate bind failure (the elastic
    driver allocates a fresh port per world incarnation for this reason)."""
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class PingRequest:
    pass


class PingResponse:
    def __init__(self, service_name: str, source_address: str):
        self.service_name = service_name
        self.source_address = source_address


class AckResponse:
    """Generic empty OK response."""


def _sign(key: bytes, payload: bytes) -> bytes:
    return hmac.new(key, payload, "sha256").digest()


def write_message(sock: socket.socket, obj: Any, key: bytes) -> None:
    payload = pickle.dumps(obj)
    sock.sendall(_LEN.pack(len(payload)) + _sign(key, payload) + payload)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("connection closed mid-message")
        buf += chunk
    return buf


def read_message(sock: socket.socket, key: bytes) -> Any:
    (length,) = _LEN.unpack(_read_exact(sock, _LEN.size))
    digest = _read_exact(sock, DIGEST_LENGTH_BYTES)
    payload = _read_exact(sock, length)
    if not hmac.compare_digest(digest, _sign(key, payload)):
        raise PermissionError("HMAC mismatch on RPC message — wrong secret key")
    return pickle.loads(payload)


class BasicService:
    """Threaded TCP server dispatching pickled requests to ``_handle``
    (reference network.py:50-148)."""

    def __init__(self, service_name: str, key: bytes, nics=None):
        self._service_name = service_name
        self._key = key
        service = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                try:
                    # Injected 'drop' raises here: the request goes
                    # unanswered and the client sees its peer hang up —
                    # the server-side half of a lost message.
                    chaos.inject("network.server.handle",
                                 service=service._service_name)
                    req = read_message(sock, service._key)
                    resp = service._handle(req, self.client_address)
                    write_message(sock, resp, service._key)
                except (ConnectionError, PermissionError, EOFError):
                    pass

        class _Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server(("0.0.0.0", 0), _Handler)
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def _handle(self, req: Any, client_address: Tuple[str, int]) -> Any:
        if isinstance(req, PingRequest):
            return PingResponse(self._service_name, client_address[0])
        raise NotImplementedError(
            f"{self._service_name}: unknown request {type(req).__name__}")

    @property
    def port(self) -> int:
        return self._port

    def addresses(self) -> Tuple[str, int]:
        return (socket.gethostname(), self._port)

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class BasicClient:
    """Connects to a BasicService and exchanges one request/response per
    call (reference network.py:150-268).

    Retries failed sends with capped exponential backoff + full jitter,
    bounded by both ``attempts`` and an optional total-deadline budget
    (``total_deadline`` seconds across all attempts of one ``_send``,
    overridable via ``HOROVOD_RPC_DEADLINE_SECS``; 0 disables the budget
    and the attempt count alone bounds the call). Backoff shape comes from
    ``HOROVOD_RPC_RETRY_BASE_SECS`` (default 0.05) doubling up to
    ``HOROVOD_RPC_RETRY_MAX_SECS`` (default 2.0).
    """

    def __init__(self, service_name: str, addr: str, port: int, key: bytes,
                 attempts: int = 3, timeout: float = 10.0,
                 total_deadline: Optional[float] = None):
        self._service_name = service_name
        self._addr = addr
        self._port = port
        self._key = key
        self._attempts = max(1, attempts)
        self._timeout = timeout
        self._retry_base = _env_float("HOROVOD_RPC_RETRY_BASE_SECS", 0.05)
        self._retry_max = _env_float("HOROVOD_RPC_RETRY_MAX_SECS", 2.0)
        self._deadline_budget = _env_float(
            "HOROVOD_RPC_DEADLINE_SECS", 0.0) \
            if total_deadline is None else total_deadline

    def _send_once(self, req: Any) -> Any:
        with socket.create_connection((self._addr, self._port),
                                      timeout=self._timeout) as sock:
            write_message(sock, req, self._key)
            return read_message(sock, self._key)

    def _send(self, req: Any) -> Any:
        start = time.monotonic()
        deadline = start + self._deadline_budget \
            if self._deadline_budget > 0 else None
        last_err: Optional[Exception] = None
        attempt = 0
        while attempt < self._attempts:
            attempt += 1
            try:
                act = chaos.inject("network.client.send",
                                   service=self._service_name,
                                   addr=f"{self._addr}:{self._port}",
                                   attempt=attempt)
                if act == "dup":
                    # Deliver the request twice (a retransmitted message
                    # both copies of which arrived): services must be
                    # idempotent per request.
                    try:
                        self._send_once(req)
                    except (OSError, ConnectionError):
                        pass
                return self._send_once(req)
            except (OSError, ConnectionError) as e:
                last_err = e
                if attempt >= self._attempts:
                    break
                # Capped exponential backoff with jitter in [0.5x, 1.5x):
                # concurrent clients of a recovering service must not
                # retry in lockstep.
                delay = min(self._retry_max,
                            self._retry_base * (2 ** (attempt - 1)))
                delay *= 0.5 + random.random()
                if deadline is not None and \
                        time.monotonic() + delay > deadline:
                    break
                counters.increment("rpc.client.retry",
                                   attrs={"service": self._service_name,
                                          "attempt": attempt})
                time.sleep(delay)
        elapsed = time.monotonic() - start
        counters.increment("rpc.client.failure",
                           attrs={"service": self._service_name,
                                  "attempts": attempt})
        raise ConnectionError(
            f"{self._service_name} RPC to {self._addr}:{self._port} failed "
            f"after {attempt} attempt(s) over {elapsed:.2f}s: {last_err}")

    def ping(self) -> PingResponse:
        return self._send(PingRequest())
