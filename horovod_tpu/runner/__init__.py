"""Launcher package: ``hvdrun`` CLI + programmatic ``run()`` API.

Reference surface: ``horovod/runner/__init__.py`` (205 LoC) — the
``horovod.run(func, np=..., hosts=...)`` API that executes a pickled
function across the job and returns the per-rank results (launch.py:549-568:
func shipped via KV store, executed by run_task.py).
"""

from __future__ import annotations

import os
import sys
from typing import Any, Callable, List, Optional

from .hosts import get_host_assignments, parse_host_files, parse_hosts
from .http_server import KVStoreServer
from .launch import run_commandline  # noqa: F401
from .static_run import launch_static


def _dumps_call(func, args: tuple, kwargs: dict) -> bytes:
    """Ship (func, args, kwargs) as data — cloudpickle when available (any
    closure), stdlib pickle otherwise (top-level functions only)."""
    payload = (func, args, kwargs)
    try:
        import cloudpickle

        return cloudpickle.dumps(payload)
    except ImportError:
        import pickle

        return pickle.dumps(payload)


def run(func: Callable[..., Any],
        args: tuple = (),
        kwargs: Optional[dict] = None,
        np: int = 1,
        hosts: Optional[str] = None,
        hostfile: Optional[str] = None,
        env: Optional[dict] = None,
        verbose: int = 0) -> List[Any]:
    """Run ``func(*args, **kwargs)`` on ``np`` horovod_tpu processes and
    return a list of the ``np`` return values ordered by rank (reference:
    horovod.run, runner/__init__.py:90).

    The function (with its closure) is cloudpickled into an in-process KV
    store; workers fetch and execute it under the full launcher env
    contract, so ``hvd.init()`` inside ``func`` joins the job world.
    """
    kwargs = kwargs or {}
    if hostfile:
        host_infos = parse_host_files(hostfile)
    elif hosts:
        host_infos = parse_hosts(hosts)
    else:
        host_infos = parse_hosts(f"localhost:{np}")
    slots = get_host_assignments(host_infos, np)

    from . import secret

    token = secret.make_secret_key().hex()
    kv = KVStoreServer(auth_token=token)
    kv_port = kv.start_server()
    kv.store.put("runfunc", "func", _dumps_call(func, args, kwargs))

    # The KV store lives in THIS (driver) process — workers must dial back
    # here, not the first worker host.
    from .static_run import rendezvous_advertise_addr

    addr = rendezvous_advertise_addr(slots)
    command = [sys.executable, "-m", "horovod_tpu.runner.run_task",
               addr, str(kv_port)]
    base_env = dict(env if env is not None else os.environ)
    base_env.setdefault("PYTHONPATH", os.pathsep.join(p for p in sys.path if p))
    base_env["HOROVOD_KV_TOKEN"] = token

    try:
        # controller_port=None → KV bootstrap through this server
        # (rank 0 binds and reports; no launcher-side port guess).
        launch_static(command, slots, controller_port=None,
                      rendezvous_port=kv_port, env=base_env, verbose=verbose)
        results: List[Any] = []
        import pickle

        for rank in range(np):
            raw = kv.store.wait_for("runfunc_result", str(rank), timeout=5.0)
            if raw is None:
                raise RuntimeError(f"rank {rank} produced no result")
            payload = pickle.loads(raw)
            if payload["status"] != "ok":
                raise RuntimeError(
                    f"rank {rank} failed:\n{payload['error']}")
            results.append(payload["value"])
        return results
    finally:
        kv.shutdown_server()
