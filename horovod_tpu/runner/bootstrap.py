"""Static-launch controller bootstrap over the rendezvous KV.

Reference surface: ``horovod/runner/driver/driver_service.py`` +
``launch.py:546`` — the reference's *static* launcher also runs interface
discovery and a driver/task address-exchange protocol before workers form
the ring; only its elastic path differs in packaging.

TPU redesign (round 4, unifying static onto the proven elastic protocol,
elastic/driver.py:255-303): the launcher no longer guesses a controller
port with ``find_free_port()`` on *its* host — a guess that can collide on
the rank-0 host and hands out ``slots[0].hostname`` even when workers
cannot resolve it. Instead:

1. the launcher injects ``HOROVOD_CONTROLLER_BOOTSTRAP=kv`` plus the
   rendezvous KV coordinates (``HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT`` —
   the launcher's own KV server) and NO controller address;
2. rank 0 binds an OS-assigned port on ITS host
   (``HOROVOD_CONTROLLER_PORT=0`` → native ``Listen(0)``) and, the moment
   the listener is up (bound-port watcher, cc/__init__.py), publishes
   ``{hostname, port, ifaces}`` into the KV;
3. every other rank polls the KV, then picks rank-0's address on an
   interface common to both hosts (``nic.select_controller_addr``,
   pairwise — the same intersection the elastic driver computes), falling
   back to the published hostname only when there is no usable
   intersection.

Port allocation happens on the host that uses it (race-free by
construction), and address selection uses routable-interface evidence
rather than the hostname-resolves-everywhere assumption.
"""

from __future__ import annotations

import json
import os
import socket
import time
from typing import Optional

from ..chaos import injector as chaos

_SCOPE = "controller"
_KEY = "static"

# KV key = launcher world id + per-process bootstrap generation.
#
# The world id (HOROVOD_BOOTSTRAP_WORLD_ID, one fresh value per
# launch_static invocation — the static analogue of the elastic driver's
# world_id) anchors the key to the launcher run, so ranks of different
# launches sharing a KV server can never cross-read port reports.
#
# The generation handles in-process shutdown()+init() cycles: every rank
# runs apply() again, in lockstep, so per-process counters agree — and
# keying by generation keeps a re-init's workers from dialing the
# PREVIOUS incarnation's dead listener. NOTE (ADVICE r4): this requires
# whole-world re-init. A single worker relaunched by an external
# supervisor restarts at generation 1 while peers are at N and will time
# out after HOROVOD_BOOTSTRAP_TIMEOUT — per-worker churn is the elastic
# driver's job (elastic/driver.py), not the static bootstrap's.
_generation = [0]


def _gen_key() -> str:
    world = os.environ.get("HOROVOD_BOOTSTRAP_WORLD_ID", "local")
    return f"{_KEY}.{world}.{_generation[0]}"


def _kv_coords():
    return (os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"],
            int(os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"]))


def bootstrap_requested() -> bool:
    return os.environ.get("HOROVOD_CONTROLLER_BOOTSTRAP") == "kv"


def publish_controller(port: int, key: Optional[str] = None) -> None:
    """Rank 0: publish the bound controller port plus this host's identity
    and interface table for the workers' pairwise NIC intersection."""
    from . import nic
    from .http_server import put_data_into_kvstore

    # Injection point for the static bootstrap: a crash/stall here is
    # rank 0 dying (or hanging) between binding its controller port and
    # publishing it — the failure mode HOROVOD_BOOTSTRAP_TIMEOUT bounds.
    chaos.inject("bootstrap.rendezvous", phase="kv_publish")
    addr, kv_port = _kv_coords()
    try:
        ifaces = nic.list_interfaces()
    except OSError:
        ifaces = []
    payload = json.dumps({
        # Prefer the launcher-assigned name (slot_env's HOROVOD_HOSTNAME):
        # ssh already proved it reachable from the launcher, and the
        # hostfile names are what remote workers can resolve — a bare
        # gethostname() may be short/misconfigured on clusters.
        "hostname": os.environ.get("HOROVOD_HOSTNAME",
                                   socket.gethostname()),
        "port": int(port),
        "ifaces": [[name, ip] for name, ip in ifaces],
    })
    put_data_into_kvstore(addr, kv_port, _SCOPE, key or _gen_key(),
                          payload.encode())


def resolve_controller(timeout: Optional[float] = None) -> None:
    """Non-zero ranks: poll the KV for rank 0's report, select a routable
    address, and write the resolved ``HOROVOD_CONTROLLER_ADDR/PORT`` into
    the environment for the native core to consume."""
    from . import nic
    from .http_server import read_data_from_kvstore
    from .static_run import is_local_host

    import urllib.error

    chaos.inject("bootstrap.rendezvous", phase="kv_resolve")
    if timeout is None:
        timeout = float(os.environ.get("HOROVOD_BOOTSTRAP_TIMEOUT", "300"))
    addr, kv_port = _kv_coords()
    deadline = time.monotonic() + timeout
    key = _gen_key()
    while True:
        try:
            raw = read_data_from_kvstore(addr, kv_port, _SCOPE, key)
        except urllib.error.HTTPError as e:
            if e.code != 404:  # 404 = not reported yet; keep polling
                raise
            raw = None
        except urllib.error.URLError:
            raw = None  # KV server not reachable yet
        if raw:
            break
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"rank 0 did not report its controller port within "
                f"{timeout:.0f}s (HOROVOD_BOOTSTRAP_TIMEOUT); the rank-0 "
                f"worker may have failed to start")
        time.sleep(0.1)
    info = json.loads(raw)
    rank0_host = info["hostname"]
    local = is_local_host(rank0_host)
    rank0_ifaces = [(n, a) for n, a in info.get("ifaces", [])]
    controller_addr = None
    if rank0_ifaces:
        try:
            mine = nic.list_interfaces()
        except OSError:
            mine = []
        if mine:
            controller_addr = nic.select_controller_addr(
                rank0_ifaces,
                {rank0_host: rank0_ifaces, "__self__": mine},
                allow=nic.iface_filter_from_env(),
                allow_loopback=local)
    if controller_addr is None:
        controller_addr = "127.0.0.1" if local else rank0_host
    os.environ["HOROVOD_CONTROLLER_ADDR"] = controller_addr
    os.environ["HOROVOD_CONTROLLER_PORT"] = str(info["port"])


def apply(rank: int):
    """Run the side of the protocol this rank plays. Returns the
    bound-port callback rank 0 must register before native init (None for
    other ranks, whose env is fully resolved on return). Each call is a
    new generation (see ``_generation``)."""
    _generation[0] += 1
    if rank == 0:
        os.environ["HOROVOD_CONTROLLER_PORT"] = "0"
        os.environ.setdefault("HOROVOD_CONTROLLER_ADDR", "127.0.0.1")
        key = _gen_key()
        return lambda port: publish_controller(port, key=key)
    resolve_controller()
    return None
