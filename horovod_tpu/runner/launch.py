"""``hvdrun`` — the horovodrun-equivalent CLI.

Reference surface: ``horovod/runner/launch.py`` (727 LoC): argparse over
-np/-H/--hostfile, tuning flags that become env vars, autotune/timeline/
stall-check groups, elastic flags (--min-np/--max-np/
--host-discovery-script), then ``_run`` → static or elastic launch
(launch.py:212-481, 689-713).

TPU redesign: there is no mpirun/jsrun dispatch — the single controller is
the native rank-0 coordinator over TCP (``run_controller`` trivially picks
it, mirroring launch.py:630-662's gloo branch). Everything else keeps the
reference CLI contract so ``horovodrun -np 4 python train.py`` scripts port
by renaming the binary.

Usage::

    python -m horovod_tpu.runner -np 4 python train.py
    python -m horovod_tpu.runner -np 4 -H h1:2,h2:2 python train.py
    python -m horovod_tpu.runner -np 2 --min-np 2 --max-np 4 \
        --host-discovery-script ./discover.sh python train.py
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from . import config_parser
from .hosts import get_host_assignments, parse_host_files, parse_hosts
from .http_server import RendezvousServer
from .static_run import launch_static


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch a horovod_tpu distributed job "
                    "(horovodrun-compatible CLI)")
    parser.add_argument("--version", action="store_true",
                        help="print version and exit")
    parser.add_argument("-np", "--num-proc", type=int, dest="np",
                        help="total number of worker processes")
    parser.add_argument("-H", "--hosts", dest="hosts",
                        help="host:slots pairs, comma separated")
    parser.add_argument("--hostfile", dest="hostfile",
                        help="mpirun-style hostfile (host slots=N)")
    parser.add_argument("-v", "--verbose", action="count", default=0,
                        help="-v for launcher logs, -vv for per-slot commands")
    parser.add_argument("--disable-cache", action="store_true",
                        dest="disable_cache",
                        help="disable the response cache "
                             "(HOROVOD_CACHE_CAPACITY=0)")
    parser.add_argument("--start-timeout", type=int, default=600,
                        help="seconds to wait for all processes to start")
    parser.add_argument("--config-file", dest="config_file",
                        help="YAML config file (same schema as horovodrun)")

    tune = parser.add_argument_group("tuning")
    tune.add_argument("--fusion-threshold-mb", type=float,
                      dest="fusion_threshold_mb")
    tune.add_argument("--cycle-time-ms", type=float, dest="cycle_time_ms")
    tune.add_argument("--cache-capacity", type=int, dest="cache_capacity")
    tune.add_argument("--hierarchical-allreduce", action="store_true",
                      dest="hierarchical_allreduce", default=None)
    tune.add_argument("--hierarchical-allgather", action="store_true",
                      dest="hierarchical_allgather", default=None)

    autotune = parser.add_argument_group("autotune")
    autotune.add_argument("--autotune", action="store_true", default=None)
    autotune.add_argument("--autotune-log-file", dest="autotune_log_file")
    autotune.add_argument("--autotune-warmup-samples", type=int,
                          dest="autotune_warmup_samples")
    autotune.add_argument("--autotune-steps-per-sample", type=int,
                          dest="autotune_steps_per_sample")
    autotune.add_argument("--autotune-bayes-opt-max-samples", type=int,
                          dest="autotune_bayes_opt_max_samples")
    autotune.add_argument("--autotune-gaussian-process-noise", type=float,
                          dest="autotune_gaussian_process_noise")
    autotune.add_argument("--autotune-warm-start", type=int,
                          dest="autotune_warm_start",
                          help="seed the GP with the top-K cost-model-"
                               "priced plans (docs/cost-model.md); "
                               "0 = cold search")

    timeline = parser.add_argument_group("timeline")
    timeline.add_argument("--timeline-filename", dest="timeline_filename")
    timeline.add_argument("--timeline-mark-cycles", action="store_true",
                          dest="timeline_mark_cycles", default=None)

    stall = parser.add_argument_group("stall check")
    stall.add_argument("--no-stall-check", action="store_true",
                       dest="no_stall_check", default=None)
    stall.add_argument("--stall-check-warning-time-seconds", type=float,
                       dest="stall_check_warning_time_seconds")
    stall.add_argument("--stall-check-shutdown-time-seconds", type=float,
                       dest="stall_check_shutdown_time_seconds")

    logging_grp = parser.add_argument_group("logging")
    logging_grp.add_argument("--log-level", dest="log_level",
                             choices=["trace", "debug", "info", "warning",
                                      "error", "fatal"])
    logging_grp.add_argument("--log-hide-timestamp", action="store_true",
                             dest="log_hide_timestamp", default=None)

    elastic = parser.add_argument_group("elastic")
    elastic.add_argument("--min-np", type=int, dest="min_np")
    elastic.add_argument("--max-np", type=int, dest="max_np")
    elastic.add_argument("--host-discovery-script",
                         dest="host_discovery_script")
    elastic.add_argument("--slots", type=int, dest="slots",
                         help="slots per discovered host (elastic)")
    elastic.add_argument("--reset-limit", type=int, dest="reset_limit")

    lsf_grp = parser.add_argument_group("lsf")
    lsf_grp.add_argument("--jsrun", action="store_true", dest="use_jsrun",
                         help="place workers with jsrun (LSF clusters; "
                              "np/hosts auto-derived from the allocation)")
    mpi_grp = parser.add_argument_group("mpi")
    mpi_grp.add_argument("--mpi", action="store_true", dest="use_mpi",
                         help="place workers with the cluster's mpirun "
                              "(OpenMPI/Spectrum/MPICH detected via "
                              "'mpirun --version'; rank identity bridges "
                              "from OMPI_COMM_WORLD_*/PMI_*)")
    mpi_grp.add_argument("--mpi-args", dest="extra_mpi_args",
                         help="extra arguments passed through to mpirun")
    parser.add_argument("--ssh-port", type=int, dest="ssh_port",
                        help="ssh port for remote workers (reference: "
                             "horovodrun --ssh-port)")
    parser.add_argument("--network-interface", dest="network_interface",
                        help="comma-separated NIC names the controller "
                             "address may use (reference: horovodrun "
                             "--network-interface / HOROVOD_GLOO_IFACE)")

    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="the training command to launch")
    args = parser.parse_args(argv)
    args.elastic = args.host_discovery_script is not None or \
        args.min_np is not None or args.max_np is not None
    return args


def _validate(args) -> None:
    from . import lsf

    if args.version:
        return
    if not args.command:
        raise ValueError("no command to run — usage: hvdrun -np N <command>")
    if getattr(args, "use_jsrun", False) and args.elastic:
        raise ValueError(
            "--jsrun places a fixed-size job; elastic flags "
            "(--min-np/--max-np/--host-discovery-script) are not "
            "supported with it")
    if getattr(args, "use_mpi", False):
        if args.elastic:
            raise ValueError(
                "--mpi places a fixed-size job; elastic flags "
                "(--min-np/--max-np/--host-discovery-script) are not "
                "supported with it")
        if getattr(args, "use_jsrun", False):
            raise ValueError("--mpi and --jsrun are mutually exclusive")
    if not args.elastic:
        if args.np is None and lsf.using_lsf():
            # Under LSF the allocation defines np/hosts (reference
            # launch.py:221: -np not required when using_lsf()).
            args.np = lsf.get_num_processes()
            if not args.hosts and not args.hostfile:
                args.hosts = lsf.get_hosts_arg()
        if args.np is None:
            raise ValueError("-np is required for static jobs")
        if args.hosts and args.hostfile:
            raise ValueError("specify only one of -H and --hostfile")
    else:
        if not args.host_discovery_script and not (args.hosts or args.hostfile):
            raise ValueError(
                "elastic jobs need --host-discovery-script (or fixed -H)")
        if args.min_np is None and args.np is None:
            raise ValueError("elastic jobs need --min-np (or -np)")
    config_parser.validate_config_args(args)


def _build_env(args) -> dict:
    env = dict(os.environ)
    env.update(_build_env_overrides(args))
    return env


def _get_hosts(args, np_: int):
    if args.hostfile:
        return parse_host_files(args.hostfile)
    if args.hosts:
        return parse_hosts(args.hosts)
    return parse_hosts(f"localhost:{np_}")


def _run_static(args) -> None:
    from . import secret

    hosts = _get_hosts(args, args.np)
    slots = get_host_assignments(hosts, args.np)
    env = _build_env(args)
    token = secret.make_secret_key().hex()
    env["HOROVOD_KV_TOKEN"] = token
    rendezvous = RendezvousServer(verbose=args.verbose, auth_token=token)
    rendezvous_port = rendezvous.start_server()
    rendezvous.init(slots)
    try:
        # controller_port=None → KV bootstrap: rank 0 binds its own port
        # and reports it through this rendezvous server (no launcher-side
        # free-port guess; runner/bootstrap.py).
        launch_static(args.command, slots,
                      controller_port=None,
                      rendezvous_port=rendezvous_port,
                      env=env, verbose=args.verbose,
                      ssh_port=args.ssh_port)
    finally:
        rendezvous.stop()


def _run_elastic(args) -> None:
    from ..elastic.launcher import launch_elastic  # lazy: optional subsystem

    launch_elastic(args, env=_build_env(args))


def _hosts_dict(args):
    """Ordered {hostname: slots} from -H/--hostfile, or None when the
    placer should derive hosts from its own allocation."""
    if not (args.hosts or args.hostfile):
        return None
    hosts = {}
    for h in _get_hosts(args, args.np):
        hosts[h.hostname] = hosts.get(h.hostname, 0) + h.slots
    return hosts


def _run_jsrun(args) -> None:
    from . import js_run

    hosts = _hosts_dict(args)
    rc = js_run.js_run(args.command, env=_build_env_overrides(args),
                       num_proc=args.np, hosts=hosts, verbose=args.verbose)
    if rc != 0:
        raise RuntimeError(f"jsrun exited with code {rc}")


def _run_mpi(args) -> None:
    from . import mpi_run

    hosts = _hosts_dict(args)
    rc = mpi_run.mpi_run(args.command, env=_build_env_overrides(args),
                         num_proc=args.np, hosts=hosts,
                         verbose=args.verbose, ssh_port=args.ssh_port,
                         extra_mpi_args=getattr(args, "extra_mpi_args",
                                                None))
    if rc != 0:
        raise RuntimeError(f"mpirun exited with code {rc}")


def _build_env_overrides(args) -> dict:
    """HOROVOD_* knobs derived from CLI flags only (for launch paths that
    must not ship the launcher's whole environment, e.g. jsrun's per-rank
    env prefix)."""
    env: dict = {}
    config_parser.set_env_from_args(env, args)
    if args.disable_cache:
        env["HOROVOD_CACHE_CAPACITY"] = "0"
    if args.network_interface:
        env["HOROVOD_IFACE"] = args.network_interface
        # The allowlist's consumer is the elastic DRIVER's interface
        # intersection (ElasticDriver._nic_controller_addr →
        # nic.iface_filter_from_env), which runs in THIS process — worker
        # env alone would leave the flag a no-op.
        os.environ["HOROVOD_IFACE"] = args.network_interface
    return env


def _run(args) -> None:
    if args.version:
        from .. import __version__

        print(__version__)
        return
    if args.config_file:
        config_parser.parse_config_file(args.config_file, args)
    _validate(args)
    if getattr(args, "use_jsrun", False):
        _run_jsrun(args)
    elif getattr(args, "use_mpi", False):
        _run_mpi(args)
    elif args.elastic:
        _run_elastic(args)
    else:
        _run_static(args)


def run_commandline(argv: Optional[List[str]] = None) -> None:
    args = parse_args(argv)
    try:
        _run(args)
    except (ValueError, RuntimeError) as e:
        print(f"hvdrun: {e}", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    run_commandline()
