"""NIC discovery and interface intersection.

Reference surface: ``horovod/runner/driver/driver_service.py:260``
(driver/task services register each host's addresses; the driver computes
the interfaces common to all hosts) + the ``HOROVOD_GLOO_IFACE`` /
``--network-interface`` selection knob (``gloo_context.cc:49-84``,
``launch.py:546``).

TPU-native redesign: the native controller already listens on all
interfaces (``TcpServer::Listen`` binds INADDR_ANY), so NIC selection is
purely about which *address* peers dial. Workers report their
``(interface, address)`` list at rendezvous; the driver intersects
interface names across the hosts of the current world (optionally
restricted by the knob) and hands peers the rank-0 host's address on the
first common interface — no probing, no "rank-0 hostname resolves
everywhere" assumption.
"""

from __future__ import annotations

import os
import socket
import struct
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


def iface_filter_from_env() -> Optional[List[str]]:
    """Comma-separated interface allowlist from ``HOROVOD_IFACE`` (alias:
    the reference's ``HOROVOD_GLOO_IFACE``), or None for no restriction."""
    raw = os.environ.get("HOROVOD_IFACE") or \
        os.environ.get("HOROVOD_GLOO_IFACE")
    if not raw:
        return None
    return [s.strip() for s in raw.split(",") if s.strip()]


def list_interfaces() -> List[Tuple[str, str]]:
    """``[(ifname, ipv4_addr)]`` for every up interface with an IPv4
    address (Linux SIOCGIFADDR ioctl; no third-party deps). The loopback
    stays in the list — single-host worlds legitimately rendezvous on it —
    but sorts last so real NICs win the intersection."""
    try:
        import fcntl
    except ImportError:  # pragma: no cover - non-POSIX
        return [("host", socket.gethostbyname(socket.gethostname()))]

    out: List[Tuple[str, str]] = []
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        for _, name in socket.if_nameindex():
            try:
                packed = fcntl.ioctl(
                    s.fileno(), 0x8915,  # SIOCGIFADDR
                    struct.pack("256s", name.encode()[:15]))
                addr = socket.inet_ntoa(packed[20:24])
            except OSError:
                continue  # interface has no IPv4 address
            out.append((name, addr))
    finally:
        s.close()
    out.sort(key=lambda t: (t[1].startswith("127."), t[0]))
    return out


def common_interfaces(per_host: Dict[str, Sequence[Tuple[str, str]]],
                      allow: Optional[Iterable[str]] = None) -> List[str]:
    """Interface names present on EVERY host (reference
    driver_service.py get_common_interfaces), optionally restricted to the
    ``allow`` list; ordered by the first host's preference order."""
    if not per_host:
        return []
    hosts = list(per_host)
    common = None
    for h in hosts:
        names = {name for name, _ in per_host[h]}
        common = names if common is None else (common & names)
    first_order = [name for name, _ in per_host[hosts[0]]]
    out = [n for n in first_order if n in (common or set())]
    if allow is not None:
        allowed = set(allow)
        out = [n for n in out if n in allowed]
    return out


def select_controller_addr(rank0_ifaces: Sequence[Tuple[str, str]],
                           per_host: Dict[str,
                                          Sequence[Tuple[str, str]]],
                           allow: Optional[Iterable[str]] = None,
                           allow_loopback: bool = False
                           ) -> Optional[str]:
    """The rank-0 host's address on the first interface common to the
    given hosts (None when there is no usable intersection — callers fall
    back to the hostname heuristic). Loopback only counts when the caller
    says the dialing host IS the rank-0 host (``allow_loopback``):
    every multi-host pair shares 'lo', and handing a remote worker
    127.0.0.1 would send it to its own machine."""
    commons = common_interfaces(per_host, allow=allow)
    by_name = dict(rank0_ifaces)
    for name in commons:
        addr = by_name.get(name)
        if addr and not addr.startswith("127."):
            return addr
    if allow_loopback:
        for name in commons:
            addr = by_name.get(name)
            if addr:
                return addr
    return None
