"""jsrun launch path for LSF clusters.

Reference surface: ``horovod/runner/js_run.py`` — ``js_run`` builds a
``jsrun`` command (ERF rankfile binding, per-rank stdio capture) and execs
it; ``generate_jsrun_rankfile`` writes the explicit-resource-file mapping
ranks to hosts/cpus (js_run.py:100-146).

TPU-native redesign: the reference routes jsrun through the MPI controller
(``--smpiargs``); this framework has no MPI — jsrun is purely the process
*placer*. Each spawned worker derives the HOROVOD_* env contract from
jsrun's own ``JSM_NAMESPACE_{RANK,SIZE,LOCAL_RANK}`` variables (bridged in
``common/basics.init``), and the native controller rendezvouses on the
first compute host of the allocation, so no rankfile-side env plumbing is
needed.
"""

from __future__ import annotations

import os
import shlex
import shutil
import tempfile
from typing import Dict, List, Optional, Sequence, Tuple

from . import lsf

# Fixed rendezvous port for jsrun-placed workers: every process of a fresh
# LSF allocation computes the same (host, port) with no launcher RPC. The
# reference's MPI controller needs no such port; ours does (native TCP
# star). Overridable via HOROVOD_CONTROLLER_PORT.
DEFAULT_CONTROLLER_PORT = 42223


def apply_rendezvous_defaults(worker_env: Dict[str, str], first_host: str,
                              num_proc: int) -> Dict[str, str]:
    """Controller-rendezvous defaults shared by the jsrun and mpirun
    process placers: every rank of a fresh allocation computes the same
    (first host, fixed port) with no launcher RPC. Launcher-exported
    HOROVOD_CONTROLLER_* beat the defaults (the env prefix overrides the
    inherited environment, so the operator's escape hatch must be
    honored here)."""
    worker_env.setdefault(
        "HOROVOD_CONTROLLER_ADDR",
        os.environ.get("HOROVOD_CONTROLLER_ADDR", first_host))
    worker_env.setdefault(
        "HOROVOD_CONTROLLER_PORT",
        os.environ.get("HOROVOD_CONTROLLER_PORT",
                       str(DEFAULT_CONTROLLER_PORT)))
    worker_env.setdefault("HOROVOD_SIZE", str(num_proc))
    return worker_env


def is_jsrun_installed() -> bool:
    """True if the jsrun binary is on PATH (reference js_run.py:44-46)."""
    return shutil.which("jsrun") is not None


def validate_host_slots(hosts: Dict[str, int], num_proc: int,
                        max_slots_per_host: Optional[int] = None
                        ) -> List[Tuple[str, int]]:
    """Truncate an ordered {host: slots} map to exactly ``num_proc`` slots
    (reference js_run.py:109-126: verify-and-truncate against the
    allocation)."""
    validated: List[Tuple[str, int]] = []
    remaining = num_proc
    for host, slots in hosts.items():
        if max_slots_per_host is not None and slots > max_slots_per_host:
            raise ValueError(
                f"host {host!r} requests {slots} slots, above the "
                f"per-host limit {max_slots_per_host}")
        take = min(slots, remaining)
        if take > 0:
            validated.append((host, take))
            remaining -= take
        if remaining == 0:
            break
    if remaining != 0:
        raise ValueError(
            f"not enough slots on the hosts to fulfill the {num_proc} "
            f"requested")
    return validated


def generate_jsrun_rankfile(hosts: Dict[str, int], num_proc: int,
                            cpus_per_slot: int = 4,
                            path: Optional[str] = None) -> str:
    """Write an ERF rankfile mapping rank r to its host and a disjoint cpu
    range (reference js_run.py:100-146; cpu width comes from
    ``cpus_per_slot`` instead of the CSM core/gpu query — no CSM on TPU
    clusters)."""
    validated = validate_host_slots(hosts, num_proc)
    if path is None:
        fd, path = tempfile.mkstemp(prefix="hvdtpu_erf_", text=True)
        os.close(fd)
    with open(path, "w") as f:
        f.write("overlapping_rs: allow\n")
        f.write("cpu_index_using: logical\n")
        rank = 0
        for host, slots in validated:
            cpu = 0
            f.write("\n")
            for _ in range(slots):
                f.write(f"rank: {rank}: {{ hostname: {host}; "
                        f"cpu: {{{cpu}-{cpu + cpus_per_slot - 1}}} ; "
                        f"mem: * }}\n")
                rank += 1
                cpu += cpus_per_slot
    return path


def build_jsrun_command(command: Sequence[str],
                        env: Optional[Dict[str, str]] = None,
                        num_proc: Optional[int] = None,
                        hosts: Optional[Dict[str, int]] = None,
                        cpus_per_slot: int = 4,
                        output_filename: Optional[str] = None,
                        binding_args: Optional[str] = None,
                        rankfile_path: Optional[str] = None) -> str:
    """Synthesize the full jsrun command line (reference js_run.py:49-98,
    minus the MPI ``--smpiargs`` leg).

    The worker env contract (controller host/port + any HOROVOD_* knobs)
    rides an ``env`` prefix inside the per-rank command; rank identity
    comes from jsrun's JSM_NAMESPACE_* variables at worker start.
    """
    hosts = hosts if hosts is not None else lsf.get_compute_hosts_and_slots()
    num_proc = num_proc if num_proc is not None else sum(hosts.values())

    if binding_args is None:
        rf = generate_jsrun_rankfile(hosts, num_proc,
                                     cpus_per_slot=cpus_per_slot,
                                     path=rankfile_path)
        binding_args = f"--erf_input {rf}"

    worker_env = apply_rendezvous_defaults(
        dict(env or {}),
        next(iter(validate_host_slots(hosts, num_proc)))[0], num_proc)
    env_prefix = " ".join(
        f"{k}={shlex.quote(v)}" for k, v in sorted(worker_env.items()))

    stdio = ""
    if output_filename:
        stdio = (f"--stdio_stdout {shlex.quote(output_filename)} "
                 f"--stdio_stderr {shlex.quote(output_filename)} ")
    cmd = " ".join(shlex.quote(c) for c in command)
    return (f"jsrun {binding_args} {stdio}"
            f"env {env_prefix} {cmd}").strip()


def js_run(command: Sequence[str], env: Optional[Dict[str, str]] = None,
           num_proc: Optional[int] = None,
           hosts: Optional[Dict[str, int]] = None,
           verbose: int = 0,
           output_filename: Optional[str] = None) -> int:
    """Build and exec the jsrun command (reference js_run.py:49-98)."""
    from . import safe_shell_exec

    if not is_jsrun_installed():
        raise RuntimeError(
            "jsrun not found on PATH. Run on an LSF cluster with jsrun "
            "installed, or use the default ssh/local launcher.")
    jsrun_cmd = build_jsrun_command(command, env=env, num_proc=num_proc,
                                    hosts=hosts,
                                    output_filename=output_filename)
    if verbose >= 2:
        print(jsrun_cmd)
    return safe_shell_exec.execute(jsrun_cmd, env=dict(os.environ))
