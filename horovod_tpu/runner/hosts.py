"""Host parsing and slot/rank assignment for the launcher.

Reference surface: ``horovod/runner/common/util/hosts.py`` —
``parse_hosts`` (host:slots strings), ``parse_host_files`` (mpirun-style
hostfiles) and ``get_host_assignments`` (hosts.py:100-150), which packs
ranks host-by-host and derives the three-level rank vocabulary
(rank / local_rank / cross_rank) that the launcher injects as the
``HOROVOD_*`` env contract (gloo_run.py:65-76).

TPU note: one slot = one worker process. On a TPU pod the natural choice is
one slot per host (each process drives all local chips through the mesh),
but the assignment math is slot-count agnostic, exactly like the reference.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class HostInfo:
    """A host and its slot count (reference: hosts.py HostInfo)."""

    hostname: str
    slots: int

    @staticmethod
    def from_string(host_string: str) -> "HostInfo":
        parts = host_string.strip().split(":")
        if len(parts) == 1 or parts[1] == "":
            return HostInfo(parts[0], 1)
        return HostInfo(parts[0], int(parts[1]))


@dataclass
class SlotInfo:
    """Placement of one rank (reference: hosts.py SlotInfo)."""

    hostname: str
    rank: int
    local_rank: int
    cross_rank: int
    size: int
    local_size: int
    cross_size: int

    def to_response_string(self) -> str:
        return ":".join(
            str(v) for v in (self.rank, self.size, self.local_rank,
                             self.local_size, self.cross_rank,
                             self.cross_size))


INVALID_HOST_CHARS = re.compile(r"[^a-zA-Z0-9.\-_]")


def parse_hosts(hosts_string: str) -> List[HostInfo]:
    """Parse ``"h1:2,h2:4"`` into HostInfo list (reference hosts.py:69-80)."""
    infos = []
    for part in hosts_string.split(","):
        part = part.strip()
        if not part:
            continue
        infos.append(HostInfo.from_string(part))
    if not infos:
        raise ValueError(f"no hosts found in {hosts_string!r}")
    return infos


def parse_host_files(filename: str) -> List[HostInfo]:
    """Parse an mpirun-style hostfile: lines of ``host slots=N`` or
    ``host:N`` or bare ``host`` (reference hosts.py:83-97)."""
    infos = []
    with open(filename) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            m = re.match(r"^(\S+)\s+slots\s*=\s*(\d+)", line)
            if m:
                infos.append(HostInfo(m.group(1), int(m.group(2))))
            else:
                infos.append(HostInfo.from_string(line))
    if not infos:
        raise ValueError(f"no hosts found in hostfile {filename}")
    return infos


def get_host_assignments(hosts: List[HostInfo], min_np: int,
                         max_np: Optional[int] = None) -> List[SlotInfo]:
    """Pack ranks host-by-host and compute local/cross ranks
    (reference hosts.py:100-150).

    ``cross_rank`` of a slot = index of its host among hosts that have a
    slot at the same ``local_rank``; ``cross_size`` = number of such hosts.
    Raises if total slots < min_np; assigns at most ``max_np or min_np``.
    """
    total_slots = sum(h.slots for h in hosts)
    if total_slots < min_np:
        raise ValueError(
            f"requested {min_np} processes but hosts "
            f"{[f'{h.hostname}:{h.slots}' for h in hosts]} only provide "
            f"{total_slots} slots")
    np_ = min(total_slots, max_np or min_np)

    # Pack: rank i goes to the first host with a free slot.
    per_host: List[int] = []  # ranks actually placed on each host
    remaining = np_
    for h in hosts:
        take = min(h.slots, remaining)
        per_host.append(take)
        remaining -= take
    assert remaining == 0

    slots: List[SlotInfo] = []
    rank = 0
    for hi, h in enumerate(hosts):
        for local_rank in range(per_host[hi]):
            cross_rank = sum(1 for j in range(hi) if per_host[j] > local_rank)
            cross_size = sum(1 for n in per_host if n > local_rank)
            slots.append(SlotInfo(
                hostname=h.hostname,
                rank=rank,
                local_rank=local_rank,
                cross_rank=cross_rank,
                size=np_,
                local_size=per_host[hi],
                cross_size=cross_size,
            ))
            rank += 1
    return slots
