"""Threaded HTTP key-value store: rendezvous + run-func transport.

Reference surface: ``horovod/runner/http/http_server.py`` (241 LoC) —
``RendezvousServer`` (a KV store scoped ``global``/``local_<host>``/
``cross_<local_rank>`` that the C++ Gloo context bootstraps against) and
``KVStoreServer`` (transport for the pickled function in ``horovod.run``).

TPU redesign: our native core negotiates over HOROVOD_CONTROLLER_ADDR/PORT
directly (rank-0 coordinator, see cc/src/operations.cc), so rendezvous here
serves the *launcher-level* jobs the reference also uses it for: publishing
slot assignments (elastic ``rank_and_size``), shipping pickled functions,
and collecting results. Same HTTP verb contract: GET/PUT/DELETE
``/scope/key``; GET on a missing key returns 404 (clients poll).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple


class _KVHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def _split(self) -> Tuple[str, str]:
        parts = self.path.lstrip("/").split("/", 1)
        scope = parts[0] if parts else ""
        key = parts[1] if len(parts) > 1 else ""
        return scope, key

    def _authorized(self) -> bool:
        """Workers unpickle what they GET here, so every verb requires the
        job's shared token (same trust model as the HMAC-signed RPC in
        network.py; the reference's rendezvous relies on network isolation,
        we don't)."""
        token = self.server.auth_token  # type: ignore[attr-defined]
        if token is None:
            return True
        import hmac as _hmac

        supplied = self.headers.get("X-Hvd-Auth", "")
        return _hmac.compare_digest(supplied, token)

    def _deny(self) -> None:
        self.send_response(403)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_GET(self):  # noqa: N802
        if not self._authorized():
            return self._deny()
        scope, key = self._split()
        value = self.server.store.get(scope, key)  # type: ignore[attr-defined]
        if value is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(value)))
        self.end_headers()
        self.wfile.write(value)

    def do_PUT(self):  # noqa: N802
        if not self._authorized():
            return self._deny()
        scope, key = self._split()
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        self.server.store.put(scope, key, body)  # type: ignore[attr-defined]
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self):  # noqa: N802
        if not self._authorized():
            return self._deny()
        scope, key = self._split()
        self.server.store.delete(scope, key)  # type: ignore[attr-defined]
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def log_message(self, fmt, *args):  # silence per-request logging
        pass


class _Store:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._data: Dict[str, Dict[str, bytes]] = {}
        self._cv = threading.Condition(self._lock)

    def get(self, scope: str, key: str) -> Optional[bytes]:
        with self._lock:
            return self._data.get(scope, {}).get(key)

    def put(self, scope: str, key: str, value: bytes) -> None:
        with self._cv:
            self._data.setdefault(scope, {})[key] = value
            self._cv.notify_all()

    def delete(self, scope: str, key: str) -> None:
        with self._cv:
            self._data.get(scope, {}).pop(key, None)
            self._cv.notify_all()

    def delete_scope(self, scope: str) -> None:
        with self._cv:
            self._data.pop(scope, None)
            self._cv.notify_all()

    def wait_for(self, scope: str, key: str,
                 timeout: Optional[float] = None) -> Optional[bytes]:
        with self._cv:
            deadline = None
            if timeout is not None:
                import time

                deadline = time.monotonic() + timeout
            while True:
                value = self._data.get(scope, {}).get(key)
                if value is not None:
                    return value
                remaining = None
                if deadline is not None:
                    import time

                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                self._cv.wait(remaining)


class KVStoreServer:
    """In-process HTTP KV server (reference http_server.py:139-235).

    ``auth_token``: shared secret required in the ``X-Hvd-Auth`` header of
    every request (exported to workers as ``HOROVOD_KV_TOKEN``); ``None``
    disables the check (single-machine tests only).
    """

    def __init__(self, auth_token: Optional[str] = None) -> None:
        self.store = _Store()
        self.auth_token = auth_token
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start_server(self) -> int:
        self._httpd = ThreadingHTTPServer(("0.0.0.0", 0), _KVHandler)
        self._httpd.store = self.store  # type: ignore[attr-defined]
        self._httpd.auth_token = self.auth_token  # type: ignore[attr-defined]
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self._httpd.server_address[1]

    @property
    def port(self) -> int:
        assert self._httpd is not None
        return self._httpd.server_address[1]

    def shutdown_server(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


class RendezvousServer(KVStoreServer):
    """KV store + slot-assignment publication (reference
    http_server.py:35-137). ``init(host_alloc_plan)`` (re)publishes every
    slot's rank/size tuple under scope ``rendezvous`` keyed by
    ``<hostname>:<local_rank>``; elastic workers GET it to learn their new
    identity after a reset (elastic/rendezvous.py:37-42)."""

    def __init__(self, verbose: int = 0,
                 auth_token: Optional[str] = None) -> None:
        super().__init__(auth_token)
        self._verbose = verbose

    def init(self, host_alloc_plan) -> None:
        # Drop the whole previous plan: stale host:local_rank keys from a
        # larger world must 404, not hand out dead identities.
        self.store.delete_scope("rendezvous")
        for slot in host_alloc_plan:
            key = f"{slot.hostname}:{slot.local_rank}"
            self.store.put("rendezvous", key,
                           slot.to_response_string().encode())

    def stop(self) -> None:
        self.shutdown_server()


def _auth_headers() -> dict:
    import os

    token = os.environ.get("HOROVOD_KV_TOKEN")
    return {"X-Hvd-Auth": token} if token else {}


def read_data_from_kvstore(addr: str, port: int, scope: str, key: str):
    """Poll-free GET helper (reference runner/util/network.py)."""
    import pickle
    import urllib.request

    url = f"http://{addr}:{port}/{scope}/{key}"
    req = urllib.request.Request(url, headers=_auth_headers())
    with urllib.request.urlopen(req) as resp:
        return pickle.loads(resp.read())


def put_data_into_kvstore(addr: str, port: int, scope: str, key: str,
                          value) -> None:
    import pickle
    import urllib.request

    url = f"http://{addr}:{port}/{scope}/{key}"
    req = urllib.request.Request(url, data=pickle.dumps(value), method="PUT",
                                 headers=_auth_headers())
    urllib.request.urlopen(req).read()
