"""LSF allocation introspection.

Reference surface: ``horovod/runner/util/lsf.py`` (``LSFUtils``: using_lsf,
get_compute_hosts, get_num_processes — np/hosts auto-derived so ``-np`` is
optional under LSF, launch.py:221) and ``runner/js_run.py`` (jsrun launch).

TPU-native redesign: the reference queries IBM CSM
(``csm_allocation_query``) for Summit-style GPU counts; a TPU cluster has
no CSM and no GPUs, so the allocation is read from LSF's own batch env —
``LSB_MCPU_HOSTS`` ("host1 n1 host2 n2 ..." as exported by LSF on every
batch host) with ``LSB_HOSTS`` ("host1 host1 host2 ..." one entry per
slot) as the fallback. Slot counts mean worker processes (one per TPU
host), exactly how the rest of the launcher treats hosts.
"""

from __future__ import annotations

import os
from typing import Dict, List


def using_lsf() -> bool:
    """True when running inside an LSF job (reference lsf.py:35-37)."""
    return "LSB_JOBID" in os.environ


def get_compute_hosts_and_slots() -> Dict[str, int]:
    """Ordered {host: slots} from the LSF batch env. The submission host
    entry (``LSB_SUB_HOST``) is excluded when LSF lists it with 0 slots."""
    mcpu = os.environ.get("LSB_MCPU_HOSTS", "").split()
    hosts: Dict[str, int] = {}
    if mcpu:
        if len(mcpu) % 2 != 0:
            raise ValueError(
                f"malformed LSB_MCPU_HOSTS: {os.environ['LSB_MCPU_HOSTS']!r}")
        for i in range(0, len(mcpu), 2):
            slots = int(mcpu[i + 1])
            if slots > 0:
                hosts[mcpu[i]] = hosts.get(mcpu[i], 0) + slots
        return hosts
    for h in os.environ.get("LSB_HOSTS", "").split():
        hosts[h] = hosts.get(h, 0) + 1
    if not hosts:
        raise RuntimeError(
            "LSF allocation env not found (neither LSB_MCPU_HOSTS nor "
            "LSB_HOSTS is set) — is this an LSF batch job?")
    return hosts


def get_compute_hosts() -> List[str]:
    """Sorted LSF compute hosts (reference lsf.py:73-76)."""
    return sorted(get_compute_hosts_and_slots())


def get_num_processes() -> int:
    """Total worker slots in the allocation (reference lsf.py:87-91)."""
    return sum(get_compute_hosts_and_slots().values())


def get_hosts_arg() -> str:
    """The allocation as a ``-H host:slots,...`` launcher argument."""
    hs = get_compute_hosts_and_slots()
    return ",".join(f"{h}:{n}" for h, n in sorted(hs.items()))
