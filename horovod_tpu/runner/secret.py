"""Shared-secret generation for RPC message signing.

Reference: ``horovod/runner/common/util/secret.py`` — a random key passed to
every service/client pair so pickle-over-TCP RPC messages are HMAC-signed
before being deserialized (network.py:50-148).
"""

import secrets

DIGEST_LENGTH_BYTES = 32


def make_secret_key() -> bytes:
    return secrets.token_bytes(32)
