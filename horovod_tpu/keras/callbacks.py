"""Public callbacks surface (reference: horovod/keras/callbacks.py — thin
re-export of the shared _keras implementations)."""

from .._keras.callbacks import (  # noqa: F401
    BroadcastGlobalVariablesCallback,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
)
