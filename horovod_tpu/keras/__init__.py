"""Keras high-level API (reference: horovod/keras/__init__.py:1-162).

Usage (the reference's recipe)::

    import horovod_tpu.keras as hvd

    hvd.init()
    model = ...
    opt = keras.optimizers.SGD(learning_rate=0.01 * hvd.size())
    model.compile(optimizer=hvd.DistributedOptimizer(opt), loss=...)
    model.fit(x, y, callbacks=[
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
    ], verbose=1 if hvd.rank() == 0 else 0)
"""

from ..common import basics as _basics
from ..common.basics import (  # noqa: F401
    cross_rank,
    cross_size,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    mpi_threads_supported,
    shutdown,
)
from ..ops.collective_ops import ReduceOp
from .._keras import (  # noqa: F401
    broadcast_model_state,
    create_distributed_optimizer,
)
from . import callbacks  # noqa: F401
from . import elastic  # noqa: F401

Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM


def rank() -> int:
    return int(_basics.rank())


def size() -> int:
    return int(_basics.size())


def DistributedOptimizer(optimizer, compression=None, op=Average,
                         prescale_factor=1.0, postscale_factor=1.0,
                         sparse_as_dense=False):
    """Wrap a Keras optimizer so gradient application averages across
    ranks (reference: keras/__init__.py DistributedOptimizer →
    _keras/__init__.py:25-85). ``sparse_as_dense`` densifies
    IndexedSlices gradients before reduction."""
    return create_distributed_optimizer(optimizer, compression, op,
                                        prescale_factor, postscale_factor,
                                        sparse_as_dense=sparse_as_dense)


def broadcast_global_variables(root_rank: int = 0, model=None) -> None:
    """Reference: keras/__init__.py broadcast_global_variables — prefer the
    BroadcastGlobalVariablesCallback; this form needs the model passed
    explicitly (there is no TF1 global-collection equivalent)."""
    if model is None:
        raise ValueError(
            "pass model= (no global-variable collection exists in Keras 3)")
    broadcast_model_state(model, root_rank)
