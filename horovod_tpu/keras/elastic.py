"""Elastic state for Keras models (reference: horovod/tensorflow/elastic.py
TensorFlowKerasState — weights + optimizer slots synced from the new rank 0
after a reset)."""

from __future__ import annotations

import copy

import numpy as np

from ..elastic.state import State
from ..elastic import run as run  # noqa: F401  (hvd.elastic.run parity)
from .._keras import broadcast_model_state, _broadcast_numpy


class KerasState(State):
    """Holds a Keras model (+ arbitrary picklable attrs). ``save`` keeps an
    in-memory weight copy, ``restore`` rolls back to it, ``sync``
    broadcasts weights/optimizer slots from rank 0."""

    def __init__(self, model, **kwargs):
        self.model = model
        self._obj_keys = list(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._saved_weights = None
        self._saved_objs = {}
        super().__init__()
        self.save()

    def save(self) -> None:
        self._saved_weights = [np.copy(w) for w in self.model.get_weights()]
        self._saved_objs = {k: copy.deepcopy(getattr(self, k))
                            for k in self._obj_keys}

    def restore(self) -> None:
        if self._saved_weights is not None:
            self.model.set_weights(self._saved_weights)
        for k, v in self._saved_objs.items():
            setattr(self, k, copy.deepcopy(v))

    def sync(self) -> None:
        broadcast_model_state(self.model, root_rank=0)
        if self._obj_keys:
            import cloudpickle

            payload = cloudpickle.dumps(
                {k: getattr(self, k) for k in self._obj_keys})
            arr = np.frombuffer(payload, dtype=np.uint8).copy()
            sz = _broadcast_numpy(np.array([len(arr)], dtype=np.int64),
                                  name="keras_state.sz")
            buf = arr if len(arr) == int(sz[0]) \
                else np.zeros(int(sz[0]), dtype=np.uint8)
            data = _broadcast_numpy(buf, name="keras_state.data")
            for k, v in cloudpickle.loads(bytes(data)).items():
                setattr(self, k, v)
        self.save()
