"""Shared Keras implementation (reference: horovod/_keras/__init__.py —
the backend-neutral guts used by both horovod.keras and
horovod.tensorflow.keras).

Targets Keras 3: the distributed optimizer overrides ``apply`` (which
``apply_gradients`` funnels into), and state broadcast works on the
framework-neutral ``variable.assign``/numpy surface so it runs under any
Keras backend.
"""

from __future__ import annotations

import numpy as np

from ..common import basics as _basics
from ..ops import collective_ops as C
from ..ops.collective_ops import ReduceOp


def _world() -> int:
    return C._eager_world()


def _allreduce_numpy(arr: np.ndarray, op=ReduceOp.AVERAGE,
                     name=None, prescale: float = 1.0,
                     postscale: float = 1.0) -> np.ndarray:
    ctrl, world = C._eager_ctx()
    if world == 1:
        scale = prescale * postscale
        return arr if scale == 1.0 else arr * arr.dtype.type(scale)
    opmap = {ReduceOp.SUM: ctrl.SUM, ReduceOp.AVERAGE: ctrl.SUM}
    post = postscale / world if op == ReduceOp.AVERAGE else postscale
    out = np.asarray(ctrl.allreduce_async(
        np.ascontiguousarray(arr), C._eager_name(name, "keras.allreduce"),
        op=opmap[op], prescale=prescale, postscale=post).wait())
    return out.reshape(arr.shape)  # wire promotes scalars to rank 1


def _broadcast_numpy(arr: np.ndarray, root_rank=0, name=None) -> np.ndarray:
    ctrl, world = C._eager_ctx()
    if world == 1:
        return arr
    out = np.asarray(ctrl.broadcast_async(
        np.ascontiguousarray(arr), C._eager_name(name, "keras.broadcast"),
        root=root_rank).wait())
    return out.reshape(arr.shape)  # wire promotes scalars to rank 1


def broadcast_model_state(model, root_rank: int = 0) -> None:
    """Broadcast model weights AND optimizer slot variables from root
    (reference: callbacks.py BroadcastGlobalVariablesCallback +
    functions.py broadcast_variables)."""
    weights = model.get_weights()
    model.set_weights([
        _broadcast_numpy(np.asarray(w), root_rank, name=f"kw.{i}")
        for i, w in enumerate(weights)])
    opt = getattr(model, "optimizer", None)
    if opt is not None and getattr(opt, "variables", None):
        for i, var in enumerate(opt.variables):
            var.assign(_broadcast_numpy(np.asarray(var), root_rank,
                                        name=f"kov.{i}"))


def create_distributed_optimizer(optimizer, compression=None,
                                 op=ReduceOp.AVERAGE,
                                 prescale_factor=1.0, postscale_factor=1.0,
                                 sparse_as_dense=False):
    """Dynamically subclass the wrapped Keras optimizer so isinstance
    checks and serialization keep working (the reference's exact approach,
    _keras/__init__.py:25-85), overriding gradient application to
    allreduce first."""
    import keras

    if op not in (ReduceOp.AVERAGE, ReduceOp.SUM):
        raise ValueError("op must be Average or Sum for Keras optimizers")

    wire_np_dtype = None
    wire = getattr(compression, "wire_dtype", None)
    if wire is not None:
        if "bfloat16" in str(wire):
            import ml_dtypes

            wire_np_dtype = np.dtype(ml_dtypes.bfloat16)
        else:
            wire_np_dtype = np.dtype(np.float16)

    class _Dist(optimizer.__class__):
        """Keras 3 funnels apply_gradients → apply, so overriding ``apply``
        alone covers both entry points (and avoids double reduction)."""

        _hvd_wrapped = True

        def apply(self, grads, trainable_variables=None, **kwargs):
            grads = self._hvd_allreduce(grads)
            return super().apply(grads, trainable_variables, **kwargs)

        def _hvd_allreduce(self, grads):
            if _world() == 1:
                return grads
            import keras.ops as K

            def reduce_np(arr, i):
                arr = np.asarray(arr)
                restore = None
                if wire_np_dtype is not None and \
                        np.issubdtype(arr.dtype, np.floating):
                    restore = arr.dtype
                    arr = arr.astype(wire_np_dtype)
                red = _allreduce_numpy(arr, op=op, name=f"kgrad.{i}",
                                       prescale=prescale_factor,
                                       postscale=postscale_factor)
                return red.astype(restore) if restore is not None else red

            # Under the TF backend Keras compiles train_step into a
            # tf.function; host collectives must escape the graph.
            is_tf = keras.backend.backend() == "tensorflow"
            in_tf_graph = False
            if is_tf:
                import tensorflow as tf

                in_tf_graph = not tf.executing_eagerly()
            out = []
            for i, g in enumerate(grads):
                if is_tf and isinstance(g, tf.IndexedSlices):
                    if sparse_as_dense:
                        # Densify escape hatch (reference keras path);
                        # falls through to the dense reduction below.
                        g = tf.convert_to_tensor(g)
                    else:
                        # Reference default for sparse grads: the
                        # values+indices allgather path shared with
                        # DistributedGradientTape.
                        from ..tensorflow import _allreduce_grads

                        out.append(_allreduce_grads(
                            [g], compression, op, prescale_factor,
                            postscale_factor)[0])
                        continue
                if g is None:
                    out.append(None)
                elif in_tf_graph:
                    y = tf.py_function(
                        lambda t, idx=i: tf.convert_to_tensor(
                            reduce_np(t.numpy(), idx)), [g], g.dtype)
                    y.set_shape(g.shape)
                    out.append(y)
                else:
                    out.append(K.convert_to_tensor(reduce_np(g, i)))
            return out

    _Dist.__name__ = optimizer.__class__.__name__
    cfg = optimizer.get_config()
    return _Dist.from_config(cfg)
