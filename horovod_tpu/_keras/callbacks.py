"""Keras callbacks (reference: horovod/_keras/callbacks.py:22-192).

Backend-neutral (Keras 3): state moves through numpy + the native control
plane, so these work under the TF, JAX, or torch Keras backends.
"""

from __future__ import annotations

import numpy as np

try:
    import keras
except ImportError as e:  # pragma: no cover
    raise ImportError("horovod_tpu keras callbacks require keras") from e

from ..common import basics as _basics
from ..ops.collective_ops import ReduceOp
from . import _allreduce_numpy, _world, broadcast_model_state


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Broadcast initial model + optimizer state from root_rank on the
    first batch (reference: _keras/callbacks.py:22-45 — first batch, not
    train_begin, so freshly-created optimizer slots are included)."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_train_batch_end(self, batch, logs=None):
        # After the first step the optimizer slots exist on every rank;
        # broadcasting now aligns both weights and slots before step 2
        # (the reference hooks the first batch for the same reason).
        if self.broadcast_done:
            return
        broadcast_model_state(self.model, self.root_rank)
        self.broadcast_done = True


class MetricAverageCallback(keras.callbacks.Callback):
    """Average epoch metrics over ranks (reference:
    _keras/callbacks.py:48-87), so rank-0 logging/checkpointing sees global
    metrics."""

    def on_epoch_end(self, epoch, logs=None):
        if logs is None or _world() == 1:
            return
        keys = sorted(k for k, v in logs.items()
                      if np.isscalar(v) or getattr(v, "shape", None) == ())
        if not keys:
            return
        vals = np.array([float(logs[k]) for k in keys], dtype=np.float64)
        avg = _allreduce_numpy(vals, op=ReduceOp.AVERAGE,
                               name=f"metric_avg.{epoch}")
        for k, v in zip(keys, np.asarray(avg)):
            logs[k] = float(v)


class LearningRateWarmupCallback(keras.callbacks.Callback):
    """Linear LR warmup from ``initial_lr / size`` up to ``initial_lr``
    over warmup_epochs (reference: _keras/callbacks.py:90-152, the Goyal et
    al. gradual-warmup rule). As in the reference, ``initial_lr`` is the
    *already size-scaled* learning rate the script configured — warmup ramps
    up to it, never beyond it."""

    def __init__(self, initial_lr: float, warmup_epochs: int = 5,
                 steps_per_epoch: int = None, verbose: int = 0):
        super().__init__()
        self.initial_lr = initial_lr
        self.warmup_epochs = warmup_epochs
        self.steps_per_epoch = steps_per_epoch
        self.verbose = verbose
        self.current_epoch = 0
        self._steps_seen = 0

    def _size(self):
        return _world()

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        self._steps_seen = 0

    def on_train_batch_begin(self, batch, logs=None):
        if self.current_epoch >= self.warmup_epochs:
            return
        steps = self.steps_per_epoch or (
            self.params.get("steps") if self.params else None) or 1
        progress = min(1.0, (self.current_epoch + batch / steps)
                       / self.warmup_epochs)
        size = self._size()
        # Reference multiplier (_keras/callbacks.py:139-143):
        # 1/size * (progress*(size-1) + 1) — from 1/size up to 1.
        multiplier = (1.0 + progress * (size - 1.0)) / size
        self.model.optimizer.learning_rate = self.initial_lr * multiplier
        self._steps_seen += 1

    def on_epoch_end(self, epoch, logs=None):
        if epoch == self.warmup_epochs - 1 and self.verbose and \
                int(_basics.rank()) == 0:
            print(f"warmup complete: lr -> {self.initial_lr:.6g}")


class LearningRateScheduleCallback(keras.callbacks.Callback):
    """Multiply the LR by ``multiplier(epoch)`` within [start_epoch,
    end_epoch) (reference: _keras/callbacks.py:155-192)."""

    def __init__(self, initial_lr: float, multiplier, start_epoch: int = 0,
                 end_epoch: int = None, staircase: bool = True,
                 steps_per_epoch: int = None):
        super().__init__()
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        if callable(multiplier):
            self.multiplier = multiplier
        else:
            self.multiplier = lambda epoch: multiplier

    def _in_range(self, epoch):
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.staircase and self._in_range(epoch):
            self.model.optimizer.learning_rate = \
                self.initial_lr * self.multiplier(epoch)

    def on_train_batch_begin(self, batch, logs=None):
        if self.staircase or not self._in_range(self.current_epoch):
            return
        steps = self.steps_per_epoch or (
            self.params.get("steps") if self.params else None) or 1
        frac_epoch = self.current_epoch + batch / steps
        self.model.optimizer.learning_rate = \
            self.initial_lr * self.multiplier(frac_epoch)
