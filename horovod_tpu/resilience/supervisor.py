"""The failure-policy supervisor: detection signals → recovery actions.

One :class:`Supervisor` wraps the runtime's existing mechanisms:

* **Preemption** — :meth:`attach` registers a SIGTERM pre-dump hook with
  the flight recorder (``monitor/flight.py``). A spot-style notice
  (real SIGTERM, or the chaos ``preempt`` action) then runs a
  *deadline-budgeted priority snapshot*: the configured snapshot
  provider's state goes through the CheckpointManager's AsyncWriter and
  is drained under ``HOROVOD_PREEMPT_SNAPSHOT_DEADLINE_SECS``, all
  *before* the flight dump re-delivers the signal — so the grace window
  buys a durable commit, and the flight record carries the
  ``RESILIENCE:PREEMPT`` event with the deadline verdict.

* **Restart** — restart-from-last-commit rides the existing
  ``CheckpointedJaxState`` reshard path; the supervisor only meters it:
  :meth:`record_restart` spends from
  ``HOROVOD_RESILIENCE_RESTART_BUDGET`` and the policy engine escalates
  when the budget is gone.

* **Degraded-link replanning** — when the straggler detector's
  link-health latch flags a hop (``observe_wire`` EWMA over the drift
  gate for ``patience`` windows), :meth:`maybe_replan` re-prices the
  PR-11 shortlist under a :class:`~horovod_tpu.plan.cost.CostModel`
  *override* (the hop's bandwidth scaled down by the observed EWMA
  ratio — not a recalibration) and returns the winning quantized-wire
  plan for the trainer to hot-swap at a step boundary. The swap is
  recorded (``RESILIENCE:REPLAN``) and reverses on recovery
  (``RESILIENCE:REPLAN_REVERT``) when the latch clears.

* **Failures generally** — :meth:`on_failure` feeds the
  :class:`~horovod_tpu.resilience.policy.PolicyEngine` and *performs*
  ladder actions it can (blacklist via the driver's HostManager);
  shrink/abort are returned to the caller, who owns the loop.

The supervisor holds no thread of its own: everything runs on the
caller's step boundary or inside the signal handler, which keeps the
ordering contract (snapshot → writer drain → flight dump → re-delivery)
trivially true.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..monitor import registry as _registry
from ..monitor.straggler import _timeline_instant
from . import policy as _policy

logger = logging.getLogger("horovod_tpu.resilience")


@dataclasses.dataclass
class ReplanDecision:
    """One recorded degraded-link replan (or its recovery revert)."""

    hop: str
    ewma_ratio: float          # measured/predicted at decision time
    plan_before: Optional[str]  # canonical encoding (None = knob default)
    plan_after: Optional[str]
    predicted_ms: float        # winner's prediction under the override
    reverted: bool = False     # set on the matching swap-back
    step: Optional[int] = None

    def as_dict(self) -> dict:
        return {"hop": self.hop,
                "ewma_ratio": round(self.ewma_ratio, 3),
                "plan_before": self.plan_before,
                "plan_after": self.plan_after,
                "predicted_ms": round(self.predicted_ms, 6),
                "reverted": self.reverted, "step": self.step}


class Supervisor:
    """Wraps ElasticDriver + CheckpointManager behind the policy layer.

    All collaborators are optional so the pieces compose à la carte:
    a serve-only job attaches with no driver, a unit test with neither.

    ``snapshot_provider`` is a zero-argument callable returning
    ``(step, tree, extra)`` — the state a preemption-notice priority
    snapshot should commit — or None when there is nothing newer than
    the last commit.
    """

    def __init__(self,
                 driver=None,
                 ckpt_manager=None,
                 snapshot_provider:
                 Optional[Callable[[], Optional[Tuple[int, dict,
                                                      Optional[dict]]]]]
                 = None,
                 engine: Optional[_policy.PolicyEngine] = None,
                 straggler=None,
                 registry: Optional[_registry.MetricsRegistry] = None,
                 snapshot_deadline_secs: Optional[float] = None,
                 restart_budget: Optional[int] = None,
                 readmission_probe:
                 Optional[Callable[[str], bool]] = None) -> None:
        self.driver = driver
        self.ckpt_manager = ckpt_manager
        self._snapshot_provider = snapshot_provider
        self.engine = engine or _policy.PolicyEngine(registry=registry)
        self._straggler = straggler
        self._registry = registry or _registry.default_registry()
        if snapshot_deadline_secs is None:
            snapshot_deadline_secs = _env_float(
                "HOROVOD_PREEMPT_SNAPSHOT_DEADLINE_SECS", 5.0)
        self.snapshot_deadline_secs = float(snapshot_deadline_secs)
        if restart_budget is None:
            restart_budget = _env_int(
                "HOROVOD_RESILIENCE_RESTART_BUDGET", 3)
        self.restart_budget = int(restart_budget)
        self._restarts = 0
        self._lock = threading.Lock()
        self._attached = False
        self._gate = _policy.ReadmissionGate(
            probe=readmission_probe, registry=self._registry)
        # Degraded-link replanning state: one active swap per hop.
        self._active_swaps: Dict[str, ReplanDecision] = {}
        self._replans: List[ReplanDecision] = []
        self._preempt_log: List[dict] = []

    # -- lifecycle -------------------------------------------------------

    def attach(self) -> "Supervisor":
        """Register the SIGTERM priority-snapshot hook (before the
        flight dump; see monitor/flight.py ordering contract) and the
        readmission gate on the driver's HostManager. Idempotent."""
        if self._attached:
            return self
        self._attached = True
        try:
            from ..monitor import flight as _flight

            _flight.register_sigterm_hook(self._on_preemption)
        except Exception:
            pass
        hm = getattr(self.driver, "host_manager", None)
        if hm is not None:
            try:
                hm.set_readmission_probe(self._gate)
            except Exception:
                pass
        _timeline_instant("RESILIENCE:ATTACH",
                          {"deadline_secs": self.snapshot_deadline_secs,
                           "restart_budget": self.restart_budget})
        return self

    def detach(self) -> None:
        if not self._attached:
            return
        self._attached = False
        try:
            from ..monitor import flight as _flight

            _flight.unregister_sigterm_hook(self._on_preemption)
        except Exception:
            pass
        hm = getattr(self.driver, "host_manager", None)
        if hm is not None:
            try:
                hm.set_readmission_probe(None)
            except Exception:
                pass

    def set_snapshot_provider(self, fn) -> None:
        self._snapshot_provider = fn

    # -- preemption ------------------------------------------------------

    def _on_preemption(self) -> None:
        """The SIGTERM pre-dump hook: a deadline-budgeted priority
        snapshot through the AsyncWriter. Never raises (the flight
        handler guards it anyway, but the dump must happen)."""
        try:
            self.on_preemption_notice()
        except Exception as e:
            logger.error(f"resilience: priority snapshot failed: {e!r}")

    def on_preemption_notice(self, source: str = "sigterm") -> dict:
        """Handle one preemption notice; returns the event record."""
        started = time.monotonic()
        deadline = started + self.snapshot_deadline_secs
        decision = self.engine.record_failure(
            _policy.CLASS_PREEMPTION, key=source)
        reg = self._registry
        reg.counter("resilience.preempt.notices").inc()
        saved_step = None
        committed = False
        if (self._snapshot_provider is not None
                and self.ckpt_manager is not None):
            snap = None
            try:
                snap = self._snapshot_provider()
            except Exception as e:
                logger.error(
                    f"resilience: snapshot provider failed: {e!r}")
            if snap is not None:
                step, tree, extra = snap
                latest = None
                try:
                    latest = self.ckpt_manager.latest_step()
                except Exception:
                    pass
                if latest is None or step > latest:
                    try:
                        self.ckpt_manager.save(int(step), tree,
                                               extra=extra)
                        saved_step = int(step)
                    except Exception as e:
                        logger.error(
                            f"resilience: priority save failed: {e!r}")
                else:
                    # Nothing newer than the last commit — the drain
                    # below still quiesces any in-flight write.
                    saved_step = latest
            try:
                remaining = max(0.0, deadline - time.monotonic())
                committed = bool(self.ckpt_manager.wait(remaining))
            except Exception:
                committed = False
        elapsed_ms = (time.monotonic() - started) * 1e3
        deadline_met = (committed
                        and elapsed_ms <= self.snapshot_deadline_secs
                        * 1e3)
        if saved_step is None:
            # No state to commit: the notice is still deadline-met as
            # long as we are inside the grace window.
            deadline_met = elapsed_ms <= self.snapshot_deadline_secs * 1e3
        event = {"source": source, "saved_step": saved_step,
                 "committed": committed,
                 "deadline_secs": self.snapshot_deadline_secs,
                 "elapsed_ms": round(elapsed_ms, 3),
                 "deadline_met": deadline_met,
                 "policy_action": decision.action}
        reg.counter("resilience.preempt.snapshots",
                    verdict=("deadline_met" if deadline_met
                             else "deadline_missed")).inc()
        reg.gauge("resilience.preempt.snapshot_ms").set(elapsed_ms)
        _timeline_instant("RESILIENCE:PREEMPT", event)
        with self._lock:
            self._preempt_log.append(event)
            del self._preempt_log[:-64]
        logger.warning(
            f"resilience: preemption notice ({source}) — priority "
            f"snapshot step={saved_step} committed={committed} in "
            f"{elapsed_ms:.0f} ms (deadline "
            f"{self.snapshot_deadline_secs:g}s, "
            f"{'met' if deadline_met else 'MISSED'})")
        return event

    # -- restart budget --------------------------------------------------

    def restart_allowed(self) -> bool:
        with self._lock:
            return self._restarts < self.restart_budget

    def record_restart(self, restored_step: Optional[int] = None) -> bool:
        """One restart-from-last-commit happened; False = budget gone
        (the caller should treat the next failure as fatal)."""
        with self._lock:
            self._restarts += 1
            n = self._restarts
        self._registry.counter("resilience.restarts").inc()
        self._registry.gauge("resilience.restart_budget_left").set(
            max(0, self.restart_budget - n))
        _timeline_instant("RESILIENCE:RESTART",
                          {"restored_step": restored_step, "count": n,
                           "budget": self.restart_budget})
        if n > self.restart_budget:
            self.engine.record_failure(_policy.CLASS_WORKER_CRASH,
                                       key="restart_budget")
            return False
        return True

    # -- generic failure routing ----------------------------------------

    def on_failure(self, cls: str, key: str = "*",
                   detail: Optional[dict] = None) -> _policy.Decision:
        """Record a failure; perform the ladder actions the supervisor
        can (blacklist); return the decision for the caller's loop."""
        decision = self.engine.record_failure(cls, key=key, detail=detail)
        if decision.action == _policy.RECOVER_BLACKLIST:
            hm = getattr(self.driver, "host_manager", None)
            if hm is not None and key not in ("*", ""):
                try:
                    hm.blacklist(key)
                except Exception:
                    pass
        return decision

    def on_success(self, cls: str, key: str = "*") -> None:
        self.engine.record_success(cls, key=key)

    # -- degraded-link replanning ---------------------------------------

    def maybe_replan(self, payload_bytes: float, *,
                     mesh_shape=None, compute_ms=None,
                     step: Optional[int] = None) -> Optional[dict]:
        """Step-boundary hook: inspect the link-health latches and
        return a swap directive, a revert directive, or None.

        On a newly degraded hop: re-price the shortlist under the
        EWMA-derated cost model and return ``{"swap": PricedPlan,
        "hop": ..., "decision": ReplanDecision}`` — the caller applies
        the plan (e.g. ``quantized=True`` on its collectives) from the
        next step. On recovery (latch cleared): return
        ``{"revert": True, "hop": ...}``. Never raises into the step.
        """
        det = self._straggler
        if det is None:
            try:
                from ..monitor import straggler as _straggler_mod

                det = _straggler_mod.straggler_detector()
            except Exception:
                return None
        try:
            degraded = det.degraded_hops()
        except Exception:
            return None
        # Recovery first: any active swap whose hop is healthy again.
        for hop in list(self._active_swaps):
            if hop not in degraded:
                rec = self._active_swaps.pop(hop)
                rec.reverted = True
                self._registry.counter("resilience.replans",
                                       kind="revert", hop=hop).inc()
                _timeline_instant("RESILIENCE:REPLAN_REVERT",
                                  {"hop": hop, "step": step,
                                   "plan": rec.plan_after})
                self.engine.record_success(_policy.CLASS_DEGRADED_LINK,
                                           key=hop)
                logger.warning(
                    f"resilience: {hop} link recovered — reverting the "
                    f"quantized-wire swap at step {step}")
                return {"revert": True, "hop": hop, "decision": rec}
        for hop, ewma in degraded.items():
            if hop in self._active_swaps:
                continue  # already swapped; hold until recovery
            decision = self.engine.record_failure(
                _policy.CLASS_DEGRADED_LINK, key=hop,
                detail={"ewma_ratio": round(ewma, 3)})
            if decision.action != _policy.RECOVER_REPLAN:
                continue
            swap = self._price_swap(hop, ewma, payload_bytes,
                                    mesh_shape=mesh_shape,
                                    compute_ms=compute_ms)
            if swap is None:
                continue
            plan_row, rec = swap
            rec.step = step
            with self._lock:
                self._active_swaps[hop] = rec
                self._replans.append(rec)
                del self._replans[:-64]
            self._registry.counter("resilience.replans",
                                   kind="swap", hop=hop).inc()
            _timeline_instant("RESILIENCE:REPLAN", rec.as_dict())
            logger.warning(
                f"resilience: {hop} link degraded (EWMA ratio "
                f"{ewma:.2f}) — hot-swapping to "
                f"{rec.plan_after} at step {step} "
                f"(predicted {rec.predicted_ms:.3f} ms under the "
                f"observed-bandwidth override)")
            return {"swap": plan_row, "hop": hop, "decision": rec}
        return None

    def _price_swap(self, hop: str, ewma: float, payload_bytes: float, *,
                    mesh_shape=None, compute_ms=None):
        """Re-price the shortlist with the hop's bandwidth derated by
        the observed EWMA ratio — a CostModel *override*, not a
        recalibration (the calibration store is untouched)."""
        try:
            from ..plan import cost as _cost
            from ..plan import planner as _planner

            base = _cost.resolve(mesh_shape)
            link = base.link(hop)
            derated = dataclasses.replace(
                link, bandwidth_gbps=max(1e-6,
                                         link.bandwidth_gbps
                                         / max(1.0, ewma)))
            override = dataclasses.replace(
                base, source=f"{base.source}+observed:{hop}",
                **{hop: derated})
            rows = _planner.shortlist(payload_bytes,
                                      mesh_shape=mesh_shape,
                                      model=override,
                                      compute_ms=compute_ms,
                                      quantized=True, k=4)
        except Exception as e:
            logger.warning(
                f"resilience: replan pricing failed for {hop}: {e!r}")
            return None
        if not rows:
            return None
        # Prefer a winner that actually uses the quantized wire on the
        # degraded hop; the top row usually does under the derated
        # bandwidth (int8 moves 4x fewer bytes over the slow link).
        best = None
        for row in rows:
            enc = row.plan.encode()
            if "int8" in enc:
                best = row
                break
        best = best or rows[0]
        before = None
        try:
            baseline = _planner.shortlist(payload_bytes,
                                          mesh_shape=mesh_shape,
                                          quantized=False, k=1)
            if baseline:
                before = baseline[0].plan.encode()
        except Exception:
            pass
        rec = ReplanDecision(hop=hop, ewma_ratio=float(ewma),
                             plan_before=before,
                             plan_after=best.plan.encode(),
                             predicted_ms=float(best.predicted_ms))
        return best, rec

    # -- reporting -------------------------------------------------------

    def active_swaps(self) -> Dict[str, ReplanDecision]:
        with self._lock:
            return dict(self._active_swaps)

    def report(self) -> dict:
        """Supervisor state for the soak report / flight dump."""
        with self._lock:
            replans = [r.as_dict() for r in self._replans]
            preempts = list(self._preempt_log)
            restarts = self._restarts
        return {
            "policy": self.engine.snapshot(),
            "replans": replans,
            "preemptions": preempts,
            "restarts": restarts,
            "restart_budget": self.restart_budget,
            "snapshot_deadline_secs": self.snapshot_deadline_secs,
        }


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    if v in (None, ""):
        return default
    try:
        return float(v)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    if v in (None, ""):
        return default
    try:
        return int(v)
    except ValueError:
        return default
