"""Self-healing runtime: the failure-policy supervisor.

Thirteen PRs built the detection and recovery *mechanisms* — deterministic
fault injection (chaos/), elastic blacklist-and-resume (elastic/driver),
async commit-or-nothing checkpoints with cross-world reshard
(checkpoint/), crash forensics and link-health scoring (monitor/flight,
monitor/straggler), and a priced plan space with an int8 wire alternative
(plan/). This package is the *policy* layer that connects them: failure
classification with per-class budgets and an escalation ladder
(:mod:`~horovod_tpu.resilience.policy`), and a supervisor that turns
detection signals into recovery actions — preemption-notice priority
snapshots, restart-from-last-commit under a budget, and degraded-link
replanning onto the quantized wire
(:mod:`~horovod_tpu.resilience.supervisor`).

All state is observable: ``resilience.*`` counters/gauges in the metrics
registry and ``RESILIENCE:*`` timeline/flight events (the prefix is
registered in ``monitor/span_audit.py``). The production contract the
layer must hold is enforced by ``scripts/soak.py`` (docs/robustness.md).
"""

from .policy import (  # noqa: F401
    CLASSES,
    CLASS_DEGRADED_LINK,
    CLASS_DISCOVERY_FLAP,
    CLASS_PREEMPTION,
    CLASS_RPC_EXHAUSTED,
    CLASS_STALL,
    CLASS_WORKER_CRASH,
    LADDER,
    RECOVER_ABORT,
    RECOVER_BLACKLIST,
    RECOVER_REPLAN,
    RECOVER_RETRY,
    RECOVER_SHRINK,
    RECOVER_SNAPSHOT,
    Decision,
    Policy,
    PolicyEngine,
    ReadmissionGate,
    default_policies,
)
from .supervisor import (  # noqa: F401
    ReplanDecision,
    Supervisor,
)
