"""Failure classification and per-class recovery policies.

Every failure signal the runtime can raise maps onto one of six classes;
each class carries a :class:`Policy` — a retry budget with capped
exponential backoff, and an escalation ladder entered once the budget is
exhausted. The ladder is the elastic playbook made explicit::

    retry (budgeted, backed off) → blacklist → shrink_world → abort

The state machine lives in :class:`PolicyEngine`: one counter per
``(class, key)`` pair (the key names the failing subject — a host, a
hop, an RPC service), advanced by :meth:`~PolicyEngine.record_failure`
and reset by :meth:`~PolicyEngine.record_success`. Decisions are pure
data (:class:`Decision`); the supervisor performs them.

Observability contract (docs/robustness.md): every recorded failure
bumps ``resilience.failures{cls}``, every decision bumps
``resilience.actions{cls,action}``, backoff state is the
``resilience.backoff_secs{cls,key}`` gauge, and class transitions emit
``RESILIENCE:FAILURE`` / ``RESILIENCE:ESCALATE`` timeline/flight
instants — all of it rides the flight dump, so a postmortem can replay
the policy's view of the incident.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from ..monitor import registry as _registry
from ..monitor.straggler import _timeline_instant

logger = logging.getLogger("horovod_tpu.resilience")

#: The failure classes (docs/robustness.md, failure-class table).
CLASS_WORKER_CRASH = "worker_crash"      # a worker died (exit, OOM, chaos)
CLASS_RPC_EXHAUSTED = "rpc_exhausted"    # client retries ran out
CLASS_STALL = "stall"                    # stall inspector escalated
CLASS_DISCOVERY_FLAP = "discovery_flap"  # discovery transiently empty
CLASS_PREEMPTION = "preemption"          # spot/maintenance SIGTERM notice
CLASS_DEGRADED_LINK = "degraded_link"    # straggler link-health latch

CLASSES = (CLASS_WORKER_CRASH, CLASS_RPC_EXHAUSTED, CLASS_STALL,
           CLASS_DISCOVERY_FLAP, CLASS_PREEMPTION, CLASS_DEGRADED_LINK)

#: Recovery actions a :class:`Decision` may carry.
RECOVER_RETRY = "retry"            # wait backoff_secs, try again
RECOVER_BLACKLIST = "blacklist"    # evict the subject host
RECOVER_SHRINK = "shrink_world"    # resume with the remaining hosts
RECOVER_ABORT = "abort"            # budgets exhausted: stop the job
RECOVER_SNAPSHOT = "snapshot"      # priority checkpoint (preemption)
RECOVER_REPLAN = "replan"          # re-price the wire (degraded link)

#: The post-budget escalation ladder, in order.
LADDER = (RECOVER_BLACKLIST, RECOVER_SHRINK, RECOVER_ABORT)


@dataclasses.dataclass(frozen=True)
class Policy:
    """One class's recovery policy.

    ``retry_budget`` failures get :data:`RECOVER_RETRY` decisions with
    capped exponential backoff (``backoff_base_secs * 2**(n-1)``, capped
    at ``backoff_cap_secs``); failures past the budget walk the
    escalation ladder one rung per failure, starting at
    ``ladder_start``. Classes whose first response is not a retry
    (preemption → snapshot, degraded link → replan) set ``on_failure``.
    """

    retry_budget: int = 3
    backoff_base_secs: float = 0.5
    backoff_cap_secs: float = 30.0
    ladder_start: int = 0           # index into LADDER after the budget
    on_failure: str = RECOVER_RETRY

    def backoff(self, failures: int) -> float:
        """Backoff for the n-th consecutive failure (1-based)."""
        if failures <= 0:
            return 0.0
        return min(self.backoff_cap_secs,
                   self.backoff_base_secs * (2.0 ** (failures - 1)))


def default_policies() -> Dict[str, Policy]:
    """The per-class defaults (docs/robustness.md knob table)."""
    return {
        # A crashed worker is the elastic bread-and-butter: a couple of
        # world rebuilds, then start evicting.
        CLASS_WORKER_CRASH: Policy(retry_budget=2, backoff_base_secs=1.0),
        # RPC exhaustion already survived the transport's own retry
        # loop, so the policy layer retries once and then escalates.
        CLASS_RPC_EXHAUSTED: Policy(retry_budget=1,
                                    backoff_base_secs=2.0),
        # A stall escalation means the watchdog already waited its
        # shutdown window — go straight to the ladder.
        CLASS_STALL: Policy(retry_budget=0),
        # Discovery flaps are usually control-plane noise: generous
        # budget, short backoff, and shrinking (not blacklisting — no
        # specific host is at fault) when it persists.
        CLASS_DISCOVERY_FLAP: Policy(retry_budget=5,
                                     backoff_base_secs=0.5,
                                     ladder_start=1),
        # A preemption notice is not retryable: snapshot now, and the
        # ladder (for repeat notices past the budget) shrinks.
        CLASS_PREEMPTION: Policy(retry_budget=3, backoff_base_secs=0.0,
                                 ladder_start=1,
                                 on_failure=RECOVER_SNAPSHOT),
        # A degraded link is a performance failure, not a liveness one:
        # replan onto the cheaper wire, never abort for it.
        CLASS_DEGRADED_LINK: Policy(retry_budget=1_000_000,
                                    backoff_base_secs=0.0,
                                    on_failure=RECOVER_REPLAN),
    }


@dataclasses.dataclass(frozen=True)
class Decision:
    """What the policy wants done about one recorded failure."""

    cls: str
    key: str
    action: str
    failures: int          # consecutive failures of this (cls, key)
    backoff_secs: float    # wait before acting (retry decisions)

    def as_dict(self) -> dict:
        return {"cls": self.cls, "key": self.key, "action": self.action,
                "failures": self.failures,
                "backoff_secs": round(self.backoff_secs, 3)}


class PolicyEngine:
    """The per-(class, key) failure state machine.

    Thread-safe. ``record_failure`` advances the counter and returns the
    policy's :class:`Decision`; ``record_success`` resets it (a healthy
    observation ends the escalation). The engine never *performs*
    actions — the supervisor does — so units can drive it to budget
    exhaustion without touching a driver.
    """

    def __init__(self,
                 policies: Optional[Dict[str, Policy]] = None,
                 registry: Optional[_registry.MetricsRegistry] = None
                 ) -> None:
        self.policies = dict(default_policies())
        if policies:
            self.policies.update(policies)
        self._registry = registry or _registry.default_registry()
        self._lock = threading.Lock()
        self._failures: Dict[Tuple[str, str], int] = {}
        self._decisions: list = []  # bounded below; rides the soak report

    def _policy(self, cls: str) -> Policy:
        if cls not in CLASSES:
            raise ValueError(
                f"unknown failure class {cls!r}; one of {CLASSES}")
        return self.policies.get(cls, Policy())

    def failures(self, cls: str, key: str = "*") -> int:
        with self._lock:
            return self._failures.get((cls, key), 0)

    def decisions(self) -> list:
        with self._lock:
            return list(self._decisions)

    def record_failure(self, cls: str, key: str = "*",
                       detail: Optional[dict] = None) -> Decision:
        """One failure of ``(cls, key)`` happened; decide the response."""
        policy = self._policy(cls)
        with self._lock:
            n = self._failures.get((cls, key), 0) + 1
            self._failures[(cls, key)] = n
        reg = self._registry
        reg.counter("resilience.failures", cls=cls).inc()
        if n <= policy.retry_budget:
            action = policy.on_failure
            backoff = policy.backoff(n)
        else:
            # Past the budget: one ladder rung per further failure,
            # clamped at abort (the ladder's last rung repeats).
            rung = min(policy.ladder_start + (n - policy.retry_budget - 1),
                       len(LADDER) - 1)
            action = LADDER[rung]
            backoff = 0.0
            reg.counter("resilience.escalations", cls=cls,
                        action=action).inc()
            _timeline_instant("RESILIENCE:ESCALATE",
                              {"cls": cls, "key": key, "action": action,
                               "failures": n})
            logger.warning(
                f"resilience: {cls} budget exhausted for {key!r} "
                f"({n} failures > budget {policy.retry_budget}) — "
                f"escalating to {action}")
        reg.counter("resilience.actions", cls=cls, action=action).inc()
        reg.gauge("resilience.backoff_secs", cls=cls, key=key).set(backoff)
        decision = Decision(cls=cls, key=key, action=action, failures=n,
                            backoff_secs=backoff)
        _timeline_instant("RESILIENCE:FAILURE",
                          {**decision.as_dict(), **(detail or {})})
        with self._lock:
            self._decisions.append(decision)
            del self._decisions[:-256]
        return decision

    def record_success(self, cls: str, key: str = "*") -> None:
        """A healthy observation of ``(cls, key)``: reset its counter."""
        with self._lock:
            had = self._failures.pop((cls, key), 0)
        if had:
            self._registry.counter("resilience.recoveries", cls=cls).inc()
            self._registry.gauge("resilience.backoff_secs", cls=cls,
                                 key=key).set(0.0)
            _timeline_instant("RESILIENCE:RECOVER",
                              {"cls": cls, "key": key,
                               "cleared_failures": had})

    def snapshot(self) -> dict:
        """Policy state for the flight dump / soak report."""
        with self._lock:
            return {
                "failures": {f"{c}:{k}": n
                             for (c, k), n in self._failures.items()},
                "decisions": [d.as_dict() for d in self._decisions[-32:]],
            }


class ReadmissionGate:
    """Health-gated blacklist readmission (docs/robustness.md).

    Installed on :class:`~horovod_tpu.elastic.discovery.HostManager` as
    its ``readmission_probe``: when a host's cooldown expires, the gate
    runs ``probe(host)`` — only a passing probe readmits; a failing (or
    raising) probe re-arms the cooldown. The default probe passes
    unconditionally, preserving cooldown-only semantics while still
    counting readmissions through the resilience metrics.
    """

    def __init__(self, probe: Optional[Callable[[str], bool]] = None,
                 registry: Optional[_registry.MetricsRegistry] = None
                 ) -> None:
        self._probe = probe
        self._registry = registry or _registry.default_registry()

    def __call__(self, host: str) -> bool:
        started = time.monotonic()
        try:
            healthy = True if self._probe is None else bool(
                self._probe(host))
        except Exception as e:
            logger.warning(
                f"resilience: readmission probe for {host} raised "
                f"{e!r} — treating as unhealthy")
            healthy = False
        verdict = "pass" if healthy else "fail"
        self._registry.counter("resilience.readmission",
                               verdict=verdict).inc()
        _timeline_instant("RESILIENCE:READMIT",
                          {"host": host, "verdict": verdict,
                           "probe_ms": round(
                               (time.monotonic() - started) * 1e3, 3)})
        return healthy
