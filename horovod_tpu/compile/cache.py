"""Persistent executable cache: compile once, run everywhere warm.

Horovod's response cache exists so a stable tensor set never re-pays
coordination (reference ``common/response_cache.h``); on the XLA path the
analogous recurring cost is *compilation* — every autotune trial, every
elastic resize, every restarted worker used to re-pay lowering + XLA
compile for programs this process (or a previous one) already built.
This module is the framework-level answer, two layers deep:

1. :func:`arm_persistent_cache` points JAX's own persistent compilation
   cache (``jax_compilation_cache_dir``) at a directory beside the
   kernel-autotune cache, so *any* jit compile in the process can be
   served from disk by XLA itself. Armed from :func:`horovod_tpu.init`
   BEFORE the mesh exists — the knob only applies cleanly ahead of the
   first compilation.
2. :class:`ExecutableCache` — a registry of *loaded executables* keyed by
   ``(tag, plan encoding, mesh_geometry() fingerprint, shape/dtype
   signature, jax version)``. A hit skips lowering AND compile entirely
   (``jax.experimental.serialize_executable`` payloads, pickled beside a
   JSON index with the autotune cache's flock + atomic-replace
   discipline), which is what makes warm bench reruns, autotune replays,
   and restarted elastic workers start in milliseconds.

Observability contract (docs/compile.md): ``compile.hits`` /
``compile.misses`` / ``compile.compile_ms{key=tag}`` metrics,
``COMPILE:LOWER`` / ``COMPILE:COMPILE`` spans + ``COMPILE:CACHE_HIT``
instants on the Timeline (span_audit vocabulary), a ``compile``
straggler phase, and flight-recorder ring entries. Failure discipline
follows ``get_cost_model``: the cache is an optimization, never a
failure — a corrupt index, an unreadable payload, or a deserialize
error logs a warning and falls back to a cold compile.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import pickle
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

logger = logging.getLogger("horovod_tpu.compile")

_lock = threading.Lock()
#: in-memory registry: key -> (compiled, compile_ms, aux)
_mem: Dict[str, Tuple[Any, float, dict]] = {}
#: process-lifetime counters (reset via :func:`reset_stats`)
_stats = {"hits": 0, "misses": 0, "disk_hits": 0, "compile_ms": 0.0}
_warned = {"disk": False, "arm": False}

#: Bump when the on-disk entry layout changes — stale-format entries are
#: ignored (treated as misses), never an error.
_FORMAT_VERSION = 1


# ---------------------------------------------------------------------------
# knobs


def enabled() -> bool:
    """Whether the compile cache (both layers) is armed.

    ``HOROVOD_COMPILE_CACHE=0`` disables persistence entirely; the
    in-memory executable registry stays on (it is what de-duplicates
    identical compiles inside one process)."""
    from ..common.config import _env_bool

    return _env_bool("HOROVOD_COMPILE_CACHE", True)


def cache_dir() -> str:
    """Root of the compile cache (``HOROVOD_COMPILE_CACHE_DIR``; default
    beside the kernel-autotune cache). Two subtrees: ``xla/`` for JAX's
    persistent compilation cache, ``exec/`` for serialized-executable
    payloads + ``index.json``."""
    from ..common.config import _env_str

    d = _env_str("HOROVOD_COMPILE_CACHE_DIR", None)
    if d:
        return d
    return os.path.join(os.path.expanduser("~"), ".cache", "horovod_tpu",
                        "compile")


def _exec_dir() -> str:
    return os.path.join(cache_dir(), "exec")


def _index_path() -> str:
    return os.path.join(_exec_dir(), "index.json")


# ---------------------------------------------------------------------------
# persistent XLA compilation cache (layer 1)


def arm_persistent_cache(config=None) -> Optional[str]:
    """Point ``jax_compilation_cache_dir`` at the compile cache dir.

    Called from ``hvd.init`` before the mesh is built (before any
    compilation — the persistent cache only covers compiles issued after
    arming). Thresholds are zeroed so fast CPU-mesh compiles persist
    too: the CI smoke and warm-rerun gates run on the 2x4 host-platform
    mesh where every compile is "too fast to be worth caching" under
    JAX's defaults. Returns the armed directory, or None when disabled
    or when arming fails (logged once, never raised)."""
    if config is not None and not getattr(config, "compile_cache", True):
        return None
    if not enabled():
        return None
    xla_dir = os.path.join(cache_dir(), "xla")
    try:
        import jax

        os.makedirs(xla_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", xla_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # cache is an optimization, never a failure
        if not _warned["arm"]:
            _warned["arm"] = True
            logger.warning("persistent compilation cache not armed "
                           "(%s: %s) — compiles stay cold across "
                           "processes", type(e).__name__, str(e)[:200])
        return None
    return xla_dir


# ---------------------------------------------------------------------------
# executable keys (layer 2)


def _shapes_signature(shapes) -> str:
    """Stable signature of an abstract-args pytree: per-leaf
    ``shape/dtype`` plus the NamedSharding spec when one is attached
    (two differently-sharded lowers of one fn are different
    executables)."""
    if shapes is None:
        return "noshapes"
    import jax

    parts = []
    for leaf in jax.tree_util.tree_leaves(shapes):
        shp = getattr(leaf, "shape", None)
        dt = getattr(leaf, "dtype", None)
        if shp is None and dt is None:
            parts.append(repr(leaf))
            continue
        sig = f"{'x'.join(str(int(s)) for s in shp)}:{dt}"
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        if spec is not None:
            sig += f":{spec}"
        parts.append(sig)
    raw = ";".join(parts)
    if len(raw) > 160:
        raw = hashlib.sha1(raw.encode()).hexdigest()[:16]
    return raw or "noshapes"


def _mesh_fingerprint(mesh) -> str:
    """``mesh_geometry()`` when the mesh fits the framework vocabulary;
    otherwise (e.g. the serve engine's 1-D ``serve_tp`` mesh over a
    device subset) a raw ``mesh<shape>@<axes>#<device-ids>`` form — two
    replicas over different device slices are different executables."""
    from ..common import basics

    try:
        if mesh is None:
            return basics.mesh_geometry()
        shp = mesh.devices.shape
        if len(shp) >= 2:
            return basics.mesh_geometry(mesh=mesh)
    except Exception:
        pass
    if mesh is None:
        return "nomesh"
    devs = list(mesh.devices.ravel())
    shape = "x".join(str(int(v)) for v in mesh.devices.shape)
    axes = ".".join(str(a) for a in mesh.axis_names)
    ids = ",".join(str(getattr(d, "id", "?")) for d in devs)
    if len(ids) > 48:
        ids = hashlib.sha1(ids.encode()).hexdigest()[:12]
    kind = str(getattr(devs[0], "device_kind", "unknown")
               or "unknown").strip().lower().replace(" ", "-")
    return f"mesh{shape}@{axes}#{ids}|world{len(devs)}|{kind}"


def executable_key(tag: str, *, plan: Optional[str] = None,
                   mesh=None, shapes=None,
                   extra: Optional[str] = None) -> str:
    """The registry key for one executable.

    Anatomy (docs/compile.md): ``xc|<tag>|<plan>|<geometry>|<shapes>|
    <extra>|jax<version>|v<format>`` — the wire-plan encoding and the
    ``mesh_geometry()`` fingerprint carry exactly the same
    transfer-safety contract as the autotune warm-start cache: an
    executable compiled for one topology/chip kind/plan never hits
    another."""
    import jax

    geo = _mesh_fingerprint(mesh)
    sig = _shapes_signature(shapes)
    return (f"xc|{tag}|{plan or 'noplan'}|{geo}|{sig}|"
            f"{extra or 'noextra'}|jax{jax.__version__}|"
            f"v{_FORMAT_VERSION}")


# ---------------------------------------------------------------------------
# disk store (flock + atomic replace, kernel_autotune discipline)


def _disk_load(key: str) -> Optional[Tuple[Any, float, dict]]:
    """Deserialize ``key``'s executable from disk, or None. Any failure
    (missing, corrupt, incompatible) is a logged miss."""
    if not enabled():
        return None
    try:
        with open(_index_path()) as f:
            index = json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None
    meta = index.get(key) if isinstance(index, dict) else None
    if not isinstance(meta, dict):
        return None
    try:
        from jax.experimental import serialize_executable as _se

        with open(os.path.join(_exec_dir(), meta["file"]), "rb") as f:
            payload, in_tree, out_tree = pickle.loads(f.read())
        compiled = _se.deserialize_and_load(payload, in_tree, out_tree)
        return (compiled, float(meta.get("compile_ms", 0.0)),
                dict(meta.get("aux") or {}))
    except Exception as e:  # corrupt/foreign entry: cold compile instead
        if not _warned["disk"]:
            _warned["disk"] = True
            logger.warning(
                "executable cache entry unreadable (%s: %s) — falling "
                "back to cold compile; delete %s to clear stale entries",
                type(e).__name__, str(e)[:200], _exec_dir())
        return None


def _disk_store(key: str, compiled, compile_ms: float, aux: dict) -> None:
    """Serialize ``compiled`` beside the index under the OS lock.

    Read-merge-write of ``index.json`` under ``fcntl.flock`` with an
    ``os.replace`` finish — concurrent processes caching different
    executables must not clobber each other (the kernel_autotune store
    discipline)."""
    if not enabled():
        return
    try:
        from jax.experimental import serialize_executable as _se

        payload = pickle.dumps(_se.serialize(compiled))
    except Exception as e:  # unserializable backend: memory-only entry
        logger.debug("executable %s not serializable (%s) — memory-only",
                     key, str(e)[:200])
        return
    fname = hashlib.sha1(key.encode()).hexdigest()[:20] + ".bin"
    path = _index_path()
    try:
        os.makedirs(_exec_dir(), exist_ok=True)
        import fcntl

        with open(path + ".lock", "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            tmp_bin = os.path.join(_exec_dir(),
                                   f"{fname}.tmp.{os.getpid()}")
            with open(tmp_bin, "wb") as f:
                f.write(payload)
            os.replace(tmp_bin, os.path.join(_exec_dir(), fname))
            disk: dict = {}
            try:
                with open(path) as f:
                    disk = json.load(f)
            except (FileNotFoundError, json.JSONDecodeError, ValueError):
                pass
            disk[key] = {"file": fname,
                         "compile_ms": round(float(compile_ms), 3),
                         "aux": aux, "wall": time.time()}
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(disk, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
    except OSError as e:  # cache is an optimization, never a failure
        logger.debug("executable cache write failed: %s", e)


# ---------------------------------------------------------------------------
# observability plumbing


def _timeline():
    from ..common import basics

    return basics._state.timeline if basics.is_initialized() else None


def _span(name: str, ph: str, args: Optional[dict] = None) -> None:
    tl = _timeline()
    if tl is not None:
        tl.emit(name, ph, tid="compile", args=args)
    from ..monitor import flight as _flight

    _flight.record(name, ph, tid="compile", args=args)


def _observe(tag: str, source: str, compile_ms: float, key: str) -> None:
    from ..monitor import registry as _metrics
    from ..monitor import straggler as _straggler

    if source == "compiled":
        _metrics.counter("compile.misses", key=tag).inc()
        _metrics.histogram("compile.compile_ms", key=tag).observe(
            compile_ms)
        _straggler.record_phase("compile", compile_ms)
    else:
        _metrics.counter("compile.hits", key=tag).inc()
        _span("COMPILE:CACHE_HIT", "i",
              {"key": key, "source": source,
               "saved_compile_ms": round(compile_ms, 3)})


# ---------------------------------------------------------------------------
# the registry


@dataclasses.dataclass(frozen=True)
class CompileResult:
    """One :func:`get_or_compile` outcome."""

    compiled: Any          #: the loaded executable (callable)
    source: str            #: ``memory`` | ``disk`` | ``compiled``
    compile_ms: float      #: cost paid (miss) or skipped (hit)
    aux: dict              #: caller metadata persisted with the entry
    key: str               #: the full registry key

    @property
    def cache_hit(self) -> bool:
        return self.source != "compiled"


def get_or_compile(tag: str, lower: Callable[[], Any], *,
                   plan: Optional[str] = None, mesh=None, shapes=None,
                   extra: Optional[str] = None,
                   aux_fn: Optional[Callable[[Any], dict]] = None,
                   ) -> CompileResult:
    """The executable for ``(tag, plan, geometry, shapes)``, compiling at
    most once per key across processes.

    ``lower()`` returns a ``Lowered`` (``jit(fn).lower(*abstract_args)``)
    and only runs on a miss — a memory or disk hit skips lowering AND
    compile. ``aux_fn(lowered)`` (miss only) returns JSON-safe metadata
    persisted with the entry and returned on every later hit; bench uses
    it to keep wire-plan byte stats available on warm reruns where no
    lowering happens. Never raises on cache trouble — the worst case is
    a cold compile."""
    key = executable_key(tag, plan=plan, mesh=mesh, shapes=shapes,
                         extra=extra)
    with _lock:
        hit = _mem.get(key)
    if hit is not None:
        compiled, ms, aux = hit
        _observe(tag, "memory", ms, key)
        with _lock:
            _stats["hits"] += 1
        return CompileResult(compiled, "memory", ms, aux, key)

    disk = _disk_load(key)
    if disk is not None:
        compiled, ms, aux = disk
        with _lock:
            _mem[key] = (compiled, ms, aux)
            _stats["hits"] += 1
            _stats["disk_hits"] += 1
        _observe(tag, "disk", ms, key)
        return CompileResult(compiled, "disk", ms, aux, key)

    # Miss: pay lowering + compile, timed as separate spans so the phase
    # breakdown distinguishes trace-heavy from XLA-heavy programs.
    t0 = time.perf_counter()
    _span("COMPILE:LOWER", "B", {"key": key})
    try:
        lowered = lower()
    finally:
        _span("COMPILE:LOWER", "E")
    _span("COMPILE:COMPILE", "B", {"key": key})
    try:
        compiled = lowered.compile()
    finally:
        _span("COMPILE:COMPILE", "E")
    compile_ms = (time.perf_counter() - t0) * 1e3
    aux = {}
    if aux_fn is not None:
        try:
            aux = dict(aux_fn(lowered) or {})
        except Exception as e:  # aux is metadata, never a failure
            logger.debug("aux_fn for %s failed: %s", tag, e)
    with _lock:
        _mem[key] = (compiled, compile_ms, aux)
        _stats["misses"] += 1
        _stats["compile_ms"] += compile_ms
    _observe(tag, "compiled", compile_ms, key)
    _disk_store(key, compiled, compile_ms, aux)
    return CompileResult(compiled, "compiled", compile_ms, aux, key)


# ---------------------------------------------------------------------------
# stats (bench JSON + gates)


def stats() -> dict:
    """Process-lifetime registry counters: ``hits`` / ``misses`` (true
    compiles) / ``disk_hits`` / ``compile_ms`` total."""
    with _lock:
        return dict(_stats)


def compile_count() -> int:
    """Number of TRUE compiles this process paid through the registry —
    the quantity the warm-rerun perf gate asserts is zero."""
    with _lock:
        return int(_stats["misses"])


def reset_stats() -> None:
    with _lock:
        _stats.update(hits=0, misses=0, disk_hits=0, compile_ms=0.0)


def clear_memory() -> None:
    """Drop the in-process registry (tests; disk entries survive)."""
    with _lock:
        _mem.clear()
