"""AOT warm pools: ``hvd.precompile(fn, specs)``.

Ahead-of-time compilation through the executable cache: callers hand a
function plus the abstract argument shapes they will serve, and get back
ready-to-call executables (``jit(fn).lower(*spec).compile()`` routed via
:mod:`.cache` so identical requests — across warm pools, engines, and
processes — compile exactly once). The serve engine warms its step for
every admission shape bucket at startup, and ``ReplicaSet`` warms the
TARGET geometry's executables in the background before a resize drain
(docs/compile.md has the lifecycle and ordering contract).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

from . import cache as _cache


def _as_spec_tuple(spec) -> Tuple:
    """Normalize one precompile spec to an args tuple."""
    if isinstance(spec, tuple):
        return spec
    if isinstance(spec, list):
        return tuple(spec)
    return (spec,)


def precompile(fn: Callable, specs: Union[Sequence, Any], *,
               tag: Optional[str] = None, plan: Optional[str] = None,
               mesh=None, static_argnums=(),
               donate_argnums=()) -> List[_cache.CompileResult]:
    """AOT-compile ``fn`` for every abstract-args spec in ``specs``.

    ``specs`` is a sequence of argument tuples (each element a
    ``jax.ShapeDtypeStruct`` — attach ``sharding=NamedSharding(...)`` for
    sharded programs — or a concrete array to borrow shapes from); a
    single tuple is accepted for the one-bucket case. Returns one
    :class:`~horovod_tpu.compile.cache.CompileResult` per spec, in
    order; ``.compiled`` is the executable to call. Compiles are
    deduplicated and persisted through the executable cache, so a warm
    pool on a restarted worker loads from disk instead of compiling.

    ``fn`` may already be a ``jax.jit`` wrapper (used as-is); otherwise
    it is jitted here with ``static_argnums``/``donate_argnums``.
    """
    import jax

    if isinstance(specs, tuple):
        spec_list = [specs]
    else:
        spec_list = [_as_spec_tuple(s) for s in specs]
    jitted = fn if hasattr(fn, "lower") else jax.jit(
        fn, static_argnums=static_argnums,
        donate_argnums=donate_argnums)
    label = tag or getattr(fn, "__name__", None) or "precompile"
    out: List[_cache.CompileResult] = []
    for i, spec in enumerate(spec_list):
        out.append(_cache.get_or_compile(
            label, lambda spec=spec: jitted.lower(*spec),
            plan=plan, mesh=mesh, shapes=spec, extra=f"bucket{i}"))
    return out
