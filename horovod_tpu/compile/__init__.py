"""horovod_tpu.compile — the compile-once runtime (docs/compile.md).

Two layers: JAX's persistent compilation cache armed from ``hvd.init``
(:func:`arm_persistent_cache`), and the framework-level executable
registry (:func:`get_or_compile`) whose serialized-executable entries
let warm reruns, autotune replays, and restarted elastic workers skip
lowering + compile entirely. :func:`precompile` is the public AOT
warm-pool entry point (``hvd.precompile``).
"""

from .cache import (CompileResult, arm_persistent_cache, cache_dir,
                    clear_memory, compile_count, enabled, executable_key,
                    get_or_compile, reset_stats, stats)
from .aot import precompile

__all__ = [
    "CompileResult",
    "arm_persistent_cache",
    "cache_dir",
    "clear_memory",
    "compile_count",
    "enabled",
    "executable_key",
    "get_or_compile",
    "precompile",
    "reset_stats",
    "stats",
]
