"""Small NumPy Gaussian process for the knob autotuner.

Reference: ``cc/src/gp.cc`` (itself mirroring
``horovod/common/optim/gaussian_process.cc``): RBF kernel, Cholesky
fit, triangular-solve predict, and closed-form expected improvement.
The autotuner's design spaces are tiny (≤ ~20 samples in ≤ 3 dims), so
a dependency-free dense implementation is the right size — NumPy's
Cholesky replaces the reference's Eigen.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import numpy as np


class GaussianProcess:
    """GP regression with an RBF kernel and observation noise.

    ``noise`` is the ``HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE`` knob
    (reference default 0.8 — deliberately large: step-time scores on a
    busy host are noisy, and a stiff prior keeps one lucky window from
    dominating the search).
    """

    def __init__(self, dims: int, length_scale: float = 0.3,
                 noise: float = 0.8) -> None:
        if dims < 1:
            raise ValueError("dims must be >= 1")
        self.dims = dims
        self.length_scale = float(length_scale)
        self.noise = float(noise)
        self._x: Optional[np.ndarray] = None
        self._l: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None

    def kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        """RBF: k(a, b) = exp(-|a-b|^2 / (2 l^2)), rows x rows."""
        a = np.atleast_2d(np.asarray(a, dtype=np.float64))
        b = np.atleast_2d(np.asarray(b, dtype=np.float64))
        d2 = np.sum((a[:, None, :] - b[None, :, :]) ** 2, axis=-1)
        return np.exp(-d2 / (2.0 * self.length_scale ** 2))

    @property
    def fitted(self) -> bool:
        return self._alpha is not None

    def fit(self, x, y) -> bool:
        """Fit on rows ``x`` and targets ``y``. Returns False (and stays
        unfitted) when K + noise^2 I is not positive definite — the
        reference's Fit() bool contract (gp.cc:17-57)."""
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        y = np.asarray(y, dtype=np.float64).reshape(-1)
        if x.shape[0] != y.shape[0] or x.shape[1] != self.dims:
            raise ValueError(
                f"fit expects x [n, {self.dims}] and matching y, got "
                f"{x.shape} / {y.shape}")
        k = self.kernel(x, x)
        k[np.diag_indices_from(k)] += self.noise ** 2
        try:
            l = np.linalg.cholesky(k)
        except np.linalg.LinAlgError:
            return False
        self._x = x
        self._l = l
        # alpha = K^-1 y via the two triangular solves (gp.cc:43-55).
        z = _solve_lower(l, y)
        self._alpha = _solve_upper(l.T, z)
        return True

    def predict(self, x) -> Tuple[float, float]:
        """Posterior (mean, stddev) at a single point ``x``."""
        if not self.fitted:
            raise RuntimeError("predict() before a successful fit()")
        x = np.asarray(x, dtype=np.float64).reshape(1, self.dims)
        kstar = self.kernel(x, self._x)[0]
        mean = float(kstar @ self._alpha)
        # v = L^-1 k*; var = k(x,x) - v.v  (gp.cc:66-76)
        v = _solve_lower(self._l, kstar)
        var = 1.0 - float(v @ v)  # k(x, x) = 1 for RBF
        return mean, math.sqrt(var) if var > 0.0 else 0.0

    def expected_improvement(self, x, best_y: float,
                             xi: float = 0.0) -> float:
        """EI of ``x`` over the incumbent ``best_y`` (gp.cc:79-89)."""
        mu, sigma = self.predict(x)
        if sigma <= 1e-12:
            return 0.0
        imp = mu - best_y - xi
        z = imp / sigma
        cdf = 0.5 * math.erfc(-z / math.sqrt(2.0))
        pdf = math.exp(-0.5 * z * z) / math.sqrt(2.0 * math.pi)
        return imp * cdf + sigma * pdf

    def predict_batch(self, xs) -> Tuple[np.ndarray, np.ndarray]:
        """Posterior (means, stddevs) at ``xs [m, dims]`` in one shot —
        the EI argmax over the proposal candidates runs O(m) python
        triangular solves otherwise (m is 1000 per proposal round)."""
        if not self.fitted:
            raise RuntimeError("predict_batch() before a successful fit()")
        xs = np.atleast_2d(np.asarray(xs, dtype=np.float64))
        kstar = self.kernel(xs, self._x)            # [m, n]
        means = kstar @ self._alpha
        # V = L^-1 K*^T, column per candidate; var = 1 - ||v||^2.
        v = np.linalg.solve(self._l, kstar.T)       # [n, m]
        var = 1.0 - np.sum(v * v, axis=0)
        return means, np.sqrt(np.maximum(var, 0.0))

    def expected_improvement_batch(self, xs, best_y: float,
                                   xi: float = 0.0) -> np.ndarray:
        """Vectorized :meth:`expected_improvement` over rows of ``xs``."""
        mu, sigma = self.predict_batch(xs)
        imp = mu - best_y - xi
        with np.errstate(divide="ignore", invalid="ignore"):
            z = np.where(sigma > 1e-12, imp / np.maximum(sigma, 1e-300),
                         0.0)
        cdf = 0.5 * np.array([math.erfc(-zz / math.sqrt(2.0))
                              for zz in z])
        pdf = np.exp(-0.5 * z * z) / math.sqrt(2.0 * math.pi)
        ei = imp * cdf + sigma * pdf
        return np.where(sigma > 1e-12, ei, 0.0)


def _solve_lower(l: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Forward substitution L z = b (L lower triangular)."""
    n = b.shape[0]
    z = np.zeros(n)
    for i in range(n):
        z[i] = (b[i] - l[i, :i] @ z[:i]) / l[i, i]
    return z


def _solve_upper(u: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Back substitution U z = b (U upper triangular)."""
    n = b.shape[0]
    z = np.zeros(n)
    for i in range(n - 1, -1, -1):
        z[i] = (b[i] - u[i, i + 1:] @ z[i + 1:]) / u[i, i]
    return z
