"""The tuning-session driver loop: recompile-per-trial + warm-start cache.

Reference: the coordinator's per-cycle ``parameter_manager.Update`` hook
(operations.cc:614-621) — there, new knob values apply between cycles at
zero cost. On the compiled path every knob is baked into the traced
program (bucket plans are trace-time, ops/fusion.py), so a trial is a
**recompile**: :func:`autotune_session` asks the caller to rebuild its
step for each :class:`TunedParams` proposal, times a scoring window of
real steps, and feeds wall-clock step rate to the
:class:`~.parameter_manager.ParameterManager`.

Recompiles dominate session cost, so the frozen winner is persisted to
the shared autotune cache (``HOROVOD_AUTOTUNE_CACHE``, one JSON file with
the Pallas block-size entries of ops/kernel_autotune.py) keyed on
(model-tree-hash, mesh shape, world size): a rerun of the same job skips
every trial and compiles once, straight at the winner.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import threading
import time
from typing import Callable, List, Optional, Tuple

from ..common import basics
from .parameter_manager import ParameterManager, TunedParams

log = logging.getLogger("horovod_tpu.autotune")

# Cache-entry schema version; bump when TunedParams gains/changes knobs.
# v2: + zero_sharding (ZeRO-1 sharded optimizer).
# v3: + overlap / num_comm_streams (overlapped gradient reduction).
# v4: zero_sharding → zero_stage {0,1,2} (ZeRO-2/3; from_dict still
#     reads pre-v4 entries, but the key's version gates real reuse).
# v5: + the canonical wire-plan encoding (horovod_tpu.plan encode_tuned:
#     leg order | per-hop dtype | stream placement) stored alongside the
#     knobs — the GP now searches plan space (docs/wire-plan.md);
#     from_dict/load stay tolerant of v3/v4 entries.
# v6: + the fused Pallas kernel backend knob (docs/fused-kernels.md) —
#     the plan encoding gains the trailing `|pl` segment and TunedParams
#     the `fused` field; from_dict/load stay tolerant of v5 entries
#     (fused defaults False, the exact pre-v6 wire).
# v7: cost-model-driven warm start (docs/cost-model.md) — the cache key
#     carries the full geometry fingerprint (mesh shape x world x device
#     kind, basics.mesh_geometry: a winner tuned on one chip kind never
#     warm-starts another) and entries record the analytic predicted_ms
#     of the frozen winner beside its measured score, so drift between
#     the cost model and reality is auditable from the cache alone.
#     from_dict/load stay tolerant of v6/v5 entries (the params schema
#     is unchanged; the version segment in the key gates real reuse).
# v8: pipeline parallelism (docs/pipeline.md) — TunedParams gains the
#     pp_microbatches/pp_interleave pair (tune_pp-gated; the plan
#     encoding's trailing `|ppM/V` segment), and pipeline meshes carry
#     a `ppS` marker in the geometry fingerprint so a winner tuned at
#     one stage count never warm-starts another. from_dict/load stay
#     tolerant of v7/v6 entries (pp fields default to the dead-knob
#     0 / 1 values — the exact pre-v8 step).
# v9: expert-parallel MoE (docs/moe.md) — TunedParams gains the
#     moe_capacity_factor/moe_quantized pair (tune_moe-gated; the plan
#     encoding's trailing `|moeC/q8|fp` segment), and expert-parallel
#     meshes carry an `epE` marker in the geometry fingerprint so a
#     winner tuned at one expert-group count never warm-starts another.
#     from_dict/load stay tolerant of v8/v7 entries (moe fields default
#     to the dead-knob 0.0 / False values — the exact pre-v9 step).
# v10: disaggregated serving (docs/serving.md) — TunedParams gains the
#     spec_draft_k/kv_migrate_quantized pair (tune_serve-gated; the plan
#     encoding's trailing `|svK/q8|fp` segment). from_dict/load stay
#     tolerant of v9/v8 entries (serve fields default to the dead-knob
#     0 / False values — the exact pre-v10 step).
# v11: zero-bubble pipelines (docs/pipeline.md) — TunedParams gains the
#     pp_schedule family knob (tune_pp-gated; the plan encoding's
#     optional `|zb1` segment riding the `|ppM/V` group). from_dict/load
#     stay tolerant of v10/v9 entries (pp_schedule defaults to the
#     dead-knob "interleaved_1f1b" value — the exact pre-v11 step).
# v12: compile-once runtime (docs/compile.md) — the trial CSV gains the
#     per-trial `compile_ms`/`compile_cache_hit` pair (the previously
#     untimed build+absorb step, now bracketed by AUTOTUNE:COMPILE
#     spans and overlapped with the prior trial's measurement window
#     when the next setting is knowable). The TunedParams schema is
#     unchanged; read_log stays tolerant of v11/v10 logs lacking the
#     new columns (compile_ms defaults 0.0, compile_cache_hit False).
_CACHE_VERSION = 12

# Process-lifetime session counter — hvd.shutdown() warns when
# HOROVOD_AUTOTUNE=1 never reached a session (the knob is otherwise a
# silent no-op on the compiled path; see docs/autotune.md).
_sessions_run = [0]


def sessions_run() -> int:
    """How many tuning sessions (including cache hits) this process ran."""
    return _sessions_run[0]


@dataclasses.dataclass(frozen=True)
class AutotuneResult:
    """What a tuning session produced.

    ``params`` is the frozen winner (feed it back as the
    ``tuned_params=`` override of :class:`horovod_tpu.DistributedOptimizer`
    / :func:`horovod_tpu.allreduce_pytree`). ``history`` is the scored
    trial list in order; empty on a warm-start ``cache_hit``.
    """

    params: TunedParams
    history: Tuple[Tuple[TunedParams, float], ...] = ()
    cache_hit: bool = False
    best_score: Optional[float] = None
    # Cost-model warm start (docs/cost-model.md): how many priced seeds
    # the session walked before the GP proposed, and the ranked
    # shortlist rows (plan encoding + predicted_ms) they came from.
    warm_start: int = 0
    shortlist: Tuple[dict, ...] = ()

    @property
    def samples(self) -> int:
        return len(self.history)


def cache_key_for(tree, mesh=None) -> str:
    """Warm-start cache key: (model-tree-hash, geometry fingerprint).

    ``tree`` is any pytree whose *structure and leaf shapes/dtypes*
    identify the workload (pass the parameter tree); values never enter
    the hash, so a checkpoint restore keys the same as a fresh init. The
    bucket plan is a pure function of leaf order/shape/dtype
    (ops/fusion.py plan_buckets is deterministic), which is exactly what
    makes this key sound. The geometry half is
    :func:`~horovod_tpu.common.basics.mesh_geometry` — mesh shape x
    world x device kind, shared with the link-calibration store, so a
    winner tuned on one chip kind never warm-starts another.
    """
    import jax

    if isinstance(tree, str):
        sig = tree
    else:
        leaves, treedef = jax.tree.flatten(tree)
        parts = [str(treedef)]
        for leaf in leaves:
            parts.append(f"{jax.numpy.shape(leaf)}:"
                         f"{jax.numpy.asarray(leaf).dtype}")
        sig = hashlib.md5("|".join(parts).encode()).hexdigest()
    geo = basics.mesh_geometry(mesh=mesh)
    return f"collective_tune|{sig}|{geo}|v{_CACHE_VERSION}"


def load_cached_params(key: str) -> Optional[TunedParams]:
    """The frozen winner cached under ``key``, or None."""
    from ..ops import kernel_autotune

    entry = kernel_autotune.cache_lookup(key)
    if not isinstance(entry, dict) or "params" not in entry:
        return None
    try:
        return TunedParams.from_dict(entry["params"])
    except (KeyError, TypeError, ValueError):
        return None  # stale/foreign entry: tune fresh rather than crash


def _store_cached_params(key: str, params: TunedParams, *,
                         score: float, samples: int,
                         quantized: bool = False, pp: bool = False,
                         moe: bool = False, serve: bool = False,
                         predicted_ms: Optional[float] = None) -> None:
    from ..plan import planner as _wire_planner
    from ..ops import kernel_autotune

    entry = {
        "params": params.as_dict(),
        "plan": _wire_planner.encode_tuned(params, quantized=quantized,
                                           pp=pp, moe=moe, serve=serve),
        "score_steps_per_sec": score,
        "samples": samples,
        "geometry": basics.mesh_geometry(),
    }
    if predicted_ms is not None:
        # v7: the analytic prediction for the winner, stored beside the
        # measured score so cost-model drift is auditable from the cache
        # alone (docs/cost-model.md).
        entry["predicted_ms"] = round(float(predicted_ms), 6)
    kernel_autotune.cache_store(key, entry)


def _priced_seeds(payload_bytes: float, k: int, *, initial: TunedParams,
                  quantized: bool, tune_hierarchical: bool,
                  tune_zero: bool, tune_overlap: bool,
                  tune_fused: bool, tune_pp: bool = False,
                  pp_stages: int = 0, pp_max_interleave: int = 1,
                  tune_moe: bool = False, moe_experts: int = 0):
    """Top-``k`` cost-model-priced candidates for this session's search
    space (docs/cost-model.md): the planner enumerates every legal plan
    the session's gates allow, prices them with the calibrated (or
    static) link model, and the ranked head seeds the GP."""
    from ..plan import calibrate as _calibrate
    from ..plan import planner as _wire_planner

    model = _calibrate.get_cost_model()
    return _wire_planner.shortlist(
        payload_bytes, quantized=quantized, k=k,
        tune_hierarchical=tune_hierarchical, tune_zero=tune_zero,
        tune_overlap=tune_overlap, tune_fused=tune_fused,
        tune_pp=tune_pp, pp_stages=pp_stages,
        pp_max_interleave=pp_max_interleave,
        tune_moe=tune_moe, moe_experts=moe_experts,
        initial=initial, model=model)


def _timeline_instant(name: str, args: dict) -> None:
    tl = basics._state.timeline if basics.is_initialized() else None
    if tl is not None:
        tl.instant(name, tid="autotune", args=args)


def _timeline_span(name: str, ph: str, args: Optional[dict] = None) -> None:
    # Compile spans ride their own tid: a background prefetch build can
    # open while the main autotune tid is mid-window, and per-tid B/E
    # balance (span_audit) must hold on both. Builds themselves are
    # serialized (at most one prefetch thread, joined before any
    # foreground build), so this tid never nests concurrent spans.
    tl = basics._state.timeline if basics.is_initialized() else None
    if tl is not None:
        tl.emit(name, ph, tid="autotune.compile", args=args)


def _build_trial(make_step, tuned: TunedParams, box: dict,
                 *, background: bool) -> None:
    """Build (and absorb the compile of) one trial's step into ``box``.

    ``box`` gains ``step`` (the callable to time), ``compile_ms`` and
    ``cache_hit`` (executable-cache miss delta == 0 across the build) on
    success, ``error`` on failure. Runs either inline or as the
    compile-ahead prefetch thread overlapping the prior trial's
    measurement window (docs/compile.md); AUTOTUNE:COMPILE brackets the
    build either way — the step that was untimed before v12."""
    from .. import compile as _xc

    s0 = _xc.stats()
    _timeline_span("AUTOTUNE:COMPILE", "B",
                   {"background": background, **tuned.as_dict()})
    try:
        t0 = time.perf_counter()
        step = make_step(tuned)
        if hasattr(step, "lower"):
            # An un-called jit step: drive the AOT path so the XLA
            # compile genuinely happens here (on the prefetch thread,
            # off the measured window) instead of at first dispatch.
            step = step.lower().compile()
        box["step"] = step
        box["compile_ms"] = (time.perf_counter() - t0) * 1e3
        s1 = _xc.stats()
        box["cache_hit"] = (s1["misses"] == s0["misses"]
                            and s1["hits"] > s0["hits"])
    except Exception as e:
        box["error"] = e
    finally:
        _timeline_span("AUTOTUNE:COMPILE", "E",
                       {"background": background,
                        "compile_ms": round(box.get("compile_ms", 0.0), 3)})


def autotune_session(
    make_step: Callable[[TunedParams], Callable[[], object]],
    *,
    cache_key=None,
    initial: Optional[TunedParams] = None,
    enabled: Optional[bool] = None,
    tune_quant_block: Optional[bool] = None,
    tune_hierarchical: bool = True,
    tune_zero: bool = False,
    tune_overlap: bool = False,
    tune_fused: bool = False,
    tune_pp: bool = False,
    pp_stages: int = 0,
    pp_max_interleave: int = 1,
    tune_moe: bool = False,
    moe_experts: int = 0,
    tune_serve: bool = False,
    warmup_samples: Optional[int] = None,
    steps_per_sample: Optional[int] = None,
    max_samples: Optional[int] = None,
    gp_noise: Optional[float] = None,
    log_path: Optional[str] = None,
    use_cache: bool = True,
    seed: int = 0x9E3779B97F4A7C15,
    warm_start=None,
) -> AutotuneResult:
    """Run an online tuning session and return the frozen winner.

    ``make_step(tuned)`` must build (and implicitly compile) the training
    step with the :class:`TunedParams` override applied — thread ``tuned``
    into ``DistributedOptimizer(tuned_params=...)`` or
    ``allreduce_pytree(tuned_params=...)`` — and return a zero-argument
    callable that advances ONE real training step (owning its state in a
    closure) and returns that step's outputs, which the driver blocks on
    for wall-clock timing. It is called once per trial; each call is a
    retrace.

    Knob defaults come from :func:`horovod_tpu.init`'s Config
    (``HOROVOD_AUTOTUNE_WARMUP_SAMPLES`` / ``_STEPS_PER_SAMPLE`` /
    ``_BAYES_OPT_MAX_SAMPLES`` / ``_GAUSSIAN_PROCESS_NOISE`` /
    ``_LOG``); explicit arguments override. ``enabled`` defaults to the
    ``HOROVOD_AUTOTUNE`` knob: with it off the session is a no-op that
    returns the initial (hand-set) parameters untouched, keeping the
    default path bit-identical.

    ``tune_zero`` adds the ZeRO-sharding flag to the search space; leave
    it False (the default) unless ``make_step`` actually threads
    ``tuned.zero_sharding`` through (``DistributedOptimizer(tuned_params=
    tuned)`` + ``hvd.value_and_grad(..., tuned_params=tuned)`` do) — the
    knob restructures the optimizer state, so a step built without it
    would silently score a config it never ran. ``tune_overlap`` gates
    the ``overlap`` + ``num_comm_streams`` pair the same way (overlap ×
    ``backward_passes_per_step`` restructures the accumulation state,
    docs/overlap.md). ``tune_fused`` adds the fused Pallas kernel
    backend (docs/fused-kernels.md) to the search — only meaningful on
    a quantized wire, where the int8 legs have a kernel lowering; on an
    unquantized wire canonicalization collapses the dimension to one
    trial. ``tune_pp`` (with ``pp_stages`` = the mesh's stage count and
    ``pp_max_interleave`` = the deepest virtual-stage split the model's
    layer count allows) adds the pipeline schedule pair —
    ``pp_microbatches`` (pow2, snapped to a stage-count multiple) and
    ``pp_interleave`` (pow2) — gated exactly like zero/overlap: both
    restructure the traced schedule, so only a step builder that
    rebuilds at the proposed values may search them (docs/pipeline.md).
    ``tune_moe`` (with ``moe_experts`` = the mesh's expert-group count)
    adds the MoE routing pair — ``moe_capacity_factor``
    (quarter-snapped 1.0–2.0) and ``moe_quantized`` (the int8 a2a
    wire) — under the same gate: capacity is trace-time dispatch-buffer
    shape, so only a step builder that rebuilds at the proposed values
    may search it (docs/moe.md). ``tune_serve`` adds the
    disaggregated-serving pair — ``spec_draft_k`` (the speculative
    draft window, 0–4) and ``kv_migrate_quantized`` (the int8+EF
    prefill→decode KV wire) — under the same gate: the window is
    trace-time decode geometry, so only a serving session whose
    ``make_step`` rebuilds its engines at the proposed values may
    search it (docs/serving.md).

    ``cache_key`` (a pytree — pass the parameter tree — or a string)
    activates the warm-start cache: a prior frozen winner for the same
    (model, geometry) returns immediately with ``cache_hit=True`` and
    zero trials; a fresh session persists its winner on convergence.
    ``use_cache=False`` forces re-tuning (the winner still overwrites the
    cache entry).

    ``warm_start`` (default: the ``HOROVOD_AUTOTUNE_WARM_START`` config,
    0 = off) seeds the GP with the cost model's ranked shortlist
    (docs/cost-model.md): an integer K derives the top-K priced
    candidates for this session's search space (the gradient payload
    size comes from the ``cache_key`` pytree, so pass the parameter
    tree), or pass an explicit sequence of :class:`TunedParams`. Seeds
    are scored FIRST, in predicted-ms order, before the GP proposes; a
    warm-started session also shrinks its trial budget to
    ``len(seeds) + 4`` windows unless ``max_samples`` is set explicitly
    — the analytic shortlist replaces the cold exploration phase.
    """
    import jax

    cfg = basics.config() if basics.is_initialized() else None
    if enabled is None:
        enabled = bool(cfg.autotune) if cfg is not None else False
    if initial is None:
        initial = (TunedParams.from_config(cfg) if cfg is not None
                   else TunedParams())
    if not enabled:
        log.info("autotune_session: HOROVOD_AUTOTUNE is off — returning "
                 "the configured parameters untuned")
        return AutotuneResult(params=initial)
    _sessions_run[0] += 1
    if tune_quant_block is None:
        tune_quant_block = bool(cfg.quantized_allreduce) if cfg else False
    if warmup_samples is None:
        warmup_samples = cfg.autotune_warmup_samples if cfg else 3
    if steps_per_sample is None:
        steps_per_sample = cfg.autotune_steps_per_sample if cfg else 10
    explicit_max = max_samples is not None
    if max_samples is None:
        max_samples = cfg.autotune_bayes_opt_max_samples if cfg else 20
    if gp_noise is None:
        gp_noise = cfg.autotune_gaussian_process_noise if cfg else 0.8
    if log_path is None:
        log_path = cfg.autotune_log if cfg else None
    if warm_start is None:
        warm_start = getattr(cfg, "autotune_warm_start", 0) if cfg else 0

    key = cache_key_for(cache_key) if cache_key is not None else None
    if key is not None and use_cache:
        cached = load_cached_params(key)
        if cached is not None:
            log.warning(
                "horovod_tpu autotune: warm-start cache hit (%s) — "
                "skipping trials, compiling straight at fusion_threshold="
                "%d quant_block=%d hierarchical=%s", key,
                cached.fusion_threshold_bytes, cached.quant_block,
                cached.hierarchical_allreduce)
            _timeline_instant("AUTOTUNE:CACHE_HIT",
                              {"key": key, **cached.as_dict()})
            return AutotuneResult(params=cached, cache_hit=True)

    # Gradient payload size (for pricing) from the cache_key pytree.
    payload_bytes = None
    if cache_key is not None and not isinstance(cache_key, str):
        try:
            payload_bytes = float(sum(
                jax.numpy.asarray(l).nbytes
                for l in jax.tree.leaves(cache_key)))
        except Exception:
            payload_bytes = None

    seeds = []
    shortlist_rows = ()
    if isinstance(warm_start, (list, tuple)):
        seeds = list(warm_start)
    elif warm_start and int(warm_start) > 0:
        if payload_bytes:
            ranked = _priced_seeds(
                payload_bytes, int(warm_start), initial=initial,
                quantized=bool(tune_quant_block),
                tune_hierarchical=tune_hierarchical,
                tune_zero=tune_zero, tune_overlap=tune_overlap,
                tune_fused=tune_fused, tune_pp=tune_pp,
                pp_stages=pp_stages,
                pp_max_interleave=pp_max_interleave,
                tune_moe=tune_moe, moe_experts=moe_experts)
            seeds = [pp.params for pp in ranked]
            shortlist_rows = tuple(pp.as_dict() for pp in ranked)
            if ranked:
                log.warning(
                    "horovod_tpu autotune: cost-model warm start — %d "
                    "priced seeds for a %.1f MB payload, top %s @ "
                    "%.4f predicted ms", len(ranked),
                    payload_bytes / 1e6, ranked[0].plan.encode(),
                    ranked[0].predicted_ms)
        else:
            log.warning(
                "horovod_tpu autotune: warm_start=%s requested but "
                "cache_key is not a pytree (no payload size to price) "
                "— falling back to the cold search", warm_start)
    pm = ParameterManager(
        initial,
        tune_quant_block=tune_quant_block,
        tune_hierarchical=tune_hierarchical,
        tune_zero=tune_zero,
        tune_overlap=tune_overlap,
        tune_fused=tune_fused,
        tune_pp=tune_pp,
        pp_stages=pp_stages,
        pp_max_interleave=pp_max_interleave,
        tune_moe=tune_moe,
        moe_experts=moe_experts,
        tune_serve=tune_serve,
        warmup_samples=warmup_samples,
        steps_per_sample=steps_per_sample,
        max_samples=max_samples,
        gp_noise=gp_noise,
        log_path=log_path,
        seed=seed,
        seeds=seeds,
    )
    if pm.seeded and not explicit_max:
        # The priced shortlist replaces the cold exploration phase: the
        # budget is the (deduplicated) seeds plus a handful of GP
        # refinements.
        pm.max_samples = min(pm.max_samples, pm.seeded + 4)
        max_samples = pm.max_samples
    log.warning(
        "horovod_tpu autotune: tuning session started (%d warmup + up to "
        "%d scored windows of %d steps; each new configuration is a "
        "recompile%s)", warmup_samples, max_samples, steps_per_sample,
        f"; {pm.seeded} cost-model seeds" if pm.seeded else "")
    _timeline_instant("AUTOTUNE:SESSION_START", {
        "warmup_samples": warmup_samples, "max_samples": max_samples,
        "steps_per_sample": steps_per_sample,
        "warm_start_seeds": pm.seeded})

    built: Optional[Tuple[TunedParams, Callable[[], object]]] = None
    # Compile-ahead prefetch (docs/compile.md): while trial k's window
    # is being measured, trial k+1's step lowers/compiles on a host
    # thread — but only when the NEXT setting is knowable without the
    # pending score (warmup repeats + the cost-model seed queue;
    # ParameterManager.peek_next). GP-phase proposals depend on the
    # score, so those builds stay in the foreground.
    prefetch: Optional[Tuple[TunedParams, threading.Thread, dict]] = None
    while not pm.done:
        tuned = pm.current
        warmup = pm.warming_up
        compile_ms = 0.0
        cache_hit = False
        try:
            if built is None or built[0] != tuned:
                box: dict = {}
                if prefetch is not None:
                    p_tuned, p_thread, p_box = prefetch
                    prefetch = None
                    p_thread.join()
                    if p_tuned == tuned and "step" in p_box:
                        box = p_box
                if "step" not in box:
                    box = {}
                    _build_trial(make_step, tuned, box, background=False)
                    if "error" in box:
                        raise box["error"]
                compile_ms = box.get("compile_ms", 0.0)
                cache_hit = bool(box.get("cache_hit", False))
                built = (tuned, box["step"])
                # One untimed step absorbs this trial's first dispatch
                # so the scored window measures steady state.
                jax.block_until_ready(built[1]())
                log.info("autotune trial build %s: %.0fms compile%s",
                         tuned.as_dict(), compile_ms,
                         " (cache hit)" if cache_hit else "")
            step = built[1]
            nxt = pm.peek_next()
            if nxt is not None and nxt != tuned and prefetch is None:
                p_box: dict = {}
                p_thread = threading.Thread(
                    target=_build_trial, args=(make_step, nxt, p_box),
                    kwargs={"background": True}, daemon=True,
                    name="autotune-compile-ahead")
                p_thread.start()
                prefetch = (nxt, p_thread, p_box)
            t0 = time.perf_counter()
            for _ in range(pm.steps_per_sample):
                out = step()
            jax.block_until_ready(out)
            dt = time.perf_counter() - t0
            score = pm.steps_per_sample / dt if dt > 0 else 0.0
        except Exception as e:
            # A candidate that cannot build or run (compile failure, OOM
            # at a huge threshold) is a terrible score, not a session
            # abort — the GP learns to avoid the region (the same skip
            # discipline as the kernel autotuner's failing candidates).
            built = None
            score = 0.0
            log.warning("autotune trial %s failed (%s: %s); scoring 0",
                        tuned.as_dict(), type(e).__name__, str(e)[:200])
        pm.record_sample(score, compile_ms=compile_ms,
                         compile_cache_hit=cache_hit)
        _timeline_instant("AUTOTUNE:SAMPLE", {
            "warmup": warmup, "score_steps_per_sec": round(score, 4),
            "compile_ms": round(compile_ms, 3),
            "compile_cache_hit": cache_hit,
            **tuned.as_dict()})
        if not warmup:
            log.info("autotune sample %d/%d: %s -> %.3f steps/sec",
                     pm.samples_done, max_samples, tuned.as_dict(), score)

    if prefetch is not None:
        # A frozen session can leave one compile-ahead build in flight;
        # join it so its AUTOTUNE:COMPILE span closes before the
        # timeline can be dumped (span_audit strict mode).
        prefetch[1].join()
    best = pm.best
    _timeline_instant("AUTOTUNE:CONVERGED", {
        "samples": pm.samples_done,
        "score_steps_per_sec": round(pm.best_score, 4),
        **best.as_dict()})
    log.warning(
        "horovod_tpu autotune: converged after %d samples — "
        "fusion_threshold=%d quant_block=%d hierarchical=%s "
        "(%.3f steps/sec)", pm.samples_done, best.fusion_threshold_bytes,
        best.quant_block, best.hierarchical_allreduce, pm.best_score)
    if key is not None:
        predicted_ms = None
        if payload_bytes:
            try:
                from ..plan import calibrate as _calibrate
                from ..plan import cost as _cost
                from ..plan import planner as _wire_planner

                sp = _wire_planner.describe_plan(
                    tuned_params=best, quantized=bool(tune_quant_block),
                    quantized_pod=False,
                    pp_stages=pp_stages if tune_pp else None,
                    moe_experts=moe_experts if tune_moe else 0,
                    moe_quantized=(best.moe_quantized if tune_moe
                                   else None))
                predicted_ms = _cost.price_step(
                    sp, payload_bytes,
                    model=_calibrate.get_cost_model()).predicted_ms
            except Exception:  # pricing must never fail the session
                predicted_ms = None
        _store_cached_params(key, best, score=pm.best_score,
                             samples=pm.samples_done,
                             quantized=bool(tune_quant_block),
                             pp=tune_pp, moe=tune_moe, serve=tune_serve,
                             predicted_ms=predicted_ms)
    return AutotuneResult(params=best, history=tuple(pm.history),
                          best_score=pm.best_score,
                          warm_start=pm.seeded,
                          shortlist=shortlist_rows)
