"""Online Bayesian autotuner for the collective tunables.

Reference: ``horovod/common/parameter_manager.{h,cc}`` + the Gaussian
process / Bayesian optimization under ``horovod/common/optim/`` — the
coordinator scores each sample window and proposes the next knob setting
by expected improvement, then freezes on the best configuration (this
repo's native eager counterpart is ``cc/src/parameter_manager.cc`` +
``gp.cc``).

TPU-native redesign
-------------------
On the compiled path the reference's runtime knob flips do not exist:
bucket plans and collective decompositions are fixed at **trace time**
(ops/fusion.py docstring), so changing a tunable means recompiling the
step. The autotuner therefore runs as an explicit *tuning session*
(:func:`horovod_tpu.autotune_session`): each trial builds the step with a
:class:`TunedParams` override, times a scoring window of real training
steps, feeds the wall-clock score to the same GP/EI proposal loop as the
reference, and freezes on the winner. Compile cost is amortized by a
warm-start cache keyed on (model-tree-hash, mesh shape, world size) —
a rerun of the same job skips straight to the frozen winner.

Tunables (the knobs that matter on TPU, ISSUE 3):

* ``fusion_threshold_bytes`` — bucket size, 1–256 MiB, log-space;
* ``quant_block`` — int8 scale-block elements, 64–1024, log-space,
  searched only when the quantized wire is on;
* ``hierarchical_allreduce`` — explicit ICI/DCN decomposition vs the
  flat psum XLA decomposes itself.

Cost-model warm start (docs/cost-model.md): instead of cold-searching
the 7-dim space, ``autotune_session(warm_start=K)`` asks the analytic
planner (:func:`horovod_tpu.plan.shortlist`) to enumerate and PRICE the
legal plan space with the calibrated per-link (bandwidth, latency,
quant-rate) model and walks the top-K predicted plans first — the GP
then refines an informed neighborhood in a handful of trials.
"""

from .gp import GaussianProcess  # noqa: F401
from .parameter_manager import (  # noqa: F401
    ParameterManager,
    TunedParams,
    read_log,
)
from .driver import (  # noqa: F401
    AutotuneResult,
    autotune_session,
    cache_key_for,
    load_cached_params,
    sessions_run,
)
