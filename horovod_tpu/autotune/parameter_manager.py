"""Trial state machine for the collective-knob autotuner.

Reference: ``cc/src/parameter_manager.cc`` (mirroring
``horovod/common/parameter_manager.cc``): warmup windows are discarded,
every later window scores the current knob setting, the next setting
comes from expected improvement over a GP fit on the normalized scores,
and after ``max_samples`` scored windows the manager freezes on the best
configuration seen.

The compiled-path differences from the native eager manager:

* knobs are :class:`TunedParams` — fusion threshold (1–256 MiB,
  log-space), ``quant_block`` (64–1024, log-space, power-of-two snapped,
  searched only when the quantized wire is on), the hierarchical
  allreduce flag, and the ``zero_stage`` level (0/1/2 as thirds of the
  unit axis; searched only when the session's step accepts it — it
  restructures the optimizer state, see docs/zero.md; stage 3 is
  excluded from the search because it restructures the TRAINING LOOP —
  the params become shards — which no tuned_params override can do to
  an already-built step). Cycle time and the response cache do not
  exist on the compiled path (the XLA schedule replaces both —
  ops/fusion.py);
* scores are wall-clock **steps/sec** of a real training window (the
  driver times them), not coordinator bytes/sec — on the compiled path
  the collective schedule is inside the step, so step rate is the
  end-to-end objective the knobs exist to move;
* proposals are deduplicated against already-tried configurations:
  log-space snapping makes the space effectively discrete, and repeat
  trials would each cost a recompile.
"""

from __future__ import annotations

import csv
import dataclasses
import logging
import math
import os
from typing import IO, List, Optional, Sequence, Tuple

from ..plan import planner as _wire_planner
from .gp import GaussianProcess

log = logging.getLogger("horovod_tpu.autotune")

# Search bounds, log2-space (ISSUE 3: fusion threshold 1-256 MiB,
# quant_block 64-1024; ISSUE 5: num_comm_streams pow2 1-4).
_MIN_FUSION_LOG = 20.0  # 2^20 = 1 MiB
_MAX_FUSION_LOG = 28.0  # 2^28 = 256 MiB
_MIN_QBLOCK_LOG = 6.0   # 2^6  = 64
_MAX_QBLOCK_LOG = 10.0  # 2^10 = 1024
_MAX_STREAMS_LOG = 2.0  # 2^2  = 4 bucket collectives in flight
# The 6 unit-cube dims now read as a compact PLAN encoding (ISSUE 9,
# docs/wire-plan.md): fusion threshold, per-hop int8 scale block, leg
# order (flat/tree vs the ZeRO rs+ag split via the zero dims), and the
# stream placement (overlap, flight width). Proposals canonicalize
# through horovod_tpu.plan.encode_tuned/decode_tuned, so two knob
# settings that compile to the SAME wire plan (e.g. hierarchical under
# ZeRO, or a stream count with overlap off) collapse to one trial
# instead of costing two recompiles.
# v6 adds the fused-kernel backend dimension (docs/fused-kernels.md):
# dead on an unquantized wire, where canonicalization collapses it.
# v8 adds the pipeline schedule pair (docs/pipeline.md): pp_microbatches
# (pow2, snapped to a multiple of the stage count) and pp_interleave
# (pow2 virtual-stage degree) — both gated by tune_pp and dead (0 / 1)
# when the session's step is not pipelined, where canonicalization
# collapses them to one trial.
# v9 adds the MoE routing pair (docs/moe.md): moe_capacity_factor
# (quarter-snapped 1.0-2.0 dispatch headroom) and moe_quantized (the
# int8 a2a wire) — both gated by tune_moe and dead (0.0 / False) when
# the session's step carries no MoE layer, where canonicalization
# collapses them to one trial.
# v10 adds the disaggregated-serving pair (docs/serving.md):
# spec_draft_k (speculative draft window 0-4; 0 = plain decode) and
# kv_migrate_quantized (the int8+EF prefill→decode KV wire) — both
# gated by tune_serve and dead (0 / False) in a training session,
# where canonicalization collapses them to one trial.
# v11 adds the pipeline schedule family (docs/pipeline.md):
# pp_schedule ("interleaved_1f1b" vs the zero-bubble "zb1" B/W split) —
# gated by tune_pp like the v8 pair and dead ("interleaved_1f1b") when
# the session's step is not pipelined, where canonicalization
# collapses it to one trial.
_DIMS = 14  # fusion, qblock, tree, zero, overlap, streams, fused,
#             ppM, ppV, moeCap, moeQ, svK, svQ, ppZb

_MIN_PPM_LOG = 1.0   # 2^1 = 2 microbatches
_MAX_PPM_LOG = 5.0   # 2^5 = 32 microbatches
_MAX_PPV_LOG = 2.0   # 2^2 = 4 virtual stages per rank

_MIN_MOE_CAP = 1.0   # dispatch capacity factor search box
_MAX_MOE_CAP = 2.0   # (quarter-snapped: 1.0, 1.25, ..., 2.0)

_MAX_SPEC_K = 4      # speculative draft-window search box (0..4)

# CSV schema (reference: parameter_manager.cc:47-50 writes knobs then the
# window score; same layout here with the compiled-path knob set).
# zero_sharding (= zero_stage > 0) stays a column for log compatibility;
# zero_stage carries the actual level. v5 appends the canonical `plan`
# encoding column; v6 the `fused` kernel-backend knob. read_log stays
# tolerant of v3/v4/v5 logs lacking the newer columns.
# v8 appends the pipeline pair; read_log stays tolerant of v3..v7 logs
# lacking the newer columns.
# v9 appends the MoE pair; read_log stays tolerant of v3..v8 logs
# lacking the newer columns.
# v10 appends the serving pair; read_log stays tolerant of v3..v9 logs
# lacking the newer columns.
# v11 appends the pipeline schedule family; read_log stays tolerant of
# v3..v10 logs lacking the newer columns.
# v12 appends the per-trial compile pair (docs/compile.md): compile_ms
# is the trial's build+absorb wall time (overlapped with the prior
# trial's window when compile-ahead prefetch hit), compile_cache_hit
# whether the executable cache served it without an XLA compile.
# read_log stays tolerant of v3..v11 logs lacking the newer columns.
CSV_FIELDS = ("sample", "fusion_threshold_bytes", "quant_block",
              "hierarchical_allreduce", "zero_sharding", "zero_stage",
              "overlap", "num_comm_streams", "fused",
              "pp_microbatches", "pp_interleave",
              "moe_capacity_factor", "moe_quantized",
              "spec_draft_k", "kv_migrate_quantized",
              "pp_schedule",
              "score_steps_per_sec", "plan",
              "compile_ms", "compile_cache_hit")


@dataclasses.dataclass(frozen=True)
class TunedParams:
    """One knob setting to build (or that built) a compiled step — the
    analogue of the Params struct the reference coordinator broadcasts
    (SynchronizeParameters, controller.cc:34-48). Hashable so trial
    dedup and the warm-start cache can key on it."""

    fusion_threshold_bytes: int = 64 * 1024 * 1024
    quant_block: int = 256
    hierarchical_allreduce: bool = False
    zero_stage: int = 0
    overlap: bool = False
    num_comm_streams: int = 1
    fused: bool = False
    # Pipeline schedule pair (docs/pipeline.md): 0 / 1 = "not a
    # pipelined step" — the canonical dead-knob values. pp_schedule
    # picks the table family ("interleaved_1f1b" vs the zero-bubble
    # "zb1" B/W split); "interleaved_1f1b" is also the canonical dead
    # value when pp is off.
    pp_microbatches: int = 0
    pp_interleave: int = 1
    pp_schedule: str = "interleaved_1f1b"
    # MoE routing pair (docs/moe.md): 0.0 / False = "not an MoE step" —
    # the canonical dead-knob values.
    moe_capacity_factor: float = 0.0
    moe_quantized: bool = False
    # Disaggregated-serving pair (docs/serving.md): 0 / False = "not a
    # serving session" — the canonical dead-knob values.
    spec_draft_k: int = 0
    kv_migrate_quantized: bool = False

    @property
    def zero_sharding(self) -> bool:
        """Back-compat boolean view of ``zero_stage`` (the PR-4 knob):
        True when any ZeRO stage is on."""
        return self.zero_stage > 0

    def as_dict(self) -> dict:
        return {
            "fusion_threshold_bytes": int(self.fusion_threshold_bytes),
            "quant_block": int(self.quant_block),
            "hierarchical_allreduce": bool(self.hierarchical_allreduce),
            "zero_sharding": bool(self.zero_sharding),
            "zero_stage": int(self.zero_stage),
            "overlap": bool(self.overlap),
            "num_comm_streams": int(self.num_comm_streams),
            "fused": bool(self.fused),
            "pp_microbatches": int(self.pp_microbatches),
            "pp_interleave": int(self.pp_interleave),
            "pp_schedule": str(self.pp_schedule),
            "moe_capacity_factor": float(self.moe_capacity_factor),
            "moe_quantized": bool(self.moe_quantized),
            "spec_draft_k": int(self.spec_draft_k),
            "kv_migrate_quantized": bool(self.kv_migrate_quantized),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TunedParams":
        # .get: entries cached before the zero/overlap knobs existed stay
        # readable (the cache key's schema version gates real reuse);
        # a pre-v4 boolean zero_sharding maps to stage 2 (the PR-4
        # behavior it named).
        stage = d.get("zero_stage")
        if stage is None:
            stage = 2 if d.get("zero_sharding", False) else 0
        return cls(
            fusion_threshold_bytes=int(d["fusion_threshold_bytes"]),
            quant_block=int(d["quant_block"]),
            hierarchical_allreduce=bool(d["hierarchical_allreduce"]),
            zero_stage=int(stage),
            overlap=bool(d.get("overlap", False)),
            num_comm_streams=int(d.get("num_comm_streams", 1)),
            fused=bool(d.get("fused", False)),
            pp_microbatches=int(d.get("pp_microbatches", 0) or 0),
            pp_interleave=int(d.get("pp_interleave", 1) or 1),
            pp_schedule=str(d.get("pp_schedule", "interleaved_1f1b")
                            or "interleaved_1f1b"),
            moe_capacity_factor=float(
                d.get("moe_capacity_factor", 0.0) or 0.0),
            moe_quantized=bool(d.get("moe_quantized", False)),
            spec_draft_k=int(d.get("spec_draft_k", 0) or 0),
            kv_migrate_quantized=bool(
                d.get("kv_migrate_quantized", False)),
        )

    @classmethod
    def from_config(cls, config) -> "TunedParams":
        """Seed from a :class:`horovod_tpu.common.config.Config` (the
        hand-set env knobs are trial 0, as in the reference where tuning
        starts from the configured values)."""
        stage = getattr(config, "zero_stage", 0)
        if not stage and getattr(config, "zero_sharding", False):
            stage = 2
        return cls(
            fusion_threshold_bytes=config.fusion_threshold_bytes,
            quant_block=config.quant_block,
            hierarchical_allreduce=config.hierarchical_allreduce,
            zero_stage=stage,
            overlap=getattr(config, "overlap", False),
            num_comm_streams=getattr(config, "num_comm_streams", 1),
            fused=getattr(config, "fused_kernels", False),
            pp_microbatches=getattr(config, "pp_microbatches", 0) or 0,
            pp_interleave=getattr(config, "pp_interleave", 1) or 1,
            pp_schedule=str(getattr(config, "pp_schedule",
                                    "interleaved_1f1b")
                            or "interleaved_1f1b"),
            moe_capacity_factor=(
                getattr(config, "moe_capacity_factor", 0.0)
                if getattr(config, "moe_experts", 0) else 0.0),
            moe_quantized=bool(getattr(config, "moe_quantized", False)
                               and getattr(config, "moe_experts", 0)),
            spec_draft_k=getattr(config, "spec_draft_k", 0) or 0,
            kv_migrate_quantized=bool(
                getattr(config, "kv_migrate_quantized", False)),
        )


class _XorShift:
    """xorshift64* — the reference manager's deterministic proposal RNG
    (parameter_manager.cc:106-113); seedable so sessions replay."""

    def __init__(self, seed: int = 0x9E3779B97F4A7C15) -> None:
        self.state = seed & 0xFFFFFFFFFFFFFFFF or 0x9E3779B97F4A7C15

    def next(self) -> float:
        s = self.state
        s ^= (s >> 12) & 0xFFFFFFFFFFFFFFFF
        s = (s ^ (s << 25)) & 0xFFFFFFFFFFFFFFFF
        s ^= s >> 27
        self.state = s
        return ((s * 0x2545F4914F6CDD1D & 0xFFFFFFFFFFFFFFFF) >> 11) / float(
            1 << 53)


class ParameterManager:
    """Warmup → sample → freeze over :class:`TunedParams` trials.

    Drive it like the reference's ``Update`` loop, one scored window at a
    time::

        pm = ParameterManager(initial, tune_quant_block=..., ...)
        while not pm.done:
            score = measure(pm.current)   # steps/sec of a timed window
            pm.record_sample(score)
        winner = pm.best

    ``warmup_samples`` windows run on the initial setting and are
    discarded (parameter_manager.cc:162 — JIT/dispatch warmup must not
    enter the GP); then every window is scored, and after ``max_samples``
    scored windows the manager freezes (``done``) on the best setting.
    """

    def __init__(
        self,
        initial: TunedParams,
        *,
        tune_quant_block: bool = False,
        tune_hierarchical: bool = True,
        tune_zero: bool = False,
        tune_overlap: bool = False,
        tune_fused: bool = False,
        tune_pp: bool = False,
        pp_stages: int = 0,
        pp_max_interleave: int = 1,
        tune_moe: bool = False,
        moe_experts: int = 0,
        tune_serve: bool = False,
        warmup_samples: int = 3,
        steps_per_sample: int = 10,
        max_samples: int = 20,
        gp_noise: float = 0.8,
        log_path: Optional[str] = None,
        seed: int = 0x9E3779B97F4A7C15,
        seeds: Sequence[TunedParams] = (),
    ) -> None:
        if max_samples < 1:
            raise ValueError("max_samples must be >= 1")
        self.initial = initial
        self.current = initial
        self.best = initial
        self.best_score = -math.inf
        self.tune_quant_block = tune_quant_block
        self.tune_hierarchical = tune_hierarchical
        # zero_sharding restructures the step (ZeroState layout), so it is
        # searched only when the session's step builder declares it can
        # accept the knob (autotune_session(tune_zero=True)).
        self.tune_zero = tune_zero
        # overlap restructures the microbatch loop when composed with
        # backward_passes_per_step (OverlapMultiStepsState), so it is
        # gated the same way (autotune_session(tune_overlap=True));
        # num_comm_streams rides the same gate — it only means anything
        # with overlap on.
        self.tune_overlap = tune_overlap
        # The fused-kernel backend only changes the wire when an int8 leg
        # exists (quantized); with quantized off, encode_tuned drops the
        # dimension and canonicalization dedups the trials away.
        self.tune_fused = tune_fused
        # The pipeline pair restructures the WHOLE training schedule
        # (microbatch count + virtual-stage interleave are trace-time
        # schedule geometry), so like zero/overlap it is searched only
        # when the session's step builder declares it can rebuild at a
        # proposed (pp_microbatches, pp_interleave)
        # (autotune_session(tune_pp=True, pp_stages=S)). With pp off the
        # encoding drops the segment and both knobs canonicalize dead.
        self.tune_pp = tune_pp
        self.pp_stages = max(0, int(pp_stages))
        self.pp_max_interleave = max(1, int(pp_max_interleave))
        # The MoE pair restructures the dispatch-buffer geometry
        # (capacity is trace-time shape) and the a2a wire dtype, so like
        # zero/overlap/pp it is searched only when the session's step
        # builder declares it can rebuild at a proposed
        # (moe_capacity_factor, moe_quantized)
        # (autotune_session(tune_moe=True, moe_experts=E)). With moe
        # off the encoding drops the segment and both knobs
        # canonicalize dead.
        self.tune_moe = tune_moe
        self.moe_experts = max(0, int(moe_experts))
        # The serving pair restructures the decode step (the speculative
        # window W = k+1 is trace-time geometry) and the prefill→decode
        # KV wire dtype, so like zero/overlap/pp/moe it is searched only
        # when the session drives a serving engine that can rebuild at a
        # proposed (spec_draft_k, kv_migrate_quantized)
        # (autotune_session(tune_serve=True)). In a training session the
        # encoding drops the segment and both knobs canonicalize dead.
        self.tune_serve = tune_serve
        self.warmup_samples = max(0, warmup_samples)
        self.steps_per_sample = max(1, steps_per_sample)
        self.max_samples = max_samples
        self.gp_noise = gp_noise
        self.done = False
        self.history: List[Tuple[TunedParams, float]] = []
        self._warmups_done = 0
        self._rng = _XorShift(seed)
        self._tried = {self._unit_key(initial)}
        # Warm-start seeds (docs/cost-model.md): the cost model's ranked
        # shortlist, walked IN ORDER before the GP proposes — the first
        # scored trials are the analytically best-priced plans, so the
        # GP fits an informed neighborhood instead of random exploration.
        self._seed_queue: List[TunedParams] = []
        seen_seeds = set(self._tried)
        for s in seeds:
            c = self._canonicalize(s)
            k = self._unit_key(c)
            if k in seen_seeds:
                continue
            seen_seeds.add(k)
            self._seed_queue.append(c)
        self.seeded = len(self._seed_queue)
        self._log: Optional[IO[str]] = None
        self._csv = None
        if log_path:
            os.makedirs(os.path.dirname(os.path.abspath(log_path)),
                        exist_ok=True)
            self._log = open(log_path, "w", newline="")
            self._csv = csv.writer(self._log)
            self._csv.writerow(CSV_FIELDS)
            self._log.flush()

    # -- unit-cube coordinates (parameter_manager.cc:63-86) -------------

    def _to_unit(self, p: TunedParams) -> Tuple[float, ...]:
        f = math.log2(max(1, p.fusion_threshold_bytes))
        q = math.log2(max(1, p.quant_block))
        s = math.log2(max(1, p.num_comm_streams))
        ppm = math.log2(max(2, p.pp_microbatches or 2))
        ppv = math.log2(max(1, p.pp_interleave))
        cap = min(_MAX_MOE_CAP,
                  max(_MIN_MOE_CAP, p.moe_capacity_factor
                      or _MIN_MOE_CAP))
        return (
            (f - _MIN_FUSION_LOG) / (_MAX_FUSION_LOG - _MIN_FUSION_LOG),
            (q - _MIN_QBLOCK_LOG) / (_MAX_QBLOCK_LOG - _MIN_QBLOCK_LOG),
            # Booleans (relaxed categoricals) sit at 0.25/0.75, well
            # inside the box.
            0.75 if p.hierarchical_allreduce else 0.25,
            # zero_stage 0/1/2 sits at the thirds' centers (stage 3
            # restructures the training loop and is never searched).
            (min(p.zero_stage, 2) + 0.5) / 3.0,
            0.75 if p.overlap else 0.25,
            s / _MAX_STREAMS_LOG,
            0.75 if p.fused else 0.25,
            (ppm - _MIN_PPM_LOG) / (_MAX_PPM_LOG - _MIN_PPM_LOG),
            ppv / _MAX_PPV_LOG,
            (cap - _MIN_MOE_CAP) / (_MAX_MOE_CAP - _MIN_MOE_CAP),
            0.75 if p.moe_quantized else 0.25,
            min(_MAX_SPEC_K, max(0, p.spec_draft_k)) / _MAX_SPEC_K,
            0.75 if p.kv_migrate_quantized else 0.25,
            0.75 if p.pp_schedule == "zb1" else 0.25,
        )

    def _from_unit(self, u) -> TunedParams:
        f = _MIN_FUSION_LOG + u[0] * (_MAX_FUSION_LOG - _MIN_FUSION_LOG)
        if self.tune_quant_block:
            # Snap to a power of two: scale blocks align with the
            # ATOMIC_UNIT-padded bucket layout (ops/fusion.py).
            q = _MIN_QBLOCK_LOG + u[1] * (_MAX_QBLOCK_LOG - _MIN_QBLOCK_LOG)
            qblock = 1 << max(int(_MIN_QBLOCK_LOG),
                              min(int(_MAX_QBLOCK_LOG), round(q)))
        else:
            qblock = self.initial.quant_block
        hier = (u[2] >= 0.5 if self.tune_hierarchical
                else self.initial.hierarchical_allreduce)
        stage = (min(2, int(u[3] * 3)) if self.tune_zero
                 else self.initial.zero_stage)
        if self.tune_overlap:
            ov = u[4] >= 0.5
            # pow2 snap 1-4; only meaningful with overlap on — pin the
            # dead dimension so it never splits otherwise-equal trials.
            ns = 1 << max(0, min(int(_MAX_STREAMS_LOG),
                                 round(u[5] * _MAX_STREAMS_LOG)))
            if not ov:
                ns = 1
        else:
            ov = self.initial.overlap
            ns = self.initial.num_comm_streams
        fz = (u[6] >= 0.5 if self.tune_fused else self.initial.fused)
        if self.tune_pp:
            # pow2 snap, then round up to a multiple of the stage count
            # (the interleaved grouping needs M % stages == 0).
            ppm_l = _MIN_PPM_LOG + u[7] * (_MAX_PPM_LOG - _MIN_PPM_LOG)
            ppm = 1 << max(int(_MIN_PPM_LOG),
                           min(int(_MAX_PPM_LOG), round(ppm_l)))
            if self.pp_stages > 1:
                ppm = max(ppm, self.pp_stages)
                ppm += (-ppm) % self.pp_stages
            ppv = 1 << max(0, min(int(_MAX_PPV_LOG),
                                  round(u[8] * _MAX_PPV_LOG)))
            ppv = min(ppv, self.pp_max_interleave)
            # Schedule family (v11): a relaxed boolean at the tail so
            # pre-v11 unit tuples stay valid coordinates.
            u13 = u[13] if len(u) > 13 else 0.25
            pps = "zb1" if u13 >= 0.5 else "interleaved_1f1b"
        else:
            ppm = self.initial.pp_microbatches
            ppv = self.initial.pp_interleave
            pps = self.initial.pp_schedule
        if self.tune_moe:
            # Quarter-snap inside the [1.0, 2.0] box: capacity is a
            # trace-time buffer shape, so the space is effectively
            # discrete (finer steps cannot change the padded capacity
            # by more than rounding). Tolerant of pre-v9 unit tuples
            # lacking the trailing dims.
            u9 = u[9] if len(u) > 9 else 0.25
            u10 = u[10] if len(u) > 10 else 0.25
            cap = _MIN_MOE_CAP + u9 * (_MAX_MOE_CAP - _MIN_MOE_CAP)
            cap = round(cap * 4) / 4.0
            moe_cap = min(_MAX_MOE_CAP, max(_MIN_MOE_CAP, cap))
            moe_q = u10 >= 0.5
        else:
            moe_cap = self.initial.moe_capacity_factor
            moe_q = self.initial.moe_quantized
        if self.tune_serve:
            # Integer-snap the draft window inside [0, _MAX_SPEC_K]
            # (the window W = k+1 is trace-time geometry — the space IS
            # discrete). Tolerant of pre-v10 unit tuples lacking the
            # trailing dims.
            u11 = u[11] if len(u) > 11 else 0.0
            u12 = u[12] if len(u) > 12 else 0.25
            sv_k = max(0, min(_MAX_SPEC_K, round(u11 * _MAX_SPEC_K)))
            sv_q = u12 >= 0.5
        else:
            sv_k = self.initial.spec_draft_k
            sv_q = self.initial.kv_migrate_quantized
        return self._canonicalize(TunedParams(
            fusion_threshold_bytes=int(2.0 ** f),
            quant_block=qblock,
            hierarchical_allreduce=hier,
            zero_stage=stage,
            overlap=ov,
            num_comm_streams=ns,
            fused=fz,
            pp_microbatches=ppm,
            pp_interleave=ppv,
            pp_schedule=pps,
            moe_capacity_factor=moe_cap,
            moe_quantized=moe_q,
            spec_draft_k=sv_k,
            kv_migrate_quantized=sv_q,
        ))

    def _plan_of(self, p: TunedParams) -> str:
        """The canonical wire-plan encoding of a knob setting — the
        search-space coordinate the GP actually explores (``plan``
        column of the CSV, ``plan`` field of the v5 cache entry)."""
        return _wire_planner.encode_tuned(
            p, quantized=self.tune_quant_block, pp=self.tune_pp,
            moe=self.tune_moe, serve=self.tune_serve)

    def _canonicalize(self, p: TunedParams) -> TunedParams:
        """Snap a proposal onto its wire plan: knobs that are dead in
        the plan it encodes (hierarchical under the ZeRO rs+ag split,
        stream count with overlap off) reset to the canonical value, so
        equal plans are equal TunedParams and dedup as one trial."""
        d = _wire_planner.decode_tuned(self._plan_of(p))
        return dataclasses.replace(
            p,
            hierarchical_allreduce=d["hierarchical_allreduce"],
            zero_stage=d["zero_stage"],
            overlap=d["overlap"],
            num_comm_streams=d["num_comm_streams"],
            fused=d.get("fused", False),
            quant_block=d.get("quant_block", p.quant_block),
            pp_microbatches=d.get("pp_microbatches", 0),
            pp_interleave=d.get("pp_interleave", 1),
            pp_schedule=d.get("pp_schedule", "interleaved_1f1b"),
            moe_capacity_factor=d.get("moe_capacity_factor", 0.0),
            moe_quantized=d.get("moe_quantized", False),
            spec_draft_k=d.get("spec_draft_k", 0),
            kv_migrate_quantized=d.get("kv_migrate_quantized", False))

    def _unit_key(self, p: TunedParams) -> tuple:
        """Dedup key: the snapped fusion threshold plus the canonical
        plan encoding, so two unit points that collapse to the same
        compiled wire plan count as one trial."""
        # Fusion threshold dedups at 1/4-octave resolution — finer than
        # that cannot change a bucket plan by more than rounding.
        return (round(math.log2(max(1, p.fusion_threshold_bytes)) * 4),
                p.quant_block, self._plan_of(p))

    # -- sampling loop ---------------------------------------------------

    @property
    def warming_up(self) -> bool:
        return (not self.done
                and self._warmups_done < self.warmup_samples)

    @property
    def samples_done(self) -> int:
        return len(self.history)

    def peek_next(self) -> Optional[TunedParams]:
        """The setting the NEXT ``record_sample`` will make current,
        when that is knowable without the pending score: the initial
        setting during warmup (warmup windows never advance it), the
        first untried cost-model seed during the seed-queue phase.
        None once proposals are GP-driven (they depend on the score
        being measured right now) or when the next sample freezes the
        session — the driver's compile-ahead prefetch only overlaps
        builds this method can name exactly (docs/compile.md)."""
        if self.done:
            return None
        if self._warmups_done < self.warmup_samples:
            return self.current
        if len(self.history) + 1 >= self.max_samples:
            return None  # next record freezes at best: no new trial
        for cand in self._seed_queue:
            if self._unit_key(cand) not in self._tried:
                return cand
        return None

    def record_sample(self, score: float, *,
                      compile_ms: float = 0.0,
                      compile_cache_hit: bool = False) -> None:
        """Feed one scored window (steps/sec of ``current``); advances the
        warmup → sample → freeze machine (parameter_manager.cc:139-194).
        ``compile_ms``/``compile_cache_hit`` describe the trial's build
        step for the v12 CSV columns (docs/compile.md)."""
        if self.done:
            raise RuntimeError("record_sample() after convergence")
        if self._warmups_done < self.warmup_samples:
            self._warmups_done += 1
            return  # discarded: current stays the initial setting
        score = float(score)
        self.history.append((self.current, score))
        self._write_row(score, compile_ms, compile_cache_hit)
        if score > self.best_score:
            self.best_score = score
            self.best = self.current
        if len(self.history) >= self.max_samples:
            self._freeze()
            return
        self.current = self._propose_next()

    def _write_row(self, score: float, compile_ms: float = 0.0,
                   compile_cache_hit: bool = False) -> None:
        if self._csv is None:
            return
        p = self.current
        self._csv.writerow([len(self.history), p.fusion_threshold_bytes,
                            p.quant_block,
                            int(p.hierarchical_allreduce),
                            int(p.zero_sharding),
                            int(p.zero_stage),
                            int(p.overlap),
                            int(p.num_comm_streams),
                            int(p.fused),
                            int(p.pp_microbatches),
                            int(p.pp_interleave),
                            f"{p.moe_capacity_factor:g}",
                            int(p.moe_quantized),
                            int(p.spec_draft_k),
                            int(p.kv_migrate_quantized),
                            p.pp_schedule,
                            f"{score:.6g}",
                            self._plan_of(p),
                            f"{float(compile_ms):.3f}",
                            int(compile_cache_hit)])
        self._log.flush()

    def _freeze(self) -> None:
        self.done = True
        self.current = self.best
        self.close()
        log.info(
            "autotune converged after %d samples: fusion_threshold=%d "
            "quant_block=%d hierarchical=%s zero_stage=%d overlap=%s "
            "streams=%d fused=%s (best %.3f steps/sec)",
            len(self.history), self.best.fusion_threshold_bytes,
            self.best.quant_block, self.best.hierarchical_allreduce,
            self.best.zero_stage, self.best.overlap,
            self.best.num_comm_streams, self.best.fused,
            self.best_score)

    def _sample_unit(self) -> Tuple[float, ...]:
        # The v11 tail dim (pp_schedule) draws from the stream only
        # when the pp pair is live, so pre-v11 seed trajectories — and
        # any replayed logs — are unchanged for non-pipelined sessions.
        u = [self._rng.next() for _ in range(_DIMS - 1)]
        u.append(self._rng.next() if self.tune_pp else 0.25)
        if not self.tune_hierarchical:
            u[2] = 0.25
        if not self.tune_zero:
            u[3] = 0.25
        if not self.tune_overlap:
            u[4] = 0.25
            u[5] = 0.0
        if not self.tune_fused:
            u[6] = 0.25
        if not self.tune_pp:
            u[7] = 0.0
            u[8] = 0.0
        if not self.tune_moe:
            u[9] = 0.25
            u[10] = 0.25
        if not self.tune_serve:
            u[11] = 0.0
            u[12] = 0.25
        return tuple(u)

    def _propose_next(self) -> TunedParams:
        """Warm-start seeds first (the cost model's ranked shortlist,
        in predicted-ms order); then EI-argmax over random candidates
        once the GP fits, random exploration before that
        (parameter_manager.cc:88-137). Prefers configurations not yet
        tried (each repeat costs a recompile)."""
        while self._seed_queue:
            cand = self._seed_queue.pop(0)
            key = self._unit_key(cand)
            if key in self._tried:
                continue  # a prior trial already covered this plan
            self._tried.add(key)
            return cand
        xs = [self._to_unit(p) for p, _ in self.history]
        ys = [s for _, s in self.history]
        # Normalize scores to zero-mean/unit-variance for the GP.
        mean = sum(ys) / len(ys)
        sd = math.sqrt(sum((y - mean) ** 2 for y in ys) / len(ys)) or 1.0
        yn = [(y - mean) / sd for y in ys]
        best_n = max(yn)
        gp = GaussianProcess(_DIMS, 0.3, self.gp_noise)
        fitted = len(xs) >= 2 and gp.fit(xs, yn)

        # EI-argmax among candidates snapping to an untried configuration;
        # if every candidate collapses onto tried points (degenerate
        # space), take the overall argmax. With a fitted GP, EI
        # evaluates in one batched predict (gp.predict_batch) over the
        # 1000-candidate pool; unfitted, each candidate draws its
        # random score right after its coordinates (the original
        # interleaved order, so replay seeds keep their trajectories).
        cands, eis = [], []
        for _ in range(1000 if fitted else 64):
            cands.append(self._sample_unit())
            if not fitted:
                eis.append(self._rng.next())
        if fitted:
            eis = gp.expected_improvement_batch(cands, best_n)
        new_x, new_ei = None, -1.0
        any_x, any_ei = None, -1.0
        for cand, ei in zip(cands, eis):
            if any_x is None or ei > any_ei:
                any_x, any_ei = cand, ei
            if ei > new_ei and \
                    self._unit_key(self._from_unit(cand)) not in self._tried:
                new_x, new_ei = cand, ei
        proposal = self._from_unit(new_x if new_x is not None else any_x)
        self._tried.add(self._unit_key(proposal))
        return proposal

    def close(self) -> None:
        if self._log is not None:
            self._log.close()
            self._log = None
            self._csv = None


def read_log(path: str) -> List[dict]:
    """Parse a ``HOROVOD_AUTOTUNE_LOG`` CSV back into typed rows — the
    round-trip counterpart of the manager's writer (tests assert the
    schema; analysis notebooks get typed values for free).

    Tolerant of older schemas: pre-v4 logs lack ``zero_stage``/
    ``overlap``/``num_comm_streams`` (the boolean ``zero_sharding``
    named stage 2), pre-v5 logs lack the ``plan`` encoding column — it
    is re-derived from the knob columns so every row carries one."""
    rows: List[dict] = []
    with open(path, newline="") as f:
        for rec in csv.DictReader(f):
            sharding = bool(int(rec.get("zero_sharding", 0) or 0))
            # Pre-v4 logs carried only the boolean; it named stage 2.
            stage = int(rec.get("zero_stage", 2 if sharding else 0) or 0)
            row = {
                "sample": int(rec["sample"]),
                "fusion_threshold_bytes": int(
                    rec["fusion_threshold_bytes"]),
                "quant_block": int(rec["quant_block"]),
                "hierarchical_allreduce": bool(
                    int(rec["hierarchical_allreduce"])),
                "zero_sharding": sharding or stage > 0,
                "zero_stage": stage,
                "overlap": bool(int(rec.get("overlap", 0) or 0)),
                "num_comm_streams": int(rec.get("num_comm_streams", 1)
                                        or 1),
                "fused": bool(int(rec.get("fused", 0) or 0)),
                "pp_microbatches": int(rec.get("pp_microbatches", 0)
                                       or 0),
                "pp_interleave": int(rec.get("pp_interleave", 1) or 1),
                "moe_capacity_factor": float(
                    rec.get("moe_capacity_factor", 0.0) or 0.0),
                "moe_quantized": bool(int(rec.get("moe_quantized", 0)
                                          or 0)),
                "spec_draft_k": int(rec.get("spec_draft_k", 0) or 0),
                "kv_migrate_quantized": bool(
                    int(rec.get("kv_migrate_quantized", 0) or 0)),
                "pp_schedule": str(rec.get("pp_schedule")
                                   or "interleaved_1f1b"),
                "score_steps_per_sec": float(rec["score_steps_per_sec"]),
                # v12 compile pair; pre-v12 logs never timed the build.
                "compile_ms": float(rec.get("compile_ms", 0.0) or 0.0),
                "compile_cache_hit": bool(
                    int(rec.get("compile_cache_hit", 0) or 0)),
            }
            enc = (rec.get("plan") or "").strip()
            if not enc:  # pre-v5 log: derive the canonical encoding
                enc = _wire_planner.encode_tuned(
                    TunedParams.from_dict(row))
            row["plan"] = enc
            rows.append(row)
    return rows
