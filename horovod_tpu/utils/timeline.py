"""Timeline: Chrome-tracing-format profiling of framework activity.

Reference: ``horovod/common/timeline.{h,cc}`` — coordinator-side writer
thread fed by a lockfree queue, emitting per-tensor lifecycle events
(NEGOTIATE_* → QUEUE → WAIT_FOR_DATA → op activities) viewable in
``chrome://tracing`` (SURVEY §5.1). Enabled by ``HOROVOD_TIMELINE=<file>``
or at runtime via :func:`horovod_tpu.start_timeline`
(reference: operations.cc:715-757, basics.py:75-98).

TPU-native redesign: on the compiled path the per-collective schedule lives
inside XLA, where the platform profiler (``jax.profiler``) already captures
device activity — so this Timeline records the *host-side* framework events
(eager collectives, controller cycles, elastic transitions, step markers)
and offers :func:`trace` context managers that bracket XLA launches. Events
are written by a dedicated writer thread consuming a queue, like the
reference's writer design (timeline.h:48-80), so tracing never blocks the
training loop.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from contextlib import contextmanager
from typing import Optional


class Timeline:
    """Chrome-tracing JSON writer (reference: timeline.cc).

    Event categories mirror the reference activities (common.h:31-62):
    NEGOTIATE_ALLREDUCE, QUEUE, WAIT_FOR_DATA, MEMCPY_IN_FUSION_BUFFER,
    XLA_ALLREDUCE (our NCCL_ALLREDUCE analogue), CYCLE markers.
    """

    def __init__(self, path: str, mark_cycles: bool = False) -> None:
        self._path = path
        self._mark_cycles = mark_cycles
        self._queue: "queue.Queue" = queue.Queue()
        self._start = time.perf_counter()
        self._closed = False
        self._close_lock = threading.Lock()
        self._pid = os.getpid()
        self._file = open(path, "w")
        self._file.write("[\n")
        self._first = True
        self._writer = threading.Thread(target=self._writer_loop,
                                        name="hvd-timeline-writer",
                                        daemon=True)
        self._writer.start()

    # -- event emission (any thread) ------------------------------------

    def _now_us(self) -> float:
        return (time.perf_counter() - self._start) * 1e6

    def emit(self, name: str, phase: str, *, tid: str = "main",
             ts: Optional[float] = None, args: Optional[dict] = None) -> None:
        if self._closed:
            return
        ev = {"name": name, "ph": phase, "pid": self._pid, "tid": tid,
              "ts": self._now_us() if ts is None else ts}
        if args:
            ev["args"] = args
        self._queue.put(ev)
        # Every Timeline event also lands in the flight recorder's ring
        # (monitor/flight.py): the crash-forensic black box holds the
        # last N events even when the timeline file dies with the rank.
        try:
            from ..monitor import flight as _flight

            _flight.tap(ev)
        except Exception:  # pragma: no cover - interpreter teardown
            pass

    def begin(self, tensor_name: str, activity: str) -> None:
        """Begin an activity for a tensor (reference: Timeline::ActivityStart)."""
        self.emit(activity, "B", tid=tensor_name)

    def end(self, tensor_name: str, activity: str = "") -> None:
        """End the current activity (reference: Timeline::ActivityEnd)."""
        self.emit(activity, "E", tid=tensor_name)

    def instant(self, name: str, *, tid: str = "main",
                args: Optional[dict] = None) -> None:
        self.emit(name, "i", tid=tid, args=args)

    def counter(self, name: str, values: dict, *,
                tid: str = "metrics") -> None:
        """Chrome counter event (``ph:"C"``): trace viewers plot ``values``
        as per-series area charts — the Timeline mirror of the metrics
        registry (monitor/sinks.py TimelineSink)."""
        self.emit(name, "C", tid=tid, args=values)

    def mark_cycle_start(self) -> None:
        """Cycle markers (HOROVOD_TIMELINE_MARK_CYCLES, operations.cc:430)."""
        if self._mark_cycles:
            self.instant("CYCLE_START", tid="cycles")

    @contextmanager
    def trace(self, tensor_name: str, activity: str):
        """Bracket a host-side activity: with tl.trace("grads", "XLA_ALLREDUCE")."""
        self.begin(tensor_name, activity)
        try:
            yield
        finally:
            self.end(tensor_name, activity)

    # -- writer thread ---------------------------------------------------

    def _write_event(self, ev: dict) -> None:
        line = json.dumps(ev)
        if not self._first:
            self._file.write(",\n")
        self._first = False
        self._file.write(line)

    def _writer_loop(self) -> None:
        while True:
            ev = self._queue.get()
            if ev is None:
                return
            self._write_event(ev)

    def close(self) -> None:
        """Flush and close. Idempotent; safe to call from any thread.

        Shutdown ordering contract (regression-tested in
        tests/test_timeline.py): every event emitted before close() is
        called reaches the file — the writer drains up to the sentinel,
        the writer thread is JOINED (with a timeout, not daemon-
        abandoned), and anything the sentinel raced past (events enqueued
        while close() was in flight) is drained synchronously before the
        closing bracket is written.
        """
        with self._close_lock:
            if self._closed:
                return
            self._closed = True  # emit() now rejects new events
        self._queue.put(None)
        self._writer.join(timeout=5)
        # Drain events that were enqueued between the last emit() check
        # and the sentinel (or left behind if the join timed out while
        # the writer was wedged).
        while True:
            try:
                ev = self._queue.get_nowait()
            except queue.Empty:
                break
            if ev is not None:
                self._write_event(ev)
        self._file.write("\n]\n")
        self._file.flush()
        self._file.close()


def start_timeline(path: str, mark_cycles: bool = False) -> Timeline:
    """Start timeline recording at runtime (reference: hvd.start_timeline,
    basics.py:75-98). Attaches to global state so framework internals emit
    into it. Idempotent on restart: an already-attached timeline is
    flushed and closed (a valid trace) before the new one starts."""
    from ..common import basics

    s = basics._require_init()
    if s.timeline is not None:
        s.timeline.close()
    s.timeline = Timeline(path, mark_cycles=mark_cycles)
    return s.timeline


def stop_timeline() -> None:
    """Stop recording (reference: hvd.stop_timeline). Idempotent: a
    second stop — or a stop with no timeline attached, or after
    ``shutdown()`` already closed it — is a no-op."""
    from ..common import basics

    s = basics._state
    if s.timeline is not None:
        s.timeline.close()
        s.timeline = None
