"""Metric sinks: JSONL snapshots, Prometheus endpoint, Timeline mirrors.

Every sink consumes the :meth:`MetricsRegistry.snapshot` dict schema; the
reporter thread (:class:`Reporter`) pushes one snapshot per interval —
and, when enabled, one cross-rank :meth:`~MetricsRegistry.aggregate` —
keeping all exporting off the training step's critical path.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .registry import LOG2_BUCKET_BOUNDS, MetricsRegistry


class JsonlSink:
    """Append one JSON line per snapshot to ``path`` (the artifact
    ``scripts/obs_report.py`` joins against the Timeline)."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()

    def write(self, snapshot: dict) -> None:
        line = json.dumps(snapshot, sort_keys=True)
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line + "\n")

    def close(self) -> None:
        pass


class TimelineSink:
    """Mirror gauges/counters onto the active Timeline as Chrome counter
    events (``ph:"C"``, name ``METRIC:<metric>``) — the trace-view
    rendering of the registry, plotted as per-series area charts right
    above the span rows they explain."""

    def write(self, snapshot: dict) -> None:
        from ..common import basics

        tl = basics._state.timeline
        if tl is None:
            return
        for key, v in snapshot["counters"].items():
            tl.counter(f"METRIC:{key}", {"value": v})
        for key, v in snapshot["gauges"].items():
            tl.counter(f"METRIC:{key}", {"value": v})
        for key, h in snapshot["histograms"].items():
            tl.counter(f"METRIC:{key}", {"count": h["count"],
                                         "sum": h["sum"]})

    def close(self) -> None:
        pass


# -- Prometheus text format -------------------------------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(key: str) -> str:
    """``name{a=b}`` snapshot key → (metric_name, label_string)."""
    if "{" in key:
        name, rest = key.split("{", 1)
        labels = rest.rstrip("}")
        parts = []
        for pair in labels.split(","):
            if not pair:
                continue
            k, _, v = pair.partition("=")
            parts.append(f'{_NAME_RE.sub("_", k)}="{v}"')
        label_str = "{" + ",".join(parts) + "}"
    else:
        name, label_str = key, ""
    return "horovod_" + _NAME_RE.sub("_", name), label_str


def render_prometheus(snapshot: dict) -> str:
    """Render a snapshot in the Prometheus exposition text format."""
    lines = []
    seen_types = set()

    def typeline(name: str, kind: str) -> None:
        if name not in seen_types:
            lines.append(f"# TYPE {name} {kind}")
            seen_types.add(name)

    for key, v in sorted(snapshot["counters"].items()):
        name, labels = _prom_name(key)
        typeline(name, "counter")
        lines.append(f"{name}{labels} {v:g}")
    for key, v in sorted(snapshot["gauges"].items()):
        name, labels = _prom_name(key)
        typeline(name, "gauge")
        lines.append(f"{name}{labels} {v:g}")
    for key, h in sorted(snapshot["histograms"].items()):
        name, labels = _prom_name(key)
        typeline(name, "histogram")
        inner = labels[1:-1] if labels else ""
        cum = 0
        for bound, c in zip(LOG2_BUCKET_BOUNDS, h["counts"]):
            cum += c
            le = "+Inf" if bound == float("inf") else f"{bound:g}"
            sep = "," if inner else ""
            lines.append(
                f'{name}_bucket{{{inner}{sep}le="{le}"}} {cum}')
        lines.append(f"{name}_sum{labels} {h['sum']:g}")
        lines.append(f"{name}_count{labels} {h['count']}")
    return "\n".join(lines) + "\n"


class PrometheusSink:
    """Serve the live registry at ``http://:port/metrics``
    (``HOROVOD_METRICS_PORT``; port 0 binds an OS-assigned port exposed
    as ``.port``). Renders at request time — ``write`` is a no-op."""

    def __init__(self, registry: MetricsRegistry, port: int) -> None:
        sink = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_error(404)
                    return
                body = render_prometheus(
                    sink.registry.snapshot()).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-request stderr spam
                pass

        self.registry = registry
        self._server = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="hvd-metrics-http", daemon=True)
        self._thread.start()

    def write(self, snapshot: dict) -> None:
        pass  # pull-model sink: rendered per scrape

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


class Reporter:
    """Interval reporter thread: every ``interval`` seconds take one
    snapshot (cross-rank aggregated when ``aggregate`` is on) and push it
    through the configured sinks — one small fused allreduce per
    reporting interval, off the step's critical path."""

    def __init__(self, registry: MetricsRegistry, sinks, interval: float,
                 aggregate: bool = False) -> None:
        self.registry = registry
        self.sinks = sinks
        self.interval = interval
        self.aggregate = aggregate
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="hvd-metrics-reporter", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.flush()
            except Exception:  # never kill the job over an export
                pass

    def flush(self) -> None:
        snap = (self.registry.aggregate() if self.aggregate
                else self.registry.snapshot())
        if self.aggregate:
            # Straggler detection folds into the SAME aggregated
            # snapshot the interval allreduce just produced — cross-rank
            # skew attribution at zero extra wire (monitor/straggler.py).
            try:
                from . import straggler as _straggler

                _straggler.straggler_detector().detect(snapshot=snap)
            except Exception:  # detection must never kill the exporter
                pass
        for s in self.sinks:
            s.write(snap)

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
