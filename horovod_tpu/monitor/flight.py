"""Flight recorder: crash-forensic ring buffer of framework events.

The monitor package's *live* pillars (registry, StallInspector, sinks)
tell you what a healthy run is doing; this module is the forensic pillar
— the artifact you autopsy after a rank crashed, hung, or was killed.

Design (docs/observability.md):

* **Always-on bounded ring.** A ``deque(maxlen=HOROVOD_FLIGHT_RECORDER_
  EVENTS)`` (default 4096, ``0`` disables) of recent framework events:
  every Timeline event is tapped in (spans, instants, counters), and the
  forensically-critical sources record directly so the ring works even
  with no Timeline attached — fault counters (``FAULT:*``), stall
  instants, eager collectives (``FLIGHT:COLLECTIVE``), step/commit marks
  (``FLIGHT:STEP``/``FLIGHT:COMMIT``), serve engine steps
  (``FLIGHT:SERVE_STEP``). A compact registry snapshot is folded in every
  ``HOROVOD_FLIGHT_SNAPSHOT_EVERY`` events (default 1024), so a dump
  carries metric history, not just the final state. Appending one event
  is a lock + deque append — the armed-forensics overhead budget is <1%
  of a representative step (tests/test_monitor.py::TestOverhead).

* **Atomic dumps.** ``dump(reason)`` serializes the ring + a full
  registry snapshot + the StallInspector's in-flight set + the straggler
  history to ``HOROVOD_FLIGHT_RECORDER_DIR`` with the checkpoint layout's
  write discipline (docs/checkpoint.md): tmp file beside the target, one
  ``os.replace`` commit, and a crc32 of the canonical event payload in
  the header so ``scripts/postmortem.py`` can reject torn files.

* **Dump triggers.** Armed by ``hvd.init()`` when the dir knob is set:
  unhandled exceptions (``sys.excepthook`` chain), SIGTERM (dump, then
  re-deliver so exit semantics are preserved), native crashes
  (``faulthandler`` tracebacks land beside the dumps), StallInspector
  escalation past the shutdown deadline, the elastic worker's
  reset-on-peer-failure and the elastic driver's abandon-incarnation
  paths, a chaos ``crash`` injection (the injector dumps before
  ``os._exit`` — a kernel-panic simulation still leaves its black box),
  and the explicit ``hvd.dump_flight_record()`` API.

Stdlib-only, like :mod:`.registry`: the launcher/driver processes record
and dump too; the one framework lookup (rank identity) is lazy and
guarded.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
import zlib
from typing import Dict, List, Optional

DEFAULT_EVENTS = 4096
DEFAULT_SNAPSHOT_EVERY = 1024
DUMP_VERSION = 1
DUMP_PREFIX = "flight_"


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    if v in (None, ""):
        return default
    try:
        return int(v)
    except ValueError:
        return default


def _identity() -> Dict[str, object]:
    """Best-effort rank identity, resolvable from any process (worker,
    launcher, driver) at any lifecycle point — including mid-teardown."""
    ident: Dict[str, object] = {
        "pid": os.getpid(),
        "hostname": os.environ.get("HOROVOD_HOSTNAME") or "",
        "local_rank": os.environ.get("HOROVOD_LOCAL_RANK") or "",
        "rank": -1,
        "world": 0,
    }
    try:
        from ..common import basics

        if basics.is_initialized():
            ident["rank"] = int(basics.rank())
            ident["world"] = int(basics.size())
            return ident
    except Exception:
        pass
    env_rank = os.environ.get("HOROVOD_RANK")
    if env_rank not in (None, ""):
        try:
            ident["rank"] = int(env_rank)
        except ValueError:
            pass
    env_size = os.environ.get("HOROVOD_SIZE")
    if env_size not in (None, ""):
        try:
            ident["world"] = int(env_size)
        except ValueError:
            pass
    if ident["rank"] == -1 and not ident["hostname"]:
        ident["role"] = "driver"
    return ident


_EXPERT_KEY_RE = None  # compiled lazily (re import stays cold-path)


def _extract_expert_load(registry_snap: Optional[dict]) -> Dict[str, float]:
    """Fold the per-expert load metrics of a registry snapshot into a
    compact ``{expert_id: tokens}`` dict: ``serve.expert_tokens{expert}``
    histogram sums (the serving engines) plus
    ``moe.expert_tokens{expert}`` counters (the training bench leg)."""
    if not registry_snap:
        return {}
    global _EXPERT_KEY_RE
    if _EXPERT_KEY_RE is None:
        import re

        _EXPERT_KEY_RE = re.compile(
            r"^(?:serve|moe)\.expert_tokens\{expert=(\d+)\}$")
    load: Dict[str, float] = {}
    for key, h in (registry_snap.get("histograms") or {}).items():
        m = _EXPERT_KEY_RE.match(key)
        if m and isinstance(h, dict):
            e = m.group(1)
            load[e] = load.get(e, 0.0) + float(h.get("sum", 0.0))
    for key, v in (registry_snap.get("counters") or {}).items():
        m = _EXPERT_KEY_RE.match(key)
        if m:
            e = m.group(1)
            load[e] = load.get(e, 0.0) + float(v)
    return load


#: Registry keys folded into the dump's compact ``serve_cache`` view
#: (docs/serving.md): the disaggregated-serving health triple — prefix
#: cache effectiveness, speculative acceptance, and KV-migration wire
#: state — so scripts/postmortem.py can name a migration-stalled
#: replica or a cold prefix cache without walking the raw registry.
_SERVE_CACHE_GAUGES = (
    "serve.prefix_lookups", "serve.prefix_hits",
    "serve.prefix_hit_tokens", "serve.prefix_hit_rate",
    "serve.prefix_cached_pages", "serve.spec.acceptance_rate",
    "serve.prefill_replicas", "serve.decode_replicas",
)
_SERVE_CACHE_COUNTERS = (
    "serve.spec.proposed", "serve.spec.accepted",
    "serve.prefill_handoffs", "serve.kv.migrations",
    "serve.kv.migrations_in", "serve.kv.stall_steps",
)


def _extract_serve_cache(registry_snap: Optional[dict]) -> dict:
    """The disaggregated-serving view of a registry snapshot: flat
    prefix/speculation/migration scalars, per-hop ``comm.kv.bytes``,
    and the per-replica stall attribution
    (``serve.kv.stall_steps_by{replica}``)."""
    if not registry_snap:
        return {}
    gauges = registry_snap.get("gauges") or {}
    counters = registry_snap.get("counters") or {}
    view: dict = {}
    for key in _SERVE_CACHE_GAUGES:
        if key in gauges:
            view[key] = float(gauges[key])
    for key in _SERVE_CACHE_COUNTERS:
        if key in counters:
            view[key] = float(counters[key])
    kv_bytes: Dict[str, float] = {}
    stall_by: Dict[str, float] = {}
    for key, v in counters.items():
        if key.startswith("comm.kv.bytes{hop="):
            kv_bytes[key[len("comm.kv.bytes{hop="):-1]] = float(v)
        elif key.startswith("serve.kv.stall_steps_by{replica="):
            stall_by[key[len("serve.kv.stall_steps_by{replica="):-1]] = \
                float(v)
    if kv_bytes:
        view["kv_bytes"] = kv_bytes
    if stall_by:
        view["stall_steps_by_replica"] = stall_by
    return view


class FlightRecorder:
    """Bounded in-memory ring of recent framework events."""

    def __init__(self, capacity: Optional[int] = None,
                 snapshot_every: Optional[int] = None) -> None:
        if capacity is None:
            capacity = _env_int("HOROVOD_FLIGHT_RECORDER_EVENTS",
                                DEFAULT_EVENTS)
        if snapshot_every is None:
            snapshot_every = _env_int("HOROVOD_FLIGHT_SNAPSHOT_EVERY",
                                      DEFAULT_SNAPSHOT_EVERY)
        self.capacity = max(0, int(capacity))
        self.snapshot_every = max(0, int(snapshot_every))
        self._lock = threading.Lock()
        self._ring: "collections.deque" = collections.deque(
            maxlen=self.capacity or 1)
        self._seq = 0
        self._since_snapshot = 0
        self._dump_seq = 0

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    # -- recording (any thread) ----------------------------------------

    def record(self, name: str, ph: str = "i", *, tid: str = "main",
               ts: Optional[float] = None,
               args: Optional[dict] = None) -> None:
        """Append one event. ``ts`` is the emitter's own clock (the
        Timeline's relative µs for tapped events); every entry also gets
        a wall-clock stamp so dumps from different ranks join on one
        axis."""
        if not self.enabled:
            return
        ev = {"name": name, "ph": ph, "tid": tid, "wall": time.time()}
        if ts is not None:
            ev["ts"] = ts
        if args:
            ev["args"] = args
        with self._lock:
            ev["seq"] = self._seq
            self._seq += 1
            self._ring.append(ev)
            self._since_snapshot += 1
            take_snap = (self.snapshot_every > 0
                         and self._since_snapshot >= self.snapshot_every)
            if take_snap:
                self._since_snapshot = 0
        if take_snap:
            self._record_registry_snapshot()

    def tap(self, ev: dict) -> None:
        """Mirror one Timeline event into the ring (called from
        ``Timeline.emit``). Copies — the writer thread serializes the
        original dict and must not see the wall/seq stamps."""
        if not self.enabled:
            return
        self.record(str(ev.get("name", "")), str(ev.get("ph", "i")),
                    tid=str(ev.get("tid", "main")), ts=ev.get("ts"),
                    args=ev.get("args"))

    def _record_registry_snapshot(self) -> None:
        try:
            from . import registry as _registry

            snap = _registry.default_registry().snapshot()
        except Exception:
            return
        ev = {"name": "FLIGHT:SNAPSHOT", "ph": "i", "tid": "flight",
              "wall": time.time(),
              "args": {"counters": snap["counters"],
                       "gauges": snap["gauges"]}}
        with self._lock:
            ev["seq"] = self._seq
            self._seq += 1
            self._ring.append(ev)

    def mark_step(self, step, phases: Optional[dict] = None) -> None:
        """Record one completed training step (the marker
        ``scripts/postmortem.py`` derives the last-common-step and the
        divergence point from)."""
        args: Dict[str, object] = {}
        if step is not None:
            args["step"] = int(step)
        if phases:
            args["phases_ms"] = {k: round(float(v), 3)
                                 for k, v in phases.items()}
        self.record("FLIGHT:STEP", tid="flight", args=args)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self._since_snapshot = 0

    # -- dumping --------------------------------------------------------

    def build_dump(self, reason: str,
                   extra: Optional[dict] = None) -> dict:
        """The dump payload: ring + registry + in-flight ops + straggler
        history, crc32-stamped over the canonical event serialization."""
        events = self.events()
        registry_snap: Optional[dict] = None
        try:
            from . import registry as _registry

            registry_snap = _registry.default_registry().snapshot()
        except Exception:
            pass
        in_flight: List[str] = []
        stalled: List[dict] = []
        try:
            from . import stall as _stall

            insp = _stall.stall_inspector()
            in_flight = insp.in_flight()
            stalled = insp.stalled()
        except Exception:
            pass
        straggler_history: List[dict] = []
        try:
            from . import straggler as _straggler

            straggler_history = _straggler.straggler_detector().history()
        except Exception:
            pass
        expert_load = _extract_expert_load(registry_snap)
        serve_cache = _extract_serve_cache(registry_snap)
        payload = json.dumps(events, sort_keys=True).encode()
        dump = {
            "version": DUMP_VERSION,
            "kind": "flight_record",
            "reason": reason,
            "ts": time.time(),
            "identity": _identity(),
            "events": events,
            "events_crc32": f"crc32:{zlib.crc32(payload) & 0xFFFFFFFF:08x}",
            "registry": registry_snap,
            "in_flight": in_flight,
            "stalled": stalled,
            "straggler": straggler_history,
        }
        if expert_load:
            # Per-expert load (docs/moe.md): the compact {expert: tokens}
            # view of the serve.expert_tokens/moe.expert_tokens metrics,
            # so scripts/postmortem.py can name a hot expert without
            # re-deriving it from raw histogram buckets.
            dump["expert_load"] = expert_load
        if serve_cache:
            # Disaggregated-serving health (docs/serving.md): compact
            # prefix-cache / speculative-acceptance / KV-migration view,
            # including the per-replica stall attribution postmortem
            # uses to name a migration-stalled replica.
            dump["serve_cache"] = serve_cache
        if extra:
            dump["extra"] = extra
        return dump

    def dump(self, reason: str = "explicit", *,
             path: Optional[str] = None,
             directory: Optional[str] = None,
             extra: Optional[dict] = None) -> Optional[str]:
        """Write one dump atomically (tmp → ``os.replace``). Returns the
        committed path, or None when recording is disabled or no
        destination is configured (``path`` > ``directory`` >
        ``HOROVOD_FLIGHT_RECORDER_DIR``). Never raises: the dump runs on
        crash paths where a second failure must not mask the first."""
        if not self.enabled:
            return None
        try:
            if path is None:
                directory = directory or os.environ.get(
                    "HOROVOD_FLIGHT_RECORDER_DIR") or None
                if not directory:
                    return None
                os.makedirs(directory, exist_ok=True)
                ident = _identity()
                tag = (f"rank{ident['rank']}" if ident["rank"] >= 0
                       else (f"{ident['hostname']}-{ident['local_rank']}"
                             if ident["hostname"] else "driver"))
                with self._lock:
                    seq = self._dump_seq
                    self._dump_seq += 1
                path = os.path.join(
                    directory,
                    f"{DUMP_PREFIX}{tag}_pid{os.getpid()}_{seq:03d}.json")
            dump = self.build_dump(reason, extra=extra)
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(dump, f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            return path
        except Exception:
            return None


# ---------------------------------------------------------------------------
# Process-global recorder + module-level recording shortcuts (what the
# framework call sites use — cheap no-ops when the ring is disabled).
# ---------------------------------------------------------------------------

_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def recorder() -> FlightRecorder:
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    return _recorder


#: Package-level accessor name (``hvd.monitor.flight_recorder()``).
def flight_recorder() -> FlightRecorder:
    return recorder()


def record(name: str, ph: str = "i", *, tid: str = "main",
           ts: Optional[float] = None, args: Optional[dict] = None) -> None:
    recorder().record(name, ph, tid=tid, ts=ts, args=args)


def instant(name: str, *, tid: str = "main",
            args: Optional[dict] = None) -> None:
    recorder().record(name, "i", tid=tid, args=args)


def tap(ev: dict) -> None:
    recorder().tap(ev)


def mark_step(step, phases: Optional[dict] = None) -> None:
    recorder().mark_step(step, phases)


def dump_flight_record(path: Optional[str] = None,
                       reason: str = "explicit",
                       extra: Optional[dict] = None) -> Optional[str]:
    """Dump the flight record now (``hvd.dump_flight_record()``). With no
    ``path`` the dump lands in ``HOROVOD_FLIGHT_RECORDER_DIR`` (None is
    returned when neither is set)."""
    return recorder().dump(reason, path=path, extra=extra)


def _reset_for_tests() -> None:
    global _recorder, _armed
    with _recorder_lock:
        _recorder = None
    _armed = False
    with _sigterm_hook_lock:
        del _sigterm_hooks[:]


# ---------------------------------------------------------------------------
# Crash-path arming: excepthook chain, SIGTERM, faulthandler. Installed
# once per process by lifecycle.start_from_env() when the dump dir is
# configured (there is nowhere to dump otherwise).
# ---------------------------------------------------------------------------

_armed = False
_prev_excepthook = None
_prev_sigterm = None
_faulthandler_file = None

# Callables run (each guarded) at the TOP of the SIGTERM handler, before
# the checkpoint-writer drain and the flight dump. The resilience
# supervisor registers its deadline-budgeted priority snapshot here: the
# ordering contract is snapshot → drain → dump → re-deliver, so the
# flight record includes the snapshot's RESILIENCE:PREEMPT event and the
# process never dies holding a torn half-written commit.
_sigterm_hooks: list = []
_sigterm_hook_lock = threading.Lock()


def register_sigterm_hook(fn) -> None:
    """Run ``fn()`` on SIGTERM before the flight dump (idempotent)."""
    with _sigterm_hook_lock:
        if fn not in _sigterm_hooks:
            _sigterm_hooks.append(fn)


def unregister_sigterm_hook(fn) -> None:
    with _sigterm_hook_lock:
        if fn in _sigterm_hooks:
            _sigterm_hooks.remove(fn)


def _flight_excepthook(exc_type, exc, tb):
    try:
        recorder().dump("exception", extra={
            "exc_type": getattr(exc_type, "__name__", str(exc_type)),
            "exc": str(exc)[:500]})
    except Exception:
        pass
    hook = _prev_excepthook or sys.__excepthook__
    hook(exc_type, exc, tb)


def _flight_sigterm(signum, frame):
    import signal

    # 1. Pre-dump hooks (e.g. the supervisor's priority snapshot) — each
    #    guarded so one bad hook can't cost the dump or the drain.
    with _sigterm_hook_lock:
        hooks = list(_sigterm_hooks)
    for fn in hooks:
        try:
            fn()
        except Exception:
            pass
    # 2. Quiesce in-flight checkpoint commits: an AsyncWriter caught
    #    mid-write must land its manifest before we re-deliver the
    #    signal, or the grace window ends with a torn commit the restore
    #    path would silently skip. Budgeted — a wedged disk can't eat
    #    the whole grace period.
    try:
        from ..checkpoint import writer as _ckpt_writer

        budget = float(os.environ.get(
            "HOROVOD_SIGTERM_DRAIN_SECS", "10"))
        _ckpt_writer.drain_all(timeout=budget)
    except Exception:
        pass
    # 3. The black box itself.
    try:
        recorder().dump("sigterm")
    except Exception:
        pass
    # Preserve delivery semantics: restore whatever handler we displaced
    # and re-raise, so the process still dies of SIGTERM (exit 143) — or
    # runs the application's own handler — exactly as before arming.
    prev = _prev_sigterm
    if callable(prev):
        prev(signum, frame)
        return
    signal.signal(signal.SIGTERM,
                  prev if prev is not None else signal.SIG_DFL)
    os.kill(os.getpid(), signal.SIGTERM)


def arm(directory: Optional[str] = None) -> bool:
    """Install the crash-path dump handlers (idempotent). Returns True
    when armed. ``directory`` overrides HOROVOD_FLIGHT_RECORDER_DIR for
    the faulthandler sidecar file; the dump destination itself is
    resolved per dump."""
    global _armed, _prev_excepthook, _prev_sigterm, _faulthandler_file
    directory = directory or os.environ.get(
        "HOROVOD_FLIGHT_RECORDER_DIR") or None
    if not directory or not recorder().enabled:
        return False
    if _armed:
        return True
    _armed = True
    os.makedirs(directory, exist_ok=True)
    _prev_excepthook = sys.excepthook
    sys.excepthook = _flight_excepthook
    # faulthandler: a native crash (SIGSEGV/SIGABRT) cannot run Python,
    # but its traceback can still land beside the dumps.
    try:
        import faulthandler

        ident = _identity()
        tag = (f"rank{ident['rank']}" if ident["rank"] >= 0
               else f"pid{os.getpid()}")
        _faulthandler_file = open(
            os.path.join(directory, f"fault_{tag}_pid{os.getpid()}.txt"),
            "w")
        faulthandler.enable(file=_faulthandler_file)
    except Exception:
        pass
    # SIGTERM: main-thread only (signal module restriction); a worker
    # being preempted/killed still leaves its black box.
    try:
        import signal

        if threading.current_thread() is threading.main_thread():
            _prev_sigterm = signal.getsignal(signal.SIGTERM)
            signal.signal(signal.SIGTERM, _flight_sigterm)
    except Exception:
        pass
    return True


def arm_from_env(config=None) -> bool:
    """lifecycle.start_from_env entry: arm when a dump dir is configured
    (Config.flight_recorder_dir / HOROVOD_FLIGHT_RECORDER_DIR)."""
    directory = None
    if config is not None:
        directory = getattr(config, "flight_recorder_dir", None)
    return arm(directory)
