"""Typed metrics registry: counters, gauges, log2-bucket histograms.

Design notes
------------
* **Stdlib-only module.** The launcher/driver processes record metrics
  too (same contract as :mod:`horovod_tpu.common.counters`), so importing
  this module must not drag jax/framework state along; the one collective
  call (:meth:`MetricsRegistry.aggregate`) imports lazily.
* **Monotone counters.** ``Counter.inc`` rejects negative deltas, so a
  chaotic run can assert ``counters stay monotone`` as an invariant.
* **Fixed log2 buckets.** Every histogram shares the same 32-bucket
  layout (upper bounds ``2^0 .. 2^30`` plus +Inf), so cross-rank
  aggregation is a pure element-wise sum — no bucket-boundary
  renegotiation, and one histogram is 34 numbers on the wire.
* **Process-lifetime values.** The registry is never cleared by
  ``hvd.shutdown()`` — an elastic job reads monotone counters across
  world incarnations (:mod:`horovod_tpu.common.counters` keeps the
  per-incarnation view).
* **Cross-rank aggregation piggybacks on the collective stack**: one
  fused eager allreduce of the flat value vector per call, explicitly
  named (so it never perturbs the auto-name alignment of user
  collectives), run from the reporter thread — off the step's critical
  path.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

#: Histogram bucket upper bounds: 2^0 .. 2^30, then +Inf. Fixed for every
#: histogram so aggregation is element-wise and the wire layout is static.
LOG2_BUCKET_BOUNDS: Tuple[float, ...] = tuple(
    float(2 ** i) for i in range(31)) + (float("inf"),)
NUM_BUCKETS = len(LOG2_BUCKET_BOUNDS)


def _bucket_index(value: float) -> int:
    """Index of the first bucket whose upper bound is >= value."""
    if value <= 1.0:
        return 0
    # bit_length of the ceil'd integer is a branch-free log2 ceiling.
    v = int(value) if float(value).is_integer() else int(value) + 1
    idx = max(0, (v - 1).bit_length())
    return min(idx, NUM_BUCKETS - 1)


def _key(name: str, labels: Dict[str, str]) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


class Counter:
    """Monotone counter. ``inc(n)`` with ``n >= 0`` only."""

    kind = "counter"

    def __init__(self, registry: "MetricsRegistry", key: str) -> None:
        self._registry = registry
        self.key = key
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.key} cannot decrease (n={n})")
        if not self._registry.enabled:
            return
        with self._registry._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-value gauge (queue depth, hidden fraction, replica count)."""

    kind = "gauge"

    def __init__(self, registry: "MetricsRegistry", key: str) -> None:
        self._registry = registry
        self.key = key
        self._value = 0.0

    def set(self, v: float) -> None:
        if not self._registry.enabled:
            return
        with self._registry._lock:
            self._value = float(v)

    def add(self, n: float) -> None:
        if not self._registry.enabled:
            return
        with self._registry._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Histogram over the fixed :data:`LOG2_BUCKET_BOUNDS` layout."""

    kind = "histogram"

    def __init__(self, registry: "MetricsRegistry", key: str) -> None:
        self._registry = registry
        self.key = key
        self.counts: List[int] = [0] * NUM_BUCKETS
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        if not self._registry.enabled:
            return
        with self._registry._lock:
            self.counts[_bucket_index(value)] += 1
            self.sum += float(value)
            self.count += 1

    def quantile_bound(self, q: float) -> Optional[float]:
        """Upper bucket bound at or above quantile ``q`` (None if empty)."""
        if self.count == 0:
            return None
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return LOG2_BUCKET_BOUNDS[i]
        return LOG2_BUCKET_BOUNDS[-1]


class MetricsRegistry:
    """Thread-safe name→metric table with typed get-or-create access."""

    def __init__(self, enabled: Optional[bool] = None) -> None:
        self._lock = threading.RLock()
        self._metrics: Dict[str, object] = {}
        if enabled is None:
            enabled = os.environ.get("HOROVOD_METRICS_DISABLE", "") not in (
                "1", "true", "yes", "on")
        self.enabled = enabled
        self._aggregate_seq = 0

    # -- typed get-or-create -------------------------------------------

    def _get(self, cls, name: str, labels: Dict[str, str]):
        key = _key(name, labels)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(self, key)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {key!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str, **labels: str) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels: str) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, **labels: str) -> Histogram:
        return self._get(Histogram, name, labels)

    def metrics(self) -> Dict[str, object]:
        with self._lock:
            return dict(self._metrics)

    # -- snapshots ------------------------------------------------------

    def snapshot(self, prefix: str = "") -> dict:
        """Point-in-time snapshot: one dict per metric kind, keys are
        ``name{label=value,...}`` strings — the JSONL sink's line schema
        and the shape ``scripts/obs_report.py`` consumes."""
        with self._lock:
            counters = {k: m.value for k, m in self._metrics.items()
                        if isinstance(m, Counter) and k.startswith(prefix)}
            gauges = {k: m.value for k, m in self._metrics.items()
                      if isinstance(m, Gauge) and k.startswith(prefix)}
            hists = {k: {"counts": list(m.counts), "sum": m.sum,
                         "count": m.count}
                     for k, m in self._metrics.items()
                     if isinstance(m, Histogram) and k.startswith(prefix)}
        return {
            "ts": time.time(),
            "kind": "metrics",
            "world": 1,
            "counters": counters,
            "gauges": gauges,
            "histograms": hists,
        }

    # -- cross-rank aggregation ----------------------------------------

    def _flat_layout(self, snap: dict) -> Tuple[List[str], List[float]]:
        keys: List[str] = []
        vals: List[float] = []
        for k in sorted(snap["counters"]):
            keys.append(f"c:{k}")
            vals.append(snap["counters"][k])
        for k in sorted(snap["gauges"]):
            keys.append(f"g:{k}")
            vals.append(snap["gauges"][k])
        for k in sorted(snap["histograms"]):
            h = snap["histograms"][k]
            keys.append(f"h:{k}")
            vals.extend(h["counts"])
            vals.append(h["sum"])
            vals.append(h["count"])
        return keys, vals

    def aggregate(self, prefix: str = "") -> dict:
        """Cross-rank SUM of the snapshot as ONE fused eager allreduce.

        Piggybacks on the existing collective stack (the native
        controller's process-world data plane): the whole registry rides
        a single flat float64 vector. The collective name carries the
        vector length and a schema digest, so ranks whose metric sets
        diverged fail loudly (name/shape mismatch in the negotiation)
        instead of silently misaligning values. Identity (world of one)
        before ``hvd.init()`` or under single-controller SPMD.

        Gauges aggregate as sums too (one wire op); the returned
        ``world`` field lets consumers divide for means.
        """
        snap = self.snapshot(prefix=prefix)
        try:
            from ..common import basics
            from ..ops import collective_ops

            if not basics.is_initialized():
                return snap
            world = collective_ops._eager_world()
        except Exception:
            return snap
        snap["world"] = world
        if world <= 1:
            return snap
        import hashlib

        import numpy as np

        keys, vals = self._flat_layout(snap)
        digest = hashlib.md5("|".join(keys).encode()).hexdigest()[:10]
        with self._lock:
            seq = self._aggregate_seq
            self._aggregate_seq += 1
        vec = np.asarray(vals, dtype=np.float64)
        red = collective_ops.allreduce(
            vec, op=collective_ops.ReduceOp.SUM,
            name=f"monitor.aggregate.{seq}.{len(vec)}.{digest}")
        red = np.asarray(red)
        out = dict(snap)
        counters, gauges, hists = {}, {}, {}
        i = 0
        for key in keys:
            tag, k = key.split(":", 1)
            if tag == "c":
                counters[k] = float(red[i]); i += 1
            elif tag == "g":
                gauges[k] = float(red[i]); i += 1
            else:
                counts = [int(x) for x in red[i:i + NUM_BUCKETS]]
                i += NUM_BUCKETS
                hists[k] = {"counts": counts, "sum": float(red[i]),
                            "count": int(red[i + 1])}
                i += 2
        out["counters"], out["gauges"], out["histograms"] = (
            counters, gauges, hists)
        return out


_default: Optional[MetricsRegistry] = None
_default_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = MetricsRegistry()
    return _default


def metrics_enabled() -> bool:
    return default_registry().enabled


# Module-level shortcuts against the default registry (the handles are
# cached by hot call sites; these are the cold-path conveniences).

def counter(name: str, **labels: str) -> Counter:
    return default_registry().counter(name, **labels)


def gauge(name: str, **labels: str) -> Gauge:
    return default_registry().gauge(name, **labels)


def histogram(name: str, **labels: str) -> Histogram:
    return default_registry().histogram(name, **labels)
