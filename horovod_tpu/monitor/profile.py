"""Host/device trace correlation: ``hvd.profile_window``.

The Timeline records *host-side* framework events; the platform profiler
(``jax.profiler``) records *device* activity. This module brackets the
two so one training window can be read across both traces:

* :func:`profile_window` starts a ``jax.profiler`` trace and marks the
  window on the Timeline (``PROFILE:WINDOW`` span + ``PROFILE:START``/
  ``PROFILE:STOP`` instants carrying the logdir, so a Timeline reader
  can find the matching device trace);
* :meth:`ProfileWindow.steps` yields each step inside a
  ``jax.profiler.StepTraceAnnotation`` (the device trace's step marker —
  the same annotation ``DistributedOptimizer`` and the serve engine use)
  and a ``PROFILE:STEP`` Timeline span, and feeds the host wall time of
  every step into the ``profile.step_ms`` histogram.
"""

from __future__ import annotations

import contextlib
import os
import tempfile
import time
from typing import Iterator, Optional

from . import registry as _registry


def _timeline():
    try:
        from ..common import basics

        return basics._state.timeline
    except Exception:  # pragma: no cover - interpreter teardown
        return None


class ProfileWindow:
    """Handle yielded by :func:`profile_window`."""

    def __init__(self, num_steps: int, logdir: str) -> None:
        self.num_steps = num_steps
        self.logdir = logdir
        self.step_times_ms = []

    def steps(self) -> Iterator[int]:
        """Iterate the window's steps: run exactly one training step per
        yielded index — each is device-marked (StepTraceAnnotation) and
        Timeline-bracketed (``PROFILE:STEP``)."""
        import jax

        tl = _timeline()
        hist = _registry.histogram("profile.step_ms")
        for i in range(self.num_steps):
            t0 = time.perf_counter()
            if tl is not None:
                tl.begin("profile", "PROFILE:STEP")
            try:
                with jax.profiler.StepTraceAnnotation("hvd_step",
                                                      step_num=i):
                    yield i
            finally:
                if tl is not None:
                    tl.end("profile", "PROFILE:STEP")
                dt_ms = (time.perf_counter() - t0) * 1e3
                self.step_times_ms.append(dt_ms)
                hist.observe(dt_ms)


@contextlib.contextmanager
def profile_window(num_steps: int, logdir: Optional[str] = None):
    """Bracket a ``jax.profiler`` trace with the Timeline.

    Usage::

        with hvd.profile_window(5) as win:
            for _ in win.steps():
                params, opt_state, loss = train_step(...)
        # win.logdir now holds the device trace; the Timeline carries the
        # matching PROFILE:WINDOW span and per-step PROFILE:STEP spans.

    ``logdir`` defaults to ``HOROVOD_PROFILE_DIR`` or a fresh temp dir.
    """
    import jax

    logdir = (logdir or os.environ.get("HOROVOD_PROFILE_DIR")
              or tempfile.mkdtemp(prefix="hvd-profile-"))
    tl = _timeline()
    win = ProfileWindow(num_steps, logdir)
    if tl is not None:
        tl.begin("profile", "PROFILE:WINDOW")
        tl.instant("PROFILE:START", tid="profile",
                   args={"logdir": logdir, "num_steps": num_steps})
    _registry.counter("profile.windows").inc()
    jax.profiler.start_trace(logdir)
    try:
        yield win
    finally:
        jax.profiler.stop_trace()
        if tl is not None:
            tl.instant("PROFILE:STOP", tid="profile",
                       args={"logdir": logdir,
                             "steps_run": len(win.step_times_ms)})
            tl.end("profile", "PROFILE:WINDOW")
