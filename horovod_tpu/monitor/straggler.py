"""Cross-rank straggler attribution: per-phase skew + link-health scores.

The MPI-characterization line of work (PAPERS.md: profiling-driven
per-phase/per-link behavior) and T3's transparent tracking both show that
per-step attribution is cheap enough to leave on. This module is that
attribution for the monitor layer: every rank records how long each
*phase* of its step took —

======================  ====================================================
``compute``              step time not attributable to wire/ckpt/bubble
``wire.ici``             intra-host collective wire (modeled on the
                         compiled path, measured on the eager path)
``wire.dcn``             cross-host wire (eager collectives charge here:
                         the process-world data plane is DCN-class TCP)
``wire.pod``             cross-pod wire of a 3-level mesh
``pp_bubble``            pipeline idle (bubble fraction × step)
``ckpt``                 checkpoint save stall visible to the trainer
======================  ====================================================

— as ``straggler.phase_ms{phase,rank}`` gauges where each rank writes
ONLY its own rank's entries (all ranks pre-create the full matrix, so
every rank's registry schema is identical). The values therefore ride
the registry's existing ONE-fused-allreduce aggregation unchanged: a SUM
over ranks reconstructs the full per-rank matrix, because every other
rank contributed zero. No second collective, no new wire protocol.

:meth:`StragglerDetector.detect` runs median/MAD outlier detection over
that matrix per phase and, for each outlier, emits a rank-and-phase-
attributed diagnosis: a ``straggler.detected{rank,phase}`` counter, a
``step.skew_ms{phase}`` gauge (max − median), a ``STRAGGLER:<PHASE>``
timeline/flight instant, and a history entry that rides the flight dump
(docs/observability.md).

**Link health** closes the loop with the PR-11 cost model: every
``observe_wire(hop, bytes, measured_ms)`` scores the hop as measured /
predicted wire-ms for *this rank's* traffic (``plan/cost``'s resolved —
calibrated-else-static — model). A persistent one-rank drift (EWMA above
``HOROVOD_LINK_DRIFT_GATE`` for ``patience`` consecutive observations)
flags a degraded link: ``straggler.link_degraded{hop}`` counter,
``link.health{hop}`` gauge, a ``STRAGGLER:LINK_DEGRADED`` instant, and a
log line recommending a :func:`~horovod_tpu.plan.calibrate.
calibrate_links` recalibration (docs/cost-model.md).

Stdlib-only at import, like the registry; the cost-model lookup is lazy
and never raises into the step.
"""

from __future__ import annotations

import collections
import logging
import os
import re
import threading
import time
from typing import Dict, List, Optional

from . import flight as _flight
from . import registry as _registry

logger = logging.getLogger("horovod_tpu.straggler")

#: Canonical phase vocabulary (docs/observability.md). record_phase
#: accepts any name, but detection/reporting tables order these first.
#: ``wire.a2a`` is the MoE dispatch/combine wire (docs/moe.md) — fed by
#: bench's ``--moe`` leg so a straggling expert group attributes to its
#: exchange phase, separate from the gradient wire's hop classes.
#: ``wire.kv`` is disaggregated serving's KV-migration wire
#: (docs/serving.md) — a replica stuck in it is blocked on a
#: prefill→decode handoff, not on compute. ``compile`` is
#: lowering+XLA-compile time paid through the executable cache
#: (docs/compile.md) — a rank stuck there missed the cache others hit.
PHASES = ("compute", "wire.ici", "wire.dcn", "wire.pod", "wire.a2a",
          "wire.kv", "pp_bubble", "ckpt", "compile")

HOPS = ("ici", "dcn", "pod")

#: Consistency scale: MAD × 1.4826 estimates the standard deviation of a
#: normal distribution, so the gate is in familiar sigma units.
MAD_SIGMA = 1.4826

_PHASE_KEY_RE = re.compile(
    r"^straggler\.phase_ms\{phase=([^,}]+),rank=(\d+)\}$")
_STEPS_KEY_RE = re.compile(r"^straggler\.steps\{rank=(\d+)\}$")


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    if v in (None, ""):
        return default
    try:
        return float(v)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    if v in (None, ""):
        return default
    try:
        return int(v)
    except ValueError:
        return default


def _world_and_rank():
    try:
        from ..common import basics

        if basics.is_initialized():
            return int(basics.size()), int(basics.rank())
    except Exception:
        pass
    return 1, 0


def _timeline_instant(name: str, args: dict) -> None:
    """STRAGGLER:* instants go to the Timeline when one is attached (the
    flight ring taps it there) and straight to the flight ring when not —
    the forensic trail exists either way."""
    tl = None
    try:
        from ..common import basics

        tl = basics._state.timeline
    except Exception:
        pass
    if tl is not None:
        tl.instant(name, tid="stragglers", args=args)
    else:
        _flight.instant(name, tid="stragglers", args=args)


class StragglerDetector:
    """Per-rank phase recording + cross-rank median/MAD detection.

    ``mad_gate`` is the outlier threshold in MAD-sigmas above the
    cross-rank median; ``min_skew_ms`` is the absolute floor below which
    skew is never flagged (guards the MAD≈0 case of near-identical
    ranks); ``link_drift_gate`` is the measured/predicted wire-ms ratio
    past which a hop counts as drifting; ``patience`` consecutive
    drifting observations flag it degraded.
    """

    def __init__(self, registry: Optional[_registry.MetricsRegistry] = None,
                 *, world: Optional[int] = None, rank: Optional[int] = None,
                 mad_gate: Optional[float] = None,
                 min_skew_ms: Optional[float] = None,
                 link_drift_gate: Optional[float] = None,
                 patience: Optional[int] = None,
                 history_len: int = 256) -> None:
        self._registry = registry or _registry.default_registry()
        self._world_override = world
        self._rank_override = rank
        self.mad_gate = (_env_float("HOROVOD_STRAGGLER_MAD_GATE", 4.0)
                         if mad_gate is None else float(mad_gate))
        self.min_skew_ms = (_env_float("HOROVOD_STRAGGLER_MIN_SKEW_MS", 5.0)
                            if min_skew_ms is None else float(min_skew_ms))
        self.link_drift_gate = (
            _env_float("HOROVOD_LINK_DRIFT_GATE", 1.5)
            if link_drift_gate is None else float(link_drift_gate))
        self.patience = (_env_int("HOROVOD_LINK_DRIFT_PATIENCE", 3)
                         if patience is None else int(patience))
        self._lock = threading.Lock()
        self._current: Dict[str, float] = {}
        self._step = 0
        self._history: "collections.deque" = collections.deque(
            maxlen=history_len)
        # Link health: per-hop EWMA of measured/predicted + consecutive
        # over-gate observations + degraded latch (warn once per latch).
        self._link_ewma: Dict[str, float] = {}
        self._link_over: Dict[str, int] = {}
        self._link_degraded: Dict[str, bool] = {}

    def _world_rank(self):
        world, rank = _world_and_rank()
        if self._world_override is not None:
            world = self._world_override
        if self._rank_override is not None:
            rank = self._rank_override
        return max(1, int(world)), int(rank)

    # -- per-step phase recording (this rank) ---------------------------

    def record_phase(self, phase: str, ms: float) -> None:
        """Accumulate ``ms`` into the current step's ``phase`` bucket."""
        if ms < 0:
            ms = 0.0
        with self._lock:
            self._current[phase] = self._current.get(phase, 0.0) + float(ms)

    def end_step(self, step: Optional[int] = None) -> Dict[str, float]:
        """Close the current step: publish this rank's phase durations as
        the rank-slotted gauges (pre-creating every rank's slot so all
        registries share one aggregation schema), bump
        ``straggler.steps{rank}``, and mark the step in the flight ring.
        Returns the phase dict."""
        with self._lock:
            phases = dict(self._current)
            self._current.clear()
            step = self._step if step is None else int(step)
            self._step = step + 1
        world, rank = self._world_rank()
        reg = self._registry
        for phase in set(PHASES) | set(phases):
            for r in range(world):
                g = reg.gauge("straggler.phase_ms", phase=phase,
                              rank=str(r))
                if r == rank:
                    g.set(phases.get(phase, 0.0))
        for r in range(world):
            c = reg.counter("straggler.steps", rank=str(r))
            if r == rank:
                c.inc()
        _flight.mark_step(step, phases)
        return phases

    # -- cross-rank detection -------------------------------------------

    @staticmethod
    def _matrix(snapshot: dict):
        """(rank → phase → ms, set of live ranks) from an (aggregated)
        registry snapshot."""
        matrix: Dict[int, Dict[str, float]] = {}
        for key, v in snapshot.get("gauges", {}).items():
            m = _PHASE_KEY_RE.match(key)
            if m:
                phase, r = m.group(1), int(m.group(2))
                matrix.setdefault(r, {})[phase] = float(v)
        live = set()
        for key, v in snapshot.get("counters", {}).items():
            m = _STEPS_KEY_RE.match(key)
            if m and v > 0:
                live.add(int(m.group(1)))
        return matrix, live

    def detect(self, snapshot: Optional[dict] = None,
               aggregate: bool = True) -> List[dict]:
        """One detection pass over the last completed step.

        With no ``snapshot`` the per-rank matrix comes from the
        registry's own fused-allreduce aggregation filtered to the
        straggler family (identity in a world of one); pass the
        reporter's already-aggregated full snapshot to fold detection
        into the existing interval allreduce at zero extra wire. Emits
        the attributed counters/gauges/instants for every outlier and
        returns them."""
        if snapshot is None:
            snapshot = (self._registry.aggregate(prefix="straggler.")
                        if aggregate
                        else self._registry.snapshot(prefix="straggler."))
        matrix, live = self._matrix(snapshot)
        ranks = sorted(r for r in matrix if r in live) if live \
            else sorted(matrix)
        detections: List[dict] = []
        if len(ranks) < 3:
            # With fewer than 3 ranks a median/MAD split cannot name an
            # outlier without guessing; skew gauges still publish below.
            pass
        phases = sorted({p for r in ranks for p in matrix.get(r, {})})
        reg = self._registry
        for phase in phases:
            vals = [matrix[r].get(phase, 0.0) for r in ranks]
            if not vals:
                continue
            med = _median(vals)
            skew = max(vals) - med
            reg.gauge("step.skew_ms", phase=phase).set(skew)
            if len(ranks) < 3:
                continue
            mad = _median([abs(v - med) for v in vals])
            gate = med + max(self.mad_gate * MAD_SIGMA * mad,
                             self.min_skew_ms)
            for r, v in zip(ranks, vals):
                if v <= gate:
                    continue
                det = {"kind": "phase", "rank": r, "phase": phase,
                       "ms": round(v, 3), "median_ms": round(med, 3),
                       "mad_ms": round(mad, 3), "skew_ms": round(v - med, 3),
                       "ts": time.time()}
                detections.append(det)
                reg.counter("straggler.detected", rank=str(r),
                            phase=phase).inc()
                _timeline_instant(
                    f"STRAGGLER:{phase.upper()}",
                    {"rank": r, "phase": phase, "ms": det["ms"],
                     "median_ms": det["median_ms"],
                     "mad_ms": det["mad_ms"]})
                logger.warning(
                    f"straggler detected: rank {r} spent {v:.1f} ms in "
                    f"phase {phase!r} vs cross-rank median {med:.1f} ms "
                    f"(MAD {mad:.1f} ms)")
        with self._lock:
            self._history.extend(detections)
        return detections

    # -- link health ----------------------------------------------------

    def observe_wire(self, hop: str, nbytes: float,
                     measured_ms: float) -> Optional[float]:
        """Score one hop's measured wire time against the cost model's
        prediction for the same traffic. Returns the EWMA ratio (None
        when no prediction is available — pricing must never break the
        step)."""
        if hop not in HOPS or nbytes <= 0 or measured_ms < 0:
            return None
        try:
            from ..plan import cost as _cost

            predicted_ms = _cost.predict_hop_ms(hop, nbytes)
        except Exception:
            return None
        if predicted_ms <= 0:
            return None
        ratio = float(measured_ms) / predicted_ms
        reg = self._registry
        with self._lock:
            prev = self._link_ewma.get(hop)
            ewma = ratio if prev is None else 0.5 * prev + 0.5 * ratio
            self._link_ewma[hop] = ewma
            if ewma > self.link_drift_gate:
                self._link_over[hop] = self._link_over.get(hop, 0) + 1
                recovered = False
            else:
                self._link_over[hop] = 0
                recovered = bool(self._link_degraded.get(hop))
                self._link_degraded[hop] = False
            over = self._link_over[hop]
            newly_degraded = (over >= self.patience
                              and not self._link_degraded.get(hop))
            if newly_degraded:
                self._link_degraded[hop] = True
        reg.gauge("link.health", hop=hop).set(ewma)
        if newly_degraded:
            _, rank = self._world_rank()
            reg.counter("straggler.link_degraded", hop=hop).inc()
            det = {"kind": "link", "rank": rank, "hop": hop,
                   "ratio": round(ewma, 3),
                   "gate": self.link_drift_gate, "ts": time.time()}
            with self._lock:
                self._history.append(det)
            _timeline_instant("STRAGGLER:LINK_DEGRADED",
                              {"rank": rank, "hop": hop,
                               "ratio": det["ratio"],
                               "gate": self.link_drift_gate})
            logger.warning(
                f"link health: {hop} hop measured/predicted wire-ms "
                f"ratio {ewma:.2f} exceeded the drift gate "
                f"{self.link_drift_gate:g} for {over} consecutive "
                f"observations on rank {rank} — the link is degraded or "
                f"the cost model is stale; re-run "
                f"horovod_tpu.plan.calibrate.calibrate_links() to "
                f"recalibrate (docs/cost-model.md)")
        if recovered:
            # The latch cleared: the hop's EWMA dropped back under the
            # gate. The resilience supervisor keys its replan swap-back
            # on this transition.
            reg.counter("straggler.link_recovered", hop=hop).inc()
            _timeline_instant("STRAGGLER:LINK_RECOVERED",
                              {"hop": hop, "ratio": round(ewma, 3),
                               "gate": self.link_drift_gate})
            logger.info(
                f"link health: {hop} hop recovered (EWMA ratio "
                f"{ewma:.2f} back under the gate "
                f"{self.link_drift_gate:g})")
        return ewma

    def link_scores(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._link_ewma)

    def degraded_hops(self) -> Dict[str, float]:
        """{hop: EWMA ratio} for hops whose degraded latch is set."""
        with self._lock:
            return {hop: self._link_ewma.get(hop, 0.0)
                    for hop, flag in self._link_degraded.items() if flag}

    def history(self) -> List[dict]:
        """Detection history (bounded) — rides every flight dump."""
        with self._lock:
            return list(self._history)

    def reset(self) -> None:
        with self._lock:
            self._current.clear()
            self._step = 0
            self._history.clear()
            self._link_ewma.clear()
            self._link_over.clear()
            self._link_degraded.clear()


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


# ---------------------------------------------------------------------------
# Process-global detector (the stall-inspector pattern): framework call
# sites (bench loop, eager collectives, reporter thread) share one.
# ---------------------------------------------------------------------------

_global: Optional[StragglerDetector] = None
_global_lock = threading.Lock()


def straggler_detector() -> StragglerDetector:
    global _global
    if _global is None:
        with _global_lock:
            if _global is None:
                _global = StragglerDetector()
    return _global


def record_phase(phase: str, ms: float) -> None:
    straggler_detector().record_phase(phase, ms)


def end_step(step: Optional[int] = None) -> Dict[str, float]:
    return straggler_detector().end_step(step)


def observe_wire(hop: str, nbytes: float, measured_ms: float):
    return straggler_detector().observe_wire(hop, nbytes, measured_ms)


def record_pp_bubble(idle_ticks: int, ticks: int, step_ms: float,
                     filled_ticks: int = 0,
                     detector: Optional[StragglerDetector] = None) -> float:
    """Attribute this rank's pipeline bubble time to the ``pp_bubble``
    phase (docs/pipeline.md).

    The zero-bubble scheduler exposes its *measured* per-rank idle-tick
    count (``PPSchedule.idle_ticks_per_rank``) and the step loop knows
    how many of those ticks ZeRO-3 flights actually filled
    (``comm.pp.filled_ticks``). A filled tick is wire work hidden in
    the bubble, not lost time, so it must NOT be charged as bubble skew
    — otherwise every rank that successfully overlaps looks like a
    straggler relative to one that could not. This helper charges only
    the *unfilled* remainder::

        ms = step_ms * (idle_ticks - min(idle_ticks, filled_ticks)) / ticks

    On a clean run the schedule's idle ticks are identical across ranks
    (the table is geometry-determined), so the phase is rank-uniform
    and detect() stays silent; genuine cross-rank skew — one rank's
    flights starved so its bubbles went unfilled — surfaces as a
    ``pp_bubble`` outlier with the usual median/MAD gate.

    Returns the charged milliseconds (0.0 when fully filled).
    """
    d = detector or straggler_detector()
    t = max(1, int(ticks))
    idle = max(0, int(idle_ticks))
    filled = min(idle, max(0, int(filled_ticks)))
    ms = float(step_ms) * (idle - filled) / float(t)
    d.record_phase("pp_bubble", ms)
    return ms


def _reset_for_tests() -> None:
    global _global
    with _global_lock:
        _global = None
