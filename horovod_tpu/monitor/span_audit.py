"""Span auditing over Timeline files: B/E balance and phase durations.

One helper for the invariant every span-emitting subsystem must hold —
each ``ph:"B"`` has a matching ``ph:"E"`` on the same tid and depth never
goes negative — plus the per-activity duration accounting that
``scripts/obs_report.py`` turns into the phase-time breakdown. Replaces
the hand-rolled balance loops that used to live in ``tests/test_overlap``
and ``tests/test_serve``.

The event vocabulary is a CHECKED table (:data:`KNOWN_PREFIXES`,
docs/observability.md has the full event table): every family a
subsystem emits is registered here, and ``audit_spans(strict=True)``
fails on an event whose prefix is not — so a typo'd span name (or a new
family someone forgot to document) breaks the span tests instead of
silently skewing a phase breakdown.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

#: The unified Timeline event vocabulary: every ``PREFIX:`` an event
#: family may use (plus the colon-free reference-parity cycle marker).
#: One row per family in docs/observability.md's event table; new
#: subsystems register here FIRST.
KNOWN_PREFIXES = frozenset({
    "FAULT",       # fault/retry counter instants (common/counters.py)
    "AUTOTUNE",    # tuning-session lifecycle (autotune/driver.py)
    "OVERLAP",     # streamed bucket collectives (docs/overlap.md)
    "SERVE",       # generation-engine events (docs/serving.md)
    "STALL",       # StallInspector instants (monitor/stall.py)
    "METRIC",      # TimelineSink registry mirrors (monitor/sinks.py)
    "PROFILE",     # hvd.profile_window brackets (monitor/profile.py)
    "CYCLE_START",  # HOROVOD_TIMELINE_MARK_CYCLES (reference parity)
    "CKPT",        # async checkpoint lifecycle (docs/checkpoint.md)
    "FUSED",       # fused Pallas kernel spans (docs/fused-kernels.md)
    "PP",          # pipeline sends + schedule slots (docs/pipeline.md)
    "MOE",         # expert dispatch/combine exchanges (docs/moe.md)
    "STRAGGLER",   # skew / link-health diagnoses (monitor/straggler.py)
    "FLIGHT",      # flight-recorder marks (monitor/flight.py)
    "RESILIENCE",  # supervisor policy actions (resilience/supervisor.py)
    "COMPILE",     # executable-cache lower/compile/hit (docs/compile.md)
})


def event_prefix(name: str) -> str:
    """The vocabulary prefix of an event name (the part before the
    first colon; colon-free names are their own prefix)."""
    return name.split(":", 1)[0] if ":" in name else name


class SpanImbalanceError(AssertionError):
    """A tid's B/E events do not balance (or depth went negative)."""


class UnknownSpanPrefixError(AssertionError):
    """``strict=True``: an event's prefix is not in the checked
    vocabulary table (:data:`KNOWN_PREFIXES`)."""


@dataclass
class SpanAudit:
    """Result of :func:`audit_spans`."""

    #: spans fully closed, per tid
    spans_per_tid: Dict[str, int] = field(default_factory=dict)
    #: final (unclosed) depth per tid — all zero when balanced
    open_depth: Dict[str, int] = field(default_factory=dict)
    #: summed span duration (µs) per activity name
    duration_us: Dict[str, float] = field(default_factory=dict)
    #: span count per activity name
    count: Dict[str, int] = field(default_factory=dict)
    #: instant (ph:"i") events seen, per name
    instants: Dict[str, int] = field(default_factory=dict)

    @property
    def balanced(self) -> bool:
        return not any(self.open_depth.values())

    @property
    def total_spans(self) -> int:
        return sum(self.spans_per_tid.values())

    def by_phase(self) -> Dict[str, float]:
        """Duration (µs) grouped by the ``PREFIX:`` before the first
        colon (``OVERLAP``, ``SERVE``, ``PROFILE``, ...)."""
        out: Dict[str, float] = {}
        for name, us in self.duration_us.items():
            phase = name.split(":", 1)[0] if ":" in name else name
            out[phase] = out.get(phase, 0.0) + us
        return out


def load_events(source: Union[str, list]) -> list:
    """Timeline events from a path or an already-loaded list."""
    if isinstance(source, str):
        with open(source) as f:
            return json.load(f)
    return list(source)


def audit_spans(source: Union[str, list], prefix: Optional[str] = None,
                require_balanced: bool = True,
                require_spans: bool = False,
                strict: bool = False) -> SpanAudit:
    """Audit B/E balance per tid over a Timeline file (or event list).

    ``prefix`` restricts the audit to events whose name starts with it
    (e.g. ``"OVERLAP"``, ``"SERVE:"``). With ``require_balanced`` (the
    default) raises :class:`SpanImbalanceError` naming the offending tid
    when any depth goes negative or fails to return to zero;
    ``require_spans`` additionally demands at least one matching span
    closed (guards against a filter that silently matched nothing).
    ``strict`` checks EVERY scanned event (before the ``prefix``
    filter) against the vocabulary table, raising
    :class:`UnknownSpanPrefixError` on the first name whose prefix is
    not in :data:`KNOWN_PREFIXES` — the mode framework span tests run
    in, so the vocabulary stays exhaustive.
    """
    events = load_events(source)
    if strict:
        for ev in events:
            name = str(ev.get("name", ""))
            p = event_prefix(name)
            if p not in KNOWN_PREFIXES:
                raise UnknownSpanPrefixError(
                    f"event {name!r} uses unknown prefix {p!r}: not in "
                    f"the checked vocabulary table "
                    f"(monitor/span_audit.KNOWN_PREFIXES — register new "
                    f"event families there and in docs/observability.md)")
    audit = SpanAudit()
    stacks: Dict[str, List[Tuple[str, float]]] = {}
    for ev in events:
        name = str(ev.get("name", ""))
        if prefix is not None and not name.startswith(prefix):
            continue
        tid = str(ev.get("tid", "main"))
        ph = ev.get("ph")
        if ph == "B":
            stacks.setdefault(tid, []).append((name, ev.get("ts", 0.0)))
        elif ph == "E":
            stack = stacks.setdefault(tid, [])
            if not stack:
                raise SpanImbalanceError(
                    f"tid {tid!r}: 'E' for {name!r} with no open 'B' "
                    f"(negative depth)")
            b_name, b_ts = stack.pop()
            audit.spans_per_tid[tid] = audit.spans_per_tid.get(tid, 0) + 1
            audit.duration_us[b_name] = (
                audit.duration_us.get(b_name, 0.0)
                + max(0.0, ev.get("ts", b_ts) - b_ts))
            audit.count[b_name] = audit.count.get(b_name, 0) + 1
        elif ph == "i":
            audit.instants[name] = audit.instants.get(name, 0) + 1
    for tid, stack in stacks.items():
        audit.open_depth[tid] = len(stack)
        if stack and require_balanced:
            raise SpanImbalanceError(
                f"tid {tid!r}: {len(stack)} span(s) never closed "
                f"(first open: {stack[0][0]!r})")
    if require_spans and audit.total_spans == 0:
        raise SpanImbalanceError(
            f"no spans matched prefix {prefix!r} "
            f"({len(events)} events scanned)")
    return audit
