"""Unified observability layer: metrics registry, StallInspector, sinks.

The reference's production posture rests on three pillars — the Timeline,
the StallInspector (stall_inspector.{h,cc}), and coordinator-side counters
(operations.cc status queries). This package unifies our reproduction's
scattered telemetry (Timeline ``FAULT:*``/``AUTOTUNE:*``/``OVERLAP:*``/
``SERVE:*`` events, ``fault_counters()``, trace-time ``record_wire_stats``)
behind one typed registry with cross-rank aggregation and pluggable sinks:

* :mod:`.registry` — counters / gauges / histograms (fixed log2 buckets),
  label support, one-fused-allreduce cross-rank aggregation piggybacked on
  the existing collective stack (off the step's critical path);
* :mod:`.sinks` — JSONL snapshots (``HOROVOD_METRICS_JSONL``), a
  Prometheus text-format endpoint (``HOROVOD_METRICS_PORT``), Timeline
  counter (``ph:"C"``) mirrors, and the interval reporter thread
  (``HOROVOD_METRICS_INTERVAL``);
* :mod:`.stall` — the live StallInspector: a watchdog over in-flight
  eager collectives and serve requests that emits rank-attributed
  warnings with the reference's warning structure, ``STALL:*`` timeline
  instants, and the ``hvd.stalled_tensors()`` API;
* :mod:`.profile` — host/device trace correlation:
  ``hvd.profile_window(num_steps)`` brackets a ``jax.profiler`` trace
  with the Timeline and per-step ``StepTraceAnnotation`` markers;
* :mod:`.span_audit` — B/E span-balance auditing over Timeline files
  (the test helper and the ``scripts/obs_report.py`` phase breakdown),
  with the CHECKED event-vocabulary table (``KNOWN_PREFIXES`` +
  ``strict=`` mode);
* :mod:`.flight` — the crash-forensic flight recorder: an always-on
  bounded ring of recent events (every Timeline event tapped in, plus
  the timeline-less sources), dumped atomically with a crc32 to
  ``HOROVOD_FLIGHT_RECORDER_DIR`` on crash paths and by
  ``hvd.dump_flight_record()`` — the artifact ``scripts/postmortem.py``
  joins across ranks;
* :mod:`.straggler` — cross-rank straggler attribution: per-step
  per-phase durations riding the registry's one-fused-allreduce
  aggregation, median/MAD outlier detection
  (``straggler.detected{rank,phase}``, ``step.skew_ms``,
  ``STRAGGLER:*`` instants), and cost-model-backed link-health scores
  (``link.health{hop}``, docs/cost-model.md).

The registry is enabled by default (``HOROVOD_METRICS_DISABLE=1`` turns
every record into a no-op); its lifecycle rides ``hvd.init()`` /
``hvd.shutdown()`` but its VALUES survive the elastic shutdown→init
cycle, so an elastic job reads process-lifetime monotone counters across
world incarnations. See docs/observability.md.
"""

from __future__ import annotations

from .registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    counter,
    default_registry,
    gauge,
    histogram,
    metrics_enabled,
)
from .sinks import (  # noqa: F401
    JsonlSink,
    PrometheusSink,
    TimelineSink,
)
from .stall import (  # noqa: F401
    StallInspector,
    stall_inspector,
    stalled_tensors,
)
from .profile import profile_window  # noqa: F401
from .span_audit import (  # noqa: F401
    KNOWN_PREFIXES,
    SpanAudit,
    UnknownSpanPrefixError,
    audit_spans,
)
from .flight import (  # noqa: F401
    FlightRecorder,
    dump_flight_record,
    flight_recorder,
)
from .straggler import (  # noqa: F401
    StragglerDetector,
    record_pp_bubble,
    straggler_detector,
)

from . import lifecycle as _lifecycle


def metrics() -> MetricsRegistry:
    """The process-global metrics registry (``hvd.metrics()``)."""
    return default_registry()


def snapshot(prefix: str = "") -> dict:
    """One registry snapshot dict (optionally filtered to ``prefix``)."""
    return default_registry().snapshot(prefix=prefix)


def aggregate(prefix: str = "") -> dict:
    """Cross-rank aggregated snapshot: one small fused allreduce over the
    process world (identity in a world of one / before init)."""
    return default_registry().aggregate(prefix=prefix)


def flush() -> None:
    """Push one snapshot through every configured sink now."""
    _lifecycle.flush()


# init()/shutdown() hooks (wired from common/basics.py).
start_from_env = _lifecycle.start_from_env
on_shutdown = _lifecycle.on_shutdown
add_sink = _lifecycle.add_sink
