"""Live StallInspector: watchdog over in-flight host-path operations.

The Python port of the reference's coordinator-side stall inspector
(``stall_inspector.{h,cc}``, mirrored in ``cc/src/stall_inspector.cc``):
eager collectives and serve requests register on entry and deregister on
completion; a watchdog thread wakes every fraction of
``stall_check_time`` (HOROVOD_STALL_CHECK_TIME_SECONDS) and, for every
operation in flight longer than the threshold, emits

* a log warning with the reference's exact structure — which ranks are
  ready, which are missing — attributed to this rank;
* a ``STALL:<name>`` instant on the active Timeline (tid ``stalls``);
* a ``stall.warnings`` bump in the metrics registry;

and keeps the entry queryable through :func:`stalled_tensors`
(``hvd.stalled_tensors()``). ``HOROVOD_STALL_SHUTDOWN_TIME_SECONDS > 0``
escalates a stall past that deadline to an error log and a
``stall.shutdowns`` counter (the abort itself stays the caller's call —
under SPMD a unilateral ``os._exit`` would take the whole mesh down).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from . import registry as _registry

logger = logging.getLogger("horovod_tpu.stall")


class _Pending:
    __slots__ = ("name", "kind", "rank", "start", "warned", "escalated")

    def __init__(self, name: str, kind: str, rank: int) -> None:
        self.name = name
        self.kind = kind
        self.rank = rank
        self.start = time.monotonic()
        self.warned = False
        self.escalated = False


def _world_and_rank(rank: Optional[int]):
    """(world, this-rank) from the live framework state; (1, 0) when the
    registry is used outside an initialized world (launcher, tests)."""
    try:
        from ..common import basics

        if basics.is_initialized():
            r = basics.rank() if rank is None else rank
            return basics.size(), int(r)
    except Exception:
        pass
    return 1, 0 if rank is None else rank


class StallInspector:
    """Tracks in-flight operations and warns about stalls.

    ``warning_secs`` mirrors the reference's ``stall_check_time``
    (stall_inspector.h:36-66); ``shutdown_secs=0`` disables escalation.
    """

    def __init__(self, warning_secs: float = 60.0,
                 shutdown_secs: float = 0.0,
                 check_interval: Optional[float] = None) -> None:
        self.warning_secs = warning_secs
        self.shutdown_secs = shutdown_secs
        # Wake often enough that a warning lands within warning_secs of
        # the stall crossing the threshold (the acceptance contract).
        self.check_interval = (
            min(max(warning_secs / 4.0, 0.05), 5.0)
            if check_interval is None else check_interval)
        self._lock = threading.Lock()
        self._pending: Dict[str, _Pending] = {}
        self._warnings: List[dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- tracking (any thread) -----------------------------------------

    def record_start(self, name: str, *, kind: str = "collective",
                     rank: Optional[int] = None) -> None:
        _, r = _world_and_rank(rank)
        with self._lock:
            self._pending[name] = _Pending(name, kind, r)

    def record_done(self, name: str) -> None:
        with self._lock:
            self._pending.pop(name, None)

    def track(self, name: str, *, kind: str = "collective",
              rank: Optional[int] = None):
        """Context manager: ``with inspector.track("eager.allreduce.0"):``."""
        inspector = self

        class _Tracked:
            def __enter__(self):
                inspector.record_start(name, kind=kind, rank=rank)
                return self

            def __exit__(self, *exc):
                inspector.record_done(name)
                return False

        return _Tracked()

    # -- inspection -----------------------------------------------------

    def stalled(self) -> List[dict]:
        """Operations currently in flight past ``warning_secs`` — the
        ``hvd.stalled_tensors()`` payload."""
        now = time.monotonic()
        with self._lock:
            return [
                {"name": p.name, "kind": p.kind, "rank": p.rank,
                 "elapsed_secs": now - p.start}
                for p in self._pending.values()
                if now - p.start >= self.warning_secs]

    def warnings(self) -> List[dict]:
        with self._lock:
            return list(self._warnings)

    def in_flight(self) -> List[str]:
        with self._lock:
            return list(self._pending)

    def check(self) -> List[dict]:
        """One watchdog pass: warn (once) about every stalled entry."""
        now = time.monotonic()
        fired = []
        with self._lock:
            pend = [p for p in self._pending.values()
                    if now - p.start >= self.warning_secs]
        for p in pend:
            waited = now - p.start
            if not p.warned:
                p.warned = True
                world, _ = _world_and_rank(p.rank)
                ready = [p.rank]
                missing = [r for r in range(world) if r != p.rank]
                # The reference's warning structure
                # (stall_inspector.cc:43-49), rank-attributed.
                msg = (
                    "One or more tensors were submitted to be reduced, "
                    "gathered or broadcasted by subset of ranks and are "
                    "waiting for remainder of ranks for more than "
                    f"{self.warning_secs} seconds. Stalled tensor: "
                    f"{p.name} [ready ranks: "
                    f"{' '.join(str(r) for r in ready)} | missing ranks: "
                    f"{' '.join(str(r) for r in missing)}]")
                logger.warning(msg)
                w = {"name": p.name, "kind": p.kind, "rank": p.rank,
                     "elapsed_secs": waited, "ready_ranks": ready,
                     "missing_ranks": missing, "message": msg}
                with self._lock:
                    self._warnings.append(w)
                fired.append(w)
                _registry.counter("stall.warnings", kind=p.kind).inc()
                self._timeline_instant(p, waited, ready, missing)
            if (self.shutdown_secs > 0 and waited >= self.shutdown_secs
                    and not p.escalated):
                p.escalated = True
                logger.error(
                    f"Tensor {p.name} stalled for {waited:.1f}s, exceeding "
                    f"the shutdown deadline of {self.shutdown_secs}s.")
                _registry.counter("stall.shutdowns", kind=p.kind).inc()
                # Escalation is a dump trigger (docs/observability.md):
                # the rank is wedged past the deadline — capture the
                # black box NOW, while the in-flight set still names the
                # stalled collective. No-op unless a dump dir is set.
                from . import flight as _flight

                _flight.dump_flight_record(
                    reason="stall.escalation",
                    extra={"tensor": p.name, "kind": p.kind,
                           "rank": p.rank,
                           "elapsed_secs": round(waited, 3)})
        return fired

    @staticmethod
    def _timeline_instant(p: _Pending, waited: float, ready, missing):
        try:
            from ..common import basics

            tl = basics._state.timeline
        except Exception:  # pragma: no cover - interpreter teardown
            return
        args = {"kind": p.kind, "rank": p.rank,
                "elapsed_secs": round(waited, 3),
                "ready_ranks": ready, "missing_ranks": missing}
        if tl is not None:
            tl.instant(f"STALL:{p.name}", tid="stalls", args=args)
        else:
            # No timeline: the stall still reaches the flight ring (the
            # timeline path is tapped there automatically).
            from . import flight as _flight

            _flight.instant(f"STALL:{p.name}", tid="stalls", args=args)

    # -- watchdog thread ------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="hvd-stall-inspector", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.check_interval):
            try:
                self.check()
            except Exception:  # pragma: no cover - never kill the job
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# ---------------------------------------------------------------------------
# Process-global inspector. Tracking call sites (eager collectives, the
# serve engine) talk to this instance; lifecycle.start_from_env() arms
# the watchdog thread with the Config's stall knobs at hvd.init().
# ---------------------------------------------------------------------------

_global = StallInspector()


def stall_inspector() -> StallInspector:
    return _global


def stalled_tensors() -> List[dict]:
    """Operations (eager collectives, serve requests) in flight past the
    stall warning threshold — name, kind, owning rank, elapsed seconds.
    The live-path analogue of the reference's stall warning state."""
    return _global.stalled()


def track(name: str, *, kind: str = "collective",
          rank: Optional[int] = None):
    """Track one in-flight operation on the global inspector."""
    return _global.track(name, kind=kind, rank=rank)


def record_start(name: str, *, kind: str = "collective",
                 rank: Optional[int] = None) -> None:
    _global.record_start(name, kind=kind, rank=rank)


def record_done(name: str) -> None:
    _global.record_done(name)
