"""Monitor lifecycle: wired into ``hvd.init()`` / ``hvd.shutdown()``.

``start_from_env()`` (from ``basics.init``) arms the observability layer
per the Config's knobs:

* ``HOROVOD_METRICS_JSONL=<path>``   — snapshot JSONL sink;
* ``HOROVOD_METRICS_PORT=<port>``    — Prometheus text endpoint (0 = any);
* ``HOROVOD_METRICS_INTERVAL=<s>``   — reporter thread period (0 = only
  flush at shutdown);
* ``HOROVOD_METRICS_AGGREGATE=1``    — reporter snapshots are cross-rank
  aggregated (one small fused allreduce per interval);
* stall knobs (``HOROVOD_STALL_CHECK_*``) — the live StallInspector
  watchdog, on by default like the reference.

``on_shutdown()`` (from ``basics.shutdown``) flushes one final snapshot,
then stops the watchdog / reporter / HTTP server. Registry VALUES are
never cleared — the next ``init()`` (an elastic world transition) bumps
``elastic.incarnations`` and re-arms exporters against the same registry,
so counters stay monotone across incarnations.
"""

from __future__ import annotations

import threading
from typing import List, Optional

from . import flight as _flight
from . import registry as _registry
from . import sinks as _sinks
from . import stall as _stall

_lock = threading.Lock()
_active_sinks: List = []
_reporter: Optional[_sinks.Reporter] = None
_timeline_sink = _sinks.TimelineSink()
_prom: Optional[_sinks.PrometheusSink] = None
_inits = 0


def prometheus_port() -> Optional[int]:
    """Bound port of the live Prometheus endpoint (None when off)."""
    return _prom.port if _prom is not None else None


def add_sink(sink) -> None:
    """Register an extra snapshot sink (tests, embedders)."""
    with _lock:
        _active_sinks.append(sink)


def start_from_env(config) -> None:
    """Arm sinks + stall watchdog from the Config (idempotent)."""
    global _reporter, _prom, _inits
    reg = _registry.default_registry()
    with _lock:
        _inits += 1
        if _inits > 1:
            # Elastic shutdown→init cycle: a world transition on the SAME
            # persistent registry (the resize-survival contract).
            reg.counter("elastic.incarnations").inc()
        if config is None or not reg.enabled:
            return
        if config.metrics_jsonl and not any(
                isinstance(s, _sinks.JsonlSink)
                and s.path == config.metrics_jsonl
                for s in _active_sinks):
            _active_sinks.append(_sinks.JsonlSink(config.metrics_jsonl))
        if config.metrics_port is not None and _prom is None:
            _prom = _sinks.PrometheusSink(reg, config.metrics_port)
            _active_sinks.append(_prom)
            # Endpoint discovery (docs/observability.md): with port 0 the
            # OS assigns the port, so scrapers cannot know it a priori —
            # publish the resolved port as a gauge and, when the JSONL
            # sink names a path, as a discovery file beside it (what
            # scripts/obs_report.py reads to locate the endpoint).
            reg.gauge("metrics.port").set(_prom.port)
            if config.metrics_jsonl:
                _write_port_discovery(config.metrics_jsonl, _prom.port)
        if config.metrics_interval > 0 and _reporter is None:
            _reporter = _sinks.Reporter(
                reg, _active_sinks + [_timeline_sink],
                config.metrics_interval,
                aggregate=config.metrics_aggregate)
    # Forensics: arm the flight recorder's crash-path dump handlers
    # (excepthook / SIGTERM / faulthandler) when a dump dir is
    # configured; the ring itself records unconditionally.
    _flight.arm_from_env(config)
    insp = _stall.stall_inspector()
    if not config.stall_check_disable:
        insp.warning_secs = config.stall_warning_time_seconds
        insp.shutdown_secs = config.stall_shutdown_time_seconds
        insp.check_interval = min(
            max(insp.warning_secs / 4.0, 0.05), 5.0)
        insp.start()
    else:
        insp.stop()


def _write_port_discovery(jsonl_path: str, port: int) -> None:
    """Atomic ``<jsonl>.port`` discovery file: {"port", "pid",
    "endpoint"} — crash-safe via the tmp→os.replace discipline."""
    import json
    import os

    path = jsonl_path + ".port"
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump({"port": int(port), "pid": os.getpid(),
                       "endpoint": f"http://127.0.0.1:{port}/metrics"}, f)
        os.replace(tmp, path)
    except OSError:  # discovery is best-effort, never fails init
        pass


def flush() -> None:
    """Push one snapshot through every sink (timeline mirror included)."""
    snap = _registry.default_registry().snapshot()
    with _lock:
        targets = list(_active_sinks)
    for s in targets + [_timeline_sink]:
        try:
            s.write(snap)
        except Exception:  # export must never take the job down
            pass


def on_shutdown() -> None:
    """Final flush, then stop watchdog / reporter / HTTP server. Values
    persist in the registry for the next incarnation."""
    global _reporter, _prom
    _stall.stall_inspector().stop()
    flush()
    with _lock:
        if _reporter is not None:
            _reporter.close()
            _reporter = None
        if _prom is not None:
            try:
                _prom.close()
            except Exception:
                pass
            if _prom in _active_sinks:
                _active_sinks.remove(_prom)
            _prom = None


def _reset_for_tests() -> None:
    """Tear everything down AND forget sink registrations (tests only)."""
    global _reporter, _prom, _inits
    on_shutdown()
    with _lock:
        _active_sinks.clear()
        _inits = 0
