"""horovod_tpu: a TPU-native distributed training framework.

A from-scratch rebuild of the capabilities of Horovod (reference:
gangiswag/horovod v0.20.3) designed for TPU hardware: collectives compile
into XLA programs over the ICI mesh via ``jax.shard_map``/``pjit`` instead of
running through a background NCCL/MPI thread; the host-side control plane
(launcher, rendezvous, elastic driver, eager collectives) mirrors the
reference's coordinator architecture.

Quick start (the reference's README recipe, TPU-style)::

    import horovod_tpu as hvd

    hvd.init()
    mesh = hvd.mesh()

    tx = hvd.DistributedOptimizer(optax.sgd(0.01 * hvd.size()))

    @jax.jit
    def train_step(params, opt_state, batch):
        def spmd(params, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            # grads are allreduced inside the optimizer update:
            updates, new_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_state, loss
        return jax.shard_map(spmd, mesh=mesh,
                             in_specs=(P(), hvd.data_pspec()),
                             out_specs=(P(), P(), P()))(params, batch)

API surface parity map (reference file → here):
  basics.py hvd.init/rank/size/...    → common/basics.py
  mpi_ops allreduce/allgather/...     → ops/collective_ops.py
  compression.py                      → ops/compression.py
  adasum (common/ops/adasum)          → ops/adasum.py
  tensor fusion (fusion_buffer)       → ops/fusion.py
  DistributedOptimizer                → parallel/optimizer.py
  DistributedGradientTape             → parallel/tape.py
  broadcast_variables/object          → parallel/functions.py
  SyncBatchNorm                       → parallel/sync_batch_norm.py
  elastic State/run                   → elastic/
  horovodrun launcher                 → runner/
  horovod.torch                       → torch/ (mpi_ops, optimizer, ...)
  horovod.tensorflow                  → tensorflow/ (ops, tape, optimizer)
  horovod.keras / tensorflow.keras    → keras/, _keras/, tensorflow/keras/
  horovod.mxnet                       → mxnet/ (gated: MXNet is EOL)
  parameter_manager + optim/ (GP/BO)  → autotune/ (hvd.autotune_session)
  (no reference analogue)             → parallel/sequence.py (ring/Ulysses
                                        attention), ops/flash_attention.py
                                        (Pallas flash kernel), models/gpt.py
"""

from .common.basics import (  # noqa: F401
    CROSS_AXIS,
    EP_AXIS,
    HVD_AXES,
    LOCAL_AXIS,
    POD_AXIS,
    PP_AXIS,
    cross_rank,
    cross_size,
    data_mesh_shape,
    data_sharding,
    ep_size,
    in_hvd_context,
    init,
    is_homogeneous,
    is_initialized,
    local_batch_size,
    local_rank,
    local_size,
    mesh,
    mpi_threads_supported,
    pod_size,
    pp_size,
    rank,
    replicated_sharding,
    shard_map,
    shutdown,
    size,
)
from .common.exceptions import (  # noqa: F401
    HorovodInternalError,
    HostsUpdatedInterrupt,
)
from .ops.collective_ops import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    Product,
    ReduceOp,
    Sum,
    all_gather,
    all_gather_stream,
    allgather,
    allgather_async,
    allreduce,
    allreduce_async,
    allreduce_stream,
    alltoall,
    alltoall_async,
    alltoall_ragged,
    barrier,
    broadcast,
    broadcast_async,
    grouped_allreduce,
    join,
    poll,
    quantized_allreduce,
    record_wire_stats,
    reduce_scatter,
    reduce_scatter_stream,
    synchronize,
)
from .ops.compression import Compression  # noqa: F401
from .ops.fusion import allreduce_pytree, stream_order  # noqa: F401
from .parallel.functions import (  # noqa: F401
    allgather_object,
    broadcast_object,
    broadcast_optimizer_state,
    broadcast_parameters,
    broadcast_variables,
)
from .ops.flash_attention import (  # noqa: F401
    flash_attention,
    flash_ring_attention,
)
from .ops.fused_collective import (  # noqa: F401
    fused_all_gather_matmul,
    fused_matmul_reduce_scatter,
)
from .ops.softmax_xent import (  # noqa: F401
    linear_cross_entropy,
    lm_head_loss,
)
from .parallel.optimizer import (  # noqa: F401
    DistributedOptimizer,
    OverlapMultiStepsState,
    QuantizedEFState,
    ZeroFullMultiStepsState,
    ZeroMultiStepsState,
    ZeroOverlapMultiStepsState,
    ZeroState,
    overlap_state_pspecs,
    zero3_gather_params,
    zero3_param_pspecs,
    zero3_plan,
    zero3_reshard_params,
    zero3_shard_params,
    zero_reshard_state,
    zero_state_pspecs,
)
from .parallel.sequence import (  # noqa: F401
    dense_attention,
    ring_attention,
    ulysses_attention,
)
from .parallel.sync_batch_norm import SyncBatchNorm  # noqa: F401
from .parallel.expert import (  # noqa: F401
    SwitchMoE,
    ep_split_params,
    switch_moe,
    switch_moe_ragged,
)
from . import moe  # noqa: F401  (expert-parallel MoE, docs/moe.md)
from .moe import (  # noqa: F401
    MoELayer,
    moe_ffn,
)
from .parallel.pipeline import (  # noqa: F401
    PPSchedule,
    PP_SCHEDULES,
    build_interleaved_schedule,
    gpipe,
    gpipe_1f1b,
    interleaved_1f1b,
    pipelined_gpt_apply,
    pipelined_gpt_loss,
    pipelined_gpt_train,
    pipelined_gpt_train_1f1b,
    pp_split_blocks,
    pp_split_chunks,
)
from .parallel.tensor import (  # noqa: F401
    tp_merge_params,
    tp_shard_params,
    tp_split_params,
    tp_unshard_params,
)
from .parallel.tape import (  # noqa: F401
    DistributedGradientTape,
    allreduce_gradients,
    grad,
    value_and_grad,
)
from .common.basics import fault_counters  # noqa: F401
from .autotune import (  # noqa: F401
    AutotuneResult,
    TunedParams,
    autotune_session,
)
from .utils.timeline import start_timeline, stop_timeline  # noqa: F401
from . import plan  # noqa: F401  (composable wire-plan IR, docs/wire-plan.md)
from .plan import (  # noqa: F401
    StepPlan,
    WirePlan,
    describe_plan,
)
from . import compile  # noqa: F401  (compile-once runtime, docs/compile.md)
from .compile import precompile  # noqa: F401  (AOT warm pools)
from . import chaos  # noqa: F401  (fault injection: hvd.chaos.FaultPlan)
from . import checkpoint  # noqa: F401  (async rank-sharded save/restore)
from . import elastic  # noqa: F401  (hvd.elastic.run / State / ElasticSampler)
from . import monitor  # noqa: F401  (metrics registry / sinks / span audit)
from . import resilience  # noqa: F401  (failure-policy supervisor)
from .monitor import (  # noqa: F401
    dump_flight_record,
    metrics,
    profile_window,
    stalled_tensors,
    straggler_detector,
)

from jax.sharding import PartitionSpec as _P
from .common import basics as _basics


def data_pspec(*extra):
    """PartitionSpec splitting the leading (batch) dim over all ranks
    (``(pod, cross, local)`` on a 3-level mesh, ``HVD_AXES`` otherwise)."""
    return _P(_basics.world_axes(), *extra)


__version__ = "0.1.0"
