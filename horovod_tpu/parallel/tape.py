"""Gradient-transform wrappers: the DistributedGradientTape equivalent.

Reference: ``hvd.DistributedGradientTape`` (tensorflow/__init__.py:511-576)
wraps a TF GradientTape so ``tape.gradient`` returns allreduced gradients,
via ``_make_allreduce_grads_fn`` (tensorflow/__init__.py:246-278).

JAX has no tape — gradients come from ``jax.grad`` / ``jax.value_and_grad``.
The equivalents here wrap those transforms so the returned gradients are
already fused-allreduced across the mesh, which is exactly what the
reference's tape wrapper does at the same point in the step.

A subtlety makes this more than sugar: under ``jax.shard_map`` autodiff
*auto-psums* gradients of replicated inputs (the transpose of the implicit
replicate-to-varying broadcast), producing per-parameter fp32 SUM
collectives outside our control — no fusion policy, no compression, no
Adasum. To reclaim Horovod semantics we first cast the differentiated
arguments to device-varying (``lax.pcast(..., to='varying')``), so the raw
gradients are true per-rank locals, then run them through the fused
allreduce exactly as the reference does.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax

from ..ops import collective_ops as C
from ..ops import fusion
from ..ops.compression import Compression


def _pvary_tree(tree, axes_t):
    """Cast every leaf to be varying over ``axes_t`` so autodiff produces
    local (un-psummed) gradients for it."""
    return jax.tree.map(lambda x: C.pvary_missing(x, axes_t), tree)


def allreduce_gradients(
    grads,
    *,
    op: C.ReduceOp = C.ReduceOp.AVERAGE,
    compression=Compression.none,
    fusion_threshold_bytes: Optional[int] = None,
    axes=None,
    hierarchical: Optional[bool] = None,
    quantized: Optional[bool] = None,
    error_feedback=None,
    tuned_params=None,
    overlap: Optional[bool] = None,
    num_comm_streams: Optional[int] = None,
    fused: Optional[bool] = None,
    plan=None,
):
    """Allreduce a gradient pytree (reference: _make_allreduce_grads_fn,
    tensorflow/__init__.py:246-278). Fused into per-dtype buckets;
    ``presummed=True`` because invariant gradient leaves under shard_map are
    autodiff-psummed sums, not equal per-rank contributions.

    ``quantized`` selects the blockwise-int8 DCN wire per bucket;
    ``error_feedback`` (a pytree of per-rank residuals matching ``grads``,
    zeros initially) switches the return value to
    ``(reduced, new_error_feedback)`` so callers can thread EF state
    functionally — :class:`horovod_tpu.DistributedOptimizer` does this
    inside its optax state instead. ``tuned_params`` applies an autotuner
    override (see :func:`~horovod_tpu.ops.fusion.allreduce_pytree`).
    ``overlap`` (default ``HOROVOD_OVERLAP``) issues the buckets through
    the reverse-layer stream schedule in flights of ``num_comm_streams``
    — bit-identical values, overlap-friendly issue order
    (docs/overlap.md). ``plan`` threads an explicit wire plan (a
    :class:`horovod_tpu.plan.WirePlan`, or a
    :class:`~horovod_tpu.plan.StepPlan` whose ``gradient`` is used) in
    place of the boolean knobs, which remain as aliases
    (docs/wire-plan.md)."""
    if plan is not None and hasattr(plan, "gradient"):
        plan = plan.gradient  # a StepPlan: thread its gradient wire
    return fusion.allreduce_pytree(
        grads, op=op, compression=compression,
        threshold_bytes=fusion_threshold_bytes, axes=axes,
        hierarchical=hierarchical, presummed=True,
        quantized=quantized, error_feedback=error_feedback,
        tuned_params=tuned_params, overlap=overlap,
        num_comm_streams=num_comm_streams, fused=fused, plan=plan)


def value_and_grad(
    fun,
    argnums=0,
    has_aux: bool = False,
    *,
    op: C.ReduceOp = C.ReduceOp.AVERAGE,
    compression=Compression.none,
    fusion_threshold_bytes: Optional[int] = None,
    axes=None,
    hierarchical: Optional[bool] = None,
    quantized: Optional[bool] = None,
    zero: Optional[bool] = None,
    zero_stage: Optional[int] = None,
    overlap: Optional[bool] = None,
    num_comm_streams: Optional[int] = None,
    tuned_params=None,
    plan=None,
    reduce: bool = True,
    pp_stages: Optional[int] = None,
    pp_microbatches: Optional[int] = None,
    pp_schedule: Optional[str] = None,
    pp_interleave: Optional[int] = None,
    moe_experts: Optional[int] = None,
    moe_capacity_factor: Optional[float] = None,
    moe_topk: Optional[int] = None,
    **jax_kwargs,
):
    """``jax.value_and_grad`` whose gradients are allreduced across ranks —
    the DistributedGradientTape of the JAX world
    (reference: tensorflow/__init__.py:511-576).

    ``reduce=False`` still pvaries the differentiated arguments (so the
    gradients come back as true per-rank locals instead of auto-psummed
    fp32 sums) but skips the allreduce — the hand-off point for callers
    that let :class:`~horovod_tpu.DistributedOptimizer` own the reduction,
    e.g. to keep error-feedback state in the optimizer when
    ``quantized=True``.

    ``zero`` / ``zero_stage`` (defaults: the ``HOROVOD_ZERO_STAGE`` /
    ``HOROVOD_ZERO_SHARDING`` knobs; ``zero=True`` aliases stage 2) mark
    the step as ZeRO-sharded: under ZeRO the gradient reduction IS the
    optimizer's reduce-scatter, so any stage > 0 behaves as
    ``reduce=False`` — raw per-rank local gradients are handed to the
    ``DistributedOptimizer(zero_stage=N)`` update, whose bucket
    reduce-scatter is then the one and only gradient collective. This is
    the knob's thread-through point: a step built with
    ``hvd.value_and_grad(..., zero_stage=n)`` + ``DistributedOptimizer(
    ..., zero_stage=n)`` flips between the replicated and sharded
    schedules with one flag (see docs/zero.md). ``plan`` (a
    :class:`horovod_tpu.plan.StepPlan` or bare ``WirePlan``) threads the
    wire plan instead of the booleans — a StepPlan with ``zero_stage>0``
    implies ``reduce=False`` exactly like the ``zero`` knob.

    ``pp_stages``/``pp_microbatches``/``pp_schedule``/``pp_interleave``
    validate the pipeline composition the step runs under exactly like
    :class:`~horovod_tpu.DistributedOptimizer`'s pp knobs
    (docs/pipeline.md) — the fused pipeline schedules
    (:func:`horovod_tpu.pipelined_gpt_train` /
    :func:`~horovod_tpu.parallel.pipeline.interleaved_1f1b`) compute
    their own gradients, so here the knobs are a loud-failure contract,
    not a behavior switch; the returned gradients are still reduced over
    the DATA axes only (``axes=None`` never includes ``hvd_pp``).

    ``moe_experts``/``moe_capacity_factor``/``moe_topk`` validate the
    MoE composition the same way (docs/moe.md): expert gradients stay
    isolated per expert group because ``axes=None`` never includes
    ``hvd_ep`` — the knobs fail loudly on a misconfiguration (expert
    count vs the live ep axis, capacity/topk bounds)."""
    if any(k is not None for k in (pp_stages, pp_microbatches,
                                   pp_schedule, pp_interleave)):
        from .optimizer import _validate_pp_knobs

        _validate_pp_knobs(pp_stages, pp_microbatches, pp_schedule,
                           pp_interleave, plan=plan,
                           tuned_params=tuned_params)
    if any(k is not None for k in (moe_experts, moe_capacity_factor,
                                   moe_topk)):
        from .optimizer import _validate_moe_knobs

        _validate_moe_knobs(moe_experts, moe_capacity_factor, moe_topk,
                            plan=plan, tuned_params=tuned_params)
    if plan is not None and hasattr(plan, "gradient"):
        if zero is None and zero_stage is None:
            zero = plan.zero_stage > 0
        if overlap is None:
            overlap = plan.overlap
        if num_comm_streams is None:
            num_comm_streams = plan.num_comm_streams
        if quantized is None:
            quantized = plan.quantized
        if hierarchical is None:
            hierarchical = plan.hierarchical
        plan = plan.gradient if plan.zero_stage == 0 else None
    if zero is None and zero_stage is not None:
        zero = zero_stage > 0
    if zero is None and tuned_params is not None:
        zero = tuned_params.zero_sharding
    vg = jax.value_and_grad(fun, argnums=argnums, has_aux=has_aux,
                            **jax_kwargs)
    idxs = (argnums,) if isinstance(argnums, int) else tuple(argnums)

    def wrapped(*args, **kwargs):
        zero_eff = zero
        if zero_eff is None:
            from ..parallel.optimizer import _resolve_zero_stage_config

            zero_eff = _resolve_zero_stage_config() > 0
        axes_t = C._resolve_axes(axes)
        if axes_t:
            args = list(args)
            for i in idxs:
                args[i] = _pvary_tree(args[i], axes_t)
        val, grads = vg(*args, **kwargs)
        if not reduce or zero_eff:
            return val, grads
        grads = allreduce_gradients(
            grads, op=op, compression=compression,
            fusion_threshold_bytes=fusion_threshold_bytes, axes=axes,
            hierarchical=hierarchical, quantized=quantized,
            tuned_params=tuned_params, overlap=overlap,
            num_comm_streams=num_comm_streams, plan=plan)
        return val, grads

    return wrapped


def grad(fun, argnums=0, has_aux: bool = False, **kwargs):
    """``jax.grad`` with allreduced gradients (see :func:`value_and_grad`).
    Mirrors the jax.grad contract: with ``has_aux`` returns
    ``(grads, aux)``, otherwise just ``grads``."""
    vg = value_and_grad(fun, argnums=argnums, has_aux=has_aux, **kwargs)

    def wrapped(*args, **kw):
        val, grads = vg(*args, **kw)
        if has_aux:
            return grads, val[1]
        return grads

    return wrapped


class DistributedGradientTape:
    """Name-parity shim for reference users porting TF2 code
    (tensorflow/__init__.py:511-576).

    Usage::

        tape = hvd.DistributedGradientTape(loss_fn)
        loss, grads = tape.gradient(params, batch)

    where ``loss_fn(params, *inputs)`` is a scalar loss. The gradients
    returned are allreduced. New code should call
    :func:`horovod_tpu.value_and_grad` directly.
    """

    def __init__(self, loss_fn, **kwargs):
        self._vg = value_and_grad(loss_fn, **kwargs)

    def gradient(self, params, *inputs):
        return self._vg(params, *inputs)
