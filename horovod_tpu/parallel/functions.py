"""State broadcast / gather helpers.

Reference: ``horovod/tensorflow/functions.py`` (broadcast_variables:47,
broadcast_object:59-134, allgather_object:136) and
``horovod/torch/functions.py`` (broadcast_parameters:30,
broadcast_optimizer_state:70-160). These implement the reference's
checkpoint/resume pattern: rank 0 owns the initial state and broadcasts it at
start (SURVEY §5.4).

On TPU the parameter tree lives replicated across the mesh inside the
compiled program, so ``broadcast_variables`` is only needed (a) to force
bit-identical initialization across hosts in multi-controller setups and
(b) after elastic resets. It lowers to fused masked-psum broadcasts.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, List

import jax
import jax.numpy as jnp
import numpy as np

from ..common import basics
from ..ops import collective_ops as C
from ..ops import fusion


def broadcast_variables(variables, root_rank: int = 0, *, axes=None):
    """Broadcast a pytree of arrays from ``root_rank`` to all ranks
    (reference: tensorflow/functions.py:47-57). Leaves are fused into
    per-dtype buckets so the broadcast is a handful of collectives, not one
    per variable (the reference gets this from tensor fusion)."""
    leaves, treedef = jax.tree.flatten(variables)
    if not leaves:
        return variables
    axes_t = C._resolve_axes(axes)
    if not axes_t:
        # Eager process-world broadcast: identity on a single process.
        return jax.tree.unflatten(
            treedef, [C._eager_broadcast(jnp.asarray(l), root_rank)
                      for l in leaves])
    buckets = fusion.plan_buckets(leaves)
    out = [None] * len(leaves)
    for bucket in buckets:
        buf = fusion.pack(bucket, leaves)
        red = C.broadcast(buf, root_rank, axes=axes_t)
        for i, leaf in zip(bucket.leaf_indices, fusion.unpack(bucket, red)):
            out[i] = leaf
    return jax.tree.unflatten(treedef, out)


# Reference torch naming (torch/functions.py:30).
broadcast_parameters = broadcast_variables


def broadcast_optimizer_state(opt_state, root_rank: int = 0, *, axes=None):
    """Broadcast optimizer state (reference: torch/functions.py:70-160 —
    there it must walk torch state dicts; optax state is already a pytree,
    so it reduces to broadcast_variables over the array leaves)."""
    leaves, treedef = jax.tree.flatten(opt_state)
    arr_idx = [i for i, l in enumerate(leaves) if _is_array(l)]
    new = broadcast_variables([leaves[i] for i in arr_idx], root_rank,
                              axes=axes)
    for i, v in zip(arr_idx, new):
        leaves[i] = v
    return jax.tree.unflatten(treedef, leaves)


def _is_array(x) -> bool:
    return isinstance(x, (jax.Array, np.ndarray))


def broadcast_object(obj: Any, root_rank: int = 0, name: str = None) -> Any:
    """Broadcast an arbitrary picklable object from ``root_rank``
    (reference: tensorflow/functions.py:59-134 — pickle → uint8 tensor →
    bcast size → bcast payload → unpickle). Eager/process-world."""
    basics._require_init()
    if C._eager_world() == 1:
        return obj
    buf = io.BytesIO()
    pickle.dump(obj, buf)
    payload = np.frombuffer(buf.getvalue(), dtype=np.uint8)
    # Two rounds, as in the reference: sizes first (payloads differ per
    # rank), then the root's payload at the agreed size.
    size = C._eager_broadcast(np.asarray([payload.size], np.int64),
                              root_rank, name and name + ".size")
    if basics.rank() == root_rank:
        wire = payload.copy()
    else:
        wire = np.zeros(int(np.asarray(size)[0]), np.uint8)
    data = C._eager_broadcast(wire, root_rank, name)
    return pickle.loads(np.asarray(data).tobytes())


def allgather_object(obj: Any, name: str = None) -> List[Any]:
    """Gather a picklable object from every process into a list
    (reference: tensorflow/functions.py:136-177 — ragged uint8 payloads
    ride the allgatherv size exchange)."""
    basics._require_init()
    if C._eager_world() == 1:
        return [obj]
    buf = io.BytesIO()
    pickle.dump(obj, buf)
    payload = np.frombuffer(buf.getvalue(), dtype=np.uint8)
    lengths = np.asarray(
        C._eager_allgather(np.asarray([payload.size], np.int64),
                           name and name + ".size"))
    data = np.asarray(C._eager_allgather(payload, name))
    out, off = [], 0
    for n in lengths.ravel():
        out.append(pickle.loads(data[off:off + int(n)].tobytes()))
        off += int(n)
    return out
