"""DistributedOptimizer: gradient-allreducing optimizer wrapper.

Reference: ``hvd.DistributedOptimizer`` for TF (tensorflow/__init__.py:293-336,
435-508) and torch (torch/optimizer.py:103-200). There, per-parameter hooks
fire asynchronous allreduces as gradients become ready and ``step()`` blocks
on all handles.

TPU-native redesign
-------------------
Our optimizer story is optax. ``DistributedOptimizer(tx)`` returns an
``optax.GradientTransformation`` whose ``update`` first allreduces the
gradient pytree — fused into per-dtype flat buckets (ops/fusion.py), with
optional bf16/fp16 wire compression — and then runs the wrapped
transformation. Because the whole step is compiled, XLA overlaps the bucket
collectives with the optimizer math and backward compute automatically; the
reference needs its background thread + ready-event machinery
(operations.cc:354-624) to get the same overlap dynamically.

``backward_passes_per_step`` reproduces the reference's local gradient
accumulation (torch/optimizer.py:67-68,133-149): gradients are accumulated
locally for k microbatches and allreduced once, via ``optax.MultiSteps``.

ZeRO sharded optimizer (``zero_stage={1,2,3}`` / ``HOROVOD_ZERO_STAGE``)
------------------------------------------------------------------------
The reference optimizer allreduces full gradients and then has every rank
redundantly run the identical update on a full replica of the moments —
on a pod that wastes ``(world-1)/world`` of the optimizer-state HBM and
repeats the update math ``world`` times. The reduce-scatter decomposition
fixes both: reduce-scatter the fused gradient buckets (half an
allreduce's bytes), run the wrapped optax transformation only on this
rank's contiguous ``1/world`` flat shard of each bucket, and all-gather
the updated values. Moments live as flat ``[bucket_padded // world]``
leaves riding ``P(HVD_AXES)``, cutting optimizer-state bytes per rank by
``world``×, and because the whole step compiles, XLA overlaps the
all-gather of early buckets with the update math of later ones — the
compile-time analogue of T3's fine-grained compute/collective overlap.

The three stages shard progressively more of the step's persistent
state (docs/zero.md):

* **stage 1** — optimizer state only. With
  ``backward_passes_per_step`` k > 1 the gradient accumulator is the
  classic FULL local-gradient pytree (per-rank leading-axis state,
  :class:`ZeroFullMultiStepsState`) — what ZeRO-2 exists to shrink.
* **stage 2** — + gradient-accumulation state: accumulation happens
  AFTER the reduce-scatter on the scattered shard
  (:class:`ZeroMultiStepsState`), so the accumulator is a
  ``[padded // world]`` leaf — grad-state bytes drop ``world``×.
  ``zero=True`` (the PR-4 spelling) is an alias for stage 2; with
  k == 1 stages 1 and 2 are the same program.
* **stage 3** — + parameters: the training loop holds only this rank's
  flat bucket shards (:func:`zero3_shard_params`), the forward pass
  gathers each bucket just in time (:func:`zero3_gather_params`, issued
  in forward order through the PR-5 stream entry points so later
  buckets' gathers overlap with earlier layers' compute), and the
  update returns SHARD updates — no trailing all-gather at all.
  Param + grad + optimizer-state persistent bytes are all ``1/world``.
"""

from __future__ import annotations

import contextlib
from typing import Any, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from ..common import basics
from ..common.config import _env_bool, _env_int
from ..monitor import registry as _metrics
from ..ops import collective_ops as C
from ..ops import fusion
from ..ops.compression import Compression


def _with_step_marker(tx):
    """Host-side step markers around a DistributedOptimizer's update.

    When ``update`` runs eagerly (the host path / process-world mode)
    each call IS one optimizer step: bracket it with
    ``jax.profiler.StepTraceAnnotation`` — the device-trace step marker
    that ``hvd.profile_window`` and the serve engine also use, so host
    steps line up with device activity in a ``jax.profiler`` trace — and
    count it in the metrics registry. Inside a trace (the compiled path,
    where the annotation would mark the single retrace rather than the
    steps) only the ``optimizer.update_traces`` counter advances; the
    per-step markers there come from :func:`hvd.profile_window`.
    """
    inner_update = tx.update
    step_no = [0]

    def update(grads, state, params=None, **extra):
        leaves = jax.tree.leaves(grads)
        if leaves and isinstance(leaves[0], jax.core.Tracer):
            _metrics.counter("optimizer.update_traces").inc()
            return inner_update(grads, state, params, **extra)
        step_no[0] += 1
        _metrics.counter("optimizer.steps").inc()
        with jax.profiler.StepTraceAnnotation("hvd_step",
                                              step_num=step_no[0]):
            return inner_update(grads, state, params, **extra)

    return optax.GradientTransformationExtraArgs(tx.init, update)


class ZeroState(NamedTuple):
    """Optimizer state of a ZeRO-sharded ``DistributedOptimizer``.

    ``inner`` is the wrapped transformation's state, initialized and run
    **only on this rank's flat bucket shards** — every moment leaf is a
    1-D ``[bucket_padded_size // world]`` array (plus replicated scalars
    like step counts). Outside the trace the global form of each moment
    leaf is the full flat bucket ``[bucket_padded_size]``; sharding it
    with ``P(HVD_AXES)`` hands each rank exactly its rank-major shard
    (:mod:`horovod_tpu.ops.fusion` shard layout), which is what the
    in-trace update produces and consumes. Use
    :func:`zero_state_pspecs` to build the matching in/out spec tree.

    ``residual`` / ``gather_residual`` are the error-feedback
    accumulators of the quantized wire (one entry per bucket, ``None``
    when the bucket or the knob is not quantized): ``residual`` feeds the
    gradient reduce-scatter's DCN leg (per rank ``padded // local_size``
    elements — the post-ICI shard it quantizes), ``gather_residual`` the
    update all-gather's DCN leg (per rank its owned ``padded // world``
    segment). Both are rank-local state and carry a leading per-rank
    axis riding ``P(HVD_AXES)`` — and both shrink with the shard, vs the
    full parameter-sized residual of :class:`QuantizedEFState`.
    """

    inner: Any
    residual: Any
    gather_residual: Any


def zero_state_pspecs(state):
    """PartitionSpec tree for a :class:`ZeroState` under ``jax.shard_map``:
    every non-scalar leaf is ZeRO-sharded along its leading axis
    (``P(HVD_AXES)`` — flat bucket moments, MultiSteps accumulators, and
    EF residuals all shard rank-major), scalars (step counters) replicate
    (``P()``). The contract this relies on: a wrapped transformation's
    non-scalar state mirrors its inputs, which here are the flat bucket
    shards — true of the standard optax optimizers (sgd, adam(w), lamb,
    rmsprop, ...); an inner transformation carrying non-scalar state that
    does NOT mirror the params needs a hand-built spec tree instead."""
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(
        lambda l: P(basics.HVD_AXES) if getattr(l, "ndim", 0) >= 1 else P(),
        state)


def _lead_read(tree):
    """Strip the per-rank leading axis of a leading-axis state tree:
    in-trace each rank's slice is its ``[1, ...]`` row; eagerly row
    ``rank()`` of the full ``[world, ...]`` stack (the
    :class:`QuantizedEFState` residual convention)."""
    r = 0 if C._hvd_axes_in_trace() else (
        basics.rank() if basics.is_initialized() else 0)
    return jax.tree.map(lambda a: a[r], tree)


def _lead_write(tree, new_local):
    """Write this rank's row back into a leading-axis state tree. In-trace
    the row is pvaried first so the ``P(HVD_AXES)`` out-spec always sees a
    device-varying value (a branchless ``where`` can hand back provably
    replicated zeros)."""
    axes = C._hvd_axes_in_trace()
    if axes:
        return jax.tree.map(
            lambda a: C.pvary_missing(a, axes)[None], new_local)
    r = basics.rank() if basics.is_initialized() else 0
    return jax.tree.map(lambda a, v: a.at[r].set(v), tree, new_local)


class OverlapMultiStepsState(NamedTuple):
    """State of the double-buffered microbatch accumulator
    (``overlap=True`` + ``backward_passes_per_step`` k > 1 on the
    replicated path — docs/overlap.md mechanism 1).

    ``inner`` is the wrapped transformation's state and ``acc`` the
    running sum of *reduced* gradients — both replicated (``P()``).
    ``pending`` holds the previous microbatch's raw per-rank local
    gradients and ``residual`` the quantized wire's error-feedback
    accumulator (``None`` unquantized); both are rank-local state with a
    leading per-rank axis riding ``P(hvd.HVD_AXES)`` in/out specs, the
    :class:`QuantizedEFState` residual convention
    (:func:`overlap_state_pspecs` builds the matching spec tree).

    Call *t* of a cycle reduces microbatch *t−1*'s buckets (``pending``)
    — a reduction with NO data dependence on the caller's microbatch-*t*
    backward traced in the same program region, which is exactly what
    lets the latency-hiding scheduler run the two concurrently. The
    final call folds the last two microbatches into one reduction (the
    wire is linear, so the accumulated sum is unchanged) and overlaps it
    with the optimizer update of already-reduced buckets. Each cycle
    issues k bucket reductions (vs ``optax.MultiSteps``' single deferred
    one): the classic DDP trade of wire volume for comm time hidden
    under backward.
    """

    mini_step: Any  # int32 scalar, 0..k-1
    inner: Any
    acc: Any
    pending: Any
    residual: Any


def overlap_state_pspecs(state: "OverlapMultiStepsState"):
    """PartitionSpec tree for an :class:`OverlapMultiStepsState` under
    ``hvd.shard_map``: ``pending``/``residual`` shard their leading
    per-rank axis (``P(HVD_AXES)``), everything else replicates."""
    from jax.sharding import PartitionSpec as P

    lead = lambda t: jax.tree.map(lambda _: P(basics.HVD_AXES), t)  # noqa: E731
    rep = lambda t: jax.tree.map(lambda _: P(), t)  # noqa: E731
    return OverlapMultiStepsState(
        mini_step=P(), inner=rep(state.inner), acc=rep(state.acc),
        pending=lead(state.pending),
        residual=None if state.residual is None else lead(state.residual))


class QuantizedEFState(NamedTuple):
    """Optimizer state of a quantized ``DistributedOptimizer``.

    ``inner`` is the wrapped transformation's state. ``residual`` is the
    error-feedback accumulator: a pytree matching the parameters whose
    leaves carry a leading **per-rank axis** — each rank's residual is
    rank-local state (every EF-SGD formulation keeps it per worker), so
    under ``jax.shard_map`` the leaves must ride ``P(hvd.HVD_AXES)``
    in/out specs (shape ``[world, *param_shape]`` outside the trace, this
    rank's ``[1, *param_shape]`` slice inside), not the replicated ``P()``
    of the inner state. A spec prefix of
    ``QuantizedEFState(P(), hvd.data_pspec())`` does exactly that — see
    ``bench.py --quantized`` for the worked example.
    """

    inner: Any
    residual: Any


def _overlap_multi_steps(
    inner: optax.GradientTransformation,
    k: int,
    allreduce_fn,
    *,
    quantized: bool,
):
    """Double-buffered microbatch accumulation for the replicated path
    (``overlap=True`` + ``backward_passes_per_step`` k > 1) — see
    :class:`OverlapMultiStepsState` for the schedule and its contract.

    Branchless like :func:`_zero_multi_steps` (``where``-selected apply,
    never ``lax.cond``), which also makes it the working
    ``backward_passes_per_step`` spelling under ``shard_map``'s
    replication checker on jax 0.4.x, where ``optax.MultiSteps``' cond
    arms fail rep inference. Meaningful for per-rank local gradients
    (``hvd.value_and_grad(..., reduce=False)``); already-psummed
    replicated gradients are detected statically (VMA) and fall back to
    accumulate-locally + one final reduction — MultiSteps semantics, no
    extra wire."""

    def init_fn(params):
        world = basics.size() if basics.is_initialized() else 1
        rows = jax.tree.map(
            lambda p: jnp.zeros((world,) + jnp.shape(p),
                                jnp.asarray(p).dtype), params)
        return OverlapMultiStepsState(
            mini_step=jnp.zeros((), jnp.int32),
            inner=inner.init(params),
            acc=jax.tree.map(
                lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params),
            pending=rows,
            residual=(jax.tree.map(jnp.zeros_like, rows)
                      if quantized else None),
        )

    def update_fn(grads, state, params=None, **extra):
        t = state.mini_step
        is_last = t == (k - 1)
        axes_t = C._hvd_axes_in_trace()
        gleaves = jax.tree.leaves(grads)
        presummed = bool(axes_t) and all(
            C._is_replicated(l, axes_t) for l in gleaves)
        res = None if state.residual is None else _lead_read(state.residual)
        if presummed:
            # Auto-psummed replicated gradients: already reduced, nothing
            # to hide — accumulate locally, reduce the mean once (the
            # reduction short-circuits per-leaf on invariant values).
            acc = jax.tree.map(lambda a, g: a + g.astype(a.dtype),
                               state.acc, grads)
            mean = jax.tree.map(
                lambda a, g: (a / k).astype(jnp.asarray(g).dtype),
                acc, grads)
            if res is not None:
                red, new_res = allreduce_fn(mean, res)
            else:
                red, new_res = allreduce_fn(mean), None
            pend_next = jax.tree.map(jnp.zeros_like, grads)
        else:
            # Double buffer: reduce microbatch t-1 (pending) now — no
            # data dependence on this call's backward — folding the last
            # microbatch into the final call's payload (linear wire).
            pend = _lead_read(state.pending)
            payload = jax.tree.map(
                lambda p_, g_: jnp.where(is_last, p_ + g_, p_), pend, grads)
            if res is not None:
                rpay, new_res = allreduce_fn(payload, res)
            else:
                rpay, new_res = allreduce_fn(payload), None
            acc = jax.tree.map(lambda a, r: a + r.astype(a.dtype),
                               state.acc, rpay)
            mean = jax.tree.map(
                lambda a, g_: (a / k).astype(jnp.asarray(g_).dtype),
                acc, grads)
            red = mean
            pend_next = jax.tree.map(
                lambda g_: jnp.where(is_last, jnp.zeros_like(g_), g_),
                grads)
        upd, inner_new = inner.update(red, state.inner, params, **extra)
        updates = jax.tree.map(
            lambda u: jnp.where(is_last, u, jnp.zeros_like(u)), upd)
        inner_next = jax.tree.map(
            lambda old, new: jnp.where(is_last, new, old),
            state.inner, inner_new)
        acc_next = jax.tree.map(
            lambda a: jnp.where(is_last, jnp.zeros_like(a), a), acc)
        return updates, OverlapMultiStepsState(
            mini_step=(t + 1) % k,
            inner=inner_next,
            acc=acc_next,
            pending=_lead_write(state.pending, pend_next),
            residual=(None if state.residual is None
                      else _lead_write(state.residual, new_res)),
        )

    return optax.GradientTransformationExtraArgs(init_fn, update_fn)


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    *,
    compression=Compression.none,
    op: C.ReduceOp = C.ReduceOp.AVERAGE,
    backward_passes_per_step: int = 1,
    gradient_predivide_factor: float = 1.0,
    fusion_threshold_bytes: Optional[int] = None,
    hierarchical: Optional[bool] = None,
    quantized: Optional[bool] = None,
    zero: Optional[bool] = None,
    zero_stage: Optional[int] = None,
    overlap: Optional[bool] = None,
    num_comm_streams: Optional[int] = None,
    fused: Optional[bool] = None,
    axes=None,
    tuned_params=None,
    plan=None,
    pp_stages: Optional[int] = None,
    pp_microbatches: Optional[int] = None,
    pp_schedule: Optional[str] = None,
    pp_interleave: Optional[int] = None,
    moe_experts: Optional[int] = None,
    moe_capacity_factor: Optional[float] = None,
    moe_topk: Optional[int] = None,
) -> optax.GradientTransformation:
    """Wrap an optax transformation with fused gradient allreduce.

    Args mirror the reference's DistributedOptimizer signature
    (tensorflow/__init__.py:435-508): ``compression`` (wire dtype),
    ``op`` (Average | Sum | Adasum), ``backward_passes_per_step``
    (local accumulation), ``gradient_predivide_factor`` (split the averaging
    divisor across pre/post scaling: prescale = 1/f applied before the sum,
    postscale = f/N after — tensorflow/__init__.py:462-476).

    ``quantized`` (default: the ``HOROVOD_QUANTIZED_ALLREDUCE`` knob) moves
    each fused gradient bucket over the blockwise-int8 DCN wire with
    per-bucket error feedback: the state becomes a
    :class:`QuantizedEFState` wrapping the inner state plus a per-rank
    residual pytree, and each step's quantization error is carried into
    the next step's gradient, keeping convergence at full-precision
    quality. Only meaningful when the gradients reaching ``update`` are
    per-rank locals (e.g. via ``hvd.value_and_grad(..., reduce=False)``);
    auto-psummed replicated gradients never touch the wire, so there is
    nothing to quantize.

    ``zero_stage`` (default: the ``HOROVOD_ZERO_STAGE`` knob; ``zero=True``
    is an alias for stage 2 and ``HOROVOD_ZERO_SHARDING=1`` still maps
    there) selects the ZeRO reduce-scatter decomposition: gradients
    reduce-scatter, the wrapped transformation runs only on this rank's
    ``1/world`` flat bucket shards (state becomes a :class:`ZeroState`;
    shard it with :func:`zero_state_pspecs`), and — stages 1/2 — the
    updates all-gather back. Stage 1 keeps the classic full
    local-gradient accumulator when ``backward_passes_per_step`` k > 1
    (:class:`ZeroFullMultiStepsState`); stage 2 accumulates AFTER the
    reduce-scatter on the scattered shard, shrinking gradient state
    ``world``×; stage 3 additionally expects the PARAMETERS as flat
    bucket shards (``params=`` is the :func:`zero3_shard_params` tuple,
    the forward runs on :func:`zero3_gather_params` output) and returns
    shard updates with no trailing all-gather. All stages compose with
    ``gradient_predivide_factor`` and ``quantized`` (the DCN legs ride
    the blockwise-int8 wire with shard-local error feedback). Like
    ``quantized``, the wire savings need per-rank local gradients
    (``hvd.value_and_grad(..., zero=True)`` or ``reduce=False``);
    already-psummed replicated gradients still shard the update math and
    the moments. See docs/zero.md.

    ``overlap`` (default: the ``HOROVOD_OVERLAP`` knob) streams the fused
    gradient buckets into collectives while backward compute still runs
    (docs/overlap.md): buckets issue in reverse-layer order through the
    per-bucket stream entry points in flights of ``num_comm_streams``
    (pow2 1–4), and with ``backward_passes_per_step`` k > 1 the
    accumulation loop double-buffers so microbatch t's backward and
    microbatch t−1's bucket reduction are dependence-free in the same
    program region (state becomes an :class:`OverlapMultiStepsState`; on
    the ZeRO path the shard accumulator double-buffers the packed
    buckets instead). With k == 1 overlap changes only collective issue
    order, so it is bit-identical to off; ``hvd.init`` arms the XLA
    async-collective/latency-hiding flags on TPU (graceful no-op
    elsewhere).

    ``fused`` (default: the ``HOROVOD_FUSED_KERNELS`` knob) lowers the
    kernel-eligible legs of the gradient wire through the fused Pallas
    backend (docs/fused-kernels.md): with ``quantized`` on, the
    blockwise int8 quantize/dequant-accumulate of the DCN legs runs as
    one VMEM kernel pass instead of separate XLA ops round-tripping the
    payload + scales through HBM. The wire format and bytes are
    identical; values agree to the last ulp of the scale division
    (tests/test_fused_collective.py pins the parity matrix). On an
    unquantized wire the knob is a no-op (no kernel-eligible leg).

    ``tuned_params`` (an ``autotune.TunedParams``, e.g. the winner of
    :func:`horovod_tpu.autotune_session`) overrides the fusion threshold,
    hierarchical flag, int8 scale-block, ZeRO flag, and the
    ``overlap``/``num_comm_streams`` pair for this optimizer's gradient
    reduction wherever the explicit kwargs above were left unset —
    rebuilding the optimizer with a new override is exactly what one
    autotune trial does (the step retraces with the new bucket plan).

    ``plan`` (a :class:`horovod_tpu.plan.StepPlan`, e.g. from
    :func:`horovod_tpu.describe_plan`) threads the resolved wire plan
    instead of the boolean knobs, which remain as aliases: wherever a
    knob above is unset it derives from the plan's knob record, and the
    replicated path's bucket collectives lower through exactly
    ``plan.gradient`` (docs/wire-plan.md). Explicit kwargs still win;
    ``tuned_params`` applies after the plan.

    ``pp_stages`` / ``pp_microbatches`` / ``pp_schedule`` /
    ``pp_interleave`` (defaults: the live mesh's ``hvd_pp`` axis and the
    ``HOROVOD_PP_*`` knobs; a ``plan``'s pp record and ``tuned_params``'
    pp fields fill unset values first) declare the pipeline composition
    this optimizer's step runs under (docs/pipeline.md). The gradient
    wire itself is already pipeline-safe — ``axes=None`` resolves to the
    DATA axes, so per-stage reductions never cross the pp axis — these
    knobs validate the composition up front (stage count vs mesh,
    schedule family, microbatch divisibility) and fail loudly instead of
    letting a mismatched schedule train garbage.

    ``moe_experts`` / ``moe_capacity_factor`` / ``moe_topk`` (defaults:
    the live mesh's ``hvd_ep`` axis and the ``HOROVOD_MOE_*`` knobs; a
    ``plan``'s moe record and ``tuned_params``' moe fields fill unset
    values first) declare the MoE composition the same way
    (docs/moe.md): the gradient wire is already expert-parallel-safe —
    ``axes=None`` resolves to the DATA axes, so an expert's gradients
    reduce only within its own data group and never across ``hvd_ep``
    — these knobs validate up front (expert count vs the ep axis,
    capacity/topk bounds) and fail loudly on a misconfiguration.
    """
    if gradient_predivide_factor != 1.0 and op != C.ReduceOp.AVERAGE:
        raise ValueError(
            "gradient_predivide_factor is only supported with op=Average "
            "(reference: tensorflow/__init__.py:452-455)")
    if backward_passes_per_step < 1:
        raise ValueError("backward_passes_per_step must be >= 1")
    _validate_pp_knobs(pp_stages, pp_microbatches, pp_schedule,
                       pp_interleave, plan=plan,
                       tuned_params=tuned_params)
    _validate_moe_knobs(moe_experts, moe_capacity_factor, moe_topk,
                        plan=plan, tuned_params=tuned_params)
    quant_block = None
    grad_plan = None
    if plan is not None:
        step_plan = plan
        if not hasattr(step_plan, "gradient"):
            raise ValueError(
                "DistributedOptimizer(plan=...) expects a StepPlan "
                "(hvd.describe_plan(...)); pass a bare WirePlan to the "
                "collective entry points or allreduce_pytree instead")
        if quantized is None:
            quantized = step_plan.quantized
        if zero_stage is None and zero is None:
            zero_stage = step_plan.zero_stage
        if overlap is None:
            overlap = step_plan.overlap
        if num_comm_streams is None:
            num_comm_streams = step_plan.num_comm_streams
        if hierarchical is None:
            hierarchical = step_plan.hierarchical
        if fused is None:
            fused = step_plan.fused
        if fusion_threshold_bytes is None:
            fusion_threshold_bytes = step_plan.fusion_threshold_bytes
        if step_plan.quantized:
            quant_block = step_plan.quant_block
        if step_plan.zero_stage == 0:
            grad_plan = step_plan.gradient
    if zero_stage is None and zero is not None:
        zero_stage = 2 if zero else 0  # zero=True is the stage-2 alias
    if tuned_params is not None:
        if fusion_threshold_bytes is None:
            fusion_threshold_bytes = tuned_params.fusion_threshold_bytes
        if hierarchical is None:
            hierarchical = tuned_params.hierarchical_allreduce
        if zero_stage is None:
            zero_stage = tuned_params.zero_stage
        if overlap is None:
            overlap = tuned_params.overlap
        if num_comm_streams is None:
            num_comm_streams = tuned_params.num_comm_streams
        if fused is None:
            fused = getattr(tuned_params, "fused", None)
    if quantized is None:
        quantized = (basics.config().quantized_allreduce
                     if basics.is_initialized()
                     else _env_bool("HOROVOD_QUANTIZED_ALLREDUCE", False))
    if zero_stage is None:
        zero_stage = _resolve_zero_stage_config()
    if zero_stage not in (0, 1, 2, 3):
        raise ValueError(f"zero_stage must be 0, 1, 2, or 3, got "
                         f"{zero_stage!r}")
    zero = zero_stage > 0
    if overlap is None:
        overlap = (basics.config().overlap if basics.is_initialized()
                   else _env_bool("HOROVOD_OVERLAP", False))
    if num_comm_streams is None:
        num_comm_streams = (basics.config().num_comm_streams
                            if basics.is_initialized() else 1)
    num_comm_streams = max(1, int(num_comm_streams))
    if zero:
        if op not in (C.ReduceOp.AVERAGE, C.ReduceOp.SUM):
            raise ValueError(
                f"zero=True supports op=Average/Sum (a reduce-scatter of "
                f"{op} has no decomposition), got {op}")
        return _with_step_marker(_build_zero_transform(
            optimizer,
            compression=compression,
            op=op,
            backward_passes_per_step=backward_passes_per_step,
            gradient_predivide_factor=gradient_predivide_factor,
            fusion_threshold_bytes=fusion_threshold_bytes,
            quantized=quantized,
            quant_block=quant_block,
            overlap=bool(overlap),
            num_comm_streams=num_comm_streams,
            fused=fused,
            axes=axes,
            stage=zero_stage,
        ))

    if gradient_predivide_factor != 1.0:
        # Average == Sum with the divisor split across pre/post scaling.
        prescale = 1.0 / gradient_predivide_factor
        reduce_op = C.ReduceOp.SUM
        # postscale completes the average: f / N, with N resolved at trace
        # time inside _allreduce (world size is static under the mesh).
        postscale_mode = "predivide"
    else:
        prescale = 1.0
        reduce_op = op
        postscale_mode = None

    def _allreduce(grads, error_feedback=None):
        postscale = 1.0
        if postscale_mode == "predivide":
            axes_t = C._resolve_axes(axes)
            n = C._world_size(axes_t) if axes_t else 1
            postscale = gradient_predivide_factor / n
        return fusion.allreduce_pytree(
            grads,
            op=reduce_op,
            compression=compression,
            threshold_bytes=fusion_threshold_bytes,
            axes=axes,
            hierarchical=hierarchical,
            prescale_factor=prescale,
            postscale_factor=postscale,
            presummed=True,  # invariant grads are autodiff-psummed sums
            quantized=quantized,
            error_feedback=error_feedback,
            block=quant_block,
            overlap=overlap,
            num_comm_streams=num_comm_streams,
            fused=fused,
            plan=grad_plan,
        )

    if overlap and backward_passes_per_step > 1:
        # Mechanism 1 (docs/overlap.md): the double-buffered microbatch
        # accumulator owns the reduction (and, when quantized, the EF
        # residual) so microbatch t's backward and microbatch t-1's
        # bucket reduction share a program region dependence-free.
        return _with_step_marker(
            _overlap_multi_steps(optimizer, backward_passes_per_step,
                                 _allreduce, quantized=quantized))

    _res_read, _res_write = _lead_read, _lead_write

    def init_fn(params):
        inner = optimizer.init(params)
        if not quantized:
            return inner
        world = basics.size() if basics.is_initialized() else 1
        residual = jax.tree.map(
            lambda p: jnp.zeros((world,) + jnp.shape(p), jnp.asarray(p).dtype),
            params)
        return QuantizedEFState(inner=inner, residual=residual)

    def update_fn(grads, state, params=None, **extra):
        if not quantized:
            reduced = _allreduce(grads)
            return optimizer.update(reduced, state, params, **extra)
        reduced, new_res = _allreduce(grads, _res_read(state.residual))
        updates, new_inner = optimizer.update(
            reduced, state.inner, params, **extra)
        return updates, QuantizedEFState(
            inner=new_inner,
            residual=_res_write(state.residual, new_res))

    tx = optax.GradientTransformationExtraArgs(init_fn, update_fn)
    if backward_passes_per_step > 1:
        # Accumulate locally, allreduce + apply every k-th microbatch
        # (reference: torch/optimizer.py:133-149).
        tx = optax.MultiSteps(tx, every_k_schedule=backward_passes_per_step)
    return _with_step_marker(tx)


def _validate_pp_knobs(pp_stages, pp_microbatches, pp_schedule,
                       pp_interleave, *, plan=None,
                       tuned_params=None) -> dict:
    """Resolve + validate the pipeline knobs of a training step
    (docs/pipeline.md). The optimizer's gradient collectives are already
    pipeline-safe by construction — ``axes=None`` resolves to the DATA
    axes, never ``hvd_pp`` — so these knobs exist to fail loudly on a
    misconfigured composition (a stage count that disagrees with the
    live mesh, an unknown schedule, an interleave the schedule cannot
    honor) and to record the resolved values for describe/debug.

    Returns the resolved ``{pp_stages, pp_microbatches, pp_schedule,
    pp_interleave}`` dict. Shared by :class:`DistributedOptimizer` and
    :func:`horovod_tpu.value_and_grad`."""
    from .pipeline import PP_SCHEDULES

    if plan is not None and hasattr(plan, "pp_stages"):
        if pp_stages is None and getattr(plan, "pp_stages", 0):
            pp_stages = plan.pp_stages
        if pp_microbatches is None and getattr(plan, "pp_microbatches", 0):
            pp_microbatches = plan.pp_microbatches
        if pp_schedule is None and getattr(plan, "send", None) is not None:
            pp_schedule = plan.pp_schedule
        if pp_interleave is None and getattr(plan, "send", None) is not None:
            pp_interleave = plan.pp_interleave
    if tuned_params is not None:
        if pp_microbatches is None:
            pp_microbatches = getattr(tuned_params, "pp_microbatches",
                                      0) or None
        if pp_interleave is None:
            pp_interleave = getattr(tuned_params, "pp_interleave",
                                    0) or None
    cfg = basics.config() if basics.is_initialized() else None
    if pp_stages is None:
        pp_stages = (basics.pp_size() if basics.is_initialized()
                     else (cfg.pp_stages if cfg else 0))
    if pp_schedule is None:
        pp_schedule = cfg.pp_schedule if cfg else "interleaved_1f1b"
    if pp_interleave is None:
        pp_interleave = (cfg.pp_interleave if cfg else 1) or 1
    if pp_microbatches is None:
        pp_microbatches = (cfg.pp_microbatches if cfg else 0)
    pp_stages = int(pp_stages or 0)
    pp_interleave = max(1, int(pp_interleave))
    pp_microbatches = int(pp_microbatches or 0)
    if pp_stages > 1:
        if pp_schedule not in PP_SCHEDULES:
            raise ValueError(
                f"unknown pp_schedule {pp_schedule!r}: one of "
                f"{PP_SCHEDULES} (docs/pipeline.md)")
        if basics.is_initialized() and basics.pp_size() > 1 \
                and pp_stages != basics.pp_size():
            raise ValueError(
                f"pp_stages={pp_stages} disagrees with the live mesh's "
                f"hvd_pp axis of {basics.pp_size()} stages — the stage "
                f"count is mesh geometry (hvd.init(pp_stages=...))")
        if pp_interleave > 1 and pp_schedule not in ("interleaved_1f1b",
                                                     "zb1"):
            raise ValueError(
                f"pp_interleave={pp_interleave} needs "
                f"pp_schedule='interleaved_1f1b' or 'zb1'; "
                f"{pp_schedule!r} does not interleave virtual stages")
        if (pp_schedule in ("interleaved_1f1b", "zb1")
                and pp_interleave > 1
                and pp_microbatches and pp_microbatches % pp_stages):
            raise ValueError(
                f"pp_microbatches={pp_microbatches} must divide by "
                f"pp_stages={pp_stages} for the interleaved schedule "
                f"(docs/pipeline.md)")
    return {"pp_stages": pp_stages, "pp_microbatches": pp_microbatches,
            "pp_schedule": pp_schedule, "pp_interleave": pp_interleave}


def _validate_moe_knobs(moe_experts, moe_capacity_factor, moe_topk, *,
                        plan=None, tuned_params=None) -> dict:
    """Resolve + validate the MoE knobs of a training step
    (docs/moe.md). Like the pp knobs, the optimizer's gradient
    collectives are already expert-parallel-safe by construction —
    ``axes=None`` resolves to the DATA axes, never ``hvd_ep`` — so these
    exist to fail loudly on a misconfigured composition: an expert
    count that does not divide by the live hvd_ep axis, a non-positive
    capacity factor, a topk out of range.

    Returns the resolved ``{moe_experts, moe_capacity_factor,
    moe_topk}`` dict. Shared by :class:`DistributedOptimizer` and
    :func:`horovod_tpu.value_and_grad`."""
    if plan is not None and hasattr(plan, "moe_experts"):
        if moe_experts is None and getattr(plan, "moe_experts", 0):
            moe_experts = plan.moe_experts
        if moe_capacity_factor is None and getattr(
                plan, "moe", None) is not None:
            moe_capacity_factor = plan.moe_capacity_factor
        if moe_topk is None and getattr(plan, "moe", None) is not None:
            moe_topk = plan.moe_topk
    if tuned_params is not None and moe_capacity_factor is None:
        moe_capacity_factor = getattr(tuned_params,
                                      "moe_capacity_factor", 0.0) or None
    cfg = basics.config() if basics.is_initialized() else None
    if moe_experts is None:
        if basics.is_initialized() and basics.ep_size() > 1:
            moe_experts = basics.ep_size()
        else:
            moe_experts = cfg.moe_experts if cfg else 0
    if moe_capacity_factor is None:
        moe_capacity_factor = (cfg.moe_capacity_factor if cfg else 1.25)
    if moe_topk is None:
        moe_topk = cfg.moe_topk if cfg else 2
    moe_experts = int(moe_experts or 0)
    moe_topk = int(moe_topk or 0)
    moe_capacity_factor = float(moe_capacity_factor or 0.0)
    if moe_experts > 1:
        if basics.is_initialized() and basics.ep_size() > 1 \
                and moe_experts % basics.ep_size():
            raise ValueError(
                f"moe_experts={moe_experts} does not divide by the live "
                f"mesh's hvd_ep axis of {basics.ep_size()} expert "
                f"groups — expert placement is mesh geometry "
                f"(hvd.init(ep_size=...), docs/moe.md)")
        if moe_capacity_factor <= 0:
            raise ValueError(
                f"moe_capacity_factor must be > 0, got "
                f"{moe_capacity_factor} — the dispatch buffer needs "
                f"headroom (docs/moe.md)")
        if not (1 <= moe_topk <= moe_experts):
            raise ValueError(
                f"moe_topk={moe_topk} out of range 1..{moe_experts} "
                f"(experts per token cannot exceed the expert count)")
    return {"moe_experts": moe_experts,
            "moe_capacity_factor": moe_capacity_factor,
            "moe_topk": moe_topk}


# ---------------------------------------------------------------------------
# ZeRO: reduce-scatter data parallelism with per-rank optax updates.
# ---------------------------------------------------------------------------


def _resolve_zero_stage_config() -> int:
    """The configured ZeRO stage: ``HOROVOD_ZERO_STAGE`` (0-3) wins;
    ``HOROVOD_ZERO_SHARDING=1`` (the PR-4 boolean) maps to stage 2."""
    if basics.is_initialized():
        cfg = basics.config()
        stage = getattr(cfg, "zero_stage", 0)
        if stage:
            return stage
        return 2 if cfg.zero_sharding else 0
    stage = _env_int("HOROVOD_ZERO_STAGE", 0)
    if stage:
        return stage
    return 2 if _env_bool("HOROVOD_ZERO_SHARDING", False) else 0


def _zero_worlds(axes) -> Tuple[int, int, bool]:
    """(plan_world, own_world, in_trace).

    ``plan_world`` fixes the bucket padding (``shard_multiple``) and must
    agree between init and update — it is always the full mesh world.
    ``own_world`` is how many ranks actually split the state at this call
    site: the mesh world in-trace, the process world under the eager
    process model (each worker owns its shard — the true ZeRO memory
    win), and 1 for host-side calls under single-controller SPMD (init
    there produces the GLOBAL state — full flat buckets — which
    ``device_put`` with :func:`zero_state_pspecs` then shards)."""
    axes_t = C._resolve_axes(axes)
    if axes_t:
        w = C._world_size(axes_t)
        return w, w, True
    if not basics.is_initialized():
        return 1, 1, False
    # On a pipeline / expert-parallel / 4-D composed mesh the ZeRO
    # world is the DATA world: each (stage, expert-group) cell's shards
    # split over (cross, local) only — exactly what the in-trace path
    # resolves, since hvd_pp/hvd_ep are never world axes.
    plan_w = basics.size() // (basics.pp_size() * basics.ep_size())
    own_w = plan_w if basics._process_world() else 1
    return plan_w, own_w, False


def _zero_local_size(in_trace: bool) -> int:
    if in_trace:
        bound = basics._bound_axes()
        return (C._axis_size(basics.LOCAL_AXIS)
                if basics.LOCAL_AXIS in bound else 1)
    return basics.local_size() if basics.is_initialized() else 1


def _zero_residual_shapes(plan, world: int, local_size: int):
    """Per-bucket (rs_shape, ag_shape) of the EF residuals, or None for
    buckets that never ride the quantized wire (non-float)."""
    out = []
    for b in plan:
        if not jnp.issubdtype(b.dtype, jnp.floating):
            out.append(None)
            continue
        seg = b.padded_size // world
        sn = b.padded_size // local_size
        out.append(((sn,), (seg,)))
    return out


class ZeroMultiStepsState(NamedTuple):
    """Shard-level gradient-accumulation state (``zero=True`` +
    ``backward_passes_per_step > 1``): ``acc_grads`` holds the running
    mean of the *scattered* shards — ``1/world`` the footprint of the
    full-gradient accumulator ``optax.MultiSteps`` keeps on the
    replicated path."""

    mini_step: Any  # int32 scalar, 0..k-1
    inner: Any
    acc_grads: Any


class ZeroFullMultiStepsState(NamedTuple):
    """Full-gradient accumulation state (``zero_stage=1`` +
    ``backward_passes_per_step`` k > 1) — the classic ZeRO-1 layout.

    ``acc`` holds the running sum of this rank's RAW local gradients in
    model-tree layout (one entry per flattened gradient leaf), i.e. the
    full-size accumulator stage 2 exists to shrink: per-rank state with
    a leading per-rank axis riding ``P(HVD_AXES)`` (the residual
    convention — ``[world, *shape]`` outside the trace, ``[1, *shape]``
    inside). The mean of the k accumulated microbatches feeds the
    reduce-scatter on the k-th call; inner state and emitted updates are
    ``where``-selected (branchless — ``lax.cond`` fails shard_map rep
    inference on jax 0.4.x), so the wire runs every microbatch but
    non-final results are discarded. Reshard only at cycle boundaries
    (``mini_step == 0``, ``acc`` zeros); :func:`zero_reshard_state`
    rebuilds the accumulator as zeros at the new world."""

    mini_step: Any  # int32 scalar, 0..k-1
    inner: Any
    acc: Any        # per grad leaf, [lead, *shape], leading per-rank axis


class ZeroOverlapMultiStepsState(NamedTuple):
    """Shard-level double-buffered accumulation state (``zero=True`` +
    ``overlap=True`` + ``backward_passes_per_step`` k > 1).

    Like :class:`ZeroMultiStepsState` the accumulator (``acc_shards``)
    holds scattered ``1/world`` shards, but the reduce-scatter is
    double-buffered: ``pending`` carries the previous microbatch's packed
    raw bucket buffers (leading per-rank axis, the residual convention),
    so call *t* reduce-scatters microbatch *t−1*'s buckets dependence-free
    alongside microbatch *t*'s backward, and the final call folds the
    last two microbatches into one reduction (linear wire — the
    accumulated shard sum is unchanged). Same k collectives per cycle as
    the non-overlapped ZeRO accumulator, shifted one call late."""

    mini_step: Any  # int32 scalar, 0..k-1
    inner: Any
    acc_shards: Any  # per bucket, fp32, flat-bucket (shard) convention
    pending: Any     # per bucket, [lead, padded], leading per-rank axis


def _zero_multi_steps(inner: optax.GradientTransformation, k: int):
    """Branchless ``optax.MultiSteps`` equivalent for the shard level.

    ``optax.MultiSteps`` selects between its accumulate and apply arms
    with ``lax.cond``, whose branches produce different replication types
    under ``shard_map`` (varying shard updates vs replicated zeros) and
    fail the rep/vma checker. At shard level the inner update is
    ``1/world`` the size of the replicated one, so running it every
    microbatch and selecting the result with ``where`` is both cheaper
    than a host of conds and type-stable: emitted updates are zeros
    except on every k-th call, where they are the inner update on the
    running mean of the k accumulated shards (the MultiSteps contract).
    """

    def init_fn(params):
        return ZeroMultiStepsState(
            mini_step=jnp.zeros((), jnp.int32),
            inner=inner.init(params),
            acc_grads=jax.tree.map(
                lambda p: jnp.zeros_like(p, dtype=jnp.float32), params))

    def update_fn(grads, state, params=None, **extra):
        t = state.mini_step
        # Running mean: acc += (g - acc) / (t + 1).
        acc = jax.tree.map(
            lambda a, g: a + (g.astype(a.dtype) - a) / (t + 1).astype(
                a.dtype),
            state.acc_grads, grads)
        is_last = t == (k - 1)
        mean = jax.tree.map(lambda a, g: a.astype(jnp.asarray(g).dtype),
                            acc, grads)
        upd, inner_new = inner.update(mean, state.inner, params, **extra)
        updates = jax.tree.map(
            lambda u: jnp.where(is_last, u, jnp.zeros_like(u)), upd)
        inner_next = jax.tree.map(
            lambda old, new: jnp.where(is_last, new, old),
            state.inner, inner_new)
        acc_next = jax.tree.map(
            lambda a: jnp.where(is_last, jnp.zeros_like(a), a), acc)
        return updates, ZeroMultiStepsState(
            mini_step=(t + 1) % k, inner=inner_next, acc_grads=acc_next)

    return optax.GradientTransformationExtraArgs(init_fn, update_fn)


def _build_zero_transform(
    optimizer: optax.GradientTransformation,
    *,
    compression,
    op: C.ReduceOp,
    backward_passes_per_step: int,
    gradient_predivide_factor: float,
    fusion_threshold_bytes: Optional[int],
    quantized: bool,
    quant_block: Optional[int],
    axes,
    overlap: bool = False,
    num_comm_streams: int = 1,
    fused=None,
    stage: int = 2,
) -> optax.GradientTransformation:
    """The ZeRO optax wrapper: reduce-scatter → shard update → (stages
    1/2) all-gather, with the wrapped transformation living entirely on
    this rank's flat bucket shards.

    ``stage`` picks the accumulation/parameter layout (docs/zero.md):
    stage 1 accumulates FULL local gradients before the wire
    (:class:`ZeroFullMultiStepsState`); stage 2 accumulates the scattered
    shard after it (:class:`ZeroMultiStepsState`, ``1/world`` the
    state); stage 3 is stage 2 whose ``params`` argument is the
    :func:`zero3_shard_params` tuple — the inner update runs shard vs
    shard and the returned updates stay in shard space (the caller
    applies them to its shard tree; the just-in-time forward gather is
    :func:`zero3_gather_params`). With k == 1 stages 1 and 2 trace the
    identical program.

    ``overlap`` issues the per-bucket reduce-scatter/all-gather through
    the reverse-layer stream schedule in flights of ``num_comm_streams``
    (docs/overlap.md); with ``backward_passes_per_step`` k > 1 it also
    double-buffers the accumulation loop (:class:`ZeroOverlapMultiSteps
    State`) so each call's reduce-scatter covers the PREVIOUS microbatch
    and runs dependence-free next to the current backward (this shard-
    level double buffer serves every stage — overlap trades stage 1's
    full-accumulator layout for the hidden wire)."""
    # Stage 2/3: backward_passes_per_step accumulates INSIDE the shard,
    # so the accumulator is a [padded // world] leaf, not a full gradient
    # replica. Stage 1 keeps the classic full local-gradient accumulator
    # (per-rank leading-axis state); the wire still runs every microbatch
    # — branchless where-selection (lax.cond fails shard_map rep
    # inference on jax 0.4.x) cannot elide a collective — so stage 1's
    # distinguishing property is the accumulator LAYOUT, which is what
    # the bench's grad-bytes-per-rank A/B measures.
    k = backward_passes_per_step
    db = overlap and k > 1  # double-buffered accumulation
    s1 = stage == 1 and k > 1 and not db  # full-grad accumulation
    stx = (_zero_multi_steps(optimizer, k)
           if k > 1 and not db and not s1 else optimizer)
    num_comm_streams = max(1, int(num_comm_streams))

    if gradient_predivide_factor != 1.0:
        prescale = 1.0 / gradient_predivide_factor
        reduce_op = C.ReduceOp.SUM
        postscale_mode = "predivide"
    else:
        prescale = 1.0
        reduce_op = op
        postscale_mode = None

    def _threshold():
        if fusion_threshold_bytes is not None:
            return fusion_threshold_bytes
        return None  # plan_buckets resolves the config default

    def _plan(leaves, plan_world):
        return fusion.plan_buckets(leaves, _threshold(),
                                   shard_multiple=plan_world)

    def _rank(in_trace: bool):
        if in_trace:
            return lax.axis_index(C._resolve_axes(axes))  # traced index
        return basics.rank() if basics.is_initialized() else 0

    def _shard_params(plan, leaves, own_world, in_trace):
        if own_world == 1:
            return tuple(fusion.pack(b, leaves) for b in plan)
        r = _rank(in_trace)
        return tuple(
            fusion.shard_slice(fusion.pack(b, leaves), own_world, r)
            for b in plan)

    def _res_read(res_entry, in_trace):
        if res_entry is None:
            return None
        r = 0 if in_trace else _rank(False)
        return res_entry[r]

    def _res_write(old_entry, new_local, in_trace):
        if old_entry is None:
            return None
        if in_trace:
            return new_local[None]
        r = _rank(False)
        return old_entry.at[r].set(new_local)

    def init_fn(params):
        # Every stage's init takes the MODEL-tree params (host-side the
        # full pytree; stage 3 callers shard the params separately with
        # zero3_shard_params — the optimizer state layout is identical).
        leaves, _ = jax.tree.flatten(params)
        plan_world, own_world, in_trace = _zero_worlds(axes)
        plan = _plan(leaves, plan_world)
        shards = _shard_params(plan, leaves, own_world, in_trace)
        inner = stx.init(shards)
        lead = 1 if in_trace else max(1, plan_world)
        if db:
            inner = ZeroOverlapMultiStepsState(
                mini_step=jnp.zeros((), jnp.int32),
                inner=inner,
                acc_shards=tuple(
                    jnp.zeros(jnp.shape(s), jnp.float32) for s in shards),
                pending=tuple(
                    jnp.zeros((lead, b.padded_size), b.dtype)
                    for b in plan))
        elif s1:
            inner = ZeroFullMultiStepsState(
                mini_step=jnp.zeros((), jnp.int32),
                inner=inner,
                acc=tuple(
                    jnp.zeros((lead,) + tuple(jnp.shape(l)), jnp.float32)
                    for l in leaves))
        if not quantized:
            return ZeroState(inner=inner, residual=None,
                             gather_residual=None)
        nl = _zero_local_size(in_trace)
        # In-trace state carries the [1, ...] per-rank leading axis slice
        # (P(HVD_AXES) convention); host-side init builds the full
        # [world, ...] stack.
        rs, ag = [], []
        for shp in _zero_residual_shapes(plan, plan_world, nl):
            if shp is None:
                rs.append(None)
                ag.append(None)
            else:
                rs.append(jnp.zeros((lead,) + shp[0], jnp.float32))
                ag.append(jnp.zeros((lead,) + shp[1], jnp.float32))
        # Stage 3 has no trailing all-gather, hence no gather residual.
        return ZeroState(inner=inner, residual=tuple(rs),
                         gather_residual=(None if stage == 3
                                          else tuple(ag)))

    def update_fn(grads, state, params=None, **extra):
        gleaves, treedef = jax.tree.flatten(grads)
        plan_world, own_world, in_trace = _zero_worlds(axes)
        plan = _plan(gleaves, plan_world)
        axes_t = C._resolve_axes(axes)

        postscale = 1.0
        if postscale_mode == "predivide":
            postscale = gradient_predivide_factor / max(1, own_world)

        if in_trace and axes_t:
            # Already-psummed replicated gradients (the auto-psum of
            # replicated params under shard_map autodiff) become exact
            # per-rank locals: rank 0 contributes the full sum, everyone
            # else zeros — bitwise-exact under any reduction order, and
            # it keeps mixed replicated/varying buckets correct through
            # one reduce-scatter.
            r0 = lax.axis_index(axes_t) == 0
            gleaves = [
                jnp.where(r0, leaf, jnp.zeros_like(leaf))
                if C._is_replicated(leaf, axes_t) else leaf
                for leaf in gleaves
            ]

        # Host-side update under single-controller SPMD (own_world == 1):
        # the state is global, the "shard" is the whole bucket, and — as
        # on the replicated path's eager allreduce over a world of one —
        # no collective runs.
        eager_local = (not in_trace) and own_world == 1

        use_quant = quantized
        order = (fusion.stream_order(plan) if overlap
                 else tuple(range(len(plan))))
        flight = num_comm_streams if overlap else 1

        ms = state.inner if (db or s1) else None
        if db or s1:
            t = ms.mini_step
            is_last = t == (k - 1)
        new_acc_full: Optional[Tuple[Any, ...]] = None
        if s1:
            # Stage 1: accumulate the RAW local gradients (full model
            # layout, per-rank leading-axis state) BEFORE the wire; the
            # running mean feeds every call's reduce-scatter and only
            # the k-th call's result survives the where-selection.
            acc_loc = tuple(_res_read(a, in_trace) for a in ms.acc)
            acc_new = tuple(a + g.astype(a.dtype)
                            for a, g in zip(acc_loc, gleaves))
            gleaves = [(a / float(k)).astype(jnp.asarray(g).dtype)
                       for a, g in zip(acc_new, gleaves)]
            new_acc_full = tuple(
                _res_write(old, jnp.where(is_last, jnp.zeros_like(n), n),
                           in_trace)
                for old, n in zip(ms.acc, acc_new))
        new_pending: List[Any] = [None] * len(plan)

        gshards: List[Any] = [None] * len(plan)
        new_rs: List[Any] = [None] * len(plan)
        for s in range(0, len(order), flight):
            issued = []
            for i in order[s:s + flight]:
                b = plan[i]
                buf = fusion.pack(b, gleaves)
                if db:
                    # Double buffer: this call's wire carries the PREVIOUS
                    # microbatch's packed buckets (no dependence on this
                    # call's backward); the final call folds the last
                    # microbatch in (the wire is linear).
                    pend = _res_read(ms.pending[i], in_trace)
                    new_pending[i] = _res_write(
                        ms.pending[i],
                        jnp.where(is_last, jnp.zeros_like(buf), buf),
                        in_trace)
                    buf = jnp.where(is_last, pend + buf, pend)
                is_float = jnp.issubdtype(b.dtype, jnp.floating)
                wire, ctx = compression.compress(buf)
                if eager_local:
                    shard = C._scale(C._scale(wire, prescale), postscale)
                    new_rs[i] = (None if state.residual is None
                                 else state.residual[i])
                    gshards[i] = compression.decompress(shard, ctx)
                    continue
                res = (None
                       if not (use_quant and is_float and state.residual)
                       else _res_read(state.residual[i], in_trace))
                rs_kw = dict(op=reduce_op, prescale_factor=prescale,
                             postscale_factor=postscale,
                             block=quant_block, fused=fused,
                             _presummed=True)
                if res is not None:
                    if overlap:
                        shard, nres = C.reduce_scatter_stream(
                            wire, res, bucket_id=i, quantized=True, **rs_kw)
                    else:
                        shard, nres = C.reduce_scatter(
                            wire, res, quantized=True, **rs_kw)
                    new_rs[i] = _res_write(state.residual[i], nres,
                                           in_trace)
                else:
                    if overlap:
                        shard = C.reduce_scatter_stream(
                            wire, bucket_id=i,
                            quantized=use_quant and is_float, **rs_kw)
                    else:
                        shard = C.reduce_scatter(
                            wire, quantized=use_quant and is_float, **rs_kw)
                    new_rs[i] = (None if state.residual is None
                                 else state.residual[i])
                issued.append((i, shard, ctx))
            # Decompress after the whole flight is issued: no consumer
            # between in-flight scatters (flight == 1 == the serial
            # schedule exactly).
            for i, shard, ctx in issued:
                gshards[i] = compression.decompress(shard, ctx)

        pshards = None
        if params is not None:
            pleaves, _ = jax.tree.flatten(params)
            if stage == 3:
                # Stage 3: params arrive ALREADY in shard space — the
                # zero3_shard_params tuple the training loop owns (each
                # rank's [padded // world] flat bucket shards in-trace;
                # the global [padded] buckets host-side).
                if len(pleaves) != len(plan):
                    raise ValueError(
                        f"zero_stage=3 expects params as the "
                        f"zero3_shard_params tuple ({len(plan)} flat "
                        f"bucket shards), got {len(pleaves)} leaves — "
                        f"pass the shard tree the loop applies updates "
                        f"to, not the gathered model params")
                pshards = tuple(pleaves)
            else:
                pshards = _shard_params(plan, pleaves, own_world, in_trace)

        if db:
            acc = tuple(a + g.astype(a.dtype)
                        for a, g in zip(ms.acc_shards, gshards))
            mean = tuple((a / k).astype(jnp.asarray(g).dtype)
                         for a, g in zip(acc, gshards))
            upd, inner_new = optimizer.update(mean, ms.inner, pshards,
                                              **extra)
            ushards = tuple(
                jnp.where(is_last, u, jnp.zeros_like(u)) for u in upd)
            inner_next = jax.tree.map(
                lambda old, new: jnp.where(is_last, new, old),
                ms.inner, inner_new)
            acc_next = tuple(
                jnp.where(is_last, jnp.zeros_like(a), a) for a in acc)
            new_inner = ZeroOverlapMultiStepsState(
                mini_step=(t + 1) % k, inner=inner_next,
                acc_shards=acc_next, pending=tuple(new_pending))
        elif s1:
            upd, inner_new = optimizer.update(tuple(gshards), ms.inner,
                                              pshards, **extra)
            ushards = tuple(
                jnp.where(is_last, u, jnp.zeros_like(u)) for u in upd)
            inner_next = jax.tree.map(
                lambda old, new: jnp.where(is_last, new, old),
                ms.inner, inner_new)
            new_inner = ZeroFullMultiStepsState(
                mini_step=(t + 1) % k, inner=inner_next,
                acc=new_acc_full)
        else:
            ushards, new_inner = stx.update(tuple(gshards), state.inner,
                                            pshards, **extra)

        if stage == 3:
            # No trailing all-gather: the updates stay in shard space and
            # the caller applies them to its shard tree (the next step's
            # forward re-gathers just in time). This is where stage 3's
            # wire asymmetry lives — the gather moved from the update's
            # tail to the forward's head, where it overlaps with compute.
            new_state = ZeroState(
                inner=new_inner,
                residual=None if state.residual is None else tuple(new_rs),
                gather_residual=None)
            if params is not None:
                updates = jax.tree.unflatten(
                    jax.tree.structure(params), list(ushards))
            else:
                updates = tuple(ushards)
            return updates, new_state

        uleaves: List[Any] = [None] * len(gleaves)
        new_ag: List[Any] = [None] * len(plan)
        for s in range(0, len(order), flight):
            issued = []
            for i in order[s:s + flight]:
                b = plan[i]
                is_float = jnp.issubdtype(b.dtype, jnp.floating)
                if eager_local:
                    new_ag[i] = (None if state.gather_residual is None
                                 else state.gather_residual[i])
                    issued.append((i, ushards[i], None))
                    continue
                wire, ctx = compression.compress(ushards[i])
                res = (None
                       if not (use_quant and is_float
                               and state.gather_residual)
                       else _res_read(state.gather_residual[i], in_trace))
                if res is not None:
                    if overlap:
                        full, nres = C.all_gather_stream(
                            wire, res, bucket_id=i, quantized=True,
                            block=quant_block, fused=fused)
                    else:
                        full, nres = C.all_gather(
                            wire, res, quantized=True, block=quant_block,
                            fused=fused)
                    new_ag[i] = _res_write(state.gather_residual[i], nres,
                                           in_trace)
                else:
                    if overlap:
                        full = C.all_gather_stream(
                            wire, bucket_id=i,
                            quantized=use_quant and is_float,
                            block=quant_block, fused=fused)
                    else:
                        full = C.all_gather(
                            wire, quantized=use_quant and is_float,
                            block=quant_block, fused=fused)
                    new_ag[i] = (None if state.gather_residual is None
                                 else state.gather_residual[i])
                issued.append((i, full, ctx))
            for i, full, ctx in issued:
                if ctx is not None or not eager_local:
                    full = compression.decompress(full, ctx)
                for j, leaf in zip(plan[i].leaf_indices,
                                   fusion.unpack(plan[i], full)):
                    uleaves[j] = leaf

        new_state = ZeroState(
            inner=new_inner,
            residual=None if state.residual is None else tuple(new_rs),
            gather_residual=(None if state.gather_residual is None
                             else tuple(new_ag)))
        return jax.tree.unflatten(treedef, uleaves), new_state

    return optax.GradientTransformationExtraArgs(init_fn, update_fn)


# ---------------------------------------------------------------------------
# ZeRO-3 parameter sharding: the training loop owns flat bucket shards;
# the forward gathers them just in time (docs/zero.md).
# ---------------------------------------------------------------------------


def _zero3_rank(in_trace: bool, axes=None):
    if in_trace:
        return lax.axis_index(C._resolve_axes(axes))
    return basics.rank() if basics.is_initialized() else 0


def zero3_plan(params_template, *, fusion_threshold_bytes=None, axes=None):
    """The stage-3 bucket plan of a parameter pytree —
    ``plan_buckets(shard_multiple=world)`` over the flattened leaves, the
    SAME plan :class:`DistributedOptimizer`'s update derives from the
    gradient tree, so parameter, gradient, and moment shard layouts all
    agree (``params_template`` needs only shapes/dtypes)."""
    leaves, _ = jax.tree.flatten(params_template)
    plan_world, _, _ = _zero_worlds(axes)
    return fusion.plan_buckets(leaves, fusion_threshold_bytes,
                               shard_multiple=plan_world)


def zero3_shard_params(params, *, fusion_threshold_bytes=None, axes=None):
    """Pack a parameter pytree into its flat bucket (shard) tuple — what
    a ``zero_stage=3`` training loop owns instead of the model tree.

    Host-side (single-controller SPMD) this returns the GLOBAL form —
    one full ``[padded]`` flat buffer per bucket; ``device_put`` with
    :func:`zero3_param_pspecs` then hands each rank its rank-major
    ``1/world`` slice. In-trace (or under the eager process world) it
    returns this rank's ``[padded // world]`` shards directly. Round-trip
    with :func:`zero3_gather_params`."""
    leaves, _ = jax.tree.flatten(params)
    plan_world, own_world, in_trace = _zero_worlds(axes)
    plan = fusion.plan_buckets(leaves, fusion_threshold_bytes,
                               shard_multiple=plan_world)
    if own_world == 1:
        return tuple(fusion.pack(b, leaves) for b in plan)
    r = _zero3_rank(in_trace, axes)
    return tuple(
        fusion.shard_slice(fusion.pack(b, leaves), own_world, r)
        for b in plan)


def zero3_param_pspecs(pshards):
    """PartitionSpec tree for a :func:`zero3_shard_params` tuple: every
    flat bucket shards rank-major along its (only) axis —
    ``P(HVD_AXES)``, exactly like the ZeRO moment leaves."""
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(lambda _: P(basics.HVD_AXES), pshards)


def zero3_gather_params(
    pshards,
    params_template,
    *,
    fusion_threshold_bytes=None,
    axes=None,
    overlap: Optional[bool] = None,
    num_comm_streams: Optional[int] = None,
    fill_sched=None,
):
    """Reassemble the full model pytree from stage-3 parameter shards —
    the just-in-time gather a ``zero_stage=3`` forward runs on.

    In-trace each bucket all-gathers (replicated by construction, so the
    result feeds replicated consumers directly) in FORWARD order
    (:func:`~horovod_tpu.ops.fusion.gather_order` — lowest leaf index
    first, the layers the forward needs soonest), through the PR-5
    stream entry points in flights of ``num_comm_streams`` when
    ``overlap`` is on: unpacking is deferred past the flight so the
    latency-hiding scheduler can run deeper layers' gathers under the
    already-gathered layers' compute. Host-side, on the GLOBAL shard
    form, this is a pure unpack (no wire) — the exact inverse of
    :func:`zero3_shard_params`. ``params_template`` supplies structure
    and shapes only (``jax.ShapeDtypeStruct`` leaves work).

    ``fill_sched`` (a ``PPSchedule``) opens a T3-style
    :func:`~horovod_tpu.plan.accounting.bubble_fill` window around the
    streamed gathers: up to ``fill_sched.idle_ticks_per_rank`` bucket
    flights are credited against the pipeline schedule's idle ticks
    (``WireStats.bubble_hidden_bytes`` / ``comm.pp.filled_ticks``,
    docs/pipeline.md). Accounting-only — the issue order is unchanged;
    requires ``overlap`` (unstreamed gathers cannot be latency-hidden).
    """
    tleaves, treedef = jax.tree.flatten(params_template)
    plan_world, own_world, in_trace = _zero_worlds(axes)
    plan = fusion.plan_buckets(tleaves, fusion_threshold_bytes,
                               shard_multiple=plan_world)
    shards = list(jax.tree.leaves(pshards))
    if len(shards) != len(plan):
        raise ValueError(
            f"pshards has {len(shards)} buckets but the template plans "
            f"{len(plan)} — pass the tuple zero3_shard_params produced "
            f"for this parameter tree (same threshold, same world)")
    overlap_on, flight = fusion._resolve_overlap(overlap, num_comm_streams,
                                                 None)
    order = fusion.gather_order(plan)
    if not overlap_on:
        flight = 1
    eager_local = (not in_trace) and own_world == 1
    fill_ctx = contextlib.nullcontext()
    if fill_sched is not None and overlap_on and not eager_local:
        from ..plan import accounting as _acct_mod

        fill_ctx = _acct_mod.bubble_fill(fill_sched.idle_ticks_per_rank,
                                         kind="zero3.ag")
    uleaves: List[Any] = [None] * len(tleaves)
    with fill_ctx:
        for s in range(0, len(order), flight):
            issued = []
            for i in order[s:s + flight]:
                if eager_local:
                    full = shards[i]  # global form already
                elif overlap_on:
                    full = C.all_gather_stream(shards[i], bucket_id=i,
                                               axes=axes)
                else:
                    full = C.all_gather(shards[i], axes=axes)
                issued.append((i, full))
            # Unpack AFTER the whole flight is issued (ops/fusion.py
            # flight contract): no consumer sits between in-flight
            # gathers.
            for i, full in issued:
                for j, leaf in zip(plan[i].leaf_indices,
                                   fusion.unpack(plan[i], full)):
                    uleaves[j] = leaf
    return jax.tree.unflatten(treedef, uleaves)


def zero3_reshard_params(
    pshards,
    params_template,
    *,
    from_world: int,
    to_world: int,
    fusion_threshold_bytes: Optional[int] = None,
):
    """Re-shard a GLOBAL (host-side) stage-3 parameter tuple between
    world sizes — the elastic/checkpoint-restore path, the parameter
    analogue of :func:`zero_reshard_state`. Exact: each bucket unpacks to
    parameter layout under the old plan and repacks under the new one
    (leaf→bucket assignment is world-independent, padding holds zeros),
    so a round-trip is the identity."""
    tleaves, _ = jax.tree.flatten(params_template)
    plan_f = fusion.plan_buckets(tleaves, fusion_threshold_bytes,
                                 shard_multiple=from_world)
    plan_t = fusion.plan_buckets(tleaves, fusion_threshold_bytes,
                                 shard_multiple=to_world)
    shards = list(jax.tree.leaves(pshards))
    if len(shards) != len(plan_f):
        raise ValueError(
            f"pshards has {len(shards)} buckets, plan has {len(plan_f)}")
    return tuple(
        fusion.pack(bt, _scatter_unpack(bf, buf, len(tleaves)))
        for bf, bt, buf in zip(plan_f, plan_t, shards))


def zero_reshard_state(
    state: ZeroState,
    params,
    *,
    from_world: int,
    to_world: int,
    to_local_size: Optional[int] = None,
    fusion_threshold_bytes: Optional[int] = None,
) -> ZeroState:
    """Re-shard a GLOBAL (host-side) :class:`ZeroState` between world
    sizes — the elastic resize path.

    Bucket padding depends on the world size
    (``plan_buckets(shard_multiple=world)``), so a state saved at one
    world cannot be ``device_put`` at another directly. This unpacks
    every bucket-flat moment leaf back to parameter layout under the old
    plan and repacks it under the new plan (leaf→bucket assignment is
    world-independent, so the mapping is exact and a round-trip is the
    identity — padding slots hold zeros by construction). EF residuals
    are approximation state tied to the old wire geometry and reset to
    zeros at the new one.

    Expects ``state`` in its global form (full ``[padded]`` flat leaves —
    what host-side ``init`` produces and what ``jax.device_get`` of a
    ``P(HVD_AXES)``-sharded running state yields); ``params`` is the
    matching parameter pytree. Shard with
    :func:`zero_state_pspecs` after resharding.

    Generalizes across all three stages (stage-3 PARAMETER shards are
    loop-owned, not optimizer state — reshard those with
    :func:`zero3_reshard_params`): bucket-flat moment groups (and the
    stage-2 :class:`ZeroMultiStepsState` shard accumulator, which shares
    their signature) remap exactly, mid-cycle included. Leading-axis
    per-rank MICROBATCH state — the stage-1
    :class:`ZeroFullMultiStepsState` accumulator and the overlap
    double-buffer's pending buckets — is wire/cycle geometry and is
    rebuilt as zeros at the new world, so reshard at a cycle boundary
    (``mini_step == 0``), where those buffers hold zeros anyway and the
    round-trip stays the identity.
    """
    leaves_p, _ = jax.tree.flatten(params)
    plan_f = fusion.plan_buckets(leaves_p, fusion_threshold_bytes,
                                 shard_multiple=from_world)
    plan_t = fusion.plan_buckets(leaves_p, fusion_threshold_bytes,
                                 shard_multiple=to_world)
    k = len(plan_f)
    n_leaves = len(leaves_p)
    sig = [(jnp.dtype(b.dtype), b.padded_size) for b in plan_f]
    pshapes = [tuple(jnp.shape(l)) for l in leaves_p]

    flat, treedef = jax.tree.flatten(state.inner)
    out: List[Any] = []
    j = 0
    while j < len(flat):
        group = flat[j:j + k]
        if (len(group) == k and all(
                getattr(g, "ndim", 0) == 1
                and jnp.dtype(g.dtype) == d and g.shape[0] == p
                for g, (d, p) in zip(group, sig))):
            # One moment group (e.g. Adam's mu across all buckets):
            # bucket-flat under plan_f → param layout → bucket-flat
            # under plan_t.
            for g, bf, bt in zip(group, plan_f, plan_t):
                out.append(
                    fusion.pack(bt, _scatter_unpack(bf, g, len(leaves_p))))
            j += k
            continue
        if (len(group) == k and all(
                getattr(g, "ndim", 0) == 2
                and jnp.dtype(g.dtype) == d
                and g.shape == (from_world, p)
                for g, (d, p) in zip(group, sig))):
            # Overlap double-buffer pending ([world, padded] per bucket):
            # cycle-boundary zeros, rebuilt at the new world's padding.
            if from_world == to_world:
                out.extend(group)
            else:
                out.extend(
                    jnp.zeros((to_world, bt.padded_size), g.dtype)
                    for g, bt in zip(group, plan_t))
            j += k
            continue
        groupa = flat[j:j + n_leaves]
        if (len(groupa) == n_leaves and n_leaves > 0 and all(
                getattr(g, "ndim", -1) == 1 + len(ps)
                and tuple(g.shape) == (from_world,) + ps
                for g, ps in zip(groupa, pshapes))):
            # Stage-1 full-gradient accumulator ([world, *param_shape]
            # per leaf): cycle-boundary zeros at the new world.
            if from_world == to_world:
                out.extend(groupa)
            else:
                out.extend(
                    jnp.zeros((to_world,) + ps, g.dtype)
                    for g, ps in zip(groupa, pshapes))
            j += n_leaves
            continue
        out.append(flat[j])
        j += 1
    inner = jax.tree.unflatten(treedef, out)

    if state.residual is None:
        return ZeroState(inner=inner, residual=None, gather_residual=None)
    nl = (to_local_size if to_local_size is not None
          else (basics.local_size() if basics.is_initialized()
                else to_world))
    rs, ag = [], []
    for shp in _zero_residual_shapes(plan_t, to_world, nl):
        if shp is None:
            rs.append(None)
            ag.append(None)
        else:
            rs.append(jnp.zeros((to_world,) + shp[0], jnp.float32))
            ag.append(jnp.zeros((to_world,) + shp[1], jnp.float32))
    return ZeroState(inner=inner, residual=tuple(rs),
                     gather_residual=tuple(ag))


def _scatter_unpack(bucket, buf, n_leaves: int) -> List[Any]:
    """Unpack one bucket-flat buffer into a dense leaf list positioned at
    the bucket's leaf indices (so ``fusion.pack`` of the TARGET plan —
    whose ``leaf_indices`` are identical — can repack it)."""
    leaves: List[Any] = [None] * n_leaves
    for i, leaf in zip(bucket.leaf_indices, fusion.unpack(bucket, buf)):
        leaves[i] = leaf
    return leaves
