"""DistributedOptimizer: gradient-allreducing optimizer wrapper.

Reference: ``hvd.DistributedOptimizer`` for TF (tensorflow/__init__.py:293-336,
435-508) and torch (torch/optimizer.py:103-200). There, per-parameter hooks
fire asynchronous allreduces as gradients become ready and ``step()`` blocks
on all handles.

TPU-native redesign
-------------------
Our optimizer story is optax. ``DistributedOptimizer(tx)`` returns an
``optax.GradientTransformation`` whose ``update`` first allreduces the
gradient pytree — fused into per-dtype flat buckets (ops/fusion.py), with
optional bf16/fp16 wire compression — and then runs the wrapped
transformation. Because the whole step is compiled, XLA overlaps the bucket
collectives with the optimizer math and backward compute automatically; the
reference needs its background thread + ready-event machinery
(operations.cc:354-624) to get the same overlap dynamically.

``backward_passes_per_step`` reproduces the reference's local gradient
accumulation (torch/optimizer.py:67-68,133-149): gradients are accumulated
locally for k microbatches and allreduced once, via ``optax.MultiSteps``.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from ..common import basics
from ..common.config import _env_bool
from ..ops import collective_ops as C
from ..ops import fusion
from ..ops.compression import Compression


class QuantizedEFState(NamedTuple):
    """Optimizer state of a quantized ``DistributedOptimizer``.

    ``inner`` is the wrapped transformation's state. ``residual`` is the
    error-feedback accumulator: a pytree matching the parameters whose
    leaves carry a leading **per-rank axis** — each rank's residual is
    rank-local state (every EF-SGD formulation keeps it per worker), so
    under ``jax.shard_map`` the leaves must ride ``P(hvd.HVD_AXES)``
    in/out specs (shape ``[world, *param_shape]`` outside the trace, this
    rank's ``[1, *param_shape]`` slice inside), not the replicated ``P()``
    of the inner state. A spec prefix of
    ``QuantizedEFState(P(), hvd.data_pspec())`` does exactly that — see
    ``bench.py --quantized`` for the worked example.
    """

    inner: Any
    residual: Any


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    *,
    compression=Compression.none,
    op: C.ReduceOp = C.ReduceOp.AVERAGE,
    backward_passes_per_step: int = 1,
    gradient_predivide_factor: float = 1.0,
    fusion_threshold_bytes: Optional[int] = None,
    hierarchical: Optional[bool] = None,
    quantized: Optional[bool] = None,
    axes=None,
    tuned_params=None,
) -> optax.GradientTransformation:
    """Wrap an optax transformation with fused gradient allreduce.

    Args mirror the reference's DistributedOptimizer signature
    (tensorflow/__init__.py:435-508): ``compression`` (wire dtype),
    ``op`` (Average | Sum | Adasum), ``backward_passes_per_step``
    (local accumulation), ``gradient_predivide_factor`` (split the averaging
    divisor across pre/post scaling: prescale = 1/f applied before the sum,
    postscale = f/N after — tensorflow/__init__.py:462-476).

    ``quantized`` (default: the ``HOROVOD_QUANTIZED_ALLREDUCE`` knob) moves
    each fused gradient bucket over the blockwise-int8 DCN wire with
    per-bucket error feedback: the state becomes a
    :class:`QuantizedEFState` wrapping the inner state plus a per-rank
    residual pytree, and each step's quantization error is carried into
    the next step's gradient, keeping convergence at full-precision
    quality. Only meaningful when the gradients reaching ``update`` are
    per-rank locals (e.g. via ``hvd.value_and_grad(..., reduce=False)``);
    auto-psummed replicated gradients never touch the wire, so there is
    nothing to quantize.

    ``tuned_params`` (an ``autotune.TunedParams``, e.g. the winner of
    :func:`horovod_tpu.autotune_session`) overrides the fusion threshold,
    hierarchical flag, and int8 scale-block for this optimizer's gradient
    allreduce wherever the explicit kwargs above were left unset —
    rebuilding the optimizer with a new override is exactly what one
    autotune trial does (the step retraces with the new bucket plan).
    """
    if gradient_predivide_factor != 1.0 and op != C.ReduceOp.AVERAGE:
        raise ValueError(
            "gradient_predivide_factor is only supported with op=Average "
            "(reference: tensorflow/__init__.py:452-455)")
    if backward_passes_per_step < 1:
        raise ValueError("backward_passes_per_step must be >= 1")
    quant_block = None
    if tuned_params is not None:
        if fusion_threshold_bytes is None:
            fusion_threshold_bytes = tuned_params.fusion_threshold_bytes
        if hierarchical is None:
            hierarchical = tuned_params.hierarchical_allreduce
        quant_block = tuned_params.quant_block
    if quantized is None:
        quantized = (basics.config().quantized_allreduce
                     if basics.is_initialized()
                     else _env_bool("HOROVOD_QUANTIZED_ALLREDUCE", False))

    if gradient_predivide_factor != 1.0:
        # Average == Sum with the divisor split across pre/post scaling.
        prescale = 1.0 / gradient_predivide_factor
        reduce_op = C.ReduceOp.SUM
        # postscale completes the average: f / N, with N resolved at trace
        # time inside _allreduce (world size is static under the mesh).
        postscale_mode = "predivide"
    else:
        prescale = 1.0
        reduce_op = op
        postscale_mode = None

    def _allreduce(grads, error_feedback=None):
        postscale = 1.0
        if postscale_mode == "predivide":
            axes_t = C._resolve_axes(axes)
            n = C._world_size(axes_t) if axes_t else 1
            postscale = gradient_predivide_factor / n
        return fusion.allreduce_pytree(
            grads,
            op=reduce_op,
            compression=compression,
            threshold_bytes=fusion_threshold_bytes,
            axes=axes,
            hierarchical=hierarchical,
            prescale_factor=prescale,
            postscale_factor=postscale,
            presummed=True,  # invariant grads are autodiff-psummed sums
            quantized=quantized,
            error_feedback=error_feedback,
            block=quant_block,
        )

    def _res_read(residual):
        """Strip the per-rank leading axis: in-trace each rank's shard is
        its ``[1, ...]`` slice; eagerly row ``rank()`` of the full stack."""
        r = 0 if C._hvd_axes_in_trace() else (
            basics.rank() if basics.is_initialized() else 0)
        return jax.tree.map(lambda a: a[r], residual)

    def _res_write(residual, new_local):
        if C._hvd_axes_in_trace():
            return jax.tree.map(lambda a: a[None], new_local)
        r = basics.rank() if basics.is_initialized() else 0
        return jax.tree.map(lambda a, v: a.at[r].set(v), residual, new_local)

    def init_fn(params):
        inner = optimizer.init(params)
        if not quantized:
            return inner
        world = basics.size() if basics.is_initialized() else 1
        residual = jax.tree.map(
            lambda p: jnp.zeros((world,) + jnp.shape(p), jnp.asarray(p).dtype),
            params)
        return QuantizedEFState(inner=inner, residual=residual)

    def update_fn(grads, state, params=None, **extra):
        if not quantized:
            reduced = _allreduce(grads)
            return optimizer.update(reduced, state, params, **extra)
        reduced, new_res = _allreduce(grads, _res_read(state.residual))
        updates, new_inner = optimizer.update(
            reduced, state.inner, params, **extra)
        return updates, QuantizedEFState(
            inner=new_inner,
            residual=_res_write(state.residual, new_res))

    tx = optax.GradientTransformationExtraArgs(init_fn, update_fn)
    if backward_passes_per_step > 1:
        # Accumulate locally, allreduce + apply every k-th microbatch
        # (reference: torch/optimizer.py:133-149).
        tx = optax.MultiSteps(tx, every_k_schedule=backward_passes_per_step)
    return tx
