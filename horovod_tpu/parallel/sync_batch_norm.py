"""SyncBatchNorm: batch normalization with cross-rank statistics.

Reference: ``horovod/torch/sync_batch_norm.py`` (199 LoC with a handwritten
autograd.Function doing allgather of counts/mean/var and a custom backward)
and TF ``horovod/tensorflow/sync_batch_norm.py``.

TPU-native redesign: in JAX the forward computes global moments with
``lax.psum`` over the Horovod mesh axes and the backward falls out of
autodiff through the collective — psum is its own transpose, so the
reference's 100-line custom backward disappears. Implemented as a flax
linen module matching ``nn.BatchNorm``'s surface.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

from ..common import basics


class SyncBatchNorm(nn.Module):
    """Drop-in ``flax.linen.BatchNorm`` that reduces batch statistics across
    the Horovod mesh axes, so every rank normalizes with the *global* batch
    moments (reference: torch/sync_batch_norm.py:60-130).

    Attributes mirror ``nn.BatchNorm``; ``axis_name`` defaults to the
    Horovod world axes when tracing under the mesh.
    """

    use_running_average: Optional[bool] = None
    axis: int = -1
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Optional[Any] = None
    use_bias: bool = True
    use_scale: bool = True
    axis_name: Optional[Any] = None

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        use_ra = nn.merge_param(
            "use_running_average", self.use_running_average,
            use_running_average)
        axis_name = self.axis_name
        if axis_name is None:
            bound = basics._bound_axes()
            in_mesh = tuple(a for a in basics.HVD_AXES if a in bound)
            axis_name = in_mesh if in_mesh else None
        norm = nn.BatchNorm(
            use_running_average=use_ra,
            axis=self.axis,
            momentum=self.momentum,
            epsilon=self.epsilon,
            dtype=self.dtype,
            use_bias=self.use_bias,
            use_scale=self.use_scale,
            axis_name=axis_name,
        )
        return norm(x)
