"""Pipeline parallelism: GPipe-style microbatch schedule over the mesh.

The reference framework is data-parallel only (SURVEY §2.7); this is the
TPU-native pipeline layer, built SPMD-style the way XLA wants it: every
rank runs the SAME program each step — its own stage on whatever
activation it holds — and activations hop to the next stage over a
non-cyclic ``lax.ppermute`` (neighbor ICI hop). With M microbatches and
n stages the schedule is the classic M + n - 1 steps; ranks in the
fill/drain bubble compute garbage that never reaches an output (masked
writes), the standard price of an SPMD pipeline.

* :func:`gpipe` — generic: ``stage_fn(stage_params, x)`` applied to a
  [M, ...] microbatch array, returns the [M, ...] outputs REPLICATED on
  every rank (the last stage's results are broadcast by a masked psum).
  Fully differentiable: the backward pass replays the schedule with
  transposed ppermutes — exactly the GPipe backward.
* :func:`pp_split_blocks` — slices a dense GPT checkpoint into stacked
  per-stage block parameters (+ the replicated embedding/head tree).
* :func:`pipelined_gpt_apply` — the GPT assembly: embedding and LM head
  are computed replicated on every rank, the transformer stack runs
  through the pipeline (inference / logits consumers).
* :func:`pipelined_gpt_loss` — the TRAINING assembly: the LM head (the
  dominant [B, T, vocab] einsum at real scale) is VOCAB-SHARDED over the
  pipeline ranks with a Megatron-style sharded cross-entropy, so the
  per-rank head cost is O(1/n) in compute and logits memory.

Exact vs the dense model (tests/test_pipeline_parallel.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .sequence import _axis_size


def _pvary_tree(tree, axes):
    """pvary_missing over every leaf (single home for the tree-mapped
    form of collective_ops' idiom)."""
    from ..ops.collective_ops import pvary_missing

    if not axes:
        return tree
    return jax.tree.map(lambda a: pvary_missing(a, tuple(axes)), tree)


def _carry_axes(axis, x_mbs, stage_params):
    """Varying-axes type for pipeline scan carries: the pipeline axis
    itself plus whatever the inputs/stage params already vary over (e.g.
    a data-parallel batch axis). Single home for both schedules' inits."""
    from ..ops.collective_ops import _vma

    ring = {axis} if isinstance(axis, str) else set(axis)
    return tuple(sorted(
        ring | _vma(x_mbs)
        | frozenset().union(*[_vma(l) for l in
                              jax.tree.leaves(stage_params)])))


def gpipe(stage_fn, stage_params, x_mbs, *, axis):
    """Run microbatches [M, ...] through n pipeline stages over ``axis``.

    ``stage_fn(stage_params, x)`` maps one microbatch through THIS rank's
    stage (same shapes in and out). Returns [M, ...] outputs of the full
    pipeline, identical on every rank.
    """
    n = _axis_size(axis)
    if n == 1:
        return jax.vmap(lambda x: stage_fn(stage_params, x))(x_mbs)
    r = lax.axis_index(axis)
    M = x_mbs.shape[0]
    steps = M + n - 1
    shift = [(i, i + 1) for i in range(n - 1)]   # non-cyclic: 0→1→...→n-1

    def body(carry, t):
        state, outputs = carry
        # Stage 0 injects microbatch t; later stages consume the incoming
        # activation from their left neighbor.
        mb_in = x_mbs[jnp.clip(t, 0, M - 1)]
        x = jnp.where(r == 0, mb_in, state)
        y = stage_fn(stage_params, x)
        # The last stage finishes microbatch t - (n - 1); write it (only
        # there, only when valid — other ranks contribute zeros so a
        # final psum broadcasts the real values).
        out_idx = t - (n - 1)
        valid = jnp.logical_and(r == n - 1, out_idx >= 0)
        write = jnp.where(valid, y, 0).astype(outputs.dtype)
        idx = jnp.clip(out_idx, 0, M - 1)
        outputs = outputs.at[idx].set(
            jnp.where(valid, write, outputs[idx]))
        # Hop to the next stage (rank n-1's output leaves the ring; rank
        # 0 receives zeros it never reads).
        state = lax.ppermute(y, axis, shift)
        return (state, outputs), None

    # Scan carries become varying over the pipeline axis (per-rank stages
    # and the masked writes); the fresh zero inits must match. pcast only
    # the axes a value does not already vary over (zeros_like inherits
    # e.g. a data-parallel batch axis from x_mbs).
    from ..ops.collective_ops import pvary_missing

    axes_t = _carry_axes(axis, x_mbs, stage_params)
    state0 = pvary_missing(jnp.zeros_like(x_mbs[0]), axes_t)
    outputs0 = pvary_missing(jnp.zeros(x_mbs.shape, x_mbs.dtype), axes_t)
    (_, outputs), _ = lax.scan(body, (state0, outputs0),
                               jnp.arange(steps))
    # Only the last stage holds real outputs; the masked psum replicates
    # them everywhere (all other ranks contributed zeros).
    return lax.psum(outputs, axis)


def pp_split_blocks(params, n: int):
    """Dense GPT params → (stages, rest).

    ``stages``: for each transformer-block leaf ``h{i}/...`` a stacked
    array [n, L/n, ...] — stage r holds blocks [r·L/n, (r+1)·L/n); pass
    through shard_map with ``in_specs=P(pp_axis)`` and squeeze the
    leading dim. ``rest``: embedding/final-LN (replicated, ``P()``).
    """
    blocks = sorted((k for k in params if k.startswith("h")),
                    key=lambda k: int(k[1:]))
    L = len(blocks)
    if L % n:
        raise ValueError(f"{L} blocks not divisible by {n} stages")
    per = L // n

    def stack_stage_leaves(*leaves):
        # leaves: the same param across all L blocks, in order.
        return jnp.stack(
            [jnp.stack(leaves[s * per:(s + 1) * per]) for s in range(n)])

    stages = jax.tree.map(stack_stage_leaves,
                          *[params[b] for b in blocks])
    rest = {k: v for k, v in params.items() if not k.startswith("h")}
    return stages, rest


def _validate_pipeline_cfg(cfg, B, T, num_microbatches, axis):
    if B % num_microbatches:
        raise ValueError(
            f"batch {B} not divisible by {num_microbatches} microbatches")
    if T > cfg.max_seq_len:
        # Same guard as GPT.__call__: jit gathers clamp out-of-bounds
        # indices, which would silently reuse the last positional
        # embedding.
        raise ValueError(f"sequence length {T} exceeds "
                         f"max_seq_len={cfg.max_seq_len}")
    if cfg.moe_experts:
        raise ValueError(
            "the pipelined GPT assembly does not support MoE blocks: the "
            "router's sown aux loss cannot be returned through the "
            "pipeline stages (apply the MoE model under DP/EP instead)")
    if getattr(cfg, "tp_axis", None) and _axis_size(cfg.tp_axis) > 1:
        # With an ACTIVE tp axis (size > 1 — models/gpt.py's _tp_size
        # no-ops a size-1 axis), _Attention/_Mlp psum partial products
        # over it — but pp_split_blocks hands every pipeline rank FULL
        # (un-tp-sliced) stage weights, so those psums would sum complete
        # outputs tp-fold and silently produce garbage.
        raise ValueError(
            "the pipelined GPT assembly does not support tp_axis: stage "
            "parameters are not tensor-parallel-sliced (compose TP with "
            "DP/SP instead, or drop tp_axis for the pipeline path)")
    if cfg.attention in ("ring", "flash_ring", "ulysses"):
        seq_axes = ({cfg.seq_axis} if isinstance(cfg.seq_axis, str)
                    else set(cfg.seq_axis))
        pp_axes = {axis} if isinstance(axis, str) else set(axis)
        if seq_axes & pp_axes:
            # Mirrors the tp/seq overlap guard in models/gpt.py _Attention:
            # a K/V rotation over the pipeline axis would exchange tensors
            # between ranks holding DIFFERENT pipeline stages and silently
            # produce garbage.
            raise ValueError(
                f"attention={cfg.attention!r} is sequence-parallel over "
                f"seq_axis={cfg.seq_axis!r}, which overlaps the pipeline "
                f"axis {axis!r}; use disjoint mesh axes")


def _embed(cfg, ep, tokens):
    """Token + positional embedding from an {wte, wpe} tree (single home
    for the pipeline paths; differentiable w.r.t. ``ep``)."""
    T = tokens.shape[1]
    return (ep["wte"][tokens]
            + ep["wpe"][jnp.arange(T)][None]).astype(cfg.dtype)


def _make_stage_fn(cfg):
    """This rank's stage: its stacked [L/n, ...] blocks folded over the
    activation (single home for both schedules)."""
    from ..models.gpt import _Block

    block = _Block(cfg)

    def stage_fn(stacked, h):
        def one(h, bp):
            return block.apply({"params": bp}, h), None

        h, _ = lax.scan(one, h, stacked)
        return h

    return stage_fn


def _pipeline_hidden(cfg, stage_params, rest, tokens, *, axis,
                     num_microbatches):
    """Embedding + pipelined transformer stack → final hidden [B, T, C]
    (pre-ln_f), replicated over ``axis``."""
    B, T = tokens.shape
    _validate_pipeline_cfg(cfg, B, T, num_microbatches, axis)
    x = _embed(cfg, rest, tokens)
    x_mbs = x.reshape(num_microbatches, B // num_microbatches, T, -1)
    h = gpipe(_make_stage_fn(cfg), stage_params, x_mbs, axis=axis)
    return h.reshape(B, T, -1)


def _head_logits(cfg, rest, h):
    import flax.linen as nn

    ln = nn.LayerNorm(dtype=cfg.dtype)
    hn = ln.apply({"params": rest["ln_f"]}, h)
    return jnp.einsum("btc,vc->btv", hn, rest["wte"].astype(cfg.dtype),
                      preferred_element_type=jnp.float32)


def pipelined_gpt_apply(cfg, stage_params, rest, tokens, *, axis,
                        num_microbatches: int):
    """Forward a GPT through the pipeline. Inside shard_map: ``tokens``
    [B, T] replicated over ``axis``, ``stage_params`` this rank's stacked
    [L/n, ...] block tree, ``rest`` the replicated embedding/head tree.
    Returns logits [B, T, vocab] (replicated over ``axis``).

    Every rank computes the full [B, T, vocab] head einsum on the
    replicated hidden states; for training prefer
    :func:`pipelined_gpt_loss`, which vocab-shards the head across the
    pipeline ranks (per-rank head compute and logits memory O(1/n); the
    [B, T, C] hidden broadcast remains)."""
    h = _pipeline_hidden(cfg, stage_params, rest, tokens, axis=axis,
                         num_microbatches=num_microbatches)
    return _head_logits(cfg, rest, h)


def pipelined_gpt_loss(cfg, stage_params, rest, tokens, targets, *, axis,
                       num_microbatches: int):
    """Mean LM cross-entropy of the pipelined GPT with a VOCAB-PARALLEL
    head: the [B, T, V] einsum — the dominant term of a GPT step at real
    scale — is sharded over the pipeline ranks instead of replicated.

    :func:`pipelined_gpt_apply` makes every rank compute the full head on
    the replicated hidden states, so pipelining saved nothing on the
    dominant cost. Here each rank computes logits for its own V/n vocab
    columns of the (replicated) hidden states and the softmax
    cross-entropy is assembled with the Megatron-style sharded-vocab
    reduction — a ``pmax`` for the global row max, one ``psum`` for the
    global sum-of-exps, one ``psum`` for the label logit (exactly one
    rank holds each label's column). Per-rank head compute AND logits
    memory are O(1/n) of the replicated form, every rank does useful
    work (no idle bubble ranks), and there is no per-device control flow
    for XLA to choke on. Fully differentiable (slice/psum/gpipe all
    transpose; the row max rides ``stop_gradient``, the standard exact
    logsumexp trick). Exact vs the dense model's loss
    (tests/test_pipeline_parallel.py)."""
    import optax

    n = _axis_size(axis)
    h = _pipeline_hidden(cfg, stage_params, rest, tokens, axis=axis,
                         num_microbatches=num_microbatches)
    if n == 1:
        logits = _head_logits(cfg, rest, h)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()

    import flax.linen as nn

    ln = nn.LayerNorm(dtype=cfg.dtype)
    hn = ln.apply({"params": rest["ln_f"]}, h)
    wte = rest["wte"].astype(cfg.dtype)
    V, C = wte.shape
    Vp = -(-V // n)  # ceil: per-rank vocab shard
    # Pad to n*Vp rows so the per-rank dynamic_slice is never clamped
    # (clamping would silently desync vpos from the actual rows).
    wpad = jnp.pad(wte, ((0, n * Vp - V), (0, 0)))
    ax = axis if isinstance(axis, str) else tuple(axis)
    r = lax.axis_index(ax)
    w_shard = lax.dynamic_slice(wpad, (r * Vp, jnp.int32(0)), (Vp, C))
    logits_loc = jnp.einsum("btc,vc->btv", hn, w_shard,
                            preferred_element_type=jnp.float32)
    vpos = r * Vp + jax.lax.broadcasted_iota(jnp.int32, (Vp,), 0)
    valid = vpos < V
    logits_loc = jnp.where(valid[None, None, :], logits_loc, -jnp.inf)

    # Label logit: exactly one rank's shard holds each target column.
    hit = vpos[None, None, :] == targets[..., None]
    tgt_logit = lax.psum(
        jnp.sum(jnp.where(hit, logits_loc, 0.0), axis=-1), ax)
    # Global logsumexp over the sharded vocab. stop_gradient goes INSIDE
    # pmax (pmax has no JVP rule, but a symbolically-zero tangent never
    # reaches it), and pmax — not all_gather+max — re-establishes the
    # replicated (invariant) typing the P() out-spec needs. Any m gives
    # the same lse mathematically; it only sets fp scaling.
    m = lax.pmax(lax.stop_gradient(jnp.max(logits_loc, axis=-1)), ax)
    sumexp = lax.psum(
        jnp.sum(jnp.exp(logits_loc - m[..., None]), axis=-1), ax)
    lse = m + jnp.log(sumexp)
    return jnp.mean(lse - tgt_logit)


def gpipe_1f1b(stage_fn, loss_fn, stage_params, head_params, x_mbs,
               tgt_mbs, *, axis):
    """1F1B pipeline schedule: loss + gradients in one fused pass with
    O(pipeline_depth) activation memory.

    :func:`gpipe` differentiates its forward scan with autodiff, so the
    backward retains residuals for ALL M microbatches per rank — O(M)
    activation memory, GPipe's classic cost. This schedule hand-interleaves
    one-forward-one-backward: stage r runs F(m) at tick m+r and B(m) at
    tick m+2n-1-r, so at most 2n-1-2r microbatches are in flight per rank
    and the stash is a static ``[2n-1, ...]`` ring buffer — O(n), however
    large M grows. Backward uses input-stash rematerialization (the stage
    forward is recomputed at B time for its VJP — one extra forward per
    microbatch, the standard remat trade).

    ``stage_fn(stage_params, x)`` is this rank's stage.
    ``loss_fn(head_params, y, tgt)`` maps the LAST stage's output to a
    scalar per-microbatch loss (every rank evaluates it SPMD-style; only
    the last rank's result/cotangents are un-masked). Returns
    ``(loss, d_stage_params, d_head_params, d_x_mbs)`` where ``loss`` is
    the mean over microbatches (replicated over the PIPELINE axis),
    ``d_stage_params`` is this rank's stage-parameter gradient
    (device-varying, like the stage parameters themselves),
    ``d_head_params`` is replicated over the pipeline axis, and
    ``d_x_mbs`` is the gradient w.r.t. the pipeline input (for the
    caller's embedding backward).

    Composing with data parallelism: when the inputs are sharded over a
    DP axis, every returned gradient is PER-DATA-SHARD — average over
    the DP axes yourself (``hvd.allreduce_pytree(op=Average,
    axes=...)``), exactly as with ``jax.grad`` under shard_map. All
    parameter trees enter their vjps as varying copies internally so the
    implicit pvary transpose cannot pre-sum shards
    (tests/test_pipeline_parallel.py::test_dp_1f1b_2d).
    """
    n = _axis_size(axis)
    M = x_mbs.shape[0]
    if n == 1:
        # Same per-data-shard gradient contract as the scheduled path:
        # when the inputs vary over a DP axis, params enter the grad as
        # varying copies or the implicit pvary transpose psums shard
        # gradients together. Everything is harmonized to the UNION of
        # varying axes (a size-1 pipeline in_spec still marks params
        # varying over it), and the trailing ring psums — numerically
        # identity over a size-1 axis — restore the n>1 output typing
        # (gh/gx ring-invariant, gs ring-varying). All of this is a
        # no-op outside shard_map, where _vma is empty.
        from ..ops.collective_ops import _vma

        ring = ({axis} if isinstance(axis, str) else set(axis))
        union = set()
        for leaf in (jax.tree.leaves(stage_params)
                     + jax.tree.leaves(head_params)
                     + [x_mbs, tgt_mbs]):
            union |= _vma(leaf)
        union_t = tuple(sorted(union))

        sp_in, hp_in, x_in, tgt_in = (
            _pvary_tree(stage_params, union_t),
            _pvary_tree(head_params, union_t),
            _pvary_tree(x_mbs, union_t), _pvary_tree(tgt_mbs, union_t))

        def total(sp, hp, x):
            ys = jax.vmap(lambda xm: stage_fn(sp, xm))(x)
            losses = jax.vmap(lambda ym, tm: loss_fn(hp, ym, tm))(
                ys, tgt_in)
            return losses.mean()

        loss, (gs, gh, gx) = jax.value_and_grad(total, argnums=(0, 1, 2))(
            sp_in, hp_in, x_in)
        ring_in_union = tuple(a for a in sorted(ring) if a in union)
        if ring_in_union:
            # identity over the size-1 ring axis; drops it from the vma
            gh = jax.tree.map(lambda a: lax.psum(a, ring_in_union), gh)
            gx = lax.psum(gx, ring_in_union)
            loss = lax.psum(loss, ring_in_union)
        return loss, gs, gh, gx

    ax = axis if isinstance(axis, str) else tuple(axis)
    r = lax.axis_index(ax)
    S = 2 * n - 1                       # max microbatches in flight
    T_ticks = M + 2 * n - 1
    up = [(i, i + 1) for i in range(n - 1)]
    down = [(i + 1, i) for i in range(n - 1)]
    is_last = r == n - 1
    fzero = jnp.float32(0)

    from ..ops.collective_ops import _vma, pvary_missing

    axes_t = _carry_axes(axis, x_mbs, stage_params)

    def vary(tree):
        return _pvary_tree(tree, axes_t)

    mb_shape = x_mbs.shape[1:]
    zeros_mb = pvary_missing(jnp.zeros(mb_shape, x_mbs.dtype), axes_t)
    carry0 = (
        zeros_mb,                                        # act in transit
        zeros_mb.astype(jnp.float32),                    # grad in transit
        vary(jnp.zeros((S,) + mb_shape, x_mbs.dtype)),   # input stash
        zeros_mb.astype(jnp.float32),                    # dy (last stage)
        vary(jax.tree.map(jnp.zeros_like, stage_params)),  # d_stage
        vary(jax.tree.map(
            lambda a: jnp.zeros(a.shape, jnp.float32), head_params)),
        vary(jnp.zeros(x_mbs.shape, jnp.float32)),       # d_x_mbs
        pvary_missing(fzero, axes_t),                    # loss accum
    )

    def tick(carry, t):
        act, gract, stash, dy_state, d_sp, d_hp, d_x, loss_acc = carry

        # ---- backward phase FIRST: B(m_b), m_b = t - (2n - 1 - r) ----
        # B consumes only previous-tick state (stash written at F time
        # ticks ago, gract/dy_state from the prior tick). Running F first
        # would overwrite dy_state with the NEXT microbatch's cotangent
        # before B(m_b) reads it — off-by-one on every last-stage grad.
        m_b = t - (2 * n - 1 - r)
        b_valid = jnp.logical_and(m_b >= 0, m_b < M)
        x_saved = stash[jnp.clip(m_b, 0, M - 1) % S]
        # Varying copy for the same reason as hp_vary below: under a DP
        # axis the stage params are invariant over it, and the implicit
        # pvary's transpose would psum shard gradients together.
        _, stage_vjp = jax.vjp(
            lambda p, x: stage_fn(p, x), vary(stage_params), x_saved)
        gy = jnp.where(is_last, dy_state, gract)
        g_sp_m, gx = stage_vjp(gy.astype(x_saved.dtype))
        d_sp = jax.tree.map(
            lambda acc, g: acc + jnp.where(b_valid, g, 0.0).astype(
                acc.dtype), d_sp, g_sp_m)
        bidx = jnp.clip(m_b, 0, M - 1)
        write_dx = jnp.logical_and(b_valid, r == 0)
        d_x = d_x.at[bidx].set(
            jnp.where(write_dx, gx.astype(jnp.float32), d_x[bidx]))
        new_gract = lax.ppermute(gx.astype(jnp.float32), ax, down)

        # ---- forward phase: F(m_f) with m_f = t - r ----
        m_f = t - r
        f_valid = jnp.logical_and(m_f >= 0, m_f < M)
        x_in = jnp.where(r == 0, x_mbs[jnp.clip(m_f, 0, M - 1)], act)
        y = stage_fn(stage_params, x_in)
        slot_f = jnp.clip(m_f, 0, M - 1) % S
        stash = stash.at[slot_f].set(
            jnp.where(f_valid, x_in, stash[slot_f]))

        # last stage: per-microbatch loss + output cotangent + head grads.
        # The head params enter the vjp as a VARYING copy: differentiating
        # through the replicated (invariant) tree would transpose the
        # implicit pvary into a psum, summing every rank's garbage-y
        # contribution into g_hp_m before our mask can drop it.
        hp_vary = vary(head_params)
        tgt = tgt_mbs[jnp.clip(m_f, 0, M - 1)]
        loss_m, head_vjp = jax.vjp(
            lambda hp, y: loss_fn(hp, y, tgt), hp_vary, y)
        # The seed cotangent must carry the same varying axes as loss_m.
        g_hp_m, dy = head_vjp(pvary_missing(jnp.float32(1),
                                            tuple(sorted(_vma(loss_m)))))
        take = jnp.logical_and(is_last, f_valid)
        loss_acc = loss_acc + jnp.where(take, loss_m, fzero)
        d_hp = jax.tree.map(
            lambda acc, g: acc + jnp.where(take, g, 0.0).astype(acc.dtype),
            d_hp, g_hp_m)
        dy_state = jnp.where(take, dy.astype(jnp.float32), dy_state)
        act = lax.ppermute(y, ax, up)

        return (act, new_gract, stash, dy_state, d_sp, d_hp, d_x,
                loss_acc), None

    (_, _, _, _, d_sp, d_hp, d_x, loss_acc), _ = lax.scan(
        tick, carry0, jnp.arange(T_ticks))

    # loss/head grads live on the last stage, input grads on stage 0;
    # masked psums replicate (every other rank contributed zeros).
    loss = lax.psum(loss_acc, ax) / M
    d_hp = jax.tree.map(
        lambda a: lax.psum(a, ax) / M, d_hp)
    d_x = lax.psum(d_x, ax) / M
    return loss, jax.tree.map(lambda a: a / M, d_sp), d_hp, d_x


def pipelined_gpt_train_1f1b(cfg, stage_params, rest, tokens, targets, *,
                             axis, num_microbatches: int):
    """One fused GPT training computation under the 1F1B schedule:
    returns ``(loss, d_stage_params, d_rest)`` directly (the schedule
    hand-interleaves forward and backward, so this is not a function you
    differentiate — it IS the gradient computation).

    Same contract as :func:`pipelined_gpt_loss` + ``jax.grad``, with
    activation memory O(pipeline_depth) instead of O(num_microbatches):
    use it when M must be large (deep pipelines want M >> n to shrink
    the bubble, which is exactly when GPipe's O(M) stash hurts). The LM
    head runs replicated per microbatch on every rank (masked off the
    last stage) — the memory-lean counterpart of
    :func:`pipelined_gpt_loss`'s vocab-sharded head; exactness vs the
    dense model is tested for both."""
    import optax

    B, T = tokens.shape
    _validate_pipeline_cfg(cfg, B, T, num_microbatches, axis)
    M = num_microbatches

    ep = {"wte": rest["wte"], "wpe": rest["wpe"]}
    # Like the head params in gpipe_1f1b: when tokens are data-sharded
    # (varying over a DP axis), the replicated embedding tree must enter
    # its vjp as a varying copy, or the implicit pvary transposes into a
    # psum over the data axis and g_ep comes back SUMMED across shards —
    # the caller's DP gradient averaging then over-counts.
    from ..ops.collective_ops import _vma

    ep = _pvary_tree(ep, tuple(sorted(_vma(tokens))))
    x, embed_vjp = jax.vjp(lambda ep: _embed(cfg, ep, tokens), ep)
    x_mbs = x.reshape(M, B // M, T, -1)
    tgt_mbs = targets.reshape(M, B // M, T)

    def loss_fn(hp, y, tgt):
        # hp carries exactly the {ln_f, wte} keys _head_logits reads.
        return optax.softmax_cross_entropy_with_integer_labels(
            _head_logits(cfg, hp, y), tgt).mean()

    hp = {"ln_f": rest["ln_f"], "wte": rest["wte"]}
    loss, g_stages, g_hp, d_x = gpipe_1f1b(
        _make_stage_fn(cfg), loss_fn, stage_params, hp, x_mbs, tgt_mbs,
        axis=axis)
    (g_ep,) = embed_vjp(d_x.reshape(B, T, -1).astype(x.dtype))
    g_rest = {
        # wte is tied: embedding-lookup grad + LM-head grad
        "wte": g_ep["wte"].astype(jnp.float32) + g_hp["wte"],
        "wpe": g_ep["wpe"].astype(jnp.float32),
        "ln_f": g_hp["ln_f"],
    }
    return loss, g_stages, g_rest
