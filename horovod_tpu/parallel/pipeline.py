"""Pipeline parallelism: GPipe-style microbatch schedule over the mesh.

The reference framework is data-parallel only (SURVEY §2.7); this is the
TPU-native pipeline layer, built SPMD-style the way XLA wants it: every
rank runs the SAME program each step — its own stage on whatever
activation it holds — and activations hop to the next stage over a
non-cyclic ``lax.ppermute`` (neighbor ICI hop). With M microbatches and
n stages the schedule is the classic M + n - 1 steps; ranks in the
fill/drain bubble compute garbage that never reaches an output (masked
writes), the standard price of an SPMD pipeline.

* :func:`gpipe` — generic: ``stage_fn(stage_params, x)`` applied to a
  [M, ...] microbatch array, returns the [M, ...] outputs REPLICATED on
  every rank (the last stage's results are broadcast by a masked psum).
  Fully differentiable: the backward pass replays the schedule with
  transposed ppermutes — exactly the GPipe backward.
* :func:`pp_split_blocks` — slices a dense GPT checkpoint into stacked
  per-stage block parameters (+ the replicated embedding/head tree).
* :func:`pipelined_gpt_apply` — the GPT assembly: embedding and LM head
  are computed replicated on every rank, the transformer stack runs
  through the pipeline (inference / logits consumers).
* :func:`pipelined_gpt_loss` — the TRAINING assembly: the LM head (the
  dominant [B, T, vocab] einsum at real scale) is VOCAB-SHARDED over the
  pipeline ranks with a Megatron-style sharded cross-entropy, so the
  per-rank head cost is O(1/n) in compute and logits memory.

Exact vs the dense model (tests/test_pipeline_parallel.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .sequence import _axis_size


def gpipe(stage_fn, stage_params, x_mbs, *, axis):
    """Run microbatches [M, ...] through n pipeline stages over ``axis``.

    ``stage_fn(stage_params, x)`` maps one microbatch through THIS rank's
    stage (same shapes in and out). Returns [M, ...] outputs of the full
    pipeline, identical on every rank.
    """
    n = _axis_size(axis)
    if n == 1:
        return jax.vmap(lambda x: stage_fn(stage_params, x))(x_mbs)
    r = lax.axis_index(axis)
    M = x_mbs.shape[0]
    steps = M + n - 1
    shift = [(i, i + 1) for i in range(n - 1)]   # non-cyclic: 0→1→...→n-1

    def body(carry, t):
        state, outputs = carry
        # Stage 0 injects microbatch t; later stages consume the incoming
        # activation from their left neighbor.
        mb_in = x_mbs[jnp.clip(t, 0, M - 1)]
        x = jnp.where(r == 0, mb_in, state)
        y = stage_fn(stage_params, x)
        # The last stage finishes microbatch t - (n - 1); write it (only
        # there, only when valid — other ranks contribute zeros so a
        # final psum broadcasts the real values).
        out_idx = t - (n - 1)
        valid = jnp.logical_and(r == n - 1, out_idx >= 0)
        write = jnp.where(valid, y, 0).astype(outputs.dtype)
        idx = jnp.clip(out_idx, 0, M - 1)
        outputs = outputs.at[idx].set(
            jnp.where(valid, write, outputs[idx]))
        # Hop to the next stage (rank n-1's output leaves the ring; rank
        # 0 receives zeros it never reads).
        state = lax.ppermute(y, axis, shift)
        return (state, outputs), None

    # Scan carries become varying over the pipeline axis (per-rank stages
    # and the masked writes); the fresh zero inits must match. pcast only
    # the axes a value does not already vary over (zeros_like inherits
    # e.g. a data-parallel batch axis from x_mbs).
    from ..ops.collective_ops import _vma, pvary_missing

    ring = {axis} if isinstance(axis, str) else set(axis)
    axes_t = tuple(sorted(
        ring | _vma(x_mbs)
        | frozenset().union(*[_vma(l) for l in
                              jax.tree.leaves(stage_params)])))
    state0 = pvary_missing(jnp.zeros_like(x_mbs[0]), axes_t)
    outputs0 = pvary_missing(jnp.zeros(x_mbs.shape, x_mbs.dtype), axes_t)
    (_, outputs), _ = lax.scan(body, (state0, outputs0),
                               jnp.arange(steps))
    # Only the last stage holds real outputs; the masked psum replicates
    # them everywhere (all other ranks contributed zeros).
    return lax.psum(outputs, axis)


def pp_split_blocks(params, n: int):
    """Dense GPT params → (stages, rest).

    ``stages``: for each transformer-block leaf ``h{i}/...`` a stacked
    array [n, L/n, ...] — stage r holds blocks [r·L/n, (r+1)·L/n); pass
    through shard_map with ``in_specs=P(pp_axis)`` and squeeze the
    leading dim. ``rest``: embedding/final-LN (replicated, ``P()``).
    """
    blocks = sorted((k for k in params if k.startswith("h")),
                    key=lambda k: int(k[1:]))
    L = len(blocks)
    if L % n:
        raise ValueError(f"{L} blocks not divisible by {n} stages")
    per = L // n

    def stack_stage_leaves(*leaves):
        # leaves: the same param across all L blocks, in order.
        return jnp.stack(
            [jnp.stack(leaves[s * per:(s + 1) * per]) for s in range(n)])

    stages = jax.tree.map(stack_stage_leaves,
                          *[params[b] for b in blocks])
    rest = {k: v for k, v in params.items() if not k.startswith("h")}
    return stages, rest


def _validate_pipeline_cfg(cfg, B, T, num_microbatches, axis):
    if B % num_microbatches:
        raise ValueError(
            f"batch {B} not divisible by {num_microbatches} microbatches")
    if T > cfg.max_seq_len:
        # Same guard as GPT.__call__: jit gathers clamp out-of-bounds
        # indices, which would silently reuse the last positional
        # embedding.
        raise ValueError(f"sequence length {T} exceeds "
                         f"max_seq_len={cfg.max_seq_len}")
    if cfg.moe_experts:
        raise ValueError(
            "the pipelined GPT assembly does not support MoE blocks: the "
            "router's sown aux loss cannot be returned through the "
            "pipeline stages (apply the MoE model under DP/EP instead)")
    if cfg.attention in ("ring", "flash_ring", "ulysses"):
        seq_axes = ({cfg.seq_axis} if isinstance(cfg.seq_axis, str)
                    else set(cfg.seq_axis))
        pp_axes = {axis} if isinstance(axis, str) else set(axis)
        if seq_axes & pp_axes:
            # Mirrors the tp/seq overlap guard in models/gpt.py _Attention:
            # a K/V rotation over the pipeline axis would exchange tensors
            # between ranks holding DIFFERENT pipeline stages and silently
            # produce garbage.
            raise ValueError(
                f"attention={cfg.attention!r} is sequence-parallel over "
                f"seq_axis={cfg.seq_axis!r}, which overlaps the pipeline "
                f"axis {axis!r}; use disjoint mesh axes")


def _pipeline_hidden(cfg, stage_params, rest, tokens, *, axis,
                     num_microbatches):
    """Embedding + pipelined transformer stack → final hidden [B, T, C]
    (pre-ln_f), replicated over ``axis``."""
    from ..models.gpt import _Block

    B, T = tokens.shape
    _validate_pipeline_cfg(cfg, B, T, num_microbatches, axis)
    wte, wpe = rest["wte"], rest["wpe"]
    x = (wte[tokens] + wpe[jnp.arange(T)][None]).astype(cfg.dtype)
    x_mbs = x.reshape(num_microbatches, B // num_microbatches, T, -1)

    block = _Block(cfg)

    def stage_fn(stacked, h):
        def one(h, bp):
            return block.apply({"params": bp}, h), None

        h, _ = lax.scan(one, h, stacked)
        return h

    h = gpipe(stage_fn, stage_params, x_mbs, axis=axis)
    return h.reshape(B, T, -1)


def _head_logits(cfg, rest, h):
    import flax.linen as nn

    ln = nn.LayerNorm(dtype=cfg.dtype)
    hn = ln.apply({"params": rest["ln_f"]}, h)
    return jnp.einsum("btc,vc->btv", hn, rest["wte"].astype(cfg.dtype),
                      preferred_element_type=jnp.float32)


def pipelined_gpt_apply(cfg, stage_params, rest, tokens, *, axis,
                        num_microbatches: int):
    """Forward a GPT through the pipeline. Inside shard_map: ``tokens``
    [B, T] replicated over ``axis``, ``stage_params`` this rank's stacked
    [L/n, ...] block tree, ``rest`` the replicated embedding/head tree.
    Returns logits [B, T, vocab] (replicated over ``axis``).

    Every rank computes the full [B, T, vocab] head einsum on the
    replicated hidden states; for training prefer
    :func:`pipelined_gpt_loss`, which vocab-shards the head across the
    pipeline ranks (per-rank head compute and logits memory O(1/n); the
    [B, T, C] hidden broadcast remains)."""
    h = _pipeline_hidden(cfg, stage_params, rest, tokens, axis=axis,
                         num_microbatches=num_microbatches)
    return _head_logits(cfg, rest, h)


def pipelined_gpt_loss(cfg, stage_params, rest, tokens, targets, *, axis,
                       num_microbatches: int):
    """Mean LM cross-entropy of the pipelined GPT with a VOCAB-PARALLEL
    head: the [B, T, V] einsum — the dominant term of a GPT step at real
    scale — is sharded over the pipeline ranks instead of replicated.

    :func:`pipelined_gpt_apply` makes every rank compute the full head on
    the replicated hidden states, so pipelining saved nothing on the
    dominant cost. Here each rank computes logits for its own V/n vocab
    columns of the (replicated) hidden states and the softmax
    cross-entropy is assembled with the Megatron-style sharded-vocab
    reduction — a ``pmax`` for the global row max, one ``psum`` for the
    global sum-of-exps, one ``psum`` for the label logit (exactly one
    rank holds each label's column). Per-rank head compute AND logits
    memory are O(1/n) of the replicated form, every rank does useful
    work (no idle bubble ranks), and there is no per-device control flow
    for XLA to choke on. Fully differentiable (slice/psum/gpipe all
    transpose; the row max rides ``stop_gradient``, the standard exact
    logsumexp trick). Exact vs the dense model's loss
    (tests/test_pipeline_parallel.py)."""
    import optax

    n = _axis_size(axis)
    h = _pipeline_hidden(cfg, stage_params, rest, tokens, axis=axis,
                         num_microbatches=num_microbatches)
    if n == 1:
        logits = _head_logits(cfg, rest, h)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()

    import flax.linen as nn

    ln = nn.LayerNorm(dtype=cfg.dtype)
    hn = ln.apply({"params": rest["ln_f"]}, h)
    wte = rest["wte"].astype(cfg.dtype)
    V, C = wte.shape
    Vp = -(-V // n)  # ceil: per-rank vocab shard
    # Pad to n*Vp rows so the per-rank dynamic_slice is never clamped
    # (clamping would silently desync vpos from the actual rows).
    wpad = jnp.pad(wte, ((0, n * Vp - V), (0, 0)))
    ax = axis if isinstance(axis, str) else tuple(axis)
    r = lax.axis_index(ax)
    w_shard = lax.dynamic_slice(wpad, (r * Vp, jnp.int32(0)), (Vp, C))
    logits_loc = jnp.einsum("btc,vc->btv", hn, w_shard,
                            preferred_element_type=jnp.float32)
    vpos = r * Vp + jax.lax.broadcasted_iota(jnp.int32, (Vp,), 0)
    valid = vpos < V
    logits_loc = jnp.where(valid[None, None, :], logits_loc, -jnp.inf)

    # Label logit: exactly one rank's shard holds each target column.
    hit = vpos[None, None, :] == targets[..., None]
    tgt_logit = lax.psum(
        jnp.sum(jnp.where(hit, logits_loc, 0.0), axis=-1), ax)
    # Global logsumexp over the sharded vocab. stop_gradient goes INSIDE
    # pmax (pmax has no JVP rule, but a symbolically-zero tangent never
    # reaches it), and pmax — not all_gather+max — re-establishes the
    # replicated (invariant) typing the P() out-spec needs. Any m gives
    # the same lse mathematically; it only sets fp scaling.
    m = lax.pmax(lax.stop_gradient(jnp.max(logits_loc, axis=-1)), ax)
    sumexp = lax.psum(
        jnp.sum(jnp.exp(logits_loc - m[..., None]), axis=-1), ax)
    lse = m + jnp.log(sumexp)
    return jnp.mean(lse - tgt_logit)
