"""Pipeline parallelism: GPipe-style microbatch schedule over the mesh.

The reference framework is data-parallel only (SURVEY §2.7); this is the
TPU-native pipeline layer, built SPMD-style the way XLA wants it: every
rank runs the SAME program each step — its own stage on whatever
activation it holds — and activations hop to the next stage over a
non-cyclic ``lax.ppermute`` (neighbor ICI hop). With M microbatches and
n stages the schedule is the classic M + n - 1 steps; ranks in the
fill/drain bubble compute garbage that never reaches an output (masked
writes), the standard price of an SPMD pipeline.

* :func:`gpipe` — generic: ``stage_fn(stage_params, x)`` applied to a
  [M, ...] microbatch array, returns the [M, ...] outputs REPLICATED on
  every rank (the last stage's results are broadcast by a masked psum).
  Fully differentiable: the backward pass replays the schedule with
  transposed ppermutes — exactly the GPipe backward.
* :func:`pp_split_blocks` — slices a dense GPT checkpoint into stacked
  per-stage block parameters (+ the replicated embedding/head tree).
* :func:`pipelined_gpt_apply` — the GPT assembly: embedding and LM head
  are computed replicated on every rank, the transformer stack runs
  through the pipeline (inference / logits consumers).
* :func:`pipelined_gpt_loss` — the TRAINING assembly: the LM head (the
  dominant [B, T, vocab] einsum at real scale) is VOCAB-SHARDED over the
  pipeline ranks with a Megatron-style sharded cross-entropy, so the
  per-rank head cost is O(1/n) in compute and logits memory.

Exact vs the dense model (tests/test_pipeline_parallel.py).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .sequence import _axis_size


def _pvary_tree(tree, axes):
    """pvary_missing over every leaf (single home for the tree-mapped
    form of collective_ops' idiom)."""
    from ..ops.collective_ops import pvary_missing

    if not axes:
        return tree
    return jax.tree.map(lambda a: pvary_missing(a, tuple(axes)), tree)


def _send_plan_for_axis(axis, *, quantized: bool = False,
                        block: Optional[int] = None,
                        error_feedback: bool = False):
    """The send plan of a pipeline hop over ``axis`` (docs/pipeline.md):
    the leg's level is the slowest link class the axis tuple spans —
    pod > dcn > ici — because a hop over a multi-level axis crosses its
    widest stride. Quantization is forced off on an ICI hop (the
    EQuARX placement rule the IR validates)."""
    from ..common import basics
    from ..common.basics import CROSS_AXIS, PP_AXIS, POD_AXIS
    from ..plan import planner as _planner

    axes = {axis} if isinstance(axis, str) else set(axis)
    if POD_AXIS in axes:
        level = _planner.POD
    elif CROSS_AXIS in axes:
        level = _planner.DCN
    elif PP_AXIS in axes and basics.is_initialized():
        # The dedicated pp axis leads the mesh: one hop jumps a whole
        # data mesh, i.e. the slowest link class the DATA mesh spans.
        level = _planner.pp_send_level(basics.data_mesh_shape())
    else:
        level = _planner.ICI
    q = quantized and level != _planner.ICI
    return _planner.send_plan(level, quantized=q, block=block,
                              error_feedback=error_feedback and q)


def _carry_axes(axis, x_mbs, stage_params):
    """Varying-axes type for pipeline scan carries: the pipeline axis
    itself plus whatever the inputs/stage params already vary over (e.g.
    a data-parallel batch axis). Single home for both schedules' inits."""
    from ..ops.collective_ops import _vma

    ring = {axis} if isinstance(axis, str) else set(axis)
    return tuple(sorted(
        ring | _vma(x_mbs)
        | frozenset().union(*[_vma(l) for l in
                              jax.tree.leaves(stage_params)])))


def gpipe(stage_fn, stage_params, x_mbs, *, axis):
    """Run microbatches [M, ...] through n pipeline stages over ``axis``.

    ``stage_fn(stage_params, x)`` maps one microbatch through THIS rank's
    stage (same shapes in and out). Returns [M, ...] outputs of the full
    pipeline, identical on every rank.
    """
    n = _axis_size(axis)
    if n == 1:
        return jax.vmap(lambda x: stage_fn(stage_params, x))(x_mbs)
    r = lax.axis_index(axis)
    M = x_mbs.shape[0]
    steps = M + n - 1
    shift = [(i, i + 1) for i in range(n - 1)]   # non-cyclic: 0→1→...→n-1
    # The relay hop is a wire-plan send leg (docs/pipeline.md): same
    # ppermute as always, but lowered by plan/compiler.py so the legacy
    # GPipe wire finally shows up in WireStats/comm.bytes{hop} (the scan
    # body traces once — ``repeats=steps`` charges the true per-pass
    # bytes; the autodiff-transposed backward hop is not re-accounted).
    from ..plan import compiler as _compiler

    splan = _send_plan_for_axis(axis)

    def body(carry, t):
        state, outputs = carry
        # Stage 0 injects microbatch t; later stages consume the incoming
        # activation from their left neighbor.
        mb_in = x_mbs[jnp.clip(t, 0, M - 1)]
        x = jnp.where(r == 0, mb_in, state)
        y = stage_fn(stage_params, x)
        # The last stage finishes microbatch t - (n - 1); write it (only
        # there, only when valid — other ranks contribute zeros so a
        # final psum broadcasts the real values).
        out_idx = t - (n - 1)
        valid = jnp.logical_and(r == n - 1, out_idx >= 0)
        write = jnp.where(valid, y, 0).astype(outputs.dtype)
        idx = jnp.clip(out_idx, 0, M - 1)
        outputs = outputs.at[idx].set(
            jnp.where(valid, write, outputs[idx]))
        # Hop to the next stage (rank n-1's output leaves the ring; rank
        # 0 receives zeros it never reads).
        state, _ = _compiler.lower_send(splan, y, axis=axis, perm=shift,
                                        repeats=steps)
        return (state, outputs), None

    # Scan carries become varying over the pipeline axis (per-rank stages
    # and the masked writes); the fresh zero inits must match. pcast only
    # the axes a value does not already vary over (zeros_like inherits
    # e.g. a data-parallel batch axis from x_mbs).
    from ..ops.collective_ops import pvary_missing

    axes_t = _carry_axes(axis, x_mbs, stage_params)
    state0 = pvary_missing(jnp.zeros_like(x_mbs[0]), axes_t)
    outputs0 = pvary_missing(jnp.zeros(x_mbs.shape, x_mbs.dtype), axes_t)
    (_, outputs), _ = lax.scan(body, (state0, outputs0),
                               jnp.arange(steps))
    # Only the last stage holds real outputs; the masked psum replicates
    # them everywhere (all other ranks contributed zeros).
    return lax.psum(outputs, axis)


def pp_split_blocks(params, n: int):
    """Dense GPT params → (stages, rest).

    ``stages``: for each transformer-block leaf ``h{i}/...`` a stacked
    array [n, L/n, ...] — stage r holds blocks [r·L/n, (r+1)·L/n); pass
    through shard_map with ``in_specs=P(pp_axis)`` and squeeze the
    leading dim. ``rest``: embedding/final-LN (replicated, ``P()``).
    """
    blocks = sorted((k for k in params if k.startswith("h")),
                    key=lambda k: int(k[1:]))
    L = len(blocks)
    if L % n:
        raise ValueError(f"{L} blocks not divisible by {n} stages")
    per = L // n

    def stack_stage_leaves(*leaves):
        # leaves: the same param across all L blocks, in order.
        return jnp.stack(
            [jnp.stack(leaves[s * per:(s + 1) * per]) for s in range(n)])

    stages = jax.tree.map(stack_stage_leaves,
                          *[params[b] for b in blocks])
    rest = {k: v for k, v in params.items() if not k.startswith("h")}
    return stages, rest


def _validate_pipeline_cfg(cfg, B, T, num_microbatches, axis):
    if B % num_microbatches:
        raise ValueError(
            f"batch {B} not divisible by {num_microbatches} microbatches")
    if T > cfg.max_seq_len:
        # Same guard as GPT.__call__: jit gathers clamp out-of-bounds
        # indices, which would silently reuse the last positional
        # embedding.
        raise ValueError(f"sequence length {T} exceeds "
                         f"max_seq_len={cfg.max_seq_len}")
    if cfg.moe_experts:
        raise ValueError(
            "the pipelined GPT assembly does not support MoE blocks: the "
            "router's sown aux loss cannot be returned through the "
            "pipeline stages (apply the MoE model under DP/EP instead)")
    if getattr(cfg, "tp_axis", None) and _axis_size(cfg.tp_axis) > 1:
        # With an ACTIVE tp axis (size > 1 — models/gpt.py's _tp_size
        # no-ops a size-1 axis), _Attention/_Mlp psum partial products
        # over it — but pp_split_blocks hands every pipeline rank FULL
        # (un-tp-sliced) stage weights, so those psums would sum complete
        # outputs tp-fold and silently produce garbage.
        raise ValueError(
            "the pipelined GPT assembly does not support tp_axis: stage "
            "parameters are not tensor-parallel-sliced (compose TP with "
            "DP/SP instead, or drop tp_axis for the pipeline path)")
    if cfg.attention in ("ring", "flash_ring", "ulysses"):
        seq_axes = ({cfg.seq_axis} if isinstance(cfg.seq_axis, str)
                    else set(cfg.seq_axis))
        pp_axes = {axis} if isinstance(axis, str) else set(axis)
        if seq_axes & pp_axes:
            # Mirrors the tp/seq overlap guard in models/gpt.py _Attention:
            # a K/V rotation over the pipeline axis would exchange tensors
            # between ranks holding DIFFERENT pipeline stages and silently
            # produce garbage.
            raise ValueError(
                f"attention={cfg.attention!r} is sequence-parallel over "
                f"seq_axis={cfg.seq_axis!r}, which overlaps the pipeline "
                f"axis {axis!r}; use disjoint mesh axes")


def _embed(cfg, ep, tokens):
    """Token + positional embedding from an {wte, wpe} tree (single home
    for the pipeline paths; differentiable w.r.t. ``ep``)."""
    T = tokens.shape[1]
    return (ep["wte"][tokens]
            + ep["wpe"][jnp.arange(T)][None]).astype(cfg.dtype)


def _make_stage_fn(cfg):
    """This rank's stage: its stacked [L/n, ...] blocks folded over the
    activation (single home for both schedules)."""
    from ..models.gpt import _Block

    block = _Block(cfg)

    def stage_fn(stacked, h):
        def one(h, bp):
            return block.apply({"params": bp}, h), None

        h, _ = lax.scan(one, h, stacked)
        return h

    return stage_fn


def _pipeline_hidden(cfg, stage_params, rest, tokens, *, axis,
                     num_microbatches):
    """Embedding + pipelined transformer stack → final hidden [B, T, C]
    (pre-ln_f), replicated over ``axis``."""
    B, T = tokens.shape
    _validate_pipeline_cfg(cfg, B, T, num_microbatches, axis)
    x = _embed(cfg, rest, tokens)
    x_mbs = x.reshape(num_microbatches, B // num_microbatches, T, -1)
    h = gpipe(_make_stage_fn(cfg), stage_params, x_mbs, axis=axis)
    return h.reshape(B, T, -1)


def _head_logits(cfg, rest, h):
    import flax.linen as nn

    ln = nn.LayerNorm(dtype=cfg.dtype)
    hn = ln.apply({"params": rest["ln_f"]}, h)
    return jnp.einsum("btc,vc->btv", hn, rest["wte"].astype(cfg.dtype),
                      preferred_element_type=jnp.float32)


def pipelined_gpt_apply(cfg, stage_params, rest, tokens, *, axis,
                        num_microbatches: int):
    """Forward a GPT through the pipeline. Inside shard_map: ``tokens``
    [B, T] replicated over ``axis``, ``stage_params`` this rank's stacked
    [L/n, ...] block tree, ``rest`` the replicated embedding/head tree.
    Returns logits [B, T, vocab] (replicated over ``axis``).

    Every rank computes the full [B, T, vocab] head einsum on the
    replicated hidden states; for training prefer
    :func:`pipelined_gpt_loss`, which vocab-shards the head across the
    pipeline ranks (per-rank head compute and logits memory O(1/n); the
    [B, T, C] hidden broadcast remains)."""
    h = _pipeline_hidden(cfg, stage_params, rest, tokens, axis=axis,
                         num_microbatches=num_microbatches)
    return _head_logits(cfg, rest, h)


def pipelined_gpt_loss(cfg, stage_params, rest, tokens, targets, *, axis,
                       num_microbatches: int):
    """Mean LM cross-entropy of the pipelined GPT with a VOCAB-PARALLEL
    head: the [B, T, V] einsum — the dominant term of a GPT step at real
    scale — is sharded over the pipeline ranks instead of replicated.

    :func:`pipelined_gpt_apply` makes every rank compute the full head on
    the replicated hidden states, so pipelining saved nothing on the
    dominant cost. Here each rank computes logits for its own V/n vocab
    columns of the (replicated) hidden states and the softmax
    cross-entropy is assembled with the Megatron-style sharded-vocab
    reduction — a ``pmax`` for the global row max, one ``psum`` for the
    global sum-of-exps, one ``psum`` for the label logit (exactly one
    rank holds each label's column). Per-rank head compute AND logits
    memory are O(1/n) of the replicated form, every rank does useful
    work (no idle bubble ranks), and there is no per-device control flow
    for XLA to choke on. Fully differentiable (slice/psum/gpipe all
    transpose; the row max rides ``stop_gradient``, the standard exact
    logsumexp trick). Exact vs the dense model's loss
    (tests/test_pipeline_parallel.py)."""
    import optax

    n = _axis_size(axis)
    h = _pipeline_hidden(cfg, stage_params, rest, tokens, axis=axis,
                         num_microbatches=num_microbatches)
    if n == 1:
        logits = _head_logits(cfg, rest, h)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()

    import flax.linen as nn

    ln = nn.LayerNorm(dtype=cfg.dtype)
    hn = ln.apply({"params": rest["ln_f"]}, h)
    wte = rest["wte"].astype(cfg.dtype)
    V, C = wte.shape
    Vp = -(-V // n)  # ceil: per-rank vocab shard
    # Pad to n*Vp rows so the per-rank dynamic_slice is never clamped
    # (clamping would silently desync vpos from the actual rows).
    wpad = jnp.pad(wte, ((0, n * Vp - V), (0, 0)))
    ax = axis if isinstance(axis, str) else tuple(axis)
    r = lax.axis_index(ax)
    w_shard = lax.dynamic_slice(wpad, (r * Vp, jnp.int32(0)), (Vp, C))
    logits_loc = jnp.einsum("btc,vc->btv", hn, w_shard,
                            preferred_element_type=jnp.float32)
    vpos = r * Vp + jax.lax.broadcasted_iota(jnp.int32, (Vp,), 0)
    valid = vpos < V
    logits_loc = jnp.where(valid[None, None, :], logits_loc, -jnp.inf)

    # Label logit: exactly one rank's shard holds each target column.
    hit = vpos[None, None, :] == targets[..., None]
    tgt_logit = lax.psum(
        jnp.sum(jnp.where(hit, logits_loc, 0.0), axis=-1), ax)
    # Global logsumexp over the sharded vocab. stop_gradient goes INSIDE
    # pmax (pmax has no JVP rule, but a symbolically-zero tangent never
    # reaches it), and pmax — not all_gather+max — re-establishes the
    # replicated (invariant) typing the P() out-spec needs. Any m gives
    # the same lse mathematically; it only sets fp scaling.
    m = lax.pmax(lax.stop_gradient(jnp.max(logits_loc, axis=-1)), ax)
    sumexp = lax.psum(
        jnp.sum(jnp.exp(logits_loc - m[..., None]), axis=-1), ax)
    lse = m + jnp.log(sumexp)
    return jnp.mean(lse - tgt_logit)


def gpipe_1f1b(stage_fn, loss_fn, stage_params, head_params, x_mbs,
               tgt_mbs, *, axis):
    """1F1B pipeline schedule: loss + gradients in one fused pass with
    O(pipeline_depth) activation memory.

    :func:`gpipe` differentiates its forward scan with autodiff, so the
    backward retains residuals for ALL M microbatches per rank — O(M)
    activation memory, GPipe's classic cost. This schedule hand-interleaves
    one-forward-one-backward: stage r runs F(m) at tick m+r and B(m) at
    tick m+2n-1-r, so at most 2n-1-2r microbatches are in flight per rank
    and the stash is a static ``[2n-1, ...]`` ring buffer — O(n), however
    large M grows. Backward uses input-stash rematerialization (the stage
    forward is recomputed at B time for its VJP — one extra forward per
    microbatch, the standard remat trade).

    ``stage_fn(stage_params, x)`` is this rank's stage.
    ``loss_fn(head_params, y, tgt)`` maps the LAST stage's output to a
    scalar per-microbatch loss (every rank evaluates it SPMD-style; only
    the last rank's result/cotangents are un-masked). Returns
    ``(loss, d_stage_params, d_head_params, d_x_mbs)`` where ``loss`` is
    the mean over microbatches (replicated over the PIPELINE axis),
    ``d_stage_params`` is this rank's stage-parameter gradient
    (device-varying, like the stage parameters themselves),
    ``d_head_params`` is replicated over the pipeline axis, and
    ``d_x_mbs`` is the gradient w.r.t. the pipeline input (for the
    caller's embedding backward).

    Composing with data parallelism: when the inputs are sharded over a
    DP axis, every returned gradient is PER-DATA-SHARD — average over
    the DP axes yourself (``hvd.allreduce_pytree(op=Average,
    axes=...)``), exactly as with ``jax.grad`` under shard_map. All
    parameter trees enter their vjps as varying copies internally so the
    implicit pvary transpose cannot pre-sum shards
    (tests/test_pipeline_parallel.py::test_dp_1f1b_2d).
    """
    n = _axis_size(axis)
    M = x_mbs.shape[0]
    if n == 1:
        # Same per-data-shard gradient contract as the scheduled path:
        # when the inputs vary over a DP axis, params enter the grad as
        # varying copies or the implicit pvary transpose psums shard
        # gradients together. Everything is harmonized to the UNION of
        # varying axes (a size-1 pipeline in_spec still marks params
        # varying over it), and the trailing ring psums — numerically
        # identity over a size-1 axis — restore the n>1 output typing
        # (gh/gx ring-invariant, gs ring-varying). All of this is a
        # no-op outside shard_map, where _vma is empty.
        from ..ops.collective_ops import _vma

        ring = ({axis} if isinstance(axis, str) else set(axis))
        union = set()
        for leaf in (jax.tree.leaves(stage_params)
                     + jax.tree.leaves(head_params)
                     + [x_mbs, tgt_mbs]):
            union |= _vma(leaf)
        union_t = tuple(sorted(union))

        sp_in, hp_in, x_in, tgt_in = (
            _pvary_tree(stage_params, union_t),
            _pvary_tree(head_params, union_t),
            _pvary_tree(x_mbs, union_t), _pvary_tree(tgt_mbs, union_t))

        def total(sp, hp, x):
            ys = jax.vmap(lambda xm: stage_fn(sp, xm))(x)
            losses = jax.vmap(lambda ym, tm: loss_fn(hp, ym, tm))(
                ys, tgt_in)
            return losses.mean()

        loss, (gs, gh, gx) = jax.value_and_grad(total, argnums=(0, 1, 2))(
            sp_in, hp_in, x_in)
        ring_in_union = tuple(a for a in sorted(ring) if a in union)
        if ring_in_union:
            # identity over the size-1 ring axis; drops it from the vma
            gh = jax.tree.map(lambda a: lax.psum(a, ring_in_union), gh)
            gx = lax.psum(gx, ring_in_union)
            loss = lax.psum(loss, ring_in_union)
        return loss, gs, gh, gx

    ax = axis if isinstance(axis, str) else tuple(axis)
    r = lax.axis_index(ax)
    S = 2 * n - 1                       # max microbatches in flight
    T_ticks = M + 2 * n - 1
    up = [(i, i + 1) for i in range(n - 1)]
    down = [(i + 1, i) for i in range(n - 1)]
    is_last = r == n - 1
    fzero = jnp.float32(0)
    from ..plan import compiler as _compiler

    splan = _send_plan_for_axis(axis)

    from ..ops.collective_ops import _vma, pvary_missing

    axes_t = _carry_axes(axis, x_mbs, stage_params)

    def vary(tree):
        return _pvary_tree(tree, axes_t)

    mb_shape = x_mbs.shape[1:]
    zeros_mb = pvary_missing(jnp.zeros(mb_shape, x_mbs.dtype), axes_t)
    carry0 = (
        zeros_mb,                                        # act in transit
        zeros_mb.astype(jnp.float32),                    # grad in transit
        vary(jnp.zeros((S,) + mb_shape, x_mbs.dtype)),   # input stash
        zeros_mb.astype(jnp.float32),                    # dy (last stage)
        vary(jax.tree.map(jnp.zeros_like, stage_params)),  # d_stage
        vary(jax.tree.map(
            lambda a: jnp.zeros(a.shape, jnp.float32), head_params)),
        vary(jnp.zeros(x_mbs.shape, jnp.float32)),       # d_x_mbs
        pvary_missing(fzero, axes_t),                    # loss accum
    )

    def tick(carry, t):
        act, gract, stash, dy_state, d_sp, d_hp, d_x, loss_acc = carry

        # ---- backward phase FIRST: B(m_b), m_b = t - (2n - 1 - r) ----
        # B consumes only previous-tick state (stash written at F time
        # ticks ago, gract/dy_state from the prior tick). Running F first
        # would overwrite dy_state with the NEXT microbatch's cotangent
        # before B(m_b) reads it — off-by-one on every last-stage grad.
        m_b = t - (2 * n - 1 - r)
        b_valid = jnp.logical_and(m_b >= 0, m_b < M)
        x_saved = stash[jnp.clip(m_b, 0, M - 1) % S]
        # Varying copy for the same reason as hp_vary below: under a DP
        # axis the stage params are invariant over it, and the implicit
        # pvary's transpose would psum shard gradients together.
        _, stage_vjp = jax.vjp(
            lambda p, x: stage_fn(p, x), vary(stage_params), x_saved)
        gy = jnp.where(is_last, dy_state, gract)
        g_sp_m, gx = stage_vjp(gy.astype(x_saved.dtype))
        d_sp = jax.tree.map(
            lambda acc, g: acc + jnp.where(b_valid, g, 0.0).astype(
                acc.dtype), d_sp, g_sp_m)
        bidx = jnp.clip(m_b, 0, M - 1)
        write_dx = jnp.logical_and(b_valid, r == 0)
        d_x = d_x.at[bidx].set(
            jnp.where(write_dx, gx.astype(jnp.float32), d_x[bidx]))
        new_gract, _ = _compiler.lower_send(
            splan, gx.astype(jnp.float32), axis=ax, perm=down,
            repeats=T_ticks)

        # ---- forward phase: F(m_f) with m_f = t - r ----
        m_f = t - r
        f_valid = jnp.logical_and(m_f >= 0, m_f < M)
        x_in = jnp.where(r == 0, x_mbs[jnp.clip(m_f, 0, M - 1)], act)
        y = stage_fn(stage_params, x_in)
        slot_f = jnp.clip(m_f, 0, M - 1) % S
        stash = stash.at[slot_f].set(
            jnp.where(f_valid, x_in, stash[slot_f]))

        # last stage: per-microbatch loss + output cotangent + head grads.
        # The head params enter the vjp as a VARYING copy: differentiating
        # through the replicated (invariant) tree would transpose the
        # implicit pvary into a psum, summing every rank's garbage-y
        # contribution into g_hp_m before our mask can drop it.
        hp_vary = vary(head_params)
        tgt = tgt_mbs[jnp.clip(m_f, 0, M - 1)]
        loss_m, head_vjp = jax.vjp(
            lambda hp, y: loss_fn(hp, y, tgt), hp_vary, y)
        # The seed cotangent must carry the same varying axes as loss_m.
        g_hp_m, dy = head_vjp(pvary_missing(jnp.float32(1),
                                            tuple(sorted(_vma(loss_m)))))
        take = jnp.logical_and(is_last, f_valid)
        loss_acc = loss_acc + jnp.where(take, loss_m, fzero)
        d_hp = jax.tree.map(
            lambda acc, g: acc + jnp.where(take, g, 0.0).astype(acc.dtype),
            d_hp, g_hp_m)
        dy_state = jnp.where(take, dy.astype(jnp.float32), dy_state)
        act, _ = _compiler.lower_send(splan, y, axis=ax, perm=up,
                                      repeats=T_ticks)

        return (act, new_gract, stash, dy_state, d_sp, d_hp, d_x,
                loss_acc), None

    (_, _, _, _, d_sp, d_hp, d_x, loss_acc), _ = lax.scan(
        tick, carry0, jnp.arange(T_ticks))

    # loss/head grads live on the last stage, input grads on stage 0;
    # masked psums replicate (every other rank contributed zeros).
    loss = lax.psum(loss_acc, ax) / M
    d_hp = jax.tree.map(
        lambda a: lax.psum(a, ax) / M, d_hp)
    d_x = lax.psum(d_x, ax) / M
    return loss, jax.tree.map(lambda a: a / M, d_sp), d_hp, d_x


def pipelined_gpt_train_1f1b(cfg, stage_params, rest, tokens, targets, *,
                             axis, num_microbatches: int):
    """One fused GPT training computation under the 1F1B schedule:
    returns ``(loss, d_stage_params, d_rest)`` directly (the schedule
    hand-interleaves forward and backward, so this is not a function you
    differentiate — it IS the gradient computation).

    Same contract as :func:`pipelined_gpt_loss` + ``jax.grad``, with
    activation memory O(pipeline_depth) instead of O(num_microbatches):
    use it when M must be large (deep pipelines want M >> n to shrink
    the bubble, which is exactly when GPipe's O(M) stash hurts). The LM
    head runs replicated per microbatch on every rank (masked off the
    last stage) — the memory-lean counterpart of
    :func:`pipelined_gpt_loss`'s vocab-sharded head; exactness vs the
    dense model is tested for both."""
    import optax

    B, T = tokens.shape
    _validate_pipeline_cfg(cfg, B, T, num_microbatches, axis)
    M = num_microbatches

    ep = {"wte": rest["wte"], "wpe": rest["wpe"]}
    # Like the head params in gpipe_1f1b: when tokens are data-sharded
    # (varying over a DP axis), the replicated embedding tree must enter
    # its vjp as a varying copy, or the implicit pvary transposes into a
    # psum over the data axis and g_ep comes back SUMMED across shards —
    # the caller's DP gradient averaging then over-counts.
    from ..ops.collective_ops import _vma

    ep = _pvary_tree(ep, tuple(sorted(_vma(tokens))))
    x, embed_vjp = jax.vjp(lambda ep: _embed(cfg, ep, tokens), ep)
    x_mbs = x.reshape(M, B // M, T, -1)
    tgt_mbs = targets.reshape(M, B // M, T)

    def loss_fn(hp, y, tgt):
        # hp carries exactly the {ln_f, wte} keys _head_logits reads.
        return optax.softmax_cross_entropy_with_integer_labels(
            _head_logits(cfg, hp, y), tgt).mean()

    hp = {"ln_f": rest["ln_f"], "wte": rest["wte"]}
    loss, g_stages, g_hp, d_x = gpipe_1f1b(
        _make_stage_fn(cfg), loss_fn, stage_params, hp, x_mbs, tgt_mbs,
        axis=axis)
    (g_ep,) = embed_vjp(d_x.reshape(B, T, -1).astype(x.dtype))
    g_rest = {
        # wte is tied: embedding-lookup grad + LM-head grad
        "wte": g_ep["wte"].astype(jnp.float32) + g_hp["wte"],
        "wpe": g_ep["wpe"].astype(jnp.float32),
        "ln_f": g_hp["ln_f"],
    }
    return loss, g_stages, g_rest


# ---------------------------------------------------------------------------
# Interleaved-1F1B (docs/pipeline.md): the production schedule. The model
# splits into K = n * v CHUNKS placed round-robin (chunk c on rank c % n,
# local index j = c // n), so each rank holds v non-contiguous "virtual
# stages". Per tick every rank executes at most ONE unit — a chunk
# forward F(m, j) or a chunk backward B(m, j) — and two cyclic ppermutes
# move the tick's products one hop: activations up (r -> r+1 mod n),
# activation-grads down. The unit order per rank is Megatron-LM's
# interleaved-1F1B stream (warmup forwards, strict 1F1B alternation,
# cooldown backwards); the tick assignment comes from a host-side
# simulation of that stream under the 1-tick hop latency, so the whole
# schedule — including every stash slot — is STATIC tables the SPMD scan
# body indexes with the traced rank. Bubble fraction falls from GPipe's
# (S-1)/(M+S-1) to ~(S-1)/(Mv+S-1): the interleave divides the fill.
#
# The ZERO-BUBBLE family (``family="zb1"``, ZB-H1 of arXiv 2401.10241,
# docs/pipeline.md): the backward splits into a dx unit **B** (the
# input-cotangent half — the only part the upstream stage waits on; it
# stays on the critical path and keeps the 1F1B placement) and a dw unit
# **W** (the weight-cotangent half — consumed by nobody downstream, so
# it is DEFERRED into the cooldown/idle ticks after its B). Each unit is
# one vjp half instead of the fused dx+dw vjp, so the per-tick compute
# shrinks while the busy fraction of the rank x tick grid rises: the
# measured ``bubble_fraction`` (idle issue slots / grid) drops strictly
# below the interleaved-1F1B bound on the same (S, M, v). The remaining
# idle ticks are enumerated per rank in ``fill_ticks`` — the T3-style
# fill capacity the ZeRO-3 bucket flights are credited against
# (``plan/accounting.bubble_fill``).
# ---------------------------------------------------------------------------

#: Schedule-table families build_interleaved_schedule can simulate.
PP_TABLE_FAMILIES = ("1f1b", "zb1")


@dataclasses.dataclass(frozen=True)
class PPSchedule:
    """Static interleaved-1F1B schedule tables (host-built, rank-major).

    Every table is ``[n, ticks]`` int32, indexed ``[rank, tick]`` inside
    the scan body. Slot ids index the three stash pools (activation /
    grad / dy); ``-1`` means "no unit" / "discard" / "read x_mbs".
    """

    stages: int
    interleave: int
    microbatches: int
    ticks: int
    act_slots: int
    grad_slots: int
    dy_slots: int
    # forward unit: valid, microbatch, local chunk, input act slot
    # (-1 = x_mbs), dy slot to write (>=0 marks the LAST chunk)
    f_valid: np.ndarray
    f_m: np.ndarray
    f_j: np.ndarray
    f_src: np.ndarray
    f_dy: np.ndarray
    # backward unit: valid, microbatch, local chunk, remat act slot
    # (-1 = x_mbs = chunk 0), grad slot to read (-1 = read dy), dy slot
    b_valid: np.ndarray
    b_m: np.ndarray
    b_j: np.ndarray
    b_src: np.ndarray
    b_g: np.ndarray
    b_dy: np.ndarray
    # arrival routing: where this tick's incoming ppermute values land
    arr_a: np.ndarray
    arr_g: np.ndarray
    # schedule family: "1f1b" (fused dx+dw backward) or "zb1" (ZB-H1
    # B/W split — the W tables below are live only for zb1)
    family: str = "1f1b"
    # weight-grad unit (zb1): valid, microbatch, local chunk, stashed
    # act slot (-1 = x_mbs), grad slot to read (-1 = read dy), dy slot
    w_valid: Optional[np.ndarray] = None
    w_m: Optional[np.ndarray] = None
    w_j: Optional[np.ndarray] = None
    w_src: Optional[np.ndarray] = None
    w_g: Optional[np.ndarray] = None
    w_dy: Optional[np.ndarray] = None
    # fill_ticks[r, t] = k if tick t is rank r's k-th idle tick (no
    # F/B/W unit), else -1 — the T3 bubble-fill capacity table
    # (docs/pipeline.md): idle counts are rank-uniform by construction.
    fill_ticks: Optional[np.ndarray] = None

    @property
    def bubble_fraction(self) -> float:
        """Idle fraction of the rank x tick grid — the measured bubble
        (each tick is one chunk-unit of compute; garbage masked units in
        the bubble cost the same wall time as real ones under SPMD).
        Under zb1 a unit is one vjp HALF (dx-only B or dw-only W), so
        the grid is finer and the idle fraction strictly smaller than
        the fused-backward 1f1b grid on the same (S, M, v)."""
        return 1.0 - self.unit_count() / float(self.stages * self.ticks)

    def unit_count(self) -> int:
        busy = int(self.f_valid.sum() + self.b_valid.sum())
        if self.w_valid is not None:
            busy += int(self.w_valid.sum())
        return busy

    @property
    def units_per_rank(self) -> int:
        """Compute units per rank: Mv forwards + Mv backwards, plus Mv
        deferred W units under zb1. Exact on every rank (the streams
        pump every microbatch through every local chunk)."""
        per = 2 * self.microbatches * self.interleave
        if self.family == "zb1":
            per += self.microbatches * self.interleave
        return per

    @property
    def idle_ticks_per_rank(self) -> int:
        """Per-rank bubble capacity in ticks — the T3 fill budget
        (rank-uniform: every rank runs exactly ``units_per_rank``)."""
        return self.ticks - self.units_per_rank


def _interleaved_streams(M: int, n: int, v: int) -> List[List[tuple]]:
    """Megatron-LM's interleaved-1F1B unit stream per rank: warmup
    forwards, 1F1B alternation, cooldown backwards. Units are
    ``("F"|"B", microbatch, local_chunk)``."""
    total = M * v

    def fwd_unit(k: int) -> tuple:
        if v == 1:
            return ("F", k, 0)
        j = (k // n) % v
        m = (k // (n * v)) * n + k % n
        return ("F", m, j)

    def bwd_unit(k: int) -> tuple:
        if v == 1:
            return ("B", k, 0)
        j = v - 1 - (k // n) % v
        m = (k // (n * v)) * n + k % n
        return ("B", m, j)

    streams = []
    for r in range(n):
        if v == 1:
            warm = min(n - r - 1, total)
        else:
            warm = min((n - r - 1) * 2 + (v - 1) * n, total)
        seq = [fwd_unit(k) for k in range(warm)]
        fi, bi = warm, 0
        while fi < total:
            seq.append(fwd_unit(fi))
            seq.append(bwd_unit(bi))
            fi += 1
            bi += 1
        while bi < total:
            seq.append(bwd_unit(bi))
            bi += 1
        streams.append(seq)
    return streams


def _alloc_slots(intervals: List[tuple]) -> Tuple[dict, int]:
    """Greedy interval-graph coloring: ``intervals`` is a list of
    ``(key, start, end)`` (inclusive); returns ``(slot_of_key,
    pool_size)``. Deterministic: sorted by (start, key)."""
    slot_of = {}
    free: List[int] = []
    in_use: List[tuple] = []  # (end, slot)
    n_slots = 0
    for key, start, end in sorted(intervals,
                                  key=lambda it: (it[1], str(it[0]))):
        still = []
        for iu_end, iu_slot in in_use:
            if iu_end < start:
                free.append(iu_slot)
            else:
                still.append((iu_end, iu_slot))
        in_use = still
        if free:
            s = min(free)
            free.remove(s)
        else:
            s = n_slots
            n_slots += 1
        slot_of[key] = s
        in_use.append((end, s))
    return slot_of, n_slots


def build_interleaved_schedule(M: int, n: int, v: int = 1,
                               family: str = "1f1b") -> PPSchedule:
    """Simulate the interleaved-1F1B streams under the 1-tick hop
    latency and freeze the result as static tables (docs/pipeline.md).

    ``family="zb1"`` runs the SAME simulation for F and B (B stays on
    the critical path: its dx is what the upstream rank waits on), then
    places each deferred W(m, c) unit greedily in the earliest idle
    tick of its rank strictly after B(m, c) — extending the tick count
    when the cooldown overflows — and re-allocates the stash pools with
    the W-extended lifetimes (W re-reads the stashed activation and
    incoming grad AFTER B consumed them).

    Requires ``M % n == 0`` when ``v > 1`` (the Megatron grouping the
    forward/backward unit order is built from)."""
    if n < 2:
        raise ValueError("build_interleaved_schedule needs >= 2 stages")
    if v < 1:
        raise ValueError(f"interleave must be >= 1, got {v}")
    if family not in PP_TABLE_FAMILIES:
        raise ValueError(
            f"unknown schedule family {family!r}: expected one of "
            f"{PP_TABLE_FAMILIES}")
    if v > 1 and M % n:
        raise ValueError(
            f"interleaved-1F1B needs microbatches ({M}) divisible by "
            f"the stage count ({n}): the Megatron unit order pumps "
            f"groups of <stages> microbatches through each virtual "
            f"stage (docs/pipeline.md)")
    K = n * v
    streams = _interleaved_streams(M, n, v)
    ptr = [0] * n
    done_f: dict = {}   # (m, c) -> tick
    done_b: dict = {}
    exec_at: List[List[tuple]] = [[] for _ in range(n)]  # (tick, unit)
    t = 0
    cap = 8 * (2 * M * v + 2 * K) + 64
    while any(p < len(s) for p, s in zip(ptr, streams)):
        if t > cap:
            raise AssertionError(
                f"pipeline schedule simulation did not converge "
                f"(M={M}, n={n}, v={v})")  # pragma: no cover
        for r in range(n):
            if ptr[r] >= len(streams[r]):
                continue
            kind, m, j = streams[r][ptr[r]]
            c = j * n + r
            if kind == "F":
                ready = c == 0 or done_f.get((m, c - 1), t) <= t - 1
            elif c == K - 1:
                ready = done_f.get((m, c), t) <= t - 1
            else:
                ready = done_b.get((m, c + 1), t) <= t - 1
            if not ready:
                continue
            (done_f if kind == "F" else done_b)[(m, c)] = t
            exec_at[r].append((t, (kind, m, j, c)))
            ptr[r] += 1
        t += 1
    T = t

    # --- zb1 W-unit placement (ZB-H1): each W(m, c) lands in the
    # earliest idle tick of its rank strictly after B(m, c), in done_b
    # order (greedy; extends T when the cooldown overflows) ------------
    done_w: dict = {}
    if family == "zb1":
        for r in range(n):
            busy_t = {tick for tick, _ in exec_at[r]}
            for tb, m, c in sorted((done_b[(m, c)], m, c)
                                   for m in range(M)
                                   for c in range(r, K, n)):
                tw = tb + 1
                while tw in busy_t:
                    tw += 1
                busy_t.add(tw)
                done_w[(m, c)] = tw
                exec_at[r].append((tw, ("W", m, c // n, c)))
        T = max(T, max(done_w.values()) + 1)

    # --- stash slot allocation (per pool, shared across ranks so the
    # tables index one pool shape). Under zb1 the stashed activation
    # and incoming grad outlive B: W re-reads both, so every lifetime
    # extends to done_w. -----------------------------------------------
    act_iv, grad_iv, dy_iv = [], [], []
    for m in range(M):
        for c in range(K):
            tf, tb = done_f[(m, c)], done_b[(m, c)]
            te = done_w.get((m, c), tb)
            if c > 0:
                ta = done_f[(m, c - 1)] + 1
                act_iv.append(((m, c), ta, te))
            if c < K - 1:
                ta = done_b[(m, c + 1)] + 1
                grad_iv.append(((m, c), ta, te))
            else:
                dy_iv.append(((m, c), tf, te))
    act_slot, n_act = _alloc_slots(act_iv)
    grad_slot, n_grad = _alloc_slots(grad_iv)
    dy_slot, n_dy = _alloc_slots(dy_iv)

    full = lambda fill: np.full((n, T), fill, np.int32)  # noqa: E731
    fv, fm, fj, fsrc, fdy = (full(0), full(0), full(0), full(-1),
                             full(-1))
    bv, bm, bj, bsrc, bg, bdy = (full(0), full(0), full(0), full(-1),
                                 full(-1), full(-1))
    wv, wm_, wj_, wsrc, wg, wdy = (full(0), full(0), full(0), full(-1),
                                   full(-1), full(-1))
    arr_a, arr_g = full(-1), full(-1)
    for r in range(n):
        for tick, (kind, m, j, c) in exec_at[r]:
            if kind == "F":
                fv[r, tick], fm[r, tick], fj[r, tick] = 1, m, j
                if c > 0:
                    fsrc[r, tick] = act_slot[(m, c)]
                if c == K - 1:
                    fdy[r, tick] = dy_slot[(m, c)]
            elif kind == "B":
                bv[r, tick], bm[r, tick], bj[r, tick] = 1, m, j
                if c > 0:
                    bsrc[r, tick] = act_slot[(m, c)]
                if c == K - 1:
                    bdy[r, tick] = dy_slot[(m, c)]
                else:
                    bg[r, tick] = grad_slot[(m, c)]
            else:  # W (zb1): same stash reads as B, one tick later
                wv[r, tick], wm_[r, tick], wj_[r, tick] = 1, m, j
                if c > 0:
                    wsrc[r, tick] = act_slot[(m, c)]
                if c == K - 1:
                    wdy[r, tick] = dy_slot[(m, c)]
                else:
                    wg[r, tick] = grad_slot[(m, c)]
            # Arrival routing at the CONSUMER: the up hop of F(m, c)
            # lands the activation of chunk c+1 on rank (r+1) % n one
            # tick later; the down hop of B(m, c) lands the grad of
            # chunk c-1 on rank (r-1) % n.
            if kind == "F" and c < K - 1 and tick + 1 < T:
                arr_a[(r + 1) % n, tick + 1] = act_slot[(m, c + 1)]
            if kind == "B" and c > 0 and tick + 1 < T:
                arr_g[(r - 1) % n, tick + 1] = grad_slot[(m, c - 1)]

    # Idle-tick enumeration: the T3 fill capacity table. Rank-uniform
    # by construction (every rank runs exactly units_per_rank units).
    fill = full(-1)
    for r in range(n):
        busy_t = {tick for tick, _ in exec_at[r]}
        k = 0
        for tick in range(T):
            if tick not in busy_t:
                fill[r, tick] = k
                k += 1

    zb = family == "zb1"
    return PPSchedule(
        stages=n, interleave=v, microbatches=M, ticks=T,
        act_slots=max(1, n_act), grad_slots=max(1, n_grad),
        dy_slots=max(1, n_dy),
        f_valid=fv, f_m=fm, f_j=fj, f_src=fsrc, f_dy=fdy,
        b_valid=bv, b_m=bm, b_j=bj, b_src=bsrc, b_g=bg, b_dy=bdy,
        arr_a=arr_a, arr_g=arr_g, family=family,
        w_valid=wv if zb else None, w_m=wm_ if zb else None,
        w_j=wj_ if zb else None, w_src=wsrc if zb else None,
        w_g=wg if zb else None, w_dy=wdy if zb else None,
        fill_ticks=fill)


def emit_schedule_spans(sched: PPSchedule) -> None:
    """Mirror the schedule onto the Timeline as per-rank ``PP:F`` /
    ``PP:B`` spans (tid ``pp-rank<r>``, tick-indexed timestamps) plus a
    ``PP:SCHEDULE`` instant carrying the measured bubble fraction —
    ``span_audit`` audits the balance, ``obs_report``/bench read the
    bubble (docs/pipeline.md). Trace-time, like every span here."""
    from ..common import basics

    tl = basics._state.timeline if basics.is_initialized() else None
    if tl is None:
        return
    tl.instant("PP:SCHEDULE", tid="pp", args={
        "stages": sched.stages, "interleave": sched.interleave,
        "microbatches": sched.microbatches, "ticks": sched.ticks,
        "family": sched.family,
        "idle_ticks": sched.idle_ticks_per_rank,
        "bubble_fraction": round(sched.bubble_fraction, 6)})
    for r in range(sched.stages):
        tid = f"pp-rank{r}"
        for t in range(sched.ticks):
            if sched.f_valid[r, t]:
                tl.begin(tid, "PP:F")
                tl.end(tid, "PP:F")
            if sched.b_valid[r, t]:
                tl.begin(tid, "PP:B")
                tl.end(tid, "PP:B")
            if sched.w_valid is not None and sched.w_valid[r, t]:
                tl.begin(tid, "PP:W")
                tl.end(tid, "PP:W")


def pp_split_chunks(params, n: int, v: int = 1):
    """Dense GPT params → (chunks, rest) for the interleaved schedule.

    ``chunks``: each transformer-block leaf stacked ``[n, v, L/(n*v),
    ...]`` — rank r's local chunk j holds blocks of GLOBAL chunk
    ``c = j * n + r`` (round-robin placement), i.e. blocks
    ``[c*L/K, (c+1)*L/K)``. Pass through shard_map with
    ``in_specs=P(pp_axis)`` and squeeze the leading dim; ``v = 1``
    degenerates to :func:`pp_split_blocks`' contiguous split. ``rest``:
    the replicated embedding/head tree."""
    blocks = sorted((k for k in params if k.startswith("h")),
                    key=lambda k: int(k[1:]))
    L = len(blocks)
    K = n * v
    if L % K:
        raise ValueError(
            f"{L} blocks not divisible by {n} stages x {v} virtual "
            f"stages = {K} chunks")
    per = L // K

    def stack(*leaves):
        return jnp.stack([
            jnp.stack([
                jnp.stack(leaves[(j * n + r) * per:(j * n + r + 1) * per])
                for j in range(v)])
            for r in range(n)])

    chunks = jax.tree.map(stack, *[params[b] for b in blocks])
    rest = {k: p for k, p in params.items() if not k.startswith("h")}
    return chunks, rest


def interleaved_1f1b(stage_fn, loss_fn, chunk_params, head_params, x_mbs,
                     tgt_mbs, *, axis, interleave: int = 1,
                     send_plan=None, sched: Optional[PPSchedule] = None,
                     family: str = "1f1b"):
    """Interleaved-1F1B pipeline: loss + gradients in one fused pass,
    bubble ~``(S-1)/(Mv+S-1)`` vs GPipe's ``(S-1)/(M+S-1)``.

    Same contract as :func:`gpipe_1f1b` with ``chunk_params`` this
    rank's ``[v, ...]`` stacked virtual-stage tree (``stage_fn(chunk,
    x)`` applies ONE chunk); returns ``(loss, d_chunk_params,
    d_head_params, d_x_mbs)`` with the same replication/per-data-shard
    semantics. Inter-stage hops are wire-plan ``send`` legs
    (``send_plan``; default: the payload-dtype plan for ``axis``' link
    class — pass a quantized plan for the int8+EF activation wire)."""
    n = _axis_size(axis)
    v = max(1, int(interleave))
    M = x_mbs.shape[0]
    if n == 1:
        def full_fn(cp, x):
            for j in range(v):
                x = stage_fn(jax.tree.map(lambda a: a[j], cp), x)
            return x

        return gpipe_1f1b(full_fn, loss_fn, chunk_params, head_params,
                          x_mbs, tgt_mbs, axis=axis)

    from ..plan import compiler as _compiler
    from ..plan.accounting import pp_span

    if sched is None:
        sched = build_interleaved_schedule(M, n, v, family=family)
    if sched.microbatches != M or sched.stages != n \
            or sched.interleave != v:
        raise ValueError(
            f"schedule is ({sched.microbatches} microbatches, "
            f"{sched.stages} stages, x{sched.interleave}), step wants "
            f"({M}, {n}, x{v})")
    zb = sched.family == "zb1"   # host-level: the 1f1b trace is unchanged
    if send_plan is None:
        send_plan = _send_plan_for_axis(axis)
    splan = send_plan.validate()
    ef = any(l.error_feedback for l in splan.legs)
    emit_schedule_spans(sched)

    ax = axis if isinstance(axis, str) else tuple(axis)
    r = lax.axis_index(ax)
    T = sched.ticks
    up = [(i, (i + 1) % n) for i in range(n)]
    down = [(i, (i - 1) % n) for i in range(n)]
    fzero = jnp.float32(0)

    from ..ops.collective_ops import _vma, pvary_missing

    axes_t = _carry_axes(axis, x_mbs, chunk_params)

    def vary(tree):
        return _pvary_tree(tree, axes_t)

    table_keys = ["f_valid", "f_m", "f_j", "f_src", "f_dy",
                  "b_valid", "b_m", "b_j", "b_src", "b_g", "b_dy",
                  "arr_a", "arr_g"]
    if zb:
        table_keys += ["w_valid", "w_m", "w_j", "w_src", "w_g", "w_dy"]
    tables = {k: jnp.asarray(getattr(sched, k)) for k in table_keys}

    mb_shape = x_mbs.shape[1:]
    zmb = pvary_missing(jnp.zeros(mb_shape, x_mbs.dtype), axes_t)
    zmb32 = zmb.astype(jnp.float32)
    pool = lambda k, dt: vary(jnp.zeros((k,) + mb_shape, dt))  # noqa: E731
    res0 = (zmb32, zmb32) if ef else None
    carry0 = (
        zmb,                                   # activation in transit
        zmb32,                                 # grad in transit
        pool(sched.act_slots, x_mbs.dtype),    # received-act + remat stash
        pool(sched.grad_slots, jnp.float32),   # received-grad stash
        pool(sched.dy_slots, jnp.float32),     # dy stash (last chunk)
        vary(jax.tree.map(jnp.zeros_like, chunk_params)),   # d_chunks
        vary(jax.tree.map(
            lambda a: jnp.zeros(a.shape, jnp.float32), head_params)),
        vary(jnp.zeros(x_mbs.shape, jnp.float32)),          # d_x_mbs
        pvary_missing(fzero, axes_t),                       # loss accum
        res0,                                  # send EF residuals
    )

    def cell(idx):
        return lambda tbl: tbl[r, idx]

    def tick(carry, t):
        (act_in, grad_in, apool, gpool, dypool, d_cp, d_hp, d_x,
         loss_acc, res) = carry
        at = cell(t)

        # -- arrivals: last tick's ppermute values land in their slots
        aslot = at(tables["arr_a"])
        ai = jnp.clip(aslot, 0, sched.act_slots - 1)
        apool = apool.at[ai].set(
            jnp.where(aslot >= 0, act_in, apool[ai]))
        gslot = at(tables["arr_g"])
        gi = jnp.clip(gslot, 0, sched.grad_slots - 1)
        gpool = gpool.at[gi].set(
            jnp.where(gslot >= 0, grad_in, gpool[gi]))

        # -- backward unit first (consumes only pre-tick state) --------
        b_on = at(tables["b_valid"]) > 0
        bm = jnp.clip(at(tables["b_m"]), 0, M - 1)
        bj = at(tables["b_j"])
        bsrc = at(tables["b_src"])
        x_saved = jnp.where(
            bsrc >= 0,
            apool[jnp.clip(bsrc, 0, sched.act_slots - 1)],
            x_mbs[bm])
        bdy = at(tables["b_dy"])
        bgs = at(tables["b_g"])
        gy = jnp.where(
            bdy >= 0,
            dypool[jnp.clip(bdy, 0, sched.dy_slots - 1)],
            gpool[jnp.clip(bgs, 0, sched.grad_slots - 1)])
        if zb:
            # zb1 B unit: the dx HALF only — params are closed over, so
            # the transpose never forms their cotangent (that is the
            # deferred W unit's tick).
            _, x_vjp = jax.vjp(
                lambda x: stage_fn(
                    jax.tree.map(lambda a: a[bj], chunk_params), x),
                x_saved)
            (gx,) = x_vjp(gy.astype(x_saved.dtype))
        else:
            _, chunk_vjp = jax.vjp(
                lambda p, x: stage_fn(
                    jax.tree.map(lambda a: a[bj], p), x),
                vary(chunk_params), x_saved)
            g_cp, gx = chunk_vjp(gy.astype(x_saved.dtype))
            d_cp = jax.tree.map(
                lambda acc, g: acc + jnp.where(b_on, g, 0.0).astype(
                    acc.dtype), d_cp, g_cp)
        write_dx = jnp.logical_and(b_on, bsrc < 0)  # chunk 0 <=> rank 0
        d_x = d_x.at[bm].set(
            jnp.where(write_dx, gx.astype(jnp.float32), d_x[bm]))

        # -- zb1 W unit: the deferred dw HALF — re-reads the stashed
        # activation and incoming grad B left alive (the builder
        # extended both lifetimes to done_w) and forms ONLY the param
        # cotangent.
        if zb:
            w_on = at(tables["w_valid"]) > 0
            wm = jnp.clip(at(tables["w_m"]), 0, M - 1)
            wj = at(tables["w_j"])
            wsrc = at(tables["w_src"])
            x_w = jnp.where(
                wsrc >= 0,
                apool[jnp.clip(wsrc, 0, sched.act_slots - 1)],
                x_mbs[wm])
            wdy = at(tables["w_dy"])
            wgs = at(tables["w_g"])
            gy_w = jnp.where(
                wdy >= 0,
                dypool[jnp.clip(wdy, 0, sched.dy_slots - 1)],
                gpool[jnp.clip(wgs, 0, sched.grad_slots - 1)])
            _, w_vjp = jax.vjp(
                lambda p: stage_fn(
                    jax.tree.map(lambda a: a[wj], p), x_w),
                vary(chunk_params))
            (g_cp_w,) = w_vjp(gy_w.astype(x_w.dtype))
            d_cp = jax.tree.map(
                lambda acc, g: acc + jnp.where(w_on, g, 0.0).astype(
                    acc.dtype), d_cp, g_cp_w)

        # -- forward unit ----------------------------------------------
        f_on = at(tables["f_valid"]) > 0
        fm = jnp.clip(at(tables["f_m"]), 0, M - 1)
        fj = at(tables["f_j"])
        fsrc = at(tables["f_src"])
        x_in = jnp.where(
            fsrc >= 0,
            apool[jnp.clip(fsrc, 0, sched.act_slots - 1)],
            x_mbs[fm])
        y = stage_fn(jax.tree.map(lambda a: a[fj], chunk_params), x_in)
        # last chunk: per-microbatch loss + head grads + dy stash (the
        # vjp enters through VARYING copies — see gpipe_1f1b).
        hp_vary = vary(head_params)
        tgt = tgt_mbs[fm]
        loss_m, head_vjp = jax.vjp(
            lambda hp, yy: loss_fn(hp, yy, tgt), hp_vary, y)
        g_hp_m, dy = head_vjp(pvary_missing(
            jnp.float32(1), tuple(sorted(_vma(loss_m)))))
        fdy = at(tables["f_dy"])
        take = jnp.logical_and(f_on, fdy >= 0)
        loss_acc = loss_acc + jnp.where(take, loss_m, fzero)
        d_hp = jax.tree.map(
            lambda acc, g: acc + jnp.where(take, g, 0.0).astype(
                acc.dtype), d_hp, g_hp_m)
        di = jnp.clip(fdy, 0, sched.dy_slots - 1)
        dypool = dypool.at[di].set(
            jnp.where(take, dy.astype(jnp.float32), dypool[di]))

        # -- the tick's two send legs ----------------------------------
        a_res, g_res = res if ef else (None, None)
        act_out, a_res = _compiler.lower_send(
            splan, y, axis=ax, perm=up, residual=a_res, repeats=T)
        grad_out, g_res = _compiler.lower_send(
            splan, gx.astype(jnp.float32), axis=ax, perm=down,
            residual=g_res, repeats=T)
        new_res = (a_res, g_res) if ef else None
        return (act_out, grad_out, apool, gpool, dypool, d_cp, d_hp,
                d_x, loss_acc, new_res), None

    with pp_span("SCHED"):
        (_, _, _, _, _, d_cp, d_hp, d_x, loss_acc, _), _ = lax.scan(
            tick, carry0, jnp.arange(T))

    loss = lax.psum(loss_acc, ax) / M
    d_hp = jax.tree.map(lambda a: lax.psum(a, ax) / M, d_hp)
    d_x = lax.psum(d_x, ax) / M
    return loss, jax.tree.map(lambda a: a / M, d_cp), d_hp, d_x


# The schedule family (docs/pipeline.md): gpipe is the autodiff baseline,
# 1f1b the O(depth)-memory hand schedule, interleaved_1f1b the
# production schedule (1f1b == interleaved with v pinned to 1; the
# explicit name keeps the baseline selectable), zb1 the ZB-H1
# zero-bubble variant of interleaved_1f1b (B/W backward split).
PP_SCHEDULES = ("gpipe", "1f1b", "interleaved_1f1b", "zb1")


def pipelined_gpt_train(cfg, chunk_params, rest, tokens, targets, *,
                        axis, num_microbatches: int,
                        schedule: str = "interleaved_1f1b",
                        interleave: int = 1, send_plan=None):
    """One fused GPT training computation under any pipeline schedule:
    returns ``(loss, d_chunk_params, d_rest)`` — the production entry
    point behind ``bench.py --pp`` (docs/pipeline.md).

    ``chunk_params`` is this rank's ``[v, L/(n*v), ...]`` stacked tree
    from :func:`pp_split_chunks` (``v = 1`` for gpipe/1f1b);
    ``schedule`` picks the family member; ``send_plan`` threads an
    explicit activation wire (e.g. the int8+EF plan) into the hops."""
    import optax

    if schedule not in PP_SCHEDULES:
        raise ValueError(
            f"unknown pipeline schedule {schedule!r}: one of "
            f"{PP_SCHEDULES} (docs/pipeline.md)")
    v = max(1, int(interleave))
    if schedule in ("gpipe", "1f1b") and v > 1:
        raise ValueError(
            f"schedule={schedule!r} does not interleave: virtual stages "
            f"(pp_interleave={v}) need schedule='interleaved_1f1b'")
    B, T = tokens.shape
    _validate_pipeline_cfg(cfg, B, T, num_microbatches, axis)
    M = num_microbatches

    ep = {"wte": rest["wte"], "wpe": rest["wpe"]}
    from ..ops.collective_ops import _vma

    ep = _pvary_tree(ep, tuple(sorted(_vma(tokens))))
    x, embed_vjp = jax.vjp(lambda e: _embed(cfg, e, tokens), ep)
    x_mbs = x.reshape(M, B // M, T, -1)
    tgt_mbs = targets.reshape(M, B // M, T)

    def loss_fn(hp, y, tgt):
        return optax.softmax_cross_entropy_with_integer_labels(
            _head_logits(cfg, hp, y), tgt).mean()

    hp = {"ln_f": rest["ln_f"], "wte": rest["wte"]}
    stage_fn = _make_stage_fn(cfg)

    if schedule == "gpipe":
        # Autodiff baseline: differentiate the relay forward + head loss
        # (O(M) activation memory — the cost 1F1B exists to cut).
        ring = ({axis} if isinstance(axis, str) else set(axis))
        union = set()
        for leaf in (jax.tree.leaves(chunk_params)
                     + jax.tree.leaves(hp) + [x_mbs, tgt_mbs]):
            union |= _vma(leaf)
        union_t = tuple(sorted(union | ring))

        def total(cp, h, xm):
            sp = jax.tree.map(lambda a: a[0], cp)  # [1, L/n, ...] -> [L/n, ...]
            ys = gpipe(stage_fn, sp, xm, axis=axis)
            losses = jax.vmap(
                lambda ym, tm: loss_fn(h, ym, tm))(
                ys, _pvary_tree(tgt_mbs, union_t))
            return losses.mean()

        loss, (g_cp, g_hp, d_x) = jax.value_and_grad(
            total, argnums=(0, 1, 2))(
            _pvary_tree(chunk_params, union_t),
            _pvary_tree(hp, union_t), _pvary_tree(x_mbs, union_t))
        n = _axis_size(axis)
        if n > 1:
            # gpipe() replicates loss/outputs itself; grads of the
            # replicated head/input come back per-rank — average.
            ax = axis if isinstance(axis, str) else tuple(axis)
            g_hp = jax.tree.map(lambda a: lax.psum(a, ax) / n, g_hp)
            d_x = lax.psum(d_x, ax) / n
            loss = lax.psum(loss, ax) / n
    elif schedule == "1f1b":
        sp = jax.tree.map(lambda a: a[0], chunk_params)
        loss, g_sp, g_hp, d_x = gpipe_1f1b(
            stage_fn, loss_fn, sp, hp, x_mbs, tgt_mbs, axis=axis)
        g_cp = jax.tree.map(lambda a: a[None], g_sp)
    else:
        loss, g_cp, g_hp, d_x = interleaved_1f1b(
            stage_fn, loss_fn, chunk_params, hp, x_mbs, tgt_mbs,
            axis=axis, interleave=v, send_plan=send_plan,
            family="zb1" if schedule == "zb1" else "1f1b")

    (g_ep,) = embed_vjp(d_x.reshape(B, T, -1).astype(x.dtype))
    g_rest = {
        # wte is tied: embedding-lookup grad + LM-head grad
        "wte": g_ep["wte"].astype(jnp.float32) + g_hp["wte"],
        "wpe": g_ep["wpe"].astype(jnp.float32),
        "ln_f": g_hp["ln_f"],
    }
    return loss, g_cp, g_rest
