"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference framework has **no** sequence parallelism (SURVEY §5.7): its
only sequence-adjacent machinery is `alltoall` with uneven splits
(operations.cc:1031-1092), which is exactly the primitive Ulysses-style SP
is built from. This module makes long-context first-class on TPU:

- :func:`ring_attention` — blockwise (flash-style) attention where K/V
  blocks rotate around the mesh axis via ``lax.ppermute`` while each chip
  streams softmax statistics; sequence memory per chip is O(T/n), and the
  rotation rides the ICI ring.
- :func:`ulysses_attention` — ``lax.all_to_all`` re-shards from
  sequence-parallel to head-parallel layout, runs exact local attention on
  each chip's head slice, and re-shards back (the reference's
  MPI_Alltoallv analogue compiled into the XLA program).

Both are drop-in attention functions for use inside ``jax.shard_map`` over
the Horovod mesh with the sequence dimension sharded on ``axis``.
Layouts are ``[batch, seq_local, heads, head_dim]``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..common.basics import LOCAL_AXIS, _bound_axes

_NEG_INF = -1e30  # finite mask value: keeps running-max arithmetic NaN-free


def _axis_size(axis) -> int:
    """Static size of a bound mesh axis (python int at trace time).
    Unbound axes (tracing outside shard_map, e.g. model.init) count as 1 —
    the shard IS the full sequence there, so callers fall back to dense."""
    from ..ops.collective_ops import _axis_size as _bound_axis_size

    bound = _bound_axes()
    names = (axis,) if isinstance(axis, str) else tuple(axis)
    n = 1
    for a in names:
        if a in bound:
            n *= int(_bound_axis_size(a))
    return n


def dense_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None,
                    q_offset=0, k_offset=0):
    """Reference (non-parallel) scaled-dot-product attention.

    ``q_offset``/``k_offset`` are the global positions of the first query /
    key token — needed for causal masking when q and k are shards of a
    longer sequence.
    """
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = scale if scale is not None else D ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = q_offset + jnp.arange(Tq)
        kpos = k_offset + jnp.arange(Tk)
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def ring_attention(q, k, v, *, axis=LOCAL_AXIS, causal: bool = True,
                   scale: Optional[float] = None):
    """Exact attention over a sequence sharded on ``axis``.

    Each of the n chips holds a contiguous [B, T/n, H, D] block. K/V blocks
    rotate n times around the ring (``lax.ppermute`` over ICI neighbours);
    the local Q block accumulates output with streaming (flash) softmax —
    running max ``m``, normalizer ``l``, and unnormalized output ``o`` —
    so the full [T, T] score matrix never materializes and per-chip memory
    stays O(T/n · T/n) per step.

    Communication is overlapped with compute by XLA: the ppermute for step
    i+1 is independent of step i's einsum, so the collective-permute DMA
    runs concurrently with the MXU work.
    """
    B, T_local, H, D = q.shape
    n = _axis_size(axis)
    if n == 1:
        return dense_attention(q, k, v, causal=causal, scale=scale)
    scale = scale if scale is not None else D ** -0.5
    my = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]  # ring: block i → chip i+1

    qpos = my * T_local + jnp.arange(T_local)

    def step(carry, i):
        o, m, l, k_blk, v_blk = carry
        # Which chip's block do we currently hold? Blocks travel +1 per
        # step, so after i rotations we hold the block of chip (my - i).
        src = (my - i) % n
        kpos = src * T_local + jnp.arange(T_local)

        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)

        m_cur = jnp.max(s, axis=-1)                      # [B,H,Tq]
        m_new = jnp.maximum(m, m_cur)
        alpha = jnp.exp(m - m_new)                       # rescale old
        p = jnp.exp(s - m_new[..., None])                # [B,H,Tq,Tk]
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))

        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        return (o_new, m_new, l_new, k_blk, v_blk), None

    # Accumulators must carry the union of the ring axis' varying type and
    # whatever axes q/k/v already vary over (e.g. a data-parallel batch
    # axis), or the scan carry types won't match.
    from ..ops.collective_ops import _vma, pvary_missing

    ring_axes = {axis} if isinstance(axis, str) else set(axis)
    axes_t = tuple(sorted(ring_axes | _vma(q) | _vma(k) | _vma(v)))

    def _vary(x):
        return pvary_missing(x, axes_t)

    o0 = _vary(jnp.zeros((B, H, T_local, D), jnp.float32))
    m0 = _vary(jnp.full((B, H, T_local), _NEG_INF, jnp.float32))
    l0 = _vary(jnp.zeros((B, H, T_local), jnp.float32))
    # scan (not fori_loop/while) so the rotation is reverse-differentiable
    # — the backward pass replays the ring with transposed ppermutes.
    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v), jnp.arange(n))
    # Causal rows always see at least their own token, so l > 0.
    out = o / l[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def ulysses_attention(q, k, v, *, axis=LOCAL_AXIS, causal: bool = True,
                      scale: Optional[float] = None, attn_fn=None):
    """Ulysses-style sequence parallelism via all-to-all head exchange.

    Input is sequence-sharded [B, T/n, H, D]; ``lax.all_to_all`` re-shards
    to head-sharded [B, T, H/n, D] (each chip gets the FULL sequence for a
    slice of heads), exact attention runs locally, and a second all-to-all
    restores the sequence-sharded layout. Two all-to-alls per attention
    call versus ring's n ppermutes — better when heads ≥ chips and the
    alltoall bisection bandwidth is high (ICI), which is the TPU case.

    ``attn_fn(q, k, v)`` may override the local attention (e.g. a pallas
    flash kernel); default is :func:`dense_attention`. CONTRACT: attn_fn
    must close over the same causal/scale semantics passed to THIS call —
    it receives only (q, k, v), including on the n == 1 early-return path
    where it is invoked directly on the unsharded inputs. A mismatch (e.g.
    ``causal=False`` here but an attn_fn hardcoding ``causal=True``)
    silently computes the attn_fn's semantics.
    """
    B, T_local, H, D = q.shape
    n = _axis_size(axis)
    if n == 1:
        # Unsharded world: still honor the caller's local-attention kernel
        # (e.g. flash) — the shard IS the full sequence.
        if attn_fn is not None:
            return attn_fn(q, k, v)
        return dense_attention(q, k, v, causal=causal, scale=scale)
    if H % n != 0:
        raise ValueError(f"heads {H} not divisible by axis size {n}")

    # [B, T/n, H, D] → [B, T, H/n, D]: split heads across chips, gather seq
    def scatter_heads(x):
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    def gather_heads(x):
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    qf, kf, vf = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    if attn_fn is None:
        out = dense_attention(qf, kf, vf, causal=causal, scale=scale)
    else:
        out = attn_fn(qf, kf, vf)
    return gather_heads(out)


def seq_shard_positions(T_local: int, axis=LOCAL_AXIS):
    """Global token positions of this chip's sequence shard (for positional
    embeddings under sequence parallelism). Outside ``shard_map`` (e.g.
    ``model.init`` tracing an unsharded dummy) the axis is unbound and the
    shard is the whole sequence: positions start at 0."""
    bound = _bound_axes()
    names = (axis,) if isinstance(axis, str) else tuple(axis)
    if not all(a in bound for a in names):
        return jnp.arange(T_local)
    return lax.axis_index(axis) * T_local + jnp.arange(T_local)
