"""Tensor parallelism: Megatron-style sharding helpers for the GPT family.

The reference framework is data-parallel only (SURVEY §2.7); tensor
parallelism is a TPU-scale extension. The model side lives in
:mod:`horovod_tpu.models.gpt` (``GPTConfig.tp_axis``): qkv and the first
MLP projection are column-parallel (each rank computes its own heads /
d_ff slice), the attention output projection and second MLP projection
are row-parallel with one ``lax.psum`` over the tp axis per half-block —
two collectives per layer, the canonical Megatron schedule, riding ICI
when the tp axis is the intra-host mesh axis.

This module turns a DENSE checkpoint into the matching local shards.
The shard_map-ready form is the two-tree split — sharded leaves stacked
with a leading tp dim, replicated leaves kept separate so they stay
provably replicated (vma-unvarying) inside the mesh program:

    full = GPT(dense_cfg).init(key, tokens)["params"]
    sharded, replicated = tp_split_params(full, n)

    def spmd(shard_stack, repl, tokens):
        local = tp_merge_params(
            jax.tree.map(lambda a: a[0], shard_stack), repl)
        return GPT(tp_cfg).apply({"params": local}, tokens)

    jax.shard_map(spmd, mesh=mesh,
                  in_specs=(P(tp_axis), P(), ...), ...)

``tp_shard_params`` (stack everything, one tree) and
``tp_unshard_params`` (inverse → dense checkpoint) are the offline
checkpoint utilities. All are exact: the tp model's outputs equal the
dense model's to float tolerance (tests/test_tensor_parallel.py).

Slicing convention (matching the model's psum placement): column-parallel
kernels/biases are sliced; row-parallel kernels are sliced on input rows
and their biases divided by n (the psum then restores the single dense
bias). Everything else (embeddings, LayerNorms, the tied head) is
replicated.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


def _split_cols(a, n, i):
    return jnp.split(a, n, axis=-1)[i]


def _split_rows(a, n, i):
    return jnp.split(a, n, axis=0)[i]


def _qkv_slice(kernel_or_bias, n, i):
    """qkv columns are [q(all heads) | k | v]: slice heads inside each."""
    q, k, v = jnp.split(kernel_or_bias, 3, axis=-1)
    return jnp.concatenate(
        [_split_cols(q, n, i), _split_cols(k, n, i), _split_cols(v, n, i)],
        axis=-1)


def _qkv_merge(shards):
    qs, ks, vs = zip(*(jnp.split(s, 3, axis=-1) for s in shards))
    return jnp.concatenate(
        [jnp.concatenate(qs, axis=-1),
         jnp.concatenate(ks, axis=-1),
         jnp.concatenate(vs, axis=-1)], axis=-1)


def _merge_cols(shards):
    return jnp.concatenate(shards, axis=-1)


def _merge_rows(shards):
    return jnp.concatenate(shards, axis=0)


def _psum_bias_slice(leaf, n, i):
    return leaf / n                    # the model's psum restores it


def _psum_bias_merge(shards):
    return shards[0] * len(shards)


# Single source of truth for which GPT parameters shard how; every
# consumer (split, stack, unshard) derives from this table. First match
# wins; unmatched leaves are replicated.
_TP_RULES = (
    ("attn/qkv", lambda leaf, n, i: _qkv_slice(leaf, n, i), _qkv_merge),
    ("attn/proj/kernel", _split_rows, _merge_rows),     # row-parallel
    ("attn/proj/bias", _psum_bias_slice, _psum_bias_merge),
    ("mlp/Dense_0", lambda leaf, n, i: _split_cols(leaf, n, i),
     _merge_cols),                                      # column-parallel
    ("mlp/Dense_1/kernel", _split_rows, _merge_rows),   # row-parallel
    ("mlp/Dense_1/bias", _psum_bias_slice, _psum_bias_merge),
)


def _rule(name: str):
    for pattern, shard, unshard in _TP_RULES:
        if pattern in name:
            return shard, unshard
    return None


def _shard_one(path, leaf, n, i):
    name = "/".join(str(getattr(p, "key", p)) for p in path)
    rule = _rule(name)
    return rule[0](leaf, n, i) if rule else leaf


def tp_shard_params(params, n: int):
    """Dense GPT params → stacked tp shards (leading dim ``n`` per leaf)."""
    def stack(path, leaf):
        return jnp.stack([_shard_one(path, leaf, n, i) for i in range(n)])

    return jax.tree_util.tree_map_with_path(stack, params)


def split_params_by_rule(params, n: int, rule):
    """Generic two-tree splitter: ``rule(path) -> shard_fn | None`` where
    ``shard_fn(leaf, n, i)`` produces rank ``i``'s shard. Matched leaves
    are stacked with a leading ``n`` dim into the first tree; everything
    else goes untouched into the second. The shared walker behind
    :func:`tp_split_params` and expert parallelism's ``ep_split_params``."""
    def walk(tree, path):
        sh, rp = {}, {}
        for key, sub in tree.items():
            p = f"{path}/{key}" if path else str(key)
            if isinstance(sub, dict):
                s, r = walk(sub, p)
                if s:
                    sh[key] = s
                if r:
                    rp[key] = r
            else:
                fn = rule(p)
                if fn is not None:
                    sh[key] = jnp.stack([fn(sub, n, i) for i in range(n)])
                else:
                    rp[key] = sub
        return sh, rp

    return walk(params, "")


def tp_split_params(params, n: int):
    """Dense GPT params → (sharded, replicated) trees for shard_map.

    ``sharded`` holds only the tp-sharded leaves, stacked with a leading
    ``n`` dim (pass with ``in_specs=P(tp_axis)``); ``replicated`` holds
    the rest untouched (pass with ``in_specs=P()`` so they stay
    vma-unvarying — there is no varying→invariant cast, so fake-stacking
    replicated leaves would poison every downstream value's vma). Keys
    absent from one tree live in the other; recombine inside the mesh
    program with :func:`tp_merge_params`."""
    return split_params_by_rule(
        params, n, lambda p: (lambda r: r[0] if r else None)(_rule(p)))


def tp_merge_params(sharded_local, replicated):
    """Recombine the two trees from :func:`tp_split_params` (after taking
    this rank's shard, e.g. ``jax.tree.map(lambda a: a[0], sharded)``)."""
    def merge(a, b):
        if a is None:
            return b
        if b is None:
            return a
        out = dict(a)
        for k, v in b.items():
            out[k] = merge(a.get(k), v) if isinstance(v, dict) else v
        return out

    return merge(sharded_local, replicated)


def tp_unshard_params(stacked):
    """Invert :func:`tp_shard_params`: stacked shards → dense params."""
    def merge(path, leaf):
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        shards = [leaf[i] for i in range(leaf.shape[0])]
        rule = _rule(name)
        if rule:
            return rule[1](shards)
        for i, s in enumerate(shards[1:], 1):
            if not np.allclose(np.asarray(s), np.asarray(shards[0])):
                raise ValueError(
                    f"replicated leaf {name!r} diverges between shard 0 "
                    f"and shard {i}; checkpoint is inconsistent")
        return shards[0]

    return jax.tree_util.tree_map_with_path(merge, stacked)
