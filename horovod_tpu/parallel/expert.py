"""Expert parallelism: Switch-style Mixture-of-Experts with all-to-all.

The reference framework has no MoE (CNN-era, SURVEY §2.7), but its
``alltoall`` collective is exactly the EP dispatch primitive — this module
is the TPU-native layer built on it. Top-1 (Switch) routing with a fixed
per-expert capacity, compiled entirely into the XLA program:

1. route: ``softmax(x @ router)`` → argmax expert + gate probability;
2. dispatch: scatter tokens into a static ``[E, capacity, C]`` buffer
   (position = running count within the chosen expert; overflow tokens
   are dropped — they ride the residual connection, standard Switch
   behavior);
3. exchange: one tiled ``lax.all_to_all`` re-shards the buffer from
   expert-major [E, cap, C] to ``[E/n, n·cap, C]`` — each rank receives
   every rank's tokens for ITS experts (the reference's MPI_Alltoallv
   analogue, riding ICI);
4. expert FFN: batched einsum over the local experts' weights;
5. exchange back + combine: tokens return to their source rank and are
   scaled by the gate (straight-through for the router's gradient).

The load-balancing auxiliary loss (Switch eq. 4: E · Σ_e f_e · P_e) is
returned alongside; callers add ``aux_weight * aux`` to the task loss.

Everything is static-shaped; outside ``shard_map`` (or with a 1-sized
axis) the same code runs with all experts local and no collective.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from .sequence import _axis_size


def switch_moe(x, router_kernel, w1, b1, w2, b2, *,
               axis: Optional[str] = None,
               capacity_factor: float = 1.25):
    """Top-1 MoE on flattened tokens ``x`` [N, C].

    ``router_kernel``: [C, E_global]; expert weights carry the LOCAL
    expert dim: ``w1`` [E_local, C, F], ``b1`` [E_local, F], ``w2``
    [E_local, F, C], ``b2`` [E_local, C]. ``E_global = E_local · n``
    where n is the bound size of ``axis``. Returns ``(y [N, C], aux)``.
    """
    N, C = x.shape
    n = _axis_size(axis) if axis else 1
    E_local = w1.shape[0]
    E = E_local * n
    if router_kernel.shape[-1] != E:
        raise ValueError(
            f"router has {router_kernel.shape[-1]} experts but "
            f"E_local {E_local} x axis size {n} = {E}")
    # Per-expert capacity: every rank contributes N tokens to E experts.
    capacity = max(1, int(N * capacity_factor / E + 0.9999))

    logits = jnp.einsum("nc,ce->ne", x.astype(jnp.float32),
                        router_kernel.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)                # [N, E]
    expert = jnp.argmax(probs, axis=-1)                    # [N]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]

    onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)    # [N, E]
    # Position of each token within its expert's queue.
    pos = jnp.sum(jnp.cumsum(onehot, axis=0) * onehot, axis=-1) - 1
    keep = pos < capacity                                  # overflow drop
    pos_c = jnp.minimum(pos, capacity - 1)

    # Switch aux loss: fraction of tokens per expert x mean router prob.
    frac = jnp.mean(onehot.astype(jnp.float32), axis=0)
    mean_p = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac * mean_p)

    dispatch = jnp.zeros((E, capacity, C), x.dtype).at[expert, pos_c].add(
        jnp.where(keep[:, None], x, 0))

    if n > 1:
        # [E, cap, C] → [E_local, n·cap, C]: rank r keeps/receives every
        # rank's buffer rows for ITS local experts.
        recv = lax.all_to_all(dispatch, axis, split_axis=0, concat_axis=1,
                              tiled=True)
    else:
        recv = dispatch                                    # all local

    h = jnp.einsum("ekc,ecf->ekf", recv, w1) + b1[:, None]
    h = nn.gelu(h)
    out = jnp.einsum("ekf,efc->ekc", h, w2) + b2[:, None]

    if n > 1:
        out = lax.all_to_all(out, axis, split_axis=1, concat_axis=0,
                             tiled=True)                   # back home

    y = out[expert, pos_c]                                 # [N, C]
    y = jnp.where(keep[:, None], y, 0) * gate[:, None].astype(y.dtype)
    return y.astype(x.dtype), aux


class SwitchMoE(nn.Module):
    """Flax module: Switch-MoE FFN (drop-in for a dense MLP block).

    ``num_experts`` is GLOBAL; with ``ep_axis`` bound inside shard_map
    each rank creates only its ``num_experts / n`` experts' weights (the
    router is replicated). See ``ep_split_params`` for slicing a dense
    (world-1) checkpoint into per-rank shards.
    """

    num_experts: int
    d_ff: int
    capacity_factor: float = 1.25
    ep_axis: Optional[str] = None
    dtype: jnp.dtype = jnp.float32
    kernel_init_std: float = 0.02

    @nn.compact
    def __call__(self, x):
        B, T, C = x.shape
        n = _axis_size(self.ep_axis) if self.ep_axis else 1
        if self.num_experts % n:
            raise ValueError(
                f"num_experts {self.num_experts} not divisible by "
                f"ep axis size {n}")
        e_local = self.num_experts // n
        init = nn.initializers.normal(self.kernel_init_std)
        router = self.param("router", init, (C, self.num_experts),
                            jnp.float32)
        w1 = self.param("w1", init, (e_local, C, self.d_ff), jnp.float32)
        b1 = self.param("b1", nn.initializers.zeros, (e_local, self.d_ff),
                        jnp.float32)
        w2 = self.param("w2", init, (e_local, self.d_ff, C), jnp.float32)
        b2 = self.param("b2", nn.initializers.zeros, (e_local, C),
                        jnp.float32)
        y, aux = switch_moe(
            x.reshape(B * T, C),
            router, w1.astype(self.dtype), b1.astype(self.dtype),
            w2.astype(self.dtype), b2.astype(self.dtype),
            axis=self.ep_axis, capacity_factor=self.capacity_factor)
        self.sow("intermediates", "moe_aux_loss", aux)
        return y.reshape(B, T, C)


def _ep_rule(path: str):
    """Expert weights live under a SwitchMoE module ('moe' in GPT blocks)
    — anchor on the module name so unrelated params that happen to be
    called w1/b1/w2/b2 elsewhere are never mis-sharded."""
    mod, _, leaf = path.rpartition("/")
    if leaf in ("w1", "b1", "w2", "b2") and mod.split("/")[-1] == "moe":
        return lambda a, n, i: jnp.split(a, n, axis=0)[i]
    return None


def ep_split_params(params, n: int):
    """Dense (world-1) SwitchMoE params → (sharded, replicated) trees,
    same contract as :func:`horovod_tpu.parallel.tensor.tp_split_params`:
    expert weights (leading expert dim) are stacked per-rank shards, the
    router (and everything else) stays in the replicated tree."""
    from .tensor import split_params_by_rule

    return split_params_by_rule(params, n, _ep_rule)
